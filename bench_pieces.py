"""Per-piece chip profiling harness — PROFILE.md's methodology as code.

Round-2 lessons, encoded so a chip session starts productive instead of
re-deriving them (PROFILE.md "measurement methodology"):
 - per-dispatch tunnel overhead is ~4 ms: every piece is timed as a
   ``lax.fori_loop`` of REPS dependent invocations inside ONE jit, then
   divided — the carry feeds back into an operand so XLA cannot CSE or
   reorder the calls;
 - ``block_until_ready`` does not synchronize over the tunnel: the sync
   point is a tiny real device->host fetch;
 - operand layouts: inputs are produced on device (iota/prng) so pallas
   custom-call layout constraints don't charge a relayout to the kernel.

Prints one JSON line per piece.  Shape mirrors bench.py's airlines-10M
workload; H2O3_PIECES_ROWS overrides for smoke runs.

Usage (chip): python bench_pieces.py
CPU smoke:    JAX_PLATFORMS=cpu H2O3_PIECES_ROWS=100000 python bench_pieces.py
"""

import json
import os
import sys
import time

import numpy as np

N_ROWS = int(os.environ.get("H2O3_PIECES_ROWS", 10_000_000))
REPS = int(os.environ.get("H2O3_PIECES_REPS", 20))
BIN_COUNTS = (21, 12, 7, 256, 256, 22, 256, 256)
F, NBINS = 8, 256
B = NBINS + 1


def main():
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp

    import h2o3_tpu
    cl = h2o3_tpu.init()
    platform = jax.devices()[0].platform
    n = N_ROWS - (N_ROWS % (512 * cl.n_row_shards))

    from h2o3_tpu.models.tree.hist import (make_varbin_hist_fn,
                                           make_hist_fn, offset_codes,
                                           best_splits)

    def emit(piece, ms, **extra):
        print(json.dumps({"piece": piece, "ms": round(ms, 3),
                          "platform": platform, "rows": n, **extra}),
              flush=True)

    # shared tunnel-safe sync + fori_loop amortization (bench_util.py)
    from bench_util import timed_amortized

    def timed(fn_build, *args):
        return timed_amortized(fn_build, *args, reps=REPS)

    # device-generated inputs (no host transfer, producer-fused layouts)
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 8)
    codes = jnp.stack([
        jax.random.randint(ks[f], (n,), 0, min(bc, NBINS), dtype=jnp.int32)
        for f, bc in enumerate(BIN_COUNTS)], axis=0)
    gcodes = offset_codes(codes, BIN_COUNTS, NBINS)
    g = jax.random.normal(ks[0], (n,), jnp.float32)
    h = jnp.abs(jax.random.normal(ks[1], (n,), jnp.float32)) + 0.1
    w = jnp.ones((n,), jnp.float32)

    # --- histogram levels: varbin (bench path) vs uniform
    # off-TPU smoke: interpret-mode pallas (slow but same code path)
    force = "" if platform == "tpu" else "pallas_interpret"
    for L in (1, 2, 4, 8, 16, 32):
        leaf = jax.random.randint(ks[2], (n,), 0, L, dtype=jnp.int32)
        fn = make_varbin_hist_fn(L, F, BIN_COUNTS, B, n, force_impl=force)

        def run_vb(acc, gc, lf, gg, hh, ww, _fn=fn):
            H = _fn(gc, lf, gg + acc * 0.0, hh, ww)
            return H[0, 0, 0, 0] * 1e-30

        emit(f"varbin_hist_L{L}", timed(run_vb, gcodes, leaf, g, h, w),
             kernel="varbin+int16+bf16")
    for L in (1, 32):
        leaf = jax.random.randint(ks[3], (n,), 0, L, dtype=jnp.int32)
        fn = make_hist_fn(L, F, B, n)

        def run_u(acc, cc, lf, gg, hh, ww, _fn=fn):
            H = _fn(cc, lf, gg + acc * 0.0, hh, ww)
            return H[0, 0, 0, 0] * 1e-30

        emit(f"uniform_hist_L{L}", timed(run_u, codes, leaf, g, h, w))

    # --- split search on a realistic histogram
    leaf32 = jax.random.randint(ks[4], (n,), 0, 32, dtype=jnp.int32)
    H = make_varbin_hist_fn(32, F, BIN_COUNTS, B, n, force_impl=force)(
        gcodes, leaf32, g, h, w)

    def run_split(acc, Hh):
        out = best_splits(Hh + acc * 0.0, NBINS, 1.0, 1.0, 0.0)
        return out[3].reshape(-1)[0].astype(jnp.float32) * 1e-30

    emit("best_splits_L32", timed(run_split, H))

    # --- whole-ensemble scoring (50 trees, depth 6)
    from h2o3_tpu.models.tree.shared import StackedTrees, traverse
    T, depth = 50, 6
    rng = np.random.default_rng(0)
    levels = []
    for d in range(depth):
        width = 2 ** d
        levels.append((
            jnp.asarray(rng.integers(0, F, (T, width)), jnp.int32),
            jnp.asarray(rng.normal(size=(T, width)), jnp.float32),
            jnp.asarray(rng.random((T, width)) < 0.5),
            jnp.ones((T, width), bool)))
    values = jnp.asarray(rng.normal(size=(T, 2 ** depth)) * 0.1,
                         jnp.float32)
    X = jax.random.normal(ks[5], (n, F), jnp.float32)

    def run_traverse(acc, Xx):
        s = traverse(levels, values, Xx + acc * 0.0)
        return s[0] * 1e-30

    t_ms = timed(run_traverse, X)
    emit("traverse_50trees_d6", t_ms,
         trees_per_sec_scoring=round(T / (t_ms / 1e3), 1))

    # --- rapids sort / merge (device)
    from h2o3_tpu.rapids import sort as _sort  # noqa: F401 — warm import
    keys_col = jax.random.randint(ks[6], (n,), 0, n, dtype=jnp.int32)

    def run_sort(acc, kk):
        out = jnp.sort(kk + acc.astype(jnp.int32) * 0)
        return out[0].astype(jnp.float32) * 1e-30

    emit("device_sort", timed(run_sort, keys_col))

    # --- projected end-to-end: one tree = 6 varbin levels + partition
    print(json.dumps({"piece": "NOTE",
                      "note": "tree total ~= sum(varbin_hist_L{1..32}) "
                              "+ 6x partition (~1.6ms) + split search; "
                              "see PROFILE.md round-2 table"}), flush=True)


def hist_piece():
    """Standalone per-level histogram comparison: uniform vs varbin vs
    smaller-sibling subtraction (hist.make_subtract_level_fn), without the
    ~1091 s full bench.

    Per level d (children L = 2^d) three JSON lines land:
      - ``uniform_L*``   — the uniform kernel over ALL rows at the parent
        slot count (what the pre-varbin driver paid per level),
      - ``varbin_L*``    — the varbin kernel over ALL rows (the masked
        left-sibling path every level below the root paid before this
        round),
      - ``subtract_L*``  — compaction + varbin over the <= N/2
        smaller-sibling prefix + reconstruction (the shipping default),
    plus a ``hist_summary`` line with the varbin/subtract speedup per
    level.  Skews the per-level splits (70/30) so the compacted side is a
    realistic minority, and chains the carries level to level exactly like
    the tree driver.

    Usage (chip): python bench_pieces.py hist
    CPU smoke:    JAX_PLATFORMS=cpu H2O3_PIECES_ROWS=200000 \\
                  python bench_pieces.py hist
    (CPU runs the same Pallas kernels in interpret mode — relative
    numbers are methodology checks, not projections; see PROFILE.md.)
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp

    import h2o3_tpu
    from bench_util import timed_amortized
    cl = h2o3_tpu.init()
    platform = jax.devices()[0].platform
    n = N_ROWS - (N_ROWS % (512 * cl.n_row_shards))

    from h2o3_tpu.models.tree.hist import (make_hist_fn, make_varbin_hist_fn,
                                           make_subtract_level_fn,
                                           offset_codes)

    def emit(**rec):
        print(json.dumps({**rec, "platform": platform, "rows": n}),
              flush=True)

    force = "" if platform == "tpu" else "pallas_interpret"
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 16)
    codes = jnp.stack([
        jax.random.randint(ks[f], (n,), 0, min(bc, NBINS), dtype=jnp.int32)
        for f, bc in enumerate(BIN_COUNTS)], axis=0)
    gcodes = offset_codes(codes, BIN_COUNTS, NBINS)
    g = jax.random.normal(ks[8], (n,), jnp.float32)
    h = jnp.abs(jax.random.normal(ks[9], (n,), jnp.float32)) + 0.1
    w = jnp.ones((n,), jnp.float32)

    # consistent leaf chain (child of the previous level's leaf, 70/30
    # split) + the subtraction carries, built once outside the timed loop
    leaves, carries = [jnp.zeros(n, jnp.int32)], []
    Hg, carry = make_subtract_level_fn(
        0, F, B, n, bin_counts=BIN_COUNTS, force_impl=force)(
        gcodes, leaves[0], g, h, w)
    carries.append(carry)
    summary = {}
    for d in range(1, 6):
        Lp = 2 ** (d - 1)
        bit = (jax.random.uniform(ks[10 + (d % 6)], (n,)) < 0.3) \
            .astype(jnp.int32)
        leaf = 2 * leaves[-1] + bit
        leaves.append(leaf)

        ufn = make_hist_fn(Lp, F, B, n, force_impl=force, precision="f32") \
            if force else make_hist_fn(Lp, F, B, n)

        def run_u(acc, lf, _fn=ufn):
            H = _fn(codes, lf, g + acc * 0.0, h, w)
            return H[0, 0, 0, 0] * 1e-30

        ms_u = timed_amortized(run_u, leaf >> 1, reps=REPS)
        emit(piece=f"uniform_L{2 ** d}", ms=round(ms_u, 3))

        vfn = make_varbin_hist_fn(Lp, F, BIN_COUNTS, B, n, force_impl=force)

        def run_v(acc, lf, _fn=vfn):
            H = _fn(gcodes, lf, g + acc * 0.0, h, w)
            return H[0, 0, 0, 0] * 1e-30

        ms_v = timed_amortized(run_v, leaf >> 1, reps=REPS)
        emit(piece=f"varbin_L{2 ** d}", ms=round(ms_v, 3),
             kernel="all-rows (masked-sibling path)")

        sfn = make_subtract_level_fn(d, F, B, n, bin_counts=BIN_COUNTS,
                                     force_impl=force)

        def run_s(acc, lf, cr, _fn=sfn):
            H, _ = _fn(gcodes, lf, g + acc * 0.0, h, w, cr)
            return H[0, 0, 0, 0] * 1e-30

        ms_s = timed_amortized(run_s, leaf, carries[-1], reps=REPS)
        emit(piece=f"subtract_L{2 ** d}", ms=round(ms_s, 3),
             kernel="compact+varbin+reconstruct")
        summary[f"L{2 ** d}"] = round(ms_v / ms_s, 2) if ms_s > 0 else None
        _, carry = sfn(gcodes, leaf, g, h, w, carries[-1])
        carries.append(carry)

    emit(piece="hist_summary", varbin_over_subtract=summary,
         note="ratio > 1: subtraction beats the all-rows masked path")


def splits_piece():
    """Standalone split-search comparison: multi-pass best_splits vs the
    fused winner-records path vs the batched-K fused path, per level of
    a depth-6 build, without the full bench.

    Per level d (leaf slots L = 2^d) three JSON lines land:
      - ``split_separate_L*`` — best_splits, the multi-pass XLA oracle
        (~15 [L, F, B] intermediates through HBM per level),
      - ``split_fused_L*``    — fused_best_splits on the platform's
        shipping impl (winner-records Pallas kernel on TPU, the
        bit-identical XLA twin elsewhere),
      - ``split_batched_K3_L*`` — fused_best_splits_batched over K=3
        class histograms flattened into ONE records pass (per-tree ms is
        the number to compare against split_fused_L*).
    The histograms chain level to level off one leaf chain (70/30
    splits) so each level's H carries realistic occupancy, and the timed
    carry feeds back into the operand so XLA cannot CSE the calls.

    A final ``ktree_dispatch`` line counts pallas_call equations in the
    traced batched level program (hist + split search for all K trees):
    the acceptance is 2 launches per level TOTAL — one histogram kernel
    (vmap batches the grid over K) and one records kernel (K*L leaves
    flatten into rows) — independent of K.

    Usage (chip): python bench_pieces.py splits
    CPU smoke:    JAX_PLATFORMS=cpu H2O3_PIECES_ROWS=200000 \\
                  python bench_pieces.py splits
    (Off-TPU the fused path ships the XLA twin; pass
    H2O3_SPLITS_INTERPRET=1 to time the Pallas kernel in interpret mode
    instead — a methodology check, not a projection.)
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp

    import h2o3_tpu
    from bench_util import timed_amortized
    cl = h2o3_tpu.init()
    platform = jax.devices()[0].platform
    n = N_ROWS - (N_ROWS % (512 * cl.n_row_shards))

    from h2o3_tpu.models.tree.hist import (
        make_varbin_hist_fn, make_batched_level_fn, offset_codes,
        best_splits, fused_best_splits, fused_best_splits_batched)

    def emit(**rec):
        print(json.dumps({**rec, "platform": platform, "rows": n}),
              flush=True)

    force = "" if platform == "tpu" else "pallas_interpret"
    fsplit = "pallas_interpret" if (platform != "tpu" and
                                    os.environ.get("H2O3_SPLITS_INTERPRET")) \
        else ""
    impl = "pallas" if platform == "tpu" else \
        ("pallas_interpret" if fsplit else "xla_twin")
    K = 3
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 16)
    codes = jnp.stack([
        jax.random.randint(ks[f], (n,), 0, min(bc, NBINS), dtype=jnp.int32)
        for f, bc in enumerate(BIN_COUNTS)], axis=0)
    gcodes = offset_codes(codes, BIN_COUNTS, NBINS)
    gK = jax.random.normal(ks[8], (K, n), jnp.float32)
    hK = jnp.abs(jax.random.normal(ks[9], (K, n), jnp.float32)) + 0.1
    w = jnp.ones((n,), jnp.float32)

    leaf = jnp.zeros(n, jnp.int32)
    summary = {}
    for d in range(6):
        L = 2 ** d
        if d:
            bit = (jax.random.uniform(ks[10 + d], (n,)) < 0.3) \
                .astype(jnp.int32)
            leaf = 2 * leaf + bit
        vfn = make_varbin_hist_fn(L, F, BIN_COUNTS, B, n, force_impl=force)
        HK = jnp.stack([vfn(gcodes, leaf, gK[k], hK[k], w)
                        for k in range(K)])
        H = HK[0]

        def run_sep(acc, Hh):
            out = best_splits(Hh + acc * 0.0, NBINS, 1.0, 1.0, 1e-5)
            return out[3].reshape(-1)[0].astype(jnp.float32) * 1e-30

        ms_sep = timed_amortized(run_sep, H, reps=REPS)
        emit(piece=f"split_separate_L{L}", ms=round(ms_sep, 3))

        def run_fus(acc, Hh):
            out = fused_best_splits(Hh + acc * 0.0, NBINS, 1.0, 1.0, 1e-5,
                                    force_impl=fsplit)
            return out[3].reshape(-1)[0].astype(jnp.float32) * 1e-30

        ms_fus = timed_amortized(run_fus, H, reps=REPS)
        emit(piece=f"split_fused_L{L}", ms=round(ms_fus, 3), impl=impl)

        def run_bat(acc, Hh):
            out = fused_best_splits_batched(Hh + acc * 0.0, NBINS, 1.0,
                                            1.0, 1e-5, force_impl=fsplit)
            return out[3].reshape(-1)[0].astype(jnp.float32) * 1e-30

        ms_bat = timed_amortized(run_bat, HK, reps=REPS)
        emit(piece=f"split_batched_K{K}_L{L}", ms=round(ms_bat, 3),
             ms_per_tree=round(ms_bat / K, 3), impl=impl)
        summary[f"L{L}"] = {
            "fused_speedup": round(ms_sep / ms_fus, 2) if ms_fus else None,
            "batched_per_tree_vs_fused":
                round(ms_fus / (ms_bat / K), 2) if ms_bat else None}

    emit(piece="splits_summary", per_level=summary,
         note="fused_speedup > 1: single-pass records path beats the "
              "multi-pass XLA search; batched_per_tree_vs_fused > 1: "
              "flattening K trees into one launch amortizes dispatch")

    # dispatch-count proof for the batched K-tree level: ONE histogram
    # launch + ONE records launch regardless of K (count from the traced
    # program, not a projection)
    lev = make_batched_level_fn(1, K, F, B, n, bin_counts=BIN_COUNTS,
                                force_impl=force or "pallas",
                                subtract=False)
    leafK = jnp.broadcast_to(leaf, (K, n))
    wK = jnp.broadcast_to(w, (K, n))

    def batched_level(c, lf, gg, hh, ww):
        Hh = lev(c, lf, gg, hh, ww)
        return fused_best_splits_batched(Hh, NBINS, 1.0, 1.0, 1e-5,
                                         force_impl="pallas")

    n_calls = str(jax.make_jaxpr(batched_level)(
        gcodes, leafK, gK, hK, wK)).count("pallas_call")
    emit(piece="ktree_dispatch", pallas_calls_per_level=n_calls, K=K,
         expect=2, ok=n_calls == 2,
         note="1 hist kernel (vmap batches the grid over K) + 1 records "
              "kernel (K*L leaves flatten into rows)")


def deep_piece():
    """Deep-level layout comparison: the dense [2^d, F, B] grid vs the
    node-sparse [A, F, B] slot layout, depth 6 -> 12 at 64 and 256 bins.

    Per (nbins, depth) two JSON lines land, each timing ONE level program
    (histogram + fused split search, the per-level unit of work):

      - ``deep_dense_b*_d*``  — make_subtract_level_fn at the full level
        width 2^d; where the dense grid exceeds the 64 MB histogram
        budget the line carries ``over_budget: true`` and is NOT timed
        (that is the wall the sparse layout removes),
      - ``deep_sparse_b*_d*`` — make_sparse_level_fn at the slot width
        A = min(2^d, sparse_slot_budget(F, B)): histogram bytes follow
        the ALIVE-bounded slot axis, plateauing at the budget instead of
        doubling per level.

    A ``deep_summary_b*`` line tabulates the per-depth byte ratio and a
    final ``deep_dispatch`` line counts pallas_call equations in the
    traced sparse level program — the acceptance is 2 launches per level
    (one sparse histogram kernel + one winner-records kernel) no matter
    how many leaves are alive.

    Usage (chip): python bench_pieces.py deep
    CPU smoke:    JAX_PLATFORMS=cpu H2O3_PIECES_ROWS=50000 \\
                  H2O3_PIECES_REPS=2 python bench_pieces.py deep
    (Off-TPU the inner histogram ships the einsum impl — same level
    program structure, smoke-scale numbers only; chip numbers are the
    deliverable.)
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp

    import h2o3_tpu
    from bench_util import timed_amortized
    cl = h2o3_tpu.init()
    platform = jax.devices()[0].platform
    n = N_ROWS - (N_ROWS % (512 * cl.n_row_shards))
    shards = cl.n_row_shards

    from h2o3_tpu.models.tree.hist import (
        fused_best_splits, make_sparse_level_fn, make_subtract_level_fn,
        offset_codes, sparse_slot_budget)

    def emit(**rec):
        print(json.dumps({**rec, "platform": platform, "rows": n}),
              flush=True)

    CAP = 64 * 1024 * 1024
    on_tpu = platform == "tpu"
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 16)
    g = jax.random.normal(ks[8], (n,), jnp.float32)
    h = jnp.abs(jax.random.normal(ks[9], (n,), jnp.float32)) + 0.1
    w = jnp.ones((n,), jnp.float32)

    for nbins in (64, 256):
        B_ = nbins + 1
        # varbin packed kernel on chip; einsum inner for CPU smoke
        bc = tuple(min(c, nbins) for c in BIN_COUNTS) if on_tpu else None
        codes = jnp.stack([
            jax.random.randint(ks[f], (n,), 0, min(c, nbins),
                               dtype=jnp.int32)
            for f, c in enumerate(BIN_COUNTS)], axis=0)
        hc = offset_codes(codes, bc, nbins) if bc else codes
        A_cap = sparse_slot_budget(F, B_)
        mem = {}
        for d in range(6, 13):
            Ld = 2 ** d
            dense_bytes = F * B_ * 3 * Ld * 4
            sp_A = min(Ld, A_cap)
            sp_Ap = min(Ld // 2, A_cap)
            sparse_bytes = F * B_ * 3 * sp_A * 4
            mem[f"d{d}"] = {"dense_mb": round(dense_bytes / 2 ** 20, 1),
                            "sparse_mb": round(sparse_bytes / 2 ** 20, 1)}

            if dense_bytes <= CAP:
                dfn = make_subtract_level_fn(d, F, B_, n, bin_counts=bc)
                leaf = jax.random.randint(ks[10], (n,), 0, Ld,
                                          dtype=jnp.int32)
                dcarry = jnp.zeros((shards, 3, Ld // 2, F, B_),
                                   jnp.float32)

                def run_d(acc, lf, cr, _fn=dfn, _b=nbins):
                    H, _ = _fn(hc, lf, g + acc * 0.0, h, w, cr)
                    out = fused_best_splits(H, _b, 1.0, 1.0, 1e-5)
                    return out[3].reshape(-1)[0].astype(jnp.float32) \
                        * 1e-30

                ms = timed_amortized(run_d, leaf, dcarry, reps=REPS)
                emit(piece=f"deep_dense_b{nbins}_d{d}", ms=round(ms, 3),
                     slots=Ld, hist_bytes=dense_bytes)
            else:
                emit(piece=f"deep_dense_b{nbins}_d{d}", ms=None,
                     slots=Ld, hist_bytes=dense_bytes, over_budget=True,
                     note="dense grid exceeds the 64 MB histogram budget")

            sfn = make_sparse_level_fn(sp_Ap, sp_A, F, B_, n,
                                       bin_counts=bc)
            sleaf = jax.random.randint(ks[11], (n,), 0, sp_A,
                                       dtype=jnp.int32)
            ps = jnp.minimum(jnp.arange(sp_A, dtype=jnp.int32) // 2,
                             sp_Ap - 1)
            scarry = jnp.zeros((shards, 3, sp_Ap, F, B_), jnp.float32)

            def run_s(acc, lf, cr, _fn=sfn, _ps=ps, _b=nbins):
                H, _ = _fn(hc, lf, g + acc * 0.0, h, w, cr, _ps)
                out = fused_best_splits(H, _b, 1.0, 1.0, 1e-5)
                return out[3].reshape(-1)[0].astype(jnp.float32) * 1e-30

            ms = timed_amortized(run_s, sleaf, scarry, reps=REPS)
            emit(piece=f"deep_sparse_b{nbins}_d{d}", ms=round(ms, 3),
                 slots=sp_A, hist_bytes=sparse_bytes,
                 mem_ratio=round(dense_bytes / sparse_bytes, 2))

        # the alive-bounded case the layout exists for: a skewed deep
        # tree with ~256 alive leaves runs the SAME level program at
        # EVERY depth — time and bytes stop depending on d entirely,
        # while the dense grid doubles per level above
        A_alive = 256
        afn = make_sparse_level_fn(A_alive, A_alive, F, B_, n,
                                   bin_counts=bc)
        sleaf = jax.random.randint(ks[12], (n,), 0, A_alive,
                                   dtype=jnp.int32)
        ps = jnp.minimum(jnp.arange(A_alive, dtype=jnp.int32) // 2,
                         A_alive - 1)
        acarry = jnp.zeros((shards, 3, A_alive, F, B_), jnp.float32)

        def run_a(acc, lf, cr, _fn=afn, _ps=ps, _b=nbins):
            H, _ = _fn(hc, lf, g + acc * 0.0, h, w, cr, _ps)
            out = fused_best_splits(H, _b, 1.0, 1.0, 1e-5)
            return out[3].reshape(-1)[0].astype(jnp.float32) * 1e-30

        ms = timed_amortized(run_a, sleaf, acarry, reps=REPS)
        emit(piece=f"deep_sparse_alive{A_alive}_b{nbins}", ms=round(ms, 3),
             slots=A_alive, hist_bytes=F * B_ * 3 * A_alive * 4,
             note="256 alive leaves: identical level cost at EVERY "
                  "depth 8..12+ — hist bytes follow alive leaves, "
                  "not 2^d")

        emit(piece=f"deep_summary_b{nbins}", slot_budget=A_cap,
             per_depth_mb=mem,
             alive256_mb=round(F * B_ * 3 * A_alive * 4 / 2 ** 20, 1),
             note="sparse bytes are alive-bounded (plateau at the slot "
                  "budget in the worst case); dense doubles per level "
                  "and blows the 64 MB cap at depth 12 x 256 bins")

    # dispatch-count proof: 2 pallas launches per sparse level (hist +
    # records), independent of the alive-slot count — from the traced
    # program, not a projection
    Ap_, A_ = 8, 16
    lev = make_sparse_level_fn(
        Ap_, A_, F, B, n, bin_counts=BIN_COUNTS,
        force_impl="pallas" if on_tpu else "pallas_interpret")
    sleaf = jnp.zeros((n,), jnp.int32)
    carry = jnp.zeros((shards, 3, Ap_, F, B), jnp.float32)
    ps = jnp.arange(A_, dtype=jnp.int32) // 2

    def sparse_level(c, lf, gg, hh, ww, cr, pp):
        H, _ = lev(c, lf, gg, hh, ww, cr, pp)
        return fused_best_splits(H, NBINS, 1.0, 1.0, 1e-5,
                                 force_impl="pallas")

    gcodes = offset_codes(jnp.zeros((F, n), jnp.int32), BIN_COUNTS, NBINS)
    n_calls = str(jax.make_jaxpr(sparse_level)(
        gcodes, sleaf, g, h, w, carry, ps)).count("pallas_call")
    emit(piece="deep_dispatch", pallas_calls_per_level=n_calls, expect=2,
         ok=n_calls == 2,
         note="1 sparse hist kernel + 1 records kernel per deep level")


def parse_piece():
    """Standalone ingest bench: bench.py's 568 MB parse line (same file,
    same warmup methodology) without the ~1091 s full suite.

    Usage:      python bench_pieces.py parse
    CPU smoke:  JAX_PLATFORMS=cpu H2O3_BENCH_ROWS=100000 \\
                python bench_pieces.py parse

    Prints one JSON line with MB/s, vs_baseline (reference: 580 MB in
    4.9 s on 5 nodes), and the pipeline's per-stage wall times
    (mmap / scan / tokenize / device / decode / vec).
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import tempfile

    import h2o3_tpu
    import bench
    from h2o3_tpu.frame.parse import parse_csv, last_parse_stats
    h2o3_tpu.init()
    dt, mb = bench.bench_parse(parse_csv, tempfile.gettempdir())
    print(json.dumps({
        "piece": "parse", "sec": round(dt, 3), "mb": round(mb, 1),
        "mb_per_sec": round(mb / dt, 1),
        "vs_baseline": round(
            (bench.REFERENCE_PARSE_S * mb / bench.REFERENCE_PARSE_MB) / dt,
            2),
        "stages": dict(last_parse_stats)}), flush=True)


def obs_piece():
    """Telemetry-overhead bench: the hist level loop (the subtract-path
    chain hist_piece times) run three ways — bare, wrapped in the
    ``level_phase`` span hooks with telemetry ON, and wrapped with
    telemetry OFF (``H2O3_TPU_METRICS=0`` fast path).

    The hooks are host-side (span event + latency histogram per phase),
    so their cost must disappear against a real kernel dispatch: the
    acceptance bar is < 2% overhead with telemetry enabled.

    Usage (chip): python bench_pieces.py obs
    CPU smoke:    JAX_PLATFORMS=cpu H2O3_PIECES_ROWS=200000 \\
                  python bench_pieces.py obs
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import time as _time

    import jax
    import jax.numpy as jnp

    import h2o3_tpu
    from h2o3_tpu.models.tree.hist import (make_subtract_level_fn,
                                           offset_codes)
    from h2o3_tpu.models.tree.shared import level_phase
    from h2o3_tpu.runtime import observability as obs

    cl = h2o3_tpu.init()
    platform = jax.devices()[0].platform
    n = N_ROWS - (N_ROWS % (512 * cl.n_row_shards))
    force = "" if platform == "tpu" else "pallas_interpret"
    reps = max(REPS // 4, 3)

    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 16)
    codes = jnp.stack([
        jax.random.randint(ks[f], (n,), 0, min(bc, NBINS), dtype=jnp.int32)
        for f, bc in enumerate(BIN_COUNTS)], axis=0)
    gcodes = offset_codes(codes, BIN_COUNTS, NBINS)
    g = jax.random.normal(ks[8], (n,), jnp.float32)
    h = jnp.abs(jax.random.normal(ks[9], (n,), jnp.float32)) + 0.1
    w = jnp.ones((n,), jnp.float32)

    # the same leaf/carry chain hist_piece uses (70/30 splits), built and
    # warmed up outside the timed loops so only steady-state dispatch is
    # measured
    chain = []
    leaf = jnp.zeros(n, jnp.int32)
    fn0 = make_subtract_level_fn(0, F, B, n, bin_counts=BIN_COUNTS,
                                 force_impl=force)
    _, carry = fn0(gcodes, leaf, g, h, w)
    for d in range(1, 6):
        bit = (jax.random.uniform(ks[10 + (d % 6)], (n,)) < 0.3) \
            .astype(jnp.int32)
        leaf = 2 * leaf + bit
        fn_d = make_subtract_level_fn(d, F, B, n, bin_counts=BIN_COUNTS,
                                      force_impl=force)
        H, next_carry = fn_d(gcodes, leaf, g, h, w, carry)   # warmup
        jax.block_until_ready(H)
        chain.append((fn_d, leaf, carry))
        carry = next_carry

    def run_loop(instrument: bool) -> float:
        t0 = _time.perf_counter()
        for _ in range(reps):
            for d, (fn_d, lf, cr) in enumerate(chain, start=1):
                if instrument:
                    with level_phase("hist", d):
                        H, _ = fn_d(gcodes, lf, g, h, w, cr)
                else:
                    H, _ = fn_d(gcodes, lf, g, h, w, cr)
                jax.block_until_ready(H)
        return (_time.perf_counter() - t0) * 1e3 / (reps * len(chain))

    def emit(**rec):
        print(json.dumps({**rec, "platform": platform, "rows": n,
                          "reps": reps}), flush=True)

    run_loop(False)                                   # loop warmup
    ms_plain = run_loop(False)
    prev = obs.set_enabled(True)
    ms_on = run_loop(True)
    obs.set_enabled(False)
    ms_off = run_loop(True)
    obs.set_enabled(prev)

    emit(piece="obs_plain", ms=round(ms_plain, 4))
    emit(piece="obs_enabled", ms=round(ms_on, 4))
    emit(piece="obs_disabled", ms=round(ms_off, 4))
    pct_on = 100.0 * (ms_on - ms_plain) / ms_plain
    pct_off = 100.0 * (ms_off - ms_plain) / ms_plain
    emit(piece="obs_summary",
         overhead_pct_enabled=round(pct_on, 3),
         overhead_pct_disabled=round(pct_off, 3),
         ok=bool(pct_on < 2.0),
         note="span+histogram hooks on the hist level loop; bar is < 2%")


def xprof_piece():
    """Device-timing overhead bench: the same subtract-path level loop as
    ``obs_piece``, dispatched through the compile-ledger ``_Program``
    wrappers three ways — ``H2O3_TPU_DEVICE_TIMING=off`` (baseline),
    ``sampled`` (every Nth dispatch block-until-ready into
    ``tree_phase_device_seconds``), and ``full`` (every dispatch).

    ``sampled`` is the mode training keeps on, so its cost must vanish
    against a real kernel dispatch: the acceptance bar is < 2% overhead
    vs ``off``.  Also proves the ledger side: the loop's programs appear
    in ``ledger_snapshot()`` and the sampled run lands observations in
    ``tree_phase_device_seconds``.

    Usage (chip): python bench_pieces.py xprof
    CPU smoke:    JAX_PLATFORMS=cpu H2O3_PIECES_ROWS=200000 \\
                  python bench_pieces.py xprof
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import time as _time

    import jax
    import jax.numpy as jnp

    import h2o3_tpu
    from h2o3_tpu.models.tree.hist import (make_subtract_level_fn,
                                           offset_codes)
    from h2o3_tpu.runtime import config as _config
    from h2o3_tpu.runtime import observability as obs
    from h2o3_tpu.runtime import xprof

    cl = h2o3_tpu.init()
    platform = jax.devices()[0].platform
    n = N_ROWS - (N_ROWS % (512 * cl.n_row_shards))
    force = "" if platform == "tpu" else "pallas_interpret"
    reps = max(REPS // 4, 3)

    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 16)
    codes = jnp.stack([
        jax.random.randint(ks[f], (n,), 0, min(bc, NBINS), dtype=jnp.int32)
        for f, bc in enumerate(BIN_COUNTS)], axis=0)
    gcodes = offset_codes(codes, BIN_COUNTS, NBINS)
    g = jax.random.normal(ks[8], (n,), jnp.float32)
    h = jnp.abs(jax.random.normal(ks[9], (n,), jnp.float32)) + 0.1
    w = jnp.ones((n,), jnp.float32)

    # same warmed leaf/carry chain as obs_piece; the level fns are
    # _Program wrappers, so every eager call below goes through the
    # ledger dispatch path that maybe_device_sync hooks
    chain = []
    leaf = jnp.zeros(n, jnp.int32)
    fn0 = make_subtract_level_fn(0, F, B, n, bin_counts=BIN_COUNTS,
                                 force_impl=force)
    _, carry = fn0(gcodes, leaf, g, h, w)
    for d in range(1, 6):
        bit = (jax.random.uniform(ks[10 + (d % 6)], (n,)) < 0.3) \
            .astype(jnp.int32)
        leaf = 2 * leaf + bit
        fn_d = make_subtract_level_fn(d, F, B, n, bin_counts=BIN_COUNTS,
                                      force_impl=force)
        H, next_carry = fn_d(gcodes, leaf, g, h, w, carry)   # warmup
        jax.block_until_ready(H)
        chain.append((fn_d, leaf, carry))
        carry = next_carry

    prev_env = os.environ.get("H2O3_TPU_DEVICE_TIMING")
    prev_enabled = obs.set_enabled(True)

    def set_mode(mode: str) -> None:
        os.environ["H2O3_TPU_DEVICE_TIMING"] = mode
        _config.reload()                 # re-reads env; resets telemetry
        obs.set_enabled(True)            # timing only records when on

    def run_loop() -> float:
        t0 = _time.perf_counter()
        for _ in range(reps):
            for fn_d, lf, cr in chain:
                H, _ = fn_d(gcodes, lf, g, h, w, cr)
                jax.block_until_ready(H)
        return (_time.perf_counter() - t0) * 1e3 / (reps * len(chain))

    def emit(**rec):
        print(json.dumps({**rec, "platform": platform, "rows": n,
                          "reps": reps}), flush=True)

    try:
        set_mode("off")
        run_loop()                                    # loop warmup
        ms_off = run_loop()
        set_mode("sampled")
        ms_sampled = run_loop()
        set_mode("full")
        ms_full = run_loop()
    finally:
        if prev_env is None:
            os.environ.pop("H2O3_TPU_DEVICE_TIMING", None)
        else:
            os.environ["H2O3_TPU_DEVICE_TIMING"] = prev_env
        _config.reload()
        obs.set_enabled(prev_enabled)

    series = {s["n"] for s in obs.metrics_wire()}
    snap = xprof.ledger_snapshot()
    emit(piece="xprof_off", ms=round(ms_off, 4))
    emit(piece="xprof_sampled", ms=round(ms_sampled, 4))
    emit(piece="xprof_full", ms=round(ms_full, 4))
    pct_sampled = 100.0 * (ms_sampled - ms_off) / ms_off
    pct_full = 100.0 * (ms_full - ms_off) / ms_off
    emit(piece="xprof_summary",
         overhead_pct_sampled=round(pct_sampled, 3),
         overhead_pct_full=round(pct_full, 3),
         device_series="tree_phase_device_seconds" in series,
         ledger_programs=len(snap["programs"]),
         ledger_compiles=snap["total_compiles"],
         ok=bool(pct_sampled < 2.0),
         note="sampled block-until-ready on the per-level loop; "
              "bar is < 2% vs off")


def mesh_piece():
    """Hierarchical-mesh data-plane proofs: the staged ICI+DCN schedule
    vs the flat oracle, on whatever mesh the process booted with.

    Three kinds of JSON lines:
      - ``mesh_collective_proof`` (one per reduce_mode) — compiled-HLO
        evidence: the flat schedule lowers to ONE all-reduce whose
        replica group spans every device; the hier schedule lowers to
        TWO all-reduces whose groups are (a) each host's chips and
        (b) one rank per host — the dispatch-count pin that the staged
        collective is really two stages,
      - ``mesh_dcn_bytes`` — the cost-model arithmetic for a level-
        histogram payload: an all-reduce over p ranks moves
        2*bytes*(p-1)/p per rank, so the hier DCN stage has n_hosts
        participants moving one ALREADY-REDUCED tensor per host, where
        the flat ring has all n_devices ranks eligible to cross DCN,
      - ``mesh_psum_flat`` / ``mesh_psum_hier`` — measured ms per
        reduction of that payload (amortized fori-style, REPS deps).

    The {8,16,32}-device trees/sec curve lives in ``bench.py
    --multichip`` (fresh subprocess per device count); this piece proves
    the schedule, not the scaling.

    Usage (chip): python bench_pieces.py mesh
    CPU smoke:    JAX_PLATFORMS=cpu H2O3_TPU_HOSTS=2 \\
                  XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
                  python bench_pieces.py mesh
    """
    import re
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import h2o3_tpu
    from bench_util import timed_amortized
    from h2o3_tpu.runtime.cluster import ROW_AXIS, cluster
    from h2o3_tpu.runtime.compat import shard_map
    from h2o3_tpu.runtime.mapreduce import psum_shards

    cl = h2o3_tpu.init()
    platform = jax.devices()[0].platform
    hosts, chips = cl.n_hosts, cl.n_chips_per_host
    n_dev = cl.n_row_shards
    n = max(512 * n_dev, N_ROWS // 100 - (N_ROWS // 100) % (512 * n_dev))

    def emit(**rec):
        print(json.dumps({**rec, "platform": platform,
                          "mesh": dict(cl.mesh.shape)}), flush=True)

    # level-histogram payload: [3 planes, L leaves, F feats, B bins] f32
    L = 32
    payload_bytes = 3 * L * F * B * 4

    def make_program(mode):
        def body(x):
            partial = jnp.sum(x) * jnp.ones((3, L, F, B), jnp.float32)
            return psum_shards(partial, mode)
        return jax.jit(shard_map(
            body, mesh=cl.mesh, in_specs=P(ROW_AXIS), out_specs=P(),
            check_vma=False))

    x = jnp.ones((n,), jnp.float32)
    for mode in ("flat", "hier"):
        f = make_program(mode)
        txt = f.lower(x).compile().as_text()
        ars = [ln for ln in txt.splitlines() if "all-reduce" in ln
               and "replica_groups" in ln]
        groups = []
        for ln in ars:
            m = re.search(r"replica_groups=(\{\{.*?\}\})", ln)
            if m:
                groups.append(m.group(1)[:120])
        emit(piece="mesh_collective_proof", reduce_mode=mode,
             all_reduces=len(ars), replica_groups=groups,
             expect=("1 group spanning all devices" if mode == "flat"
                     else "stage 1: per-host chip rings; "
                          "stage 2: one rank per host"))

        def run(acc, xx, _f=f):
            return _f(xx + acc * 0.0)[0, 0, 0, 0] * 1e-30

        ms = timed_amortized(run, x, reps=REPS)
        emit(piece=f"mesh_psum_{mode}", ms=round(ms, 3),
             payload_bytes=payload_bytes)

    # all-reduce over p ranks moves 2*bytes*(p-1)/p per rank; in the flat
    # schedule every one of the n_dev ranks' transfers may cross DCN, in
    # the staged schedule only the n_hosts-rank second stage touches DCN
    # and its operand was already reduced chips-fold on ICI.
    flat_dcn = 2 * payload_bytes * (n_dev - 1) / n_dev * hosts
    hier_dcn = 2 * payload_bytes * (hosts - 1) / hosts * hosts \
        if hosts > 1 else 0.0
    emit(piece="mesh_dcn_bytes", payload_bytes=payload_bytes,
         n_devices=n_dev, hosts=hosts, chips_per_host=chips,
         flat_dcn_bytes=int(flat_dcn), hier_dcn_bytes=int(hier_dcn),
         dcn_reduction=round(flat_dcn / hier_dcn, 2) if hier_dcn else None,
         model="ring all-reduce: 2*B*(p-1)/p per rank; DCN ranks: "
               "flat=all chips on every host, hier=one per host")


def serve_piece():
    """Online-scoring latency bench: the packed fused-traversal program
    vs the ``ScoringModel`` numpy scorer, plus the continuous
    micro-batcher's request-level p50/p99/QPS.

    The bench ensemble is a binomial-GBM-shaped forest (trees/depth via
    H2O3_SERVE_TREES / H2O3_SERVE_DEPTH, default 300 x depth 10 over 32
    features — the airlines-shape serving profile) scored at B=256.
    Acceptance: packed >= 5x the numpy scorer at B=256.

    Usage (chip): python bench_pieces.py serve
    CPU smoke:    JAX_PLATFORMS=cpu python bench_pieces.py serve
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import threading
    import time as _time

    import jax

    import h2o3_tpu
    from h2o3_tpu.export.scoring import ScoringModel
    from h2o3_tpu.serving.batcher import MicroBatcher
    from h2o3_tpu.serving.kernel import PackedScorer

    h2o3_tpu.init()
    platform = jax.devices()[0].platform
    T = int(os.environ.get("H2O3_SERVE_TREES", 300))
    depth = int(os.environ.get("H2O3_SERVE_DEPTH", 10))
    Fs, Bb = 32, 256
    rng = np.random.default_rng(7)

    # synthetic binomial-GBM export: ~85%-split heap trees, f32 planes
    arrays = {}
    valid_prev = np.ones((T, 1), bool)
    for d in range(depth):
        W = 2 ** d
        arrays[f"feat_{d}"] = rng.integers(0, Fs, (T, W)).astype(np.int32)
        arrays[f"thr_{d}"] = rng.normal(size=(T, W)).astype(np.float32)
        arrays[f"na_left_{d}"] = rng.integers(0, 2, (T, W)).astype(bool)
        exist = np.repeat(valid_prev, 2, axis=1) if d else \
            np.ones((T, 1), bool)
        v = (rng.random((T, W)) < 0.85) & exist
        arrays[f"valid_{d}"] = v
        valid_prev = v
    arrays["values"] = (rng.normal(size=(T, 2 ** depth)) * 0.1) \
        .astype(np.float32)
    meta = {
        "algo": "gbm", "family": "tree", "tree_average": False,
        "nclass_trees": 1, "ntrees": T, "depth": depth,
        "link": "identity", "init_score": 0.0, "default_threshold": 0.5,
        "datainfo": {
            "specs": [{"name": f"x{i}", "type": "num", "domain": None,
                       "mean": 0.0, "sigma": 1.0, "offset": i, "width": 1}
                      for i in range(Fs)],
            "response_domain": ["no", "yes"], "response_column": "y",
            "use_all_factor_levels": False, "standardize": False,
            "add_intercept": False, "nfeatures": Fs,
        },
    }
    sm = ScoringModel(meta, arrays)
    ps = PackedScorer(sm)
    X = rng.normal(size=(Bb, Fs)).astype(np.float32)
    X[rng.random((Bb, Fs)) < 0.02] = np.nan
    cols = {f"x{i}": X[:, i] for i in range(Fs)}

    def emit(piece, **rec):
        print(json.dumps({"piece": piece, "platform": platform,
                          "trees": T, "depth": depth, "batch": Bb,
                          **rec}), flush=True)

    def timed_ms(fn, reps):
        fn()                                       # warm (AOT compile)
        t0 = _time.perf_counter()
        for _ in range(reps):
            fn()
        return (_time.perf_counter() - t0) * 1e3 / reps

    reps = max(REPS, 20)
    ref_ms = timed_ms(lambda: sm._score(cols, Bb), max(reps // 4, 5))
    packed_ms = timed_ms(lambda: ps.score(X), reps)
    speedup = ref_ms / packed_ms if packed_ms else float("inf")
    emit("serve_ref", ms=round(ref_ms, 4),
         note="ScoringModel numpy scorer (featurize + packed walk)")
    emit("serve_packed", ms=round(packed_ms, 4),
         n_nodes=ps.packed.n_nodes,
         packed_mb=round(ps.packed.nbytes() / 2 ** 20, 2))
    emit("serve_speedup", speedup=round(speedup, 2), ok=bool(speedup >= 5),
         note="acceptance bar: packed >= 5x numpy at B=256")

    # request-level latency through the continuous micro-batcher:
    # closed-loop clients, single-row requests (the REST realtime shape)
    mb = MicroBatcher(ps, max_batch=Bb, tick_ms=1.0, queue_depth=8192)
    mb.warmup()
    lat: list = []
    lat_lock = threading.Lock()
    n_clients, n_reqs = 8, 50
    rows1 = [np.ascontiguousarray(X[i % Bb:i % Bb + 1])
             for i in range(n_clients * n_reqs)]

    def client(c):
        mine = []
        for i in range(n_reqs):
            xi = rows1[c * n_reqs + i]
            t0 = _time.perf_counter()
            mb.submit(xi)
            mine.append((_time.perf_counter() - t0) * 1e3)
        with lat_lock:
            lat.extend(mine)

    t0 = _time.perf_counter()
    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = _time.perf_counter() - t0
    mb.close()
    p50 = float(np.percentile(lat, 50))
    p99 = float(np.percentile(lat, 99))
    qps = len(lat) / wall
    emit("serve_latency", serve_p50_ms=round(p50, 3),
         serve_p99_ms=round(p99, 3), serve_qps=round(qps, 1),
         clients=n_clients, requests=len(lat),
         note="single-row closed-loop clients through the micro-batcher")
    return {"serve_ref_ms": ref_ms, "serve_packed_ms": packed_ms,
            "serve_speedup": speedup, "serve_p50_ms": p50,
            "serve_p99_ms": p99, "serve_qps": qps}


def remat_piece():
    """Partial-vs-full recovery bench (the shard-lineage data plane).

    Times recovering ONE lost shard of a 4-host frame from lineage
    (survivor copy + a single ranged re-parse of the dead host's byte
    range) against the pre-lineage recovery unit: a full re-import of
    the source file.  ``remat_partial_vs_baseline`` is the speedup the
    gate tracks — the partial path must stay well under a full ingest.

    Usage:      python bench_pieces.py remat
    CPU smoke:  JAX_PLATFORMS=cpu H2O3_PIECES_ROWS=120000 \\
                python bench_pieces.py remat
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import tempfile

    import h2o3_tpu
    h2o3_tpu.init(hosts=4)
    from h2o3_tpu.frame import lineage
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.runtime import dkv, remat

    rows = min(N_ROWS, 500_000)
    rng = np.random.default_rng(11)
    body = np.column_stack([rng.random((rows, 4)).astype(np.float32),
                            rng.random(rows).astype(np.float32)])
    path = os.path.join(tempfile.gettempdir(), f"remat_bench_{rows}.csv")
    with open(path, "w") as f:
        f.write("x0,x1,x2,x3,y\n")
        f.write("\n".join(",".join(f"{v:.7g}" for v in r) for r in body))
        f.write("\n")
    mb = os.path.getsize(path) / 1e6

    import_file(path, destination_frame="remat_bench_fr")
    rec = lineage.get_record("remat_bench_fr")
    assert rec is not None and rec["n_shards"] == 4, "no lineage record"

    t0 = time.perf_counter()
    remat.recover_frame("remat_bench_fr", lost={1})
    partial = time.perf_counter() - t0
    s1 = rec["shards"][1]
    assert remat.last_stats["reparsed"] == [[s1["lo"], s1["hi"]]], \
        "partial recovery touched more than the lost shard's byte range"

    dkv.remove("remat_bench_fr")
    t0 = time.perf_counter()
    import_file(path, destination_frame="remat_bench_fr")
    full = time.perf_counter() - t0

    dkv.remove("remat_bench_fr")
    lineage.drop_record("remat_bench_fr")
    os.remove(path)
    print(json.dumps({
        "piece": "remat", "rows": rows, "mb": round(mb, 1),
        "remat_partial_s": round(partial, 3),
        "remat_full_s": round(full, 3),
        "remat_partial_vs_baseline": round(full / partial, 2)
        if partial else float("inf")}), flush=True)


def sched_piece():
    """Fair-share co-residency bench: small-job makespan beside a
    pod-holding large job, fair-share vs FIFO-behind-the-big-job.

    Synthetic chip-holding jobs (sleeps) isolate scheduler behavior
    from kernel throughput: the large job holds its chips for
    H2O3_SCHED_BIG_S seconds, each small job for H2O3_SCHED_SMALL_S.
    Fair-share gives the large job half the mesh (device_budget=0.5)
    so the smalls co-reside and finish in ~SMALL_S; the FIFO baseline
    gives it the full pod, so the smalls queue out the whole large job
    first.  Metrics feed tools/bench_gate.py: the makespans gate
    lower-is-better, ``sched_fair_vs_baseline`` higher-is-better.

    Usage: python bench_pieces.py sched    (host-side only; no chips)
    """
    import time as _time

    from h2o3_tpu.runtime.job import Job
    from h2o3_tpu.runtime.scheduler import ClusterScheduler

    BIG_S = float(os.environ.get("H2O3_SCHED_BIG_S", 2.0))
    SMALL_S = float(os.environ.get("H2O3_SCHED_SMALL_S", 0.3))
    N_SMALL = int(os.environ.get("H2O3_SCHED_SMALLS", 3))

    def hold(seconds):
        def fn(job):
            end = _time.monotonic() + seconds
            while _time.monotonic() < end:
                _time.sleep(0.01)
        return fn

    def small_makespan(big_budget):
        s = ClusterScheduler(capacity=8, queue_limit=64, elastic=False)
        try:
            big = Job("sched-bench big")
            s.submit(big, hold(BIG_S), device_budget=big_budget,
                     user="bench-big")
            t0 = _time.monotonic()
            smalls = []
            for i in range(N_SMALL):
                j = Job(f"sched-bench small {i}")
                s.submit(j, hold(SMALL_S), device_budget=1,
                         user=f"bench-small-{i}")
                smalls.append(j)
            for j in smalls:
                j.join()
            span = _time.monotonic() - t0
            big.join()
            return span
        finally:
            s.stop()

    def emit(piece, **rec):
        print(json.dumps({"piece": piece, **rec}), flush=True)

    fifo = small_makespan(1.0)      # pod-holding: smalls wait it out
    fair = small_makespan(0.5)      # half the mesh: smalls co-reside
    ratio = fifo / fair if fair else float("inf")
    emit("sched_fifo", sched_small_makespan_fifo_s=round(fifo, 3),
         big_s=BIG_S, small_s=SMALL_S, n_small=N_SMALL,
         note="baseline: large job holds the full pod")
    emit("sched_fair", sched_small_makespan_fair_s=round(fair, 3),
         note="large job at device_budget=0.5; smalls co-resident")
    emit("sched_speedup", sched_fair_vs_baseline=round(ratio, 2),
         ok=bool(fair < fifo),
         note="acceptance bar: fair-share makespan below FIFO")
    return {"sched_small_makespan_fifo_s": fifo,
            "sched_small_makespan_fair_s": fair,
            "sched_fair_vs_baseline": ratio}


def autotune_piece():
    """Cost-model autotuner bench: cold-cache vs warm-cache vs best
    hand-set trees/s on one GBM signature.

    Three trainings of the same airlines-shaped regression GBM:
      * best hand-set — each hand-tunable (hist_mode, split_mode)
        combination timed steady-state, best throughput kept;
      * auto, cold cache — knobs "auto" with an empty cache dir, so the
        roofline model seeds the choice at trace time;
      * auto, warm cache — tuner state reset but the cache file kept,
        so the choice comes back source="cache" with zero re-measures.

    ``autotune_vs_best`` (warm auto / best hand-set) is the gate metric:
    tools/bench_gate.py holds it to an absolute floor of 0.97 — the
    tuner is never allowed to be meaningfully slower than the best
    hand-set configuration on a seen signature.

    Usage (chip): python bench_pieces.py autotune
    CPU smoke:    JAX_PLATFORMS=cpu H2O3_PIECES_ROWS=50000 \\
                  python bench_pieces.py autotune
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import shutil
    import tempfile
    import time as _time

    import jax

    import h2o3_tpu
    from h2o3_tpu import Frame
    from h2o3_tpu.models.tree.gbm import GBM
    from h2o3_tpu.runtime import autotune
    from h2o3_tpu.runtime import config as _cfg

    h2o3_tpu.init()
    platform = jax.devices()[0].platform
    rows = min(N_ROWS, 200_000)
    trees = int(os.environ.get("H2O3_AUTOTUNE_TREES", 16))
    reps = int(os.environ.get("H2O3_AUTOTUNE_REPS", 3))
    rng = np.random.default_rng(5)
    Fs = 8
    X = rng.normal(size=(rows, Fs)).astype(np.float64)
    y = (X[:, 0] * 0.7 - X[:, 1] ** 2 * 0.2
         + 0.1 * rng.normal(size=rows))
    fr = Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(Fs)}, "y": y})
    kw = dict(response_column="y", ntrees=trees, max_depth=6, nbins=64,
              min_rows=10, seed=3)

    def timed(**knob_kw):
        t0 = _time.perf_counter()
        GBM(**kw, **knob_kw).train(fr)
        return _time.perf_counter() - t0

    def tps(**knob_kw):
        """Steady-state trees/s: warm the jit caches once, then take
        the best of ``reps`` timed trainings."""
        GBM(**kw, **knob_kw).train(fr)
        return trees / min(timed(**knob_kw) for _ in range(reps))

    saved = {k: os.environ.get(k) for k in
             ("H2O3_TPU_AUTOTUNE", "H2O3_TPU_AUTOTUNE_CACHE_DIR")}
    cache_dir = tempfile.mkdtemp(prefix="autotune_bench_")
    try:
        # hand-set sweep (tuner off: the knobs mean what they say)
        os.environ["H2O3_TPU_AUTOTUNE"] = "off"
        _cfg.reload()
        autotune.reset()
        hand = {}
        for hm, sm in (("subtract", "fused"), ("full", "fused"),
                       ("subtract", "separate")):
            hand[f"{hm}|{sm}"] = tps(hist_mode=hm, split_mode=sm)
        best_key = max(hand, key=hand.get)
        bhm, bsm = best_key.split("|")

        os.environ["H2O3_TPU_AUTOTUNE"] = "on"
        os.environ["H2O3_TPU_AUTOTUNE_CACHE_DIR"] = cache_dir
        _cfg.reload()
        autotune.reset()
        cold = tps()                       # model-seeded decision
        autotune.reset()                   # drop memory, keep the file
        # warm-cache vs best-hand-set: interleaved timings so host-side
        # drift (GC, turbo, noisy neighbors) hits both sides equally —
        # the choices usually name the SAME kernels, and the gate ratio
        # must reflect the tuner's decision, not the clock's mood
        GBM(**kw).train(fr)                          # warm: cache hit
        GBM(**kw, hist_mode=bhm, split_mode=bsm).train(fr)
        t_warm, t_hand = float("inf"), float("inf")
        for _ in range(reps):
            t_hand = min(t_hand, timed(hist_mode=bhm, split_mode=bsm))
            t_warm = min(t_warm, timed())
        warm = trees / t_warm
        hand[best_key] = max(hand[best_key], trees / t_hand)
        ratio = t_hand / t_warm if t_warm else float("inf")
        table = autotune.decision_table()
        warm_sources = sorted({d["source"] for d in table["decisions"]
                               if d["signature"].startswith("gbm")}) \
            or ["none"]
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        _cfg.reload()
        autotune.reset()
        shutil.rmtree(cache_dir, ignore_errors=True)

    print(json.dumps({
        "piece": "autotune", "platform": platform, "rows": rows,
        "trees": trees,
        "autotune_hand_best": best_key,
        "autotune_hand_trees_per_sec": round(hand[best_key], 2),
        "autotune_cold_trees_per_sec": round(cold, 2),
        "autotune_warm_trees_per_sec": round(warm, 2),
        "autotune_vs_best": round(ratio, 3),
        "warm_sources": warm_sources,
        "note": "gate: autotune_vs_best >= 0.97 absolute floor"}),
        flush=True)
    return {"autotune_hand_trees_per_sec": hand[best_key],
            "autotune_cold_trees_per_sec": cold,
            "autotune_warm_trees_per_sec": warm,
            "autotune_vs_best": ratio}


def stream_piece():
    """Streaming-ingest overlap bench: end-to-end wall-clock of
    (StreamingFrame + stream= GBM training) vs (parse fully, then
    train) on the same synthetic CSV.

    The streamed run starts boosting once half the rows have landed
    (H2O3_TPU_STREAM_MIN_ROWS = rows/2, quantized via
    H2O3_TPU_STREAM_ROUND_ROWS so repeat runs reuse compiled shapes):
    early trees train on the landed prefix while the rest of the file
    tokenizes, so ingest disappears from the critical path and the
    prefix segments are cheaper than full-frame rounds.  Both paths are
    run once to warm the jit caches, then timed.

    ``stream_overlap_vs_baseline`` (batch / streamed, higher is better)
    is the gate metric: tools/bench_gate.py holds it to an absolute
    floor of 1.176 — streamed end-to-end must stay at or under 0.85x of
    parse-then-train wall-clock.

    Usage (chip): python bench_pieces.py stream
    CPU smoke:    JAX_PLATFORMS=cpu H2O3_PIECES_ROWS=120000 \\
                  python bench_pieces.py stream
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import tempfile
    import time as _time

    import jax

    import h2o3_tpu
    from h2o3_tpu.frame.parse import parse_csv
    from h2o3_tpu.models.tree.gbm import GBM
    from h2o3_tpu.runtime import config as _cfg
    from h2o3_tpu.runtime import dkv

    h2o3_tpu.init()
    platform = jax.devices()[0].platform
    rows = min(N_ROWS, int(os.environ.get("H2O3_STREAM_ROWS", 400_000)))
    trees = int(os.environ.get("H2O3_STREAM_TREES", 24))
    rng = np.random.default_rng(11)
    Fs = 8
    path = os.path.join(tempfile.gettempdir(), f"stream_bench_{rows}.csv")
    if not os.path.exists(path):
        with open(path, "w") as f:
            f.write(",".join(f"x{i}" for i in range(Fs)) + ",g,y\n")
            block = 50_000
            for lo in range(0, rows, block):
                n = min(block, rows - lo)
                X = rng.normal(size=(n, Fs))
                g = rng.integers(0, 12, size=n)
                yv = (X[:, 0] * 0.7 - X[:, 1] ** 2 * 0.2 + 0.05 * g
                      + 0.2 * rng.normal(size=n)) > 0
                for r_ in range(n):
                    f.write(",".join(f"{v:.5f}" for v in X[r_]) +
                            f",lvl{g[r_]},c{int(yv[r_])}\n")
    kw = dict(response_column="y", ntrees=trees, max_depth=6, nbins=64,
              min_rows=10, seed=7, score_tree_interval=4)

    saved = {k: os.environ.get(k) for k in
             ("H2O3_TPU_STREAM_MIN_ROWS", "H2O3_TPU_STREAM_ROUND_ROWS",
              "H2O3_TPU_STREAM_GROW_MIN_FRAC",
              "H2O3_TPU_STREAM_BUFFER_ROWS", "H2O3_PARSE_RANGE_MIN")}
    # smoke-sized files must still land as MANY ranges (the default
    # 4 MB ranged-parse threshold would make the whole file one range
    # and the watermark a single step)
    os.environ["H2O3_PARSE_RANGE_MIN"] = str(
        min(1 << 22, max(65536, os.path.getsize(path) // 16)))
    os.environ["H2O3_TPU_STREAM_MIN_ROWS"] = str(rows // 2)
    os.environ["H2O3_TPU_STREAM_ROUND_ROWS"] = str(rows // 2)
    os.environ["H2O3_TPU_STREAM_GROW_MIN_FRAC"] = "0.25"
    # backpressure at 3/4 of the file: landing can never run more than
    # that ahead of training, so the first segment ALWAYS boosts on the
    # half-frame prefix while the tail is still in flight — the overlap
    # being measured, made deterministic across file sizes — and the
    # landed-fraction tree budget lets ~3/4 of the trees train on the
    # cheap prefix before the cut
    os.environ["H2O3_TPU_STREAM_BUFFER_ROWS"] = str(3 * rows // 4)
    _cfg.reload()

    def batch_run(tag):
        t0 = _time.perf_counter()
        fr = parse_csv(path, destination_frame=tag)
        m = GBM(**kw).train(fr)
        dt = _time.perf_counter() - t0
        dkv.remove(tag)
        return dt, m

    def stream_run(tag):
        t0 = _time.perf_counter()
        sf = h2o3_tpu.stream_file(path, destination_frame=tag)
        m = GBM(**kw, stream=True).train(sf)
        sf.frame()               # model AND fully-landed frame ready
        dt = _time.perf_counter() - t0
        dkv.remove(tag)
        return dt, m

    try:
        batch_run("stb_warm")       # warm jit caches: full-frame shapes
        stream_run("sts_warm")      # ... and the half-frame segment
        reps = int(os.environ.get("H2O3_STREAM_REPS", 2))
        batch_s = min(batch_run(f"stb_t{i}")[0] for i in range(reps))
        stream_s, m = min((stream_run(f"sts_t{i}") for i in range(reps)),
                          key=lambda r: r[0])
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        _cfg.reload()
    ratio = batch_s / stream_s
    print(json.dumps({
        "piece": "stream", "platform": platform, "rows": rows,
        "trees": trees,
        "stream_batch_s": round(batch_s, 3),
        "stream_overlap_s": round(stream_s, 3),
        "stream_overlap_vs_baseline": round(ratio, 3),
        "stream_segments": m.output.get("stream_segments"),
        "stream_coverage": m.output.get("stream_coverage"),
        "note": "gate: stream_overlap_vs_baseline >= 1.176 absolute "
                "floor (streamed <= 0.85x batch wall-clock)"}),
        flush=True)
    return {"stream_batch_s": batch_s, "stream_overlap_s": stream_s,
            "stream_overlap_vs_baseline": ratio,
            "stream_segments": m.output.get("stream_segments")}




def treescan_piece():
    """Whole-tree scan-fusion bench: tree_program="scan" vs "level" on
    the deep-tree shape (max_depth 10, small N — the regime where
    per-level dispatch and the unrolled 2*depth-kernel program dominate
    a tree's cost).

    Two proofs land:
      * dispatch pin — ``count_kernel_launches`` (runtime/xprof.py)
        counts kernel dispatch SITES in the traced build program.  The
        level program carries one histogram launch per level (grows
        with depth); the scan program is pinned O(1) regardless of
        depth (one scan-carried hist body + one level-0 seed).  Both
        counts are emitted at depth 6 and 10; the gate holds the scan
        count lower-better from this round on.
      * trees/s — the same deep GBM trained under both programs.
        ``treescan_cold_*`` includes compile (the scan program is one
        small scan body instead of 2*depth unrolled kernels — this is
        the serving-adjacent retrain-latency win);
        ``treescan_trees_per_sec_*`` is steady-state post-warmup.

    ``treescan_scan_vs_level_speedup`` (cold scan / cold level, higher
    is better) is the headline gate metric.

    Usage (chip): python bench_pieces.py treescan
    CPU smoke:    JAX_PLATFORMS=cpu H2O3_PIECES_ROWS=30000 \\
                  python bench_pieces.py treescan
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import time as _time

    import jax
    import jax.numpy as jnp

    import h2o3_tpu
    from h2o3_tpu import Frame
    from h2o3_tpu.models.tree.gbm import GBM
    from h2o3_tpu.models.tree.shared import make_build_tree_fn
    from h2o3_tpu.runtime.xprof import count_kernel_launches

    h2o3_tpu.init()
    platform = jax.devices()[0].platform
    rows = min(N_ROWS, 30_000)
    trees = int(os.environ.get("H2O3_TREESCAN_TREES", 16))
    cold_trees = int(os.environ.get("H2O3_TREESCAN_COLD_TREES", 4))
    depth = int(os.environ.get("H2O3_TREESCAN_DEPTH", 10))
    nbins = 64
    Fs = 8

    # ---- dispatch pin: launches per tree from the traced jaxpr
    rng = np.random.default_rng(9)
    Nb = 4096
    codes = jnp.asarray(rng.integers(0, nbins, (Fs, Nb)), jnp.int32)
    g = jnp.asarray(rng.normal(size=Nb), jnp.float32)
    hh = jnp.ones(Nb, jnp.float32)
    ww = jnp.ones(Nb, jnp.float32)
    edges = jnp.sort(jnp.asarray(rng.normal(size=(Fs, nbins)),
                                 jnp.float32), axis=1)
    args = (codes, g, hh, ww, edges, jax.random.PRNGKey(1), 0.0, 1.0,
            1e-5, 0.1, 1.0, jnp.ones(Fs, bool), 0.0, 0.0, 0.0)
    launches = {}
    for md in (6, depth):
        for prog in ("level", "scan"):
            fn = make_build_tree_fn(md, nbins, Fs, Nb, "f32",
                                    tree_program=prog)
            launches[f"{prog}_d{md}"] = count_kernel_launches(fn, *args)

    # ---- trees/s on the deep shape, both programs
    X = rng.normal(size=(rows, Fs)).astype(np.float64)
    y = (np.sin(3 * X[:, 0]) + X[:, 1] * X[:, 2]
         + 0.1 * rng.normal(size=rows))
    fr = Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(Fs)}, "y": y})
    # dense layout pinned on both sides: the scan program composes with
    # dense uniform kernels only (node-sparse slot maps reshape per
    # level), and an apples-to-apples comparison needs one layout
    kw = dict(response_column="y", ntrees=trees, max_depth=depth,
              nbins=nbins, min_rows=5, seed=3, hist_layout="dense",
              score_tree_interval=trees)

    def cold(prog):
        """Fresh-program retrain: compile + a short boost (the
        serving-adjacent retrain-latency shape — compile cost is the
        point, so the tree count stays small)."""
        jax.clear_caches()
        from h2o3_tpu.models.tree import hist as _h, shared as _s
        for f in (_h.make_hist_fn, _h.make_subtract_level_fn,
                  _h.make_batched_level_fn, _h.make_scan_level_fn,
                  _h.make_batched_scan_level_fn, _s.make_build_tree_fn,
                  _s.make_tree_scan_fn):
            f.cache_clear()
        t0 = _time.perf_counter()
        GBM(**{**kw, "ntrees": cold_trees,
               "score_tree_interval": cold_trees},
            tree_program=prog).train(fr)
        return _time.perf_counter() - t0

    def steady(prog):
        GBM(**kw, tree_program=prog).train(fr)      # warm the caches
        best = float("inf")
        for _ in range(3):
            t0 = _time.perf_counter()
            GBM(**kw, tree_program=prog).train(fr)
            best = min(best, _time.perf_counter() - t0)
        return best

    cold_level = cold("level")
    cold_scan = cold("scan")
    steady_level = steady("level")
    steady_scan = steady("scan")
    speedup = cold_level / cold_scan if cold_scan else float("inf")

    rec = {
        "piece": "treescan", "platform": platform, "rows": rows,
        "trees": trees, "depth": depth,
        "treescan_launches_per_tree_scan": launches[f"scan_d{depth}"],
        "treescan_launches_per_tree_level": launches[f"level_d{depth}"],
        "treescan_launches_scan_d6": launches["scan_d6"],
        "treescan_launches_level_d6": launches["level_d6"],
        "cold_trees": cold_trees,
        "treescan_cold_level_s": round(cold_level, 3),
        "treescan_cold_scan_s": round(cold_scan, 3),
        "treescan_trees_per_sec_level": round(trees / steady_level, 2),
        "treescan_trees_per_sec_scan": round(trees / steady_scan, 2),
        "treescan_scan_vs_level_speedup": round(speedup, 3),
        "launches_depth_independent": bool(
            launches[f"scan_d{depth}"] == launches["scan_d6"]),
        "note": "dispatch pin: scan launches O(1) in depth vs "
                "one-per-level; speedup = fresh-program retrain "
                "(compile + short boost) level/scan wall",
    }
    print(json.dumps(rec), flush=True)
    return rec


def grid_piece():
    """Batched grid sweep bench: G same-shape members as ONE program.

    Two proofs land:
      * dispatch pin — ``count_kernel_launches`` over the traced chunk
        programs.  The batched G-member cohort program carries the SAME
        dispatch-site count as ONE sequential member's program (the
        model axis rides the kernels' ``nk`` batch dim, it adds no
        launches), so a sequential G-member sweep pays G× the dispatches
        per chunk while the cohort pays 1×.
        ``grid_batched_vs_sequential`` = G·L_seq / L_batched is that
        dispatch ratio — the platform-independent quantity the ~4 ms/
        launch tunnel turns into wall-clock on chip ("G configs for the
        price of ~1 dispatch").  Also pinned: the batched count is
        G-INDEPENDENT (G=2 and G=8 trace to identical counts).
      * wall clocks + bitwise parity — the same G-member sweep trained
        batched (grid_batch="on") vs the sequential wave path ("off"),
        warm.  On the CPU host the kernels are compute-bound, so the
        wall ratio sits near 1 (recorded as context); the parity check
        is the real assertion — every batched member's predictions are
        BITWISE equal to its sequential twin's.

    Usage (chip): python bench_pieces.py grid
    CPU smoke:    JAX_PLATFORMS=cpu H2O3_PIECES_ROWS=20000 \\
                  python bench_pieces.py grid
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import time as _time

    import jax
    import jax.numpy as jnp

    import h2o3_tpu
    from h2o3_tpu import Frame
    from h2o3_tpu.models.grid import GridSearch
    from h2o3_tpu.models.tree.gbm import GBM
    from h2o3_tpu.models.tree.shared import (make_grid_scan_fn,
                                             make_tree_scan_fn)
    from h2o3_tpu.runtime.xprof import count_kernel_launches

    h2o3_tpu.init()
    platform = jax.devices()[0].platform
    rows = min(N_ROWS, 20_000)
    G = int(os.environ.get("H2O3_GRID_MEMBERS", 8))
    trees = int(os.environ.get("H2O3_GRID_TREES", 16))
    depth = 5
    nbins = 64
    Fs = 8

    # ---- dispatch pin: launch sites per chunk from the traced jaxprs
    rng = np.random.default_rng(17)
    Nb = 4096
    nchunk = 5
    codes = jnp.asarray(rng.integers(0, nbins, (Fs, Nb)), jnp.int32)
    yv = jnp.asarray(rng.normal(size=Nb), jnp.float32)
    wv = jnp.ones(Nb, jnp.float32)
    F0 = jnp.zeros(Nb, jnp.float32)
    edges = jnp.sort(jnp.asarray(rng.normal(size=(Fs, nbins)),
                                 jnp.float32), axis=1)
    seq_fn = make_tree_scan_fn("gaussian", 1.5, 0.5, 0.9, depth, nbins,
                               Fs, Nb, "f32", 1.0, 1.0)
    seq_args = (codes, yv, wv, F0, edges, jax.random.PRNGKey(1), 0,
                nchunk, 1.0, 10.0, 1e-5, 0.1, 1.0, 0.0, 0.0, 0.0, 0)
    L_seq = count_kernel_launches(seq_fn, *seq_args,
                                  static_argnums=(7,))
    L_grid = {}
    for g in (2, G):
        gfn = make_grid_scan_fn(g, "gaussian", 1.5, 0.5, 0.9, depth,
                                nbins, Fs, Nb, "f32")
        arr = lambda v, n=g: jnp.full((n,), v, jnp.float32)
        gargs = (codes, yv, wv,
                 jnp.zeros((g, Nb), jnp.float32), edges,
                 jnp.stack([jax.random.PRNGKey(i) for i in range(g)]),
                 0, nchunk, arr(1.0), arr(10.0), arr(1e-5), arr(0.1),
                 arr(1.0), arr(1.0), arr(1.0),
                 jnp.ones((g,), bool), arr(0.0), arr(0.0), arr(0.0))
        L_grid[g] = count_kernel_launches(gfn, *gargs,
                                          static_argnums=(7,))
    dispatch_ratio = G * L_seq / L_grid[G]

    # ---- wall clocks + bitwise parity, batched vs the wave path
    X = rng.normal(size=(rows, Fs)).astype(np.float64)
    yr = (np.sin(3 * X[:, 0]) + X[:, 1] * X[:, 2]
          + 0.1 * rng.normal(size=rows))
    fr = Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(Fs)}, "y": yr})
    lrs = [round(0.02 + 0.03 * i, 3) for i in range(G)]
    hp = {"learn_rate": lrs}
    kw = dict(response_column="y", ntrees=trees, max_depth=depth,
              nbins=nbins, seed=3, score_tree_interval=trees,
              hist_layout="dense", reproducible=True)

    def sweep(mode):
        GridSearch(GBM, hp, grid_batch=mode, **kw).train(fr)  # warm
        t0 = _time.perf_counter()
        g = GridSearch(GBM, hp, grid_batch=mode, **kw).train(fr)
        return _time.perf_counter() - t0, g

    wall_b, g_on = sweep("on")
    wall_s, g_off = sweep("off")
    assert all(m.output.get("grid_cohort", {}).get("size") == G
               for m in g_on.models), "cohort did not engage"
    GBM(learn_rate=lrs[0], **kw).train(fr)                    # warm
    t0 = _time.perf_counter()
    GBM(learn_rate=lrs[0], **kw).train(fr)
    wall_1 = _time.perf_counter() - t0

    by_lr = lambda g: {m.params.learn_rate: m for m in g.models}
    mo, mf = by_lr(g_on), by_lr(g_off)
    bitwise = all(
        np.array_equal(mo[k].predict(fr).to_numpy()[:, 0],
                       mf[k].predict(fr).to_numpy()[:, 0]) for k in mo)
    assert bitwise, "batched cohort diverged from the sequential path"

    rec = {
        "piece": "grid", "platform": platform, "rows": rows,
        "trees": trees, "grid_members": G,
        "grid_launches_batched": L_grid[G],
        "grid_launches_sequential_member": L_seq,
        "grid_batched_vs_sequential": round(dispatch_ratio, 3),
        "grid_launches_g_independent": bool(L_grid[2] == L_grid[G]),
        "grid_batched_wall_s": round(wall_b, 3),
        "grid_sequential_wall_s": round(wall_s, 3),
        "grid_one_member_wall_s": round(wall_1, 3),
        "grid_bitwise_equal": bitwise,
        "note": "dispatch pin: one batched cohort program serves G "
                "members per chunk at a single member's launch count "
                "(ratio = G on any platform); walls are CPU-host "
                "context — compute-bound there, dispatch-bound on chip",
    }
    print(json.dumps(rec), flush=True)
    return rec


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "parse":
        parse_piece()
    elif len(sys.argv) > 1 and sys.argv[1] == "hist":
        hist_piece()
    elif len(sys.argv) > 1 and sys.argv[1] == "splits":
        splits_piece()
    elif len(sys.argv) > 1 and sys.argv[1] == "deep":
        deep_piece()
    elif len(sys.argv) > 1 and sys.argv[1] == "obs":
        obs_piece()
    elif len(sys.argv) > 1 and sys.argv[1] == "xprof":
        xprof_piece()
    elif len(sys.argv) > 1 and sys.argv[1] == "mesh":
        mesh_piece()
    elif len(sys.argv) > 1 and sys.argv[1] == "serve":
        serve_piece()
    elif len(sys.argv) > 1 and sys.argv[1] == "sched":
        sched_piece()
    elif len(sys.argv) > 1 and sys.argv[1] == "remat":
        remat_piece()
    elif len(sys.argv) > 1 and sys.argv[1] == "autotune":
        autotune_piece()
    elif len(sys.argv) > 1 and sys.argv[1] == "stream":
        stream_piece()
    elif len(sys.argv) > 1 and sys.argv[1] == "treescan":
        treescan_piece()
    elif len(sys.argv) > 1 and sys.argv[1] == "grid":
        grid_piece()
    else:
        main()
