"""Elastic fair-share scheduler: admission, packing, cancel, degraded-mode
requeue, membership/quarantine, and restart re-admission.  (Process-kill
variants live in test_chaos.py.)"""

import importlib.util
import os
import threading
import time

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.runtime import dkv, failure, heartbeat, recovery
from h2o3_tpu.runtime import observability as obs
from h2o3_tpu.runtime import scheduler as sched_mod
from h2o3_tpu.runtime.job import (CANCELLED, DONE, FAILED, RUNNING, Job,
                                  JobScheduler, scheduler)
from h2o3_tpu.runtime.scheduler import (PRIORITY_ADMIN, PRIORITY_BUILD,
                                        PRIORITY_INTERACTIVE,
                                        ClusterScheduler, Quarantine)


def _binary_frame(seed, n, dest):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=n).astype(np.float32)
    y = np.where(x + 0.3 * rng.normal(size=n) > 0, "Y", "N")
    return h2o3_tpu.H2OFrame({"x": x, "y": y.astype(object)},
                             destination_frame=dest)


# ------------------------------------------------------------ budget mapping
def test_budget_chip_mapping():
    s = ClusterScheduler(capacity=8, queue_limit=4)
    try:
        assert s._chips_for(None, 8) == 4        # default fraction 0.5
        assert s._chips_for(0.125, 8) == 1
        assert s._chips_for(1.0, 8) == 8
        assert s._chips_for(3, 8) == 3
        assert s._chips_for(100, 8) == 8         # capped at the mesh
        with pytest.raises(ValueError):
            s._chips_for(0, 8)
        with pytest.raises(ValueError):
            s._chips_for(-1.5, 8)
        # submit validates the budget before touching the queue
        with pytest.raises(ValueError):
            s.submit(Job("bad budget"), lambda j: None, device_budget=-2)
    finally:
        s.stop()


def test_fit_hosts():
    assert sched_mod._fit_hosts(1, 8) == 1
    assert sched_mod._fit_hosts(2, 8) == 2
    assert sched_mod._fit_hosts(3, 8) == 2       # 3 does not divide 8
    assert sched_mod._fit_hosts(5, 8) == 4
    assert sched_mod._fit_hosts(8, 8) == 8
    assert sched_mod._fit_hosts(2, 6) == 2


# ----------------------------------------------------------- packing + order
def test_small_jobs_pack_beside_large_job():
    s = ClusterScheduler(capacity=8, queue_limit=16)
    order, lock = [], threading.Lock()
    big_started, big_release = threading.Event(), threading.Event()

    def big_fn(job):
        with lock:
            order.append("big-start")
        big_started.set()
        big_release.wait(30)
        with lock:
            order.append("big-end")

    def small_fn(name):
        def fn(job):
            with lock:
                order.append(name)
        return fn

    big = Job("big train")
    try:
        s.submit(big, big_fn, device_budget=0.5, user="alice")
        assert big_started.wait(10)
        smalls = [Job(f"small {i}") for i in range(3)]
        for i, j in enumerate(smalls):
            s.submit(j, small_fn(f"s{i}"), device_budget=1, user=f"u{i}")
        for j in smalls:
            j.join(timeout=30)
        # the smalls completed WHILE the big job still held its chips:
        # concurrency is real, not FIFO-behind-the-big-job
        assert big.status == RUNNING
        assert all(j.status == DONE for j in smalls)
    finally:
        big_release.set()
    big.join(timeout=30)
    assert order[0] == "big-start" and order[-1] == "big-end"
    assert set(order[1:-1]) == {"s0", "s1", "s2"}
    s.stop()


def test_priority_then_fair_share_then_fifo():
    s = ClusterScheduler(capacity=1, queue_limit=16)
    order, lock = [], threading.Lock()
    started, release = threading.Event(), threading.Event()

    def blocker_fn(job):
        started.set()
        release.wait(30)

    def named(name):
        def fn(job):
            with lock:
                order.append(name)
        return fn

    blocker = Job("blocker")
    try:
        s.submit(blocker, blocker_fn, device_budget=1)
        assert started.wait(10)
        ja, jb, jadm = Job("build a"), Job("build b"), Job("admin ping")
        s.submit(ja, named("a"), priority=PRIORITY_BUILD,
                 device_budget=1, user="a")
        s.submit(jb, named("b"), priority=PRIORITY_BUILD,
                 device_budget=1, user="b")
        s.submit(jadm, named("admin"), priority=PRIORITY_ADMIN,
                 device_budget=1, user="a")
        with s._cv:                  # tenant "a" has burned chip-seconds
            s._usage["a"] = 100.0
            s._usage["b"] = 0.0
    finally:
        release.set()
    for j in (ja, jb, jadm, blocker):
        j.join(timeout=30)
    # admin priority first, then the under-served tenant, then FIFO
    assert order == ["admin", "b", "a"]
    s.stop()


# ---------------------------------------------------------------- admission
def test_admission_queue_full_rejects():
    s = ClusterScheduler(capacity=1, queue_limit=2)
    started, release = threading.Event(), threading.Event()
    blocker = Job("blocker")
    q1, q2 = Job("q1"), Job("q2")
    try:
        s.submit(blocker, lambda j: (started.set(), release.wait(30)),
                 device_budget=1)
        assert started.wait(10)
        s.submit(q1, lambda j: None, device_budget=1)
        s.submit(q2, lambda j: None, device_budget=1)
        before = obs.counter("sched_admission_rejected_total",
                             reason="queue_full").value
        overflow = Job("q3")
        with pytest.raises(RuntimeError, match="admission queue full"):
            s.submit(overflow, lambda j: None, device_budget=1)
        if obs.enabled():
            assert obs.counter("sched_admission_rejected_total",
                               reason="queue_full").value == before + 1
        dkv.remove(overflow.key)
        q1.cancel()
        q2.cancel()
        assert q1.status == CANCELLED and q2.status == CANCELLED
    finally:
        release.set()
    blocker.join(timeout=30)
    s.stop()


# ------------------------------------------------------------------- cancel
def test_cancel_queued_job_never_runs():
    s = ClusterScheduler(capacity=1, queue_limit=8)
    started, release = threading.Event(), threading.Event()
    ran = []
    blocker, victim = Job("blocker"), Job("victim")
    try:
        s.submit(blocker, lambda j: (started.set(), release.wait(30)),
                 device_budget=1)
        assert started.wait(10)
        s.submit(victim, lambda j: ran.append(1), device_budget=1)
        victim.cancel()
        assert victim.status == CANCELLED
        assert victim.join() is None
        assert not ran                              # fn never executed
        # its WAL-mirrored scheduling record is gone too
        assert dkv.get(sched_mod.SCHED_PREFIX + victim.key) is None
    finally:
        release.set()
    blocker.join(timeout=30)
    assert not ran
    s.stop()


def test_legacy_jobscheduler_cancel_and_escaped_exception():
    js = JobScheduler(workers=1)
    started, release = threading.Event(), threading.Event()
    ran = []
    blocker = Job("blocker")
    try:
        js.submit(blocker, lambda j: (started.set(), release.wait(30)))
        assert started.wait(10)
        victim = Job("victim")
        js.submit(victim, lambda j: ran.append(1))
        victim.cancel()
        assert victim.status == CANCELLED and not ran

        # an exception that escapes Job.run entirely (run itself blows
        # up before any bookkeeping) must still reach the job: joiners
        # are released with the error, never left hanging
        weird = Job("weird")

        def boom_run(fn):
            raise RuntimeError("escaped worker exception")

        weird.run = boom_run
        js.submit(weird, lambda j: None)
    finally:
        release.set()
    blocker.join(timeout=30)
    with pytest.raises(RuntimeError, match="escaped worker exception"):
        weird.join(timeout=30)
    assert weird.status == FAILED and not ran
    js.stop()


def test_sched_assign_injection_reaches_job_fail(cl, monkeypatch):
    failure.reset()
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "sched_assign:0:1:raise")
    s = ClusterScheduler(capacity=4, queue_limit=8)
    job = Job("doomed")
    try:
        s.submit(job, lambda j: "ok", device_budget=1)
        with pytest.raises(failure.InjectedFault):
            job.join(timeout=30)
        assert job.status == FAILED
    finally:
        failure.reset()
        s.stop()
        dkv.remove(sched_mod.SCHED_PREFIX + job.key)


# -------------------------------------------------------------- degraded mode
def test_node_death_requeues_job_with_retry_budget(cl, tmp_path, monkeypatch):
    """A host death mid-job requeues the SAME Job from its journal onto
    the surviving mesh: joiners still get the model, retries == 1."""
    from h2o3_tpu.models import GBM
    monkeypatch.setenv("H2O3_TPU_RECOVERY_DIR", str(tmp_path))
    failure.reset()
    fr = _binary_frame(11, 400, "sched_requeue_fr")
    builder = GBM(response_column="y", ntrees=3, max_depth=2, seed=2)
    job = Job("victim train")
    uri = recovery.journal_start(builder, fr, job)
    assert uri
    job.journal_uri = uri
    started, wedge = threading.Event(), threading.Event()

    def wedged_fn(j):
        started.set()
        wedge.wait(60)     # models a worker blocked in a dead collective

    s = scheduler()        # module singleton: the watchdog path reaches it
    ghost = "sched_ghost_requeue"
    try:
        s.submit(job, wedged_fn, device_budget=0.5, retry_budget=1,
                 user="tenant")
        assert started.wait(15)
        dkv.put(heartbeat.PREFIX + ghost,
                {"ts": time.time() - 1.0, "interval": 0.05, "pid": 1})
        newly = failure.check(hb_interval=0.05)
        assert ghost in newly
        model = job.join(timeout=300)
        assert job.status == DONE
        assert job.retries == 1
        assert model is not None
        assert model.output["ntrees_trained"] == 3
        if obs.enabled():
            assert obs.counter("sched_requeue_total",
                               reason="node_dead").value >= 1
    finally:
        wedge.set()
        failure.reset()
        dkv.remove(heartbeat.PREFIX + ghost)
        dkv.remove(failure.FAILURES_PREFIX + ghost)


def test_node_death_without_retry_budget_fails(cl):
    failure.reset()
    s = scheduler()
    started, wedge = threading.Event(), threading.Event()
    job = Job("doomed train")
    ghost = "sched_ghost_fatal"
    try:
        s.submit(job, lambda j: (started.set(), wedge.wait(60)),
                 device_budget=1, retry_budget=0)
        assert started.wait(15)
        dkv.put(heartbeat.PREFIX + ghost,
                {"ts": time.time() - 1.0, "interval": 0.05, "pid": 1})
        failure.check(hb_interval=0.05)
        with pytest.raises(failure.NodeFailedError):
            job.join(timeout=30)
        assert job.status == FAILED
    finally:
        wedge.set()
        failure.reset()
        dkv.remove(heartbeat.PREFIX + ghost)
        dkv.remove(failure.FAILURES_PREFIX + ghost)
        dkv.remove(sched_mod.SCHED_PREFIX + job.key)


# ------------------------------------------------------------- restart path
def test_readmit_restores_queue_after_restart(cl, tmp_path, monkeypatch):
    """Journal entry + WAL-mirrored !sched/ record ⇒ readmit() re-submits
    the job with its original priority/budget/tenant after a restart."""
    from h2o3_tpu.models import GBM
    monkeypatch.setenv("H2O3_TPU_RECOVERY_DIR", str(tmp_path))
    failure.reset()
    fr = _binary_frame(5, 300, "sched_readmit_fr")
    builder = GBM(response_column="y", ntrees=2, max_depth=2, seed=5)
    orig = Job("original train")
    uri = recovery.journal_start(builder, fr, orig)
    assert uri
    # the scheduling record a WAL rehydration would restore
    dkv.put(sched_mod.SCHED_PREFIX + orig.key, {
        "job": orig.key, "state": "running",
        "priority": PRIORITY_INTERACTIVE, "device_budget": 1.0,
        "retry_budget": 1, "user": "alice"})
    jobs = sched_mod.readmit(block=True)
    assert len(jobs) == 1
    j = jobs[0]
    assert j.status == DONE
    assert j.priority == PRIORITY_INTERACTIVE
    assert j.user == "alice"
    assert j.result is not None
    # superseded record removed; journal consumed by the resumed run
    assert dkv.get(sched_mod.SCHED_PREFIX + orig.key) is None
    assert not list(tmp_path.glob("job_*.json"))


# --------------------------------------------------------------- membership
def test_quarantine_entry_and_exit():
    q = Quarantine(window_s=10.0, max_flaps=2)
    assert q.note_join("h1", now=0.0)
    assert q.note_join("h1", now=1.0)
    assert not q.note_join("h1", now=2.0)        # 3rd flap in the window
    assert q.is_quarantined("h1", now=3.0)
    assert "h1" in q.active(3.0)
    assert not q.note_join("h1", now=5.0)        # still quarantined
    # after the window (and join history) expires, admitted again
    assert q.note_join("h1", now=30.0)
    assert not q.is_quarantined("h1", now=30.0)
    assert q.describe(30.0)["quarantined"] == []


def test_observe_members_flap_bounded():
    s = ClusterScheduler(capacity=8, queue_limit=4, elastic=False)
    s.quarantine = Quarantine(window_s=60.0, max_flaps=2)
    alive = {"status": "alive"}
    armed = 0

    def observe(members, now):
        nonlocal armed
        s.observe_members(members=members, now=now)
        with s._cv:
            if s._pending_rebuild:
                armed += 1
                s._pending_rebuild = False       # fence consumed

    try:
        observe({"h0": alive}, 0.0)              # seeding: no rebuild
        assert armed == 0
        # kill/rejoin h1 three times inside one window
        observe({"h0": alive, "h1": alive}, 1.0)
        observe({"h0": alive}, 2.0)
        observe({"h0": alive, "h1": alive}, 3.0)
        observe({"h0": alive}, 4.0)
        observe({"h0": alive, "h1": alive}, 5.0)
        observe({"h0": alive}, 6.0)
        observe({"h0": alive, "h1": alive}, 7.0)
        # rebuilds bounded by the quarantine policy, not the flap count
        assert armed == 2
        assert "h1" in s.quarantine.active(7.0)
        # window expiry readmits the (now stable) host
        observe({"h0": alive}, 119.0)
        observe({"h0": alive, "h1": alive}, 120.0)
        assert armed == 3
    finally:
        s.stop()


# ------------------------------------------------------- heartbeat edge cases
def test_members_mixed_per_stamp_intervals():
    now = time.time()
    stamps = {
        "mx_fast_alive": {"ts": now - 0.25, "interval": 0.1, "pid": 1},
        "mx_slow_alive": {"ts": now - 0.25, "interval": 5.0, "pid": 2},
        "mx_suspect": {"ts": now - 0.5, "interval": 0.1, "pid": 3},
        "mx_dead": {"ts": now - 2.0, "interval": 0.1, "pid": 4},
    }
    try:
        for name, stamp in stamps.items():
            dkv.put(heartbeat.PREFIX + name, stamp)
        view = heartbeat.members(now=now)
        # each stamp classifies in units of its OWN interval: the same
        # 0.25 s age is 2.5 fast intervals (alive edge) but a fraction
        # of a slow one
        assert view["mx_fast_alive"]["status"] == "alive"
        assert view["mx_slow_alive"]["status"] == "alive"
        assert view["mx_suspect"]["status"] == "suspect"
        assert view["mx_dead"]["status"] == "dead"
    finally:
        for name in stamps:
            dkv.remove(heartbeat.PREFIX + name)


def test_members_gc_removes_long_dead_stamps():
    now = time.time()
    key = heartbeat.PREFIX + "mx_long_gone"
    dkv.put(key, {"ts": now - 11.0, "interval": 0.1, "pid": 9})
    view = heartbeat.members(now=now)      # 110 intervals > the 100 GC bar
    assert "mx_long_gone" not in view
    assert dkv.get(key) is None            # removed from the DKV itself


# ------------------------------------------------------------------ REST/API
def test_scheduler_rest_status(cl):
    from h2o3_tpu.api.server import Api
    out = Api().scheduler_status()
    d = out["scheduler"]
    for k in ("capacity_chips", "used_chips", "free_chips", "queue_limit",
              "elastic", "pending_rebuild", "known_hosts",
              "fair_share_usage", "quarantine", "queued", "running"):
        assert k in d
    assert d["capacity_chips"] >= 1
    assert isinstance(d["queued"], list) and isinstance(d["running"], list)


# ------------------------------------------------------------------ bench gate
def test_bench_gate_classifies_sched_metrics():
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "bench_gate.py")
    spec = importlib.util.spec_from_file_location("bench_gate_sched", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.classify("sched_small_makespan_fifo_s") == "lower"
    assert mod.classify("sched_small_makespan_fair_s") == "lower"
    assert mod.classify("sched_fair_vs_baseline") == "higher"
