"""Batched grid sweeps: G same-shape configs as ONE compiled program.

The model axis rides the kernels' ``nk`` batch dimension (SURVEY.md: the
reference trains grid members as separate scheduler jobs; here
shape-compatible members vmap), so the contract is bitwise: every member
of a batched cohort must predict exactly what its sequential wave-path
twin predicts.  Successive halving retires losers through the traced
alive mask — same program, zero recompiles.
"""

import json
import math

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.models import GBM, GridSearch
from h2o3_tpu.models.tree import grid_batch as gb
from h2o3_tpu.runtime import dkv, failure, recovery, snapshot
from h2o3_tpu.runtime.config import reload as config_reload
from h2o3_tpu.runtime.observability import timeline_events


def _reg_frame(rng, n=300, f=5):
    X = rng.normal(size=(n, f))
    y = X[:, 0] + 0.5 * X[:, 1] ** 2 + rng.normal(scale=0.1, size=n)
    return Frame.from_numpy(
        {**{f"x{j}": X[:, j] for j in range(f)}, "y": y})


_BASE = dict(response_column="y", ntrees=5, max_depth=3, nbins=16,
             seed=11, reproducible=True)


def _pred(m, fr):
    return np.asarray(m.predict(fr).vec("predict").to_numpy())


def _by(models, *names):
    return {tuple(getattr(m.params, n) for n in names): m for m in models}


# ---------------------------------------------------------------- parity

@pytest.mark.parametrize("tree_program", ["level", "scan"])
def test_cohort_parity_bitwise(cl, rng, tree_program):
    """Batched cohort == sequential wave path, bit for bit, and the
    cohort actually ENGAGED (grid_cohort tag) — a silent fallback would
    make this parity vacuously true."""
    fr = _reg_frame(rng)
    hp = {"learn_rate": [0.05, 0.2], "reg_lambda": [0.0, 1.0]}
    kw = dict(_BASE, tree_program=tree_program)
    g_on = GridSearch(GBM, hp, grid_batch="on", **kw).train(fr)
    g_off = GridSearch(GBM, hp, grid_batch="off", **kw).train(fr)
    assert len(g_on.models) == 4 and len(g_off.models) == 4
    for m in g_on.models:
        assert m.output["grid_cohort"] == {
            "size": 4, "member": m.output["grid_cohort"]["member"]}
    for m in g_off.models:
        assert m.output.get("grid_cohort") is None
    mo = _by(g_on.models, "learn_rate", "reg_lambda")
    mf = _by(g_off.models, "learn_rate", "reg_lambda")
    assert set(mo) == set(mf)
    for k in mo:
        assert np.array_equal(_pred(mo[k], fr), _pred(mf[k], fr)), k


def test_cohort_parity_sampling_params(cl, rng):
    """Row/column sampling rates batch as [G] operands: the vmapped
    threefry draws must match the sequential per-member streams (rate-1.0
    members take the always-draw path whose masks are IEEE-identical to
    the sequential static skip)."""
    fr = _reg_frame(rng)
    hp = {"sample_rate": [0.7, 1.0], "col_sample_rate_per_tree": [0.8, 1.0]}
    kw = dict(_BASE, col_sample_rate=0.6)
    g_on = GridSearch(GBM, hp, grid_batch="on", **kw).train(fr)
    g_off = GridSearch(GBM, hp, grid_batch="off", **kw).train(fr)
    assert all(m.output.get("grid_cohort") for m in g_on.models)
    mo = _by(g_on.models, "sample_rate", "col_sample_rate_per_tree")
    mf = _by(g_off.models, "sample_rate", "col_sample_rate_per_tree")
    for k in mo:
        assert np.array_equal(_pred(mo[k], fr), _pred(mf[k], fr)), k


def test_mixed_shape_grid_partitions_into_cohorts(cl, rng):
    """max_depth changes the traced program, so a [2,3]x[lr] grid splits
    into two depth-homogeneous cohorts — both batched, both bitwise."""
    fr = _reg_frame(rng)
    hp = {"max_depth": [2, 3], "learn_rate": [0.1, 0.2]}
    kw = {k: v for k, v in _BASE.items() if k != "max_depth"}
    g_on = GridSearch(GBM, hp, grid_batch="on", **kw).train(fr)
    g_off = GridSearch(GBM, hp, grid_batch="off", **kw).train(fr)
    coh = [m.output.get("grid_cohort") for m in g_on.models]
    assert all(c is not None and c["size"] == 2 for c in coh), coh
    mo = _by(g_on.models, "max_depth", "learn_rate")
    mf = _by(g_off.models, "max_depth", "learn_rate")
    for k in mo:
        assert np.array_equal(_pred(mo[k], fr), _pred(mf[k], fr)), k


# ----------------------------------------------------- cohort planning

def test_plan_cohorts_partitioning_rules(cl):
    """Unit contract: batchable knobs group, shape knobs split, ineligible
    and singleton members take the wave path with a reason."""
    base = dict(_BASE)
    combos = [
        {"learn_rate": 0.1, "max_depth": 3},    # cohort A
        {"learn_rate": 0.2, "max_depth": 3},    # cohort A
        {"learn_rate": 0.1, "max_depth": 4},    # cohort B
        {"reg_lambda": 2.0, "max_depth": 4},    # cohort B
        {"learn_rate": 0.1, "max_depth": 5},    # singleton -> rest
        {"learn_rate": 0.1, "max_depth": 3, "nfolds": 2},  # ineligible
    ]
    cohorts, rest = gb.plan_cohorts(GBM, base, combos)
    grouped = sorted(sorted(c) for c in cohorts)
    assert grouped == [[0, 1], [2, 3]]
    reasons = dict(rest)
    assert set(reasons) == {4, 5}
    assert "singleton" in reasons[4]
    assert "nfolds" in reasons[5]


def test_fallback_is_recorded_and_wave_path_still_trains(cl, rng):
    """An all-ineligible grid (nfolds) falls back wholesale: every model
    still trains (wave path), none carries a cohort tag, and the
    fallback reasons land on the observability timeline."""
    fr = _reg_frame(rng)
    hp = {"learn_rate": [0.1, 0.2]}
    g = GridSearch(GBM, hp, grid_batch="auto", nfolds=2,
                   **_BASE).train(fr)
    assert len(g.models) == 2
    assert all(m.output.get("grid_cohort") is None for m in g.models)
    falls = [e for e in timeline_events(500)
             if e["kind"] == "grid_batch_fallback"]
    assert any("nfolds" in str(e.get("reason")) for e in falls)


# ------------------------------------------------- successive halving

def test_halving_survivors_match_oracle(cl, rng):
    """In-batch successive halving: retirement happens at scoring
    fences via the alive mask, the survivor equals the
    train-to-completion oracle's best member, and the one compiled
    cohort program never recompiles (ledger: no shape_change)."""
    from h2o3_tpu.runtime import xprof
    fr = _reg_frame(rng)
    hp = {"learn_rate": [0.01, 0.05, 0.1, 0.3]}
    kw = dict(_BASE, ntrees=12, score_tree_interval=3)
    before = xprof.ledger_snapshot().get("programs", {}).get(
        "tree_scan_grid", {})
    g = GridSearch(
        GBM, hp, grid_batch="on",
        search_criteria={"successive_halving": True, "halving_eta": 2},
        **kw).train(fr)
    after = xprof.ledger_snapshot().get("programs", {}).get(
        "tree_scan_grid", {})
    # warmup costs at most 2 compiles (first trace + the one sharding
    # settle every fused driver pays under the mesh — tree_scan shows
    # the same); 3 retirements across 3 rungs must add ZERO, or this
    # delta would be >= 5
    delta = after.get("compiles", 0) - before.get("compiles", 0)
    assert delta <= 2, dict(after.get("reasons", {}))

    retired = [m for m in g.models
               if (m.output.get("halving") or {}).get("retired_at")]
    survivors = [m for m in g.models
                 if not (m.output.get("halving") or {}).get("retired_at")]
    assert len(retired) == 3 and len(survivors) == 1
    # retired members froze at their rung's tree count
    for m in retired:
        assert m.output["ntrees_trained"] < 12
    assert survivors[0].output["ntrees_trained"] == 12

    full = GridSearch(GBM, hp, grid_batch="off", **kw).train(fr)

    def final_dev(m):
        return m.scoring_history[-1].get("mean_residual_deviance",
                                         math.inf)

    best = min(full.models, key=final_dev)
    assert survivors[0].params.learn_rate == best.params.learn_rate


def test_halving_rungs_schedule(cl):
    assert gb._halving_rungs(8, 40, 2.0) == [(5, 4), (10, 2), (20, 1)]
    assert gb._halving_rungs(2, 10, 3.0) == []  # R=0: nothing to retire
    assert gb._halving_rungs(9, 27, 3.0) == [(3, 3), (9, 1)]
    assert gb._halving_rungs(4, 8, 1.0) == []   # eta<=1 disables


# ------------------------------------------------- fault tolerance

def test_grid_member_failure_is_isolated(cl, rng, monkeypatch):
    """A member that dies (injected at the grid_member point) becomes a
    failed_entries row; its cohort siblings finish normally and their
    predictions still match the sequential path bitwise."""
    fr = _reg_frame(rng)
    hp = {"learn_rate": [0.05, 0.1, 0.2]}
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "grid_member:0:2:raise")
    failure.reset()
    g = GridSearch(GBM, hp, grid_batch="on", **_BASE).train(fr)
    monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
    failure.reset()
    assert len(g.models) == 2
    assert len(g.failed_entries) == 1
    assert "InjectedFault" in g.failed_entries[0]["error"]
    failed_lr = g.failed_entries[0]["learn_rate"]
    g_off = GridSearch(GBM, hp, grid_batch="off", **_BASE).train(fr)
    mo = _by(g.models, "learn_rate")
    mf = _by(g_off.models, "learn_rate")
    for k in mo:
        assert k[0] != failed_lr
        assert np.array_equal(_pred(mo[k], fr), _pred(mf[k], fr)), k


def test_wave_member_failure_is_isolated(cl, rng, monkeypatch):
    """Same contract on the sequential wave path (grid_batch='off')."""
    fr = _reg_frame(rng)
    hp = {"learn_rate": [0.05, 0.2]}
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "grid_member:0:1:raise")
    failure.reset()
    g = GridSearch(GBM, hp, grid_batch="off", **_BASE).train(fr)
    monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
    failure.reset()
    assert len(g.models) == 1
    assert len(g.failed_entries) == 1
    assert "InjectedFault" in g.failed_entries[0]["error"]


def test_failed_entries_survive_grid_save_load(cl, rng, monkeypatch,
                                               tmp_path):
    fr = _reg_frame(rng)
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "grid_member:0:1:raise")
    failure.reset()
    g = GridSearch(GBM, {"learn_rate": [0.05, 0.2]}, grid_batch="on",
                   **_BASE).train(fr)
    monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
    failure.reset()
    assert g.failed_entries
    path = g.save(str(tmp_path / "grid"))
    g2 = type(g).load(path)
    assert g2.failed_entries == g.failed_entries


# ------------------------------------------------- runtime budget

def test_max_runtime_secs_expired_before_start(cl, rng):
    """A deadline that has already passed trains nothing — the grid
    raises rather than silently returning an empty Grid."""
    fr = _reg_frame(rng)
    with pytest.raises(ValueError, match="no models"):
        GridSearch(GBM, {"learn_rate": [0.1, 0.2]}, grid_batch="on",
                   search_criteria={"max_runtime_secs": 1e-9},
                   **_BASE).train(fr)


def test_max_runtime_secs_generous_budget_completes(cl, rng):
    fr = _reg_frame(rng)
    g = GridSearch(GBM, {"learn_rate": [0.1, 0.2]}, grid_batch="on",
                   search_criteria={"max_runtime_secs": 600},
                   **_BASE).train(fr)
    assert len(g.models) == 2
    assert all(m.output.get("grid_cohort") for m in g.models)


# ------------------------------------------------- mid-cohort resume

@pytest.fixture()
def recovery_env(cl, tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3_TPU_RECOVERY_DIR", str(tmp_path))
    monkeypatch.setenv("H2O3_TPU_SNAPSHOT_INTERVAL", "0")
    monkeypatch.setenv("H2O3_TPU_SNAPSHOT_ASYNC", "0")
    config_reload()
    snapshot.reset()
    failure.reset()
    yield tmp_path
    snapshot.reset()
    failure.reset()
    monkeypatch.delenv("H2O3_TPU_RECOVERY_DIR", raising=False)
    monkeypatch.delenv("H2O3_TPU_SNAPSHOT_INTERVAL", raising=False)
    monkeypatch.delenv("H2O3_TPU_SNAPSHOT_ASYNC", raising=False)
    monkeypatch.delenv("H2O3_TPU_FAULT_INJECT", raising=False)
    config_reload()


def test_mid_cohort_crash_resumes_every_member(recovery_env, monkeypatch,
                                               rng):
    """Kill a cohort at the 2nd tree-chunk fence: every member's journal
    stays 'running' with a per-member snapshot, and recovery.resume()
    finishes each one through the sequential checkpoint path to the same
    predictions (resume tolerance) as an uninterrupted run."""
    tmp_path = recovery_env
    n = 300
    X = np.random.default_rng(5).random((n, 4))
    y = 7 * np.sin(np.pi * X[:, 0]) + 3 * X[:, 1] + 0.1 * X[:, 2]
    cols = {**{f"x{j}": X[:, j] for j in range(4)}, "y": y}
    fr = h2o3_tpu.H2OFrame(cols, destination_frame="gridbatch_resume_fr")
    kw = dict(response_column="y", ntrees=8, max_depth=3, nbins=16,
              seed=7, score_tree_interval=2, reproducible=True)
    hp = {"learn_rate": [0.1, 0.3]}
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "tree_chunk:0:2:raise")
    failure.reset()
    failure._handled.add("ghost")       # degraded: keep journal resumable
    with pytest.raises(failure.InjectedFault):
        GridSearch(GBM, hp, grid_batch="on", **kw).train(fr)
    monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
    failure.reset()

    entries = [json.loads(p.read_text())
               for p in tmp_path.glob("job_*.json")]
    running = [e for e in entries if e["status"] == "running"]
    assert len(running) == 2            # one per cohort member
    for e in running:
        assert e["snapshot_uri"]
        assert e["snapshot_cursor"]["trees_done"] == 2

    done = recovery.resume(str(tmp_path))
    assert len(done) == 2
    resumed = {}
    for key in done:
        m = dkv.get(key)
        assert m.output["ntrees_trained"] == 8
        assert m.output["resumed_from_snapshot"]["cursor"][
            "trees_done"] == 2
        resumed[m.params.learn_rate] = m
    assert set(resumed) == {0.1, 0.3}

    ref = GridSearch(GBM, hp, grid_batch="off", **kw).train(fr)
    for m in ref.models:
        # same tolerance as the single-model resume contract
        # (test_snapshot_recovery): the checkpoint continuation is
        # allclose to uninterrupted, not bitwise
        np.testing.assert_allclose(
            _pred(resumed[m.params.learn_rate], fr), _pred(m, fr),
            rtol=1e-4, atol=1e-4)
