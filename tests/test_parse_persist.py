"""Parse + Persist tests: multi-file globs, compression, SVMLight/ARFF,
persist URIs (mock GCS root), frame/model import-export round trips.

Mirrors the reference's parser pyunits (h2o-py/tests/testdir_parser) and
the PersistGcs fake-server tests.
"""

import gzip
import os
import zipfile

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame, import_file, export_file


@pytest.fixture()
def shards(tmp_path):
    """Three gz CSV shards of one logical dataset."""
    paths = []
    rng = np.random.default_rng(0)
    for i in range(3):
        rows = ["x,y,g"]
        for r in range(100):
            rows.append(f"{rng.normal():.6f},{i * 100 + r},{'ab'[r % 2]}")
        p = tmp_path / f"shard{i}.csv.gz"
        with gzip.open(p, "wt") as f:
            f.write("\n".join(rows))
        paths.append(str(p))
    return paths


def test_multifile_glob_import(cl, shards, tmp_path):
    fr = import_file(str(tmp_path / "shard*.csv.gz"))
    assert fr.shape == (300, 3)
    assert fr.types() == {"x": "num", "y": "num", "g": "cat"}
    y = np.sort(fr.vec("y").to_numpy())
    np.testing.assert_array_equal(y, np.arange(300.0))


def test_import_directory(cl, shards, tmp_path):
    fr = import_file(str(tmp_path))
    assert fr.nrows == 300


def test_import_list_and_chunked(cl, shards):
    fr = h2o3_tpu.parse_files(shards, chunksize=37)
    assert fr.nrows == 300
    assert fr.vec("x").data is not None      # numeric stayed on device


def test_zip_import(cl, tmp_path):
    p = tmp_path / "data.zip"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("inner.csv", "a,b\n1,2\n3,4\n")
    fr = import_file(str(p))
    assert fr.shape == (2, 2)
    np.testing.assert_array_equal(fr.vec("a").to_numpy(), [1.0, 3.0])


def test_svmlight(cl, tmp_path):
    p = tmp_path / "d.svm"
    p.write_text("1 1:0.5 3:2.0\n-1 2:1.5 # comment\n")
    fr = import_file(str(p))
    assert fr.names == ["target", "C1", "C2", "C3"]
    np.testing.assert_array_equal(fr.vec("target").to_numpy(), [1.0, -1.0])
    np.testing.assert_array_equal(fr.vec("C3").to_numpy(), [2.0, 0.0])


def test_arff(cl, tmp_path):
    p = tmp_path / "d.arff"
    p.write_text("""% comment
@relation test
@attribute num1 numeric
@attribute cls {red,green,blue}
@attribute note string
@data
1.5,red,hello
2.5,blue,world
?,green,!
""")
    fr = import_file(str(p))
    assert fr.types() == {"num1": "num", "cls": "cat", "note": "str"}
    assert fr.vec("cls").domain == ["red", "green", "blue"]
    x = fr.vec("num1").to_numpy()
    assert x[0] == 1.5 and np.isnan(x[2])


def test_parquet_orc_feather(cl, tmp_path, rng):
    fr = Frame.from_numpy({
        "a": rng.normal(size=40),
        "g": np.array(["x", "y"], dtype=object)[rng.integers(0, 2, 40)]})
    for ext in ("parquet", "feather"):
        uri = str(tmp_path / f"t.{ext}")
        export_file(fr, uri)
        back = import_file(uri)
        np.testing.assert_allclose(back.vec("a").to_numpy(),
                                   fr.vec("a").to_numpy(), rtol=1e-9)
        assert list(back.vec("g").decoded()) == list(fr.vec("g").decoded())
    # ORC import (written via pyarrow directly)
    import pyarrow as pa
    import pyarrow.orc as porc
    porc.write_table(pa.table({"v": np.arange(5.0)}),
                     str(tmp_path / "t.orc"))
    orc_fr = import_file(str(tmp_path / "t.orc"))
    np.testing.assert_array_equal(orc_fr.vec("v").to_numpy(),
                                  np.arange(5.0))
    # avro now has a real parser (frame/avro.py); truncated input is a
    # clean parse error, not a missing-library gate
    with pytest.raises(ValueError, match="truncated avro"):
        (tmp_path / "x.avro").write_bytes(b"Obj\x01")
        import_file(str(tmp_path / "x.avro"))


def test_export_roundtrip(cl, tmp_path, rng):
    fr = Frame.from_numpy({
        "a": rng.normal(size=20),
        "g": np.array(["u", "v"], dtype=object)[rng.integers(0, 2, 20)]})
    uri = str(tmp_path / "out.csv")
    export_file(fr, uri)
    back = import_file(uri)
    np.testing.assert_allclose(back.vec("a").to_numpy(),
                               fr.vec("a").to_numpy(), rtol=1e-6)
    assert list(back.vec("g").decoded()) == list(fr.vec("g").decoded())


def test_gcs_mock_uri_roundtrip(cl, tmp_path, rng, monkeypatch):
    monkeypatch.setenv("H2O3_TPU_GCS_ROOT", str(tmp_path / "gcs"))
    fr = Frame.from_numpy({"a": rng.normal(size=10)})
    export_file(fr, "gcs://bucket/dir/data.csv")
    assert (tmp_path / "gcs" / "bucket" / "dir" / "data.csv").exists()
    back = import_file("gcs://bucket/dir/data.csv")
    np.testing.assert_allclose(back.vec("a").to_numpy(),
                               fr.vec("a").to_numpy(), rtol=1e-6)


def test_model_save_load_uri(cl, tmp_path, rng, monkeypatch):
    monkeypatch.setenv("H2O3_TPU_GCS_ROOT", str(tmp_path / "gcs"))
    from h2o3_tpu.models import GLM
    n = 500
    X = rng.normal(size=(n, 3))
    y = X @ [1.0, -2.0, 0.5] + 0.01 * rng.normal(size=n)
    fr = Frame.from_numpy({**{f"x{j}": X[:, j] for j in range(3)}, "y": y})
    m = GLM(response_column="y", family="gaussian").train(fr)
    uri = "gcs://models/glm1.bin"
    h2o3_tpu.save_model(m, uri)
    m2 = h2o3_tpu.load_model(uri)
    p1 = m.predict(fr).vec("predict").to_numpy()
    p2 = m2.predict(fr).vec("predict").to_numpy()
    np.testing.assert_allclose(p1, p2, rtol=1e-6)


def test_sql_import(cl, tmp_path):
    import sqlite3
    import h2o3_tpu
    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE users (age REAL, city TEXT, income REAL)")
    conn.executemany(
        "INSERT INTO users VALUES (?,?,?)",
        [(30 + i, ["sf", "nyc", "la"][i % 3], 50000 + i * 1000)
         for i in range(50)])
    conn.commit()
    fr = h2o3_tpu.import_sql_table(conn, "users")
    assert fr.shape == (50, 3)
    assert fr.types() == {"age": "num", "city": "cat", "income": "num"}
    fr2 = h2o3_tpu.import_sql_select(
        f"sqlite://{db}", "SELECT age, income FROM users WHERE age > 50")
    assert fr2.nrows == 29 and fr2.names == ["age", "income"]
    with pytest.raises(NotImplementedError, match="DB-API"):
        h2o3_tpu.import_sql_table("jdbc:postgresql://x/y", "users")


def test_from_pandas_and_h2oframe(cl):
    import pandas as pd
    df = pd.DataFrame({
        "num": [1.5, 2.5, None],
        "i": [1, 2, 3],
        "b": [True, False, True],
        "cat": pd.Categorical(["lo", "hi", None],
                              categories=["lo", "mid", "hi"]),
        "s": ["x", "y", "zzz-long-un1que"],
        "t": pd.to_datetime(["2020-01-01", "2020-06-01", "2021-01-01"]),
        "mixed": ["1", "2", "oops"],
    })
    fr = h2o3_tpu.from_pandas(df)
    t = fr.types()
    assert t["num"] == "num" and t["i"] == "num" and t["b"] == "num"
    assert t["cat"] == "cat" and t["t"] == "time"
    assert fr.vec("cat").domain == ["lo", "mid", "hi"]
    x = fr.vec("num").to_numpy()
    assert x[1] == 2.5 and np.isnan(x[2])
    np.testing.assert_array_equal(fr.vec("b").to_numpy(), [1.0, 0.0, 1.0])
    codes = fr.vec("cat").data
    assert int(np.asarray(codes)[2]) == -1          # NaN category -> NA
    assert t["mixed"] in ("cat", "str")             # not numeric
    # H2OFrame: dict, list-of-rows with header, 2-D array
    f2 = h2o3_tpu.H2OFrame({"a": [1.0, 2.0], "g": ["u", "v"]})
    assert f2.shape == (2, 2) and f2.types()["g"] == "cat"
    f3 = h2o3_tpu.H2OFrame([["a", "b"], [1, 2], [3, 4]])
    assert f3.names == ["a", "b"] and f3.nrows == 2
    np.testing.assert_array_equal(f3.vec("a").to_numpy(), [1.0, 3.0])
    f4 = h2o3_tpu.H2OFrame(np.arange(6.0).reshape(3, 2))
    assert f4.names == ["C1", "C2"] and f4.nrows == 3
    # pandas round trip
    back = fr.to_pandas()
    assert list(back.columns) == list(df.columns)


def test_h2oframe_edges(cl):
    import pandas as pd
    # nullable boolean with NA
    fb = h2o3_tpu.from_pandas(pd.DataFrame(
        {"b": pd.Series([True, None, False], dtype="boolean")}))
    x = fb.vec("b").to_numpy()
    assert x[0] == 1.0 and np.isnan(x[1]) and x[2] == 0.0
    # dict with None stays numeric with NaN (no "None" category)
    f = h2o3_tpu.H2OFrame({"a": [1.0, 2.0, None]})
    assert f.types()["a"] == "num"
    a = f.vec("a").to_numpy()
    assert a[1] == 2.0 and np.isnan(a[2])
    assert f.key is not None                 # registered in the DKV
    # 1-D string list is data, not a header
    f1 = h2o3_tpu.H2OFrame(["a", "b", "c"])
    assert f1.nrows == 3 and f1.names == ["C1"]


def test_distributed_parse_single_process_parity(cl, tmp_path):
    """parse_files_distributed (nproc=1 degenerate) matches parse_files
    cell-for-cell on every column type, including boundary-line handling
    across uneven multi-file shards."""
    rng = np.random.default_rng(0)
    for k, nrows in enumerate((700, 150, 1201)):
        with open(tmp_path / f"part{k}.csv", "w") as f:
            f.write("num,cat,when,txt,resp\n")
            for i in range(nrows):
                num = "" if (i % 97 == 0) else f"{rng.normal():.4f}"
                f.write(f"{num},lvl{k}_{i % (3 + k)},"
                        f"2024-0{k+1}-{(i % 27) + 1:02d},id_{k}_{i},"
                        f"{'Y' if (i % 3) else 'N'}\n")
    from h2o3_tpu.frame import dparse
    import h2o3_tpu.frame.parse as P
    paths = sorted(str(p) for p in tmp_path.glob("part*.csv"))
    fr = dparse.parse_files_distributed(paths)
    fr2 = P.parse_files(paths)
    assert fr.shape == fr2.shape == (2051, 5)
    assert fr.types() == fr2.types() == {
        "num": "num", "cat": "cat", "when": "time", "txt": "str",
        "resp": "cat"}
    assert np.allclose(fr.vec("num").to_numpy(), fr2.vec("num").to_numpy(),
                       equal_nan=True)
    assert list(fr.vec("cat").decoded()) == list(fr2.vec("cat").decoded())
    assert np.allclose(fr.vec("when").to_numpy(),
                       fr2.vec("when").to_numpy(), equal_nan=True)
    assert list(fr.vec("txt").to_numpy()) == list(fr2.vec("txt").to_numpy())
    assert dparse.last_stats["bytes_tokenized"] > 0


# ------------------------------------------- ranged-parallel parse pipeline

def _pipeline_csv(tmp_path, nrows=1200, header=True, quoted=False,
                  name="pipe.csv"):
    """A fixture CSV exercising every column type the pipeline handles:
    numeric with NAs, categorical, time, free text, and negative floats."""
    rng = np.random.default_rng(7)
    lines = ["num,cat,when,txt,neg"] if header else []
    for i in range(nrows):
        num = "" if i % 53 == 0 else f"{rng.normal():.5f}"
        cat = f"lvl{i % 5}"
        when = f"2024-03-{(i % 27) + 1:02d}"
        txt = f'"say ""{i}"" twice"' if (quoted and i % 7 == 0) \
            else f"id_{i}"
        lines.append(f"{num},{cat},{when},{txt},{-1.5 * (i % 11):.2f}")
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def _assert_frames_identical(fa, fb):
    assert fa.names == fb.names
    assert fa.types() == fb.types()
    for n in fa.names:
        va, vb = fa.vec(n), fb.vec(n)
        assert va.domain == vb.domain
        xa, xb = va.to_numpy(), vb.to_numpy()
        if xa.dtype == object:
            assert list(xa) == list(xb)
        else:
            np.testing.assert_array_equal(xa, xb)


def _parse_ranged(path, monkeypatch, threads=4, **kw):
    """Parse with the ranged-parallel path forced on (tiny range floor)."""
    import h2o3_tpu.frame.parse as P
    monkeypatch.setenv("H2O3_PARSE_THREADS", str(threads))
    monkeypatch.setenv("H2O3_PARSE_RANGE_MIN", "1")
    try:
        return P.parse_csv(path, **kw)
    finally:
        monkeypatch.delenv("H2O3_PARSE_THREADS")
        monkeypatch.delenv("H2O3_PARSE_RANGE_MIN")


def test_ranged_vs_single_thread_parity(cl, tmp_path, monkeypatch):
    """Ranged-parallel output is identical (names, types, values, domains)
    to the single-threaded native path on the same file — the splits land
    mid-row by construction and must be realigned to line starts."""
    from h2o3_tpu import native
    if native.load() is None:
        pytest.skip("native tokenizer unavailable")
    import h2o3_tpu.frame.parse as P
    path = _pipeline_csv(tmp_path)
    ranged = _parse_ranged(path, monkeypatch, threads=4)
    assert P.last_parse_stats.get("ranges", 0) > 1   # really went parallel
    monkeypatch.setenv("H2O3_PARSE_THREADS", "1")
    single = P.parse_csv(path)
    assert P.last_parse_stats.get("ranges") == 1
    _assert_frames_identical(ranged, single)
    assert ranged.types() == {"num": "num", "cat": "cat", "when": "time",
                              "txt": "str", "neg": "num"}
    assert np.isnan(ranged.vec("num").to_numpy()[0])          # NA cell
    assert ranged.vec("cat").domain == [f"lvl{i}" for i in range(5)]


def test_ranged_parity_many_tiny_ranges(cl, tmp_path, monkeypatch):
    """16 ranges over a small file: nearly every byte cut splits mid-row."""
    from h2o3_tpu import native
    if native.load() is None:
        pytest.skip("native tokenizer unavailable")
    import h2o3_tpu.frame.parse as P
    path = _pipeline_csv(tmp_path, nrows=97)
    ranged = _parse_ranged(path, monkeypatch, threads=16)
    monkeypatch.setenv("H2O3_PARSE_THREADS", "1")
    _assert_frames_identical(ranged, P.parse_csv(path))


def test_mmap_vs_bytes_input_equivalence(cl, tmp_path, monkeypatch):
    """The mmap'd path route and the bytes/stream route produce identical
    frames; the path route reports its mmap stage in the parse stats."""
    import io
    import h2o3_tpu.frame.parse as P
    path = _pipeline_csv(tmp_path)
    content = open(path, "rb").read()
    from_path = P.parse_csv(path)
    stats = dict(P.last_parse_stats)
    from_bytes = P.parse_csv(content)
    from_stream = P.parse_csv(io.BytesIO(content))
    _assert_frames_identical(from_path, from_bytes)
    _assert_frames_identical(from_path, from_stream)
    if stats:                                 # native engine engaged
        assert "mmap_s" in stats and stats["rows"] == from_path.nrows


def test_quoted_fields_parallel_and_fallback(cl, tmp_path, monkeypatch):
    """Benign quotes (escaped "" payloads, no hidden newlines) keep the
    ranged path; quoted embedded newlines/separators still parse correctly
    through whatever engine handles them."""
    import h2o3_tpu.frame.parse as P
    # benign quoting: ranged vs single parity including "" unescaping
    path = _pipeline_csv(tmp_path, quoted=True, name="q.csv")
    ranged = _parse_ranged(path, monkeypatch, threads=4)
    monkeypatch.setenv("H2O3_PARSE_THREADS", "1")
    single = P.parse_csv(path)
    monkeypatch.delenv("H2O3_PARSE_THREADS")
    _assert_frames_identical(ranged, single)
    assert 'say "0" twice' in list(ranged.vec("txt").to_numpy())
    # hostile quoting: newline + separator inside a quoted cell
    p2 = tmp_path / "q2.csv"
    p2.write_text('a,b\n1,"x,\ny"\n2,"plain"\n3,last\n')
    fr = _parse_ranged(str(p2), monkeypatch, threads=4)
    assert fr.shape == (3, 2)
    np.testing.assert_array_equal(fr.vec("a").to_numpy(), [1.0, 2.0, 3.0])
    vals = list(fr.vec("b").decoded() if fr.vec("b").domain
                else fr.vec("b").to_numpy())
    assert "x,\ny" in vals and "plain" in vals


def test_header_and_no_header_paths(cl, tmp_path, monkeypatch):
    """Header autodetect, explicit no-header, and all-numeric headerless
    files agree between the ranged and single-threaded engines."""
    import h2o3_tpu.frame.parse as P
    # headerless all-numeric: C1..Cn names
    p = tmp_path / "nh.csv"
    p.write_text("\n".join(f"{i},{i * 0.5},{i % 3}" for i in range(400))
                 + "\n")
    fr = _parse_ranged(str(p), monkeypatch)
    assert fr.names == ["C1", "C2", "C3"] and fr.nrows == 400
    np.testing.assert_array_equal(fr.vec("C1").to_numpy(),
                                  np.arange(400.0))
    # header=False forces the text first line into the data
    p2 = tmp_path / "h2.csv"
    p2.write_text("a,b\n1,2\n3,4\n")
    fr2 = P.parse_csv(str(p2), header=False)
    assert fr2.nrows == 3
    # autodetected header vs the same file parsed ranged
    path = _pipeline_csv(tmp_path, name="hd.csv")
    auto = _parse_ranged(path, monkeypatch)
    explicit = P.parse_csv(path, header=True)
    _assert_frames_identical(auto, explicit)


def test_parse_stage_timings_recorded(cl, tmp_path):
    """The native pipeline records per-stage wall times (PROFILE.md's
    measurement surface) and observability keeps the parse record."""
    from h2o3_tpu import native
    if native.load() is None:
        pytest.skip("native tokenizer unavailable")
    import h2o3_tpu.frame.parse as P
    path = _pipeline_csv(tmp_path, nrows=300, name="tm.csv")
    P.parse_csv(path)
    st = P.last_parse_stats
    for k in ("mmap_s", "scan_s", "tokenize_s", "device_s", "decode_s",
              "native_total_s", "vec_s", "rows", "bytes", "ranges"):
        assert k in st, k
    assert st["rows"] == 300
