"""tree_program="scan": the whole-tree lax.scan program vs the per-level
dispatch loop.

The scan-fused build must be BITWISE identical to the per-level program
on every knob combination it supports (padding slots are inert, masks
are pre-drawn with the level path's exact key sequence), and must
compile to O(1) kernel launches per tree regardless of depth — that is
the whole point of the fusion.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.models import DRF, GBM
from h2o3_tpu.models.tree.gbm import GBMParameters
from h2o3_tpu.models.tree.shared import (make_build_tree_fn,
                                         resolve_tree_program,
                                         run_program_crosscheck)
from h2o3_tpu.runtime.xprof import count_kernel_launches


# ---------------------------------------------------------- build level

def _problem(rng, F=5, N=256, nbins=16):
    codes = jnp.asarray(rng.integers(0, nbins, (F, N)), jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.ones(N, jnp.float32)
    w = jnp.ones(N, jnp.float32)
    edges = jnp.sort(jnp.asarray(rng.normal(size=(F, nbins)), jnp.float32),
                     axis=1)
    return codes, g, h, w, edges


def _args(rng, key=7, min_rows=1.0, col_rate=0.8, F=5):
    codes, g, h, w, edges = _problem(rng, F=F)
    tm = jnp.ones(F, bool)
    return (codes, g, h, w, edges, jax.random.PRNGKey(key), 0.0, min_rows,
            1e-5, 0.1, col_rate, tm, 0.0, 0.0, 0.0)


def _assert_trees_equal(a, b):
    la, va, ca, fa = a
    lb, vb, cb, fb = b
    for d, (x, y) in enumerate(zip(la, lb)):
        for i, nm in enumerate(("feat", "thr", "na_left", "valid")):
            np.testing.assert_array_equal(
                np.asarray(x[i]), np.asarray(y[i]),
                err_msg=f"level {d} {nm}")
    np.testing.assert_array_equal(np.asarray(va), np.asarray(vb),
                                  err_msg="values")
    np.testing.assert_array_equal(np.asarray(ca), np.asarray(cb),
                                  err_msg="cover")
    np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb),
                                  err_msg="leaf")


@pytest.mark.parametrize("hm", ["subtract", "full"])
@pytest.mark.parametrize("sm", ["separate", "fused"])
def test_scan_matches_level_bitwise(cl, rng, hm, sm):
    F, N, nbins, md = 5, 256, 16, 4
    args = _args(rng)
    lv = make_build_tree_fn(md, nbins, F, N, "f32", hist_mode=hm,
                            split_mode=sm)
    sc = make_build_tree_fn(md, nbins, F, N, "f32", hist_mode=hm,
                            split_mode=sm, tree_program="scan")
    _assert_trees_equal(lv(*args), sc(*args))


def test_scan_matches_level_early_exit(cl, rng):
    """min_rows so large nothing past the root splits: the scan's dead
    predicate must reproduce the level loop's early-terminated tree
    (inert iterations emit the exact parent-passthrough leaves)."""
    F, N, nbins, md = 5, 256, 16, 5
    args = _args(rng, min_rows=200.0, col_rate=1.0)
    lv = make_build_tree_fn(md, nbins, F, N, "f32")
    sc = make_build_tree_fn(md, nbins, F, N, "f32", tree_program="scan")
    _assert_trees_equal(lv(*args), sc(*args))


@pytest.mark.parametrize("hm", ["subtract", "full"])
def test_scan_matches_level_batched(cl, rng, hm):
    """K-batched build (the multinomial / batched-DRF axis)."""
    F, N, nbins, md, K = 5, 256, 16, 4, 3
    codes, _, _, w, edges = _problem(rng, F=F)
    gK = jnp.asarray(rng.normal(size=(K, N)), jnp.float32)
    hK = jnp.ones((K, N), jnp.float32)
    keysK = jax.random.split(jax.random.PRNGKey(11), K)
    tmK = jnp.ones((K, F), bool)
    args = (codes, gK, hK, w, edges, keysK, 0.0, 1.0, 1e-5, 0.1, 0.8,
            tmK, 0.0, 0.0, 0.0)
    lv = make_build_tree_fn(md, nbins, F, N, "f32", hist_mode=hm, nk=K,
                            split_mode="fused")
    sc = make_build_tree_fn(md, nbins, F, N, "f32", hist_mode=hm, nk=K,
                            split_mode="fused", tree_program="scan")
    lo, so = lv(*args), sc(*args)
    for i in range(4):
        for d, (x, y) in enumerate(zip(lo[0], so[0])):
            np.testing.assert_array_equal(np.asarray(x[i]),
                                          np.asarray(y[i]),
                                          err_msg=f"level {d} field {i}")
    for i in (1, 2, 3):
        np.testing.assert_array_equal(np.asarray(lo[i]), np.asarray(so[i]))


def test_program_crosscheck_runs_clean(cl, rng):
    """The tree_program="check" oracle itself (drivers call this on the
    real first-round gradients)."""
    codes, g, h, w, edges = _problem(rng)
    run_program_crosscheck(
        codes, g, h, w, edges, jax.random.PRNGKey(3),
        max_depth=4, nbins=16, F=5, n_padded=256,
        reg_lambda=0.0, min_rows=1.0, min_split_improvement=1e-5,
        learn_rate=0.1, col_sample_rate=1.0)


# --------------------------------------------------------- dispatch pin

def test_launches_per_tree_is_depth_independent(cl, rng):
    """THE acceptance pin: the scan program compiles to O(1) kernel
    dispatch sites regardless of depth, while the level program grows
    one hist launch per level."""
    F, N, nbins = 5, 256, 16
    args = _args(rng)
    scan_counts, level_counts = [], []
    for md in (3, 4, 6):
        sc = make_build_tree_fn(md, nbins, F, N, "f32",
                                tree_program="scan")
        lv = make_build_tree_fn(md, nbins, F, N, "f32")
        scan_counts.append(count_kernel_launches(sc, *args))
        level_counts.append(count_kernel_launches(lv, *args))
    assert len(set(scan_counts)) == 1, scan_counts   # depth-independent
    assert scan_counts[0] <= 4, scan_counts          # O(1), small
    # the level program dispatches per level: strictly increasing in depth
    assert level_counts[0] < level_counts[1] < level_counts[2], level_counts
    assert scan_counts[-1] < level_counts[-1]


# ------------------------------------------------------- knob semantics

def test_scan_rejects_unsupported_shapes(cl):
    p = GBMParameters(response_column="y", tree_program="scan", max_depth=5)
    with pytest.raises(ValueError, match="mono"):
        resolve_tree_program(p, mono={"x0": 1})
    with pytest.raises(ValueError, match="hier"):
        resolve_tree_program(p, hier=True)
    p1 = GBMParameters(response_column="y", tree_program="scan",
                       max_depth=1)
    with pytest.raises(ValueError, match="depth"):
        resolve_tree_program(p1)
    deep = GBMParameters(response_column="y", tree_program="scan",
                         max_depth=12, sparse_depth_threshold=3)
    with pytest.raises(ValueError, match="sparse"):
        resolve_tree_program(deep, hist_layout="sparse")
    with pytest.raises(ValueError, match="tree_program"):
        resolve_tree_program(
            GBMParameters(response_column="y", tree_program="bogus"))


def test_check_downgrades_where_scan_cannot_grow(cl):
    """tree_program="check" silently rides the level program on shapes
    the scan cannot grow — never raises, never forfeits the model."""
    deep = GBMParameters(response_column="y", tree_program="check",
                         max_depth=12, sparse_depth_threshold=3)
    assert resolve_tree_program(deep, hist_layout="sparse") == "level"
    assert resolve_tree_program(
        GBMParameters(response_column="y", tree_program="check",
                      max_depth=5), mono={"x0": 1}) == "level"
    assert resolve_tree_program(
        GBMParameters(response_column="y", tree_program="check",
                      max_depth=1)) == "level"
    # the happy path stays "check" (the driver then runs the oracle)
    assert resolve_tree_program(
        GBMParameters(response_column="y", tree_program="check",
                      max_depth=5)) == "check"
    # "auto" under H2O3_TPU_AUTOTUNE=off is the historical level path
    assert resolve_tree_program(
        GBMParameters(response_column="y", max_depth=5)) == "level"


def test_build_fn_rejects_scan_with_engaged_sparse(cl):
    with pytest.raises(ValueError, match="sparse"):
        make_build_tree_fn(10, 16, 5, 4096, "f32", hist_layout="sparse",
                           sparse_depth_threshold=2, tree_program="scan")
    with pytest.raises(ValueError, match="depth"):
        make_build_tree_fn(1, 16, 5, 256, "f32", tree_program="scan")


# ------------------------------------------------------------- drivers

def _reg_frame(n=400, seed=0, key="scan_reg"):
    r = np.random.default_rng(seed)
    X = r.normal(size=(n, 5))
    y = X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.1 * r.normal(size=n)
    cols = {f"x{j}": X[:, j] for j in range(5)}
    cols["y"] = y
    return Frame.from_numpy(cols, key=key)


def _multi_frame(n=400, seed=1, key="scan_multi"):
    r = np.random.default_rng(seed)
    centers = np.array([[2, 0], [-2, 1], [0, -2]])
    labels = r.integers(0, 3, n)
    X = centers[labels] + r.normal(size=(n, 2))
    return Frame.from_numpy(
        {"x0": X[:, 0], "x1": X[:, 1],
         "y": np.array(["a", "b", "c"], dtype=object)[labels]}, key=key)


_KW = dict(response_column="y", ntrees=5, max_depth=4, nbins=16, seed=7,
           reproducible=True)


def _pred(m, fr):
    return np.asarray(m.predict(fr).vec("predict").to_numpy())


def test_gbm_scan_bitwise_and_check(cl):
    fr = _reg_frame()
    m_lv = GBM(**_KW, tree_program="level").train(fr)
    m_sc = GBM(**_KW, tree_program="scan").train(fr)
    np.testing.assert_array_equal(_pred(m_lv, fr), _pred(m_sc, fr))
    assert m_sc.output["tree_program"] == "scan"
    assert m_lv.output["tree_program"] == "level"
    # "check": grow the first tree both ways on the real gradients,
    # assert, then train on the scan path
    m_ck = GBM(**_KW, tree_program="check").train(fr)
    np.testing.assert_array_equal(_pred(m_lv, fr), _pred(m_ck, fr))
    assert m_ck.output["tree_program"] == "scan"


def test_gbm_multinomial_scan_bitwise(cl):
    fr = _multi_frame()
    kw = dict(response_column="y", ntrees=4, max_depth=3, nbins=16,
              seed=3, reproducible=True)
    m_lv = GBM(**kw, tree_program="level").train(fr)
    m_sc = GBM(**kw, tree_program="scan").train(fr)
    np.testing.assert_array_equal(_pred(m_lv, fr), _pred(m_sc, fr))


def test_drf_scan_bitwise(cl):
    fr = _reg_frame(key="scan_drf")
    kw = dict(response_column="y", ntrees=4, max_depth=4, nbins=16,
              seed=5, reproducible=True)
    m_lv = DRF(**kw, tree_program="level").train(fr)
    m_sc = DRF(**kw, tree_program="scan").train(fr)
    np.testing.assert_array_equal(_pred(m_lv, fr), _pred(m_sc, fr))


def test_checkpoint_continuation_across_program_switch(cl):
    """A checkpoint grown under the level program continues bit-identically
    under the scan program (and vice versa) — the knob changes dispatch
    strategy, never trees, so snapshots/checkpoints are portable."""
    fr = _reg_frame(key="scan_ckpt")
    kw = dict(response_column="y", max_depth=3, nbins=16, min_rows=10,
              seed=11)
    prior = GBM(**kw, ntrees=3, tree_program="level").train(fr)
    cont_lv = GBM(**kw, ntrees=7, checkpoint=prior.key,
                  tree_program="level").train(fr)
    cont_sc = GBM(**kw, ntrees=7, checkpoint=prior.key,
                  tree_program="scan").train(fr)
    np.testing.assert_array_equal(_pred(cont_lv, fr), _pred(cont_sc, fr))
    # and a scan-grown prior continues under level
    prior_sc = GBM(**kw, ntrees=3, tree_program="scan").train(fr)
    cont_back = GBM(**kw, ntrees=7, checkpoint=prior_sc.key,
                    tree_program="level").train(fr)
    np.testing.assert_array_equal(_pred(cont_lv, fr), _pred(cont_back, fr))
