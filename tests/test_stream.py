"""Streaming ingest plane (tier-1): StreamingFrame parity + stream= training.

The contract under test: a frame assembled from ranges landing
incrementally is BITWISE identical to the batch ``parse_csv`` /
``parse_parquet`` result (numeric, NA, categorical and string columns,
mid-row range cuts included), the watermark/backpressure surface behaves
as documented, and a ``stream=True`` tree build over a fully-landed
stream produces the very same model as the batch path.
"""

import os

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import StreamingFrame, stream_file
from h2o3_tpu.frame import lineage
from h2o3_tpu.frame.parse import parse_csv
from h2o3_tpu.ingest.stream import StreamError
from h2o3_tpu.models import GBM
from h2o3_tpu.runtime import failure
from h2o3_tpu.runtime.config import reload as config_reload

_STREAM_ENV = ("H2O3_PARSE_RANGE_MIN", "H2O3_TPU_FAULT_INJECT",
               "H2O3_TPU_STREAM_MIN_ROWS", "H2O3_TPU_STREAM_BUFFER_ROWS",
               "H2O3_TPU_STREAM_GROW_MIN_FRAC", "H2O3_TPU_STREAM_ROUND_ROWS")


@pytest.fixture(autouse=True)
def _clean(cl):
    failure.reset()
    yield
    failure.reset()
    for k in _STREAM_ENV:
        os.environ.pop(k, None)
    config_reload()


def _write_csv(tmp_path, name="stream.csv", n=1200):
    """Mixed-type CSV: numeric, numeric-with-NA, categorical (with NA),
    high-cardinality string — every row a different width so tiny range
    plans cut mid-file at awkward (but newline-aligned) offsets."""
    lines = ["num,gappy,cat,tag,y"]
    for i in range(n):
        gap = "NA" if i % 11 == 0 else f"{i * 0.25}"
        cat = ["red", "green", "blue"][i % 3] if i % 13 else "NA"
        lines.append(f"{i},{gap},{cat},tag_{i:05d},{(i * 7) % 5}")
    path = tmp_path / name
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def _assert_frames_equal(a, b, what=""):
    assert a.names == b.names and a.nrows == b.nrows, what
    ca, cb = lineage.canonical_cols(a), lineage.canonical_cols(b)
    for name, x, y in zip(a.names, ca, cb):
        if x.dtype == object:
            assert list(x) == list(y), f"{what}: column {name}"
        else:
            assert x.dtype == y.dtype, f"{what}: column {name} dtype"
            np.testing.assert_array_equal(x, y, err_msg=f"{what}: {name}")
    for name in a.names:
        assert a.vec(name).type == b.vec(name).type, f"{what}: {name} type"
        assert a.vec(name).domain == b.vec(name).domain, f"{what}: {name}"


# ------------------------------------------------------------- frame parity

def test_csv_streamed_bitwise_equals_batch(cl, tmp_path):
    path = _write_csv(tmp_path)
    batch = parse_csv(path, destination_frame="stream_batch_ref")
    # force many newline-aligned ranges (mid-row byte cuts snapped by the
    # range planner) so assembly genuinely spans range boundaries
    os.environ["H2O3_PARSE_RANGE_MIN"] = "2048"
    sf = stream_file(path, destination_frame="stream_csv_parity")
    fr = sf.frame(timeout=60)
    prog = sf.progress()
    assert prog["complete"] and prog["ranges_total"] > 1, prog
    assert prog["watermark"] == batch.nrows
    _assert_frames_equal(batch, fr, "csv streamed vs batch")
    # streamed parse publishes the same replayable lineage record shape
    rec = lineage.get_record(fr.key)
    assert rec is not None and rec["kind"] == "parse"


def test_parquet_streamed_bitwise_equals_batch(cl, tmp_path):
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")
    from h2o3_tpu.frame.parse import parse_arrow
    n = 900
    rng = np.random.default_rng(5)
    tab = pa.table({
        "num": rng.random(n),
        "gappy": pa.array([None if i % 9 == 0 else float(i)
                           for i in range(n)], pa.float64()),
        "cat": pa.array([["a", "b", "c"][i % 3] for i in range(n)]
                        ).dictionary_encode(),
        "tag": [f"t{i:04d}" for i in range(n)],
    })
    path = str(tmp_path / "stream.parquet")
    pq.write_table(tab, path, row_group_size=128)  # 8 row groups
    batch = parse_arrow(path, "parquet", destination_frame="pq_batch_ref")
    sf = stream_file(path, destination_frame="pq_stream_parity")
    fr = sf.frame(timeout=60)
    assert sf.progress()["ranges_total"] > 1
    _assert_frames_equal(batch, fr, "parquet streamed vs batch")


# ------------------------------------------------- watermark / backpressure

def test_watermark_backpressure_and_consume(cl, tmp_path):
    path = _write_csv(tmp_path, n=800)
    os.environ["H2O3_PARSE_RANGE_MIN"] = "1024"
    os.environ["H2O3_TPU_STREAM_BUFFER_ROWS"] = "200"
    config_reload()
    sf = StreamingFrame(path, destination_frame="stream_bp").start()
    wm = sf.wait_rows(100, timeout=30)
    assert wm >= 100
    # worker must stall once landed-but-unconsumed exceeds the buffer cap
    # (one in-flight range of slack): it cannot land the whole file
    deadline = sf.wait_rows(800, timeout=1.0)
    assert deadline < 800 and not sf.complete
    assert sf.progress()["backpressure_waits"] > 0
    # frame() drains the buffer and unblocks the worker
    fr = sf.frame(timeout=60)
    assert fr.nrows == 800 and sf.complete


def test_stream_error_surfaces_and_wait_raises(cl, tmp_path):
    path = _write_csv(tmp_path, n=600)
    os.environ["H2O3_PARSE_RANGE_MIN"] = "1024"
    os.environ["H2O3_TPU_FAULT_INJECT"] = "parse_range:0:2:raise"
    config_reload()
    sf = StreamingFrame(path, destination_frame="stream_err").start()
    with pytest.raises(StreamError):
        sf.wait_rows(600, timeout=30)
    assert sf.error is not None and not sf.complete


# --------------------------------------------------------- stream= training

def _train_kw():
    return dict(response_column="y", ntrees=6, max_depth=3, nbins=32,
                min_rows=10, seed=7, score_tree_interval=3)


def test_stream_train_fully_landed_equals_batch(cl, tmp_path):
    """Degenerate stream (everything landed before boosting starts) must
    reproduce the batch model bitwise — one segment, no re-bin."""
    path = _write_csv(tmp_path, n=1000)
    batch_fr = parse_csv(path, destination_frame="stream_tr_batch")
    m_batch = GBM(**_train_kw()).train(batch_fr)

    os.environ["H2O3_TPU_STREAM_MIN_ROWS"] = "1000"
    config_reload()
    sf = stream_file(path, destination_frame="stream_tr_stream")
    m_stream = GBM(**_train_kw(), stream=True).train(sf)
    cov = m_stream.output["stream_coverage"]
    assert cov[-1]["rows"] == 1000 and cov[-1]["trees"] == 6
    assert m_stream.output["stream_segments"] == 1

    pb = m_batch.predict(batch_fr).vec("predict").to_numpy()
    ps = m_stream.predict(batch_fr).vec("predict").to_numpy()
    np.testing.assert_array_equal(pb, ps)


def test_stream_train_multisegment_coverage(cl, tmp_path):
    """Throttled landing forces boosting to start behind the watermark:
    multiple segments, monotone row coverage, full data in the last."""
    from h2o3_tpu.runtime.observability import counter
    path = _write_csv(tmp_path, n=1000)
    os.environ["H2O3_PARSE_RANGE_MIN"] = "2048"
    os.environ["H2O3_TPU_STREAM_MIN_ROWS"] = "150"
    os.environ["H2O3_TPU_STREAM_GROW_MIN_FRAC"] = "0.2"
    # deterministic throttle: every range delayed so chunk fences observe
    # a moving watermark
    os.environ["H2O3_TPU_FAULT_INJECT"] = "parse_range:0:0:delay:40:999"
    config_reload()
    rebin0 = counter("stream_rebin_total", algo="gbm").value
    sf = stream_file(path, destination_frame="stream_tr_multi")
    builder = GBM(**_train_kw(), stream=True)
    m = builder.train(sf)
    cov = m.output["stream_coverage"]
    assert len(cov) >= 2, cov
    rows = [c["rows"] for c in cov]
    trees = [c["trees"] for c in cov]
    assert rows == sorted(rows) and rows[-1] == 1000
    assert trees == sorted(trees) and trees[-1] == 6
    assert counter("stream_rebin_total", algo="gbm").value > rebin0
    # every landed row was consumed by the trainer; job carries progress
    assert sf.progress()["consumed"] >= 1000
    assert builder.job.stream["complete"] is True
