"""Mid-stream host death (chaos half of the streaming plane).

Contract: a parse worker killed partway through landing leaves a
``streaming`` lineage record stamped with exactly the ranges that
landed; ``resume()`` re-parses ONLY the missing ranges (proved by
counting ``native.parse_bytes`` calls) and the recovered frame is
bitwise identical to the batch parse.
"""

import os

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import StreamingFrame
from h2o3_tpu.frame import lineage
from h2o3_tpu.frame.parse import parse_csv
from h2o3_tpu.ingest.stream import StreamError
from h2o3_tpu import native
from h2o3_tpu.runtime import failure
from h2o3_tpu.runtime.config import reload as config_reload


@pytest.fixture(autouse=True)
def _clean(cl):
    failure.reset()
    yield
    failure.reset()
    for k in ("H2O3_PARSE_RANGE_MIN", "H2O3_TPU_FAULT_INJECT",
              "H2O3_TPU_STREAM_BUFFER_ROWS"):
        os.environ.pop(k, None)
    config_reload()


def _write_csv(tmp_path, n=1500):
    lines = ["num,gappy,cat,tag"]
    for i in range(n):
        gap = "NA" if i % 7 == 0 else f"{i * 0.5}"
        cat = ["ok", "warn", "crit"][i % 3]
        lines.append(f"{i},{gap},{cat},tag_{i:05d}")
    path = tmp_path / "chaos.csv"
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def test_mid_stream_death_resumes_missing_ranges_only(cl, tmp_path,
                                                      monkeypatch):
    path = _write_csv(tmp_path)
    batch = parse_csv(path, destination_frame="chaos_batch_ref")

    # many small ranges, then kill the worker (in-process analog of a
    # host death: the injection raises inside the landing loop) on its
    # fourth range
    os.environ["H2O3_PARSE_RANGE_MIN"] = "1024"
    os.environ["H2O3_TPU_FAULT_INJECT"] = "parse_range:0:4:raise"
    config_reload()
    sf = StreamingFrame(path, destination_frame="chaos_stream").start()
    with pytest.raises(StreamError):
        sf.wait_rows(batch.nrows, timeout=30)
    assert sf.error is not None

    prog = sf.progress()
    n_total = prog["ranges_total"]
    n_landed = prog["ranges_landed"]
    assert n_total > 4 and 0 < n_landed < n_total, prog

    # the partial lineage record carries exactly the landed ranges,
    # each stamped with source bytes + sha1 for replay verification
    rec = lineage.get_record(sf.key)
    assert rec is not None and rec.get("streaming") \
        and rec["complete"] is False
    assert len(rec["ranges"]) == n_landed
    for rng in rec["ranges"]:
        assert rng["hi"] > rng["lo"] and rng["src_sha1"]

    # resume with the fault disarmed: ONLY the missing ranges re-parse
    os.environ.pop("H2O3_TPU_FAULT_INJECT")
    failure.reset()
    calls = {"n": 0}
    real = native.parse_bytes

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(native, "parse_bytes", counting)
    fr = sf.resume().frame(timeout=60)
    assert calls["n"] == n_total - n_landed

    # recovered frame is bitwise identical to the batch parse
    assert fr.names == batch.names and fr.nrows == batch.nrows
    for x, y in zip(lineage.canonical_cols(batch),
                    lineage.canonical_cols(fr)):
        if x.dtype == object:
            assert list(x) == list(y)
        else:
            np.testing.assert_array_equal(x, y)
    # and the lineage record was promoted to a complete parse record
    final = lineage.get_record(fr.key)
    assert final is not None and final["kind"] == "parse"
