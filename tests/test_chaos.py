"""Chaos matrix: kill a training process mid-GBM, restart, resume, and
prove the resumed model matches the uninterrupted run.

This is the end-to-end acceptance scenario for survivable training:
``H2O3_TPU_FAULT_INJECT`` hard-kills (exit 137) a real subprocess at
tree-chunk k, the journal keeps the entry 'running' with the snapshot
taken at the last chunk boundary, a FRESH process re-imports the frame
and ``resume()``s — training continues from the snapshot (the log and
resume provenance prove it was not tree 0) and final predictions match
a never-interrupted run.  ``tools/chaos.sh`` is the operator entry
point for this suite.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import h2o3_tpu

NTREES = 12
KILL_AT_CHUNK = 3          # chunks are 2 trees: snapshot covers 4 trees
COORD_KILL_AT_CONN = 12    # coordinator self-kills at the nth connection


def _chaos_env(tmp_path, extra=None):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "H2O3_TPU_RECOVERY_DIR": str(tmp_path),
        "H2O3_TPU_SNAPSHOT_INTERVAL": "0",
        "H2O3_TPU_SNAPSHOT_ASYNC": "0",
        "H2O3_TPU_LOG_STDERR": "1",
    })
    env.update(extra or {})
    return env


def _write_csv(path, seed=11, n=600):
    rng = np.random.default_rng(seed)
    X = rng.random((n, 4))
    y = (10 * np.sin(np.pi * X[:, 0]) + 5 * X[:, 1] ** 2
         + 3 * X[:, 2] + 0.1 * rng.normal(size=n))
    rows = np.column_stack([X, y])
    path.write_text("x0,x1,x2,x3,y\n" + "\n".join(
        ",".join(f"{v:.9g}" for v in r) for r in rows))
    return str(path)


_TRAIN = textwrap.dedent("""
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    h2o3_tpu.init()
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.models import GBM
    fr = import_file(sys.argv[1], destination_frame="chaos_fr")
    m = GBM(response_column="y", ntrees={nt}, max_depth=3, learn_rate=0.2,
            seed=7, score_tree_interval=2).train(fr)
    np.save(sys.argv[2], m.predict(fr).to_numpy()[:, 0])
    print("TRAINED", m.output["ntrees_trained"])
""").format(nt=NTREES)

_RESUME = textwrap.dedent("""
    import json
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    h2o3_tpu.init()
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.runtime import dkv, recovery
    fr = import_file(sys.argv[1], destination_frame="chaos_fr")
    done = recovery.resume()
    assert len(done) == 1, f"expected 1 resumed model, got {{done}}"
    m = dkv.get(done[0])
    from h2o3_tpu.runtime.observability import recent_logs
    resumed_lines = [l for l in recent_logs()
                     if "resuming GBM from snapshot" in l]
    print("RESUME_INFO", json.dumps({{
        "ntrees": m.output["ntrees_trained"],
        "cursor": m.output["resumed_from_snapshot"]["cursor"],
        "log_proof": len(resumed_lines)}}))
    np.save(sys.argv[2], m.predict(fr).to_numpy()[:, 0])
""").format()


def _run(script, env, *args, expect_rc=0, timeout=420):
    proc = subprocess.run(
        [sys.executable, "-c", script, *args],
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
        capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == expect_rc, (
        f"rc={proc.returncode} (wanted {expect_rc})\n"
        f"stdout:\n{proc.stdout[-3000:]}\nstderr:\n{proc.stderr[-3000:]}")
    return proc


def test_kill_resume_verify_gbm(cl, tmp_path):
    """The full scenario: baseline run, killed run (exit 137 at chunk 3),
    fresh-process resume, predictions equal, resumed-from-snapshot
    proven by cursor + log."""
    csv = _write_csv(tmp_path / "chaos.csv")
    base_dir = tmp_path / "base_recovery"
    base_dir.mkdir()

    # 1. uninterrupted baseline (own journal dir: completes + cleans up)
    base_npy = str(tmp_path / "base.npy")
    out = _run(_TRAIN, _chaos_env(base_dir), csv, base_npy)
    assert f"TRAINED {NTREES}" in out.stdout
    assert not list(base_dir.glob("job_*.json"))

    # 2. killed run: SIGKILL-style exit 137 at the 3rd tree chunk
    kill_dir = tmp_path / "kill_recovery"
    kill_dir.mkdir()
    kill_npy = str(tmp_path / "kill.npy")
    _run(_TRAIN,
         _chaos_env(kill_dir,
                    {"H2O3_TPU_FAULT_INJECT":
                     f"tree_chunk:0:{KILL_AT_CHUNK}"}),
         csv, kill_npy, expect_rc=137)
    assert not os.path.exists(kill_npy)          # it really died mid-train
    entries = list(kill_dir.glob("job_*.json"))
    assert len(entries) == 1
    entry = json.loads(entries[0].read_text())
    assert entry["status"] == "running"
    assert entry["frame_source"] == csv
    assert entry["snapshot_uri"]
    assert entry["snapshot_cursor"]["trees_done"] == 2 * (KILL_AT_CHUNK - 1)
    assert list(kill_dir.glob("snap_*.bin"))

    # 3. fresh process: re-import under the original key, resume()
    res_npy = str(tmp_path / "resumed.npy")
    out = _run(_RESUME, _chaos_env(kill_dir), csv, res_npy)
    info = json.loads(
        next(line for line in out.stdout.splitlines()
             if line.startswith("RESUME_INFO ")).split(" ", 1)[1])
    assert info["ntrees"] == NTREES
    assert info["cursor"]["trees_done"] == 2 * (KILL_AT_CHUNK - 1)
    assert info["log_proof"] >= 1                # "resuming GBM from snapshot"
    # journal + snapshot cleaned up after the successful resume
    assert not list(kill_dir.glob("job_*.json"))
    assert not list(kill_dir.glob("snap_*.bin"))

    # 4. the resumed model equals the uninterrupted one
    base = np.load(base_npy)
    resumed = np.load(res_npy)
    np.testing.assert_allclose(resumed, base, rtol=1e-4, atol=1e-4)


_TRAIN_DEEP = textwrap.dedent("""
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    h2o3_tpu.init()
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.models import GBM
    fr = import_file(sys.argv[1], destination_frame="chaos_fr")
    m = GBM(response_column="y", ntrees={nt}, max_depth=5, learn_rate=0.2,
            seed=7, score_tree_interval=2, hist_layout="sparse",
            sparse_depth_threshold=2).train(fr)
    assert m.output["hist_layout"] == "sparse"
    np.save(sys.argv[2], m.predict(fr).to_numpy()[:, 0])
    print("TRAINED", m.output["ntrees_trained"])
""").format(nt=NTREES)


def test_kill_resume_mid_deep_tree(cl, tmp_path):
    """Chaos row for the node-sparse deep-level path: ``deep_level``
    fires at the top of each tree chunk only when ``hist_layout="sparse"``
    is engaged past its depth threshold, so the kill lands while the
    sparse slot layout is live.  Resume must restart from the last
    chunk-boundary snapshot, rebuild the sparse level program in a fresh
    process, and reproduce the uninterrupted run's predictions."""
    csv = _write_csv(tmp_path / "chaos_deep.csv")
    base_dir = tmp_path / "base_deep"
    base_dir.mkdir()

    base_npy = str(tmp_path / "base_deep.npy")
    out = _run(_TRAIN_DEEP, _chaos_env(base_dir), csv, base_npy)
    assert f"TRAINED {NTREES}" in out.stdout

    kill_dir = tmp_path / "kill_deep"
    kill_dir.mkdir()
    kill_npy = str(tmp_path / "kill_deep.npy")
    _run(_TRAIN_DEEP,
         _chaos_env(kill_dir,
                    {"H2O3_TPU_FAULT_INJECT":
                     f"deep_level:0:{KILL_AT_CHUNK}"}),
         csv, kill_npy, expect_rc=137)
    assert not os.path.exists(kill_npy)          # it really died mid-train
    (entry_path,) = kill_dir.glob("job_*.json")
    entry = json.loads(entry_path.read_text())
    assert entry["status"] == "running"
    assert entry["snapshot_uri"]
    assert entry["snapshot_cursor"]["trees_done"] == 2 * (KILL_AT_CHUNK - 1)

    res_npy = str(tmp_path / "resumed_deep.npy")
    out = _run(_RESUME, _chaos_env(kill_dir), csv, res_npy)
    info = json.loads(
        next(line for line in out.stdout.splitlines()
             if line.startswith("RESUME_INFO ")).split(" ", 1)[1])
    assert info["ntrees"] == NTREES
    assert info["cursor"]["trees_done"] == 2 * (KILL_AT_CHUNK - 1)
    assert info["log_proof"] >= 1
    assert not list(kill_dir.glob("job_*.json"))

    np.testing.assert_allclose(np.load(res_npy), np.load(base_npy),
                               rtol=1e-4, atol=1e-4)


_TRAIN_SCAN = textwrap.dedent("""
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    h2o3_tpu.init()
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.models import GBM
    fr = import_file(sys.argv[1], destination_frame="chaos_fr")
    m = GBM(response_column="y", ntrees={nt}, max_depth=5, learn_rate=0.2,
            seed=7, score_tree_interval=2,
            tree_program="scan").train(fr)
    assert m.output["tree_program"] == "scan"
    np.save(sys.argv[2], m.predict(fr).to_numpy()[:, 0])
    print("TRAINED", m.output["ntrees_trained"])
""").format(nt=NTREES)


def test_kill_resume_mid_scan_program(cl, tmp_path):
    """Chaos row for the scan-fused tree program: under
    ``tree_program="scan"`` the per-level host loop is gone, so the
    tree-chunk fence is the only interruption point and snapshots carry
    the coarser per-tree-chunk granularity tag.  The kill lands at a
    chunk fence mid-scan-training; resume must restart from the
    per-tree snapshot (cursor proves which one, and that it is
    chunk-granular), rebuild the scan program in a fresh process, and
    reproduce the uninterrupted run's predictions — the snapshot
    granularity change loses no recoverability."""
    csv = _write_csv(tmp_path / "chaos_scan.csv")
    base_dir = tmp_path / "base_scan"
    base_dir.mkdir()

    base_npy = str(tmp_path / "base_scan.npy")
    out = _run(_TRAIN_SCAN, _chaos_env(base_dir), csv, base_npy)
    assert f"TRAINED {NTREES}" in out.stdout

    kill_dir = tmp_path / "kill_scan"
    kill_dir.mkdir()
    kill_npy = str(tmp_path / "kill_scan.npy")
    _run(_TRAIN_SCAN,
         _chaos_env(kill_dir,
                    {"H2O3_TPU_FAULT_INJECT":
                     f"tree_chunk:0:{KILL_AT_CHUNK}"}),
         csv, kill_npy, expect_rc=137)
    assert not os.path.exists(kill_npy)          # it really died mid-train
    (entry_path,) = kill_dir.glob("job_*.json")
    entry = json.loads(entry_path.read_text())
    assert entry["status"] == "running"
    assert entry["snapshot_uri"]
    cursor = entry["snapshot_cursor"]
    assert cursor["trees_done"] == 2 * (KILL_AT_CHUNK - 1)
    assert cursor["granularity"] == "tree_chunk"

    res_npy = str(tmp_path / "resumed_scan.npy")
    out = _run(_RESUME, _chaos_env(kill_dir), csv, res_npy)
    info = json.loads(
        next(line for line in out.stdout.splitlines()
             if line.startswith("RESUME_INFO ")).split(" ", 1)[1])
    assert info["ntrees"] == NTREES
    assert info["cursor"]["trees_done"] == 2 * (KILL_AT_CHUNK - 1)
    assert info["log_proof"] >= 1
    assert not list(kill_dir.glob("job_*.json"))

    np.testing.assert_allclose(np.load(res_npy), np.load(base_npy),
                               rtol=1e-4, atol=1e-4)


_MULTI_CSV_ROWS = 600

_TRAIN_MULTI = textwrap.dedent("""
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    h2o3_tpu.init()
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.models import GBM
    fr = import_file(sys.argv[1], destination_frame="chaos_multi_fr")
    m = GBM(response_column="y", ntrees={nt}, max_depth=3, learn_rate=0.2,
            seed=7, score_tree_interval=2).train(fr)
    probs = np.stack([m.predict(fr).vec(c).to_numpy() for c in "abc"],
                     axis=1)
    np.save(sys.argv[2], probs)
    print("TRAINED", m.output["ntrees_trained"])
""").format(nt=NTREES)

_RESUME_MULTI = textwrap.dedent("""
    import json
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    h2o3_tpu.init()
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.runtime import dkv, recovery
    fr = import_file(sys.argv[1], destination_frame="chaos_multi_fr")
    done = recovery.resume()
    assert len(done) == 1, f"expected 1 resumed model, got {{done}}"
    m = dkv.get(done[0])
    print("RESUME_INFO", json.dumps({{
        "ntrees": m.output["ntrees_trained"],
        "cursor": m.output["resumed_from_snapshot"]["cursor"]}}))
    probs = np.stack([m.predict(fr).vec(c).to_numpy() for c in "abc"],
                     axis=1)
    np.save(sys.argv[2], probs)
""").format()


def _write_multi_csv(path, seed=13, n=_MULTI_CSV_ROWS):
    rng = np.random.default_rng(seed)
    centers = np.array([[2.0, 0.0], [-2.0, 1.0], [0.0, -2.0]])
    labels = rng.integers(0, 3, n)
    X = centers[labels] + rng.normal(size=(n, 2))
    names = np.array(["a", "b", "c"])[labels]
    path.write_text("x0,x1,y\n" + "\n".join(
        f"{r[0]:.9g},{r[1]:.9g},{s}" for r, s in zip(X, names)))
    return str(path)


def test_kill_resume_mid_multinomial_round(cl, tmp_path):
    """Chaos row for the batched K-tree path: ``ktree_round`` fires at the
    top of every fused multinomial chunk (one launch per level for all K
    class trees), so the kill lands mid-boosting-round on the batched
    pipeline.  Resume must restart from the last chunk-boundary snapshot
    and reproduce the uninterrupted run's class probabilities."""
    csv = _write_multi_csv(tmp_path / "chaos_multi.csv")
    base_dir = tmp_path / "base_multi"
    base_dir.mkdir()

    base_npy = str(tmp_path / "base_multi.npy")
    out = _run(_TRAIN_MULTI, _chaos_env(base_dir), csv, base_npy)
    assert f"TRAINED {NTREES}" in out.stdout

    kill_dir = tmp_path / "kill_multi"
    kill_dir.mkdir()
    kill_npy = str(tmp_path / "kill_multi.npy")
    _run(_TRAIN_MULTI,
         _chaos_env(kill_dir,
                    {"H2O3_TPU_FAULT_INJECT":
                     f"ktree_round:0:{KILL_AT_CHUNK}"}),
         csv, kill_npy, expect_rc=137)
    assert not os.path.exists(kill_npy)
    (entry_path,) = kill_dir.glob("job_*.json")
    entry = json.loads(entry_path.read_text())
    assert entry["status"] == "running"
    assert entry["snapshot_uri"]
    assert entry["snapshot_cursor"]["trees_done"] == 2 * (KILL_AT_CHUNK - 1)

    res_npy = str(tmp_path / "resumed_multi.npy")
    out = _run(_RESUME_MULTI, _chaos_env(kill_dir), csv, res_npy)
    info = json.loads(
        next(line for line in out.stdout.splitlines()
             if line.startswith("RESUME_INFO ")).split(" ", 1)[1])
    assert info["ntrees"] == NTREES
    assert info["cursor"]["trees_done"] == 2 * (KILL_AT_CHUNK - 1)
    assert not list(kill_dir.glob("job_*.json"))

    np.testing.assert_allclose(np.load(res_npy), np.load(base_npy),
                               rtol=1e-4, atol=1e-4)


_COORD = textwrap.dedent("""
    import sys
    import time
    from h2o3_tpu.runtime import dkv
    port = dkv.serve(host="127.0.0.1", port=int(sys.argv[1]))
    print("SERVING", port, dkv._epoch, flush=True)
    while True:
        time.sleep(0.1)
""")

_TRAIN_COORD_KILL = textwrap.dedent("""
    import json
    import sys
    import time
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    h2o3_tpu.init()
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.models import GBM
    from h2o3_tpu.runtime import dkv, heartbeat
    dkv.attach("127.0.0.1", int(sys.argv[3]))
    heartbeat.start(interval=0.3)        # steady control-plane traffic
    dkv.put("!coordchaos/fact", {{"who": "worker", "n": 42}})
    fr = import_file(sys.argv[1], destination_frame="chaos_fr")
    m = GBM(response_column="y", ntrees={nt}, max_depth=3, learn_rate=0.2,
            seed=7, score_tree_interval=2).train(fr)
    np.save(sys.argv[2], m.predict(fr).to_numpy()[:, 0])
    # poll until the RESTARTED coordinator serves our fact again (either
    # rehydrated from its WAL or re-pushed on the epoch bump)
    fact = None
    deadline = time.time() + 120
    while time.time() < deadline:
        try:
            fact = dkv._rpc("get", key="!coordchaos/fact")
            if fact is not None:
                break
        except Exception:
            pass
        time.sleep(0.2)
    # telemetry must survive the restart: the re-shipped heartbeat stamp
    # (dkv._repush -> heartbeat.reship) must already carry this worker's
    # metrics snapshot on the NEW coordinator incarnation — no gap until
    # the next beat interval
    hb_metrics = 0
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            stamp = dkv._rpc("get", key="!hb/" + heartbeat.node_name())
            if isinstance(stamp, dict) and stamp.get("metrics"):
                hb_metrics = len(stamp["metrics"])
                break
        except Exception:
            pass
        time.sleep(0.2)
    from h2o3_tpu.runtime.observability import timeline_events
    evs = timeline_events(2000)
    print("WORKER_INFO", json.dumps({{
        "ntrees": m.output["ntrees_trained"],
        "seen_epoch": dkv._seen_epoch,
        "fact": fact,
        "retries": sum(1 for e in evs if e["kind"] == "dkv_retry"),
        "bumps": sum(1 for e in evs if e["kind"] == "dkv_epoch_bump"),
        "reships": sum(1 for e in evs if e["kind"] == "metrics_reship"),
        "hb_metrics_after_bump": hb_metrics}}))
    # join the beat thread before exit: a beat sampling device gauges
    # mid-teardown can abort the interpreter from XLA's C++ side
    heartbeat.stop(remove=False)
""").format(nt=NTREES)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _start_coord(port, env):
    proc = subprocess.Popen(
        [sys.executable, "-c", _COORD, str(port)], env=env,
        cwd=os.path.dirname(os.path.dirname(__file__)),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    line = proc.stdout.readline().strip()
    if not line.startswith("SERVING"):
        try:
            _, err = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            err = "<coordinator hung>"
        raise AssertionError(f"coordinator failed: {line!r}\n{err}")
    return proc, int(line.split()[2])


def test_coordinator_hard_kill_midtrain_rehydrate_reattach(cl, tmp_path):
    """The coordinator-chaos acceptance scenario: a worker trains a GBM
    against an external DKV coordinator; the coordinator hard-kills
    itself (exit 137) mid-run via ``dkv_handle:coordinator:N``, is
    restarted on the same port + recovery dir, the worker's retry budget
    rides out the outage (zero job failures), the restarted incarnation
    presents a higher epoch, the worker re-attaches/fences it, the
    durable store comes back, and the predictions equal an uninterrupted
    run's."""
    csv = _write_csv(tmp_path / "coordchaos.csv")
    base_dir = tmp_path / "base_coord"
    base_dir.mkdir()
    base_npy = str(tmp_path / "base_coord.npy")
    out = _run(_TRAIN, _chaos_env(base_dir), csv, base_npy)
    assert f"TRAINED {NTREES}" in out.stdout

    coord_dir = tmp_path / "coord_state"
    coord_dir.mkdir()
    port = _free_port()
    proc1, ep1 = _start_coord(
        port, _chaos_env(coord_dir, {
            "H2O3_TPU_FAULT_INJECT":
            f"dkv_handle:coordinator:{COORD_KILL_AT_CONN}"}))

    worker_dir = tmp_path / "worker_recovery"
    worker_dir.mkdir()
    worker_npy = str(tmp_path / "coord_worker.npy")
    worker = subprocess.Popen(
        [sys.executable, "-c", _TRAIN_COORD_KILL, csv, worker_npy,
         str(port)],
        env=_chaos_env(worker_dir, {
            # the outage spans a subprocess restart: widen the client
            # retry envelope so no in-flight op exhausts its budget
            "H2O3_TPU_DKV_RETRIES": "60",
            "H2O3_TPU_DKV_BACKOFF_MAX": "0.5",
            "H2O3_TPU_DKV_RETRY_BUDGET": "120"}),
        cwd=os.path.dirname(os.path.dirname(__file__)),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    proc2 = None
    try:
        # the injected kill is a real os._exit(137) inside the handler
        assert proc1.wait(timeout=240) == 137

        proc2, ep2 = _start_coord(port, _chaos_env(coord_dir))
        wout, werr = worker.communicate(timeout=300)
        assert worker.returncode == 0, (
            f"worker rc={worker.returncode}\nstdout:\n{wout[-3000:]}\n"
            f"stderr:\n{werr[-3000:]}")
        info = json.loads(
            next(line for line in wout.splitlines()
                 if line.startswith("WORKER_INFO ")).split(" ", 1)[1])
        assert info["ntrees"] == NTREES              # zero job failures
        assert ep2 > ep1                             # new incarnation
        assert info["seen_epoch"] == ep2             # worker re-fenced
        assert info["fact"] == {"who": "worker", "n": 42}
        assert info["retries"] >= 1                  # outage was real
        # telemetry re-shipped after the epoch bump: the new incarnation
        # holds the worker's metrics without waiting out a beat interval
        assert info["reships"] >= 1
        assert info["hb_metrics_after_bump"] > 0
        np.testing.assert_allclose(np.load(worker_npy), np.load(base_npy),
                                   rtol=1e-4, atol=1e-4)
    finally:
        for p in (proc1, proc2, worker):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=15)


# -------------------------------------------------- multi-tenant host kill

MT_BIG_TREES = 16          # 8 chunks of 2 trees
MT_SMALL_TREES = 12        # 6 chunks each
MT_KILL_AT_HIT = 5         # shared tree_chunk counter: < any job's 6th
                           # chunk-top, so NO job can have completed

_TENANT_TRAIN = textwrap.dedent("""
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    h2o3_tpu.init()
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.models import GBM
    from h2o3_tpu.runtime import dkv
    dkv.serve(host="127.0.0.1", port=0)   # coordinator role: WAL on
    fr = import_file(sys.argv[1], destination_frame="mt_fr")
    jobs = []
    big = GBM(response_column="y", ntrees={big}, max_depth=3,
              learn_rate=0.2, seed=7, score_tree_interval=2,
              device_budget=0.5, retry_budget=1)
    jobs.append((7, big.train_async(fr, user="alice")))
    for seed, user in ((101, "bob"), (102, "carol"), (103, "dave")):
        small = GBM(response_column="y", ntrees={small}, max_depth=2,
                    learn_rate=0.2, seed=seed, score_tree_interval=2,
                    device_budget=0.125, retry_budget=1)
        jobs.append((seed, small.train_async(fr, user=user)))
    for seed, job in jobs:
        m = job.join(timeout=600)
        assert job.status == "DONE", (seed, job.status, job.exception)
        np.save(sys.argv[2] + "_" + str(seed) + ".npy",
                m.predict(fr).to_numpy()[:, 0])
    print("TRAINED_ALL", len(jobs))
""").format(big=MT_BIG_TREES, small=MT_SMALL_TREES)

_TENANT_READMIT = textwrap.dedent("""
    import json
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    h2o3_tpu.init()
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.runtime import dkv, scheduler
    dkv.serve(host="127.0.0.1", port=0)   # rehydrates the WAL: the
    # !sched/ scheduling records and the make_key counter come back
    fr = import_file(sys.argv[1], destination_frame="mt_fr")
    jobs = scheduler.readmit(block=True)
    assert len(jobs) == 4, [j.describe() for j in jobs]
    users = set()
    for job in jobs:
        assert job.status == "DONE", (job.key, job.status, job.exception)
        users.add(job.user)
        m = job.result
        np.save(sys.argv[2] + "_" + str(m.params.seed) + ".npy",
                m.predict(fr).to_numpy()[:, 0])
    print("READMIT_INFO", json.dumps({"n": len(jobs),
                                      "users": sorted(users)}))
""")


def test_host_kill_mid_multitenant_load(cl, tmp_path):
    """Chaos row: one large + three small tenant jobs run CONCURRENTLY
    under the fair-share scheduler when the host is hard-killed.  A fresh
    process rehydrates the coordinator WAL, re-imports the frame, and
    ``scheduler.readmit()`` re-admits all four jobs with their original
    tenants — zero job failures, every prediction matches an
    uninterrupted run."""
    csv = _write_csv(tmp_path / "mt.csv")
    base_dir = tmp_path / "base_mt"
    base_dir.mkdir()

    base_prefix = str(tmp_path / "base_mt_pred")
    out = _run(_TENANT_TRAIN, _chaos_env(base_dir), csv, base_prefix,
               timeout=600)
    assert "TRAINED_ALL 4" in out.stdout
    assert not list(base_dir.glob("job_*.json"))    # all journals consumed

    # hard-kill while all four jobs are in flight: the shared injection
    # counter guarantees no job has reached its final chunk by hit 5
    kill_dir = tmp_path / "kill_mt"
    kill_dir.mkdir()
    _run(_TENANT_TRAIN,
         _chaos_env(kill_dir,
                    {"H2O3_TPU_FAULT_INJECT":
                     f"tree_chunk:0:{MT_KILL_AT_HIT}"}),
         csv, str(tmp_path / "unused_mt"), expect_rc=137, timeout=600)
    entries = [json.loads(p.read_text())
               for p in kill_dir.glob("job_*.json")]
    assert len(entries) == 4                        # every tenant journaled
    assert all(e["status"] == "running" for e in entries)

    res_prefix = str(tmp_path / "res_mt_pred")
    out = _run(_TENANT_READMIT, _chaos_env(kill_dir), csv, res_prefix,
               timeout=600)
    info = json.loads(
        next(line for line in out.stdout.splitlines()
             if line.startswith("READMIT_INFO ")).split(" ", 1)[1])
    assert info["n"] == 4
    assert info["users"] == ["alice", "bob", "carol", "dave"]
    assert not list(kill_dir.glob("job_*.json"))

    for seed in (7, 101, 102, 103):
        np.testing.assert_allclose(
            np.load(f"{res_prefix}_{seed}.npy"),
            np.load(f"{base_prefix}_{seed}.npy"),
            rtol=1e-4, atol=1e-4, err_msg=f"tenant model seed={seed}")


# ------------------------------------------------- host join / fenced rebuild

_JOIN_TRAIN = textwrap.dedent("""
    import json
    import sys
    import time
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    h2o3_tpu.init()
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.models import GBM
    from h2o3_tpu.runtime import cluster, dkv, heartbeat
    from h2o3_tpu.runtime import observability as obs
    from h2o3_tpu.runtime.job import scheduler
    s = scheduler()              # elastic membership observer is on
    heartbeat.start(interval=0.5)
    time.sleep(0.5)              # first poll baselines the membership
    fr = import_file(sys.argv[1], destination_frame="join_fr")
    big = GBM(response_column="y", ntrees={nt}, max_depth=3,
              learn_rate=0.2, seed=7, score_tree_interval=2,
              device_budget=1.0)
    job = big.train_async(fr, user="alice")
    deadline = time.time() + 300
    while job.progress < 0.15 and time.time() < deadline:
        time.sleep(0.05)
    assert job.progress >= 0.15, job.describe()
    if sys.argv[3] == "join":
        # a new host appears mid-train: an alive stamp the observer will
        # pick up within its poll; the rebuild applies at a chunk fence
        dkv.put("!hb/joiner:1",
                {{"ts": time.time(), "interval": 10.0, "pid": 1}})
    m = job.join(timeout=600)
    assert job.status == "DONE", (job.status, job.exception)
    reinits = [e for e in obs.timeline_events(5000)
               if e["kind"] == "cluster_reinit"]
    wire = obs.metrics_wire()
    print("JOIN_INFO", json.dumps({{
        "reinits": len(reinits),
        "rebuild_total": sum(s["v"] for s in wire
                             if s["n"] == "sched_rebuild_total"),
        "reinit_recompiles": sum(
            s["v"] for s in wire if s["n"] == "recompiles_total"
            and s["l"].get("reason") == "cluster_reinit"),
        "hosts_axis": cluster._cluster.mesh.shape["hosts"]}}))
    np.save(sys.argv[2], m.predict(fr).to_numpy()[:, 0])
    heartbeat.stop(remove=False)
""").format(nt=NTREES)


def test_host_join_fenced_rebuild_midtrain(cl, tmp_path):
    """Chaos row: a host joins mid-train on an elastic 1-host cluster.
    The membership observer arms a rebuild, ``chunk_fence()`` applies
    EXACTLY ONE fenced ``cluster.init(hosts=2)`` at a chunk boundary
    (proven by the timeline + ``recompiles_total{reason=cluster_reinit}``),
    and the finished model still matches an uninterrupted 1-host run."""
    csv = _write_csv(tmp_path / "join.csv")
    elastic = {"H2O3_TPU_HOSTS": "1", "H2O3_TPU_SCHED_ELASTIC": "1",
               "H2O3_TPU_SCHED_MEMBER_POLL": "0.2"}

    base_dir = tmp_path / "base_join"
    base_dir.mkdir()
    base_npy = str(tmp_path / "base_join.npy")
    out = _run(_JOIN_TRAIN, _chaos_env(base_dir, elastic), csv, base_npy,
               "nojoin", timeout=600)
    info = json.loads(
        next(line for line in out.stdout.splitlines()
             if line.startswith("JOIN_INFO ")).split(" ", 1)[1])
    assert info["reinits"] == 0 and info["hosts_axis"] == 1

    join_dir = tmp_path / "join_run"
    join_dir.mkdir()
    join_npy = str(tmp_path / "join_run.npy")
    out = _run(_JOIN_TRAIN, _chaos_env(join_dir, elastic), csv, join_npy,
               "join", timeout=600)
    info = json.loads(
        next(line for line in out.stdout.splitlines()
             if line.startswith("JOIN_INFO ")).split(" ", 1)[1])
    assert info["reinits"] == 1                # exactly one fenced rebuild
    assert info["rebuild_total"] == 1
    assert info["reinit_recompiles"] >= 1      # attributed recompiles
    assert info["hosts_axis"] == 2             # mesh actually grew

    np.testing.assert_allclose(np.load(join_npy), np.load(base_npy),
                               rtol=1e-4, atol=1e-4)


def test_kill_without_snapshot_still_resumes_from_zero(cl, tmp_path):
    """Matrix row 2: killed before the first snapshot could land
    (snapshot_write is the kill point) — the journal has no snapshot_uri
    and resume() falls back to the from-scratch retrain contract."""
    csv = _write_csv(tmp_path / "chaos0.csv")
    kill_dir = tmp_path / "kill0_recovery"
    kill_dir.mkdir()
    _run(_TRAIN,
         _chaos_env(kill_dir,
                    {"H2O3_TPU_FAULT_INJECT": "snapshot_write:0:1"}),
         csv, str(tmp_path / "unused.npy"), expect_rc=137)
    (entry_path,) = kill_dir.glob("job_*.json")
    entry = json.loads(entry_path.read_text())
    assert entry["status"] == "running"
    assert entry.get("snapshot_uri") is None

    res_npy = str(tmp_path / "resumed0.npy")
    out = _run(_RESUME.replace(
        'm.output["resumed_from_snapshot"]["cursor"]',
        'm.output.get("resumed_from_snapshot", {"cursor": None})["cursor"]'),
        _chaos_env(kill_dir), csv, res_npy)
    info = json.loads(
        next(line for line in out.stdout.splitlines()
             if line.startswith("RESUME_INFO ")).split(" ", 1)[1])
    assert info["ntrees"] == NTREES and info["cursor"] is None
    assert not list(kill_dir.glob("job_*.json"))


_TRAIN_GRID = textwrap.dedent("""
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    h2o3_tpu.init()
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.models import GBM, GridSearch
    fr = import_file(sys.argv[1], destination_frame="chaos_fr")
    g = GridSearch(GBM, {{"learn_rate": [0.1, 0.3]}}, grid_batch="on",
                   response_column="y", ntrees={nt}, max_depth=3,
                   seed=7, score_tree_interval=2).train(fr)
    assert all(m.output["grid_cohort"]["size"] == 2 for m in g.models)
    out = {{str(m.params.learn_rate):
           m.predict(fr).to_numpy()[:, 0] for m in g.models}}
    np.savez(sys.argv[2], **out)
    print("TRAINED", sorted(m.output["ntrees_trained"] for m in g.models))
""").format(nt=NTREES)

_RESUME_GRID = textwrap.dedent("""
    import json
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    h2o3_tpu.init()
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.runtime import dkv, recovery
    fr = import_file(sys.argv[1], destination_frame="chaos_fr")
    done = recovery.resume()
    assert len(done) == 2, f"expected 2 resumed members, got {{done}}"
    models = [dkv.get(k) for k in done]
    print("RESUME_INFO", json.dumps({{
        "ntrees": sorted(m.output["ntrees_trained"] for m in models),
        "cursors": sorted(
            m.output["resumed_from_snapshot"]["cursor"]["trees_done"]
            for m in models)}}))
    np.savez(sys.argv[2], **{{str(m.params.learn_rate):
             m.predict(fr).to_numpy()[:, 0] for m in models}})
""").format()


def test_kill_resume_mid_grid_cohort(cl, tmp_path):
    """Chaos row for batched grid sweeps: a 2-member cohort trains as ONE
    compiled program, so a hard kill at a tree-chunk fence interrupts
    BOTH members at once — and must leave one resumable journal entry
    per member (each with its own chunk-granular snapshot).  A fresh
    process resume()s every member independently through the sequential
    checkpoint path; both surviving models must match the uninterrupted
    batched run."""
    csv = _write_csv(tmp_path / "chaos_grid.csv")
    base_dir = tmp_path / "base_grid"
    base_dir.mkdir()

    base_npz = str(tmp_path / "base_grid.npz")
    out = _run(_TRAIN_GRID, _chaos_env(base_dir), csv, base_npz)
    assert f"TRAINED [{NTREES}, {NTREES}]" in out.stdout
    assert not list(base_dir.glob("job_*.json"))

    kill_dir = tmp_path / "kill_grid"
    kill_dir.mkdir()
    kill_npz = str(tmp_path / "kill_grid.npz")
    _run(_TRAIN_GRID,
         _chaos_env(kill_dir,
                    {"H2O3_TPU_FAULT_INJECT":
                     f"tree_chunk:0:{KILL_AT_CHUNK}"}),
         csv, kill_npz, expect_rc=137)
    assert not os.path.exists(kill_npz)          # it really died mid-cohort
    entries = [json.loads(p.read_text())
               for p in kill_dir.glob("job_*.json")]
    assert len(entries) == 2                     # one journal PER MEMBER
    for entry in entries:
        assert entry["status"] == "running"
        assert entry["snapshot_uri"]
        cursor = entry["snapshot_cursor"]
        assert cursor["trees_done"] == 2 * (KILL_AT_CHUNK - 1)
        assert cursor["granularity"] == "tree_chunk"

    res_npz = str(tmp_path / "resumed_grid.npz")
    out = _run(_RESUME_GRID, _chaos_env(kill_dir), csv, res_npz)
    info = json.loads(
        next(line for line in out.stdout.splitlines()
             if line.startswith("RESUME_INFO ")).split(" ", 1)[1])
    assert info["ntrees"] == [NTREES, NTREES]
    assert info["cursors"] == [2 * (KILL_AT_CHUNK - 1)] * 2
    assert not list(kill_dir.glob("job_*.json"))

    base, resumed = np.load(base_npz), np.load(res_npz)
    assert sorted(base.files) == sorted(resumed.files) == ["0.1", "0.3"]
    for lr in base.files:
        np.testing.assert_allclose(resumed[lr], base[lr],
                                   rtol=1e-4, atol=1e-4)
