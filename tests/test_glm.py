"""GLM tests — golden comparisons against sklearn/numpy closed forms.

Mirrors the reference's pyunit_glm* strategy (h2o-py/tests/testdir_algos/glm):
coefficient recovery on synthetic data, family sanity, regularization,
weights, CV, and predict/save/load roundtrips.
"""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.models import GLM, GLMParameters


def _make_regression(rng, n=4000, p=5, noise=0.1):
    X = rng.normal(size=(n, p))
    beta = np.arange(1, p + 1, dtype=np.float64)
    y = X @ beta + 2.5 + noise * rng.normal(size=n)
    cols = {f"x{j}": X[:, j] for j in range(p)}
    cols["y"] = y
    return Frame.from_numpy(cols), beta


def _make_logistic(rng, n=4000, p=4):
    X = rng.normal(size=(n, p))
    beta = np.array([1.5, -2.0, 0.8, 0.0])
    logits = X @ beta - 0.5
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(int)
    cols = {f"x{j}": X[:, j] for j in range(p)}
    cols["y"] = np.array(["no", "yes"], dtype=object)[y]
    return Frame.from_numpy(cols), X, y


def test_glm_ordinal_proportional_odds(cl, rng):
    """family=ordinal recovers latent slopes AND the true cutpoints."""
    n = 3000
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    latent = 1.5 * x1 - 1.0 * x2 + rng.logistic(size=n)
    yi = np.digitize(latent, [-1.5, 0.5, 2.0])
    labels = np.array(["lvl0", "lvl1", "lvl2", "lvl3"], dtype=object)[yi]
    fr = Frame.from_numpy({"x1": x1, "x2": x2, "y": labels})
    m = GLM(response_column="y", family="ordinal").train(fr)
    beta = dict(zip(m.output["coef_names"], m.output["beta_std"]))
    assert beta["x1"] == pytest.approx(1.5, abs=0.25)
    assert beta["x2"] == pytest.approx(-1.0, abs=0.25)
    th = m.output["ordinal_thresholds"]
    assert np.all(np.diff(th) > 0)
    np.testing.assert_allclose(th, [-1.5, 0.5, 2.0], atol=0.3)
    pred = m.predict(fr)
    probs = np.stack([pred.vec(c).to_numpy()
                      for c in ["lvl0", "lvl1", "lvl2", "lvl3"]], axis=1)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-4)
    acc = (pred.vec("predict").decoded() == labels).mean()
    assert acc > 0.45                    # 4 ordered classes, noisy latent
    with pytest.raises(ValueError, match="ordered levels"):
        GLM(response_column="x1", family="ordinal").train(fr)


def test_gaussian_matches_ols(cl, rng):
    fr, beta_true = _make_regression(rng)
    m = GLM(family="gaussian", lambda_=0.0, response_column="y").train(fr)
    coef = m.coef
    for j, b in enumerate(beta_true):
        assert abs(coef[f"x{j}"] - b) < 0.05, (j, coef)
    assert abs(coef["Intercept"] - 2.5) < 0.05
    assert m.training_metrics.r2 > 0.99


def test_binomial_matches_sklearn(cl, rng):
    from sklearn.linear_model import LogisticRegression
    fr, X, y = _make_logistic(rng)
    m = GLM(family="binomial", lambda_=0.0, response_column="y",
            max_iterations=100).train(fr)
    sk = LogisticRegression(penalty=None, max_iter=1000).fit(X, y)
    coef = m.coef
    for j in range(X.shape[1]):
        assert abs(coef[f"x{j}"] - sk.coef_[0, j]) < 0.05, (coef, sk.coef_)
    assert abs(coef["Intercept"] - sk.intercept_[0]) < 0.05
    assert m.training_metrics.auc > 0.85


def test_binomial_auc_against_sklearn(cl, rng):
    from sklearn.metrics import roc_auc_score
    fr, X, y = _make_logistic(rng)
    m = GLM(family="binomial", lambda_=0.0, response_column="y").train(fr)
    preds = m.predict(fr)
    p1 = preds.vec("yes").to_numpy()
    sk_auc = roc_auc_score(y, p1)
    assert abs(m.training_metrics.auc - sk_auc) < 0.01


def test_lasso_sparsifies(cl, rng):
    n, p = 2000, 10
    X = rng.normal(size=(n, p))
    y = 3 * X[:, 0] - 2 * X[:, 1] + 0.05 * rng.normal(size=n)
    cols = {f"x{j}": X[:, j] for j in range(p)}
    cols["y"] = y
    fr = Frame.from_numpy(cols)
    m = GLM(family="gaussian", alpha=1.0, lambda_=0.5,
            response_column="y").train(fr)
    coef = np.array([m.coef[f"x{j}"] for j in range(p)])
    assert np.sum(np.abs(coef) > 1e-6) <= 4          # mostly zeroed
    assert abs(coef[0]) > 1.0 and abs(coef[1]) > 0.5  # signal survives


def test_poisson(cl, rng):
    n = 3000
    x = rng.normal(size=n)
    lam = np.exp(0.7 * x + 1.0)
    y = rng.poisson(lam)
    fr = Frame.from_numpy({"x": x, "y": y.astype(float)})
    m = GLM(family="poisson", lambda_=0.0, response_column="y").train(fr)
    assert abs(m.coef["x"] - 0.7) < 0.05
    assert abs(m.coef["Intercept"] - 1.0) < 0.05


def test_gamma(cl, rng):
    n = 4000
    x = rng.normal(size=n)
    mu = np.exp(0.5 * x + 0.3)
    shape = 5.0
    y = rng.gamma(shape, mu / shape)
    fr = Frame.from_numpy({"x": x, "y": y})
    m = GLM(family="gamma", lambda_=0.0, response_column="y",
            max_iterations=100).train(fr)
    assert abs(m.coef["x"] - 0.5) < 0.1
    assert abs(m.coef["Intercept"] - 0.3) < 0.1


def test_multinomial(cl, rng):
    n = 3000
    centers = np.array([[2, 0], [-2, 1], [0, -2]])
    labels = rng.integers(0, 3, n)
    X = centers[labels] + rng.normal(size=(n, 2))
    fr = Frame.from_numpy({
        "x0": X[:, 0], "x1": X[:, 1],
        "y": np.array(["a", "b", "c"], dtype=object)[labels]})
    m = GLM(family="multinomial", lambda_=0.0, response_column="y").train(fr)
    assert m.training_metrics.accuracy > 0.85
    preds = m.predict(fr)
    assert preds.names == ["predict", "a", "b", "c"]
    probs = np.stack([preds.vec(c).to_numpy() for c in "abc"], axis=1)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)


def test_categorical_features_and_weights(cl, rng):
    n = 2000
    g = np.array(["u", "v", "w"], dtype=object)[rng.integers(0, 3, n)]
    x = rng.normal(size=n)
    eff = {"u": 0.0, "v": 1.0, "w": -1.0}
    y = x + np.array([eff[s] for s in g]) + 0.1 * rng.normal(size=n)
    fr = Frame.from_numpy({"g": g, "x": x, "y": y,
                           "wt": np.ones(n)})
    m = GLM(family="gaussian", lambda_=0.0, response_column="y",
            weights_column="wt").train(fr)
    # v and w effects relative to base level u
    assert abs(m.coef["g.v"] - 1.0) < 0.05
    assert abs(m.coef["g.w"] + 1.0) < 0.05
    assert m.training_metrics.r2 > 0.98


def test_cv_and_validation(cl, rng):
    fr, X, y = _make_logistic(rng, n=2500)
    train, valid = fr.split_frame([0.75], seed=7)
    m = GLM(family="binomial", lambda_=0.0, response_column="y",
            nfolds=3, seed=42).train(train, valid=valid)
    assert m.cross_validation_metrics is not None
    assert m.cross_validation_metrics.auc > 0.8
    assert m.validation_metrics.auc > 0.8
    assert len(m.output["cv_fold_models"]) == 3


def test_predict_save_load(cl, rng, tmp_path):
    fr, X, y = _make_logistic(rng, n=1000)
    m = GLM(family="binomial", lambda_=0.0, response_column="y").train(fr)
    preds = m.predict(fr)
    assert preds.names == ["predict", "no", "yes"]
    assert preds.nrows == fr.nrows
    path = m.save(str(tmp_path / "glm.bin"))
    h2o3_tpu.remove(m.key)
    m2 = h2o3_tpu.Model.load(path) if hasattr(h2o3_tpu, "Model") else None
    from h2o3_tpu.models import Model
    m2 = Model.load(path)
    p2 = m2.predict(fr)
    np.testing.assert_allclose(p2.vec("yes").to_numpy(),
                               preds.vec("yes").to_numpy(), rtol=1e-5)


def test_lambda_search(cl, rng):
    fr, beta_true = _make_regression(rng, n=1500)
    m = GLM(family="gaussian", lambda_search=True, nlambdas=10, alpha=1.0,
            response_column="y").train(fr)
    assert m.training_metrics.r2 > 0.95   # smallest lambda ~ unpenalized


def test_tweedie(cl, rng):
    n = 4000
    x = rng.normal(size=n)
    mu = np.exp(0.4 * x + 0.5)
    # tweedie p=1.5 via compound poisson-gamma simulation
    npois = rng.poisson(mu)
    y = np.array([rng.gamma(s, 1.0) if s > 0 else 0.0 for s in npois])
    fr = Frame.from_numpy({"x": x, "y": y})
    m = GLM(family="tweedie", tweedie_variance_power=1.5, lambda_=0.0,
            response_column="y", max_iterations=100).train(fr)
    assert abs(m.coef["x"] - 0.4) < 0.15


def test_lambda_path_fused_matches_host(cl):
    """The fused device lambda path must land where per-lambda host
    solves land (same warm-started IRLS/COD math, one program)."""
    import numpy as np
    from h2o3_tpu import Frame
    from h2o3_tpu.models import GLM
    rng = np.random.default_rng(8)
    n, d = 2000, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.array([1.5, -1.0, 0.5, 0.0, 0.0, 0.0])
    yy = rng.random(n) < 1 / (1 + np.exp(-(X @ beta - 0.3)))
    cols = {f"x{j}": X[:, j] for j in range(d)}
    cols["y"] = np.where(yy, "1", "0").astype(object)
    fr = Frame.from_numpy(cols)
    m = GLM(response_column="y", family="binomial", lambda_search=True,
            nlambdas=12, alpha=0.5, seed=1).train(fr)
    # solved path: final (smallest-lambda) coefficients recover the truth
    coefs = m.coef
    assert abs(coefs["x0"]) > 0.8 and abs(coefs["x3"]) < 0.25
    assert len(m.scoring_history) == 12
    # per-lambda host solves at the path's own lambdas agree at the end
    m_host = GLM(response_column="y", family="binomial",
                 lambda_=[float(h["lambda"]) for h in m.scoring_history][-1],
                 alpha=0.5, seed=1).train(fr)
    for name in ("x0", "x1", "x2"):
        assert np.isclose(coefs[name], m_host.coef[name], atol=5e-3), name
