"""Hive import, both modes (VERDICT r03 next-step #7; h2o-hive analog).

SQL mode is exercised against sqlite-as-HiveServer (any DB-API works);
direct-metadata mode against a sqlite database carrying the real HMS
backing schema (DBS/TBLS/SDS/COLUMNS_V2/SERDE_PARAMS/PARTITIONS/
PARTITION_KEYS) pointing at real files on disk — the same metadata
DirectHiveMetadata.java reads over thrift.
"""

import sqlite3

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import import_hive_metadata, import_hive_table


@pytest.fixture(scope="module", autouse=True)
def _init():
    h2o3_tpu.init()


def test_sql_mode_with_partition_pruning(tmp_path):
    conn = sqlite3.connect(":memory:")
    conn.execute("CREATE TABLE flights (origin TEXT, dist REAL, year TEXT)")
    conn.executemany("INSERT INTO flights VALUES (?, ?, ?)", [
        ("SFO", 500.0, "2006"), ("JFK", 200.0, "2007"),
        ("LAX", 300.0, "2007")])
    conn.commit()
    fr = import_hive_table(conn, "flights")
    assert fr.nrows == 3
    pruned = import_hive_table(conn, "flights",
                               partitions={"year": "2007"})
    assert pruned.nrows == 2
    assert set(np.asarray(pruned.vec("dist").to_numpy())) == {200.0, 300.0}


def test_sql_mode_rejects_bad_identifier():
    with pytest.raises(ValueError, match="identifier"):
        import_hive_table(None, "flights; DROP TABLE x")


def _metastore(tmp_path, partitioned: bool):
    """Build an HMS-shaped sqlite DB + on-disk storage directories."""
    db = sqlite3.connect(":memory:")
    db.executescript("""
      CREATE TABLE DBS (DB_ID INTEGER, NAME TEXT);
      CREATE TABLE TBLS (TBL_ID INTEGER, DB_ID INTEGER, TBL_NAME TEXT,
                         SD_ID INTEGER);
      CREATE TABLE SDS (SD_ID INTEGER, CD_ID INTEGER, LOCATION TEXT,
                        INPUT_FORMAT TEXT, SERDE_ID INTEGER);
      CREATE TABLE COLUMNS_V2 (CD_ID INTEGER, COLUMN_NAME TEXT,
                               TYPE_NAME TEXT, INTEGER_IDX INTEGER);
      CREATE TABLE SERDE_PARAMS (SERDE_ID INTEGER, PARAM_KEY TEXT,
                                 PARAM_VALUE TEXT);
      CREATE TABLE PARTITIONS (PART_ID INTEGER, TBL_ID INTEGER,
                               SD_ID INTEGER, PART_NAME TEXT);
      CREATE TABLE PARTITION_KEYS (TBL_ID INTEGER, PKEY_NAME TEXT,
                                   PKEY_TYPE TEXT, INTEGER_IDX INTEGER);
    """)
    db.execute("INSERT INTO DBS VALUES (1, 'default')")
    db.execute("INSERT INTO TBLS VALUES (10, 1, 'flights', 100)")
    db.execute("INSERT INTO COLUMNS_V2 VALUES (7, 'origin', 'string', 0)")
    db.execute("INSERT INTO COLUMNS_V2 VALUES (7, 'dist', 'double', 1)")
    fmt = "org.apache.hadoop.mapred.TextInputFormat"
    db.execute("INSERT INTO SERDE_PARAMS VALUES (55, 'field.delim', ',')")
    if not partitioned:
        loc = tmp_path / "warehouse" / "flights"
        loc.mkdir(parents=True)
        (loc / "000000_0").write_text("SFO,500.0\nJFK,200.0\n")
        (loc / "000001_0").write_text("LAX,300.0\n")
        (loc / "_SUCCESS").write_text("")          # marker files skipped
        db.execute("INSERT INTO SDS VALUES (100, 7, ?, ?, 55)",
                   (str(loc), fmt))
    else:
        db.execute("INSERT INTO SDS VALUES (100, 7, 'unused', ?, 55)",
                   (fmt,))
        db.execute("INSERT INTO PARTITION_KEYS VALUES "
                   "(10, 'year', 'string', 0)")
        for i, (year, rows) in enumerate(
                [("2006", "SFO,500.0\n"), ("2007", "JFK,200.0\nLAX,300.0\n")]):
            loc = tmp_path / "warehouse" / "flights" / f"year={year}"
            loc.mkdir(parents=True)
            (loc / "000000_0").write_text(rows)
            db.execute("INSERT INTO SDS VALUES (?, 7, ?, ?, 55)",
                       (200 + i, str(loc), fmt))
            db.execute("INSERT INTO PARTITIONS VALUES (?, 10, ?, ?)",
                       (300 + i, 200 + i, f"year={year}"))
    db.commit()
    return db


def test_direct_metadata_unpartitioned(tmp_path):
    db = _metastore(tmp_path, partitioned=False)
    fr = import_hive_metadata(db, "flights")
    assert fr.names == ["origin", "dist"]
    assert fr.nrows == 3
    assert set(fr.vec("dist").to_numpy()) == {500.0, 200.0, 300.0}


def test_direct_metadata_partitioned_appends_keys(tmp_path):
    db = _metastore(tmp_path, partitioned=True)
    fr = import_hive_metadata(db, "flights")
    assert fr.names == ["origin", "dist", "year"]
    assert fr.nrows == 3
    years = fr.vec("year")
    codes = years.to_numpy()
    labels = [years.domain[int(c)] for c in codes]
    by_year = dict(zip(fr.vec("dist").to_numpy(), labels))
    assert by_year == {500.0: "2006", 200.0: "2007", 300.0: "2007"}


def test_direct_metadata_missing_table(tmp_path):
    db = _metastore(tmp_path, partitioned=False)
    with pytest.raises(KeyError, match="nope"):
        import_hive_metadata(db, "nope")
