"""Avro / xlsx / legacy-xls ingestion (VERDICT r03 next-step #7).

Fixtures are built by independent spec-following writers in this file
(zigzag varints + container framing for Avro, OOXML XML for xlsx, a CFB +
BIFF8 byte builder for xls), so the readers are exercised against the
public formats rather than against themselves.
"""

import json
import struct
import zipfile
import zlib

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import import_file


@pytest.fixture(scope="module", autouse=True)
def _init():
    h2o3_tpu.init()


# -------------------------------------------------------------- avro writer

def _zigzag(n: int) -> bytes:
    u = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _avro_str(s: str) -> bytes:
    b = s.encode()
    return _zigzag(len(b)) + b


def _write_avro(path, codec="null"):
    schema = {
        "type": "record", "name": "flight", "fields": [
            {"name": "distance", "type": "double"},
            {"name": "delay", "type": ["null", "long"]},
            {"name": "carrier", "type": {"type": "enum", "name": "c",
                                         "symbols": ["AA", "UA", "DL"]}},
            {"name": "origin", "type": "string"},
            {"name": "cancelled", "type": "boolean"},
        ]}
    rows = [
        (700.5, 12, 0, "SFO", False),
        (123.0, None, 2, "JFK", True),
        (88.25, -4, 1, "SFO", False),
    ]
    body = bytearray()
    for dist, delay, car, orig, canc in rows:
        body += struct.pack("<d", dist)
        if delay is None:
            body += _zigzag(0)                 # union branch 0 = null
        else:
            body += _zigzag(1) + _zigzag(delay)
        body += _zigzag(car)
        body += _avro_str(orig)
        body += b"\x01" if canc else b"\x00"
    block = bytes(body)
    if codec == "deflate":
        co = zlib.compressobj(wbits=-15)
        block = co.compress(block) + co.flush()
    sync = bytes(range(16))
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    out = bytearray(b"Obj\x01")
    out += _zigzag(len(meta))
    for k, v in meta.items():
        out += _avro_str(k) + _zigzag(len(v)) + v
    out += _zigzag(0)                          # end of metadata map
    out += sync
    out += _zigzag(len(rows)) + _zigzag(len(block)) + block + sync
    path.write_bytes(bytes(out))
    return rows


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_avro_import(tmp_path, codec):
    p = tmp_path / "flights.avro"
    rows = _write_avro(p, codec=codec)
    fr = import_file(str(p))
    assert fr.names == ["distance", "delay", "carrier", "origin",
                        "cancelled"]
    assert fr.nrows == 3
    assert fr.types()["carrier"] == "cat"
    dist = fr.vec("distance").to_numpy()
    np.testing.assert_allclose(dist, [r[0] for r in rows])
    delay = fr.vec("delay").to_numpy()
    assert delay[0] == 12 and np.isnan(delay[1]) and delay[2] == -4
    assert fr.vec("carrier").domain == ["AA", "UA", "DL"]
    canc = fr.vec("cancelled").to_numpy()
    np.testing.assert_allclose(canc, [0.0, 1.0, 0.0])


def test_avro_rejects_non_avro(tmp_path):
    p = tmp_path / "bad.avro"
    p.write_bytes(b"definitely,not,avro\n1,2,3\n")
    with pytest.raises(ValueError, match="magic"):
        import_file(str(p))


# -------------------------------------------------------------- xlsx writer

def _write_xlsx(path):
    shared = ["name", "score", "grade", "alice", "bob", "carol", "A", "B"]
    sheet_rows = [
        [("s", 0), ("s", 1), ("s", 2)],
        [("s", 3), ("n", 91.5), ("s", 6)],
        [("s", 4), ("n", 78.0), ("s", 7)],
        [("s", 5), ("n", 85.25), ("s", 6)],
    ]
    sst = ("<sst xmlns='http://schemas.openxmlformats.org/spreadsheetml/"
           "2006/main'>" + "".join(f"<si><t>{s}</t></si>" for s in shared)
           + "</sst>")
    rows_xml = []
    for i, row in enumerate(sheet_rows, start=1):
        cells = []
        for j, (t, v) in enumerate(row):
            ref = f"{chr(65 + j)}{i}"
            if t == "s":
                cells.append(f"<c r='{ref}' t='s'><v>{v}</v></c>")
            else:
                cells.append(f"<c r='{ref}'><v>{v}</v></c>")
        rows_xml.append(f"<row r='{i}'>{''.join(cells)}</row>")
    ws = ("<worksheet xmlns='http://schemas.openxmlformats.org/"
          "spreadsheetml/2006/main'><sheetData>" + "".join(rows_xml)
          + "</sheetData></worksheet>")
    wb = ("<workbook xmlns='http://schemas.openxmlformats.org/"
          "spreadsheetml/2006/main' xmlns:r='http://schemas."
          "openxmlformats.org/officeDocument/2006/relationships'>"
          "<sheets><sheet name='S1' sheetId='1' r:id='rId1'/></sheets>"
          "</workbook>")
    rels = ("<Relationships xmlns='http://schemas.openxmlformats.org/"
            "package/2006/relationships'>"
            "<Relationship Id='rId1' Type='x' Target='worksheets/"
            "sheet1.xml'/></Relationships>")
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("xl/workbook.xml", wb)
        zf.writestr("xl/_rels/workbook.xml.rels", rels)
        zf.writestr("xl/sharedStrings.xml", sst)
        zf.writestr("xl/worksheets/sheet1.xml", ws)


def test_xlsx_import(tmp_path):
    p = tmp_path / "grades.xlsx"
    _write_xlsx(p)
    fr = import_file(str(p))
    assert fr.names == ["name", "score", "grade"]
    assert fr.nrows == 3
    np.testing.assert_allclose(fr.vec("score").to_numpy(),
                               [91.5, 78.0, 85.25])
    assert sorted(fr.vec("grade").domain) == ["A", "B"]


# --------------------------------------------------- legacy xls (CFB+BIFF8)

def _biff_rec(opcode, payload=b""):
    return struct.pack("<HH", opcode, len(payload)) + payload


def _build_biff_stream():
    out = bytearray()
    out += _biff_rec(0x0809, struct.pack("<HH", 0x0600, 0x0005)
                     + b"\x00" * 12)                    # BOF globals
    strings = ["x", "y", "label", "yes", "no"]
    sst = struct.pack("<II", len(strings), len(strings))
    for s in strings:
        sst += struct.pack("<HB", len(s), 0) + s.encode("latin-1")
    out += _biff_rec(0x00FC, sst)                       # SST
    out += _biff_rec(0x000A)                            # EOF globals
    out += _biff_rec(0x0809, struct.pack("<HH", 0x0600, 0x0010)
                     + b"\x00" * 12)                    # BOF sheet 1
    # header row: LABELSST "x", "y", "label"
    for col, idx in ((0, 0), (1, 1), (2, 2)):
        out += _biff_rec(0x00FD, struct.pack("<HHHI", 0, col, 0, idx))
    # row 1: MULRK cols 0-1 (7 int-coded; 2.5 = 250/100) | LABELSST "yes"
    out += _biff_rec(0x00BD, struct.pack("<HH", 1, 0)
                     + struct.pack("<HI", 0, (7 << 2) | 2)
                     + struct.pack("<HI", 0, (250 << 2) | 3)
                     + struct.pack("<H", 1))
    out += _biff_rec(0x00FD, struct.pack("<HHHI", 1, 2, 0, 3))
    # row 2: NUMBER 3.5 | RK 1025 | LABELSST "no"
    out += _biff_rec(0x0203, struct.pack("<HHH", 2, 0, 0)
                     + struct.pack("<d", 3.5))
    out += _biff_rec(0x027E, struct.pack("<HHH", 2, 1, 0)
                     + struct.pack("<I", (1025 << 2) | 2))
    out += _biff_rec(0x00FD, struct.pack("<HHHI", 2, 2, 0, 4))
    # row 3: NUMBER 1.0 | BOOLERR true | LABELSST "yes"
    out += _biff_rec(0x0203, struct.pack("<HHH", 3, 0, 0)
                     + struct.pack("<d", 1.0))
    out += _biff_rec(0x0205, struct.pack("<HHH", 3, 1, 0) + b"\x01\x00")
    out += _biff_rec(0x00FD, struct.pack("<HHHI", 3, 2, 0, 3))
    out += _biff_rec(0x000A)                            # EOF sheet
    return bytes(out)


def _build_xls(path, stream: bytes):
    """Minimal CFB v3 container: FAT sector + dir sector + stream sectors.
    The stream is padded past the 4096-byte mini-stream cutoff so it lives
    in regular sectors."""
    while len(stream) < 4096:
        stream += _biff_rec(0x005C, b"\x00" * 16)       # WRITEACCESS filler
    ssz = 512
    n_stream_sectors = -(-len(stream) // ssz)
    # sector map: 0 = FAT, 1 = directory, 2.. = workbook stream
    fat = [0xFFFFFFFD, 0xFFFFFFFE]
    for i in range(n_stream_sectors):
        fat.append(2 + i + 1 if i + 1 < n_stream_sectors else 0xFFFFFFFE)
    fat += [0xFFFFFFFF] * (ssz // 4 - len(fat))
    fat_sector = struct.pack(f"<{ssz // 4}I", *fat)

    def dir_entry(name, obj_type, start, size, child=0xFFFFFFFF):
        raw = name.encode("utf-16-le") + b"\x00\x00"
        e = raw + b"\x00" * (64 - len(raw))
        e += struct.pack("<H", len(raw))                # name length
        e += bytes([obj_type, 1])                       # type, black
        e += struct.pack("<III", 0xFFFFFFFF, 0xFFFFFFFF, child)
        e += b"\x00" * 36                               # clsid+state+times
        e += struct.pack("<IQ", start, size)
        assert len(e) == 128, len(e)
        return e

    directory = (dir_entry("Root Entry", 5, 0xFFFFFFFE, 0, child=1)
                 + dir_entry("Workbook", 2, 2, len(stream))
                 + b"\x00" * 256)
    header = bytearray(512)
    header[0:8] = b"\xd0\xcf\x11\xe0\xa1\xb1\x1a\xe1"
    struct.pack_into("<H", header, 24, 0x003E)          # minor
    struct.pack_into("<H", header, 26, 3)               # major v3
    struct.pack_into("<H", header, 28, 0xFFFE)          # little endian
    struct.pack_into("<H", header, 30, 9)               # 512-byte sectors
    struct.pack_into("<H", header, 32, 6)               # 64-byte mini
    struct.pack_into("<I", header, 44, 1)               # one FAT sector
    struct.pack_into("<I", header, 48, 1)               # dir start
    struct.pack_into("<I", header, 56, 4096)            # mini cutoff
    struct.pack_into("<I", header, 60, 0xFFFFFFFE)      # no miniFAT
    struct.pack_into("<I", header, 68, 0xFFFFFFFE)      # no DIFAT chain
    difat = [0] + [0xFFFFFFFF] * 108
    struct.pack_into("<109I", header, 76, *difat)
    body = fat_sector + directory
    body += stream + b"\x00" * (n_stream_sectors * ssz - len(stream))
    path.write_bytes(bytes(header) + body)


def test_legacy_xls_import(tmp_path):
    p = tmp_path / "legacy.xls"
    _build_xls(p, _build_biff_stream())
    fr = import_file(str(p))
    assert fr.names == ["x", "y", "label"]
    assert fr.nrows == 3
    np.testing.assert_allclose(fr.vec("x").to_numpy(), [7.0, 3.5, 1.0])
    np.testing.assert_allclose(fr.vec("y").to_numpy(), [2.5, 1025.0, 1.0])
    assert sorted(fr.vec("label").domain) == ["no", "yes"]


def test_xls_sst_continue_records(tmp_path):
    """SST split across CONTINUE records, with one string straddling the
    boundary (fresh option-flags byte re-emitted — [MS-XLS] 2.5.293)."""
    out = bytearray()
    out += _biff_rec(0x0809, struct.pack("<HH", 0x0600, 0x0005)
                     + b"\x00" * 12)
    # 4 strings; "straddled" splits after "strad"
    s0, s1, s2, s3 = "alpha", "beta", "straddled", "gamma"
    sst = struct.pack("<II", 4, 4)
    for s in (s0, s1):
        sst += struct.pack("<HB", len(s), 0) + s.encode()
    sst += struct.pack("<HB", len(s2), 0) + b"strad"
    cont = b"\x00" + b"dled"                    # flag byte + remainder
    cont += struct.pack("<HB", len(s3), 0) + s3.encode()
    out += _biff_rec(0x00FC, sst)
    out += _biff_rec(0x003C, cont)              # CONTINUE
    out += _biff_rec(0x000A)
    out += _biff_rec(0x0809, struct.pack("<HH", 0x0600, 0x0010)
                     + b"\x00" * 12)
    for col, idx in ((0, 0), (1, 1)):           # header: alpha, beta
        out += _biff_rec(0x00FD, struct.pack("<HHHI", 0, col, 0, idx))
    out += _biff_rec(0x00FD, struct.pack("<HHHI", 1, 0, 0, 2))
    out += _biff_rec(0x00FD, struct.pack("<HHHI", 1, 1, 0, 3))
    out += _biff_rec(0x000A)
    p = tmp_path / "cont.xls"
    _build_xls(p, bytes(out))
    fr = import_file(str(p))
    assert fr.names == ["alpha", "beta"]
    cells = [fr.vec("alpha").domain[int(fr.vec("alpha").to_numpy()[0])],
             fr.vec("beta").domain[int(fr.vec("beta").to_numpy()[0])]]
    assert cells == ["straddled", "gamma"]


def test_xls_rejects_non_cfb(tmp_path):
    p = tmp_path / "fake.xls"
    p.write_bytes(b"not a compound file")
    with pytest.raises(ValueError, match="CFB"):
        import_file(str(p))
