"""POJO codegen (TreeJCodeGen analog): the C twin of the generated trees
is gcc-compiled and must score bit-identically to the in-framework
scorer; the Java rendering is checked structurally (no javac in image)."""

import ctypes
import subprocess

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.export.pojo import export_pojo, export_pojo_c
from h2o3_tpu.frame.vec import T_CAT


@pytest.fixture(scope="module", autouse=True)
def _init():
    h2o3_tpu.init()


def _frame(n=300, seed=0):
    rng = np.random.default_rng(seed)
    cols = {
        "a": rng.normal(size=n).astype(np.float32),
        "b": np.round(rng.random(n) * 10, 2).astype(np.float32),
        "c": rng.choice(["u", "v", "w"], n).astype(object),
        "y": np.where(rng.random(n) < 0.45, "yes", "no").astype(object),
        "t": (rng.normal(size=n) * 3).astype(np.float32),
    }
    return Frame.from_numpy(cols, types={"c": T_CAT, "y": T_CAT})


def _compile_and_score(c_path, tmp_path, X, preds_len):
    # one .so per source: dlopen caches by path, so a shared name would
    # silently return the previously loaded scorer
    so = str(c_path) + ".so"
    subprocess.run(["gcc", "-O2", "-shared", "-fPIC", "-o", so, c_path,
                    "-lm"], check=True, capture_output=True)
    lib = ctypes.CDLL(so)
    lib.score0.restype = ctypes.POINTER(ctypes.c_double)
    lib.score0.argtypes = [ctypes.POINTER(ctypes.c_double),
                           ctypes.POINTER(ctypes.c_double)]
    out = np.zeros((X.shape[0], preds_len))
    for r in range(X.shape[0]):
        row = (ctypes.c_double * X.shape[1])(*X[r])
        preds = (ctypes.c_double * preds_len)()
        lib.score0(row, preds)
        out[r] = list(preds)
    return out


def _design(model, fr):
    return np.asarray(model._design(fr))[: fr.nrows].astype(np.float64)


def test_gbm_binomial_c_twin_matches(tmp_path):
    from h2o3_tpu.models import GBM
    fr = _frame()
    m = GBM(response_column="y", ntrees=7, max_depth=4, seed=3).train(fr)
    cpath = export_pojo_c(m, str(tmp_path / "gbm.c"))
    got = _compile_and_score(cpath, tmp_path, _design(m, fr), 3)
    native = m.predict(fr).to_numpy()[:, 2].astype(np.float64)
    np.testing.assert_allclose(got[:, 2], native, rtol=0, atol=1e-7)
    # preds[0] is the thresholded label
    assert set(got[:, 0]) <= {0.0, 1.0}


def test_gbm_regression_and_multinomial_c_twin(tmp_path):
    from h2o3_tpu.models import GBM
    fr = _frame()
    mr = GBM(response_column="t", ntrees=5, max_depth=4, seed=1).train(fr)
    cpath = export_pojo_c(mr, str(tmp_path / "reg.c"))
    got = _compile_and_score(cpath, tmp_path, _design(mr, fr), 1)
    native = mr.predict(fr).to_numpy()[:, 0].astype(np.float64)
    np.testing.assert_allclose(got[:, 0], native, rtol=0, atol=1e-5)

    mm = GBM(response_column="c", ntrees=4, max_depth=3, seed=2).train(fr)
    cpath = export_pojo_c(mm, str(tmp_path / "multi.c"))
    got = _compile_and_score(cpath, tmp_path, _design(mm, fr), 4)
    native = mm.predict(fr).to_numpy()[:, 1:4].astype(np.float64)
    np.testing.assert_allclose(got[:, 1:4], native, rtol=0, atol=1e-6)


def test_drf_c_twin_matches(tmp_path):
    from h2o3_tpu.models import DRF
    fr = _frame()
    m = DRF(response_column="y", ntrees=9, max_depth=4, seed=5).train(fr)
    cpath = export_pojo_c(m, str(tmp_path / "drf.c"))
    got = _compile_and_score(cpath, tmp_path, _design(m, fr), 3)
    native = m.predict(fr).to_numpy()[:, 2].astype(np.float64)
    np.testing.assert_allclose(got[:, 2], native, rtol=0, atol=1e-7)


def test_java_pojo_structure(tmp_path):
    from h2o3_tpu.models import GBM
    fr = _frame()
    m = GBM(response_column="y", ntrees=3, max_depth=3, seed=7).train(fr)
    jpath = export_pojo(m, str(tmp_path / "Model.java"), class_name="MyGbm")
    src = open(jpath).read()
    assert src.count("{") == src.count("}")
    for token in ("public class MyGbm", "String[] NAMES",
                  "String[][] DOMAINS", "double[] score0",
                  "Double.isNaN", "static double tree_0_0",
                  "static double tree_0_2"):
        assert token in src, token
    # every feature index referenced is in range
    import re
    idxs = {int(x) for x in re.findall(r"data\[(\d+)\]", src)}
    assert max(idxs) < len(m.datainfo.specs)


def test_glm_pojo_c_binomial(cl, tmp_path):
    """GLM POJO (generic Model.toJava analog): gcc-compiled C twin scores
    bit-identically to the in-framework GLM on mixed num/cat rows."""
    import numpy as np
    from h2o3_tpu import Frame
    from h2o3_tpu.frame.vec import T_CAT
    from h2o3_tpu.models import GLM
    from h2o3_tpu.export.pojo import export_pojo, export_pojo_c
    rng = np.random.default_rng(11)
    n = 300
    cols = {
        "x0": rng.normal(size=n).astype(np.float32),
        "x1": rng.normal(size=n).astype(np.float32),
        "c0": rng.choice(["a", "b", "c"], n).astype(object),
    }
    logit = 1.2 * cols["x0"] - 0.7 * cols["x1"] + (cols["c0"] == "b")
    cols["y"] = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)),
                         "Y", "N").astype(object)
    fr = Frame.from_numpy(cols, types={"c0": T_CAT, "y": T_CAT})
    m = GLM(response_column="y", family="binomial", seed=2).train(fr)
    # data rows in POJO convention: cats as domain codes
    dom = {lbl: i for i, lbl in enumerate(fr.vec("c0").domain)}
    X = np.column_stack([
        np.asarray(cols["x0"], np.float64),
        np.asarray(cols["x1"], np.float64),
        np.asarray([dom[v] for v in cols["c0"]], np.float64)])
    X[5, 0] = np.nan                      # missing numeric
    X[6, 2] = np.nan                      # missing categorical
    fr2 = Frame.from_numpy({
        "x0": X[:, 0].astype(np.float32),
        "x1": X[:, 1].astype(np.float32),
        "c0": np.asarray([None if np.isnan(c) else
                          fr.vec("c0").domain[int(c)] for c in X[:, 2]],
                         object)}, types={"c0": T_CAT})
    cpath = str(tmp_path / "glm_pojo.c")
    export_pojo_c(m, cpath)
    got = _compile_and_score(cpath, tmp_path, X, 3)
    ours = m.predict(fr2).to_numpy()[:, 2].astype(np.float64)
    np.testing.assert_allclose(got[:, 2], ours, rtol=0, atol=1e-6)
    jpath = str(tmp_path / "GlmPojo.java")
    export_pojo(m, jpath, "GlmPojo")
    src = open(jpath).read()
    assert "class GlmPojo" in src and "score0" in src
