"""POJO codegen (TreeJCodeGen analog): the C twin of the generated trees
is gcc-compiled and must score bit-identically to the in-framework
scorer; the Java rendering is checked structurally (no javac in image)."""

import ctypes
import subprocess

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.export.pojo import export_pojo, export_pojo_c
from h2o3_tpu.frame.vec import T_CAT


@pytest.fixture(scope="module", autouse=True)
def _init():
    h2o3_tpu.init()


def _frame(n=300, seed=0):
    rng = np.random.default_rng(seed)
    cols = {
        "a": rng.normal(size=n).astype(np.float32),
        "b": np.round(rng.random(n) * 10, 2).astype(np.float32),
        "c": rng.choice(["u", "v", "w"], n).astype(object),
        "y": np.where(rng.random(n) < 0.45, "yes", "no").astype(object),
        "t": (rng.normal(size=n) * 3).astype(np.float32),
    }
    return Frame.from_numpy(cols, types={"c": T_CAT, "y": T_CAT})


def _compile_and_score(c_path, tmp_path, X, preds_len):
    # one .so per source: dlopen caches by path, so a shared name would
    # silently return the previously loaded scorer
    so = str(c_path) + ".so"
    subprocess.run(["gcc", "-O2", "-shared", "-fPIC", "-o", so, c_path,
                    "-lm"], check=True, capture_output=True)
    lib = ctypes.CDLL(so)
    lib.score0.restype = ctypes.POINTER(ctypes.c_double)
    lib.score0.argtypes = [ctypes.POINTER(ctypes.c_double),
                           ctypes.POINTER(ctypes.c_double)]
    out = np.zeros((X.shape[0], preds_len))
    for r in range(X.shape[0]):
        row = (ctypes.c_double * X.shape[1])(*X[r])
        preds = (ctypes.c_double * preds_len)()
        lib.score0(row, preds)
        out[r] = list(preds)
    return out


def _design(model, fr):
    return np.asarray(model._design(fr))[: fr.nrows].astype(np.float64)


def test_gbm_binomial_c_twin_matches(tmp_path):
    from h2o3_tpu.models import GBM
    fr = _frame()
    m = GBM(response_column="y", ntrees=7, max_depth=4, seed=3).train(fr)
    cpath = export_pojo_c(m, str(tmp_path / "gbm.c"))
    got = _compile_and_score(cpath, tmp_path, _design(m, fr), 3)
    native = m.predict(fr).to_numpy()[:, 2].astype(np.float64)
    np.testing.assert_allclose(got[:, 2], native, rtol=0, atol=1e-7)
    # preds[0] is the thresholded label
    assert set(got[:, 0]) <= {0.0, 1.0}


def test_gbm_regression_and_multinomial_c_twin(tmp_path):
    from h2o3_tpu.models import GBM
    fr = _frame()
    mr = GBM(response_column="t", ntrees=5, max_depth=4, seed=1).train(fr)
    cpath = export_pojo_c(mr, str(tmp_path / "reg.c"))
    got = _compile_and_score(cpath, tmp_path, _design(mr, fr), 1)
    native = mr.predict(fr).to_numpy()[:, 0].astype(np.float64)
    np.testing.assert_allclose(got[:, 0], native, rtol=0, atol=1e-5)

    mm = GBM(response_column="c", ntrees=4, max_depth=3, seed=2).train(fr)
    cpath = export_pojo_c(mm, str(tmp_path / "multi.c"))
    got = _compile_and_score(cpath, tmp_path, _design(mm, fr), 4)
    native = mm.predict(fr).to_numpy()[:, 1:4].astype(np.float64)
    np.testing.assert_allclose(got[:, 1:4], native, rtol=0, atol=1e-6)


def test_drf_c_twin_matches(tmp_path):
    from h2o3_tpu.models import DRF
    fr = _frame()
    m = DRF(response_column="y", ntrees=9, max_depth=4, seed=5).train(fr)
    cpath = export_pojo_c(m, str(tmp_path / "drf.c"))
    got = _compile_and_score(cpath, tmp_path, _design(m, fr), 3)
    native = m.predict(fr).to_numpy()[:, 2].astype(np.float64)
    np.testing.assert_allclose(got[:, 2], native, rtol=0, atol=1e-7)


def test_java_pojo_structure(tmp_path):
    from h2o3_tpu.models import GBM
    fr = _frame()
    m = GBM(response_column="y", ntrees=3, max_depth=3, seed=7).train(fr)
    jpath = export_pojo(m, str(tmp_path / "Model.java"), class_name="MyGbm")
    src = open(jpath).read()
    assert src.count("{") == src.count("}")
    for token in ("public class MyGbm", "String[] NAMES",
                  "String[][] DOMAINS", "double[] score0",
                  "Double.isNaN", "static double tree_0_0",
                  "static double tree_0_2"):
        assert token in src, token
    # every feature index referenced is in range
    import re
    idxs = {int(x) for x in re.findall(r"data\[(\d+)\]", src)}
    assert max(idxs) < len(m.datainfo.specs)
