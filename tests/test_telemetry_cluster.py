"""Cross-process telemetry acceptance: a worker process attaches to this
process's DKV coordinator, heartbeats, and trains a tiny GBM; the
coordinator's merged view must then show (a) the worker's shipped metric
series next to the coordinator's own under per-node labels in one
Prometheus exposition, and (b) one stitched trace — the worker's job
span, its tree spans, and the coordinator-side ``dkv_handle`` spans all
sharing a trace_id across the RPC boundary."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from h2o3_tpu.runtime import dkv, heartbeat
from h2o3_tpu.runtime import observability as obs

_WORKER = textwrap.dedent("""
    import json
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    h2o3_tpu.init()
    from h2o3_tpu import Frame
    from h2o3_tpu.models import GBM
    from h2o3_tpu.runtime import dkv, heartbeat
    from h2o3_tpu.runtime import observability as obs

    dkv.attach("127.0.0.1", int(sys.argv[1]))
    heartbeat.start(0.3)
    rng = np.random.default_rng(3)
    X = rng.random((400, 4))
    y = 3.0 * X[:, 0] + np.sin(4 * X[:, 1]) + 0.1 * rng.normal(size=400)
    fr = Frame.from_numpy({"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2],
                           "x3": X[:, 3], "y": y})
    m = GBM(response_column="y", ntrees=3, max_depth=3, seed=7).train(fr)
    assert heartbeat.reship()    # stamp now carries the post-train registry
    job_evs = [e for e in obs.timeline_events(2000)
               if e["kind"] == "job" and e.get("trace_id")]
    print("WORKER_DONE", json.dumps({
        "trace_id": job_evs[-1]["trace_id"],
        "node": heartbeat.node_name(),
        "ntrees": m.output["ntrees_trained"]}))
    # join the beat thread but LEAVE the stamp behind — the coordinator-
    # side merge assertions read it after this process exits
    heartbeat.stop(remove=False)
""")


def test_worker_metrics_and_trace_stitch_across_processes(tmp_path):
    obs.set_enabled(True)
    port = dkv.serve("127.0.0.1", 0)
    worker_node = None
    try:
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "H2O3_TPU_RECOVERY_DIR": str(tmp_path),
            "H2O3_TPU_SNAPSHOT_INTERVAL": "0",
        })
        proc = subprocess.run(
            [sys.executable, "-c", _WORKER, str(port)],
            env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
            capture_output=True, text=True, timeout=420)
        assert proc.returncode == 0, (
            f"worker rc={proc.returncode}\nstdout:\n{proc.stdout[-3000:]}"
            f"\nstderr:\n{proc.stderr[-3000:]}")
        info = json.loads(proc.stdout.split("WORKER_DONE", 1)[1])
        worker_node, trace_id = info["node"], info["trace_id"]
        assert info["ntrees"] == 3

        # -- the worker's stamp landed here with metrics + an event tail
        stamps = obs.cluster_stamps()
        assert worker_node in stamps
        stamp = stamps[worker_node]
        assert stamp.get("metrics"), "worker shipped no metric snapshot"
        shipped_names = {s["n"] for s in stamp["metrics"]}
        assert "dkv_rpc_seconds" in shipped_names
        assert "tree_phase_seconds" in shipped_names
        # the compile ledger rides the same snapshot: the worker's train
        # compiled at least the tree-scan program, so its compile series
        # and cost gauges land on the coordinator without extra plumbing
        assert "compile_seconds" in shipped_names
        assert "recompiles_total" in shipped_names

        # -- one scrape covers both processes, split by the node label
        text = obs.render_prometheus(cluster=True)
        me = obs.node_name()
        worker_lines = [ln for ln in text.splitlines()
                        if f'node="{worker_node}"' in ln]
        assert any(ln.startswith("dkv_rpc_seconds_bucket")
                   for ln in worker_lines)
        assert any(ln.startswith("tree_phase_seconds_bucket")
                   for ln in worker_lines)
        assert any(ln.startswith("compile_seconds_bucket")
                   for ln in worker_lines)
        assert any(ln.startswith("recompiles_total{")
                   and 'reason="first"' in ln for ln in worker_lines)
        # the coordinator side of the same RPCs, under its own label
        assert any(ln.startswith("dkv_handle_seconds_bucket")
                   and f'node="{me}"' in ln for ln in text.splitlines())

        # -- trace stitching: worker job/tree spans and coordinator
        #    dkv_handle spans form ONE tree, keyed by the job's trace_id
        events = obs.timeline_events(2000) + list(stamp.get("events") or [])
        forest = obs.trace_forest(events)
        target = [t for t in forest if t["trace_id"] == trace_id]
        assert target, f"job trace {trace_id} not stitched"

        def kinds(spans):
            out = set()
            for s in spans:
                out.add(s["kind"])
                out |= kinds(s["children"])
            return out

        got = kinds(target[0]["spans"])
        assert "job" in got                      # worker root span
        assert "tree_chunk" in got               # worker tree work
        assert "dkv_handle" in got, (            # coordinator, via envelope
            f"no coordinator-side span joined the trace: {sorted(got)}")
    finally:
        if worker_node:
            try:
                dkv.remove(heartbeat.PREFIX + worker_node)
            except Exception:            # noqa: BLE001
                pass
        dkv.detach()
