"""EFB (exclusive feature bundling) — the wide/sparse tree path.

Reference behavior matched: sparse wide frames train correctly and fast
(water/fvec NewChunk CX codecs + hex/tree/xgboost SparseMatrixFactory);
here the mechanism is LightGBM-style bundling (efb.py) and the tests pin
(a) the planner's exclusivity/packing invariants, (b) end-to-end model
equivalence vs the un-bundled pipeline, (c) the ranged partition rule.
"""

import numpy as np
import pytest

from h2o3_tpu import Frame


def _onehot_frame(rng, n=3000, groups=6, levels=12, noise_cols=2):
    """Wide sparse frame: ``groups`` one-hot-expanded categoricals (columns
    within a group are perfectly mutually exclusive) + dense numerics."""
    cols = {}
    gidx = []
    for g in range(groups):
        z = rng.integers(0, levels, n)
        gidx.append(z)
        for l in range(levels):
            cols[f"g{g}_l{l}"] = (z == l).astype(np.float64)
    for j in range(noise_cols):
        cols[f"num{j}"] = rng.normal(size=n)
    y = (gidx[0] % 3 == 0).astype(np.float64) * 2.0 \
        + 0.5 * (gidx[1] % 2) + cols["num0"] * 0.3 \
        + 0.05 * rng.normal(size=n)
    cols["y"] = y
    return Frame.from_numpy(cols)


def test_plan_bundles_packs_exclusive_features(cl, rng):
    from h2o3_tpu.models.tree.binning import fit_bins
    from h2o3_tpu.models.tree.efb import plan_bundles

    fr = _onehot_frame(rng)
    feats = [n for n in fr.names if n != "y"]
    binned = fit_bins(fr, feats, nbins=64)
    plan = plan_bundles(binned.codes, binned.bin_counts, binned.nbins,
                        fr.nrows)
    assert plan is not None
    n_bundles = sum(1 for w in plan.working if w[0] == "bundle")
    assert n_bundles >= 1
    # 72 sparse one-hots collapse into far fewer working features
    assert plan.n_working < len(feats) // 2
    # members inside one bundle never overlap slots
    for w in plan.working:
        if w[0] != "bundle":
            continue
        spans = sorted((m[1], m[1] + m[2] - 1) for m in w[1])
        for (a1, b1), (a2, b2) in zip(spans, spans[1:]):
            assert b1 <= a2, "overlapping member slots"
        assert spans[0][0] >= 1          # slot 0 is the shared default bin
        for _, _, bf, df in w[1]:
            assert 0 <= df < bf          # default bin inside the range


def test_plan_declines_dense_frames(cl, rng):
    from h2o3_tpu.models.tree.binning import fit_bins
    from h2o3_tpu.models.tree.efb import plan_bundles

    X = rng.normal(size=(2000, 40))
    fr = Frame.from_numpy({f"x{j}": X[:, j] for j in range(40)})
    binned = fit_bins(fr, list(fr.names), nbins=64)
    assert plan_bundles(binned.codes, binned.bin_counts, binned.nbins,
                        fr.nrows) is None


def test_apply_bundles_roundtrip(cl, rng):
    from h2o3_tpu.models.tree.binning import fit_bins
    from h2o3_tpu.models.tree.efb import plan_bundles, apply_bundles

    fr = _onehot_frame(rng, n=1500)
    feats = [n for n in fr.names if n != "y"]
    binned = fit_bins(fr, feats, nbins=64)
    plan = plan_bundles(binned.codes, binned.bin_counts, binned.nbins,
                        fr.nrows)
    wcodes = np.asarray(apply_bundles(binned.codes, plan))[:, : fr.nrows]
    codes = np.asarray(binned.codes)[:, : fr.nrows]
    assert wcodes.shape[0] == plan.n_working
    for wi, w in enumerate(plan.working):
        if w[0] == "raw":
            np.testing.assert_array_equal(wcodes[wi], codes[w[1]])
        else:
            # decode: each row's working code identifies the (single)
            # non-default member and its original bin
            for f, start, bf, df in w[1]:
                nz = codes[f] != df
                c = codes[f][nz]
                np.testing.assert_array_equal(
                    wcodes[wi][nz], start + c - (c > df))
            alldef = np.ones(fr.nrows, bool)
            for f, _, _, df in w[1]:
                alldef &= codes[f] == df
            np.testing.assert_array_equal(wcodes[wi][alldef], 0)


def test_gbm_efb_matches_unbundled(cl, rng):
    """Same data, EFB on vs off: near-identical fits (identical candidate
    gains; only argmax tie-breaks may differ)."""
    from h2o3_tpu.models import GBM

    fr = _onehot_frame(rng)
    kw = dict(response_column="y", ntrees=10, max_depth=4, nbins=64,
              seed=3, score_tree_interval=10)
    m_on = GBM(efb="auto", **kw).train(fr)
    assert m_on.output.get("efb_bundles", 0) >= 1
    m_off = GBM(efb="off", **kw).train(fr)
    p_on = m_on.predict(fr).vec("predict").to_numpy()
    p_off = m_off.predict(fr).vec("predict").to_numpy()
    y = fr.vec("y").to_numpy()
    mse_on = float(np.mean((p_on - y) ** 2))
    assert mse_on < 0.5 * float(np.var(y))    # genuinely fits the signal
    # the bundled search is EXACT: identical candidate gains, identical
    # trees — predictions match the un-bundled pipeline to float precision
    assert np.abs(p_on - p_off).max() < 1e-4
    # recorded trees reference ORIGINAL features (prediction space)
    t0 = m_on.output["trees"][0]
    nfeat = len([n for n in fr.names if n != "y"])
    for lvl in t0.feat:
        assert (np.asarray(lvl) < nfeat).all()


def test_drf_efb_trains(cl, rng):
    from h2o3_tpu.models import DRF

    fr = _onehot_frame(rng, n=2000)
    m = DRF(response_column="y", ntrees=15, max_depth=5, nbins=64,
            seed=3).train(fr)
    pred = m.predict(fr).vec("predict").to_numpy()
    y = fr.vec("y").to_numpy()
    assert np.mean((pred - y) ** 2) < np.var(y) * 0.6


def test_partition_ranged_prefix_equivalence(cl, rng):
    """hi = nbins degenerates partition_ranged to the prefix rule."""
    import jax.numpy as jnp
    from h2o3_tpu.models.tree.hist import partition, partition_ranged

    nbins = 16
    N, L = 512, 4
    codes = jnp.asarray(rng.integers(0, nbins + 1, size=(3, N)), jnp.int32)
    leaf = jnp.asarray(rng.integers(0, L, N), jnp.int32)
    feat = jnp.asarray(rng.integers(0, 3, L), jnp.int32)
    bin_ = jnp.asarray(rng.integers(0, nbins - 1, L), jnp.int32)
    na_left = jnp.asarray(rng.integers(0, 2, L).astype(bool))
    valid = jnp.ones(L, bool)
    a = partition(codes, leaf, feat, bin_, na_left, valid, jnp.int32(nbins))
    b = partition_ranged(codes, leaf, feat, bin_,
                         jnp.full((L,), nbins, jnp.int32),
                         jnp.zeros(L, bool), na_left, valid,
                         jnp.int32(nbins))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
