"""Flow workbench endpoint-sequence test (VERDICT r03 weak #7).

No browser/JSDOM exists in this image, so this replays — verbatim — the
request sequence, bodies, and response-field dereferences the Flow JS
performs (api/flow.py: doImport, refresh, fillParams, doTrain,
doPredict, doPD, doSplit, doDelete, doRapids).  Every assertion mirrors
a property access in the JS (e.g. ``out.destination_frame.name``,
``out.model.model_id.name``, ``f.columns[].label``), so a server-side
schema change that would break the UI breaks this test.
"""

import json
import urllib.request

import numpy as np
import pytest

import h2o3_tpu


@pytest.fixture(scope="module", autouse=True)
def _init():
    h2o3_tpu.init()


def _get(url):
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read().decode())


def _post(url, body: dict):
    # exactly what P() sends: JSON body, application/json
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read().decode())


def test_flow_js_request_sequence(tmp_path):
    from h2o3_tpu.api.server import start_server
    srv = start_server(port=0)
    base = srv.url
    try:
        # the workbench page itself serves with the JS hooks present
        with urllib.request.urlopen(f"{base}/flow") as r:
            html = r.read().decode()
        for hook in ("doImport", "doTrain", "doAutoML", "doPredict",
                     "doSplit", "doRapids", "/3/Parse",
                     "/3/ModelBuilders/", "/99/AutoMLBuilder"):
            assert hook in html, hook

        # --- doImport: P('/3/Parse', {path, destination_frame})
        rng = np.random.default_rng(0)
        csv = tmp_path / "flow.csv"
        csv.write_text("x1,x2,y\n" + "\n".join(
            f"{rng.normal():.4f},{rng.normal():.4f},"
            f"{'A' if rng.random() < 0.5 else 'B'}" for _ in range(200)))
        out = _post(f"{base}/3/Parse",
                    {"path": str(csv), "destination_frame": None})
        fkey = out["destination_frame"]["name"]      # JS dereference

        # --- refresh(): J('/3/Frames') -> frameCache entries carry
        # frame_id.name and columns[].label (fillCols reads them)
        frames = _get(f"{base}/3/Frames")["frames"]
        entry = next(f for f in frames if f["frame_id"]["name"] == fkey)
        labels = [c["label"] for c in entry["columns"]]
        assert labels == ["x1", "x2", "y"]

        # --- fillParams(): J('/3/ModelBuilders/gbm') ->
        # model_builders[*].parameters[].name
        mb = _get(f"{base}/3/ModelBuilders/gbm")["model_builders"]
        params_meta = list(mb.values())[0]["parameters"]
        assert any(p["name"] == "ntrees" for p in params_meta)

        # --- doTrain: P('/3/ModelBuilders/gbm', params) with the
        # training_frame/response_column fields the JS injects
        out = _post(f"{base}/3/ModelBuilders/gbm",
                    {"ntrees": 3, "max_depth": 3, "seed": 1,
                     "training_frame": fkey, "response_column": "y"})
        mkey = out["model"]["model_id"]["name"]      # JS dereference

        # --- doPredict: P('/3/Predictions/models/M/frames/F', {}) then
        # J('/3/Frames/<preds>/data?row_count=20')
        out = _post(f"{base}/3/Predictions/models/{mkey}/frames/{fkey}",
                    {})
        pkey = out["predictions_frame"]["name"]      # JS dereference
        data = _get(f"{base}/3/Frames/{pkey}/data?row_count=20")
        assert data["row_count"] == 20
        assert len(next(iter(data["data"].values()))) == 20

        # --- doPD: P('/3/PartialDependence', {model, frame, column})
        pd = _post(f"{base}/3/PartialDependence",
                   {"model": mkey, "frame": fkey, "column": "x1"})
        assert "partial_dependence_data" in pd or pd  # shape rendered raw

        # --- doSplit: P('/3/SplitFrame', {key, ratios: "[0.75]"})
        sp = _post(f"{base}/3/SplitFrame",
                   {"key": fkey, "ratios": json.dumps([0.75])})
        assert sp

        # --- doRapids: P('/99/Rapids', {ast})
        rp = _post(f"{base}/99/Rapids", {"ast": f"(nrow {fkey})"})
        assert rp.get("scalar") == 200.0

        # --- doDelete: DELETE /3/DKV/<key>
        req = urllib.request.Request(f"{base}/3/DKV/{pkey}",
                                     method="DELETE")
        with urllib.request.urlopen(req) as r:
            assert json.loads(r.read().decode())["removed"] == pkey
    finally:
        srv.stop()
