"""GridSearch / StackedEnsemble / Leaderboard / AutoML tests.

Mirrors testdir_algos/{grid,stackedensemble,automl} pyunits: grid budgets
and ordering, CV stacking beating-or-matching base models, leaderboard
ranking, a small end-to-end AutoML run.
"""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.automl import AutoML, Leaderboard
from h2o3_tpu.models import (GBM, GLM, StackedEnsemble, GridSearch)


# Every expensive test runs twice: a tiny-shape variant inside the tier-1
# budget, and the original full shape behind `-m heavy` (VERDICT r5 weak
# #4: this module cost 402 s as a single-shape suite).
@pytest.fixture(params=[pytest.param(False, id="tiny"),
                        pytest.param(True, id="full",
                                     marks=pytest.mark.heavy)])
def full(request):
    return request.param


def _binary_frame(rng, n=2500):
    X = rng.normal(size=(n, 4))
    logits = 1.2 * X[:, 0] - 0.8 * X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    y = rng.random(n) < 1 / (1 + np.exp(-logits))
    cols = {f"x{j}": X[:, j] for j in range(4)}
    cols["y"] = np.where(y, "yes", "no").astype(object)
    return Frame.from_numpy(cols)


def test_grid_cartesian(cl, rng, full):
    fr = _binary_frame(rng, n=2500 if full else 300)
    hp = {"max_depth": [2, 4], "ntrees": [5, 10] if full else [2, 3]}
    grid = GridSearch(GBM, hp, response_column="y", seed=1).train(fr)
    assert len(grid.models) == 4
    table = grid.sorted_metric_table()
    assert table[0]["auc"] >= table[-1]["auc"]
    assert grid.best_model.key == table[0]["model_id"]
    assert set(table[0]) >= {"max_depth", "ntrees", "model_id", "auc"}


def test_grid_random_discrete_budget(cl, rng, full):
    fr = _binary_frame(rng, n=1200 if full else 250)
    grid = GridSearch(
        GBM, {"max_depth": [2, 3, 4, 5], "learn_rate": [0.05, 0.1, 0.3]},
        search_criteria={"strategy": "RandomDiscrete", "max_models": 3,
                         "seed": 7},
        response_column="y", ntrees=5 if full else 2, seed=1).train(fr)
    assert len(grid.models) == 3


def test_stacked_ensemble_cv(cl, rng, full):
    fr = _binary_frame(rng, n=2500 if full else 400)
    common = dict(response_column="y", nfolds=3, seed=11,
                  keep_cross_validation_predictions=True)
    gbm = GBM(ntrees=20 if full else 3, max_depth=3, **common).train(fr)
    glm = GLM(family="binomial", lambda_=1e-4, **common).train(fr)
    se = StackedEnsemble(response_column="y",
                         base_models=[gbm.key, glm.key]).train(fr)
    base_auc = max(gbm.training_metrics.auc, glm.training_metrics.auc)
    perf = se.model_performance(fr)
    assert perf.auc > base_auc - (0.02 if full else 0.08)
    pred = se.predict(fr)
    assert pred.names[0] == "predict"
    assert len(pred.vecs[0].to_numpy()) == fr.nrows


def test_stacked_ensemble_requires_cv_preds(cl, rng):
    fr = _binary_frame(rng, n=600)
    gbm = GBM(response_column="y", ntrees=5, seed=1).train(fr)
    with pytest.raises(ValueError, match="CV holdout"):
        StackedEnsemble(response_column="y",
                        base_models=[gbm.key]).train(fr)


def test_stacked_ensemble_blending(cl, rng, full):
    fr = _binary_frame(rng, n=2500 if full else 400)
    blend = _binary_frame(rng, n=800 if full else 300)
    gbm = GBM(response_column="y", ntrees=10 if full else 3,
              seed=1).train(fr)
    glm = GLM(response_column="y", family="binomial",
              lambda_=1e-4, seed=1).train(fr)
    se = StackedEnsemble(response_column="y", base_models=[gbm.key, glm.key],
                         blending_frame=blend).train(blend)
    assert se.model_performance(blend).auc > (0.7 if full else 0.6)


def test_leaderboard_ranking(cl, rng, full):
    fr = _binary_frame(rng, n=1500 if full else 400)
    weak = GLM(response_column="y", family="binomial", lambda_=10.0,
               alpha=0.0, seed=1).train(fr)
    strong = GBM(response_column="y", ntrees=30 if full else 5, max_depth=4,
                 seed=1).train(fr)
    lb = Leaderboard([weak, strong])
    assert lb.sort_metric == "auc"
    assert lb.leader.key == strong.key
    table = lb.as_table()
    assert table[0]["model_id"] == strong.key


def test_automl_small_run(cl, rng, full):
    fr = _binary_frame(rng, n=1200 if full else 300)
    aml = AutoML(response_column="y", max_models=3, nfolds=3, seed=5,
                 include_algos=["glm", "gbm"])
    leader = aml.train(fr)
    assert leader is aml.leader
    steps = [e["step"] for e in aml.events if "model" in e]
    assert len(steps) >= 3
    table = aml.leaderboard.as_table()
    assert len(table) == len(aml.models)
    # SEs built from CV stacking should be present
    assert any(s.startswith("SE_") for s in steps), aml.events
    assert aml.leaderboard.sort_metric == "auc"
    vals = [r["auc"] for r in table]
    assert vals == sorted(vals, reverse=True)


def test_automl_plan_providers_and_grids(cl):
    aml = AutoML(response_column="y", seed=3)
    plan = aml._plan()
    ids = [s["id"] for s in plan]
    # defaults from every provider, grids after defaults
    assert "GLM_1" in ids and "GBM_1" in ids and "XGBoost_1" in ids
    grid_pos = [i for i, s in enumerate(plan) if s["group"] == "grid"]
    default_pos = [i for i, s in enumerate(plan) if s["group"] == "default"]
    assert grid_pos and min(grid_pos) > max(default_pos)
    # grid steps are deterministic under seed
    ids2 = [s["id"] for s in AutoML(response_column="y", seed=3)._plan()]
    p2 = AutoML(response_column="y", seed=3)._plan()
    assert [s["params"] for s in plan] == [s["params"] for s in p2]
    assert ids == ids2


def test_automl_resume_from_recovery_dir(cl, rng, tmp_path, full):
    fr = _binary_frame(rng, n=1000 if full else 250)
    d = str(tmp_path / "recovery")
    kw = dict(response_column="y", max_models=2, nfolds=0, seed=7,
              include_algos=["glm", "gbm"], auto_recovery_dir=d,
              exclude_algos=["stackedensemble"])
    a1 = AutoML(**kw)
    a1.train(fr)
    done1 = list(a1._completed_steps)
    assert len(done1) == 2
    # a resumed run skips completed steps and keeps their models
    a2 = AutoML(**{**kw, "max_models": 4})
    a2.train(fr)
    resumed = [e for e in a2.events if "resumed_steps" in e]
    assert resumed and resumed[0]["resumed_steps"] == done1
    new_steps = [e["step"] for e in a2.events if "model" in e]
    assert not set(done1) & set(new_steps), (done1, new_steps)
    assert len(a2.models) >= 4


@pytest.mark.heavy
def test_job_scheduler_priorities(cl, rng):
    """Priority scheduler (F/J pool analog): async training + priority
    queue-jumping + Job.join on scheduler-run jobs.

    heavy: two async trainings dispatch eagerly from scheduler worker
    threads concurrently (see test_parallel_cv note)."""
    from h2o3_tpu.models import GLM
    from h2o3_tpu.runtime.job import scheduler, JobScheduler, Job
    n = 600
    X = rng.normal(size=(n, 3))
    fr = _frame_for_sched(X, rng)
    jobs = [GLM(response_column="y", family="gaussian").train_async(fr)
            for _ in range(2)]
    done = []
    aj = scheduler().submit(Job("admin ping"), lambda j: done.append(1),
                            priority=JobScheduler.PRIORITY_ADMIN)
    models = [j.join(timeout=180) for j in jobs]
    aj.join(timeout=10)
    assert done == [1]
    assert all(j.status == "DONE" for j in jobs)
    assert all(m.training_metrics.r2 > 0.99 for m in models)


def _frame_for_sched(X, rng):
    import numpy as _np
    from h2o3_tpu import Frame as _F
    y = X @ [1.0, -1.0, 2.0] + 0.01 * rng.normal(size=len(X))
    return _F.from_numpy({**{f"x{j}": X[:, j] for j in range(3)}, "y": y})


def test_job_resurrection(cl, rng, tmp_path, monkeypatch):
    """Interrupted training journals survive and resume() re-trains them
    once the frame is back under its original key."""
    import json
    import h2o3_tpu
    from h2o3_tpu.runtime import recovery
    from h2o3_tpu.models import GLM
    rec = str(tmp_path / "recovery")
    monkeypatch.setenv("H2O3_TPU_RECOVERY_DIR", rec)
    n = 300
    X = rng.normal(size=(n, 2))
    y = X @ [1.0, -1.0] + 0.05 * rng.normal(size=n)
    fr = h2o3_tpu.Frame.from_numpy(
        {"x0": X[:, 0], "x1": X[:, 1], "y": y}, key="rec_frame")
    # completed training removes its journal entry
    GLM(response_column="y", family="gaussian").train(fr)
    import glob
    assert glob.glob(f"{rec}/job_*.json") == []
    # simulate an interrupted run: hand-write a running entry
    entry = {"algo": "GLM",
             "params": {"response_column": "y", "family": "gaussian"},
             "frame_key": "rec_frame", "status": "running"}
    (tmp_path / "recovery" / "job_dead.json").write_text(json.dumps(entry))
    keys = recovery.resume()
    assert len(keys) == 1
    m = h2o3_tpu.get_model(keys[0])
    p = m.predict(fr).vec("predict").to_numpy()
    assert np.corrcoef(p, y)[0, 1] > 0.99
    assert glob.glob(f"{rec}/job_*.json") == []       # consumed
    # missing frame -> entry kept, not crashed
    entry["frame_key"] = "gone_frame"
    (tmp_path / "recovery" / "job_dead2.json").write_text(json.dumps(entry))
    assert recovery.resume() == []
    assert glob.glob(f"{rec}/job_*.json") != []
    h2o3_tpu.remove("rec_frame")


def test_failed_jobs_not_resurrected(cl, rng, tmp_path, monkeypatch):
    import glob
    import json
    import pytest
    import h2o3_tpu
    from h2o3_tpu.runtime import recovery
    from h2o3_tpu.models import GLM
    rec = str(tmp_path / "rec2")
    monkeypatch.setenv("H2O3_TPU_RECOVERY_DIR", rec)
    fr = h2o3_tpu.Frame.from_numpy(
        {"x": rng.normal(size=50), "y": rng.normal(size=50)},
        key="rec2_frame")
    # a deterministic failure marks its entry failed instead of running
    with pytest.raises(Exception):
        GLM(response_column="nope", family="gaussian").train(fr)
    entries = glob.glob(f"{rec}/job_*.json")
    assert len(entries) == 0 or all(
        json.loads(open(p).read())["status"] == "failed" for p in entries)
    # resume() ignores failed entries entirely
    assert recovery.resume() == []
    h2o3_tpu.remove("rec2_frame")


def test_automl_explain(cl, rng):
    import h2o3_tpu
    from h2o3_tpu.automl import AutoML
    X = rng.normal(size=(200, 2))
    y = np.where(X[:, 0] > 0, "Y", "N").astype(object)
    fr = h2o3_tpu.Frame.from_numpy({"x0": X[:, 0], "x1": X[:, 1], "y": y})
    aml = AutoML(response_column="y", max_models=2, seed=1)
    aml.train(fr)
    b = aml.explain(fr, top_n=1)
    assert {"leader", "model_correlation", "varimp_heatmap"} <= set(b)
    assert b["varimp_heatmap"]["importance"].shape[1] == \
        len(aml.leaderboard.models)
    # the "leader" bundle explains the metric-ranked leader, and the
    # heatmap's first model column is the leader too
    assert b["varimp_heatmap"]["model"][0] == aml.leader.key


@pytest.mark.heavy
def test_parallel_cv_matches_sequential(cl, rng):
    """CVModelBuilder parallelization (hex/CVModelBuilder.java:16): fold
    models built on a thread pool produce the same CV metrics as the
    sequential build, and the fold count is intact.

    heavy: explicit parallelism>1 runs concurrent eager dispatch, which
    stalls XLA:CPU's single execution stream on single-core CI hosts."""
    fr = _binary_frame(rng, n=1200)
    seq = GBM(response_column="y", ntrees=5, max_depth=3, nfolds=3,
              seed=7, parallelism=1).train(fr)
    par = GBM(response_column="y", ntrees=5, max_depth=3, nfolds=3,
              seed=7, parallelism=3).train(fr)
    assert len(par.output["cv_fold_models"]) == 3
    assert np.isclose(par.cross_validation_metrics.auc,
                      seq.cross_validation_metrics.auc, atol=1e-6)


@pytest.mark.heavy
def test_parallel_grid_matches_sequential(cl, rng):
    fr = _binary_frame(rng, n=900)
    hp = {"max_depth": [2, 3], "ntrees": [3, 5]}
    g1 = GridSearch(GBM, hp, response_column="y", seed=5,
                    parallelism=1).train(fr)
    g4 = GridSearch(GBM, hp, response_column="y", seed=5,
                    parallelism=4).train(fr)
    assert len(g4.models) == len(g1.models) == 4
    m1 = {tuple(sorted(e.items())): g1.models[i].training_metrics.auc
          for i, e in enumerate(g1.entries)}
    m4 = {tuple(sorted(e.items())): g4.models[i].training_metrics.auc
          for i, e in enumerate(g4.entries)}
    for k in m1:
        assert np.isclose(m1[k], m4[k], atol=1e-6)


@pytest.mark.heavy
def test_automl_parallel_steps(cl, rng):
    fr = _binary_frame(rng, n=800)
    aml = AutoML(response_column="y", max_models=3, nfolds=0, seed=3,
                 parallelism=3)
    aml.train(fr)
    assert len(aml.models) >= 2
