"""Scoring-pipeline artifact (mojo-pipeline analog, VERDICT r03 missing
#6): fitted TargetEncoder + model bundle scores standalone and matches
the in-framework transform->predict path exactly."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.export.pipeline import export_pipeline, load_pipeline
from h2o3_tpu.frame.vec import T_CAT


@pytest.fixture(scope="module", autouse=True)
def _init():
    h2o3_tpu.init()


def _data(n=400, seed=0):
    rng = np.random.default_rng(seed)
    city = rng.choice(["nyc", "sfo", "chi", "aus"], n)
    lift = {"nyc": 0.3, "sfo": -0.2, "chi": 0.1, "aus": 0.0}
    x = rng.normal(size=n).astype(np.float32)
    logit = x * 0.5 + np.array([lift[c] for c in city])
    y = rng.random(n) < 1 / (1 + np.exp(-logit))
    return Frame.from_numpy({
        "city": city.astype(object), "x": x,
        "y": np.where(y, "yes", "no").astype(object),
    }, types={"city": T_CAT, "y": T_CAT})


def test_pipeline_roundtrip_matches_in_framework(tmp_path):
    from h2o3_tpu.models import GBM, TargetEncoder
    fr = _data()
    te = TargetEncoder(response_column="y", columns=["city"],
                       blending=True, noise=0.0, seed=1).train(fr)
    enc = te.transform(fr)                      # inference mode
    m = GBM(response_column="y", ntrees=6, max_depth=3, seed=2,
            ignored_columns=["city"]).train(enc)
    path = export_pipeline(m, str(tmp_path / "pipe.zip"),
                           transformers=[te])
    pipe = load_pipeline(path)
    data = {"city": [str(v) for v in fr.vec("city").decoded()],
            "x": fr.vec("x").to_numpy().tolist()}
    out = pipe.predict(data)
    native = m.predict(enc).to_numpy()[:, 2].astype(np.float64)
    np.testing.assert_allclose(out["probabilities"][:, 1], native,
                               atol=1e-6)
    # unseen level scores with the prior, not an error
    out2 = pipe.predict({"city": ["mars"], "x": [0.0]})
    assert np.isfinite(out2["probabilities"]).all()


def test_pipeline_rejects_unknown_transformer(tmp_path):
    from h2o3_tpu.models import GBM
    fr = _data(100)
    m = GBM(response_column="y", ntrees=2, max_depth=2, seed=1).train(fr)
    with pytest.raises(ValueError, match="transformer"):
        export_pipeline(m, str(tmp_path / "x.zip"),
                        transformers=["not-a-model"])
