"""DKV control-plane retry: a transient coordinator outage shorter than
the retry budget must be invisible to callers (zero job failures)."""

import socket
import threading
import time

import pytest

import h2o3_tpu
from h2o3_tpu.runtime import dkv, failure
from h2o3_tpu.runtime.config import reload as config_reload


@pytest.fixture()
def fast_retry(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_DKV_RETRIES", "6")
    monkeypatch.setenv("H2O3_TPU_DKV_BACKOFF_BASE", "0.05")
    monkeypatch.setenv("H2O3_TPU_DKV_BACKOFF_MAX", "0.3")
    monkeypatch.setenv("H2O3_TPU_DKV_RETRY_BUDGET", "10")
    config_reload()
    failure.reset()
    yield
    dkv.detach()
    failure.reset()
    for k in ("H2O3_TPU_DKV_RETRIES", "H2O3_TPU_DKV_BACKOFF_BASE",
              "H2O3_TPU_DKV_BACKOFF_MAX", "H2O3_TPU_DKV_RETRY_BUDGET",
              "H2O3_TPU_FAULT_INJECT"):
        monkeypatch.delenv(k, raising=False)
    config_reload()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_coordinator_outage_below_budget_causes_zero_failures(
        cl, fast_retry):
    """Kill the coordinator mid-session, restart it 0.5s later on the
    same port: in-flight ops retry with backoff and succeed — the
    acceptance contract for the DKV retry budget."""
    from h2o3_tpu.runtime.observability import timeline_events
    port = dkv.serve(port=0)
    dkv.attach("127.0.0.1", port)
    try:
        assert dkv._rpc("incr", key="!retry_ctr", delta=1) == 1.0
        dkv._server.shutdown()            # coordinator goes away
        dkv._server.server_close()        # listen socket released: refused
        dkv._server = None

        def revive():
            time.sleep(0.5)
            dkv.serve(port=port)

        threading.Thread(target=revive, daemon=True).start()
        t0 = time.time()
        # same-process store survives; the op still crosses the (dead,
        # then revived) TCP control plane because _remote is set
        assert dkv._rpc("incr", key="!retry_ctr", delta=1) == 2.0
        assert time.time() - t0 >= 0.3    # it actually waited the outage out
        retries = [e for e in timeline_events(2000)
                   if e["kind"] == "dkv_retry"]
        assert retries, "retry events must hit the timeline"
    finally:
        dkv.detach()
        dkv.remove("!retry_ctr")


def test_retry_budget_exhaustion_raises(cl, fast_retry, monkeypatch):
    """Nothing listening and no revival: the op fails after the attempt
    budget instead of hanging forever."""
    monkeypatch.setenv("H2O3_TPU_DKV_RETRIES", "2")
    monkeypatch.setenv("H2O3_TPU_DKV_BACKOFF_BASE", "0.01")
    config_reload()
    dkv._remote = ("127.0.0.1", _free_port())
    try:
        t0 = time.time()
        with pytest.raises(OSError):
            dkv._rpc("ping")
        assert time.time() - t0 < 5.0
    finally:
        dkv._remote = None


def test_injected_dkv_drops_are_absorbed(cl, fast_retry, monkeypatch):
    """The dkv_drop injection point: two transient drops on the client
    side retry through; a permanent drop (repeat beyond the attempt
    budget) surfaces as ConnectionError."""
    port = dkv.serve(port=0)
    dkv.attach("127.0.0.1", port)
    try:
        failure.reset()
        monkeypatch.setenv("H2O3_TPU_FAULT_INJECT",
                           "dkv_rpc:0:1:dkv_drop:2")
        assert dkv._rpc("ping") == "pong"
        failure.reset()
        monkeypatch.setenv("H2O3_TPU_DKV_RETRIES", "2")
        monkeypatch.setenv("H2O3_TPU_FAULT_INJECT",
                           "dkv_rpc:0:1:dkv_drop:99")
        config_reload()
        with pytest.raises(ConnectionError):
            dkv._rpc("ping")
    finally:
        monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
        failure.reset()
        dkv.detach()
