"""DKV control-plane retry: a transient coordinator outage shorter than
the retry budget must be invisible to callers (zero job failures)."""

import socket
import subprocess
import threading
import time

import pytest

import h2o3_tpu
from h2o3_tpu.runtime import dkv, failure, heartbeat
from h2o3_tpu.runtime.config import reload as config_reload


@pytest.fixture()
def fast_retry(monkeypatch):
    # stop the background DKV traffic (heartbeat stamps, watchdog key
    # scans): it would otherwise consume fault-injection hits and make
    # the exactly-once assertions nondeterministic
    heartbeat.stop()
    failure.stop()
    monkeypatch.setenv("H2O3_TPU_DKV_RETRIES", "6")
    monkeypatch.setenv("H2O3_TPU_DKV_BACKOFF_BASE", "0.05")
    monkeypatch.setenv("H2O3_TPU_DKV_BACKOFF_MAX", "0.3")
    monkeypatch.setenv("H2O3_TPU_DKV_RETRY_BUDGET", "10")
    config_reload()
    failure.reset()
    yield
    dkv.detach()
    failure.reset()
    for k in ("H2O3_TPU_DKV_RETRIES", "H2O3_TPU_DKV_BACKOFF_BASE",
              "H2O3_TPU_DKV_BACKOFF_MAX", "H2O3_TPU_DKV_RETRY_BUDGET",
              "H2O3_TPU_FAULT_INJECT"):
        monkeypatch.delenv(k, raising=False)
    config_reload()
    heartbeat.start()
    failure.start()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_coordinator_outage_below_budget_causes_zero_failures(
        cl, fast_retry):
    """Kill the coordinator mid-session, restart it 0.5s later on the
    same port: in-flight ops retry with backoff and succeed — the
    acceptance contract for the DKV retry budget."""
    from h2o3_tpu.runtime.observability import timeline_events
    port = dkv.serve(port=0)
    dkv.attach("127.0.0.1", port)
    try:
        assert dkv._rpc("incr", key="!retry_ctr", delta=1) == 1.0
        dkv._server.shutdown()            # coordinator goes away
        dkv._server.server_close()        # listen socket released: refused
        dkv._server = None

        def revive():
            time.sleep(0.5)
            dkv.serve(port=port)

        threading.Thread(target=revive, daemon=True).start()
        t0 = time.time()
        # same-process store survives; the op still crosses the (dead,
        # then revived) TCP control plane because _remote is set
        assert dkv._rpc("incr", key="!retry_ctr", delta=1) == 2.0
        assert time.time() - t0 >= 0.3    # it actually waited the outage out
        retries = [e for e in timeline_events(2000)
                   if e["kind"] == "dkv_retry"]
        assert retries, "retry events must hit the timeline"
    finally:
        dkv.detach()
        dkv.remove("!retry_ctr")


def test_retry_budget_exhaustion_raises(cl, fast_retry, monkeypatch):
    """Nothing listening and no revival: the op fails after the attempt
    budget instead of hanging forever."""
    monkeypatch.setenv("H2O3_TPU_DKV_RETRIES", "2")
    monkeypatch.setenv("H2O3_TPU_DKV_BACKOFF_BASE", "0.01")
    config_reload()
    dkv._remote = ("127.0.0.1", _free_port())
    try:
        t0 = time.time()
        with pytest.raises(OSError):
            dkv._rpc("ping")
        assert time.time() - t0 < 5.0
    finally:
        dkv._remote = None


def test_injected_dkv_drops_are_absorbed(cl, fast_retry, monkeypatch):
    """The dkv_drop injection point: two transient drops on the client
    side retry through; a permanent drop (repeat beyond the attempt
    budget) surfaces as ConnectionError."""
    port = dkv.serve(port=0)
    dkv.attach("127.0.0.1", port)
    try:
        failure.reset()
        monkeypatch.setenv("H2O3_TPU_FAULT_INJECT",
                           "dkv_rpc:0:1:dkv_drop:2")
        assert dkv._rpc("ping") == "pong"
        failure.reset()
        monkeypatch.setenv("H2O3_TPU_DKV_RETRIES", "2")
        monkeypatch.setenv("H2O3_TPU_FAULT_INJECT",
                           "dkv_rpc:0:1:dkv_drop:99")
        config_reload()
        with pytest.raises(ConnectionError):
            dkv._rpc("ping")
    finally:
        monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
        failure.reset()
        dkv.detach()


def test_incr_and_make_key_exactly_once_under_dropped_response(
        cl, fast_retry, monkeypatch):
    """The exactly-once acceptance proof: ``dkv_rpc_resp`` drops the
    RESPONSE after the server applied the op.  The retry resends the same
    request id and must answer from the dedup window — no double-applied
    ``incr``, no gap in the ``make_key`` counter."""
    from h2o3_tpu.runtime.observability import counters
    port = dkv.serve(port=0)
    dkv.attach("127.0.0.1", port)
    try:
        failure.reset()
        monkeypatch.setenv("H2O3_TPU_FAULT_INJECT",
                           "dkv_rpc_resp:0:1:dkv_drop")
        before = counters().get("dkv_dedup_hits", 0)
        assert dkv._rpc("incr", key="!eo_ctr", delta=1.0) == 1.0
        assert dkv._store["!eo_ctr"] == 1.0          # applied exactly once
        assert counters().get("dkv_dedup_hits", 0) > before
        monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
        assert dkv._rpc("incr", key="!eo_ctr", delta=1.0) == 2.0

        failure.reset()
        monkeypatch.setenv("H2O3_TPU_FAULT_INJECT",
                           "dkv_rpc_resp:0:1:dkv_drop")
        k1 = dkv._rpc("make_key", prefix="!eo")
        monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
        k2 = dkv._rpc("make_key", prefix="!eo")
        n1, n2 = (int(k.rsplit("_", 1)[1]) for k in (k1, k2))
        assert n2 == n1 + 1                          # no counter gap
    finally:
        monkeypatch.delenv("H2O3_TPU_FAULT_INJECT", raising=False)
        failure.reset()
        dkv.remove("!eo_ctr")
        dkv.detach()


def test_tls_retry_and_exactly_once(cl, fast_retry, monkeypatch, tmp_path):
    """The retry + dedup machinery must hold over a TLS control plane,
    and detach() must drop the client TLS context with the remote."""
    cert, key = str(tmp_path / "dkv.pem"), str(tmp_path / "dkv.key")
    subprocess.run(["openssl", "req", "-x509", "-newkey", "rsa:2048",
                    "-keyout", key, "-out", cert, "-days", "1", "-nodes",
                    "-subj", "/CN=localhost"],
                   capture_output=True, check=True)
    monkeypatch.setenv("H2O3_TPU_TLS_CERT", cert)
    monkeypatch.setenv("H2O3_TPU_TLS_KEY", key)
    config_reload()
    port = dkv.serve(port=0)
    dkv.attach("127.0.0.1", port)
    try:
        assert dkv._client_ssl is not None           # handshake is real
        failure.reset()
        monkeypatch.setenv("H2O3_TPU_FAULT_INJECT",
                           "dkv_rpc:0:1:dkv_drop:2")
        assert dkv._rpc("ping") == "pong"            # drops retried over TLS
        failure.reset()
        monkeypatch.setenv("H2O3_TPU_FAULT_INJECT",
                           "dkv_rpc_resp:0:1:dkv_drop")
        assert dkv._rpc("incr", key="!tls_ctr", delta=1.0) == 1.0
        assert dkv._store["!tls_ctr"] == 1.0
    finally:
        monkeypatch.delenv("H2O3_TPU_FAULT_INJECT", raising=False)
        failure.reset()
        dkv.remove("!tls_ctr")
        dkv.detach()
        monkeypatch.delenv("H2O3_TPU_TLS_CERT")
        monkeypatch.delenv("H2O3_TPU_TLS_KEY")
        config_reload()
    # the satellite contract: a later plaintext attach must not reuse a
    # stale TLS context
    assert dkv._client_ssl is None and dkv._remote is None
