"""Deployment manifests: structural validation (VERDICT r03: 'Dockerfile/
GKE manifest still untested').  No docker daemon or helm binary exists in
this image, so k8s.yaml is schema-parsed directly and the helm templates
are rendered by a minimal in-test engine covering exactly the constructs
the chart uses ({{ .Values.* }}, {{ .Release.Name }}, quote, {{- if }} /
{{- end }}), then yaml-parsed."""

import os
import re

import pytest
import yaml

ROOT = os.path.join(os.path.dirname(__file__), "..", "deploy")


def test_k8s_manifest_parses_and_wires_discovery():
    docs = list(yaml.safe_load_all(open(os.path.join(ROOT, "k8s.yaml"))))
    kinds = {d["kind"] for d in docs}
    assert kinds == {"Service", "Job"}
    job = next(d for d in docs if d["kind"] == "Job")
    spec = job["spec"]
    assert spec["completionMode"] == "Indexed"
    ctr = spec["template"]["spec"]["containers"][0]
    env_names = {e["name"] for e in ctr["env"]}
    assert "H2O3_TPU_POD_INDEX" in env_names        # discovery ordinal
    assert "H2O3_TPU_RECOVERY_DIR" in env_names     # restart resume
    cmd = ctr["command"]
    assert "--discover" in cmd and "--cluster-size" in cmd
    # parallelism matches the advertised cluster size
    assert spec["parallelism"] == spec["completions"] == \
        int(cmd[cmd.index("--cluster-size") + 1])
    svc = next(d for d in docs if d["kind"] == "Service")
    # headless service (DNS A records per pod); YAML's unquoted None
    # parses as the string "None"
    assert svc["spec"]["clusterIP"] in (None, "None")


def test_dockerfile_builds_the_launcher():
    src = open(os.path.join(ROOT, "Dockerfile")).read()
    assert re.search(r"^FROM ", src, re.M)
    assert "h2o3_tpu" in src
    assert "deploy.serve" in src or "deploy/serve" in src


# ------------------------------------------------------- mini helm render

def _get(values, dotted):
    cur = values
    for part in dotted.split("."):
        cur = cur[part]
    return cur


def _render(template: str, values: dict, release: str) -> str:
    # strip {{- if X }} ... {{- end }} blocks when X is falsy; keep body
    # otherwise.  Non-nested usage only (what the chart uses).
    out = re.sub(r"\{\{- if \.Values\.([^}]+)\}\}(.*?)\{\{- end \}\}",
                 lambda m: m.group(2) if _get(values, m.group(1).strip())
                 else "", template, flags=re.S)
    out = out.replace("{{ .Release.Name }}", release)

    def val_repl(m):
        expr = m.group(1).strip()
        quote = expr.endswith("| quote")
        expr = expr.replace("| quote", "").strip()
        v = _get(values, expr.replace(".Values.", ""))
        return f'"{v}"' if quote else str(v)

    out = re.sub(r"\{\{ (\.Values\.[^}]+) \}\}", val_repl, out)
    return out


@pytest.fixture(scope="module")
def chart():
    base = os.path.join(ROOT, "helm", "h2o3-tpu")
    values = yaml.safe_load(open(os.path.join(base, "values.yaml")))
    return base, values


def test_helm_chart_default_render(chart):
    base, values = chart
    for name in ("job.yaml", "service.yaml"):
        tpl = open(os.path.join(base, "templates", name)).read()
        doc = yaml.safe_load(_render(tpl, values, "rel"))
        assert doc["kind"] in ("Job", "Service")
        if doc["kind"] == "Job":
            ctr = doc["spec"]["template"]["spec"]["containers"][0]
            assert "--discover" in ctr["command"]
            # defaults: no auth/recovery/tls blocks rendered
            env_names = {e["name"] for e in ctr["env"]}
            assert env_names == {"H2O3_TPU_POD_INDEX"}
            assert "--https" not in ctr["command"]


def test_helm_chart_full_options_render(chart):
    base, values = chart
    values = yaml.safe_load(yaml.safe_dump(values))  # deep copy
    values["auth"]["spec"] = "hash_file:/etc/h2o3/realm"
    values["recovery"]["dir"] = "gcs://bkt/rec"
    values["tls"]["certSecret"] = "my-tls"
    tpl = open(os.path.join(base, "templates", "job.yaml")).read()
    doc = yaml.safe_load(_render(tpl, values, "rel"))
    ctr = doc["spec"]["template"]["spec"]["containers"][0]
    env = {e["name"]: e.get("value") for e in ctr["env"]}
    assert env["H2O3_TPU_AUTH"] == "hash_file:/etc/h2o3/realm"
    assert env["H2O3_TPU_RECOVERY_DIR"] == "gcs://bkt/rec"
    assert env["H2O3_TPU_TLS_CERT"] == "/etc/h2o3-tls/tls.crt"
    # TLS secret wires the HTTPS flags AND the mount
    assert "--https" in ctr["command"]
    assert "--https-cert" in ctr["command"]
    assert ctr["volumeMounts"][0]["mountPath"] == "/etc/h2o3-tls"
    vols = doc["spec"]["template"]["spec"]["volumes"]
    assert vols[0]["secret"]["secretName"] == "my-tls"
    assert doc["spec"]["parallelism"] == values["cluster"]["hosts"]


def test_helm_loadtest_render(chart):
    base, values = chart
    tpl = open(os.path.join(base, "templates", "loadtest-job.yaml")).read()
    # disabled by default: the whole template is if-wrapped -> no document
    assert yaml.safe_load(_render(tpl, values, "rel")) is None
    values = yaml.safe_load(yaml.safe_dump(values))  # deep copy
    values["loadtest"]["enabled"] = True
    values["loadtest"]["model"] = "gbm_1"
    doc = yaml.safe_load(_render(tpl, values, "rel"))
    assert doc["kind"] == "Job"
    assert doc["metadata"]["name"] == "rel-loadtest"
    ctr = doc["spec"]["template"]["spec"]["containers"][0]
    url = ctr["args"][-1]
    # targets the coordinator service on the REST port, realtime route
    assert url == ("http://rel-coordinator:54321"
                   "/3/Predictions/realtime/gbm_1")
    assert "POST" in ctr["args"]
    # closed-loop knobs flow through
    i = ctr["args"].index("-n")
    assert ctr["args"][i + 1] == str(values["loadtest"]["requests"])
