"""Failure detection acts: watchdog aborts jobs, injection kills, recovery
resumes.  (Chaos/multi-process variant lives in test_multiprocess.py.)"""

import os
import time

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.runtime import dkv, failure, heartbeat, recovery
from h2o3_tpu.runtime.job import Job, RUNNING, FAILED


def test_watchdog_aborts_running_jobs_on_dead_member(cl):
    failure.reset()
    name = heartbeat.start(interval=0.05)
    job = Job("stuck train")
    job.status = RUNNING            # simulate a job blocked in a collective
    try:
        # a ghost peer that stopped stamping long enough ago to be dead
        dkv.put(heartbeat.PREFIX + "ghost", {"ts": time.time() - 1.0,
                                             "interval": 0.05, "pid": 1})
        newly = failure.check(hb_interval=0.05)
        assert newly == ["ghost"]
        assert job.status == FAILED
        with pytest.raises(failure.NodeFailedError, match="ghost"):
            job.join()
        # failure record published for REST/tooling
        rec = dkv.get(failure.FAILURES_PREFIX + "ghost")
        assert rec and rec["pid"] == 1
        # second sweep is idempotent
        assert failure.check(hb_interval=0.05) == []
        assert failure.any_dead() and failure.cluster_degraded()
    finally:
        heartbeat.stop()
        failure.reset()
        dkv.remove(heartbeat.PREFIX + "ghost")
        dkv.remove(failure.FAILURES_PREFIX + "ghost")
        dkv.remove(job.key)


def test_node_death_keeps_journal_resumable(cl, tmp_path, monkeypatch):
    """A train that fails while the cluster is degraded keeps its journal
    entry 'running', and recovery.resume() retrains it."""
    from h2o3_tpu.models import GBM
    monkeypatch.setenv("H2O3_TPU_RECOVERY_DIR", str(tmp_path))
    failure.reset()
    rng = np.random.default_rng(3)
    n = 600
    x = rng.normal(size=n).astype(np.float32)
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-2 * x)), "Y", "N")
    fr = h2o3_tpu.H2OFrame({"x": x, "y": y.astype(object)},
                           destination_frame="chaos_unit_fr")
    # mark a member dead, then make the build blow up mid-fit: the journal
    # must stay 'running' (node failure), not flip to 'failed'
    failure._handled.add("ghost")
    boom = RuntimeError("collective aborted: peer closed connection")

    class BoomGBM(GBM):
        def _fit(self, *a, **k):
            raise boom

    BoomGBM.__name__ = "GBM"        # journal records the resumable algo
    with pytest.raises(RuntimeError):
        BoomGBM(response_column="y", ntrees=3, max_depth=2, seed=1).train(fr)
    entries = list(tmp_path.glob("job_*.json"))
    assert len(entries) == 1
    import json
    assert json.loads(entries[0].read_text())["status"] == "running"
    failure.reset()                 # "restart": healthy again
    done = recovery.resume(str(tmp_path))
    assert len(done) == 1
    model = dkv.get(done[0])
    assert model is not None and model.output["ntrees_trained"] == 3
    assert not list(tmp_path.glob("job_*.json"))


def test_plain_failure_still_marks_journal_failed(cl, tmp_path, monkeypatch):
    from h2o3_tpu.models import GBM
    monkeypatch.setenv("H2O3_TPU_RECOVERY_DIR", str(tmp_path))
    failure.reset()
    heartbeat.start(interval=0.5)   # healthy self-stamp: not degraded
    try:
        fr = h2o3_tpu.H2OFrame({"x": [1.0, 2.0, 3.0],
                                "y": ["a", "b", "a"]},
                               destination_frame="plainfail_fr")
        with pytest.raises(Exception):
            GBM(response_column="nosuch", ntrees=2).train(fr)
    finally:
        heartbeat.stop()
    # a deterministic failure must NOT be resurrected
    import json
    for e in tmp_path.glob("job_*.json"):
        assert json.loads(e.read_text())["status"] == "failed"
    assert recovery.resume(str(tmp_path)) == []


def test_fault_injection_spec_parsing(cl, monkeypatch):
    """maybe_inject is a no-op for other points/processes (the kill path
    is exercised by the multi-process chaos test)."""
    failure.reset()
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "tree_chunk:7:1")
    failure.maybe_inject("tree_chunk")      # wrong process index: survive
    failure.maybe_inject("dl_iter")         # wrong point: survive
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "garbage")
    failure.maybe_inject("tree_chunk")      # malformed: survive
