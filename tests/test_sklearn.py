"""sklearn adapter tests — wrapper protocol, clone/Pipeline compat.

Mirrors the reference's h2o-py/tests_sklearn smoke coverage.
"""

import numpy as np

import h2o3_tpu  # noqa: F401  (cl fixture boots the mesh)


def test_classifier_protocol(cl, rng):
    from h2o3_tpu.sklearn import H2OGradientBoostingClassifier
    X = rng.normal(size=(300, 3))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    est = H2OGradientBoostingClassifier(ntrees=8, max_depth=3, seed=1)
    assert est.get_params() == {"ntrees": 8, "max_depth": 3, "seed": 1}
    est.fit(X, y)
    yhat = est.predict(X)
    assert yhat.dtype.kind in "il" and set(yhat) <= {0, 1}
    assert est.score(X, y) > 0.9
    proba = est.predict_proba(X)
    assert proba.shape == (300, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)
    # column order follows classes_; labels use the model's own
    # threshold (max-F1, like the reference), so compare rank agreement
    assert list(est.classes_) == [0, 1]
    assert np.mean((proba[:, 1] > 0.5).astype(int) == yhat) > 0.95


def test_regressor_and_kmeans(cl, rng):
    from h2o3_tpu.sklearn import H2OGLMRegressor, H2OKMeans
    X = rng.normal(size=(300, 3))
    y = X @ [1.0, -2.0, 0.5] + 0.05 * rng.normal(size=300)
    r = H2OGLMRegressor().fit(X, y)
    assert r.score(X, y) > 0.98
    km = H2OKMeans(k=3, seed=1).fit(X)
    labels = km.predict(X)
    assert labels.shape == (300,) and len(set(labels)) <= 3


def test_sklearn_clone_and_pipeline(cl, rng):
    from sklearn.base import clone
    from sklearn.pipeline import Pipeline
    from sklearn.preprocessing import StandardScaler
    from h2o3_tpu.sklearn import H2OGLMClassifier
    X = rng.normal(size=(200, 2))
    y = np.where(X[:, 0] > 0, "pos", "neg")
    est = H2OGLMClassifier(lambda_=0.0)
    c = clone(est)
    assert c is not est and c.get_params() == est.get_params()
    pipe = Pipeline([("scale", StandardScaler()),
                     ("glm", H2OGLMClassifier())])
    pipe.fit(X, y)
    acc = float(np.mean(pipe.predict(X) == y))
    assert acc > 0.9


def test_sklearn_edge_contracts(cl, rng):
    from h2o3_tpu.sklearn import (H2OGLMClassifier,
                                  H2OGradientBoostingRegressor)
    import pytest
    X = rng.normal(size=(240, 2))
    # multinomial auto-family from the class count
    y3 = np.array(["a", "b", "c"], dtype=object)[
        np.clip((X[:, 0] > -0.4).astype(int) + (X[:, 0] > 0.4), 0, 2)]
    est = H2OGLMClassifier().fit(X, y3)
    assert est.predict_proba(X).shape == (240, 3)
    assert est.score(X, y3) > 0.8
    # regressors carry no predict_proba at all (sklearn hasattr probes)
    assert not hasattr(H2OGradientBoostingRegressor(), "predict_proba")
    # unfitted state: fitted attributes absent, clear error on predict
    fresh = H2OGLMClassifier()
    assert not hasattr(fresh, "model_") and not hasattr(fresh, "classes_")
    with pytest.raises(RuntimeError, match="not fitted"):
        fresh.predict(X)
    # 1-D X rejected with guidance
    with pytest.raises(ValueError, match="2-D"):
        H2OGLMClassifier().fit(X[:, 0], y3)
    # n_features_in_ reflects fit data and survives predict calls
    assert est.n_features_in_ == 2
    est.predict(X)
    assert est.n_features_in_ == 2
