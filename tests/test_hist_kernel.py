"""tpu_hist Pallas kernel parity tests (interpret mode vs einsum reference).

The CPU test mesh exercises the einsum path in normal runs; these tests pin
``force_impl`` to run the actual Pallas kernel through the interpreter and
cross-check it bit-for-bit-ish against the portable program, over geometries
that cover: single/multi row blocks, single/multi bin tiles, L=1..32, the
deep-tree fallback kernel, and weighted/NA rows.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from h2o3_tpu.models.tree.hist import make_hist_fn


GEOMETRIES = [
    # (N, F, B, L): small single-block
    (512, 3, 17, 1),
    # multiple row blocks
    (4096, 5, 17, 8),
    # multiple bin tiles (B > TB)
    (2048, 4, 129, 4),
    # airlines-shape: many bins, deeper level
    (4096, 8, 257, 16),
    # wide-ish features
    (1024, 30, 33, 2),
]


@pytest.mark.parametrize("N,F,B,L", GEOMETRIES)
def test_pallas_matches_einsum(cl, rng, N, F, B, L):
    codes = jnp.asarray(rng.integers(0, B, (F, N)), jnp.int32)
    leaf = jnp.asarray(rng.integers(0, L, N), jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.asarray(rng.random(N), jnp.float32)
    w = jnp.asarray((rng.random(N) > 0.1), jnp.float32)
    He = make_hist_fn(L, F, B, N, force_impl="einsum")(codes, leaf, g, h, w)
    Hp = make_hist_fn(L, F, B, N, force_impl="pallas_interpret",
                      precision="f32")(codes, leaf, g, h, w)
    np.testing.assert_allclose(np.asarray(He), np.asarray(Hp),
                               atol=1e-3, rtol=1e-5)


def test_pallas_deep_fallback_matches(cl, rng):
    """Geometry big enough to trigger the VMEM-fallback kernel variant."""
    N, F, B, L = 2048, 8, 257, 512
    codes = jnp.asarray(rng.integers(0, B, (F, N)), jnp.int32)
    leaf = jnp.asarray(rng.integers(0, L, N), jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.asarray(rng.random(N), jnp.float32)
    w = jnp.ones(N, jnp.float32)
    He = make_hist_fn(L, F, B, N, force_impl="einsum")(codes, leaf, g, h, w)
    Hp = make_hist_fn(L, F, B, N, force_impl="pallas_interpret",
                      precision="f32")(codes, leaf, g, h, w)
    np.testing.assert_allclose(np.asarray(He), np.asarray(Hp),
                               atol=1e-3, rtol=1e-5)


def test_hist_totals_and_na_bin(cl, rng):
    """Histogram marginals equal direct sums; NA codes land in the last bin."""
    N, F, B, L = 1024, 4, 9, 2
    nbins = B - 1
    codes_np = rng.integers(0, B, (F, N))
    codes = jnp.asarray(codes_np, jnp.int32)
    leaf = jnp.asarray(rng.integers(0, L, N), jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.asarray(rng.random(N), jnp.float32)
    w = jnp.ones(N, jnp.float32)
    H = np.asarray(make_hist_fn(L, F, B, N, force_impl="einsum")(
        codes, leaf, g, h, w))
    # sum over (leaf, bin) recovers the global sum for every feature
    np.testing.assert_allclose(H[0].sum(axis=(0, 2)),
                               [float(jnp.sum(g))] * F, rtol=1e-4)
    # NA bin counts = rows with code == nbins
    for f in range(F):
        na_count = (codes_np[f] == nbins).sum()
        assert H[2, :, f, nbins].sum() == pytest.approx(na_count)
