"""tpu_hist Pallas kernel parity tests (interpret mode vs einsum reference).

The CPU test mesh exercises the einsum path in normal runs; these tests pin
``force_impl`` to run the actual Pallas kernel through the interpreter and
cross-check it bit-for-bit-ish against the portable program, over geometries
that cover: single/multi row blocks, single/multi bin tiles, L=1..32, the
deep-tree fallback kernel, and weighted/NA rows.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from h2o3_tpu.models.tree.hist import make_hist_fn


GEOMETRIES = [
    # (N, F, B, L): small single-block
    (512, 3, 17, 1),
    # multiple row blocks
    (4096, 5, 17, 8),
    # multiple bin tiles (B > TB)
    (2048, 4, 129, 4),
    # airlines-shape: many bins, deeper level
    (4096, 8, 257, 16),
    # wide-ish features
    (1024, 30, 33, 2),
]


@pytest.mark.parametrize("N,F,B,L", GEOMETRIES)
def test_pallas_matches_einsum(cl, rng, N, F, B, L):
    codes = jnp.asarray(rng.integers(0, B, (F, N)), jnp.int32)
    leaf = jnp.asarray(rng.integers(0, L, N), jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.asarray(rng.random(N), jnp.float32)
    w = jnp.asarray((rng.random(N) > 0.1), jnp.float32)
    He = make_hist_fn(L, F, B, N, force_impl="einsum")(codes, leaf, g, h, w)
    Hp = make_hist_fn(L, F, B, N, force_impl="pallas_interpret",
                      precision="f32")(codes, leaf, g, h, w)
    np.testing.assert_allclose(np.asarray(He), np.asarray(Hp),
                               atol=1e-3, rtol=1e-5)


def test_pallas_deep_fallback_matches(cl, rng):
    """Geometry big enough to trigger the VMEM-fallback kernel variant."""
    N, F, B, L = 2048, 8, 257, 512
    codes = jnp.asarray(rng.integers(0, B, (F, N)), jnp.int32)
    leaf = jnp.asarray(rng.integers(0, L, N), jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.asarray(rng.random(N), jnp.float32)
    w = jnp.ones(N, jnp.float32)
    He = make_hist_fn(L, F, B, N, force_impl="einsum")(codes, leaf, g, h, w)
    Hp = make_hist_fn(L, F, B, N, force_impl="pallas_interpret",
                      precision="f32")(codes, leaf, g, h, w)
    np.testing.assert_allclose(np.asarray(He), np.asarray(Hp),
                               atol=1e-3, rtol=1e-5)


def test_fine_hist_parity_and_semantics(cl, rng):
    """Fine-refinement kernel: interpret-mode Pallas vs einsum vs numpy."""
    from h2o3_tpu.models.tree.hist import make_fine_hist_fn
    N, F, L, K, W, nbins = 2048, 5, 4, 2, 8, 61   # nbins < S*W on purpose
    codes_np = rng.integers(0, nbins + 1, (F, N))
    leaf_np = rng.integers(0, L, N)
    sel_np = rng.integers(0, 8, (L, F, K))
    codes = jnp.asarray(codes_np, jnp.int32)
    leaf = jnp.asarray(leaf_np, jnp.int32)
    sel = jnp.asarray(sel_np, jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.asarray(rng.random(N), jnp.float32)
    w = jnp.ones(N, jnp.float32)
    He = np.asarray(make_fine_hist_fn(L, F, W, K, nbins, N,
                                      force_impl="einsum")(
        codes, leaf, g, h, w, sel))
    Hp = np.asarray(make_fine_hist_fn(L, F, W, K, nbins, N,
                                      force_impl="pallas_interpret",
                                      precision="f32")(
        codes, leaf, g, h, w, sel))
    np.testing.assert_allclose(He, Hp, atol=1e-3, rtol=1e-5)
    # numpy reference: slot (l,f,k,t) sums rows with leaf l, code sel*W+t
    gh = np.asarray(g)
    for l in range(L):
        for f in range(F):
            for k in range(K):
                s = sel_np[l, f, k]
                for t in range(W):
                    want = gh[(leaf_np == l)
                              & (codes_np[f] == s * W + t)
                              & (codes_np[f] < nbins)].sum()
                    assert He[0, l, f, k, t] == pytest.approx(want, abs=1e-3)


def test_hier_split_search_finds_signal_split(cl, rng):
    """On data with a real signal split, the hierarchical search picks the
    exact same (feature, bin) as the full pass, with matching gain and
    child statistics."""
    from h2o3_tpu.models.tree.hist import (
        make_hist_fn, make_fine_hist_fn, select_superbins, best_splits,
        best_splits_hier)
    N, F, L, nbins, K = 8192, 6, 4, 64, 2
    S, W = 8, 8
    lam, alpha, gam, min_rows, mcw = 1.0, 0.0, 0.0, 5.0, 0.0
    codes_np = rng.integers(0, nbins + 1, (F, N))
    codes_np[0] = rng.integers(0, 8, N)        # low-cardinality feature
    codes = jnp.asarray(codes_np, jnp.int32)
    leaf = jnp.asarray(rng.integers(0, L, N), jnp.int32)
    # strong signal on feature 1 at bin 36 (interior of super-bin 4)
    g_np = np.where(codes_np[1] <= 36, -1.0, 1.0) + 0.05 * rng.normal(size=N)
    g = jnp.asarray(g_np, jnp.float32)
    h = jnp.asarray(np.full(N, 1.0), jnp.float32)
    w = jnp.ones(N, jnp.float32)

    Hfull = make_hist_fn(L, F, nbins + 1, N, force_impl="einsum")(
        codes, leaf, g, h, w)
    feat0, bin0, nal0, gain0, valid0, ch0 = best_splits(
        Hfull, nbins, lam, min_rows, 1e-5, None, alpha, gam, mcw)

    ccodes = jnp.where(codes >= nbins, S, codes // W)
    Hc = make_hist_fn(L, F, S + 1, N, force_impl="einsum")(
        ccodes, leaf, g, h, w)
    sel, ub = select_superbins(Hc, nbins, W, K, lam, alpha, gam,
                               min_rows, mcw)
    Hf = make_fine_hist_fn(L, F, W, K, nbins, N, force_impl="einsum")(
        codes, leaf, g, h, w, sel)
    feat1, bin1, nal1, gain1, valid1, ch1, _ = best_splits_hier(
        Hc, Hf, sel, ub, nbins, W, lam, min_rows, 1e-5, None, alpha, gam,
        mcw)
    np.testing.assert_array_equal(np.asarray(feat1), np.asarray(feat0))
    np.testing.assert_array_equal(np.asarray(bin1), np.asarray(bin0))
    assert list(np.asarray(feat0)) == [1] * L
    assert list(np.asarray(bin0)) == [36] * L
    np.testing.assert_allclose(np.asarray(gain1), np.asarray(gain0),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(valid1), np.asarray(valid0))
    np.testing.assert_allclose(np.asarray(ch1), np.asarray(ch0),
                               rtol=1e-3, atol=1e-2)


def test_build_tree_hier_equals_full_on_signal(cl, rng):
    """Whole-tree growth: the hierarchical path reproduces the full-pass
    tree when splits carry signal (depth-2, two planted split features)."""
    from h2o3_tpu.models.tree.shared import build_tree
    import jax
    N, F, nbins, depth = 8192, 5, 64, 2
    codes_np = rng.integers(0, nbins, (F, N))
    codes = jnp.asarray(codes_np, jnp.int32)
    g_np = (np.where(codes_np[2] <= 21, -2.0, 2.0)
            + np.where(codes_np[3] <= 44, -0.7, 0.7)
            + 0.05 * rng.normal(size=N))
    g = jnp.asarray(g_np, jnp.float32)
    h = jnp.asarray(np.full(N, 1.0), jnp.float32)
    w = jnp.ones(N, jnp.float32)
    edges = [np.sort(rng.normal(size=nbins - 1)).astype(np.float32)
             for _ in range(F)]
    key = jax.random.PRNGKey(7)
    t0, leaf0 = build_tree(codes, g, h, w, edges, nbins, depth, 1.0, 5.0,
                           1e-5, 0.1, key, hist_precision="f32", hier=False)
    t1, leaf1 = build_tree(codes, g, h, w, edges, nbins, depth, 1.0, 5.0,
                           1e-5, 0.1, key, hist_precision="f32", hier=True)
    np.testing.assert_array_equal(np.asarray(leaf0), np.asarray(leaf1))
    np.testing.assert_allclose(np.asarray(t0.values), np.asarray(t1.values),
                               rtol=1e-3, atol=1e-4)
    for d in range(depth):
        np.testing.assert_array_equal(np.asarray(t0.feat[d]),
                                      np.asarray(t1.feat[d]))
        np.testing.assert_allclose(np.asarray(t0.thr[d]),
                                   np.asarray(t1.thr[d]), rtol=1e-5)


def test_varbin_hist_matches_dense(cl, rng):
    """Packed per-feature bin axis == dense histogram, bit-for-bit-ish."""
    from h2o3_tpu.models.tree.hist import (make_hist_fn, make_varbin_hist_fn,
                                           offset_codes)
    N, F, nbins, L = 2048, 5, 64, 4
    bin_counts = (7, 64, 22, 3, 40)        # mixed cardinalities
    B = nbins + 1
    codes_np = np.stack([
        np.where(rng.random(N) < 0.1, nbins,       # NA
                 rng.integers(0, bc, N))
        for bc in bin_counts])
    codes = jnp.asarray(codes_np, jnp.int32)
    leaf = jnp.asarray(rng.integers(0, L, N), jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.asarray(rng.random(N), jnp.float32)
    w = jnp.asarray((rng.random(N) > 0.1), jnp.float32)
    He = np.asarray(make_hist_fn(L, F, B, N, force_impl="einsum")(
        codes, leaf, g, h, w))
    gcodes = offset_codes(codes, bin_counts, nbins)
    Hv = np.asarray(make_varbin_hist_fn(
        L, F, bin_counts, B, N, force_impl="pallas_interpret",
        precision="f32")(gcodes, leaf, g, h, w))
    np.testing.assert_allclose(He, Hv, atol=1e-3, rtol=1e-5)


def test_hist_totals_and_na_bin(cl, rng):
    """Histogram marginals equal direct sums; NA codes land in the last bin."""
    N, F, B, L = 1024, 4, 9, 2
    nbins = B - 1
    codes_np = rng.integers(0, B, (F, N))
    codes = jnp.asarray(codes_np, jnp.int32)
    leaf = jnp.asarray(rng.integers(0, L, N), jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.asarray(rng.random(N), jnp.float32)
    w = jnp.ones(N, jnp.float32)
    H = np.asarray(make_hist_fn(L, F, B, N, force_impl="einsum")(
        codes, leaf, g, h, w))
    # sum over (leaf, bin) recovers the global sum for every feature
    np.testing.assert_allclose(H[0].sum(axis=(0, 2)),
                               [float(jnp.sum(g))] * F, rtol=1e-4)
    # NA bin counts = rows with code == nbins
    for f in range(F):
        na_count = (codes_np[f] == nbins).sum()
        assert H[2, :, f, nbins].sum() == pytest.approx(na_count)
