"""Smoke tier: every algorithm trains + predicts on a tiny shape, fast.

Run with ``pytest -m smoke`` (<90 s target).  This is the round-trip
sanity gate — behavioral depth lives in the per-algo suites; this file
only proves the end-to-end train->predict path stays alive for all 30
reference algorithms (SURVEY.md §2.4).
"""

import numpy as np
import pytest

from h2o3_tpu import Frame

pytestmark = pytest.mark.smoke

N = 400


@pytest.fixture(scope="module")
def bin_fr():
    rng = np.random.default_rng(7)
    X = rng.normal(size=(N, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0)
    return Frame.from_numpy({
        **{f"x{j}": X[:, j] for j in range(4)},
        "y": np.where(y, "yes", "no").astype(object)})


@pytest.fixture(scope="module")
def reg_fr():
    rng = np.random.default_rng(8)
    X = rng.normal(size=(N, 4))
    y = X[:, 0] * 2 - X[:, 1] + 0.1 * rng.normal(size=N)
    return Frame.from_numpy({**{f"x{j}": X[:, j] for j in range(4)}, "y": y})


@pytest.fixture(scope="module")
def num_fr():
    rng = np.random.default_rng(9)
    return Frame.from_numpy({f"x{j}": rng.normal(size=N) for j in range(4)})


def _check_pred(model, fr):
    pred = model.predict(fr)
    assert pred.nrows == fr.nrows
    return pred


def test_smoke_gbm(cl, bin_fr):
    from h2o3_tpu.models import GBM
    m = GBM(response_column="y", ntrees=3, max_depth=2, nbins=16, seed=1).train(bin_fr)
    _check_pred(m, bin_fr)


def test_smoke_drf(cl, bin_fr):
    from h2o3_tpu.models import DRF
    m = DRF(response_column="y", ntrees=3, max_depth=2, nbins=16, seed=1).train(bin_fr)
    _check_pred(m, bin_fr)


def test_smoke_xgboost(cl, reg_fr):
    from h2o3_tpu.models import XGBoost
    m = XGBoost(response_column="y", ntrees=3, max_depth=2, nbins=16,
                seed=1).train(reg_fr)
    _check_pred(m, reg_fr)


def test_smoke_decision_tree(cl, bin_fr):
    from h2o3_tpu.models import DecisionTree
    m = DecisionTree(response_column="y", max_depth=2, seed=1).train(bin_fr)
    _check_pred(m, bin_fr)


def test_smoke_uplift_drf(cl):
    from h2o3_tpu.models import UpliftDRF
    rng = np.random.default_rng(10)
    X = rng.normal(size=(N, 3))
    treat = rng.integers(0, 2, N)
    y = (X[:, 0] + 0.5 * treat > 0.2)
    fr = Frame.from_numpy({
        **{f"x{j}": X[:, j] for j in range(3)},
        "treatment": np.where(treat == 1, "t", "c").astype(object),
        "y": np.where(y, "1", "0").astype(object)})
    m = UpliftDRF(response_column="y", treatment_column="treatment",
                  ntrees=3, max_depth=2, nbins=16, seed=1).train(fr)
    _check_pred(m, fr)


def test_smoke_isolation_forest(cl, num_fr):
    from h2o3_tpu.models import IsolationForest
    m = IsolationForest(ntrees=3, seed=1).train(num_fr)
    _check_pred(m, num_fr)


def test_smoke_ext_isolation_forest(cl, num_fr):
    from h2o3_tpu.models import ExtendedIsolationForest
    m = ExtendedIsolationForest(ntrees=3, seed=1).train(num_fr)
    _check_pred(m, num_fr)


def test_smoke_deeplearning(cl, bin_fr):
    from h2o3_tpu.models import DeepLearning
    m = DeepLearning(response_column="y", hidden=[8], epochs=2,
                     seed=1).train(bin_fr)
    _check_pred(m, bin_fr)


def test_smoke_deeplearning_autoencoder(cl, num_fr):
    from h2o3_tpu.models import DeepLearning
    m = DeepLearning(autoencoder=True, hidden=[3], epochs=2,
                     seed=1).train(num_fr)
    _check_pred(m, num_fr)


def test_smoke_glm(cl, reg_fr):
    from h2o3_tpu.models import GLM
    m = GLM(response_column="y", family="gaussian", lambda_=0.0).train(reg_fr)
    _check_pred(m, reg_fr)


def test_smoke_gam(cl, reg_fr):
    from h2o3_tpu.models import GAM
    m = GAM(response_column="y", gam_columns=["x0"],
            family="gaussian").train(reg_fr)
    _check_pred(m, reg_fr)


def test_smoke_anovaglm(cl):
    from h2o3_tpu.models import ANOVAGLM
    rng = np.random.default_rng(11)
    a = rng.integers(0, 2, N)
    b = rng.integers(0, 3, N)
    y = a * 1.0 + b * 0.5 + 0.2 * rng.normal(size=N)
    fr = Frame.from_numpy({
        "a": np.array(["a0", "a1"], dtype=object)[a],
        "b": np.array(["b0", "b1", "b2"], dtype=object)[b], "y": y})
    m = ANOVAGLM(response_column="y", family="gaussian").train(fr)
    assert "anova_table" in m.output or m.output


def test_smoke_modelselection(cl, reg_fr):
    from h2o3_tpu.models import ModelSelection
    m = ModelSelection(response_column="y", mode="forward",
                       max_predictor_number=2).train(reg_fr)
    assert m.output


def test_smoke_coxph(cl):
    from h2o3_tpu.models import CoxPH
    rng = np.random.default_rng(12)
    x = rng.normal(size=N)
    t = rng.exponential(1.0 / np.exp(0.5 * x))
    fr = Frame.from_numpy({"x": x, "time": t,
                           "event": np.ones(N)})
    m = CoxPH(stop_column="time", event_column="event",
              standardize=False).train(fr)
    assert "coef" in m.output


def test_smoke_kmeans(cl, num_fr):
    from h2o3_tpu.models import KMeans
    m = KMeans(k=3, seed=1).train(num_fr)
    _check_pred(m, num_fr)


def test_smoke_pca(cl, num_fr):
    from h2o3_tpu.models import PCA
    m = PCA(k=2).train(num_fr)
    _check_pred(m, num_fr)


def test_smoke_svd(cl, num_fr):
    from h2o3_tpu.models import SVD
    m = SVD(nv=2).train(num_fr)
    assert m.output


def test_smoke_glrm(cl, num_fr):
    from h2o3_tpu.models import GLRM
    m = GLRM(k=2, max_iterations=5, seed=1).train(num_fr)
    xfr = m.transform(num_fr)
    assert xfr.nrows == num_fr.nrows


def test_smoke_naive_bayes(cl, bin_fr):
    from h2o3_tpu.models import NaiveBayes
    m = NaiveBayes(response_column="y").train(bin_fr)
    _check_pred(m, bin_fr)


def test_smoke_psvm(cl, bin_fr):
    from h2o3_tpu.models import PSVM
    m = PSVM(response_column="y", max_iterations=10, seed=1).train(bin_fr)
    _check_pred(m, bin_fr)


def test_smoke_rulefit(cl, bin_fr):
    from h2o3_tpu.models import RuleFit
    m = RuleFit(response_column="y", rule_generation_ntrees=2,
                max_rule_length=2, seed=1).train(bin_fr)
    _check_pred(m, bin_fr)


def test_smoke_isotonic(cl):
    from h2o3_tpu.models import IsotonicRegression
    rng = np.random.default_rng(13)
    x = rng.uniform(-2, 2, N)
    y = np.tanh(x) + 0.2 * rng.normal(size=N)
    fr = Frame.from_numpy({"x": x, "y": y})
    m = IsotonicRegression(response_column="y").train(fr)
    _check_pred(m, fr)


def test_smoke_adaboost(cl, bin_fr):
    from h2o3_tpu.models import AdaBoost
    m = AdaBoost(response_column="y", nlearners=3, seed=1).train(bin_fr)
    _check_pred(m, bin_fr)


def test_smoke_word2vec(cl):
    from h2o3_tpu.models import Word2Vec
    rng = np.random.default_rng(14)
    vocab = ["cat", "dog", "car", "road"]
    words = [vocab[i] for i in rng.integers(0, 4, 600)]
    fr = Frame.from_numpy({"w": np.array(words, dtype=object)},
                          types={"w": "str"})
    m = Word2Vec(vec_size=4, epochs=2, min_word_freq=1, seed=1).train(fr)
    assert m.output["vocab_size"] == 4


def test_smoke_stacked_ensemble(cl, bin_fr):
    from h2o3_tpu.models import GBM, GLM, StackedEnsemble
    common = dict(response_column="y", nfolds=3, seed=1,
                  keep_cross_validation_predictions=True)
    g1 = GBM(ntrees=2, max_depth=2, nbins=16, **common).train(bin_fr)
    g2 = GLM(family="binomial", lambda_=1e-4, **common).train(bin_fr)
    se = StackedEnsemble(response_column="y",
                         base_models=[g1.key, g2.key]).train(bin_fr)
    _check_pred(se, bin_fr)


def test_smoke_aggregator(cl, num_fr):
    from h2o3_tpu.models import Aggregator
    m = Aggregator(target_num_exemplars=20, seed=1).train(num_fr)
    assert m.aggregated_frame.nrows <= 20


def test_smoke_target_encoder(cl):
    from h2o3_tpu.models import TargetEncoder
    rng = np.random.default_rng(15)
    g = rng.integers(0, 4, N)
    fr = Frame.from_numpy({
        "c": np.array([f"l{i}" for i in range(4)], dtype=object)[g],
        "y": g + 0.1 * rng.normal(size=N)})
    te = TargetEncoder(response_column="y").train(fr)
    assert "c_te" in te.transform(fr).names


def test_smoke_quantile(cl, num_fr):
    from h2o3_tpu.models import Quantile
    m = Quantile(probs=(0.25, 0.5, 0.75)).train(num_fr)
    assert len(m.output["quantiles"]["x0"]) == 3


def test_smoke_grep(cl, tmp_path):
    from h2o3_tpu.models import Grep
    p = tmp_path / "log.txt"
    p.write_text("ok\nERROR one\nok\nERROR two\n")
    m = Grep(regex="ERROR \\w+").train_on_path(str(p))
    assert m.output["n_matches"] == 2


def test_smoke_infogram(cl, bin_fr):
    from h2o3_tpu.models import Infogram
    m = Infogram(response_column="y", algorithm="glm").train(bin_fr)
    assert m.output


def test_smoke_generic_mojo_roundtrip(cl, reg_fr, tmp_path):
    """Generic model: re-import an exported artifact and score."""
    import h2o3_tpu
    from h2o3_tpu.models import GBM
    m = GBM(response_column="y", ntrees=3, max_depth=2, nbins=16, seed=1).train(reg_fr)
    path = m.download_mojo(str(tmp_path / "m.zip"))
    sm = h2o3_tpu.import_mojo(path)
    out = sm.predict({f"x{j}": reg_fr.vec(f"x{j}").to_numpy()
                      for j in range(4)})
    ref = m.predict(reg_fr).vecs[0].to_numpy()
    np.testing.assert_allclose(out["predict"], ref, atol=5e-4)
