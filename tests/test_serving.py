"""Online scoring plane: bitpacked traversal + micro-batcher + REST.

Parity strategy mirrors test_mojo: train real models in the cluster,
extract the portable arrays, and require the packed device program to
reproduce the numpy ``ScoringModel`` scores (which test_mojo already
pins to in-cluster ``Model.predict``) — including NA rows, categorical
splits, multinomial class groups and the isolation-forest path.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.export import mojo
from h2o3_tpu.export.scoring import ScoringModel
from h2o3_tpu.models import GBM, DRF, XGBoost, IsolationForest
from h2o3_tpu.serving import pack
from h2o3_tpu.serving.batcher import MicroBatcher
from h2o3_tpu.serving.kernel import PackedScorer


# ------------------------------------------------------------- pack unit

def _random_heap_group(rng, T, depth, F, full=False):
    """Synthetic heap-layout trees in the mojo export format."""
    arrays = {"values": rng.normal(size=(T, 2 ** depth))
              .astype(np.float32)}
    for d in range(depth):
        w = 2 ** d
        arrays[f"feat_{d}"] = rng.integers(0, F, (T, w))
        arrays[f"thr_{d}"] = rng.normal(size=(T, w)).astype(np.float32)
        arrays[f"na_left_{d}"] = rng.integers(0, 2, (T, w)).astype(bool)
        arrays[f"valid_{d}"] = (np.ones((T, w), dtype=bool) if full
                                else rng.random((T, w)) < 0.8)
    return arrays


def _heap_walk(arrays, depth, X):
    """Brute-force per-row heap descent (the pre-PR-11 semantics)."""
    n, T = X.shape[0], arrays["values"].shape[0]
    out = np.zeros((n, T), dtype=np.float32)
    for r in range(n):
        for t in range(T):
            i = 0
            for d in range(depth):
                if not arrays[f"valid_{d}"][t, i]:
                    break
                x = X[r, arrays[f"feat_{d}"][t, i]]
                if np.isnan(x):
                    right = not arrays[f"na_left_{d}"][t, i]
                else:
                    right = x >= arrays[f"thr_{d}"][t, i]
                i = 2 * i + int(right)
            else:
                d = depth
            out[r, t] = arrays["values"][t, i << (depth - d)]
    return out


@pytest.mark.parametrize("depth,full", [(0, True), (1, True), (3, False),
                                        (6, False), (9, False)])
def test_pack_traverse_matches_heap_walk(rng, depth, full):
    T, F, n = 7, 5, 40
    arrays = _random_heap_group(rng, T, depth, F, full=full)
    X = rng.normal(size=(n, F)).astype(np.float32)
    X[rng.random((n, F)) < 0.15] = np.nan
    i32, f32, roots = pack.pack_group(arrays, depth)
    got = pack.traverse(i32, f32, roots, X, depth)
    np.testing.assert_array_equal(got, _heap_walk(arrays, depth, X))


def test_pack_layout_invariants(rng):
    arrays = _random_heap_group(rng, 4, 5, 8)
    i32, f32, roots = pack.pack_group(arrays, 5)
    assert i32.dtype == np.int32 and f32.dtype == np.float32
    assert roots.shape == (4,) and roots[0] == 0
    leaf = (i32 >> pack.LEAF_BIT) & 1
    # every tree ends in at least one leaf; both children stay in-bounds
    delta = (i32.astype(np.int64) >> pack.DELTA_SHIFT) & pack.DELTA_MASK
    child = np.arange(i32.shape[0]) + delta
    assert (child[leaf == 0] + 1 < i32.shape[0]).all()
    assert (delta[leaf == 0] > 0).all()
    assert leaf.sum() >= 4


def test_pack_feature_id_overflow_rejected(rng):
    arrays = _random_heap_group(rng, 1, 1, 2, full=True)
    arrays["feat_0"] = np.full((1, 1), pack.MAX_FEATURES)
    with pytest.raises(ValueError, match="feature ids"):
        pack.pack_group(arrays, 1)


# -------------------------------------------------- trained-model parity

def _frames(rng, n=600):
    X = rng.normal(size=(n, 3))
    cat = np.array(["u", "v", "w"], dtype=object)[rng.integers(0, 3, n)]
    y_num = X @ [1.0, -2.0, 0.5] + (cat == "v") * 1.5 \
        + 0.1 * rng.normal(size=n)
    y_bin = np.where(y_num > 0, "yes", "no").astype(object)
    cols = {"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2], "c": cat}
    return (Frame.from_numpy({**cols, "y": y_num}),
            Frame.from_numpy({**cols, "y": y_bin}), dict(cols))


def _scorer(model):
    meta, arrays = mojo._extract(model)
    return PackedScorer(ScoringModel(meta, arrays))


def _na_rows(data, rng, k=40):
    """Row dicts from the training columns, with missing cells."""
    n = len(next(iter(data.values())))
    rows = []
    for i in rng.integers(0, n, k):
        row = {c: (v[i].item() if hasattr(v[i], "item") else v[i])
               for c, v in data.items()}
        drop = rng.choice(list(data), rng.integers(0, 3), replace=False)
        for c in drop:
            row.pop(c)
        rows.append(row)
    return rows


def _cols_from_rows(rows, names):
    """Row dicts -> column dict the way featurize fills missing cells."""
    cols = {}
    for c in names:
        vals = [r.get(c) for r in rows]
        if any(isinstance(v, str) for v in vals):
            cols[c] = np.asarray(["" if v is None else v for v in vals],
                                 dtype=object)
        else:
            cols[c] = np.asarray([np.nan if v is None else v for v in vals],
                                 dtype=float)
    return cols


def _assert_parity(model, data, rng, classifier=True):
    ps = _scorer(model)
    rows = _na_rows(data, rng)
    X = ps.featurize(rows)
    # check mode raises on any packed-vs-ref divergence
    probs = ps.score(X, score_mode="check")
    # and the ref path IS the deployed numpy scorer
    sm_out = ps.ref.predict(_cols_from_rows(rows, list(data)))
    out = ps.predict_rows(rows)
    if classifier:
        np.testing.assert_allclose(probs, sm_out["probabilities"],
                                   rtol=1e-4, atol=1e-5)
        assert (out["predict"] == sm_out["predict"]).all()
    else:
        np.testing.assert_allclose(out["predict"], sm_out["predict"],
                                   rtol=1e-4, atol=1e-5)
    return ps


def test_packed_parity_gbm_binomial(cl, rng):
    _, fr_bin, data = _frames(rng)
    m = GBM(response_column="y", ntrees=8, seed=1).train(fr_bin)
    ps = _assert_parity(m, data, rng)
    assert ps.binomial and ps.n_class == 1


def test_packed_parity_gbm_regression(cl, rng):
    fr_num, _, data = _frames(rng)
    m = GBM(response_column="y", ntrees=6, seed=1).train(fr_num)
    _assert_parity(m, data, rng, classifier=False)


def test_packed_parity_gbm_multinomial(cl, rng):
    n = 400
    X = rng.normal(size=(n, 3))
    cls = np.argmax(X + 0.2 * rng.normal(size=(n, 3)), axis=1)
    data = {f"x{j}": X[:, j] for j in range(3)}
    fr = Frame.from_numpy({**data, "y": np.array(
        ["a", "b", "c"], dtype=object)[cls]})
    m = GBM(response_column="y", ntrees=5, seed=1).train(fr)
    ps = _assert_parity(m, data, rng)
    assert ps.n_class == 3


def test_packed_parity_drf(cl, rng):
    _, fr_bin, data = _frames(rng)
    m = DRF(response_column="y", ntrees=8, seed=1, max_depth=6).train(fr_bin)
    ps = _assert_parity(m, data, rng)
    assert ps.avg          # DRF averages, it does not boost


def test_packed_parity_xgboost(cl, rng):
    _, fr_bin, data = _frames(rng)
    m = XGBoost(response_column="y", ntrees=8, seed=1).train(fr_bin)
    _assert_parity(m, data, rng)


def test_packed_parity_isolation_forest(cl, rng):
    n = 400
    data = {"a": rng.normal(size=n), "b": rng.normal(size=n)}
    m = IsolationForest(ntrees=10, seed=2).train(Frame.from_numpy(data))
    ps = _assert_parity(m, data, rng, classifier=False)
    assert ps.family == "isolation"


def test_score_mode_knob_and_ref(cl, rng):
    _, fr_bin, data = _frames(rng)
    m = GBM(response_column="y", ntrees=5, seed=1).train(fr_bin)
    ps = _scorer(m)
    X = ps.featurize(_na_rows(data, rng, k=16))
    np.testing.assert_allclose(ps.score(X, score_mode="packed"),
                               ps.score(X, score_mode="ref"),
                               rtol=1e-4, atol=1e-5)
    with pytest.raises(ValueError, match="score_mode"):
        ps.score(X, score_mode="bogus")


def test_pallas_interpret_impl_matches(cl, rng):
    _, fr_bin, data = _frames(rng)
    m = GBM(response_column="y", ntrees=5, seed=1).train(fr_bin)
    meta, arrays = mojo._extract(m)
    sm = ScoringModel(meta, arrays)
    xla = PackedScorer(sm, impl="xla")
    pli = PackedScorer(sm, impl="pallas_interpret")
    X = xla.featurize(_na_rows(data, rng, k=32))
    np.testing.assert_allclose(pli.score(X), xla.score(X),
                               rtol=1e-5, atol=1e-6)


def test_scoring_model_iterative_traverse(cl, rng):
    """export/scoring.py now routes _traverse through the packed walk;
    the portable predict must keep matching in-cluster predict."""
    _, fr_bin, data = _frames(rng)
    m = GBM(response_column="y", ntrees=8, seed=1).train(fr_bin)
    meta, arrays = mojo._extract(m)
    sm = ScoringModel(meta, arrays)
    out = sm.predict(data)
    pred = m.predict(fr_bin)
    probs = np.stack([v.to_numpy() for v in pred.vecs[1:]], axis=1)
    np.testing.assert_allclose(out["probabilities"], probs, atol=2e-4)
    assert "_pack_cache" in sm.__dict__      # iterative walk engaged


# --------------------------------------------------------- micro-batcher

def test_microbatcher_concurrent_demux(cl, rng):
    _, fr_bin, data = _frames(rng)
    m = GBM(response_column="y", ntrees=5, seed=1).train(fr_bin)
    ps = _scorer(m)
    mb = MicroBatcher(ps, max_batch=32, tick_ms=2.0, queue_depth=4096)
    try:
        assert mb.warmup() > 0
        X = ps.featurize(_na_rows(data, rng, k=64))
        want = ps.score(X)
        outs = [None] * 16
        errs = []

        def client(i):
            lo, hi = 4 * i, 4 * i + 4
            try:
                outs[i] = mb.submit(X[lo:hi])
            except Exception as e:           # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=client, args=(i,)) for i in range(16)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert not errs
        got = np.concatenate(outs)
        np.testing.assert_allclose(got, want[:64], rtol=1e-5, atol=1e-6)
        # wide requests chunk through the same queue
        np.testing.assert_allclose(mb.submit(X), want, rtol=1e-5, atol=1e-6)
    finally:
        mb.close()


def test_microbatcher_queue_overflow(cl, rng):
    _, fr_bin, data = _frames(rng)
    m = GBM(response_column="y", ntrees=3, seed=1).train(fr_bin)
    ps = _scorer(m)
    mb = MicroBatcher(ps, max_batch=8, tick_ms=500.0, queue_depth=8)
    try:
        X = ps.featurize(_na_rows(data, rng, k=8))

        def fill():
            try:
                mb.submit(X)
            except RuntimeError:
                pass                       # close() errors the leftover

        done = threading.Thread(target=fill, daemon=True)
        done.start()                       # fills the queue for a while
        import time
        time.sleep(0.05)
        with pytest.raises(RuntimeError, match="queue full"):
            mb.submit(X)
    finally:
        mb.close()


def test_microbatcher_deadline_sheds(cl, rng):
    """A request that waits past H2O3_TPU_SERVE_DEADLINE_MS is shed at
    drain time (counted, never dispatched), not scored late."""
    from h2o3_tpu.runtime import observability as obs
    from h2o3_tpu.serving.batcher import DeadlineExceeded
    _, fr_bin, data = _frames(rng)
    m = GBM(response_column="y", ntrees=3, seed=1).train(fr_bin)
    ps = _scorer(m)
    # the tick lands the first drain well past the 50 ms deadline
    mb = MicroBatcher(ps, max_batch=8, tick_ms=300.0, queue_depth=64,
                      deadline_ms=50.0)
    try:
        before = obs.counter("serve_rejected_total",
                             reason="deadline").value
        X = ps.featurize(_na_rows(data, rng, k=2))
        with pytest.raises(DeadlineExceeded, match="deadline"):
            mb.submit(X)
        if obs.enabled():
            assert obs.counter("serve_rejected_total",
                               reason="deadline").value > before
    finally:
        mb.close()


def test_microbatcher_close_sheds_expired(cl, rng):
    """SIGTERM drain: close() sheds already-expired requests as deadline
    rejections instead of erroring them as a plain shutdown."""
    from h2o3_tpu.serving.batcher import DeadlineExceeded
    _, fr_bin, data = _frames(rng)
    m = GBM(response_column="y", ntrees=3, seed=1).train(fr_bin)
    ps = _scorer(m)
    mb = MicroBatcher(ps, max_batch=8, tick_ms=500.0, queue_depth=64,
                      deadline_ms=30.0)
    X = ps.featurize(_na_rows(data, rng, k=2))
    errs = []

    def client():
        try:
            mb.submit(X)
        except BaseException as e:           # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=client, daemon=True)
    t.start()
    import time
    time.sleep(0.1)                          # stale by close time
    mb.close()
    t.join(timeout=10)
    assert len(errs) == 1
    assert isinstance(errs[0], DeadlineExceeded)


def test_rest_deadline_returns_503(cl, rng):
    """The REST layer maps a shed request to HTTP 503 so clients retry
    elsewhere instead of treating it as a bad request."""
    from h2o3_tpu.api import start_server
    from h2o3_tpu import serving
    _, fr_bin, data = _frames(rng)
    m = GBM(response_column="y", ntrees=3, seed=1).train(fr_bin)
    s = start_server(port=0)
    try:
        ent = serving.ensure_published(m.key)
        ent.batcher.warmup()
        ent.batcher.tick_s = 0.3             # drain lands past...
        ent.batcher.deadline_s = 0.02        # ...a 20 ms deadline
        rows = _na_rows(data, rng, k=2)
        req = urllib.request.Request(
            s.url + f"/3/Predictions/realtime/{m.key}",
            data=json.dumps({"rows": rows}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 503
    finally:
        serving.shutdown_all()
        s.stop()


def test_microbatcher_close_errors_waiters(cl, rng):
    _, fr_bin, data = _frames(rng)
    m = GBM(response_column="y", ntrees=3, seed=1).train(fr_bin)
    ps = _scorer(m)
    mb = MicroBatcher(ps, max_batch=8, tick_ms=0.0, queue_depth=64)
    mb.close()
    with pytest.raises(RuntimeError, match="shut down"):
        mb.submit(ps.featurize(_na_rows(data, rng, k=2)))


# ---------------------------------------------------------------- REST

def test_rest_realtime_roundtrip(cl, rng):
    from h2o3_tpu.api import start_server
    from h2o3_tpu import serving
    _, fr_bin, data = _frames(rng)
    m = GBM(response_column="y", ntrees=5, seed=1).train(fr_bin)
    s = start_server(port=0)
    try:
        def post(path, payload):
            req = urllib.request.Request(
                s.url + path, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read())

        out = post(f"/3/Predictions/realtime/{m.key}/warmup", {})
        assert out["published"] and out["n_nodes"] > 0
        assert out["warmup_seconds"] > 0
        rows = _na_rows(data, rng, k=3)
        out = post(f"/3/Predictions/realtime/{m.key}", {"rows": rows})
        assert len(out["predictions"]) == 3
        for p in out["predictions"]:
            assert p["predict"] in ("yes", "no")
            assert abs(sum(p["probabilities"]) - 1.0) < 1e-5
        # single-row body + check-mode parity drill over REST
        out = post(f"/3/Predictions/realtime/{m.key}",
                   {"row": rows[0], "score_mode": "check"})
        assert out["predictions"][0]["predict"] in ("yes", "no")
        # unknown model -> 404
        with pytest.raises(urllib.error.HTTPError) as e:
            post("/3/Predictions/realtime/not_a_model", {"rows": rows})
        assert e.value.code == 404
    finally:
        serving.shutdown_all()
        s.stop()


@pytest.mark.heavy
def test_deploy_serve_sigterm_drains_realtime(cl, rng, tmp_path):
    """SIGTERM mid-request: the in-flight realtime prediction completes
    (REST drain + batcher shutdown) and the launcher exits 0.

    heavy: boots a full second interpreter + jax runtime (up to 90 s)."""
    import os
    import signal
    import subprocess
    import sys
    import time
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # a long tick guarantees the request is still queued when SIGTERM lands
    env["H2O3_TPU_SERVE_TICK_MS"] = "1500"
    port = "54397"
    p = subprocess.Popen(
        [sys.executable, "-m", "h2o3_tpu.deploy.serve", "--port", port],
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        base = f"http://127.0.0.1:{port}"
        for _ in range(90):
            time.sleep(1)
            try:
                out = json.load(urllib.request.urlopen(
                    base + "/3/Cloud", timeout=2))
                assert out["cloud_healthy"]
                break
            except AssertionError:
                raise
            except Exception:
                continue
        else:
            raise AssertionError("launcher never served /3/Cloud")

        def post(path, payload, timeout=60):
            req = urllib.request.Request(
                base + path, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return json.loads(r.read())

        n = 200
        X = rng.normal(size=(n, 2))
        csv = tmp_path / "serve.csv"
        with open(csv, "w") as f:
            f.write("a,b,y\n")
            for i in range(n):
                f.write(f"{X[i,0]},{X[i,1]},"
                        f"{'yes' if X[i,0] > 0 else 'no'}\n")
        post("/3/Parse", {"path": str(csv),
                          "destination_frame": "serve_train"})
        out = post("/3/ModelBuilders/gbm",
                   {"training_frame": "serve_train",
                    "response_column": "y", "ntrees": 3, "seed": 1})
        key = out["job"]["dest"]["name"]
        post(f"/3/Predictions/realtime/{key}/warmup", {})

        result = {}

        def inflight():
            result["out"] = post(f"/3/Predictions/realtime/{key}",
                                 {"row": {"a": 0.5, "b": -0.2}})

        t = threading.Thread(target=inflight)
        t.start()
        time.sleep(0.3)            # request sits in the 1.5 s tick window
        p.send_signal(signal.SIGTERM)
        t.join(timeout=30)
        assert not t.is_alive(), "in-flight request never completed"
        assert result["out"]["predictions"][0]["predict"] in ("yes", "no")
        assert p.wait(timeout=20) == 0
        log = p.stdout.read().decode()
        assert "h2o3_tpu REST drained" in log
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()


def test_publish_journal_survives_coordinator_restart(cl, rng, tmp_path,
                                                      monkeypatch):
    """A journaled publish (`!serve/` record + saved artifact) brings the
    serving plane back after a coordinator restart: the registry is wiped
    AND the model is gone from the DKV, yet ``republish_journaled()``
    reloads the artifact and scoring output is unchanged."""
    monkeypatch.setenv("H2O3_TPU_RECOVERY_DIR", str(tmp_path))
    from h2o3_tpu import serving
    from h2o3_tpu.runtime import dkv
    from h2o3_tpu.serving import batcher
    _, fr_bin, data = _frames(rng)
    m = GBM(response_column="y", ntrees=6, seed=1).train(fr_bin)
    rows = _na_rows(data, rng, k=12)
    try:
        ent = batcher.publish(m.key, m, warm=False)
        ref = ent.predict_rows(rows)
        rec = dkv.get(batcher.SERVE_PREFIX + m.key)
        assert rec and rec["uri"].endswith(".model") and rec["warm"] is False

        # "restart": serving registry cleared and the model lost with it
        serving.shutdown_all()
        dkv.remove(m.key)
        assert batcher.republish_journaled() == [m.key]
        assert dkv.get(m.key) is not None      # Model.load re-registered it

        out = batcher.ensure_published(m.key).predict_rows(rows)
        assert (out["predict"] == ref["predict"]).all()
        np.testing.assert_allclose(out["probabilities"],
                                   ref["probabilities"], rtol=1e-5)
        # idempotent: everything already live
        assert batcher.republish_journaled() == []
        # unpublish retracts the journal so the model stays retired
        assert batcher.unpublish(m.key)
        assert dkv.get(batcher.SERVE_PREFIX + m.key) is None
        assert batcher.republish_journaled() == []
    finally:
        serving.shutdown_all()
        dkv.remove(batcher.SERVE_PREFIX + m.key)
        dkv.remove(m.key)
