"""Node-sparse deep-level layout: parity + regression pins.

Past the depth threshold the builder switches from the dense [2^d, F, B]
histogram grid to [A, F, B] slots keyed by ALIVE leaves
(hist.make_sparse_level_fn).  These tests pin (a) bit-identity of the
sparse kernel against the dense subtraction kernel when the slot map is
the identity, (b) the varbin inner kernel through the sparse body,
(c) dense-vs-sparse whole-tree parity through shared.make_build_tree_fn
under NA / skew / col-sampling / batched-K / dead-chain shapes including
the one-alive-leaf-at-depth-10 extreme, (d) the slot-assignment math
(atomic pair drop on overflow, determinism), (e) the dispatch-count pin
— 2 pallas launches per sparse level (hist + fused records), and
(f) driver-level parity: GBM / DRF / XGBoost / UpliftDRF grow IDENTICAL
trees through hist_layout="sparse" and the dense oracle, with
hist_layout="check" asserting it in-driver on the first tree.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from h2o3_tpu.models.tree import hist, shared
from h2o3_tpu.models.tree.hist import (fused_best_splits,
                                       make_hist_fn,
                                       make_sparse_level_fn,
                                       make_subtract_level_fn,
                                       offset_codes, sparse_slot_maps)


def _chain_leaves(rng, N, depth, p_right=0.3):
    """Consistent leaf assignments per level (child of previous level)."""
    leaves = [np.zeros(N, np.int64)]
    for _ in range(1, depth):
        bit = (rng.random(N) < p_right).astype(np.int64)
        leaves.append(2 * leaves[-1] + bit)
    return leaves


# --------------------------------------------------------------- kernel layer

def test_sparse_level_identity_bit_parity(cl, rng):
    """All parents valid and A = 2^d makes the slot map the identity; the
    sparse level must then be BIT-identical to the dense subtraction
    level — histogram and per-shard carry both (same compaction prefix,
    same subtraction order)."""
    N, F, nbins, depth = 2048, 5, 16, 4
    B = nbins + 1
    codes = jnp.asarray(rng.integers(0, B, (F, N)), jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.asarray(rng.random(N), jnp.float32)
    w = jnp.asarray((rng.random(N) > 0.15), jnp.float32)
    leaves = _chain_leaves(rng, N, depth)
    _, carry = make_subtract_level_fn(0, F, B, N)(
        codes, jnp.zeros(N, jnp.int32), g, h, w)
    for d in range(1, depth):
        leaf = jnp.asarray(leaves[d], jnp.int32)
        A_prev, A = 2 ** (d - 1), 2 ** d
        Hd, carry_d = make_subtract_level_fn(d, F, B, N)(
            codes, leaf, g, h, w, carry)
        ps = jnp.arange(A, dtype=jnp.int32) // 2
        Hs, carry_s = make_sparse_level_fn(A_prev, A, F, B, N)(
            codes, leaf, g, h, w, carry, ps)
        np.testing.assert_array_equal(np.asarray(Hs), np.asarray(Hd))
        np.testing.assert_array_equal(np.asarray(carry_s),
                                      np.asarray(carry_d))
        carry = carry_d


def test_sparse_level_varbin_parity(cl, rng):
    """The varbin (packed ragged bins, interpret Pallas) inner kernel
    through the sparse body == dense einsum full build at the identity
    slot map — the categorical-feature path below the depth threshold."""
    N, F, nbins = 2048, 5, 32
    B = nbins + 1
    bin_counts = (7, 32, 22, 3, 32)
    codes_np = np.stack([
        np.where(rng.random(N) < 0.1, nbins, rng.integers(0, bc, N))
        for bc in bin_counts])
    codes = jnp.asarray(codes_np, jnp.int32)
    gcodes = offset_codes(codes, bin_counts, nbins)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.ones(N, jnp.float32)
    w = jnp.asarray((rng.random(N) > 0.1), jnp.float32)
    leaves = _chain_leaves(rng, N, 3)
    _, carry = make_subtract_level_fn(
        0, F, B, N, bin_counts=bin_counts, force_impl="pallas_interpret",
        precision="f32")(gcodes, jnp.zeros(N, jnp.int32), g, h, w)
    for d in (1, 2):
        leaf = jnp.asarray(leaves[d], jnp.int32)
        ps = jnp.arange(2 ** d, dtype=jnp.int32) // 2
        Hs, carry = make_sparse_level_fn(
            2 ** (d - 1), 2 ** d, F, B, N, bin_counts=bin_counts,
            force_impl="pallas_interpret", precision="f32")(
                gcodes, leaf, g, h, w, carry, ps)
        Hf = make_hist_fn(2 ** d, F, B, N, force_impl="einsum")(
            codes, leaf, g, h, w)
        np.testing.assert_allclose(np.asarray(Hs), np.asarray(Hf),
                                   atol=1e-4, rtol=1e-5)


def test_sparse_slot_maps_overflow_atomic(cl):
    """More alive children than slots: later pairs are dropped ATOMICALLY
    in slot order (both children or neither), dropped parents read the
    A_next sentinel in child_base, phantom slots are masked off by
    ``real`` — and the assignment is deterministic."""
    valid = np.ones(16, bool)
    valid[[2, 5, 11, 13]] = False                       # 12 alive parents
    out1 = jax.device_get(sparse_slot_maps(jnp.asarray(valid), 16))
    out2 = jax.device_get(sparse_slot_maps(jnp.asarray(valid), 16))
    for a, b in zip(out1, out2):
        np.testing.assert_array_equal(a, b)             # deterministic
    child_base, ps_of_slot, real = out1
    kept_parents = np.flatnonzero(valid)[:8]            # 8 pairs fit in 16
    rank = 0
    for p in range(16):
        if p in kept_parents:
            assert child_base[p] == 2 * rank
            assert ps_of_slot[2 * rank] == p
            assert ps_of_slot[2 * rank + 1] == p
            rank += 1
        else:
            assert child_base[p] == 16                  # dropped/invalid
    assert child_base[16] == 16                         # sentinel row
    assert real.all()                                   # 8 pairs fill 16
    # head-room case: the same parents with A_next=32 keep ALL 12 pairs
    # and the phantom tail is masked off
    child_base, ps_of_slot, real = jax.device_get(
        sparse_slot_maps(jnp.asarray(valid), 32))
    assert (child_base[np.flatnonzero(valid)] < 32).all()
    np.testing.assert_array_equal(real, np.arange(32) < 24)


def test_sparse_level_dispatch_count(cl):
    """The deep-level pin: one sparse histogram launch + one fused
    split-records launch per level — 2 pallas_calls, independent of how
    many leaves are alive."""
    Ap, A, F, nbins, N = 8, 16, 4, 16, 2048
    B = nbins + 1
    lev = make_sparse_level_fn(Ap, A, F, B, N, bin_counts=(nbins,) * F,
                               force_impl="pallas_interpret")

    def level(codes, sleaf, g, h, w, carry, ps):
        H, carry2 = lev(codes, sleaf, g, h, w, carry, ps)
        return fused_best_splits(H, nbins, 1.0, 1.0, 1e-5,
                                 force_impl="pallas"), carry2

    codes = jnp.zeros((F, N), jnp.int32)
    sleaf = jnp.zeros(N, jnp.int32)
    g = jnp.zeros(N, jnp.float32)
    carry = jnp.zeros((cl.n_row_shards, 3, Ap, F, B), jnp.float32)
    ps = jnp.arange(A, dtype=jnp.int32) // 2
    jaxpr = str(jax.make_jaxpr(level)(codes, sleaf, g, g, g, carry, ps))
    assert jaxpr.count("pallas_call") == 2


# ------------------------------------------------------------- build-tree fns

def _compare_builds(outs, md):
    """Dense-vs-sparse build parity: valid + routing exact, feat/na exact
    where valid (dense keeps candidate records on dead slots, sparse
    drops them), thresholds/values f32-close."""
    lv_d, v_d, leaf_d = outs["dense"]
    lv_s, v_s, leaf_s = outs["sparse"]
    for d in range(md):
        vd = np.asarray(lv_d[d][3], bool)
        vs = np.asarray(lv_s[d][3], bool)
        np.testing.assert_array_equal(vd, vs, err_msg=f"valid, level {d}")
        for name, i in (("feat", 0), ("na", 2)):
            a, b = np.asarray(lv_d[d][i]), np.asarray(lv_s[d][i])
            np.testing.assert_array_equal(a[vd], b[vd],
                                          err_msg=f"{name}, level {d}")
        a, b = np.asarray(lv_d[d][1]), np.asarray(lv_s[d][1])
        np.testing.assert_allclose(a[vd], b[vd], atol=1e-5, rtol=1e-5,
                                   err_msg=f"thr, level {d}")
    np.testing.assert_array_equal(np.asarray(leaf_d), np.asarray(leaf_s))
    np.testing.assert_allclose(np.asarray(v_d), np.asarray(v_s),
                               atol=1e-4, rtol=1e-4)


def _skewed_inputs(rng, F, N, nbins):
    base = rng.integers(0, nbins, size=(F, N))
    base[:, : N // 2] = 3                 # half the rows identical -> skew
    base[0, rng.integers(0, N, size=100)] = nbins            # NAs
    codes = jnp.asarray(base, jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.ones(N, jnp.float32)
    w = jnp.asarray((rng.random(N) > 0.1).astype(np.float32))
    edges = jnp.asarray(rng.normal(size=(F, nbins)).cumsum(axis=1),
                        jnp.float32)
    return codes, g, h, w, edges


def test_build_tree_sparse_equals_dense(cl, rng):
    """Single tree, fused split search, column sampling, NAs, skewed
    codes: the sparse deep levels (threshold 3 of depth 7) grow the SAME
    tree as the dense grid."""
    F, N, nbins, md = 5, 2048, 16, 7
    codes, g, h, w, edges = _skewed_inputs(rng, F, N, nbins)
    key = jax.random.PRNGKey(7)
    tm = jnp.ones(F, bool)
    outs = {}
    for layout in ("dense", "sparse"):
        fn = shared.make_build_tree_fn(
            md, nbins, F, N, "f32", hist_mode="subtract",
            split_mode="fused", hist_layout=layout,
            sparse_depth_threshold=3)
        levels, vals, cover, leaf = fn(codes, g, h, w, edges, key, 0.5,
                                       2.0, 1e-5, 0.1, 0.7, tm, 0.1,
                                       0.01, 0.0)
        outs[layout] = jax.device_get([[list(l) for l in levels], vals,
                                       leaf])
    _compare_builds(outs, md)


def test_build_tree_sparse_batched_k3(cl, rng):
    """Batched K=3 trees through make_batched_sparse_level_fn: one
    launch per level for all K trees, same trees as the dense grid."""
    F, N, nbins, md, K = 5, 2048, 16, 7, 3
    codes, _, _, w, edges = _skewed_inputs(rng, F, N, nbins)
    gK = jnp.asarray(rng.normal(size=(K, N)), jnp.float32)
    hK = jnp.ones((K, N), jnp.float32)
    keysK = jax.vmap(jax.random.PRNGKey)(jnp.arange(K))
    tmK = jnp.ones((K, F), bool)
    outs = {}
    for layout in ("dense", "sparse"):
        fn = shared.make_build_tree_fn(
            md, nbins, F, N, "f32", hist_mode="subtract",
            split_mode="fused", nk=K, hist_layout=layout,
            sparse_depth_threshold=3)
        levels, vals, cover, leaf = fn(codes, gK, hK, w, edges, keysK,
                                       0.5, 2.0, 1e-5, 0.1, 0.7, tmK,
                                       0.1, 0.01, 0.0)
        outs[layout] = jax.device_get([[list(l) for l in levels], vals,
                                       leaf])
    _compare_builds(outs, md)


def test_build_tree_sparse_dead_chains(cl, rng):
    """Constant features kill the root's children immediately: every
    deeper sparse level runs with (almost) no live slots, and the dead
    chains must stay dead on both layouts (terminality invariant)."""
    F, N, nbins = 5, 2048, 16
    codes = jnp.asarray(np.full((F, N), 2, np.int16))
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.ones(N, jnp.float32)
    w = jnp.asarray((rng.random(N) > 0.1).astype(np.float32))
    edges = jnp.asarray(rng.normal(size=(F, nbins)).cumsum(axis=1),
                        jnp.float32)
    key = jax.random.PRNGKey(3)
    tm = jnp.ones(F, bool)
    outs = {}
    for layout in ("dense", "sparse"):
        fn = shared.make_build_tree_fn(
            5, nbins, F, N, "f32", hist_mode="subtract",
            split_mode="separate", hist_layout=layout,
            sparse_depth_threshold=2)
        levels, vals, cover, leaf = fn(codes, g, h, w, edges, key, 0.0,
                                       1.0, 1e-5, 0.1, 1.0, tm, 0.0, 0.0,
                                       0.0)
        outs[layout] = jax.device_get([[list(l) for l in levels], vals,
                                       leaf])
    _compare_builds(outs, 5)


def test_build_tree_one_alive_leaf_depth_10(cl, rng):
    """Extreme leaf-count skew: gradients grow geometrically with the
    bin, so every level peels bins off the top and only 1-2 of the up to
    2^d nodes stay alive all the way to depth 10 — the shape the sparse
    layout exists for.  Parity must hold and the alive count per deep
    level must stay O(1), not O(2^d)."""
    F, N, nbins, md = 2, 2048, 32, 10
    codes_np = np.stack([rng.integers(0, nbins, N),
                         np.full(N, 3)])              # 2nd feature constant
    codes = jnp.asarray(codes_np, jnp.int32)
    g = jnp.asarray(-(1.7 ** codes_np[0]) / 100.0, jnp.float32)
    h = jnp.ones(N, jnp.float32)
    w = jnp.ones(N, jnp.float32)
    edges = jnp.asarray(
        np.stack([np.arange(nbins, dtype=np.float64)] * F), jnp.float32)
    key = jax.random.PRNGKey(5)
    tm = jnp.ones(F, bool)
    outs = {}
    for layout in ("dense", "sparse"):
        fn = shared.make_build_tree_fn(
            md, nbins, F, N, "f32", hist_mode="subtract",
            split_mode="fused", hist_layout=layout,
            sparse_depth_threshold=2)
        levels, vals, cover, leaf = fn(codes, g, h, w, edges, key, 1.0,
                                       1.0, 1e-5, 0.1, 1.0, tm, 0.0, 0.0,
                                       0.0)
        outs[layout] = jax.device_get([[list(l) for l in levels], vals,
                                       leaf])
    _compare_builds(outs, md)
    for d in range(1, md):
        n_alive = int(np.asarray(outs["sparse"][0][d][3], bool).sum())
        assert 1 <= n_alive <= 2, (d, n_alive)


def test_build_tree_sparse_varbin(cl, rng, monkeypatch):
    """Categorical (ragged-bin) features through the sparse deep levels:
    H2O3_TPU_HIST_IMPL=varbin forces the packed interpret-Pallas inner
    kernel off-TPU; dense and sparse layouts must still agree."""
    monkeypatch.setenv("H2O3_TPU_HIST_IMPL", "varbin")
    F, N, nbins, md = 4, 2048, 32, 6
    bin_counts = (32, 32, 7, 5)
    codes_np = np.stack([
        np.where(rng.random(N) < 0.1, nbins, rng.integers(0, bc, N))
        for bc in bin_counts])
    codes = jnp.asarray(codes_np, jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.ones(N, jnp.float32)
    w = jnp.asarray((rng.random(N) > 0.1).astype(np.float32))
    edges = jnp.asarray(rng.normal(size=(F, nbins)).cumsum(axis=1),
                        jnp.float32)
    key = jax.random.PRNGKey(11)
    tm = jnp.ones(F, bool)
    outs = {}
    for layout in ("dense", "sparse"):
        fn = shared.make_build_tree_fn(
            md, nbins, F, N, "f32", bin_counts=bin_counts,
            hist_mode="subtract", split_mode="fused", hist_layout=layout,
            sparse_depth_threshold=3)
        levels, vals, cover, leaf = fn(codes, g, h, w, edges, key, 0.5,
                                       2.0, 1e-5, 0.1, 1.0, tm, 0.0, 0.0,
                                       0.0)
        outs[layout] = jax.device_get([[list(l) for l in levels], vals,
                                       leaf])
    _compare_builds(outs, md)


def test_run_layout_crosscheck(cl, rng):
    """The in-driver crosscheck (hist_layout="check") passes on its own:
    single tree and batched K=3, with NAs and skew in the mix."""
    F, N, nbins, md = 5, 2048, 16, 7
    codes, g, h, w, edges = _skewed_inputs(rng, F, N, nbins)
    key = jax.random.PRNGKey(7)
    shared.run_layout_crosscheck(codes, g * w, h * w, w, edges, key,
                                 max_depth=md, nbins=nbins, F=F,
                                 n_padded=N, sparse_depth_threshold=3)
    K = 3
    gK = jnp.asarray(rng.normal(size=(K, N)), jnp.float32)
    hK = jnp.ones((K, N), jnp.float32)
    keysK = jax.vmap(jax.random.PRNGKey)(jnp.arange(K))
    shared.run_layout_crosscheck(codes, gK, hK, w, edges, keysK,
                                 max_depth=md, nbins=nbins, F=F,
                                 n_padded=N, sparse_depth_threshold=3)


def test_effective_depth_sparse_drops_memory_cap(cl):
    """The 64 MB dense wall (depth 10 at 256 bins, 32 features — the
    Kaggle-shape workload) does not apply to the sparse layout:
    effective depth becomes row-capped only, so depth-12/256-bin trains
    that the dense grid must truncate."""
    F, nbins, N = 32, 256, 8192
    assert shared.dense_mem_cap(nbins, F) == 10
    assert shared.effective_max_depth(12, nbins, F, N) == 10
    assert shared.effective_max_depth(
        12, nbins, F, N, hist_layout="sparse") == 12
    assert shared.effective_max_depth(
        12, nbins, F, N, hist_layout="auto") == 12


# ------------------------------------------------------------------- drivers

def _airlines(rng, n=800, with_na=True, multiclass=False):
    """Airlines-shaped frame: numerics + categoricals + NAs."""
    from h2o3_tpu import Frame
    from h2o3_tpu.frame.vec import T_CAT
    dist = np.abs(rng.normal(700, 500, n)).astype(np.float64)
    dep = rng.integers(0, 2400, n).astype(np.float64)
    if with_na:
        dist[rng.random(n) < 0.1] = np.nan
    carrier = rng.integers(0, 7, n)
    dow = rng.integers(0, 5, n)
    logit = (0.002 * (dep / 100 - 12) ** 2 - 0.0005 * np.nan_to_num(dist)
             / 100 + 0.3 * (carrier == 2) + 0.1 * rng.normal(size=n))
    if multiclass:
        y3 = np.digitize(logit, np.quantile(logit, [0.33, 0.66]))
        resp = np.array(["A", "B", "C"], dtype=object)[y3]
    else:
        yy = rng.random(n) < 1 / (1 + np.exp(-logit))
        resp = np.where(yy, "YES", "NO").astype(object)
    return Frame.from_numpy(
        {"dep": dep, "dist": dist, "carrier": carrier, "dow": dow,
         "delayed": resp},
        types={"carrier": T_CAT, "dow": T_CAT},
        domains={"carrier": [str(i) for i in range(7)],
                 "dow": [str(i) for i in range(5)]})


def _assert_same_routing(m_a, m_b):
    """Same trees node-for-node: valid flags exact, split features equal
    wherever the node is valid."""
    ta, tb = list(m_a.output["trees"]), list(m_b.output["trees"])
    assert len(ta) == len(tb)
    for xs, ys in zip(ta, tb):
        xs = xs if isinstance(xs, list) else [xs]
        ys = ys if isinstance(ys, list) else [ys]
        for a, b in zip(xs, ys):
            for d in range(len(a.feat)):
                va = np.asarray(a.valid[d])
                vb = np.asarray(b.valid[d])
                np.testing.assert_array_equal(va, vb)
                np.testing.assert_array_equal(
                    np.where(va, np.asarray(a.feat[d]), 0),
                    np.where(vb, np.asarray(b.feat[d]), 0))


def _assert_same_preds(m_a, m_b, fr, col, atol=1e-4):
    a = m_a.predict(fr).vec(col).to_numpy()
    b = m_b.predict(fr).vec(col).to_numpy()
    np.testing.assert_allclose(a, b, atol=atol, rtol=1e-4)


_DRIVER_KW = dict(response_column="delayed", ntrees=3, max_depth=6,
                  nbins=16, min_rows=2, seed=11, reproducible=True,
                  sparse_depth_threshold=2)


def test_gbm_sparse_whole_model_parity(cl, rng):
    from h2o3_tpu.models.tree.gbm import GBM
    fr = _airlines(rng)
    m_d = GBM(hist_layout="dense", **_DRIVER_KW).train(fr)
    m_s = GBM(hist_layout="sparse", **_DRIVER_KW).train(fr)
    assert m_s.output["hist_layout"] == "sparse"
    assert m_d.output["hist_layout"] == "dense"
    _assert_same_routing(m_d, m_s)
    _assert_same_preds(m_d, m_s, fr, "YES")
    # "check" trains the first tree on BOTH layouts and asserts agreement
    # in-driver, then continues sparse
    m_c = GBM(hist_layout="check", **_DRIVER_KW).train(fr)
    assert m_c.output["hist_layout"] == "sparse"
    _assert_same_preds(m_c, m_s, fr, "YES")


def test_gbm_multinomial_sparse_parity(cl, rng):
    """Batched K-tree (one launch per level for all class trees) through
    the sparse slot layout."""
    from h2o3_tpu.models.tree.gbm import GBM
    fr3 = _airlines(rng, multiclass=True)
    m_d = GBM(hist_layout="dense", **_DRIVER_KW).train(fr3)
    m_s = GBM(hist_layout="sparse", **_DRIVER_KW).train(fr3)
    _assert_same_routing(m_d, m_s)
    _assert_same_preds(m_d, m_s, fr3, "B")
    m_c = GBM(hist_layout="check", **_DRIVER_KW).train(fr3)
    _assert_same_preds(m_c, m_s, fr3, "B")


def test_drf_sparse_whole_model_parity(cl, rng):
    from h2o3_tpu.models.tree.drf import DRF
    fr = _airlines(rng)
    m_d = DRF(hist_layout="dense", **_DRIVER_KW).train(fr)
    m_s = DRF(hist_layout="sparse", **_DRIVER_KW).train(fr)
    _assert_same_routing(m_d, m_s)
    _assert_same_preds(m_d, m_s, fr, "YES")


def test_xgboost_sparse_parity_and_fail_fast(cl, rng):
    from h2o3_tpu.models.tree.xgboost import XGBoost
    fr = _airlines(rng)
    m_d = XGBoost(hist_layout="dense", **_DRIVER_KW).train(fr)
    m_s = XGBoost(hist_layout="sparse", **_DRIVER_KW).train(fr)
    _assert_same_routing(m_d, m_s)
    _assert_same_preds(m_d, m_s, fr, "YES")
    with pytest.raises(ValueError, match="hist_layout"):
        XGBoost(response_column="y", hist_layout="bogus")


@pytest.mark.heavy
def test_depth12_256bin_trains_past_dense_wall(cl, rng):
    """The ISSUE-7 acceptance run: a depth-12, 256-bin, 32-feature GBM
    (and the batched-K=3 multinomial equivalent) trains under the 64 MB
    histogram budget with the sparse layout, where the dense layout must
    truncate at depth 10 (its memory cap at this geometry)."""
    from h2o3_tpu import Frame
    from h2o3_tpu.models.tree.gbm import GBM
    n, F = 3000, 32
    X = rng.normal(size=(n, F))
    y = X[:, :4].sum(axis=1) + 0.3 * rng.normal(size=n)
    cols = {f"x{i}": X[:, i] for i in range(F)}
    fr = Frame.from_numpy({**cols, "y": y})
    kw = dict(response_column="y", ntrees=1, max_depth=12, nbins=256,
              min_rows=1, seed=3, reproducible=True)
    with pytest.warns(UserWarning, match="capped to 10"):
        m_dense = GBM(hist_layout="dense", **kw).train(fr)
    assert m_dense.output["effective_max_depth"] == 10
    m_sparse = GBM(hist_layout="sparse", **kw).train(fr)
    assert m_sparse.output["effective_max_depth"] == 12
    tree = m_sparse.output["trees"][0]
    tree = tree[0] if isinstance(tree, list) else tree
    assert len(tree.feat) == 12
    # batched-K=3 multinomial at the same deep geometry
    y3 = np.array(["A", "B", "C"], dtype=object)[
        np.digitize(y, np.quantile(y, [0.33, 0.66]))]
    fr3 = Frame.from_numpy({**cols, "y": y3})
    m3 = GBM(hist_layout="sparse", **kw).train(fr3)
    assert m3.output["effective_max_depth"] == 12
    assert len(m3.output["trees"][0]) == 3           # K class trees


def test_uplift_sparse_whole_model_parity(cl, rng):
    from h2o3_tpu import Frame
    from h2o3_tpu.models.tree.uplift import UpliftDRF
    n = 800
    x0 = rng.normal(size=n)
    x1 = rng.normal(size=n)
    treat = rng.integers(0, 2, n)
    pp = 1 / (1 + np.exp(-(0.5 * x0 + 0.8 * treat * (x1 > 0))))
    yv = (rng.random(n) < pp).astype(int)
    fr = Frame.from_numpy({
        "x0": x0, "x1": x1, "treatment": treat.astype(np.float64),
        "y": np.array(["no", "yes"], dtype=object)[yv]})
    kw = dict(response_column="y", treatment_column="treatment", ntrees=3,
              max_depth=6, nbins=16, min_rows=5, seed=9, sample_rate=0.8,
              reproducible=True, sparse_depth_threshold=2)
    for sm in ("separate", "fused"):
        m_d = UpliftDRF(hist_layout="dense", split_mode=sm, **kw).train(fr)
        m_s = UpliftDRF(hist_layout="sparse", split_mode=sm,
                        **kw).train(fr)
        _assert_same_routing(m_d, m_s)
        pa = m_d.predict(fr).vec("uplift_predict").to_numpy()
        pb = m_s.predict(fr).vec("uplift_predict").to_numpy()
        np.testing.assert_allclose(pa, pb, atol=1e-4, rtol=1e-4)
    m_c = UpliftDRF(hist_layout="check", **kw).train(fr)
    assert m_c.output["hist_layout"] == "sparse"
