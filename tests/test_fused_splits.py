"""Fused coarse split search + batched K-tree growth parity suite.

Three layers of oracle checks for the one-launch-per-level pipeline:

1. ``fused_best_splits`` (single-pass winner-records path) vs
   ``best_splits`` (the multi-pass XLA oracle) — bit-exact off-TPU,
   across NA mass, L1/gamma/min_child_weight regularizers, feature
   masks, and deliberately tied gains.
2. ``make_multinomial_scan_fn(split_mode="fused")`` (one batched build
   for all K class trees) vs the sequential per-class loop — same RNG
   stream, same trees, same predictions, including shared row sampling
   and per-class column-sample masks.
3. The driver-facing ``split_mode="check"`` crosschecks
   (``run_split_crosscheck`` / ``run_hist_crosscheck(nk=...)``) and a
   tiny end-to-end GBM ``split_mode="check"`` train — the tier-1 smoke
   for the whole fused pipeline.

The dispatch-count test asserts the load-bearing property directly from
the jaxpr: a batched level issues ONE histogram kernel launch for all K
trees (vmap batches the grid, it does not replicate the call).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from h2o3_tpu.models.tree import hist, shared


def _rand_hist(rng, L, F, B, na_mass=0.2):
    """Histogram block [3, L, F, B] with positive hessians/counts and an
    NA bucket carrying ``na_mass`` of the rows on average."""
    C = rng.integers(0, 40, size=(L, F, B)).astype(np.float32)
    C[..., -1] = rng.integers(0, int(40 * na_mass) + 1,
                              size=(L, F)).astype(np.float32)
    G = rng.normal(size=(L, F, B)).astype(np.float32) * np.sqrt(C + 1e-3)
    H = (C * rng.uniform(0.5, 1.5, size=(L, F, B))).astype(np.float32)
    G, H, C = (np.where(C > 0, a, 0.0).astype(np.float32)
               for a in (G, H, C))
    return jnp.asarray(np.stack([G, H, C]))


_REG_CONFIGS = [
    dict(reg_alpha=0.0, gamma=0.0, min_child_weight=0.0),
    dict(reg_alpha=0.7, gamma=0.0, min_child_weight=0.0),
    dict(reg_alpha=0.0, gamma=1.5, min_child_weight=0.0),
    dict(reg_alpha=0.0, gamma=0.0, min_child_weight=4.0),
    dict(reg_alpha=0.3, gamma=0.8, min_child_weight=2.0),
]


@pytest.mark.parametrize("cfg", _REG_CONFIGS,
                         ids=["plain", "l1", "gamma", "mcw", "all"])
def test_fused_matches_best_splits(cl, rng, cfg):
    """Off-TPU the fused path lowers to the XLA twin, which replays
    best_splits' op sequence — the outputs must be bit-identical."""
    L, F, nbins = 8, 6, 16
    H = _rand_hist(rng, L, F, nbins + 1)
    mask = jnp.asarray(rng.uniform(size=(L, F)) < 0.8, bool)
    mask = mask.at[:, 0].set(True)
    ref = best = None
    for fm in (None, mask):
        ref = jax.device_get(hist.best_splits(
            H, nbins, 0.5, 2.0, 1e-5, feat_mask=fm, **cfg))
        fus = jax.device_get(hist.fused_best_splits(
            H, nbins, 0.5, 2.0, 1e-5, feat_mask=fm, **cfg))
        for name, a, b in zip(("feat", "bin", "na_left", "gain", "valid",
                               "children"), ref, fus):
            assert np.array_equal(a, b), (name, fm is not None)


def test_fused_matches_best_splits_tied_gains(cl, rng):
    """Duplicated feature columns force exact gain ties; both searches
    must resolve to the same lowest flat (feature, bin) index."""
    L, F, nbins = 4, 6, 8
    H = np.asarray(_rand_hist(rng, L, 2, nbins + 1))
    H = jnp.asarray(np.concatenate([H, H, H], axis=2))   # f, f+2, f+4 tie
    ref = jax.device_get(hist.best_splits(H, nbins, 0.5, 1.0, 1e-5))
    fus = jax.device_get(hist.fused_best_splits(H, nbins, 0.5, 1.0, 1e-5))
    for name, a, b in zip(("feat", "bin", "na_left", "gain", "valid",
                           "children"), ref, fus):
        assert np.array_equal(a, b), name
    assert (np.asarray(ref[0]) < 2).all()      # ties resolve to first copy


def test_fused_batched_matches_per_tree(cl, rng):
    """fused_best_splits_batched flattens K trees into one records pass;
    per-tree slices must equal independent fused searches."""
    K, L, F, nbins = 3, 8, 5, 16
    HK = jnp.stack([_rand_hist(rng, L, F, nbins + 1) for _ in range(K)])
    maskK = jnp.asarray(rng.uniform(size=(K, F)) < 0.7, bool)
    maskK = maskK.at[:, 0].set(True)
    bat = jax.device_get(hist.fused_best_splits_batched(
        HK, nbins, 0.5, 2.0, 1e-5, feat_mask=maskK, reg_alpha=0.2))
    for k in range(K):
        one = jax.device_get(hist.fused_best_splits(
            HK[k], nbins, 0.5, 2.0, 1e-5,
            feat_mask=jnp.broadcast_to(maskK[k], (L, F)), reg_alpha=0.2))
        for name, a, b in zip(("feat", "bin", "na_left", "gain", "valid",
                               "children"), bat, one):
            assert np.array_equal(a[k], b), (k, name)


def _tiny_problem(rng, F=5, N=1024, K=3, nbins=16):
    codes = jnp.asarray(rng.integers(0, nbins + 1, size=(F, N)), jnp.int32)
    edges = jnp.asarray(np.sort(rng.normal(size=(F, nbins)), axis=1),
                        jnp.float32)
    Y = rng.integers(0, K, size=N)
    Y1 = jnp.asarray(np.eye(K)[Y], jnp.float32)
    w = jnp.ones(N, jnp.float32)
    return codes, edges, Y1, w


@pytest.mark.parametrize("mode", ["multinomial", "drf"])
def test_batched_scan_matches_separate(cl, rng, mode):
    """One batched K-tree build per round vs the sequential per-class
    loop, chained over 3 rounds, with shared row sampling
    (sample_rate=0.8) and per-class column masks
    (col_sample_rate_per_tree=0.7) — same RNG stream on both paths."""
    F, N, K, nbins, depth = 5, 1024, 3, 16, 4
    codes, edges, Y1, w = _tiny_problem(rng, F, N, K, nbins)
    kwargs = dict(hist_precision="f32", sample_rate=0.8,
                  col_sample_rate_per_tree=0.7)
    scal = (0.5, 1.0, 1e-5, 0.1, 0.8, 0.0, 0.0, 0.0)
    key = jax.random.PRNGKey(7)
    outs = {}
    for sm in ("separate", "fused"):
        fn = shared.make_multinomial_scan_fn(
            K, depth, nbins, F, N, split_mode=sm, mode=mode, **kwargs)
        outs[sm] = jax.device_get(fn(
            codes, Y1, w, jnp.zeros((N, K), jnp.float32), edges,
            key, 0, 3, *scal))
    (Fs, lvs, vs, cs), (Ff, lvf, vf, cf) = outs["separate"], outs["fused"]
    np.testing.assert_allclose(Fs, Ff, atol=1e-5)
    for d, (a, b) in enumerate(zip(lvs, lvf)):
        va, vb = np.asarray(a[3], bool), np.asarray(b[3], bool)
        assert np.array_equal(va, vb), (d, "valid")
        # feat/thr/na_left only matter where the node actually split: the
        # fused path picks an arbitrary (feat, bin) at masked-out leaves
        assert np.array_equal(np.asarray(a[0])[va], np.asarray(b[0])[va])
        np.testing.assert_allclose(np.asarray(a[1])[va],
                                   np.asarray(b[1])[va], atol=1e-5)
        assert np.array_equal(np.asarray(a[2])[va], np.asarray(b[2])[va])
    np.testing.assert_allclose(vs, vf, atol=1e-5)
    np.testing.assert_allclose(cs, cf, atol=1e-4)


def test_single_tree_scan_fused_bitexact(cl, rng):
    """K=1: the fused split search slots into the same build — outputs
    are bit-exact vs the separate best_splits path (no batching in play,
    identical RNG, identical arithmetic off-TPU)."""
    F, N, nbins, depth = 5, 1024, 16, 4
    codes, edges, _, w = _tiny_problem(rng, F, N, 3, nbins)
    y = jnp.asarray(np.random.default_rng(3).normal(size=N), jnp.float32)
    scal = (0.5, 1.0, 1e-5, 0.1, 0.8, 0.0, 0.0, 0.0)
    outs = []
    for sm in ("separate", "fused"):
        fn = shared.make_tree_scan_fn(
            "gaussian", 1.5, 0.5, 0.9, depth, nbins, F, N, "f32",
            0.8, 0.7, split_mode=sm)
        outs.append(jax.device_get(fn(
            codes, y, w, jnp.zeros(N, jnp.float32), edges,
            jax.random.PRNGKey(7), 0, 3, *scal)))
    assert np.array_equal(outs[0][0], outs[1][0])      # F carry
    assert np.array_equal(outs[0][2], outs[1][2])      # leaf values


def test_split_and_hist_crosschecks(cl, rng):
    """The driver-facing check helpers: batched-K build vs K sequential
    oracle builds (run_split_crosscheck) and batched-K histograms vs the
    full-hist oracle (run_hist_crosscheck(nk=K))."""
    F, N, K, nbins, depth = 5, 1024, 3, 16, 4
    codes, edges, _, w = _tiny_problem(rng, F, N, K, nbins)
    g = jnp.asarray(rng.normal(size=(K, N)), jnp.float32)
    h = jnp.asarray(rng.uniform(0.1, 1.0, size=(K, N)), jnp.float32)
    key = jax.random.PRNGKey(11)
    keys = jnp.stack([jax.random.fold_in(key, k) for k in range(K)])
    tms = jnp.asarray(rng.uniform(size=(K, F)) < 0.8, bool)
    tms = tms.at[:, 0].set(True)
    shared.run_split_crosscheck(codes, g, h, w, edges, keys,
                                max_depth=depth, nbins=nbins, F=F,
                                n_padded=N, tree_masks=tms,
                                reg_lambda=0.5, col_sample_rate=0.8)
    shared.run_split_crosscheck(codes, g[0], h[0], w, edges, keys[0],
                                max_depth=depth, nbins=nbins, F=F,
                                n_padded=N, reg_lambda=0.5,
                                reg_alpha=0.2, gamma=0.1)
    shared.run_hist_crosscheck(codes, g, h, w, edges, keys,
                               max_depth=depth, nbins=nbins, F=F,
                               n_padded=N, nk=K, reg_lambda=0.5)


def test_batched_level_single_hist_dispatch(cl, rng):
    """The load-bearing claim, verified by dispatch count in the traced
    program: one batched level over K trees contains exactly ONE
    histogram pallas_call (the vmap batching rule prepends K to the
    grid; it does not replicate the launch)."""
    F, N, K, nbins = 4, 1024, 3, 8
    B = nbins + 1
    lev = hist.make_batched_level_fn(1, K, F, B, N,
                                     bin_counts=(nbins,) * F,
                                     force_impl="pallas_interpret",
                                     subtract=False)
    codes = jnp.asarray(rng.integers(0, B, size=(F, N)), jnp.int32)
    leafK = jnp.zeros((K, N), jnp.int32)
    gK = jnp.asarray(rng.normal(size=(K, N)), jnp.float32)
    hK = jnp.ones((K, N), jnp.float32)
    jaxpr = jax.make_jaxpr(lev)(codes, leafK, gK, hK, hK)
    n_calls = str(jaxpr).count("pallas_call")
    assert n_calls == 1, f"expected 1 hist launch for K={K}, got {n_calls}"


def test_gbm_split_mode_check_smoke(cl, rng):
    """Tier-1 smoke: a tiny multinomial GBM trained with
    split_mode='check' runs the batched-vs-sequential crosscheck inside
    the real driver and must train through cleanly; a bogus mode fails
    fast at construction."""
    from h2o3_tpu import Frame
    from h2o3_tpu.models import GBM
    n = 600
    centers = np.array([[2, 0], [-2, 1], [0, -2]])
    labels = rng.integers(0, 3, n)
    X = centers[labels] + rng.normal(size=(n, 2))
    fr = Frame.from_numpy({
        "x0": X[:, 0], "x1": X[:, 1],
        "y": np.array(["a", "b", "c"], dtype=object)[labels]})
    kw = dict(response_column="y", ntrees=3, max_depth=3, seed=4,
              sample_rate=0.8, col_sample_rate_per_tree=0.7)
    m_chk = GBM(**kw, split_mode="check").train(fr)
    m_sep = GBM(**kw, split_mode="separate").train(fr)
    pc = np.stack([m_chk.predict(fr).vec(c).to_numpy() for c in "abc"], 1)
    ps = np.stack([m_sep.predict(fr).vec(c).to_numpy() for c in "abc"], 1)
    np.testing.assert_allclose(pc, ps, atol=1e-5)
    with pytest.raises(ValueError, match="split_mode"):
        GBM(response_column="y", split_mode="bogus").train(fr)


@pytest.mark.slow
def test_drivers_fused_matches_separate(cl, rng):
    """Full-driver parity (slow tier): GBM multinomial, DART multinomial
    (legacy loop), DRF multiclass, and UpliftDRF each produce identical
    predictions under split_mode='fused' and 'separate'."""
    from h2o3_tpu import Frame
    from h2o3_tpu.models import GBM, DRF, UpliftDRF, XGBoost
    n = 1200
    centers = np.array([[2, 0], [-2, 1], [0, -2]])
    labels = rng.integers(0, 3, n)
    X = centers[labels] + rng.normal(size=(n, 2))
    fr = Frame.from_numpy({
        "x0": X[:, 0], "x1": X[:, 1],
        "y": np.array(["a", "b", "c"], dtype=object)[labels]})

    def probs(m):
        p = m.predict(fr)
        return np.stack([p.vec(c).to_numpy() for c in "abc"], axis=1)

    for mk in (
        lambda sm: GBM(response_column="y", ntrees=6, max_depth=3, seed=4,
                       col_sample_rate_per_tree=0.7, sample_rate=0.8,
                       split_mode=sm),
        lambda sm: XGBoost(response_column="y", ntrees=5, max_depth=3,
                           seed=4, booster="dart", rate_drop=0.3,
                           one_drop=True, split_mode=sm),
        lambda sm: DRF(response_column="y", ntrees=6, max_depth=4,
                       seed=10, col_sample_rate_per_tree=0.8,
                       split_mode=sm),
    ):
        a = probs(mk("separate").train(fr))
        b = probs(mk("fused").train(fr))
        np.testing.assert_allclose(a, b, atol=1e-5)

    treat = rng.integers(0, 2, n)
    base = 1 / (1 + np.exp(-X[:, 1]))
    eff = np.where(X[:, 0] > 0, 0.3, -0.05)
    yb = (rng.random(n) < np.clip(base + treat * eff, 0.01, 0.99))
    fru = Frame.from_numpy({
        "x0": X[:, 0], "x1": X[:, 1],
        "treatment": np.array(["control", "treatment"],
                              dtype=object)[treat],
        "y": np.array(["no", "yes"], dtype=object)[yb.astype(int)]})
    us, uf = (UpliftDRF(response_column="y", treatment_column="treatment",
                        ntrees=4, max_depth=4, seed=1, split_mode=sm)
              .train(fru).predict(fru).vec("uplift_predict").to_numpy()
              for sm in ("separate", "fused"))
    np.testing.assert_allclose(us, uf, atol=1e-5)
