"""Survivable training: progress snapshots, journal state, resume.

In-process half of the chaos matrix (the process-kill half lives in
test_chaos.py): a training run interrupted while the cluster is degraded
leaves a 'running' journal entry pointing at its latest progress
snapshot; ``recovery.resume()`` continues from the snapshot through the
checkpoint machinery instead of retraining from zero.
"""

import dataclasses
import json

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.runtime import dkv, failure, recovery, snapshot
from h2o3_tpu.runtime.config import reload as config_reload


@pytest.fixture()
def recovery_env(cl, tmp_path, monkeypatch):
    """Recovery dir + snapshot-every-opportunity + synchronous writes."""
    monkeypatch.setenv("H2O3_TPU_RECOVERY_DIR", str(tmp_path))
    monkeypatch.setenv("H2O3_TPU_SNAPSHOT_INTERVAL", "0")
    monkeypatch.setenv("H2O3_TPU_SNAPSHOT_ASYNC", "0")
    config_reload()
    snapshot.reset()
    failure.reset()
    yield tmp_path
    snapshot.reset()
    failure.reset()
    monkeypatch.delenv("H2O3_TPU_RECOVERY_DIR", raising=False)
    monkeypatch.delenv("H2O3_TPU_SNAPSHOT_INTERVAL", raising=False)
    monkeypatch.delenv("H2O3_TPU_SNAPSHOT_ASYNC", raising=False)
    monkeypatch.delenv("H2O3_TPU_FAULT_INJECT", raising=False)
    config_reload()


_FR_SEQ = [0]


def _reg_frame(seed=3, n=600, destination_frame=None):
    rng = np.random.default_rng(seed)
    X = rng.random((n, 4))
    y = (10 * np.sin(np.pi * X[:, 0]) + 5 * X[:, 1] ** 2
         + 3 * X[:, 2] + 0.1 * rng.normal(size=n))
    cols = {f"x{j}": X[:, j] for j in range(4)}
    cols["y"] = y
    if destination_frame is None:
        _FR_SEQ[0] += 1
        destination_frame = f"snaprec_fr_{seed}_{n}_{_FR_SEQ[0]}"
    return h2o3_tpu.H2OFrame(cols, destination_frame=destination_frame)


def _crash_gbm_mid_train(tmp_path, monkeypatch, fr, ntrees=12):
    """Interrupt a GBM at the 3rd chunk while the cluster looks degraded:
    the journal entry must stay 'running' with a snapshot recorded."""
    from h2o3_tpu.models import GBM
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "tree_chunk:0:3:raise")
    failure.reset()
    failure._handled.add("ghost")        # degraded: keep journal resumable
    kw = dict(response_column="y", ntrees=ntrees, max_depth=3,
              learn_rate=0.2, seed=7, score_tree_interval=2)
    with pytest.raises(failure.InjectedFault):
        GBM(**kw).train(fr)
    monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
    failure.reset()
    entries = list(tmp_path.glob("job_*.json"))
    assert len(entries) == 1
    entry = json.loads(entries[0].read_text())
    assert entry["status"] == "running"
    return entry, kw


def test_gbm_resume_from_snapshot_matches_uninterrupted(
        recovery_env, monkeypatch):
    """The headline contract: interrupted at tree 4 of 12, resume()
    continues from the snapshot (not tree 0) and the final predictions
    match an uninterrupted 12-tree run."""
    from h2o3_tpu.models import GBM
    tmp_path = recovery_env
    fr = _reg_frame()
    entry, kw = _crash_gbm_mid_train(tmp_path, monkeypatch, fr)
    # chunks of 2 trees; killed at the 3rd chunk -> snapshot covers 4
    assert entry["snapshot_uri"]
    assert entry["snapshot_cursor"]["trees_done"] == 4
    snap_files = list(tmp_path.glob("snap_*.bin"))
    assert len(snap_files) == 1          # superseded generations deleted

    done = recovery.resume(str(tmp_path))
    assert len(done) == 1
    model = dkv.get(done[0])
    assert model.output["ntrees_trained"] == 12
    # proof the run continued instead of restarting: the resume
    # provenance carries the snapshot cursor
    resumed = model.output["resumed_from_snapshot"]
    assert resumed["cursor"]["trees_done"] == 4
    from h2o3_tpu.runtime.observability import recent_logs
    assert any("resuming GBM from snapshot" in line
               for line in recent_logs())
    # journal + snapshot are cleaned up after a successful resume
    assert not list(tmp_path.glob("job_*.json"))
    assert not list(tmp_path.glob("snap_*.bin"))

    straight = GBM(**kw).train(fr)
    p_resumed = model.predict(fr).vec("predict").to_numpy()
    p_straight = straight.predict(fr).vec("predict").to_numpy()
    np.testing.assert_allclose(p_resumed, p_straight, rtol=1e-4, atol=1e-4)


def test_resume_reimports_frame_from_journaled_source(
        recovery_env, monkeypatch, tmp_path_factory):
    """The frame re-import path: the journaled frame_source is re-imported
    under the original key when the DKV lost the frame (fresh process)."""
    csv_dir = tmp_path_factory.mktemp("reimport_data")
    fr0 = _reg_frame(seed=5)
    csv = csv_dir / "re.csv"
    cols = {n: fr0.vec(n).to_numpy() for n in fr0.names}
    header = ",".join(cols)
    rows = np.stack(list(cols.values()), axis=1)
    csv.write_text(header + "\n" + "\n".join(
        ",".join(f"{v:.9g}" for v in r) for r in rows))
    from h2o3_tpu.frame.parse import import_file
    fr = import_file(str(csv), destination_frame="reimport_fr")
    assert fr.source_uri == str(csv)

    entry, _ = _crash_gbm_mid_train(recovery_env, monkeypatch, fr)
    assert entry["frame_key"] == "reimport_fr"
    assert entry["frame_source"] == str(csv)

    dkv.remove("reimport_fr")            # simulate the restarted cluster
    done = recovery.resume(str(recovery_env))
    assert len(done) == 1
    assert dkv.get("reimport_fr") is not None
    model = dkv.get(done[0])
    assert model.output["ntrees_trained"] == 12
    assert model.output["resumed_from_snapshot"]["cursor"]["trees_done"] == 4


def test_drf_and_xgboost_resume_from_snapshot(recovery_env, monkeypatch):
    """The other tree builders share GBM's fused-chunk snapshot wiring:
    interrupted DRF/XGBoost runs continue from their snapshot too.
    (No prediction-equality assert for DRF: the continuation PRNG stream
    is decorrelated from the prior run by design, so bootstrap samples
    differ — same contract as test_drf_checkpoint_continues.)"""
    from h2o3_tpu.models import DRF, XGBoost
    fr = _reg_frame()
    for cls_ in (DRF, XGBoost):
        monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "tree_chunk:0:3:raise")
        failure.reset()
        failure._handled.add("ghost")
        with pytest.raises(failure.InjectedFault):
            cls_(response_column="y", ntrees=12, max_depth=3, seed=7,
                 score_tree_interval=2).train(fr)
        monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
        failure.reset()
        done = recovery.resume(str(recovery_env))
        assert len(done) == 1, cls_.__name__
        m = dkv.get(done[0])
        assert m.output["ntrees_trained"] == 12
        assert m.output["resumed_from_snapshot"]["cursor"]["trees_done"] == 4
        assert not list(recovery_env.glob("job_*.json"))
        snapshot.reset()                 # fresh throttle for the next algo


def test_cancelled_and_deterministic_failures_not_resurrected(
        recovery_env, monkeypatch):
    """journal_fail contract: cancelled jobs and deterministic failures
    flip the entry to 'failed' — resume() must never resurrect them."""
    from h2o3_tpu.models import GBM
    from h2o3_tpu.runtime.job import JobCancelled
    fr = _reg_frame()

    class CancelGBM(GBM):
        def _fit(self, *a, **k):
            raise JobCancelled("user hit stop")

    CancelGBM.__name__ = "GBM"
    with pytest.raises(JobCancelled):
        CancelGBM(response_column="y", ntrees=3).train(fr)
    # a deterministic (injected, non-degraded) failure also marks failed
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "tree_chunk:0:1:raise")
    failure.reset()
    from h2o3_tpu.runtime import heartbeat
    heartbeat.start(interval=0.5)        # healthy self-stamp
    try:
        with pytest.raises(failure.InjectedFault):
            GBM(response_column="y", ntrees=3, max_depth=2,
                seed=1).train(fr)
    finally:
        heartbeat.stop()
        monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
    entries = [json.loads(p.read_text())
               for p in recovery_env.glob("job_*.json")]
    assert len(entries) == 2
    assert all(e["status"] == "failed" for e in entries)
    assert recovery.resume(str(recovery_env)) == []


def test_journal_start_honors_params_override(recovery_env):
    """Regression: journal_start used to rebind ``params = {}`` before
    evaluating the caller's override, silently journaling builder.params
    instead (recovery.py:42) — balance_classes runs journaled the
    synthetic weights column and resumed into a broken builder."""
    from h2o3_tpu.models import GBM
    fr = _reg_frame()
    b = GBM(response_column="y", ntrees=3)
    override = dataclasses.replace(b.params, ntrees=7,
                                   weights_column=None)
    uri = recovery.journal_start(b, fr, params=override)
    with open(uri) as f:
        entry = json.load(f)
    assert entry["params"]["ntrees"] == 7        # the override, not 3
    recovery.journal_done(uri)


def test_snapshot_write_failures_never_fail_training(
        recovery_env, monkeypatch):
    """Best-effort contract: every snapshot write blowing up (injected
    ``raise`` at the snapshot_write point) must leave training untouched."""
    from h2o3_tpu.models import GBM
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT",
                       "snapshot_write:0:1:raise:99")
    failure.reset()
    fr = _reg_frame()
    m = GBM(response_column="y", ntrees=6, max_depth=2, seed=2,
            score_tree_interval=2).train(fr)
    assert m.output["ntrees_trained"] == 6
    # job completed: journal entry removed, no snapshot left behind
    assert not list(recovery_env.glob("job_*.json"))
    assert not list(recovery_env.glob("snap_*.bin"))


def test_snapshot_throttle_and_per_job_interval(recovery_env, monkeypatch):
    """A huge snapshot_interval on the job suppresses every write except
    the first; interval 0 writes at every chunk boundary."""
    from h2o3_tpu.models import GBM
    fr = _reg_frame()
    calls = []
    orig = snapshot._write_task

    def counting(task):
        calls.append(task[0])
        orig(task)

    monkeypatch.setattr(snapshot, "_write_task", counting)
    GBM(response_column="y", ntrees=8, max_depth=2, seed=2,
        score_tree_interval=2, snapshot_interval=3600.0).train(fr)
    assert len(calls) == 1               # first write, then throttled
    snapshot.reset()
    GBM(response_column="y", ntrees=8, max_depth=2, seed=2,
        score_tree_interval=2, snapshot_interval=0.0).train(fr)
    assert len(calls) == 1 + 4           # every 2-tree chunk of 8 trees


def test_deeplearning_resume_from_snapshot(recovery_env, monkeypatch):
    """DL epoch snapshots: resume restores the journaled weights and
    trains only the remaining epochs (resume_params cursor)."""
    from h2o3_tpu.models import DeepLearning
    fr = _reg_frame(n=400)
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "dl_iter:0:3:raise")
    failure.reset()
    failure._handled.add("ghost")
    kw = dict(response_column="y", hidden=[8], epochs=6, seed=4,
              mini_batch_size=32, train_samples_per_iteration=400)
    with pytest.raises(failure.InjectedFault):
        DeepLearning(**kw).train(fr)
    monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
    failure.reset()
    entries = [json.loads(p.read_text())
               for p in recovery_env.glob("job_*.json")]
    assert len(entries) == 1 and entries[0]["status"] == "running"
    cursor = entries[0]["snapshot_cursor"]
    assert cursor["epochs_done"] > 0
    assert cursor["resume_params"]["epochs"] == pytest.approx(
        6 - cursor["epochs_done"])
    done = recovery.resume(str(recovery_env))
    assert len(done) == 1
    model = dkv.get(done[0])
    assert model.output["resumed_from_snapshot"]["cursor"] == cursor
    # only the remaining epochs were retrained
    assert model.output["epochs_trained"] == pytest.approx(
        6 - cursor["epochs_done"], abs=0.5)
    assert not list(recovery_env.glob("job_*.json"))


def test_recovery_status_route_reports_journal_and_snapshot(
        recovery_env, monkeypatch):
    """GET /3/Recovery: journal + snapshot state for the operator."""
    from h2o3_tpu.api.server import Api
    fr = _reg_frame()
    _crash_gbm_mid_train(recovery_env, monkeypatch, fr)
    out = Api().recovery_status(recovery_dir=str(recovery_env))
    assert out["resumable"] == 1
    (e,) = out["entries"]
    assert e["algo"] == "GBM" and e["status"] == "running"
    assert e["snapshot_uri"] and e["snapshot_cursor"]["trees_done"] == 4
    # leave the dir clean for the fixture teardown
    recovery.resume(str(recovery_env))


def test_glm_lambda_path_journals_progress_cursor(recovery_env, monkeypatch):
    """GLM's host lambda loop records a cursor-only progress update (the
    warm-start beta is not a loadable model; the journal still shows how
    far the path got for the /3/Recovery view)."""
    from h2o3_tpu.models import GLM
    fr = _reg_frame()
    failure.reset()
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "glm_lambda:0:3:raise")
    failure._handled.add("ghost")
    with pytest.raises(failure.InjectedFault):
        GLM(response_column="y", family="gaussian", lambda_search=True,
            nlambdas=8, non_negative=True, alpha=0.5).train(fr)
    monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
    failure.reset()
    entries = [json.loads(p.read_text())
               for p in recovery_env.glob("job_*.json")]
    assert len(entries) == 1 and entries[0]["status"] == "running"
    assert entries[0]["snapshot_cursor"]["lambda_index"] >= 0
    assert entries[0].get("snapshot_uri") is None    # cursor-only
    done = recovery.resume(str(recovery_env))        # from-scratch retrain
    assert len(done) == 1


def test_fault_injection_matrix_actions(cl, monkeypatch):
    """The spec grammar: kill stays default, raise/delay/dkv_drop fire
    ``repeat`` times from the nth hit, malformed specs are no-ops."""
    failure.reset()
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT",
                       "pt:0:2:raise,other:0:1:dkv_drop")
    failure.maybe_inject("pt")                       # hit 1: below nth
    with pytest.raises(failure.InjectedFault):
        failure.maybe_inject("pt")                   # hit 2: fires
    failure.maybe_inject("pt")                       # hit 3: healed
    with pytest.raises(ConnectionError):
        failure.maybe_inject("other")
    failure.maybe_inject("other")                    # healed
    failure.reset()
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "pt:0:1:delay:50:2")
    import time
    t0 = time.time()
    failure.maybe_inject("pt")
    failure.maybe_inject("pt")
    assert time.time() - t0 >= 0.09                  # two 50 ms delays
    failure.maybe_inject("pt")                       # repeat exhausted
    failure.reset()
    monkeypatch.setenv("H2O3_TPU_FAULT_INJECT",
                       "pt:zero:1,pt:0,garbage,pt:0:1:frobnicate")
    failure.maybe_inject("pt")                       # all malformed: no-op
    failure.reset()
