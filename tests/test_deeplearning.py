"""DeepLearning tests — mirrors pyunit_deeplearning* coverage."""

import numpy as np

from h2o3_tpu import Frame
from h2o3_tpu.models.deeplearning import DeepLearning


def _spiral(rng, n=1200):
    """Two-class nonlinear problem an MLP must solve but a GLM can't."""
    t = rng.random(n) * 3 * np.pi
    cls = rng.integers(0, 2, n)
    r = t / (3 * np.pi)
    x = r * np.cos(t + np.pi * cls) + 0.05 * rng.normal(size=n)
    y = r * np.sin(t + np.pi * cls) + 0.05 * rng.normal(size=n)
    return Frame.from_numpy({
        "x": x, "y": y,
        "label": np.array(["a", "b"], dtype=object)[cls]}), cls


def test_classification_nonlinear(cl, rng):
    fr, cls = _spiral(rng)
    m = DeepLearning(response_column="label", hidden=[64, 64], epochs=60,
                     seed=1, stopping_rounds=0).train(fr)
    assert m.training_metrics.auc > 0.95, m.training_metrics.describe()
    preds = m.predict(fr)
    assert preds.names == ["predict", "a", "b"]


def test_regression(cl, rng):
    n = 2000
    x = rng.normal(size=(n, 3))
    y = np.sin(x[:, 0]) + x[:, 1] ** 2 + 0.1 * rng.normal(size=n)
    fr = Frame.from_numpy({"x0": x[:, 0], "x1": x[:, 1], "x2": x[:, 2],
                           "y": y})
    m = DeepLearning(response_column="y", hidden=[32, 32], epochs=40,
                     seed=2, stopping_rounds=0).train(fr)
    assert m.training_metrics.r2 > 0.85, m.training_metrics.describe()


def test_activations_and_dropout(cl, rng):
    fr, _ = _spiral(rng, n=600)
    for act in ["tanh", "maxout", "rectifier_with_dropout"]:
        m = DeepLearning(response_column="label", hidden=[32], epochs=10,
                         activation=act, seed=3, stopping_rounds=0).train(fr)
        assert m.training_metrics.auc > 0.5


def test_checkpoint_continues(cl, rng):
    fr, _ = _spiral(rng, n=800)
    m1 = DeepLearning(response_column="label", hidden=[32, 32], epochs=5,
                      seed=4, stopping_rounds=0).train(fr)
    ll1 = m1.training_metrics.logloss
    m2 = DeepLearning(response_column="label", hidden=[32, 32], epochs=25,
                      checkpoint=m1.key, seed=4, stopping_rounds=0).train(fr)
    assert m2.training_metrics.logloss < ll1


def test_autoencoder_anomaly(cl, rng):
    n = 1000
    X = rng.normal(size=(n, 4))
    X[-5:] += 8.0                       # planted outliers
    fr = Frame.from_numpy({f"x{j}": X[:, j] for j in range(4)})
    m = DeepLearning(autoencoder=True, hidden=[2], epochs=40, seed=5,
                     stopping_rounds=0).train(fr)
    err = m.anomaly(fr).vec("Reconstruction.MSE").to_numpy()
    assert err[-5:].mean() > 3 * err[:-5].mean()


def test_single_sync_training_no_per_iteration_fetch(cl, rng, monkeypatch):
    """Mechanism proof for the round-3 throughput fix (VERDICT r03 weak #3):
    with early stopping off, the training loop dispatches per iteration but
    FETCHES device data a constant number of times — independent of the
    iteration count — so a remote-tunnelled accelerator is never starved by
    per-iteration round trips.  Device->host conversions all funnel through
    ``np.asarray`` in this codebase, so a counting wrapper is the probe.
    """
    import jax

    n = 1024
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0)
    fr = Frame.from_numpy({
        "x0": x[:, 0], "x1": x[:, 1], "x2": x[:, 2], "x3": x[:, 3],
        "label": np.array(["n", "p"], dtype=object)[y.astype(int)]})

    def counted_train(epochs):
        fetches = [0]
        real = np.asarray

        def counting(a, *args, **kw):
            if isinstance(a, jax.Array):
                fetches[0] += 1
            return real(a, *args, **kw)

        kw = dict(response_column="label", hidden=[16], seed=1,
                  stopping_rounds=0, mini_batch_size=128,
                  train_samples_per_iteration=128, score_interval=1e9)
        with monkeypatch.context() as mp:
            mp.setattr(np, "asarray", counting)
            m = DeepLearning(epochs=epochs, **kw).train(fr)
        return m, fetches[0]

    m8, f8 = counted_train(epochs=1.0)     # 8 iterations
    m32, f32 = counted_train(epochs=4.0)   # 32 iterations
    assert m32.output["samples_trained"] == 4 * m8.output["samples_trained"]
    assert f32 == f8, (f8, f32)            # zero fetches per extra iteration
