"""Coordinator crash recovery: WAL+snapshot rehydration across a real
SIGKILL, compaction, epoch fencing with client re-push, dedup-window
survival, and the hardened connection handler.

The round-trip contract (ISSUE acceptance): populate a coordinator,
``kill -9`` it, restart ``serve()`` on the same port and recovery dir,
and ``keys()``/``get()``/the ``make_key`` counter all match the pre-kill
state — with the next incarnation presenting a strictly higher epoch.
"""

import os
import pickle
import socket
import struct
import subprocess
import sys
import textwrap
import time

import pytest

from h2o3_tpu.runtime import dkv, failure, heartbeat
from h2o3_tpu.runtime.config import reload as config_reload

_REPO = os.path.dirname(os.path.dirname(__file__))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _raw_rpc(port: int, op: str, **kw):
    """One protocol-level round trip, independent of this process's DKV
    client state (so background threads can't consume injection hits or
    repush behind the assertions)."""
    payload = pickle.dumps({"op": op, **kw},
                           protocol=pickle.HIGHEST_PROTOCOL)
    with socket.create_connection(("127.0.0.1", port), timeout=15) as s:
        s.sendall(struct.pack("<Q", len(payload)) + payload)
        n = struct.unpack("<Q", dkv._recvall(s, 8))[0]
        resp = pickle.loads(dkv._recvall(s, n))
    return resp


_COORD = textwrap.dedent("""
    import sys
    import time
    from h2o3_tpu.runtime import dkv
    port = dkv.serve(host="127.0.0.1", port=int(sys.argv[1]))
    print("SERVING", port, dkv._epoch, flush=True)
    while True:
        time.sleep(0.1)
""")


def _coord_env(recovery_dir=None):
    env = dict(os.environ)
    env.pop("H2O3_TPU_FAULT_INJECT", None)
    env.pop("H2O3_TPU_RECOVERY_DIR", None)
    env.pop("H2O3_TPU_DKV_WAL_DIR", None)
    env.update({"JAX_PLATFORMS": "cpu", "H2O3_TPU_LOG_STDERR": "1"})
    if recovery_dir is not None:
        env["H2O3_TPU_RECOVERY_DIR"] = str(recovery_dir)
    return env


def _start_coord(port: int, env: dict):
    """Launch a coordinator subprocess; returns (proc, epoch)."""
    proc = subprocess.Popen(
        [sys.executable, "-c", _COORD, str(port)], env=env, cwd=_REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    line = proc.stdout.readline().strip()
    if not line.startswith("SERVING"):
        try:
            _, err = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            err = "<no stderr: coordinator hung>"
        raise AssertionError(f"coordinator failed to serve: {line!r}\n{err}")
    _, _, epoch = line.split()
    return proc, int(epoch)


def test_wal_rehydration_survives_kill9(tmp_path):
    """The acceptance round trip, with a REAL process kill: no atexit, no
    flush-on-close — only the per-record WAL flush stands between the
    store and oblivion."""
    port = _free_port()
    env = _coord_env(tmp_path)
    proc, ep1 = _start_coord(port, env)
    try:
        assert _raw_rpc(port, "put", key="alpha", value=1,
                        req_id="t:1")["value"] == "alpha"
        _raw_rpc(port, "put", key="beta", value={"rows": [1, 2, 3]},
                 req_id="t:2")
        k1 = _raw_rpc(port, "make_key", prefix="job", req_id="t:3")["value"]
        assert _raw_rpc(port, "incr", key="ctr", delta=2.5,
                        req_id="t:4")["value"] == 2.5
        _raw_rpc(port, "put", key="gone", value="x", req_id="t:5")
        _raw_rpc(port, "remove", key="gone", req_id="t:6")
        assert _raw_rpc(port, "cas", key="alpha", expected=1, new=2,
                        req_id="t:7")["value"] is True
        pre_keys = _raw_rpc(port, "keys", prefix="")["value"]
        assert "gone" not in pre_keys
    finally:
        proc.kill()                                  # SIGKILL, not shutdown
        proc.wait(timeout=15)

    proc2, ep2 = _start_coord(port, env)
    try:
        assert ep2 > ep1                             # monotonic incarnations
        assert _raw_rpc(port, "keys", prefix="")["value"] == pre_keys
        assert _raw_rpc(port, "get", key="alpha")["value"] == 2
        assert _raw_rpc(port, "get",
                        key="beta")["value"] == {"rows": [1, 2, 3]}
        assert _raw_rpc(port, "get", key="ctr")["value"] == 2.5
        assert _raw_rpc(port, "get", key="gone")["value"] is None
        # the make_key counter continues past its pre-kill high-water mark
        k2 = _raw_rpc(port, "make_key", prefix="job", req_id="t:8")["value"]
        assert int(k2.rsplit("_", 1)[1]) == int(k1.rsplit("_", 1)[1]) + 1
        # a RETRIED pre-kill request id answers from the WAL-rebuilt dedup
        # window instead of re-applying (exactly-once across restart)
        assert _raw_rpc(port, "make_key", prefix="job",
                        req_id="t:3")["value"] == k1
        assert _raw_rpc(port, "incr", key="ctr", delta=2.5,
                        req_id="t:4")["value"] == 2.5
    finally:
        proc2.kill()
        proc2.wait(timeout=15)


@pytest.fixture()
def local_coord(monkeypatch, tmp_path):
    """In-process coordinator sandbox: background DKV traffic stopped so
    injection counters and WAL records are deterministic."""
    heartbeat.stop()
    failure.stop()
    failure.reset()
    wal_dir = str(tmp_path / "waldir")
    monkeypatch.setenv("H2O3_TPU_DKV_WAL_DIR", wal_dir)
    monkeypatch.setenv("H2O3_TPU_DKV_WAL_COMPACT", "8")
    monkeypatch.setenv("H2O3_TPU_DKV_RECV_TIMEOUT", "0.6")
    config_reload()
    yield wal_dir
    dkv.detach()
    failure.reset()
    for k in ("H2O3_TPU_DKV_WAL_DIR", "H2O3_TPU_DKV_WAL_COMPACT",
              "H2O3_TPU_DKV_RECV_TIMEOUT", "H2O3_TPU_FAULT_INJECT"):
        monkeypatch.delenv(k, raising=False)
    config_reload()
    heartbeat.start()
    failure.start()


def test_wal_compaction_rotates_generations(cl, local_coord):
    """Every dkv_wal_compact_every records the WAL folds into a snapshot
    generation; exactly one (snap, wal) pair survives, and a restart
    rehydrates from the pair — not the deleted history."""
    dkv.serve(port=0)
    my_keys = [f"!walc/k{i}" for i in range(20)]
    for i, k in enumerate(my_keys):
        dkv.put(k, i)
    names = sorted(os.listdir(local_coord))
    snaps = [n for n in names if n.startswith("snap_")]
    wals = [n for n in names if n.startswith("wal_")]
    assert len(snaps) == 1 and len(wals) == 1, names
    gen = int(snaps[0].split("_")[1].split(".")[0])
    assert gen >= 1 and wals[0] == f"wal_{gen}.log"
    from h2o3_tpu.runtime.observability import counters
    assert counters().get("dkv_wal_compactions", 0) >= 1

    # crash simulation: drop the served state without a clean close
    dkv._server.shutdown()
    dkv._server.server_close()
    dkv._server = None
    dkv._wal_f = None
    with dkv._lock:
        for k in my_keys:
            dkv._store.pop(k, None)
            dkv._local_plain.discard(k)
    dkv.serve(port=0)
    for i, k in enumerate(my_keys):
        assert dkv.get(k) == i
    assert dkv.wal_stats()["restored_keys"] >= len(my_keys)


def test_handler_frame_cap_and_recv_timeout(cl, local_coord):
    """Satellite hardening: an absurd declared frame length is rejected
    before allocation, and a half-open client is cut loose by the recv
    timeout instead of pinning a handler thread forever."""
    port = dkv.serve(port=0)
    with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
        s.sendall(struct.pack("<Q", 1 << 40))        # claims a 1 TiB frame
        n = struct.unpack("<Q", dkv._recvall(s, 8))[0]
        resp = pickle.loads(dkv._recvall(s, n))
    assert "exceeds" in resp["err"] and "MB cap" in resp["err"]

    t0 = time.time()
    with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
        # half-open: never send the frame; H2O3_TPU_DKV_RECV_TIMEOUT=0.6
        n = struct.unpack("<Q", dkv._recvall(s, 8))[0]
        resp = pickle.loads(dkv._recvall(s, n))
    assert "err" in resp and time.time() - t0 < 3.0


def test_epoch_bump_repush_and_stale_fence(cl, local_coord, monkeypatch,
                                           tmp_path):
    """A coordinator restart bumps the epoch; the attached client detects
    it on its next op, re-pushes its locally-originated plain keys, and
    refuses responses stamped with an older epoch."""
    monkeypatch.setenv("H2O3_TPU_DKV_BACKOFF_BASE", "0.02")
    monkeypatch.setenv("H2O3_TPU_DKV_RETRIES", "40")
    monkeypatch.setenv("H2O3_TPU_DKV_RETRY_BUDGET", "60")
    config_reload()
    port = _free_port()
    env = _coord_env()                 # NON-durable: epoch is time-seeded
    proc, ep1 = _start_coord(port, env)
    proc2 = None
    try:
        dkv.attach("127.0.0.1", port)
        assert dkv._seen_epoch == ep1
        dkv.put("!repush/fact", {"v": 7})
        assert _raw_rpc(port, "get", key="!repush/fact")["value"] == {"v": 7}

        proc.kill()
        proc.wait(timeout=15)
        time.sleep(1.1)                # time-seeded epochs tick at 1 s
        proc2, ep2 = _start_coord(port, env)
        assert ep2 > ep1

        # fresh coordinator lost the key; the client's next op fences the
        # bump and re-pushes it
        assert _raw_rpc(port, "get", key="!repush/fact")["value"] is None
        dkv.get("!no_such_key_anywhere")             # any op sees the bump
        assert dkv._seen_epoch == ep2
        assert _raw_rpc(port, "get", key="!repush/fact")["value"] == {"v": 7}
        from h2o3_tpu.runtime.observability import timeline_events
        bumps = [e for e in timeline_events(2000)
                 if e["kind"] == "dkv_epoch_bump"]
        assert bumps and bumps[-1]["new_epoch"] == ep2
        assert bumps[-1]["repushed"] >= 1

        # split-brain protection: a stale incarnation's epoch is refused
        with pytest.raises(dkv.StaleCoordinatorError):
            dkv._note_epoch(ep2 - 1)
    finally:
        dkv.detach()
        dkv.remove("!repush/fact")
        for k in ("H2O3_TPU_DKV_BACKOFF_BASE", "H2O3_TPU_DKV_RETRIES",
                  "H2O3_TPU_DKV_RETRY_BUDGET"):
            monkeypatch.delenv(k, raising=False)
        for p in (proc, proc2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=15)
