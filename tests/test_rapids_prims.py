"""Per-category tests for the Rapids breadth tier (rapids/prims.py).

Reference op tokens: ``water/rapids/ast/prims/*/Ast*.java`` ``str()``
values; lambda syntax ``{ ids . body }`` per ``AstFunction.java:63``.
"""

import datetime

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.frame.vec import Vec, T_STR, T_CAT
from h2o3_tpu.rapids.ast import rapids


@pytest.fixture
def fr():
    return Frame.from_numpy(
        {"a": np.array([1.0, 2, 3, 4]), "b": np.array([5.0, 6, 7, 8])},
        key="pfr")


def col(res, j=0):
    return np.asarray(res.vecs[j].to_numpy(), np.float64)[: res.nrows]


# ------------------------------------------------------------------ math
def test_math_extra(fr):
    assert np.allclose(col(rapids("(acosh pfr)"))[:2],
                       np.arccosh([1.0, 2.0]))
    assert np.allclose(col(rapids("(cospi pfr)")),
                       np.cos(np.pi * np.array([1.0, 2, 3, 4])), atol=1e-5)
    assert np.isclose(col(rapids("(lgamma pfr)"))[3],
                      np.log(6.0), atol=1e-4)   # lgamma(4) = log(3!)
    sig = col(rapids("(signif pfr 1)"))
    assert sig[0] == 1.0


def test_logical_aliases(fr):
    out = rapids("(%% pfr 2)")
    assert np.allclose(col(out), [1, 0, 1, 0])
    out = rapids("(%/% pfr 2)")
    assert np.allclose(col(out), [0, 1, 1, 2])


# ------------------------------------------------------------------ reducers
def test_reducers(fr):
    assert rapids("(prod pfr)") == float(np.prod([1, 2, 3, 4, 5, 6, 7, 8]))
    assert rapids("(all (> pfr 0))") == 1.0
    assert rapids("(any (> pfr 7))") == 1.0
    assert rapids("(any.na pfr)") == 0.0
    assert rapids("(naCnt pfr)") == 0.0
    assert np.allclose(col(rapids("(cumsum pfr 0)")), [1, 3, 6, 10])
    assert np.allclose(col(rapids("(cummax pfr 0)")), [1, 2, 3, 4])
    assert np.allclose(col(rapids("(cummin pfr 0)")), [1, 1, 1, 1])
    mad = rapids("(h2o.mad pfr)")
    assert mad > 0


def test_topn(fr):
    out = rapids("(topn pfr 1 50 0)")       # top 50% of col b
    assert out.nrows == 2
    assert col(out, 1)[0] == 8.0            # largest first


def test_sumaxis(fr):
    rows = rapids("(sumaxis pfr 0 1)")
    assert np.allclose(col(rows), [6, 8, 10, 12])


# ------------------------------------------------------------------ matrix
def test_matrix(fr):
    t = rapids("(t pfr)")
    assert t.nrows == 2 and t.ncols == 4
    mm = rapids("(x pfr (t pfr))")
    A = np.array([[1, 5], [2, 6], [3, 7], [4, 8.0]])
    assert np.allclose(np.column_stack([col(mm, j) for j in range(4)]),
                       A @ A.T)


# ------------------------------------------------------------------ search
def test_search(fr):
    assert np.allclose(col(rapids("(which (> pfr 2))")), [2, 3])
    assert np.allclose(col(rapids("(which.max pfr 0 1)")), [1, 1, 1, 1])
    assert np.allclose(col(rapids("(match pfr [2 3] -1 1)")),
                       [-1, 1, 2, -1])


# ------------------------------------------------------------------ repeaters
def test_repeaters():
    assert np.allclose(col(rapids("(seq 1 5 1)")), [1, 2, 3, 4, 5])
    assert np.allclose(col(rapids("(seq_len 3)")), [1, 2, 3])
    assert np.allclose(col(rapids("(rep_len 7 4)")), [7, 7, 7, 7])


# ------------------------------------------------------------------ advmath
def test_advmath(fr):
    assert abs(rapids("(mode pfr)") - 1.0) < 5   # unique values: any mode
    sk = rapids("(skewness pfr)")
    assert isinstance(sk, (float, list))
    fold = rapids("(kfold_column pfr 2 42)")
    assert set(col(fold)) <= {0.0, 1.0}
    mod = rapids("(modulo_kfold_column pfr 2)")
    assert np.allclose(col(mod), [0, 1, 0, 1])
    d = rapids("(distance pfr pfr 'l2')")
    assert d.nrows == 4 and abs(col(d)[0]) < 1e-5


def test_runif(fr):
    r = rapids("(h2o.runif pfr 17)")
    assert r.nrows == 4 and np.all((col(r) >= 0) & (col(r) < 1))


# ------------------------------------------------------------------ mungers
def test_munger_predicates(fr):
    assert rapids("(any.factor pfr)") == 0.0
    assert rapids("(is.numeric (cols pfr 0))") == 1.0
    assert rapids("(is.factor (cols pfr 0))") == 0.0


def test_na_omit():
    Frame.from_numpy({"x": np.array([1.0, np.nan, 3.0])}, key="nfr")
    out = rapids("(na.omit nfr)")
    assert out.nrows == 2


def test_melt_pivot():
    Frame.from_numpy({"id": np.array([1.0, 2.0]),
                      "p": np.array([10.0, 20.0]),
                      "q": np.array([30.0, 40.0])}, key="mfr")
    melted = rapids("(melt mfr [0] [1 2] 'variable' 'value' False)")
    assert melted.nrows == 4
    assert set(melted.names) == {"id", "variable", "value"}
    melted2 = Frame(melted.names, melted.vecs, key="melted")
    piv = rapids("(pivot melted 'id' 'variable' 'value')")
    assert piv.nrows == 2 and "p" in piv.names and "q" in piv.names


def test_fillna():
    Frame.from_numpy({"x": np.array([1.0, np.nan, np.nan, 4.0])},
                     key="ffr")
    out = rapids("(h2o.fillna ffr 'forward' 0 1)")
    assert np.allclose(col(out), [1, 1, np.nan, 4], equal_nan=True)


def test_getrow_flatten(fr):
    assert rapids("(flatten (cols (rows pfr [0]) 0))") == 1.0
    assert rapids("(getrow (rows pfr [1]))") == [2.0, 6.0]


def test_rect_assign(fr):
    out = rapids("(:= pfr 99 [0] [1 2])")
    assert np.allclose(col(out), [1, 99, 99, 4])


def test_append(fr):
    out = rapids("(append pfr (* (cols pfr 0) 2) 'dbl')")
    assert "dbl" in out.names
    assert np.allclose(col(out, 2), [2, 4, 6, 8])


def test_levels_domain():
    Frame(["c"], [Vec.from_numpy(
        np.array(["x", "y", "x"], object), T_CAT)], key="cfr")
    lv = rapids("(levels cfr)")
    assert list(lv.vecs[0].to_numpy()[:2]) == ["x", "y"]
    assert rapids("(nlevels cfr)") == 2.0
    out = rapids("(setDomain cfr False ['xx' 'yy'])")
    assert list(out.vecs[0].decoded()[:3]) == ["xx", "yy", "xx"]
    rl = rapids("(relevel cfr 'y')")
    assert rl.vecs[0].domain[0] == "y"


def test_cut(fr):
    out = rapids("(cut (cols pfr 0) [0 2 5] [] False True 3)")
    v = out.vecs[0]
    assert v.type == T_CAT and len(v.domain) == 2


# ------------------------------------------------------------------ lambdas
def test_lambda_apply(fr):
    assert rapids("({x . (+ x 1)} 41)") == 42.0
    per_col = rapids("(apply pfr 2 {x . (sum x)})")
    assert np.allclose([col(per_col, 0)[0], col(per_col, 1)[0]], [10, 26])
    per_row = rapids("(apply pfr 1 'mean')")
    assert np.allclose(col(per_row), [3, 4, 5, 6])
    vec_row = rapids("(apply pfr 1 {row . (+ (cols row 0) (cols row 1))})")
    assert np.allclose(col(vec_row), [6, 8, 10, 12])


def test_ddply():
    Frame.from_numpy({"g": np.array([0.0, 0, 1, 1]),
                      "v": np.array([1.0, 3, 5, 9])}, key="dfr")
    out = rapids("(ddply dfr [0] {g . (mean (cols g 1))})")
    assert out.nrows == 2
    assert np.allclose(sorted(col(out, 1)), [2, 7])


# ------------------------------------------------------------------ string
def test_tokenize_grep_entropy():
    Frame(["t"], [Vec.from_numpy(
        np.array(["hello world", "foo bar", None], object), T_STR)],
        key="sfr")
    tok = rapids("(tokenize sfr ' ')")
    toks = list(tok.vecs[0].to_numpy()[: tok.nrows])
    assert toks[:2] == ["hello", "world"] and toks[2] is None
    g = rapids("(grep sfr 'foo' 0 0 0)")
    assert list(col(g)) == [1.0]
    e = rapids("(entropy sfr)")
    assert col(e)[0] > 0
    sl = rapids("(strlen sfr)")
    assert col(sl)[0] == 11.0


def test_str_distance():
    Frame(["a"], [Vec.from_numpy(np.array(["kitten"], object), T_STR)],
          key="sda")
    Frame(["b"], [Vec.from_numpy(np.array(["sitting"], object), T_STR)],
          key="sdb")
    d = rapids("(strDistance sda sdb 'lv' False)")
    assert col(d)[0] == 3.0


# ------------------------------------------------------------------ time
def test_time_fields():
    from h2o3_tpu.frame.vec import T_TIME
    ms = datetime.datetime(2021, 7, 4, 12, 30, 15,
                           tzinfo=datetime.timezone.utc).timestamp() * 1000
    Frame(["t"], [Vec.from_numpy(np.array([ms]), T_TIME)], key="tfr2")
    vals = {op: col(rapids(f"({op} tfr2)"))[0]
            for op in ("year", "month", "day", "hour", "minute", "second")}
    assert vals == {"year": 2021, "month": 7, "day": 4, "hour": 12,
                    "minute": 30, "second": 15}
    # 2021-07-04 is a Sunday -> dayOfWeek 6 (Mon=0)
    assert col(rapids("(dayOfWeek tfr2)"))[0] == 6.0


def test_mktime_roundtrip():
    # months/days are 0-based (AstMktime.java:55-56)
    out = rapids("(mktime 2021 6 3 12 30 15 0)")
    ms = col(out)[0]
    dt = datetime.datetime.fromtimestamp(ms / 1000.0,
                                         tz=datetime.timezone.utc)
    assert (dt.year, dt.month, dt.day, dt.hour) == (2021, 7, 4, 12)


def test_as_date():
    Frame(["d"], [Vec.from_numpy(
        np.array(["2020-01-31"], object), T_STR)], key="adf")
    out = rapids("(as.Date adf 'yyyy-MM-dd')")
    dt = datetime.datetime.fromtimestamp(col(out)[0] / 1000.0,
                                         tz=datetime.timezone.utc)
    assert (dt.year, dt.month, dt.day) == (2020, 1, 31)


# ------------------------------------------------------------------ ts/misc
def test_timeseries(fr):
    d = rapids("(difflag1 (cols pfr 0))")
    assert np.allclose(col(d), [1, 1, 1])
    sax = rapids("(isax pfr 2 4 0)")
    assert "iSax_index" in sax.names


def test_ls(fr):
    out = rapids("(ls)")
    assert out.nrows >= 1


def test_prim_count_target():
    """SURVEY/VERDICT coverage gate: >= 120 prims total."""
    from h2o3_tpu.rapids import ast as ast_mod
    from h2o3_tpu.rapids.prims import PRIMS
    import inspect
    src = inspect.getsource(ast_mod.Session._apply)
    core_ops = set()
    import re
    for m in re.finditer(r'op (?:==|in) \(?([^)\n:]+)\)?:', src):
        for tok in re.findall(r'"([^"]+)"', m.group(1)):
            core_ops.add(tok)
    for table in (ast_mod._UNARY, ast_mod._STRING, ast_mod._AGG):
        core_ops.update(table)
    total = len(core_ops | set(PRIMS))
    assert total >= 120, f"only {total} rapids prims"
