"""R client package consistency: every route the R source calls exists
on the server, and every NAMESPACE export is defined.

(No R interpreter ships in this image, so the package is validated
structurally + against the live route tables rather than executed —
the same routes are exercised end-to-end by the Python client tests.)
"""

import os
import re

ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "h2o3-r", "h2o3tpu")


def _r_sources():
    rdir = os.path.join(ROOT, "R")
    return {f: open(os.path.join(rdir, f)).read()
            for f in sorted(os.listdir(rdir)) if f.endswith(".R")}


def test_r_package_layout():
    assert os.path.exists(os.path.join(ROOT, "DESCRIPTION"))
    assert os.path.exists(os.path.join(ROOT, "NAMESPACE"))
    srcs = _r_sources()
    assert {"connection.R", "frame.R", "models.R", "automl.R"} <= set(srcs)


def test_r_namespace_exports_are_defined():
    ns = open(os.path.join(ROOT, "NAMESPACE")).read()
    exports = re.findall(r"^export\(([^)]+)\)", ns, re.M)
    assert len(exports) >= 40
    body = "\n".join(_r_sources().values())
    for fn in exports:
        pat = re.escape(fn) + r"\s*<-\s*function"
        assert re.search(pat, body), f"export {fn} has no definition"
    for s3 in re.findall(r"^S3method\((\w+),\s*(\w+)\)", ns, re.M):
        pat = re.escape(f"{s3[0]}.{s3[1]}") + r"\s*<-\s*function"
        assert re.search(pat, body), f"S3 method {s3} has no definition"


def test_r_routes_exist_on_server(cl):
    """Every literal route fragment in the R source must match a
    registered server route (client/server drift gate)."""
    from h2o3_tpu.api.server import H2OServer, _Handler
    srv = H2OServer(port=0)       # registers the route tables on _Handler
    try:
        patterns = (list(_Handler.routes_get)
                    + list(_Handler.routes_post)
                    + list(_Handler.routes_delete)
                    + [r"/3/Models\.upload\.bin"])
    finally:
        # never started serve_forever: close the socket directly
        # (shutdown() would block waiting for the serve loop)
        srv.httpd.server_close()
    body = "\n".join(_r_sources().values())
    called = set(re.findall(r'"(/(?:3|99)/[^"?]*)"', body))
    assert called, "no routes found in R sources"
    # literal prefix of each registered pattern (up to the first group)
    literals = [p.split("(")[0].replace("\\.", ".") for p in patterns]
    for route in called:
        # full-route fragments must fullmatch; paste0 prefixes (ending in
        # "/" or otherwise completed with a key) must extend to a
        # registered pattern's literal prefix
        ok = any(re.fullmatch(p, route) for p in patterns) or any(
            lit.startswith(route) or route.startswith(lit)
            for lit in literals if len(lit) > 4)
        assert ok, f"R client calls unregistered route {route!r}"


def test_r_balanced_delimiters():
    """Cheap syntax smoke for the R sources (no interpreter in image):
    quotes-aware paren/brace balance per file."""
    for name, src in _r_sources().items():
        stack = []
        pairs = {")": "(", "}": "{", "]": "["}
        in_str = None
        esc = False
        for i, ch in enumerate(src):
            if esc:
                esc = False
                continue
            if ch == "\\":
                esc = True
                continue
            if in_str:
                if ch == in_str:
                    in_str = None
                continue
            if ch in "\"'":
                in_str = ch
            elif ch == "#":
                nl = src.find("\n", i)
                if nl == -1:
                    break
                # skip to end of comment by faking a string until newline
                in_str = "\n"
            elif ch in "({[":
                stack.append(ch)
            elif ch in ")}]":
                assert stack and stack[-1] == pairs[ch], \
                    f"{name}: unbalanced {ch!r} at offset {i}"
                stack.pop()
        assert not stack, f"{name}: unclosed {stack[-3:]}"
