"""No-hardware Mosaic lowering gate (VERDICT r03 next-step #2).

Interpret mode lies: the real Mosaic compiler rejects programs interpret
mode accepts (PROFILE.md — f32 iotas, unit-minor-dim iota vectors).  This
gate cross-platform-lowers every histogram-kernel geometry bench.py uses
via ``jax.export(..., platforms=["tpu"])`` on the CPU host: Pallas runs its
TPU lowering + the Mosaic MLIR verifier at export time, so an illegal iota
form / op signature in ``hist.py`` fails HERE, without a chip.  (Verified:
a unit-minor-dim f32 iota raises VerificationError at export in this
image.)  The residual risk is the Mosaic *compiler* pass pipeline
(layout inference etc.), which only runs on a real backend — bench.py's
warmup covers that when the tunnel is up.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import export as jexport

import h2o3_tpu


@pytest.fixture(scope="module", autouse=True)
def _init():
    h2o3_tpu.init()


# bench.py's airlines shape: 8 features, nbins=256 -> B=257, depth 6.
# bin_counts mirror fit_bins on make_airlines_like: small-cardinality
# numerics (year/month/day), full-bin numerics, a 22-level cat, capped cats.
BENCH_BIN_COUNTS = (21, 12, 7, 256, 256, 22, 256, 256)
F, B, NBINS = 8, 257, 256
N_PADDED = 10_000_000 - (10_000_000 % (8 * 512))  # divisible by mesh*tile
BENCH_LEVELS = (1, 4, 32)                          # depth-6 level widths


def _lower_tpu(jitted, *arg_shapes):
    args = [jax.ShapeDtypeStruct(s, d) for s, d in arg_shapes]
    exp = jexport.export(jitted, platforms=["tpu"])(*args)
    assert len(exp.mlir_module_serialized) > 0
    return exp


def _stat_shapes(n):
    return ((F, n), jnp.int16), ((n,), jnp.int32), \
        ((n,), jnp.float32), ((n,), jnp.float32), ((n,), jnp.float32)


def test_varbin_int16_bf16_kernel_lowers_for_tpu():
    """The exact kernel path bench.py times (varbin + int16 codes + bf16
    stats), at every level width of a depth-6 build."""
    from h2o3_tpu.models.tree.hist import make_varbin_hist_fn
    for L in BENCH_LEVELS:
        fn = make_varbin_hist_fn(L, F, BENCH_BIN_COUNTS, B, N_PADDED)
        _lower_tpu(fn, *_stat_shapes(N_PADDED))


def test_varbin_f32_kernel_lowers_for_tpu():
    """reproducible=True forces f32 stat streaming — lower that too."""
    from h2o3_tpu.models.tree.hist import make_varbin_hist_fn
    fn = make_varbin_hist_fn(8, F, BENCH_BIN_COUNTS, B, N_PADDED,
                             precision="f32")
    _lower_tpu(fn, *_stat_shapes(N_PADDED))


def test_uniform_kernel_lowers_for_tpu():
    """The uniform-bin kernel (hist_type without per-feature bins), both
    the shallow and deep-L variants."""
    from h2o3_tpu.models.tree.hist import make_hist_fn
    for L in (1, 32):
        fn = make_hist_fn(L, F, B, N_PADDED)
        codes = ((F, N_PADDED), jnp.int32)
        rest = _stat_shapes(N_PADDED)[1:]
        _lower_tpu(fn, codes, *rest)


def test_hier_fine_kernel_lowers_for_tpu():
    """Opt-in split_search='hier' fine-refinement kernel."""
    from h2o3_tpu.models.tree.hist import make_fine_hist_fn
    W, K = 16, 2
    fn = make_fine_hist_fn(4, F, W, K, NBINS, N_PADDED)
    codes = ((F, N_PADDED), jnp.int32)
    leaf, g, h, w = _stat_shapes(N_PADDED)[1:]
    sel = ((4, F, K), jnp.int32)
    _lower_tpu(fn, codes, leaf, g, h, w, sel)


def test_subtract_level_lowers_for_tpu():
    """The smaller-sibling subtraction level program — count one-hot,
    cumsum-scatter compaction, varbin kernel over the N/2 prefix,
    reconstruction — as ONE exported TPU program at bench geometry.
    The compaction is plain XLA (scatter), but it composes with the
    Pallas custom call inside one shard_mapped jit; this proves the whole
    per-level program lowers for TPU from a CPU host."""
    from h2o3_tpu.models.tree.hist import make_subtract_level_fn
    from h2o3_tpu.runtime.cluster import cluster
    shards = cluster().n_row_shards
    for d in (1, 5):
        Lp = 2 ** (d - 1)
        fn = make_subtract_level_fn(d, F, B, N_PADDED,
                                    bin_counts=BENCH_BIN_COUNTS,
                                    force_impl="pallas")
        codes = ((F, N_PADDED), jnp.int16)
        leaf, g, h, w = _stat_shapes(N_PADDED)[1:]
        carry = ((shards, 3, Lp, F, B), jnp.float32)
        _lower_tpu(fn, codes, leaf, g, h, w, carry)


def test_sparse_level_lowers_for_tpu():
    """The node-sparse deep-level program — slot-table lookup (MXU
    one-hot product), parent-slot compaction, varbin kernel over the
    N/2 prefix, subtraction + slot-axis gather — as ONE exported TPU
    program at bench deep-level geometry (slot widths past the dense
    threshold, where hist_layout='auto' engages)."""
    from h2o3_tpu.models.tree.hist import make_sparse_level_fn
    from h2o3_tpu.runtime.cluster import cluster
    shards = cluster().n_row_shards
    for Ap, A in ((128, 256), (256, 512)):
        fn = make_sparse_level_fn(Ap, A, F, B, N_PADDED,
                                  bin_counts=BENCH_BIN_COUNTS,
                                  force_impl="pallas")
        codes = ((F, N_PADDED), jnp.int16)
        sleaf, g, h, w = _stat_shapes(N_PADDED)[1:]
        carry = ((shards, 3, Ap, F, B), jnp.float32)
        ps = ((A,), jnp.int32)
        _lower_tpu(fn, codes, sleaf, g, h, w, carry, ps)


def test_batched_sparse_level_lowers_for_tpu():
    """The batched-K sparse level (one launch for all K class trees at
    deep-level slot geometry) lowers for TPU — K prepends to the Pallas
    grid exactly as the dense batched kernel does."""
    from h2o3_tpu.models.tree.hist import make_batched_sparse_level_fn
    from h2o3_tpu.runtime.cluster import cluster
    shards = cluster().n_row_shards
    K, Ap, A = 3, 128, 256
    fn = make_batched_sparse_level_fn(Ap, A, K, F, B, N_PADDED,
                                      bin_counts=BENCH_BIN_COUNTS,
                                      force_impl="pallas")
    codes = ((F, N_PADDED), jnp.int16)
    rowK = ((K, N_PADDED), jnp.float32)
    sleafK = ((K, N_PADDED), jnp.int32)
    carry = ((shards, K, 3, Ap, F, B), jnp.float32)
    psK = ((K, A), jnp.int32)
    _lower_tpu(fn, codes, sleafK, rowK, rowK, rowK, carry, psK)


def test_split_records_kernel_lowers_for_tpu():
    """The fused coarse split search's winner-records kernel (triangular
    one-hot matmul cumsum + on-chip per-(leaf, feature) argmax) at every
    level width of a depth-6 build, plus the batched-K multinomial shape
    (K trees flatten into the leaf-row axis, so K*L*F rows is just a
    bigger grid of the same geometry)."""
    from functools import partial
    from h2o3_tpu.models.tree.hist import split_records

    for L in BENCH_LEVELS + (3 * 32,):             # K=3 classes at depth 5
        fn = jax.jit(partial(split_records, nbins=NBINS, reg_lambda=0.5,
                             min_rows=10.0, reg_alpha=0.1, gamma=0.1,
                             min_child_weight=1.0, force_impl="pallas"))
        _lower_tpu(fn, ((3, L, F, B), jnp.float32))


@pytest.mark.xfail(
    reason="jax 0.4.37 (the PR-1 compat downgrade) does not run the "
           "Mosaic MLIR verifier inside jax.export — the f32 "
           "unit-minor-dim iota exports cleanly here (verified directly: "
           "every known-bad kernel form exports without error on this "
           "jax). The gate's lowering tests above still catch op-signature "
           "and shape breakage; full Mosaic verification needs jax>=0.5 "
           "or a real TPU backend (the @slow AOT test below).",
    strict=False)
def test_export_catches_known_mosaic_violation():
    """Meta-test: the gate actually rejects the iota form PROFILE.md
    documents as interpret-accepted / chip-rejected — proving the gate
    sees Mosaic verification, not just StableHLO emission."""
    from jax.experimental import pallas as pl

    def bad_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] + jax.lax.broadcasted_iota(
            jnp.float32, (128, 1), 0)

    def f(x):
        return pl.pallas_call(bad_kernel, out_shape=jax.ShapeDtypeStruct(
            (128, 1), jnp.float32))(x)

    with pytest.raises(Exception, match="iota|Verification"):
        jexport.export(jax.jit(f), platforms=["tpu"])(
            jax.ShapeDtypeStruct((128, 1), jnp.float32))


@pytest.mark.slow
def test_aot_backend_compile_on_tpu_when_reachable():
    """FULL backend compilation (not just the MLIR verifier) of the
    geometries the round-4 chip session proved the export gate cannot
    judge: the bf16 stat-select layout (apply-vector-layout rejects
    non-32-bit minor-dim inserts) and the deep-level scoped-VMEM budget
    (L=256 uniform kernel).  Runs only when a real TPU backend is
    reachable — on the CPU CI mesh it skips; in a chip session it is the
    cheap pre-flight that keeps kernel regressions from burning tunnel
    time (VERDICT r03 next-step #2)."""
    if jax.devices()[0].platform != "tpu":
        pytest.skip("no TPU backend in this environment")
    from h2o3_tpu.models.tree.hist import make_varbin_hist_fn, make_hist_fn

    n = 512 * 1024                      # small rows: compile-only check
    # varbin + int16 codes + bf16 stats (the bench path)
    fn = make_varbin_hist_fn(32, F, BENCH_BIN_COUNTS, B, n)
    args = [jax.ShapeDtypeStruct(s, d) for s, d in
            (((F, n), jnp.int16), ((n,), jnp.int32), ((n,), jnp.float32),
             ((n,), jnp.float32), ((n,), jnp.float32))]
    fn.lower(*args).compile()
    # deep-level uniform kernel (L=256 -> R shrunk against the VMEM stack)
    fn2 = make_hist_fn(256, 3, 33, n)
    args2 = [jax.ShapeDtypeStruct(s, d) for s, d in
             (((3, n), jnp.int32), ((n,), jnp.int32), ((n,), jnp.float32),
              ((n,), jnp.float32), ((n,), jnp.float32))]
    fn2.lower(*args2).compile()
