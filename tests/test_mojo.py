"""Portable-artifact round-trip tests: in-cluster predict == offline scorer.

Mirrors the reference's testdir_javapredict strategy: train in the cluster,
export the artifact, score with the standalone (numpy-only) library, compare.
"""

import sys

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.models import (GLM, GBM, DRF, XGBoost, DeepLearning, KMeans,
                             NaiveBayes, PCA, IsotonicRegression,
                             IsolationForest)


def _frames(rng, n=800):
    X = rng.normal(size=(n, 3))
    cat = np.array(["u", "v", "w"], dtype=object)[rng.integers(0, 3, n)]
    y_num = X @ [1.0, -2.0, 0.5] + (cat == "v") * 1.5 + 0.1 * rng.normal(size=n)
    y_bin = np.where(y_num > 0, "yes", "no").astype(object)
    cols = {"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2], "c": cat}
    data = dict(cols)
    return (Frame.from_numpy({**cols, "y": y_num}),
            Frame.from_numpy({**cols, "y": y_bin}), data)


def _roundtrip(model, frame, data, tmp_path, atol=2e-4):
    path = model.download_mojo(str(tmp_path / f"{model.algo}.zip"))
    sm = h2o3_tpu.import_mojo(path)
    assert "jax" not in type(sm).__module__
    out = sm.predict(data)
    pred = model.predict(frame)
    if model.datainfo.is_classifier:
        probs = np.stack([v.to_numpy() for v in pred.vecs[1:]], axis=1)
        np.testing.assert_allclose(out["probabilities"], probs, atol=atol)
        assert (out["predict"] == pred.vecs[0].decoded()).mean() > 0.999
    else:
        np.testing.assert_allclose(out["predict"],
                                   pred.vecs[0].to_numpy(), atol=atol,
                                   rtol=1e-4)
    return sm


def test_glm_mojo(cl, rng, tmp_path):
    fr_num, fr_bin, data = _frames(rng)
    _roundtrip(GLM(response_column="y", lambda_=1e-4).train(fr_num),
               fr_num, data, tmp_path)
    _roundtrip(GLM(response_column="y", family="binomial",
                   lambda_=1e-4).train(fr_bin), fr_bin, data, tmp_path)


def test_tree_mojos(cl, rng, tmp_path):
    fr_num, fr_bin, data = _frames(rng)
    _roundtrip(GBM(response_column="y", ntrees=10, seed=1).train(fr_num),
               fr_num, data, tmp_path)
    _roundtrip(XGBoost(response_column="y", ntrees=10, seed=1).train(fr_bin),
               fr_bin, data, tmp_path)
    _roundtrip(DRF(response_column="y", ntrees=10, seed=1,
                   max_depth=6).train(fr_bin), fr_bin, data, tmp_path)


def test_tree_mojo_multinomial(cl, rng, tmp_path):
    n = 600
    X = rng.normal(size=(n, 3))
    cls = np.argmax(X + 0.2 * rng.normal(size=(n, 3)), axis=1)
    cols = {f"x{j}": X[:, j] for j in range(3)}
    fr = Frame.from_numpy({**cols,
                           "y": np.array(["a", "b", "c"],
                                         dtype=object)[cls]})
    m = GBM(response_column="y", ntrees=8, seed=1).train(fr)
    _roundtrip(m, fr, cols, tmp_path)


def test_deeplearning_kmeans_nb_pca_mojo(cl, rng, tmp_path):
    fr_num, fr_bin, data = _frames(rng)
    _roundtrip(DeepLearning(response_column="y", hidden=[16], epochs=3,
                            seed=1).train(fr_bin), fr_bin, data, tmp_path,
               atol=1e-3)
    km = KMeans(k=3, seed=1).train(fr_num["x0", "x1"] if False else
                                   Frame.from_numpy({"x0": data["x0"],
                                                     "x1": data["x1"]}))
    path = km.download_mojo(str(tmp_path / "km.zip"))
    sm = h2o3_tpu.import_mojo(path)
    out = sm.predict({"x0": data["x0"], "x1": data["x1"]})
    pred = km.predict(Frame.from_numpy({"x0": data["x0"],
                                        "x1": data["x1"]}))
    assert (out["predict"].astype(int)
            == pred.vecs[0].to_numpy().astype(int)).mean() > 0.999
    _roundtrip(NaiveBayes(response_column="y").train(fr_bin), fr_bin, data,
               tmp_path, atol=1e-3)
    pca = PCA(k=2, transform="demean").train(
        Frame.from_numpy({k: data[k] for k in ("x0", "x1", "x2")}))
    sm = h2o3_tpu.import_mojo(pca.download_mojo(str(tmp_path / "p.zip")))
    Z = sm._score(
        {k: np.asarray(data[k]) for k in ("x0", "x1", "x2")}, len(data["x0"]))
    Zm = np.stack([v.to_numpy() for v in pca.predict(Frame.from_numpy(
        {k: data[k] for k in ("x0", "x1", "x2")})).vecs], axis=1)
    np.testing.assert_allclose(Z, Zm, atol=1e-3)


def test_isotonic_isofor_mojo(cl, rng, tmp_path):
    n = 500
    x = np.sort(rng.uniform(-2, 2, n))
    y = x + 0.2 * rng.normal(size=n)
    iso = IsotonicRegression(response_column="y").train(
        Frame.from_numpy({"x": x, "y": y}))
    sm = h2o3_tpu.import_mojo(iso.download_mojo(str(tmp_path / "i.zip")))
    out = sm.predict({"x": x})
    np.testing.assert_allclose(
        out["predict"], iso.predict(Frame.from_numpy({"x": x}))
        .vecs[0].to_numpy(), atol=5e-4)

    fr = Frame.from_numpy({"a": rng.normal(size=n), "b": rng.normal(size=n)})
    anom = IsolationForest(ntrees=15, seed=2).train(fr)
    sm = h2o3_tpu.import_mojo(anom.download_mojo(str(tmp_path / "a.zip")))
    out = sm.predict({"a": fr.vec("a").to_numpy(),
                      "b": fr.vec("b").to_numpy()})
    np.testing.assert_allclose(out["predict"],
                               anom.predict(fr).vecs[0].to_numpy(),
                               atol=1e-4)


def test_single_row_dict(cl, rng, tmp_path):
    fr_num, fr_bin, data = _frames(rng)
    m = GBM(response_column="y", ntrees=5, seed=1).train(fr_bin)
    sm = h2o3_tpu.import_mojo(m.download_mojo(str(tmp_path / "g.zip")))
    row = {"x0": 0.5, "x1": -1.0, "x2": 0.2, "c": "v"}
    out = sm.predict(row)
    assert out["predict"] in ("yes", "no")
    assert out["probabilities"].shape == (2,)
