"""Infogram / admissible ML golden tests (hex/Infogram analog)."""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.models import Infogram


def _core_data(rng, n=900):
    x1 = rng.normal(size=n).astype(np.float32)       # strong signal
    x2 = rng.normal(size=n).astype(np.float32)       # moderate signal
    noise = rng.normal(size=n).astype(np.float32)    # pure noise
    logit = 2.5 * x1 + 1.0 * x2
    y = rng.random(n) < 1 / (1 + np.exp(-logit))
    return h2o3_tpu.H2OFrame({
        "x1": x1, "x2": x2, "noise": noise,
        "y": np.where(y, "Y", "N").astype(object)})


def test_core_infogram(cl, rng):
    fr = _core_data(rng)
    ig = Infogram(response_column="y", seed=7,
                  infogram_algorithm_params={"ntrees": 10,
                                             "max_depth": 4}).train(fr)
    rows = {d["column"]: d for d in ig.output["admissible_score"]}
    assert set(rows) == {"x1", "x2", "noise"}
    # the strong signal dominates both axes (normalized to 1.0)
    assert rows["x1"]["relevance"] == pytest.approx(1.0)
    assert rows["x1"]["cmi"] == pytest.approx(1.0)
    # noise is neither relevant nor informative
    assert rows["noise"]["relevance"] < 0.1
    assert rows["noise"]["cmi"] < 0.35
    assert "x1" in ig.admissible_features
    assert "noise" not in ig.admissible_features
    # sorted by admissible_index descending, thresholds recorded
    idx = [d["admissible_index"] for d in ig.output["admissible_score"]]
    assert idx == sorted(idx, reverse=True)
    assert ig.output["build_core"] is True
    assert ig.output["nmodels_trained"] == 4
    with pytest.raises(NotImplementedError):
        ig.predict(fr)


def test_fair_infogram_flags_proxy_feature(cl, rng):
    n = 900
    protected = rng.integers(0, 2, n)                # protected attribute
    x_safe = rng.normal(size=n).astype(np.float32)   # legitimate signal
    # proxy: almost a copy of the protected attribute
    x_leak = (protected + rng.normal(0, 0.05, n)).astype(np.float32)
    logit = 2.0 * x_safe + 2.0 * (protected - 0.5)
    y = rng.random(n) < 1 / (1 + np.exp(-logit))
    fr = h2o3_tpu.H2OFrame({
        "x_safe": x_safe, "x_leak": x_leak,
        "prot": np.where(protected == 1, "a", "b").astype(object),
        "y": np.where(y, "Y", "N").astype(object)})
    ig = Infogram(response_column="y", protected_columns=["prot"], seed=7,
                  infogram_algorithm_params={"ntrees": 10,
                                             "max_depth": 4}).train(fr)
    rows = {d["column"]: d for d in ig.output["admissible_score"]}
    assert set(rows) == {"x_safe", "x_leak"}
    # conditioned on the protected column, the proxy adds ~no information
    assert rows["x_safe"]["cmi"] == pytest.approx(1.0)
    assert rows["x_leak"]["cmi"] < 0.2
    assert ig.admissible_features == ["x_safe"]
    assert ig.output["build_core"] is False


def test_infogram_over_rest(cl, rng, tmp_path):
    """Infogram exposed through /3/ModelBuilders/infogram."""
    from h2o3_tpu.api import start_server
    from h2o3_tpu import client as h2oc
    fr = _core_data(rng, n=500)
    fr.key = "ig_frame"
    from h2o3_tpu.runtime import dkv
    dkv.put("ig_frame", fr)
    server = start_server(port=0)
    try:
        conn = h2oc.connect(server.url)
        m = conn.train("infogram", "ig_frame", response_column="y", seed=3,
                       infogram_algorithm_params={"ntrees": 5,
                                                  "max_depth": 3})
        out = m.schema["output"]
        assert out["build_core"] is True
    finally:
        server.stop()
