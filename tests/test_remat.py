"""Chaos row: kill a host mid-GBM on a 4-host virtual mesh and prove
recovery re-parses ONLY the dead host's byte ranges (counted via the
``parse_range`` injection point), with predictions matching an
uninterrupted run.  Also: derived frames resume through lineage replay
(no source URI journaled — previously unresumable), a failed re-mat
degrades to full re-import instead of producing wrong data, and a failed
re-import is surfaced as a visible downgrade rather than a silent skip.
``tools/chaos.sh`` runs this module as the ``remat-partial`` row.
"""

import json
import time

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.frame import lineage
from h2o3_tpu.frame.parse import import_file
from h2o3_tpu.models import GBM
from h2o3_tpu.runtime import dkv, failure, heartbeat, recovery, remat
from h2o3_tpu.runtime.observability import counter, timeline_events

NTREES = 8
_GBM_PARAMS = dict(response_column="y", ntrees=NTREES, max_depth=3,
                   learn_rate=0.2, seed=7, score_tree_interval=2)


def _write_csv(path, seed=11, n=600):
    rng = np.random.default_rng(seed)
    X = rng.random((n, 4))
    y = (10 * np.sin(np.pi * X[:, 0]) + 5 * X[:, 1] ** 2
         + 3 * X[:, 2] + 0.1 * rng.normal(size=n))
    rows = np.column_stack([X, y])
    path.write_text("x0,x1,x2,x3,y\n" + "\n".join(
        ",".join(f"{v:.9g}" for v in r) for r in rows))
    return str(path)


def _drop(*keys):
    for k in keys:
        dkv.remove(k)
        lineage.drop_record(k)


def test_host_kill_midtrain_repairs_only_lost_shards(cl, tmp_path,
                                                     monkeypatch):
    """The acceptance scenario: host 2 of 4 dies mid-GBM.  The watchdog
    stamps its jax process index into the failure record, the journal
    keeps the job 'running', and resume() repairs the frame by copying
    the three survivor shards and re-parsing exactly ONE byte range —
    proven by arming ``parse_range`` to raise on its second invocation."""
    monkeypatch.setenv("H2O3_TPU_RECOVERY_DIR", str(tmp_path))
    csv = _write_csv(tmp_path / "remat4.csv")
    orig_hosts = cl.n_hosts
    h2o3_tpu.init(hosts=4)
    failure.reset()
    try:
        fr = import_file(csv, destination_frame="remat4_fr")
        rec = lineage.get_record("remat4_fr")
        assert rec is not None and rec["n_shards"] == 4

        ref = GBM(**_GBM_PARAMS).train(fr)
        ref_pred = ref.predict(fr).to_numpy()[:, 0]
        assert not list(tmp_path.glob("job_*.json"))   # clean baseline

        # host 2 stops heartbeating long enough to be classified dead;
        # its stamp carries the jax process index the repair needs
        dkv.put(heartbeat.PREFIX + "ghost:9",
                {"ts": time.time() - 60.0, "interval": 5.0, "pid": 9,
                 "proc": 2})
        assert failure.check(hb_interval=5.0) == ["ghost:9"]
        frec = dkv.get(failure.FAILURES_PREFIX + "ghost:9")
        assert frec["host_index"] == 2
        assert remat.lost_host_indices() == {2}

        # the in-flight train dies on the degraded cluster: the journal
        # entry must stay 'running' (resumable), not flip to 'failed'
        monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "tree_chunk:0:2:raise")
        with pytest.raises(failure.InjectedFault):
            GBM(**_GBM_PARAMS).train(fr)
        (entry_path,) = tmp_path.glob("job_*.json")
        assert json.loads(entry_path.read_text())["status"] == "running"

        # resume while degraded: a SECOND ranged re-parse would raise —
        # recovery must touch only the dead host's byte range
        monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "parse_range:0:2:raise")
        before_copy = counter("remat_shards_total", mode="copy").value
        done = recovery.resume(str(tmp_path))
        monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
        assert len(done) == 1

        s2 = rec["shards"][2]
        assert remat.last_stats["frame"] == "remat4_fr"
        assert remat.last_stats["reparsed"] == [[s2["lo"], s2["hi"]]]
        assert sorted(remat.last_stats["copied"]) == [0, 1, 3]
        assert counter("remat_shards_total", mode="copy").value \
            == before_copy + 3

        model = dkv.get(done[0])
        assert model.output["ntrees_trained"] == NTREES
        res_pred = model.predict(dkv.get("remat4_fr")).to_numpy()[:, 0]
        np.testing.assert_allclose(res_pred, ref_pred, rtol=1e-4, atol=1e-4)
        assert not list(tmp_path.glob("job_*.json"))
    finally:
        monkeypatch.delenv("H2O3_TPU_FAULT_INJECT", raising=False)
        failure.reset()
        dkv.remove(heartbeat.PREFIX + "ghost:9")
        dkv.remove(failure.FAILURES_PREFIX + "ghost:9")
        _drop("remat4_fr")
        h2o3_tpu.init(hosts=orig_hosts)


def test_derived_frame_resumes_via_lineage_replay(cl, tmp_path,
                                                  monkeypatch):
    """A job trained on a split piece has NO journaled source URI — after
    a restart that loses the frame, lineage replay is the only automated
    path back (previously these entries were unresumable)."""
    monkeypatch.setenv("H2O3_TPU_RECOVERY_DIR", str(tmp_path))
    failure.reset()
    csv = _write_csv(tmp_path / "derived.csv", seed=13)
    try:
        root = import_file(csv, destination_frame="remat_droot")
        train = root.split_frame([0.8, 0.2], seed=5)[0]
        lineage.register(train, "remat_dtrain")
        ref = GBM(**_GBM_PARAMS).train(train)
        ref_pred = ref.predict(train).to_numpy()[:, 0]

        failure._handled.add("ghost")   # degraded: journal stays running

        class BoomGBM(GBM):
            def _fit(self, *a, **k):
                raise RuntimeError("collective aborted: peer gone")

        BoomGBM.__name__ = "GBM"
        with pytest.raises(RuntimeError):
            BoomGBM(**_GBM_PARAMS).train(train)
        (entry_path,) = tmp_path.glob("job_*.json")
        entry = json.loads(entry_path.read_text())
        assert entry["status"] == "running"
        assert entry["frame_source"] is None      # nothing to re-import

        # "restart": frames gone from the DKV, cluster healthy again
        failure.reset()
        dkv.remove("remat_dtrain")
        dkv.remove("remat_droot")
        done = recovery.resume(str(tmp_path))
        assert len(done) == 1
        assert remat.last_stats["frame"] == "remat_dtrain"
        assert remat.last_stats["mode"] == "replay"
        model = dkv.get(done[0])
        res_pred = model.predict(train).to_numpy()[:, 0]
        np.testing.assert_allclose(res_pred, ref_pred, rtol=1e-4, atol=1e-4)
    finally:
        failure.reset()
        _drop("remat_droot", "remat_dtrain")


def test_failed_remat_degrades_to_full_reimport(cl, tmp_path, monkeypatch):
    """The ``remat`` injection point fires at the top of every rebuild:
    a raise there must degrade to a full source re-import — never wrong
    data, and the downgrade lands on the timeline."""
    monkeypatch.setenv("H2O3_TPU_RECOVERY_DIR", str(tmp_path))
    failure.reset()
    csv = _write_csv(tmp_path / "degrade.csv", seed=17)
    try:
        fr = import_file(csv, destination_frame="remat_degr_fr")
        ref = GBM(**_GBM_PARAMS).train(fr)
        ref_pred = ref.predict(fr).to_numpy()[:, 0]

        failure._handled.add("ghost")

        class BoomGBM(GBM):
            def _fit(self, *a, **k):
                raise RuntimeError("collective aborted: peer gone")

        BoomGBM.__name__ = "GBM"
        with pytest.raises(RuntimeError):
            BoomGBM(**_GBM_PARAMS).train(fr)
        failure.reset()
        dkv.remove("remat_degr_fr")

        monkeypatch.setenv("H2O3_TPU_FAULT_INJECT", "remat:0:1:raise")
        done = recovery.resume(str(tmp_path))
        monkeypatch.delenv("H2O3_TPU_FAULT_INJECT")
        assert len(done) == 1
        falls = [e for e in timeline_events(500)
                 if e.get("kind") == "remat_fallback"]
        assert falls and falls[-1]["frame"] == "remat_degr_fr"
        model = dkv.get(done[0])
        res_pred = model.predict(dkv.get("remat_degr_fr")).to_numpy()[:, 0]
        np.testing.assert_allclose(res_pred, ref_pred, rtol=1e-4, atol=1e-4)
    finally:
        monkeypatch.delenv("H2O3_TPU_FAULT_INJECT", raising=False)
        failure.reset()
        _drop("remat_degr_fr")


def test_reimport_failure_surfaces_downgrade(cl, tmp_path):
    """Satellite: when lineage can't rebuild AND the source re-import
    fails, the skip is no longer silent — counter bump, timeline event,
    and a ``downgrade`` stanza in the journal entry + status report."""
    entry = {"algo": "GBM", "params": {}, "frame_key": "vanished_fr",
             "frame_source": str(tmp_path / "missing.csv"),
             "status": "running"}
    p = tmp_path / "job_vanished.json"
    p.write_text(json.dumps(entry))
    before = counter("recovery_reimport_failed_total").value
    assert recovery.resume(str(tmp_path)) == []
    assert counter("recovery_reimport_failed_total").value == before + 1
    evs = [e for e in timeline_events(500)
           if e.get("kind") == "recovery_reimport_failed"]
    assert evs and evs[-1]["frame"] == "vanished_fr"
    updated = json.loads(p.read_text())
    assert updated["downgrade"]["reimport_failed"]
    assert updated["downgrade"]["error"]
    status = recovery.journal_status(str(tmp_path))
    assert any((e.get("downgrade") or {}).get("reimport_failed")
               for e in status)
