"""Frame/Vec/parse tests — analog of water/fvec tests + parser pyunits."""

import io

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.frame.vec import T_CAT, T_NUM, T_STR, T_TIME
from h2o3_tpu.runtime.mapreduce import map_partitions, map_reduce


CSV = """id,age,city,income,signup,comment
1,34,ny,55000.5,2021-01-02,hello
2,28,sf,72000,2021-02-03,world
3,,ny,NA,2021-03-04,foo
4,45,la,91000,2021-04-05,bar
5,51,sf,,2021-05-06,baz
"""


def make_frame(cl):
    return h2o3_tpu.upload_string(CSV, destination_frame="f1")


def test_parse_types(cl):
    f = make_frame(cl)
    assert f.shape == (5, 6)
    t = f.types()
    assert t["id"] == T_NUM and t["age"] == T_NUM and t["income"] == T_NUM
    assert t["city"] == T_CAT
    assert t["signup"] == T_TIME
    assert sorted(f.vec("city").domain) == ["la", "ny", "sf"]


def test_rollups(cl):
    f = make_frame(cl)
    age = f.vec("age")
    r = age.rollups()
    assert r.nmissing == 1
    assert r.vmin == 28 and r.vmax == 51
    np.testing.assert_allclose(r.mean, np.mean([34, 28, 45, 51]), rtol=1e-6)
    np.testing.assert_allclose(
        r.sigma, np.std([34, 28, 45, 51], ddof=1), rtol=1e-5)


def test_padding_and_sharding(cl):
    f = make_frame(cl)
    v = f.vec("age")
    assert v.padded_len % cl.row_multiple() == 0
    assert v.data.sharding.spec[0] == ("hosts", "chips")
    back = v.to_numpy()
    assert len(back) == 5
    assert np.isnan(back[2])


def test_cat_decode_roundtrip(cl):
    f = make_frame(cl)
    city = f.vec("city").decoded()
    assert list(city) == ["ny", "sf", "ny", "la", "sf"]


def test_frame_munging(cl):
    f = make_frame(cl)
    g = f[["age", "income"]]
    assert g.names == ["age", "income"]
    h = f.drop("comment")
    assert "comment" not in h.names
    sub = f.filter(np.array([True, False, True, False, True]))
    assert sub.nrows == 3
    assert list(sub.vec("id").to_numpy()) == [1, 3, 5]


def test_split_frame(cl):
    big = h2o3_tpu.Frame.from_numpy(
        {"x": np.arange(1000, dtype=np.float32)}, key="big")
    a, b = big.split_frame([0.75], seed=1)
    assert a.nrows + b.nrows == 1000
    assert 650 < a.nrows < 850


def test_matrix(cl):
    f = make_frame(cl)
    m = f.matrix(["age", "income"])
    assert m.shape == (f.padded_rows, 2)
    assert m.sharding.spec[0] == ("hosts", "chips")


def test_dkv(cl):
    make_frame(cl)
    assert "f1" in h2o3_tpu.ls()
    assert h2o3_tpu.get_frame("f1").nrows == 5
    h2o3_tpu.remove("f1")
    with pytest.raises(KeyError):
        h2o3_tpu.get_frame("f1")


def test_map_reduce(cl, rng):
    x = h2o3_tpu.Vec.from_numpy(rng.normal(size=1000).astype(np.float32))
    valid = x.valid_mask()

    def msum(data, mask):
        import jax.numpy as jnp
        return jnp.sum(jnp.where(mask, data, 0.0))

    total = map_reduce(msum, x.data, valid)
    np.testing.assert_allclose(float(total), float(np.sum(x.to_numpy())),
                               rtol=1e-4)


def test_map_partitions(cl, rng):
    x = h2o3_tpu.Vec.from_numpy(np.arange(64, dtype=np.float32))
    doubled = map_partitions(lambda d: d * 2, x.data)
    np.testing.assert_allclose(np.asarray(doubled)[:64], np.arange(64) * 2)


def test_string_column_host_side(cl):
    f = make_frame(cl)
    c = f.vec("comment")
    assert c.type == T_CAT or c.type == T_STR  # low-card text may be cat
    vals = list(c.decoded())
    assert vals == ["hello", "world", "foo", "bar", "baz"]


def test_time_precision_roundtrip(cl):
    # float32 device storage must not destroy sub-minute timestamp resolution
    f = h2o3_tpu.upload_string(
        "t\n2021-01-02 00:00:00\n2021-01-02 00:01:00\n2021-01-02 00:01:30\n")
    t = f.vec("t")
    assert t.type == T_TIME
    ms = t.to_numpy()
    assert ms[1] - ms[0] == 60_000.0 and ms[2] - ms[1] == 30_000.0
    # device payload is rebased seconds: distinct and well-conditioned
    dev = np.asarray(t.data)[:3]
    np.testing.assert_allclose(dev, [0.0, 60.0, 90.0], atol=1e-3)


def test_split_frame_ratios_sum_to_one(cl):
    big = h2o3_tpu.Frame.from_numpy({"x": np.arange(1000, dtype=np.float32)})
    parts = big.split_frame([0.1] * 10, seed=3)
    assert len(parts) == 10
    assert sum(p.nrows for p in parts) == 1000


def test_from_numpy_explicit_cat(cl):
    f = h2o3_tpu.Frame.from_numpy({"c": np.array(["a", "b", "a"])},
                                  types={"c": T_CAT})
    assert f.vec("c").domain == ["a", "b"]
    assert list(f.vec("c").decoded()) == ["a", "b", "a"]


def test_all_missing_column_rollups(cl):
    f = h2o3_tpu.upload_string("x,y\nNA,1\nNA,2\n", col_types={"x": T_NUM})
    r = f.vec("x").rollups()
    assert r.nmissing == 2
    assert np.isnan(r.mean) and np.isnan(r.vmin)


def test_reinit_geometry_change_rebuilds(cl):
    # re-init with a different geometry rebuilds the mesh (recording a
    # cluster_reinit event) instead of raising or silently returning the
    # stale cached one — see tests/test_mesh_hier.py for the full contract
    from h2o3_tpu.runtime import observability as obs
    try:
        c2 = h2o3_tpu.init(model_axis=4)
        assert dict(c2.mesh.shape)["model"] == 4
        assert any(e.get("kind") == "cluster_reinit"
                   for e in obs.timeline_events(1000))
    finally:
        restored = h2o3_tpu.init(model_axis=1)
        assert dict(restored.mesh.shape)["model"] == 1
        assert restored.n_row_shards == cl.n_row_shards


def test_spill_and_transparent_restore(cl, rng):
    from h2o3_tpu.runtime import cleaner, dkv
    fr = h2o3_tpu.Frame.from_numpy(
        {"a": rng.normal(size=100), "g": np.array(["x", "y"], object)[
            rng.integers(0, 2, 100)]}, key="spillme")
    a0 = fr.vec("a").to_numpy().copy()
    freed = fr.spill()
    assert freed > 0
    assert fr.vec("a").is_spilled and fr.vec("g").is_spilled
    assert fr.vec("a")._device is None
    # host reads serve from the spill buffer without touching HBM
    np.testing.assert_array_equal(fr.vec("a").to_numpy(), a0)
    assert fr.vec("a").is_spilled
    assert fr.vec("a").padded_len >= 100
    # device access transparently restores, dtype preserved
    assert fr.vec("a").data is not None
    assert not fr.vec("a").is_spilled
    np.testing.assert_array_equal(fr.vec("a").to_numpy(), a0)
    assert fr.vec("g").data.dtype == np.int32     # cat codes restored
    # cleaner targets LRU frames and skips excluded keys
    fr2 = h2o3_tpu.Frame.from_numpy({"b": rng.normal(size=50)},
                                    key="hot")
    fr2.vec("b")                                   # touch: most recent
    got = cleaner.spill_until(1 << 40, exclude=["hot"])
    assert got > 0 and fr.vec("a").is_spilled
    assert not fr2.vec("b").is_spilled
    dkv.remove("spillme"); dkv.remove("hot")


def test_frame_munging_sugar(cl):
    left = h2o3_tpu.Frame.from_numpy({
        "k": np.array([3.0, 1.0, 2.0]), "v": np.array([30.0, 10.0, 20.0])})
    right = h2o3_tpu.Frame.from_numpy({
        "k": np.array([1.0, 2.0]), "w": np.array([100.0, 200.0])})
    s = left.sort("k")
    np.testing.assert_array_equal(s.vec("k").to_numpy(), [1.0, 2.0, 3.0])
    m = left.merge(right, "k")
    assert m.nrows == 2 and "w" in m.names
    g = left.group_by("k", {"v": ["sum"]})
    assert g.nrows == 3
    c = left.cor(["k", "v"])
    assert abs(c["matrix"][0, 1] - 1.0) < 1e-6   # v = 10*k exactly
    sc = left.scale()
    assert abs(float(np.mean(sc.vec("v").to_numpy()))) < 1e-6
    v = left.var(["k", "v"])
    assert abs(v["matrix"][0, 0] - 1.0) < 1e-6   # var of 1,2,3
    na = h2o3_tpu.Frame.from_numpy({"a": np.array([1.0, np.nan, 3.0])})
    imp = na.impute("a", method="median", combine_method="lo")
    assert np.isfinite(imp.vec("a").to_numpy()).all()


def test_assign_and_deep_copy(cl):
    fr = h2o3_tpu.Frame.from_numpy({"a": np.arange(4.0)}, key="orig_k")
    out = h2o3_tpu.assign(fr, "alias1")
    # true rebind: same frame object, old binding released
    assert out is fr and fr.key == "alias1"
    assert "alias1" in h2o3_tpu.ls() and "orig_k" not in h2o3_tpu.ls()
    cp = h2o3_tpu.deep_copy(fr, "copy_x")
    # device payloads are immutable and shared; wrappers independent
    assert cp.vec("a") is not fr.vec("a")
    np.testing.assert_array_equal(cp.vec("a").to_numpy(),
                                  fr.vec("a").to_numpy())
    # spilled columns stay spilled through deep_copy (no HBM restore)
    fr.spill()
    cp2 = h2o3_tpu.deep_copy(fr, "copy_y")
    assert fr.vec("a").is_spilled and cp2.vec("a").is_spilled
    np.testing.assert_array_equal(cp2.vec("a").to_numpy(),
                                  np.arange(4.0))
    h2o3_tpu.remove("alias1")
    h2o3_tpu.remove("copy_x"); h2o3_tpu.remove("copy_y")


def test_load_dataset(cl):
    import pytest
    pytest.importorskip("sklearn")
    iris = h2o3_tpu.load_dataset("iris")
    assert iris.shape == (150, 5)
    assert iris.vec("class").domain is not None
    assert len(iris.vec("class").domain) == 3
    assert iris.key in h2o3_tpu.ls()          # DKV-registered like loaders
    from h2o3_tpu.models import GBM
    m = GBM(response_column="class", ntrees=3, max_depth=3,
            seed=1).train(iris)
    assert m.training_metrics is not None
    with pytest.raises(ValueError, match="available"):
        h2o3_tpu.load_dataset("nope")


def test_describe_and_progress_toggles(cl):
    import logging
    fr = h2o3_tpu.Frame.from_numpy({"a": np.arange(5.0)})
    assert fr.describe() == fr.summary()
    lg = logging.getLogger("h2o3_tpu")
    before = lg.level
    h2o3_tpu.no_progress()
    assert lg.level == logging.WARNING
    h2o3_tpu.show_progress()
    assert lg.level == before        # restores the PRIOR level exactly
