"""Smaller-sibling histogram subtraction: exactness + regression pins.

The subtraction level driver (hist.make_subtract_level_fn) compacts each
parent's smaller child into a dense row prefix per shard, histograms only
that prefix and reconstructs the larger sibling as parent - small from a
per-shard carry.  These tests pin (a) histogram-level parity against the
full build across chained levels, shards, weights and NA bins, (b) that
the compaction loses no rows under extreme skew (terminal leaves), and
(c) whole-model parity: GBM / DRF / uplift grow IDENTICAL trees through
hist_mode="subtract" and the hist_mode="full" oracle (tier-1 CPU shapes,
including categorical varbin features) — plus a seed-determinism pin for
isolation forest, which shares shared.py's tree plumbing.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from h2o3_tpu.models.tree.hist import (make_hist_fn, make_subtract_level_fn,
                                       offset_codes)


def _chain_leaves(rng, N, depth, p_right=0.3):
    """Consistent leaf assignments per level (child of previous level)."""
    leaves = [np.zeros(N, np.int64)]
    for _ in range(1, depth):
        bit = (rng.random(N) < p_right).astype(np.int64)
        leaves.append(2 * leaves[-1] + bit)
    return leaves


def test_subtract_level_parity_chain(cl, rng):
    """Chained subtraction levels == full einsum build, with zero-weight
    rows and NA codes in the mix (8-shard CPU mesh)."""
    N, F, nbins, depth = 2048, 5, 16, 4
    B = nbins + 1
    codes_np = rng.integers(0, B, (F, N))            # includes NA code
    codes = jnp.asarray(codes_np, jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.asarray(rng.random(N), jnp.float32)
    w = jnp.asarray((rng.random(N) > 0.15), jnp.float32)
    carry = None
    for d, leaf_np in enumerate(_chain_leaves(rng, N, depth)):
        leaf = jnp.asarray(leaf_np, jnp.int32)
        if d == 0:
            Hg, carry = make_subtract_level_fn(0, F, B, N)(
                codes, leaf, g, h, w)
        else:
            Hg, carry = make_subtract_level_fn(d, F, B, N)(
                codes, leaf, g, h, w, carry)
        Hf = make_hist_fn(2 ** d, F, B, N, force_impl="einsum")(
            codes, leaf, g, h, w)
        np.testing.assert_allclose(np.asarray(Hg), np.asarray(Hf),
                                   atol=1e-4, rtol=1e-5)
        assert carry.shape == (cl.n_row_shards, 3, 2 ** d, F, B)
        # carries sum to the global histogram (they ARE the pre-psum parts)
        np.testing.assert_allclose(np.asarray(carry).sum(axis=0),
                                   np.asarray(Hf), atol=1e-4, rtol=1e-5)


def test_subtract_level_varbin_parity(cl, rng):
    """The varbin (packed ragged bins, interpret Pallas) inner kernel
    through compaction + subtraction == dense einsum full build."""
    N, F, nbins = 2048, 5, 32
    B = nbins + 1
    bin_counts = (7, 32, 22, 3, 32)
    codes_np = np.stack([
        np.where(rng.random(N) < 0.1, nbins, rng.integers(0, bc, N))
        for bc in bin_counts])
    codes = jnp.asarray(codes_np, jnp.int32)
    gcodes = offset_codes(codes, bin_counts, nbins)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.ones(N, jnp.float32)
    w = jnp.asarray((rng.random(N) > 0.1), jnp.float32)
    carry = None
    for d, leaf_np in enumerate(_chain_leaves(rng, N, 3)):
        leaf = jnp.asarray(leaf_np, jnp.int32)
        fn = make_subtract_level_fn(d, F, B, N, bin_counts=bin_counts,
                                    force_impl="pallas_interpret",
                                    precision="f32")
        if d == 0:
            Hg, carry = fn(gcodes, leaf, g, h, w)
        else:
            Hg, carry = fn(gcodes, leaf, g, h, w, carry)
        Hf = make_hist_fn(2 ** d, F, B, N, force_impl="einsum")(
            codes, leaf, g, h, w)
        np.testing.assert_allclose(np.asarray(Hg), np.asarray(Hf),
                                   atol=1e-4, rtol=1e-5)


def test_compaction_extreme_skew_no_row_loss(cl, rng):
    """Terminal-leaf shape: EVERY row routes to the left child, so the
    smaller sibling is the empty right child and the compacted prefix is
    empty — the left histogram must still be exactly the parent."""
    N, F, nbins = 1024, 3, 8
    B = nbins + 1
    codes = jnp.asarray(rng.integers(0, B, (F, N)), jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.asarray(rng.random(N), jnp.float32)
    w = jnp.ones(N, jnp.float32)
    leaf0 = jnp.zeros(N, jnp.int32)
    H0, carry = make_subtract_level_fn(0, F, B, N)(codes, leaf0, g, h, w)
    H1, _ = make_subtract_level_fn(1, F, B, N)(codes, leaf0, g, h, w, carry)
    H1 = np.asarray(H1)
    np.testing.assert_allclose(H1[:, 0], np.asarray(H0)[:, 0],
                               atol=1e-5, rtol=1e-6)
    np.testing.assert_array_equal(H1[:, 1], 0.0)
    # the flip side: every row right
    leaf_r = jnp.ones(N, jnp.int32)
    H1r, _ = make_subtract_level_fn(1, F, B, N)(codes, leaf_r, g, h, w,
                                                carry)
    H1r = np.asarray(H1r)
    np.testing.assert_allclose(H1r[:, 1], np.asarray(H0)[:, 0],
                               atol=1e-5, rtol=1e-6)
    np.testing.assert_array_equal(H1r[:, 0], 0.0)


def test_build_tree_subtract_equals_full(cl, rng):
    """Whole-tree growth: subtraction path == full oracle (structure,
    routing and leaf values) on planted-signal data with NAs and
    zero-weight rows."""
    from h2o3_tpu.models.tree.shared import build_tree
    N, F, nbins, depth = 4096, 5, 32, 4
    codes_np = rng.integers(0, nbins, (F, N))
    codes_np[2] = np.where(rng.random(N) < 0.08, nbins, codes_np[2])
    codes = jnp.asarray(codes_np, jnp.int32)
    g_np = (np.where(codes_np[1] <= 12, -2.0, 2.0)
            + np.where(codes_np[3] <= 20, -0.7, 0.7)
            + 0.05 * rng.normal(size=N))
    g = jnp.asarray(g_np, jnp.float32)
    h = jnp.ones(N, jnp.float32)
    w = jnp.asarray((rng.random(N) > 0.1), jnp.float32)
    edges = [np.sort(rng.normal(size=nbins - 1)).astype(np.float32)
             for _ in range(F)]
    key = jax.random.PRNGKey(7)
    kw = dict(hist_precision="f32")
    t_f, leaf_f = build_tree(codes, g * w, h * w, w, edges, nbins, depth,
                             1.0, 5.0, 1e-5, 0.1, key, hist_mode="full",
                             **kw)
    t_s, leaf_s = build_tree(codes, g * w, h * w, w, edges, nbins, depth,
                             1.0, 5.0, 1e-5, 0.1, key, hist_mode="subtract",
                             **kw)
    np.testing.assert_array_equal(np.asarray(leaf_f), np.asarray(leaf_s))
    for d in range(depth):
        np.testing.assert_array_equal(np.asarray(t_f.feat[d]),
                                      np.asarray(t_s.feat[d]))
        np.testing.assert_array_equal(np.asarray(t_f.valid[d]),
                                      np.asarray(t_s.valid[d]))
        np.testing.assert_allclose(np.asarray(t_f.thr[d]),
                                   np.asarray(t_s.thr[d]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(t_f.values),
                               np.asarray(t_s.values), atol=1e-5)


def test_run_hist_crosscheck(cl, rng):
    """The hist_mode='check' driver assert passes on real data."""
    from h2o3_tpu.models.tree.shared import run_hist_crosscheck
    from h2o3_tpu.models.tree.binning import edges_matrix
    N, F, nbins = 2048, 4, 16
    codes = jnp.asarray(rng.integers(0, nbins + 1, (F, N)), jnp.int32)
    g = jnp.asarray(rng.normal(size=N), jnp.float32)
    h = jnp.ones(N, jnp.float32)
    w = jnp.ones(N, jnp.float32)
    edges = [np.sort(rng.normal(size=nbins - 1)).astype(np.float32)
             for _ in range(F)]
    em = jnp.asarray(edges_matrix(edges, nbins), jnp.float32)
    run_hist_crosscheck(codes, g, h, w, em, jax.random.PRNGKey(3),
                        max_depth=3, nbins=nbins, F=F, n_padded=N,
                        reg_lambda=1.0, min_rows=5.0)


def _airlines_tiny(rng, n=800, with_na=True):
    """Tiny airlines-shaped frame: numerics + categoricals (+ NAs)."""
    from h2o3_tpu import Frame
    from h2o3_tpu.frame.vec import T_CAT
    dist = np.abs(rng.normal(700, 500, n)).astype(np.float64)
    dep = rng.integers(0, 2400, n).astype(np.float64)
    if with_na:
        dist[rng.random(n) < 0.1] = np.nan
    carrier = rng.integers(0, 7, n)
    dow = rng.integers(0, 5, n)
    logit = (0.002 * (dep / 100 - 12) ** 2 - 0.0005 * dist / 100
             + 0.3 * (carrier == 2) + 0.1 * rng.normal(size=n))
    y = rng.random(n) < 1 / (1 + np.exp(-np.nan_to_num(logit)))
    cols = {"dep": dep, "dist": dist, "carrier": carrier, "dow": dow,
            "delayed": np.where(y, "YES", "NO").astype(object)}
    types = {"carrier": T_CAT, "dow": T_CAT}
    domains = {"carrier": [str(i) for i in range(7)],
               "dow": [str(i) for i in range(5)]}
    return Frame.from_numpy(cols, types=types, domains=domains)


def _assert_same_trees(m_s, m_f):
    """Tree-for-tree structural equality between two trained models."""
    trees_s, trees_f = list(m_s.output["trees"]), list(m_f.output["trees"])
    assert len(trees_s) == len(trees_f)
    for ts, tf in zip(trees_s, trees_f):
        ts_list = ts if isinstance(ts, list) else [ts]
        tf_list = tf if isinstance(tf, list) else [tf]
        for a, b in zip(ts_list, tf_list):
            for d in range(len(a.feat)):
                np.testing.assert_array_equal(np.asarray(a.feat[d]),
                                              np.asarray(b.feat[d]))
                np.testing.assert_array_equal(np.asarray(a.valid[d]),
                                              np.asarray(b.valid[d]))
                np.testing.assert_allclose(np.asarray(a.thr[d]),
                                           np.asarray(b.thr[d]), rtol=1e-6)
            np.testing.assert_allclose(np.asarray(a.values),
                                       np.asarray(b.values), atol=1e-5)


def test_gbm_subtract_parity_airlines(cl, rng):
    """Satellite: subtraction-path GBM == full build on a tiny airlines
    shape — identical split structure and predictions, NA buckets and
    categorical features included (reproducible=True pins f32 kernels)."""
    from h2o3_tpu.models.tree.gbm import GBM
    fr = _airlines_tiny(rng)
    kw = dict(response_column="delayed", ntrees=8, max_depth=4, nbins=16,
              min_rows=5, seed=11, reproducible=True)
    m_s = GBM(hist_mode="subtract", **kw).train(fr)
    m_f = GBM(hist_mode="full", **kw).train(fr)
    _assert_same_trees(m_s, m_f)
    np.testing.assert_allclose(
        m_s.predict(fr).vec("YES").to_numpy(),
        m_f.predict(fr).vec("YES").to_numpy(), atol=1e-6)


def test_gbm_subtract_parity_higgs_numeric(cl, rng):
    """Satellite: parity on a higgs-like all-numeric binary shape, with
    row sampling active (w=0 rows must not corrupt the compaction)."""
    from h2o3_tpu.models.tree.gbm import GBM
    from h2o3_tpu import Frame
    n = 1000
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] * X[:, 1] + X[:, 2] ** 2 - 1
         + 0.3 * rng.normal(size=n)) > 0
    cols = {f"f{j}": X[:, j] for j in range(4)}
    cols["y"] = np.where(y, "s", "b").astype(object)
    fr = Frame.from_numpy(cols)
    kw = dict(response_column="y", ntrees=6, max_depth=4, nbins=32,
              sample_rate=0.7, min_rows=3, seed=5, reproducible=True)
    m_s = GBM(hist_mode="subtract", **kw).train(fr)
    m_f = GBM(hist_mode="full", **kw).train(fr)
    _assert_same_trees(m_s, m_f)


def test_gbm_hist_mode_check_trains(cl, rng):
    """hist_mode='check' runs the driver crosscheck then trains normally."""
    from h2o3_tpu.models.tree.gbm import GBM
    fr = _airlines_tiny(rng, n=400, with_na=False)
    m = GBM(response_column="delayed", ntrees=4, max_depth=3, nbins=16,
            seed=3, reproducible=True, hist_mode="check").train(fr)
    assert m.output["ntrees_trained"] == 4


def test_hist_mode_validation(cl):
    from h2o3_tpu.models.tree.shared import resolve_hist_mode
    from h2o3_tpu.models.tree.xgboost import XGBoost
    with pytest.raises(ValueError, match="hist_mode"):
        resolve_hist_mode(type("P", (), {"hist_mode": "bogus"})())
    with pytest.raises(ValueError, match="hist_mode"):
        XGBoost(response_column="y", hist_mode="bogus")


def test_drf_subtract_equals_full(cl, rng):
    """Satellite: DRF (bootstrap + mtries through the shared scan driver)
    grows identical forests under both histogram modes."""
    from h2o3_tpu.models.tree.drf import DRF
    fr = _airlines_tiny(rng, n=600)
    kw = dict(response_column="delayed", ntrees=6, max_depth=4, nbins=16,
              min_rows=2, seed=7, reproducible=True)
    m_s = DRF(hist_mode="subtract", **kw).train(fr)
    m_f = DRF(hist_mode="full", **kw).train(fr)
    _assert_same_trees(m_s, m_f)
    np.testing.assert_allclose(
        m_s.predict(fr).vec("YES").to_numpy(),
        m_f.predict(fr).vec("YES").to_numpy(), atol=1e-6)


def test_uplift_subtract_equals_full(cl, rng):
    """Satellite: uplift DRF's two-arm histograms through the subtraction
    level driver == the full build, tree for tree."""
    from h2o3_tpu.models.tree.uplift import UpliftDRF
    from h2o3_tpu import Frame
    n = 600
    x0 = rng.normal(size=n)
    x1 = rng.normal(size=n)
    treat = rng.integers(0, 2, n)
    p = 1 / (1 + np.exp(-(0.5 * x0 + 0.8 * treat * (x1 > 0))))
    y = (rng.random(n) < p).astype(int)
    fr = Frame.from_numpy({
        "x0": x0, "x1": x1,
        "treatment": treat.astype(np.float64),
        "y": np.array(["no", "yes"], dtype=object)[y]})
    kw = dict(response_column="y", treatment_column="treatment", ntrees=3,
              max_depth=3, nbins=16, min_rows=5, seed=9, sample_rate=0.8,
              reproducible=True)
    m_s = UpliftDRF(hist_mode="subtract", **kw).train(fr)
    m_f = UpliftDRF(hist_mode="full", **kw).train(fr)
    _assert_same_trees(m_s, m_f)
    m_c = UpliftDRF(hist_mode="check", **kw).train(fr)   # driver assert
    _assert_same_trees(m_c, m_s)


def test_isofor_determinism_regression(cl, rng):
    """Isolation forest shares shared.py's tree plumbing but no histograms;
    pin that the reworked driver leaves it bit-deterministic per seed."""
    from h2o3_tpu.models.tree.isofor import IsolationForest
    from h2o3_tpu import Frame
    n = 500
    X = rng.normal(size=(n, 3))
    X[:10] += 6.0                                    # planted anomalies
    fr = Frame.from_numpy({f"x{j}": X[:, j] for j in range(3)})
    kw = dict(ntrees=10, sample_size=128, max_depth=6, seed=21)
    m1 = IsolationForest(**kw).train(fr)
    m2 = IsolationForest(**kw).train(fr)
    for t1, t2 in zip(m1.output["trees"], m2.output["trees"]):
        for d in range(len(t1.feat)):
            np.testing.assert_array_equal(np.asarray(t1.feat[d]),
                                          np.asarray(t2.feat[d]))
            np.testing.assert_array_equal(np.asarray(t1.thr[d]),
                                          np.asarray(t2.thr[d]))
        np.testing.assert_array_equal(np.asarray(t1.values),
                                      np.asarray(t2.values))
    s1 = m1.predict(fr).vecs[0].to_numpy()
    s2 = m2.predict(fr).vecs[0].to_numpy()
    np.testing.assert_array_equal(s1, s2)
    # anomalies rank above the bulk
    assert s1[:10].mean() > s1[10:].mean()
