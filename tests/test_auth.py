"""REST authn hardening: SPI, hash-file + cmd authenticators, form login
sessions, HTTPS, client propagation.

Reference surface: ``h2o-security/`` + ``h2o-jaas-pam/`` (hash_login /
ldap_login / pam_login / form_auth / HTTPS Jetty flags).
"""

import json
import os
import stat
import subprocess
import urllib.request

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.api.auth import (CommandAuthenticator, HashFileAuthenticator,
                               StaticAuthenticator, hash_password,
                               resolve_authenticator)
from h2o3_tpu.api.server import start_server


@pytest.fixture(scope="module", autouse=True)
def _init():
    h2o3_tpu.init()


# ------------------------------------------------------------ SPI unit tests

def test_static_authenticator():
    a = StaticAuthenticator("bob", "s3cret")
    assert a.check("bob", "s3cret")
    assert not a.check("bob", "wrong")
    assert not a.check("alice", "s3cret")


def test_hash_file_authenticator_and_rotation(tmp_path):
    path = tmp_path / "realm.properties"
    path.write_text(f"# users\nbob:{hash_password('pw1', iters=1000)}\n")
    a = HashFileAuthenticator(str(path))
    assert a.check("bob", "pw1")
    assert not a.check("bob", "pw2")
    assert not a.check("eve", "pw1")
    # rotate on disk -> picked up without restart (mtime reload)
    path.write_text(f"bob:{hash_password('pw2', iters=1000)}\n")
    os.utime(path, (os.stat(path).st_atime, os.stat(path).st_mtime + 5))
    assert a.check("bob", "pw2")
    assert not a.check("bob", "pw1")


def test_cmd_authenticator_pam_style_hook(tmp_path):
    """External verifier: username argv[1], password on stdin, rc 0 = ok —
    the 3-line wrapper contract for PAM/LDAP backends."""
    script = tmp_path / "verify.sh"
    script.write_text("#!/bin/sh\n"
                      'read -r pw\n'
                      '[ "$1" = "carol" ] && [ "$pw" = "letmein" ]\n')
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    a = CommandAuthenticator(str(script))
    assert a.check("carol", "letmein")
    assert not a.check("carol", "nope")
    assert not a.check("mallory", "letmein")
    assert not a.check("x\ny", "letmein")      # newline injection denied


def test_resolve_specs(tmp_path):
    assert resolve_authenticator(None) is None
    a = resolve_authenticator("static:u:p")
    assert a.check("u", "p") and not a.check("u", "q")
    path = tmp_path / "h"
    path.write_text(f"u:{hash_password('p', iters=1000)}\n")
    assert resolve_authenticator(f"hash_file:{path}").check("u", "p")
    with pytest.raises(ValueError):
        resolve_authenticator("kerberos:bogus")


# --------------------------------------------------------- server-level flow

def _get(url, headers=None, ctx=None):
    req = urllib.request.Request(url)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req, context=ctx) as r:
        return r.status, json.loads(r.read().decode()), dict(r.headers)


def test_form_login_session_flow():
    srv = start_server(port=0, auth="static:bob:pw")
    try:
        # anonymous -> 401
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{srv.url}/3/Cloud")
        assert ei.value.code == 401
        # bad form login -> 401
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"{srv.url}/3/Login", data=b"username=bob&password=no",
                method="POST"))
        assert ei.value.code == 401
        # good form login -> session cookie works without credentials
        req = urllib.request.Request(
            f"{srv.url}/3/Login", data=b"username=bob&password=pw",
            method="POST")
        with urllib.request.urlopen(req) as r:
            cookie = r.headers["Set-Cookie"].split(";")[0]
            assert cookie.startswith("h2o3-session=")
        st, payload, _ = _get(f"{srv.url}/3/Cloud", {"Cookie": cookie})
        assert st == 200 and payload["cloud_size"] >= 1
        # logout invalidates the session
        urllib.request.urlopen(urllib.request.Request(
            f"{srv.url}/3/Logout", data=b"", method="POST",
            headers={"Cookie": cookie}))
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{srv.url}/3/Cloud", {"Cookie": cookie})
        assert ei.value.code == 401
    finally:
        srv.stop()


def test_client_session_and_basic_paths():
    from h2o3_tpu import client
    srv = start_server(port=0, auth="static:ann:tok")
    try:
        # Basic header path
        conn = client.connect(srv.url, username="ann", password="tok")
        assert conn.cloud["cloud_size"] >= 1
        # form-login session path: password sent once, cookie thereafter
        conn2 = client.connect(srv.url, username="ann", password="tok",
                               use_session=True)
        assert conn2._auth is None and conn2._cookie
        assert conn2.get("/3/Cloud")["cloud_size"] >= 1
    finally:
        srv.stop()


@pytest.fixture(scope="module")
def tls_pair(tmp_path_factory):
    d = tmp_path_factory.mktemp("tls")
    cert, key = str(d / "cert.pem"), str(d / "key.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-keyout", key,
         "-out", cert, "-days", "2", "-nodes", "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True)
    return cert, key


def test_https_server_and_client(tls_pair):
    cert, key = tls_pair
    srv = start_server(port=0, auth="static:tls:user",
                      https_cert=cert, https_key=key)
    try:
        assert srv.url.startswith("https://")
        from h2o3_tpu import client
        conn = client.connect(srv.url, username="tls", password="user",
                              cafile=cert)
        assert conn.cloud["cloud_size"] >= 1
        # frame import over TLS round-trips
        rng = np.random.default_rng(0)
        import tempfile
        with tempfile.NamedTemporaryFile("w", suffix=".csv",
                                         delete=False) as fh:
            fh.write("x,y\n" + "\n".join(
                f"{v:.3f},{v * 2:.3f}" for v in rng.normal(size=100)))
            tmp = fh.name
        fr = conn.import_file(tmp)
        assert fr.nrows == 100
        os.unlink(tmp)
    finally:
        srv.stop()


def test_https_refuses_without_cert(monkeypatch):
    monkeypatch.delenv("H2O3_TPU_TLS_CERT", raising=False)
    monkeypatch.delenv("H2O3_TPU_TLS_KEY", raising=False)
    from h2o3_tpu.runtime import config as _cfg
    _cfg.reload()
    with pytest.raises(ValueError, match="https"):
        start_server(port=0, https=True)
    _cfg.reload()
