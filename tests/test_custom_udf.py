"""Custom distribution / loss UDFs — water/udf/CDistributionFunc analog."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.models import GBM, DeepLearning


class PoissonUDF:
    """Re-states the built-in Poisson formulas through the UDF protocol."""

    def init_score(self, y, w):
        m = jnp.maximum(jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-12),
                        1e-6)
        return jnp.log(m)

    def grad_hess(self, y, f):
        mu = jnp.exp(jnp.clip(f, -30, 30))
        return mu - y, mu

    def linkinv(self, f):
        return jnp.exp(jnp.clip(f, -30, 30))


def _count_frame(rng, n=1500):
    x1 = rng.normal(size=n).astype(np.float32)
    x2 = rng.normal(size=n).astype(np.float32)
    lam = np.exp(0.6 * x1 - 0.4 * x2)
    y = rng.poisson(lam).astype(np.float32)
    return h2o3_tpu.H2OFrame({"x1": x1, "x2": x2, "y": y})


def test_gbm_custom_distribution_matches_builtin(cl, rng):
    fr = _count_frame(rng)
    kw = dict(response_column="y", ntrees=8, max_depth=3, nbins=32, seed=5)
    m_builtin = GBM(distribution="poisson", **kw).train(fr)
    m_custom = GBM(distribution="custom",
                   custom_distribution_func=PoissonUDF(), **kw).train(fr)
    pb = m_builtin.predict(fr).vec("predict").to_numpy()
    pc = m_custom.predict(fr).vec("predict").to_numpy()
    assert np.allclose(pb, pc, rtol=1e-5), (pb[:4], pc[:4])
    assert m_custom.output["distribution"] == "custom"


def test_gbm_custom_requires_protocol(cl):
    with pytest.raises(ValueError, match="grad_hess"):
        GBM(response_column="y", custom_distribution_func=object(),
            ntrees=1).train(h2o3_tpu.H2OFrame({"x": [1.0, 2.0],
                                               "y": [0.0, 1.0]}))
    with pytest.raises(ValueError, match="custom_distribution_func"):
        GBM(response_column="y", distribution="custom",
            ntrees=1).train(h2o3_tpu.H2OFrame({"x": [1.0, 2.0],
                                               "y": [0.0, 1.0]}))


def test_deeplearning_custom_loss_matches_builtin(cl, rng):
    n = 2000
    x = rng.normal(size=(n, 6)).astype(np.float32)
    y = (x[:, 0] - 0.5 * x[:, 1] + 0.1 * rng.normal(size=n)).astype(
        np.float32)
    cols = {f"c{j}": x[:, j] for j in range(6)}
    cols["y"] = y
    fr = h2o3_tpu.H2OFrame(cols)
    kw = dict(response_column="y", hidden=(32,), mini_batch_size=128,
              epochs=1.0, seed=11, score_interval=1e9, stopping_rounds=0)
    m_builtin = DeepLearning(loss="absolute", **kw).train(fr)
    m_custom = DeepLearning(
        custom_loss_func=lambda pred, yy: jnp.abs(pred - yy),
        **kw).train(fr)
    pb = m_builtin.predict(fr).vec("predict").to_numpy()
    pc = m_custom.predict(fr).vec("predict").to_numpy()
    assert np.allclose(pb, pc, rtol=1e-4, atol=1e-5)
