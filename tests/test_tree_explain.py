"""Tree checkpoint continuation + TreeSHAP contribution tests.

Mirrors pyunit_gbm_checkpoint / pyunit_contributions coverage: checkpoint
10->20 trees equals a straight 20-tree run; SHAP rows sum to the margin
prediction; exact Shapley golden check against brute-force enumeration
with path-dependent expectations.
"""

import itertools
import math

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models import GBM, DRF, XGBoost


def _reg_frame(rng, n=1500):
    X = rng.random((n, 4))
    y = (10 * np.sin(np.pi * X[:, 0]) + 5 * X[:, 1] ** 2
         + 3 * X[:, 2] + 0.1 * rng.normal(size=n))
    cols = {f"x{j}": X[:, j] for j in range(4)}
    cols["y"] = y
    return Frame.from_numpy(cols)


def test_gbm_checkpoint_equals_straight_run(cl, rng):
    fr = _reg_frame(rng)
    kw = dict(response_column="y", max_depth=3, learn_rate=0.2, seed=7,
              score_tree_interval=100)
    m20 = GBM(ntrees=20, **kw).train(fr)
    m10 = GBM(ntrees=10, **kw).train(fr)
    mck = GBM(ntrees=20, checkpoint=m10.key, **kw).train(fr)
    assert mck.output["ntrees_trained"] == 20
    p20 = m20.predict(fr).vec("predict").to_numpy()
    pck = mck.predict(fr).vec("predict").to_numpy()
    np.testing.assert_allclose(pck, p20, rtol=1e-4, atol=1e-4)


def test_checkpoint_validation(cl, rng):
    fr = _reg_frame(rng)
    m = GBM(response_column="y", ntrees=5, max_depth=3, seed=1).train(fr)
    with pytest.raises(ValueError, match="must exceed"):
        GBM(response_column="y", ntrees=5, max_depth=3, seed=1,
            checkpoint=m.key).train(fr)
    with pytest.raises(ValueError, match="non-modifiable"):
        GBM(response_column="y", ntrees=10, max_depth=4, seed=1,
            checkpoint=m.key).train(fr)


def test_drf_checkpoint_continues(cl, rng):
    fr = _reg_frame(rng)
    kw = dict(response_column="y", max_depth=4, seed=3,
              score_tree_interval=100)
    m5 = DRF(ntrees=5, **kw).train(fr)
    mck = DRF(ntrees=12, checkpoint=m5.key, **kw).train(fr)
    assert mck.output["ntrees_trained"] == 12
    r2 = mck.training_metrics.r2
    assert r2 > 0.7, r2


def test_shap_sums_to_margin(cl, rng):
    fr = _reg_frame(rng)
    m = GBM(response_column="y", ntrees=8, max_depth=3, learn_rate=0.3,
            seed=2).train(fr)
    sub = Frame.from_numpy({n: fr.vec(n).to_numpy()[:50]
                            for n in fr.names})
    contrib = m.predict_contributions(sub)
    assert contrib.names[-1] == "BiasTerm"
    total = contrib.to_numpy().sum(axis=1)
    pred = m.predict(sub).vec("predict").to_numpy()
    np.testing.assert_allclose(total, pred, rtol=1e-4, atol=1e-4)


def test_shap_sums_to_margin_binomial_and_drf(cl, rng):
    n = 1200
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0.2)
    fr = Frame.from_numpy({**{f"x{j}": X[:, j] for j in range(3)},
                           "y": np.where(y, "Y", "N").astype(object)})
    sub = Frame.from_numpy({nm: fr.vec(nm).to_numpy()[:40]
                            if fr.vec(nm).type != "cat"
                            else fr.vec(nm).decoded()[:40]
                            for nm in fr.names})
    m = XGBoost(response_column="y", ntrees=6, max_depth=3, seed=4).train(fr)
    total = m.predict_contributions(sub).to_numpy().sum(axis=1)
    p1 = m.predict(sub).vec("Y").to_numpy()
    margin = np.log(np.clip(p1, 1e-9, 1) / np.clip(1 - p1, 1e-9, 1))
    np.testing.assert_allclose(total, margin, rtol=1e-3, atol=1e-3)

    d = DRF(response_column="y", ntrees=7, max_depth=4, seed=4).train(fr)
    total_d = d.predict_contributions(sub).to_numpy().sum(axis=1)
    p1_d = d.predict(sub).vec("Y").to_numpy()
    np.testing.assert_allclose(total_d, p1_d, rtol=1e-3, atol=1e-3)


def _brute_force_shap(tree, x, F):
    """Exact Shapley with path-dependent expectations (the TreeSHAP
    definition): v(S) follows known features, cover-averages unknown."""
    def ev(d, i, S):
        if tree.is_leaf(d, i):
            return tree.value[d][i]
        f = int(tree.feat[d][i])
        if f in S:
            xv = x[f]
            left = (not np.isnan(xv) and xv < tree.thr[d][i]) or \
                (np.isnan(xv) and tree.na_left[d][i])
            return ev(d + 1, 2 * i + (0 if left else 1), S)
        cl = tree.cover[d + 1][2 * i]
        cr = tree.cover[d + 1][2 * i + 1]
        tot = max(cl + cr, 1e-300)
        return (cl * ev(d + 1, 2 * i, S) + cr * ev(d + 1, 2 * i + 1, S)) / tot

    phi = np.zeros(F)
    feats = list(range(F))
    for i in range(F):
        others = [f for f in feats if f != i]
        for r in range(F):
            for S in itertools.combinations(others, r):
                wgt = math.factorial(len(S)) * math.factorial(
                    F - len(S) - 1) / math.factorial(F)
                phi[i] += wgt * (ev(0, 0, set(S) | {i}) - ev(0, 0, set(S)))
    return phi


def test_shap_exact_vs_brute_force(cl, rng):
    from h2o3_tpu.export.treeshap import (shap_trees_from_model,
                                          tree_contributions)
    n = 800
    X = rng.normal(size=(n, 3))
    y = X[:, 0] * 2 + np.where(X[:, 1] > 0, X[:, 2], -X[:, 2])
    fr = Frame.from_numpy({**{f"x{j}": X[:, j] for j in range(3)}, "y": y})
    m = GBM(response_column="y", ntrees=1, max_depth=3, learn_rate=1.0,
            seed=5).train(fr)
    trees = shap_trees_from_model(list(m.output["trees"]))
    Xq = X[:10].astype(np.float64)
    got = tree_contributions(trees[0], Xq)
    for r in range(10):
        want = _brute_force_shap(trees[0], Xq[r], 3)
        np.testing.assert_allclose(got[r, :3], want, rtol=1e-5, atol=1e-7)


def test_mojo_contributions_roundtrip(cl, rng, tmp_path):
    import h2o3_tpu
    fr = _reg_frame(rng)
    m = GBM(response_column="y", ntrees=5, max_depth=3, seed=6).train(fr)
    path = str(tmp_path / "m.mojo")
    m.download_mojo(path)
    sm = h2o3_tpu.import_mojo(path)
    data = {nm: fr.vec(nm).to_numpy()[:20] for nm in fr.names
            if nm != "y"}
    out = sm.predict_contributions(data)
    live = m.predict_contributions(
        Frame.from_numpy({nm: fr.vec(nm).to_numpy()[:20]
                          for nm in fr.names}))
    np.testing.assert_allclose(out["contributions"],
                               live.to_numpy(), rtol=1e-4, atol=1e-5)


def test_partial_dependence_and_ice(cl, rng):
    import h2o3_tpu
    from h2o3_tpu import explain as ex
    from h2o3_tpu.models import GBM
    n = 500
    X = rng.normal(size=(n, 2))
    g = rng.integers(0, 3, n)
    y = X[:, 0] + 0.8 * (g == 1) + 0.1 * rng.normal(size=n) > 0
    fr = h2o3_tpu.Frame.from_numpy({
        "x0": X[:, 0], "x1": X[:, 1],
        "g": np.array(["a", "b", "c"], object)[g],
        "y": np.where(y, "YES", "NO").astype(object)})
    m = GBM(response_column="y", ntrees=5, max_depth=3, seed=1).train(fr)
    pd = ex.partial_dependence(m, fr, "x0", nbins=8)
    assert len(pd["grid"]) == 8
    # response must rise with x0 (the true signal)
    assert pd["mean_response"][-1] > pd["mean_response"][0] + 0.1
    assert (pd["std_error_mean_response"] >= 0).all()
    # categorical grid uses the domain; level b carries the +0.8 signal
    pdg = ex.partial_dependence(m, fr, "g")
    assert list(pdg["grid"]) == ["a", "b", "c"]
    assert pdg["mean_response"][1] == pdg["mean_response"].max()
    # ICE curves average back to the PDP by construction
    ic = ex.ice(m, fr, "x0", nbins=5, sample_rows=20, seed=3)
    assert ic["curves"].shape == (20, 5)
    np.testing.assert_allclose(ic["pdp"], ic["curves"].mean(axis=0))


def test_explain_bundle(cl, rng):
    import h2o3_tpu
    from h2o3_tpu import explain as ex
    from h2o3_tpu.models import GBM, GLM
    n = 400
    X = rng.normal(size=(n, 3))
    yb = X[:, 0] > 0
    fr = h2o3_tpu.Frame.from_numpy({
        "x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2],
        "y": np.where(yb, "YES", "NO").astype(object)})
    m = GBM(response_column="y", ntrees=4, max_depth=3, seed=1).train(fr)
    b = ex.explain(m, fr, top_n=2, nbins=6)
    assert {"varimp", "pdp", "shap_summary"} <= set(b)
    assert list(b["shap_summary"]["feature"])[0] == "x0"
    assert all(len(t["mean_response"]) > 0 for t in b["pdp"].values())
    # regression GLM: varimp falls back to |coef|, residuals included
    yr = 2.0 * X[:, 0] + 0.05 * rng.normal(size=n)
    fr2 = h2o3_tpu.Frame.from_numpy(
        {"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2], "y": yr})
    glm = GLM(response_column="y", family="gaussian").train(fr2)
    b2 = ex.explain(glm, fr2, top_n=2)
    assert list(b2["varimp"])[0] == "x0"
    assert b2["residual_analysis"]["rmse"] < 0.2


def test_explain_extras_and_grid_io(cl, rng, tmp_path, monkeypatch):
    import h2o3_tpu
    from h2o3_tpu import explain as ex
    from h2o3_tpu.models import GBM, GLM
    from h2o3_tpu.models.grid import Grid, GridSearch
    n = 300
    X = rng.normal(size=(n, 2))
    y = np.where(X[:, 0] > 0, "YES", "NO").astype(object)
    fr = h2o3_tpu.Frame.from_numpy({"x0": X[:, 0], "x1": X[:, 1], "y": y})
    m1 = GBM(response_column="y", ntrees=3, max_depth=2, seed=1).train(fr)
    m2 = GLM(response_column="y", family="binomial").train(fr)
    # learning curve from scoring history (may be empty for tiny runs)
    lc = ex.learning_curve(m1)
    assert isinstance(lc, dict)
    # varimp heatmap over mixed model types
    hm = ex.varimp_heatmap([m1, m2])
    assert hm["importance"].shape == (len(hm["feature"]), 2)
    assert hm["feature"][0] == "x0"        # strongest for both
    # model correlation: both models learn the same signal
    mc = ex.model_correlation([m1, m2], fr)
    assert mc["correlation"].shape == (2, 2)
    assert mc["correlation"][0, 1] > 0.7
    # grid save/load round trip through a persist URI
    monkeypatch.setenv("H2O3_TPU_GCS_ROOT", str(tmp_path / "gcs"))
    grid = GridSearch(GBM, {"max_depth": [2, 3]},
                      response_column="y", ntrees=2, seed=1).train(fr)
    grid.save("gcs://grids/g1")
    back = Grid.load("gcs://grids/g1")
    assert len(back.models) == len(grid.models)
    assert back.sort_metric == grid.sort_metric
    p1 = grid.best_model.predict(fr).vec("YES").to_numpy()
    p2 = back.best_model.predict(fr).vec("YES").to_numpy()
    np.testing.assert_allclose(p1, p2, rtol=1e-6)


def test_explain_models_bundle(cl, rng):
    import h2o3_tpu
    from h2o3_tpu import explain as ex
    from h2o3_tpu.models import GBM, GLM
    X = rng.normal(size=(200, 2))
    y = np.where(X[:, 0] > 0, "Y", "N").astype(object)
    fr = h2o3_tpu.Frame.from_numpy({"x0": X[:, 0], "x1": X[:, 1], "y": y})
    ms = [GBM(response_column="y", ntrees=3, max_depth=2, seed=1).train(fr),
          GLM(response_column="y", family="binomial").train(fr)]
    b = ex.explain_models(ms, fr, top_n=2)
    assert {"varimp_heatmap", "model_correlation", "leader"} <= set(b)
    # classifiers: agreement fraction, symmetric with unit diagonal
    C = b["model_correlation"]["correlation"]
    assert C[0, 0] == 1.0 and C[0, 1] == C[1, 0] and 0 <= C[0, 1] <= 1


def test_permutation_importance(cl, rng):
    import h2o3_tpu
    from h2o3_tpu import explain as ex
    from h2o3_tpu.models import GBM, GLM
    n = 400
    X = rng.normal(size=(n, 3))
    g = rng.integers(0, 2, n)
    yb = X[:, 0] + 0.3 * X[:, 1] + 0.8 * g > 0.4
    fr = h2o3_tpu.Frame.from_numpy({
        "x0": X[:, 0], "x1": X[:, 1], "noise": X[:, 2],
        "cat": np.array(["a", "b"], object)[g], "id": np.arange(n) * 1.0,
        "y": np.where(yb, "Y", "N").astype(object)})
    m = GBM(response_column="y", ntrees=10, max_depth=3, seed=1,
            ignored_columns=("id",)).train(fr)
    pi = ex.permutation_importance(m, fr, seed=2)
    assert pi["feature"][0] == "x0"              # dominant signal first
    assert "id" not in pi["feature"]             # ignored cols excluded
    assert pi["relative_importance"][0] == 1.0
    x0_imp = dict(zip(pi["feature"], pi["importance"]))
    assert x0_imp["x0"] > x0_imp["noise"]
    assert x0_imp["cat"] > x0_imp["noise"]       # cat permute is real
    assert x0_imp["x0"] > 0.05                   # real logloss degradation
    import pytest
    with pytest.raises(ValueError, match="metric"):
        ex.permutation_importance(m, fr, metric="rsme")
    # regression path with rmse
    yr = 2.0 * X[:, 0] + 0.05 * rng.normal(size=n)
    fr2 = h2o3_tpu.Frame.from_numpy(
        {"x0": X[:, 0], "x1": X[:, 1], "y": yr})
    g = GLM(response_column="y", family="gaussian").train(fr2)
    pr = ex.permutation_importance(g, fr2, metric="rmse", n_repeats=3)
    assert pr["feature"][0] == "x0" and pr["baseline_score"] < 0.1


def test_tree_api(cl, rng):
    import h2o3_tpu
    from h2o3_tpu.export.tree_api import tree_from_model
    from h2o3_tpu.models import GBM
    n = 400
    X = rng.normal(size=(n, 2))
    y = np.where(X[:, 0] > 0, "Y", "N").astype(object)
    fr = h2o3_tpu.Frame.from_numpy({"x0": X[:, 0], "x1": X[:, 1], "y": y})
    m = GBM(response_column="y", ntrees=3, max_depth=3, seed=1).train(fr)
    t = tree_from_model(m, 0)
    assert t.features[t.root_node_id] == "x0"        # dominant split
    assert abs(t.thresholds[0]) < 0.6                 # near the boundary
    # structural invariants: leaves have predictions, decisions children
    for n_ in range(len(t)):
        if t.features[n_] is None:
            assert t.predictions[n_] is not None
            assert t.left_children[n_] == -1
        else:
            assert t.left_children[n_] > n_ and t.right_children[n_] > n_
            assert t.na_directions[n_] in ("LEFT", "RIGHT")
    # hand-traverse rows through the H2OTree and match the engine's
    # per-tree contribution (model F starts at the prior; tree 0's delta
    # equals the traversed leaf value)
    def route(row):
        n_ = 0
        while t.features[n_] is not None:
            x = row[t.features[n_]]
            go_left = (x < t.thresholds[n_]) if np.isfinite(x) else \
                (t.na_directions[n_] == "LEFT")
            n_ = t.left_children[n_] if go_left else t.right_children[n_]
        return t.predictions[n_]
    from h2o3_tpu.models.tree.shared import stack_trees
    lv, vals = stack_trees([m.output["trees"][0]])
    from h2o3_tpu.models.tree.shared import traverse_jit
    eng = np.asarray(traverse_jit(lv, vals, fr.matrix(["x0", "x1"])))
    for r in (0, 7, 123):
        row = {"x0": X[r, 0], "x1": X[r, 1]}
        np.testing.assert_allclose(route(row), eng[r], rtol=1e-6)
    dot = t.to_dot()
    assert dot.startswith("digraph") and "x0 <" in dot
    # multinomial: per-class trees addressable by label
    y3 = np.array(["a", "b", "c"], object)[
        rng.integers(0, 3, n)]
    fr3 = h2o3_tpu.Frame.from_numpy({"x0": X[:, 0], "y": y3})
    m3 = GBM(response_column="y", ntrees=2, max_depth=2, seed=1).train(fr3)
    tb = tree_from_model(m3, 0, tree_class="b")
    assert tb.tree_class == "b" and len(tb) >= 1


def test_pdp_2d_and_multi(cl, rng):
    import h2o3_tpu
    from h2o3_tpu import explain as ex
    from h2o3_tpu.models import GBM, GLM
    n = 400
    X = rng.normal(size=(n, 2))
    y = X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.normal(size=n)
    fr = h2o3_tpu.Frame.from_numpy({"x0": X[:, 0], "x1": X[:, 1], "y": y})
    m = GBM(response_column="y", ntrees=10, max_depth=3, seed=1).train(fr)
    p2 = ex.partial_dependence_2d(m, fr, "x0", "x1", nbins=5)
    assert p2["mean_response"].shape == (5, 5)
    # response rises along both grid axes (additive increasing truth)
    M = p2["mean_response"]
    assert M[-1, -1] > M[0, 0]
    assert (np.diff(M.mean(axis=1)) >= -0.05).all()   # along x0
    assert (np.diff(M.mean(axis=0)) >= -0.05).all()   # along x1
    glm = GLM(response_column="y", family="gaussian").train(fr)
    pm = ex.partial_dependence_multi([m, glm], fr, "x0", nbins=6)
    assert list(pm["model"]) == [m.key, glm.key]
    assert pm["curves"].shape == (2, 6)
    for c in pm["curves"]:
        assert c[-1] > c[0]
    # duplicate models keep one curve each (positional, not dict-keyed)
    dup = ex.partial_dependence_multi([m, m], fr, "x0", nbins=4)
    assert dup["curves"].shape == (2, 4)
    import pytest
    with pytest.raises(ValueError, match="distinct"):
        ex.partial_dependence_2d(m, fr, "x0", "x0")


def test_feature_interactions(cl, rng):
    import h2o3_tpu
    from h2o3_tpu.export import feature_interactions
    from h2o3_tpu.models import GBM
    n = 600
    X = rng.normal(size=(n, 3))
    # XOR-ish: y needs x0 AND x1 together; x2 is noise
    y = np.where((X[:, 0] > 0) ^ (X[:, 1] > 0), "Y", "N").astype(object)
    fr = h2o3_tpu.Frame.from_numpy({
        "x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2], "y": y})
    m = GBM(response_column="y", ntrees=10, max_depth=3, seed=1).train(fr)
    fi = feature_interactions(m)
    singles = dict(zip(fi["singles"]["feature"], fi["singles"]["count"]))
    assert singles["x0"] > singles.get("x2", 0)
    assert fi["pairs"]["feature_pair"][0] == "x0|x1"     # the interaction
    assert (fi["singles"]["cover"] > 0).all()
    # counts are sorted descending
    assert (np.diff(fi["singles"]["count"]) <= 0).all()
    # max_trees truncation reduces counts
    fi1 = feature_interactions(m, max_trees=1)
    assert fi1["singles"]["count"].sum() < fi["singles"]["count"].sum()


def test_ice_centered(cl, rng):
    import h2o3_tpu
    from h2o3_tpu import explain as ex
    from h2o3_tpu.models import GLM
    X = rng.normal(size=(200, 1))
    y = 2.0 * X[:, 0] + 0.05 * rng.normal(size=200)
    fr = h2o3_tpu.Frame.from_numpy({"x0": X[:, 0], "y": y})
    m = GLM(response_column="y", family="gaussian").train(fr)
    ic = ex.ice(m, fr, "x0", nbins=5, sample_rows=10, centered=True)
    np.testing.assert_allclose(ic["curves"][:, 0], 0.0, atol=1e-9)
    assert (ic["curves"][:, -1] > 0).all()   # increasing truth
