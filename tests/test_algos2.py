"""Wave-2 algorithm tests: AdaBoost, TargetEncoder, GLRM, CoxPH, Word2Vec,
RuleFit, Aggregator, GAM — golden checks against closed forms / known
structure (testdir_algos pyunit strategy)."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models import (AdaBoost, TargetEncoder, GLRM, CoxPH, Word2Vec,
                             RuleFit, Aggregator, GAM)


def test_adaboost_binary(cl, rng):
    n = 2000
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 - 0.5 > 0)
    cols = {f"x{j}": X[:, j] for j in range(4)}
    cols["y"] = np.where(y, "yes", "no").astype(object)
    fr = Frame.from_numpy(cols)
    m = AdaBoost(response_column="y", nlearners=30, seed=1).train(fr)
    assert m.training_metrics.auc > 0.9
    pred = m.predict(fr)
    assert set(np.unique(pred.vecs[0].decoded())) <= {"yes", "no"}


def test_target_encoder(cl, rng):
    n = 3000
    g = rng.integers(0, 8, n)
    noise = 0.1 * rng.normal(size=n)
    y = g * 0.5 + noise
    fr = Frame.from_numpy({
        "c": np.array([f"lv{i}" for i in range(8)], dtype=object)[g],
        "y": y})
    te = TargetEncoder(response_column="y", blending=False).train(fr)
    out = te.transform(fr)
    assert "c_te" in out.names
    enc = out.vec("c_te").to_numpy()
    for lvl in range(8):
        seg = enc[g == lvl]
        assert np.allclose(seg, y[g == lvl].mean(), atol=1e-5)
    # blending pulls rare levels toward the prior
    te_b = TargetEncoder(response_column="y", blending=True,
                         inflection_point=10000).train(fr)
    enc_b = te_b.transform(fr).vec("c_te").to_numpy()
    prior = y.mean()
    assert np.all(np.abs(enc_b - prior) < np.abs(enc - prior) + 1e-9)


def test_target_encoder_holdout_modes(cl, rng):
    n = 1200
    g = rng.integers(0, 4, n)
    y = g * 1.0 + 0.1 * rng.normal(size=n)
    folds = rng.integers(0, 3, n).astype(np.float64)
    fr = Frame.from_numpy({
        "c": np.array([f"l{i}" for i in range(4)], dtype=object)[g],
        "fold": folds, "y": y})
    # leave-one-out: row's own y must not contribute
    te = TargetEncoder(response_column="y", blending=False,
                       data_leakage_handling="leave_one_out",
                       ignored_columns=["fold"]).train(fr)
    enc = te.transform(fr, as_training=True).vec("c_te").to_numpy()
    for i in range(30):
        seg = y[(g == g[i])]
        loo = (seg.sum() - y[i]) / (len(seg) - 1)
        assert enc[i] == pytest.approx(loo, rel=1e-6)
    # k_fold: encoding excludes the row's own fold entirely
    te2 = TargetEncoder(response_column="y", blending=False,
                        data_leakage_handling="k_fold", fold_column="fold",
                        ignored_columns=["fold"]).train(fr)
    enc2 = te2.transform(fr, as_training=True).vec("c_te").to_numpy()
    for i in range(30):
        mask = (g == g[i]) & (folds != folds[i])
        expect = y[mask].mean()
        assert enc2[i] == pytest.approx(expect, rel=1e-6)


def test_glrm_low_rank_recovery(cl, rng):
    n, p, k = 800, 8, 3
    A = rng.normal(size=(n, k)) @ rng.normal(size=(k, p))
    fr = Frame.from_numpy({f"c{i}": A[:, i] for i in range(p)})
    m = GLRM(k=k, max_iterations=50, seed=1).train(fr)
    assert m.output["objective"] < 1e-4 * (A ** 2).sum()
    rec = m.reconstruct(fr)
    R = np.stack([v.to_numpy() for v in rec.vecs], axis=1)
    assert np.abs(R - A).max() < 0.05 * np.abs(A).max() + 1e-3


def test_coxph_recovers_hazard_ratio(cl, rng):
    n = 3000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    beta_true = np.array([0.8, -0.5])
    lam = np.exp(x1 * beta_true[0] + x2 * beta_true[1])
    t = rng.exponential(1.0 / lam)
    cens = rng.exponential(2.0, n)
    time = np.minimum(t, cens)
    event = (t <= cens).astype(np.float64)
    fr = Frame.from_numpy({"x1": x1, "x2": x2, "time": time,
                           "event": event})
    m = CoxPH(stop_column="time", event_column="event",
              standardize=False).train(fr)
    coef = m.output["coef"]
    assert abs(coef["x1"] - 0.8) < 0.1
    assert abs(coef["x2"] + 0.5) < 0.1
    assert m.training_metrics["concordance"] > 0.6


def test_word2vec_synonyms(cl, rng):
    # two topic clusters of co-occurring words
    topics = [["cat", "dog", "pet", "animal"],
              ["car", "road", "drive", "wheel"]]
    words = []
    for _ in range(400):
        topic = topics[rng.integers(0, 2)]
        sent = [topic[i] for i in rng.integers(0, 4, 6)]
        words.extend(sent)
        words.append(None)
    fr = Frame.from_numpy({"words": np.array(words, dtype=object)},
                          types={"words": "str"})
    m = Word2Vec(vec_size=16, epochs=15, min_word_freq=2, seed=3,
                 window_size=3, sent_sample_rate=1.0).train(fr)
    assert m.output["vocab_size"] == 8
    syn = m.find_synonyms("cat", 3)
    assert set(syn) <= {"dog", "pet", "animal"}, syn
    emb = m.transform(fr, aggregate_method="none")
    assert emb.ncols == 16


def test_rulefit(cl, rng):
    n = 2000
    X = rng.normal(size=(n, 3))
    y = np.where((X[:, 0] > 0) & (X[:, 1] > 0), 2.0, 0.0) \
        + 0.05 * rng.normal(size=n)
    cols = {f"x{j}": X[:, j] for j in range(3)}
    cols["y"] = y
    fr = Frame.from_numpy(cols)
    m = RuleFit(response_column="y", rule_generation_ntrees=10,
                max_rule_length=2, seed=1).train(fr)
    assert m.training_metrics.rmse < 0.5
    imp = m.rule_importance()
    assert len(imp) > 0
    assert "rule" in imp[0] or imp[0]["variable"].startswith("linear")
    pred = m.predict(fr).vecs[0].to_numpy()
    assert np.corrcoef(pred, y)[0, 1] > 0.9


def test_aggregator(cl, rng):
    n = 5000
    fr = Frame.from_numpy({"x": rng.normal(size=n),
                           "y": rng.normal(size=n)})
    m = Aggregator(target_num_exemplars=50, seed=1).train(fr)
    agg = m.aggregated_frame
    assert 1 < agg.nrows <= 50
    counts = agg.vec("counts").to_numpy()
    assert counts.sum() == pytest.approx(n)


def test_gam_fits_nonlinear(cl, rng):
    n = 3000
    x = rng.uniform(-3, 3, n)
    z = rng.normal(size=n)
    y = np.sin(x) * 2 + 0.5 * z + 0.1 * rng.normal(size=n)
    fr = Frame.from_numpy({"x": x, "z": z, "y": y})
    glm_rmse = None
    from h2o3_tpu.models import GLM
    glm = GLM(response_column="y", lambda_=1e-6).train(fr)
    glm_rmse = glm.training_metrics.rmse
    m = GAM(response_column="y", gam_columns=["x"], num_knots=8,
            seed=1).train(fr)
    assert m.training_metrics.rmse < 0.5 * glm_rmse
    pred = m.predict(fr).vecs[0].to_numpy()
    assert np.corrcoef(pred, y)[0, 1] > 0.95
