"""Cost-model autotuner: decision lifecycle, cache persistence, and the
correctness net underneath it.

Five behaviours pin the design (see docs/operations.md "Autotuning"):
  1. the warm-start cache round-trips ACROSS processes — a fresh process
     serves source="cache" and never explores (zero re-measures);
  2. a corrupt or version-stale cache file silently degrades to
     model-seeded decisions — the tuner can never error a training path;
  3. a mesh rebuild (cluster_reinit epoch bump) drops every decision;
  4. a forced-wrong cost model self-corrects from measured device
     samples — the epsilon-greedy re-measure flips the choice;
  5. the ``*="check"`` oracles still run (and still bit-match) with the
     tuner on: checks bypass tuning entirely.

The suite-wide conftest pins H2O3_TPU_AUTOTUNE=off; these tests opt back
in per-test through the ``tuner_on`` fixture (explicit env save/restore,
because config() caches the environment).
"""

import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest

import h2o3_tpu


def _params(**kw):
    """Attribute bag standing in for SharedTreeParameters at resolve."""
    d = dict(hist_mode="auto", split_mode="auto", hist_layout="auto",
             sparse_depth_threshold=8, max_depth=10, nbins=64)
    d.update(kw)
    return types.SimpleNamespace(**d)


@pytest.fixture()
def tuner_on(tmp_path):
    """Autotuner on with an isolated cache dir; restores the suite's
    pinned-off environment (and the cached Config) afterwards."""
    from h2o3_tpu.runtime import autotune, config
    keys = ("H2O3_TPU_AUTOTUNE", "H2O3_TPU_AUTOTUNE_CACHE_DIR",
            "H2O3_TPU_AUTOTUNE_EXPLORE")
    saved = {k: os.environ.get(k) for k in keys}
    os.environ["H2O3_TPU_AUTOTUNE"] = "on"
    os.environ["H2O3_TPU_AUTOTUNE_CACHE_DIR"] = str(tmp_path / "atcache")
    config.reload()
    autotune.reset()
    yield autotune
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    config.reload()
    autotune.reset()


# --------------------------------------------------------- off == before

def test_off_resolves_auto_to_historical_defaults():
    """With the tuner off (the suite default), every "auto" knob lands on
    the pre-tuner fixed default — bit-identical kernels to the seed."""
    from h2o3_tpu.runtime import autotune
    assert autotune.autotune_mode() == "off"
    k = autotune.resolve_tree_knobs(_params(), kind="gbm", F=8, N=4096)
    assert (k.hist_mode, k.split_mode) == ("subtract", "fused")
    assert k.hist_layout == "sparse"      # builder value: below-threshold
    assert k.sparse_depth_threshold == 8
    assert k.sig is None                  # tuner never engaged
    assert set(k.sources.values()) == {"default"}


def test_unknown_mode_reads_as_off(tuner_on):
    from h2o3_tpu.runtime import config
    os.environ["H2O3_TPU_AUTOTUNE"] = "bogus"
    config.reload()
    assert tuner_on.autotune_mode() == "off"


def test_user_pinned_knobs_pass_through(tuner_on):
    """Explicit values are never overridden — only "auto" knobs tune."""
    k = tuner_on.resolve_tree_knobs(
        _params(hist_mode="full", split_mode="separate"),
        kind="gbm", F=8, N=4096)
    assert (k.hist_mode, k.split_mode) == ("full", "separate")
    assert k.sources["hist_mode"] == "user"
    assert k.sources["split_mode"] == "user"


# ------------------------------------------------------- model decisions

def test_model_seeded_decision_and_table(tuner_on):
    k = tuner_on.resolve_tree_knobs(_params(), kind="gbm", F=8, N=65536)
    assert k.sig is not None
    assert k.sources["hist_mode"] in ("model", "explore")
    t = tuner_on.decision_table()
    assert t["mode"] == "on" and t["entries"] == 1
    row = t["decisions"][0]
    assert row["signature"] == k.sig
    assert row["source"] == "model"
    assert row["predicted_s"], "model must record per-candidate costs"


def test_checkpoint_pins_sparse_threshold(tuner_on):
    """Checkpoint continuations keep the params threshold: the resumed
    tree was depth-validated against it."""
    k = tuner_on.resolve_tree_knobs(_params(), kind="gbm", F=8, N=65536,
                                    checkpoint=True)
    assert k.sparse_depth_threshold == 8
    assert k.sources["sparse_depth_threshold"] == "default"


def test_check_mode_bypasses_tuner(tuner_on):
    k = tuner_on.resolve_tree_knobs(_params(hist_mode="check"),
                                    kind="gbm", F=8, N=4096)
    assert k.hist_mode == "check" and k.sig is None
    assert tuner_on.decision_table()["entries"] == 0


# ------------------------------------------------- cache: cross-process

_CHILD = r"""
import json, sys
from h2o3_tpu.runtime import autotune
import types
p = types.SimpleNamespace(hist_mode="auto", split_mode="auto",
                          hist_layout="auto", sparse_depth_threshold=8,
                          max_depth=10, nbins=64)
sources = []
for _ in range(8):                       # well past explore_every=2
    k = autotune.resolve_tree_knobs(p, kind="gbm", F=8, N=65536)
    sources.append(k.sources["hist_mode"])
t = autotune.decision_table()
print(json.dumps({"sources": sources, "table": t}))
"""


def _run_child(cache_dir):
    env = os.environ.copy()
    env.update(JAX_PLATFORMS="cpu", H2O3_TPU_AUTOTUNE="on",
               H2O3_TPU_AUTOTUNE_CACHE_DIR=str(cache_dir),
               H2O3_TPU_AUTOTUNE_EXPLORE="2")
    r = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    return json.loads(r.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_cache_round_trip_across_processes(tmp_path):
    """Process 1 decides from the model and persists; process 2 warm-
    starts with source="cache" and NEVER dispatches a re-measure — the
    acceptance bar for warm restarts."""
    cache = tmp_path / "atcache"
    first = _run_child(cache)
    assert first["table"]["decisions"][0]["source"] == "model"
    assert (cache / "autotune_cache.json").exists()

    second = _run_child(cache)
    row = second["table"]["decisions"][0]
    assert row["source"] == "cache"
    assert set(second["sources"]) == {"cache"}, \
        "warm-start resolves must all come from the cache"
    assert row["exploring"] is None, \
        "cache-sourced decisions never explore (zero re-measures)"
    assert row["choice"] == first["table"]["decisions"][0]["choice"]


def test_corrupt_cache_degrades_to_model(tuner_on, tmp_path):
    """Garbage in the cache file must never error — decisions fall back
    to the cost model."""
    cache_dir = tmp_path / "atcache"
    cache_dir.mkdir(parents=True, exist_ok=True)
    (cache_dir / "autotune_cache.json").write_text("{not json !!!")
    tuner_on.reset()
    k = tuner_on.resolve_tree_knobs(_params(), kind="gbm", F=8, N=65536)
    assert k.sig is not None
    assert tuner_on.decision_table()["decisions"][0]["source"] == "model"


def test_stale_cache_header_is_ignored(tuner_on, tmp_path):
    """A cache written by a different backend/jax version is dead weight,
    not an error and not a decision source."""
    cache_dir = tmp_path / "atcache"
    cache_dir.mkdir(parents=True, exist_ok=True)
    sig = tuner_on._signature("gbm", 8, 65536, 1, 10, 64)
    payload = {"header": {"version": 1, "backend": "tpu", "jax": "9.9.9"},
               "entries": {sig: {"choice": "full|separate|dense|t10",
                                 "predicted": {}, "measured": {}}}}
    (cache_dir / "autotune_cache.json").write_text(json.dumps(payload))
    tuner_on.reset()
    k = tuner_on.resolve_tree_knobs(_params(), kind="gbm", F=8, N=65536)
    row = tuner_on.decision_table()["decisions"][0]
    assert row["source"] == "model"
    assert k.hist_mode != "full" or row["choice"] != "full|separate|dense|t10"


# --------------------------------------------------------- invalidation

def test_cluster_reinit_invalidates_decisions(tuner_on):
    """invalidate("cluster_reinit") drops the table AND marks the loaded
    cache file dead for this process — a geometry change can never serve
    a stale choice (the file stays for FRESH processes, whose signature
    includes the new mesh)."""
    tuner_on.resolve_tree_knobs(_params(), kind="gbm", F=8, N=65536)
    assert tuner_on.decision_table()["entries"] == 1
    epoch = tuner_on.decision_table()["epoch"]
    tuner_on.invalidate("cluster_reinit")
    t = tuner_on.decision_table()
    assert t["entries"] == 0 and t["epoch"] == epoch + 1
    # post-invalidate resolves re-decide from the model, not the file
    tuner_on.resolve_tree_knobs(_params(), kind="gbm", F=8, N=65536)
    assert tuner_on.decision_table()["decisions"][0]["source"] == "model"


# ------------------------------------------------- measured refinement

def test_forced_wrong_model_self_corrects(tuner_on, monkeypatch):
    """Invert the cost model so it seeds the WORST candidate, then feed
    real-shaped device samples: once two candidates carry measurements
    the faster one wins permanently (source="measured")."""
    real = tuner_on._predict_costs

    def inverted(F, N, K, max_depth, nbins, candidates):
        costs = real(F, N, K, max_depth, nbins, candidates)
        finite = [v for v in costs.values() if v != float("inf")]
        top = max(finite) if finite else 1.0
        return {k: (v if v == float("inf") else top - v + 1e-9)
                for k, v in costs.items()}

    monkeypatch.setattr(tuner_on, "_predict_costs", inverted)
    os.environ["H2O3_TPU_AUTOTUNE_EXPLORE"] = "2"
    from h2o3_tpu.runtime import config
    config.reload()

    k = tuner_on.resolve_tree_knobs(_params(), kind="gbm", F=8, N=65536)
    wrong = tuner_on.decision_table()["decisions"][0]["choice"]
    # the true argmin under the real model — what measurement should find
    ent = tuner_on._DECISIONS[k.sig]
    truth = real(8, 65536, 1, 10, 64, list(ent["candidates"].values()))
    right = min((c for c in truth if truth[c] != float("inf")),
                key=truth.get)
    assert wrong != right, "inversion failed to mis-seed the model"

    # sampled device timings: the mis-seeded choice is slow, the true
    # best is fast (fed through the public measurement sink, as
    # xprof.maybe_device_sync would)
    tuner_on.activate(tuner_on.TreeKnobs(
        "subtract", "fused", "dense", 8, "level", {}, sig=k.sig, run_key=wrong))
    tuner_on.on_device_sample("tree_scan", 2.0)
    tuner_on.activate(tuner_on.TreeKnobs(
        "subtract", "fused", "dense", 8, "level", {}, sig=k.sig, run_key=right))
    tuner_on.on_device_sample("tree_scan", 0.1)

    row = tuner_on.decision_table()["decisions"][0]
    assert row["choice"] == right, "measured evidence must overturn model"
    assert row["source"] == "measured"
    # subsequent resolves serve the corrected choice (unless that very
    # resolve is itself an epsilon exploration of another candidate)
    k2 = tuner_on.resolve_tree_knobs(_params(), kind="gbm", F=8, N=65536)
    if "explore" not in k2.sources.values():
        assert k2.run_key == right
    assert tuner_on.decision_table()["decisions"][0]["choice"] == right
    tuner_on.deactivate()


def test_non_tree_phases_do_not_pollute(tuner_on):
    """map_reduce / serve phase samples on the driver thread must not be
    attributed to the active tree decision."""
    k = tuner_on.resolve_tree_knobs(_params(), kind="gbm", F=8, N=65536)
    tuner_on.activate(k)
    tuner_on.on_device_sample("map_reduce", 5.0)
    row = tuner_on.decision_table()["decisions"][0]
    assert not row["measured_s"]
    tuner_on.deactivate()


# ------------------------------------------------ whole-model correctness

def _tiny_frame(rng, n=600):
    from h2o3_tpu import Frame
    X = rng.normal(size=(n, 4))
    y = X[:, 0] * 0.6 - 0.3 * X[:, 1] + 0.1 * rng.normal(size=n)
    return Frame.from_numpy(
        {**{f"x{i}": X[:, i] for i in range(4)}, "y": y})


def test_check_oracle_runs_clean_under_tuner(cl, rng, tuner_on):
    """The correctness net survives the tuner: a hist_mode="check" build
    (which crosschecks subtract against the full-build oracle on the
    real data and raises on any bit mismatch) passes with autotune on."""
    from h2o3_tpu.models.tree.gbm import GBM
    fr = _tiny_frame(rng)
    m = GBM(response_column="y", ntrees=3, max_depth=3, nbins=16,
            seed=7, hist_mode="check", split_mode="check").train(fr)
    assert m.output["trees"]


def test_tuned_auto_matches_pinned_choice_bitwise(cl, rng, tuner_on):
    """Whatever the tuner picks, training under it equals training with
    the same knobs pinned by hand — the tuner changes strategy, never
    results."""
    from h2o3_tpu.models.tree.gbm import GBM
    fr = _tiny_frame(rng)
    kw = dict(response_column="y", ntrees=4, max_depth=3, nbins=16,
              seed=11, reproducible=True)
    m_auto = GBM(**kw).train(fr)
    t = tuner_on.decision_table()
    rows = [d for d in t["decisions"]
            if d["signature"].startswith("gbm:")]
    assert rows, "training under the tuner must record a decision"
    hm, sm, layout, thr, prog = rows[0]["choice"].split("|")
    tuner_on.reset()
    m_pin = GBM(**kw, hist_mode=hm, split_mode=sm, hist_layout=layout,
                sparse_depth_threshold=int(thr[1:]),
                tree_program=prog[1:]).train(fr)
    a = np.asarray(m_auto.predict(fr).vec("predict").to_numpy())
    b = np.asarray(m_pin.predict(fr).vec("predict").to_numpy())
    np.testing.assert_array_equal(a, b)


# ------------------------------------------------------ reduce / serving

def test_reduce_mode_auto(tuner_on, cl):
    from h2o3_tpu.runtime.mapreduce import resolve_reduce_mode
    want = "hier" if cl.n_hosts > 1 else "flat"
    assert resolve_reduce_mode("auto") == want
    sigs = [d["signature"] for d in
            tuner_on.decision_table()["decisions"]]
    assert any(s.startswith("reduce:") for s in sigs)


def test_reduce_mode_auto_off_is_hier():
    """Suite default (tuner off): "auto" keeps the historical hier."""
    from h2o3_tpu.runtime.mapreduce import resolve_reduce_mode
    assert resolve_reduce_mode("auto") == "hier"


def test_serve_impl_auto(tuner_on):
    impl = tuner_on.resolve_serve_impl(depth=10, R=300, F=32, B=256)
    assert impl == "xla"                 # cpu backend under the suite
    sigs = [d["signature"] for d in
            tuner_on.decision_table()["decisions"]]
    assert any(s.startswith("serve:") for s in sigs)
