"""Round-2 algorithm additions: UpliftDRF, DecisionTree, SegmentModels,
ModelSelection — golden/semantic tests per reference behavior."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models import (UpliftDRF, DecisionTree, ModelSelection,
                             train_segments, GLM)


def _uplift_frame(rng, n=4000):
    X = rng.normal(size=(n, 4))
    treat = rng.integers(0, 2, n)
    base = 1 / (1 + np.exp(-X[:, 1]))
    effect = np.where(X[:, 0] > 0, 0.3, -0.05)
    p1 = np.clip(base + treat * effect, 0.01, 0.99)
    y = (rng.random(n) < p1).astype(int)
    return Frame.from_numpy({
        **{f"x{j}": X[:, j] for j in range(4)},
        "treatment": np.array(["control", "treatment"],
                              dtype=object)[treat],
        "y": np.array(["no", "yes"], dtype=object)[y]}), X, treat, y


def test_upliftdrf_recovers_heterogeneous_effect(cl, rng):
    fr, X, treat, y = _uplift_frame(rng)
    m = UpliftDRF(response_column="y", treatment_column="treatment",
                  ntrees=10, max_depth=4, seed=1).train(fr)
    pred = m.predict(fr)
    assert pred.names == ["uplift_predict", "p_y1_ct1", "p_y1_ct0"]
    u = pred.vec("uplift_predict").to_numpy()
    # planted uplift: +0.3 for x0>0, -0.05 otherwise
    assert u[X[:, 0] > 0].mean() > u[X[:, 0] < 0].mean() + 0.1
    d = m.training_metrics.describe()
    assert d["qini"] > 0.3            # much better than random ranking
    assert d["ate"] == pytest.approx(
        y[treat == 1].mean() - y[treat == 0].mean(), abs=1e-6)
    # uplift = p_t - p_c consistency
    pt = pred.vec("p_y1_ct1").to_numpy()
    pc = pred.vec("p_y1_ct0").to_numpy()
    np.testing.assert_allclose(u, pt - pc, atol=1e-5)


def test_decision_tree_single_tree(cl, rng):
    n = 2000
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] > 0.3)
    fr = Frame.from_numpy({**{f"x{j}": X[:, j] for j in range(3)},
                           "y": np.where(y, "A", "B").astype(object)})
    m = DecisionTree(response_column="y", max_depth=4, seed=2).train(fr)
    assert m.output["ntrees_trained"] == 1
    assert m.training_metrics.auc > 0.95


def test_segment_models(cl, rng):
    n = 3000
    seg = np.array(["s1", "s2", "s3"], dtype=object)[rng.integers(0, 3, n)]
    x = rng.normal(size=n)
    # per-segment slope differs: the per-segment GLM must recover each
    slope = np.where(seg == "s1", 1.0, np.where(seg == "s2", -2.0, 0.5))
    y = slope * x + 0.01 * rng.normal(size=n)
    fr = Frame.from_numpy({"seg": seg, "x": x, "y": y})
    sm = train_segments(
        lambda: GLM(response_column="y", family="gaussian"),
        fr, "seg")
    tbl = sm.as_frame()
    assert tbl.nrows == 3
    assert all(s == "SUCCEEDED" for s in tbl.vec("status").decoded())
    for name, want in (("s1", 1.0), ("s2", -2.0), ("s3", 0.5)):
        m = sm.model(seg=name)
        assert m.coef["x"] == pytest.approx(want, abs=0.05)


def test_tree_calibration(cl, rng):
    """Platt/isotonic calibration — hex/tree CalibrationHelper analog."""
    n = 3000
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] + X[:, 1] > 0.3)
    fr = Frame.from_numpy({**{f"x{j}": X[:, j] for j in range(3)},
                           "y": np.where(y, "Y", "N").astype(object)})
    from h2o3_tpu.models import GBM
    tr, cal = fr.split_frame([0.7], seed=2)
    yv = (cal.vec("y").decoded() == "Y").astype(float)
    for method in ("platt", "isotonic"):
        m = GBM(response_column="y", ntrees=15, max_depth=4, seed=1,
                calibrate_model=True, calibration_frame=cal,
                calibration_method=method).train(tr)
        p1 = m.calibrated_probabilities(cal)
        assert abs(p1.mean() - yv.mean()) < 0.03
        pred = m.predict(cal)
        assert "cal_p1" in pred.names and "cal_p0" in pred.names


def test_interaction_columns(cl, rng):
    from h2o3_tpu.rapids import interaction
    n = 2000
    g1 = np.array(["a", "b"], dtype=object)[rng.integers(0, 2, n)]
    g2 = np.array(["x", "y", "z"], dtype=object)[rng.integers(0, 3, n)]
    fr = Frame.from_numpy({"g1": g1, "g2": g2})
    out = interaction(fr, ["g1", "g2"])
    assert "g1_g2" in out.names
    assert out.vec("g1_g2").cardinality == 6
    dec = out.vec("g1_g2").decoded()
    assert all(d == f"{a}_{b}" for d, a, b in zip(dec, g1, g2))
    capped = interaction(fr, ["g1", "g2"], max_factors=3)
    assert capped.vec("g1_g2").cardinality <= 4   # 3 + "other"


def test_psvm_nonlinear_boundary(cl, rng):
    """RBF-kernel SVM separates the circle a linear model cannot."""
    from h2o3_tpu.models import PSVM
    n = 3000
    X = rng.normal(size=(n, 2))
    y = ((X ** 2).sum(axis=1) < 1.2)
    fr = Frame.from_numpy({"x0": X[:, 0], "x1": X[:, 1],
                           "y": np.where(y, "in", "out").astype(object)})
    m = PSVM(response_column="y", hyper_param=1.0, seed=1).train(fr)
    lin = GLM(response_column="y", family="binomial").train(fr)
    assert m.training_metrics.auc > 0.97
    assert lin.training_metrics.auc < 0.6
    pred = m.predict(fr)
    acc = (pred.vec("predict").decoded()
           == np.where(y, "in", "out")).mean()
    assert acc > 0.9
    assert 0 < m.output["svs_count"] < n


def test_scope_sweeps_temporaries(cl, rng):
    import h2o3_tpu
    from h2o3_tpu import Scope
    before = set(h2o3_tpu.ls())
    with Scope() as s:
        fr = Frame.from_numpy({"x": rng.normal(size=200),
                               "y": rng.normal(size=200)}, key="scope_tmp")
        m = GLM(response_column="y", family="gaussian").train(fr)
        s.protect(m)
    after = set(h2o3_tpu.ls())
    assert "scope_tmp" not in after
    assert m.key in after
    h2o3_tpu.remove(m.key)
    assert before <= set(h2o3_tpu.ls()) | {m.key}


def test_gam_crs_splines(cl, rng):
    """CRS basis fits a sine; huge smoothing collapses EXACTLY to the
    unpenalized null space (the linear fit) — the penalty is the true
    curvature quadratic form."""
    from h2o3_tpu.models import GAM
    n = 3000
    x = rng.uniform(-3, 3, n)
    z = rng.normal(size=n)
    y = np.sin(2 * x) + 0.5 * z + 0.1 * rng.normal(size=n)
    fr = Frame.from_numpy({"x": x, "z": z, "y": y})
    m = GAM(response_column="y", gam_columns=["x"], num_knots=10,
            scale=0.001, family="gaussian").train(fr)
    lin = GLM(response_column="y", family="gaussian").train(fr)
    assert m.training_metrics.r2 > 0.9 > lin.training_metrics.r2
    ms = GAM(response_column="y", gam_columns=["x"], num_knots=10,
             scale=1e9, family="gaussian").train(fr)
    assert abs(ms.training_metrics.r2 - lin.training_metrics.r2) < 0.05
    # prediction path round-trips the basis expansion
    pred = m.predict(fr).vec("predict").to_numpy()
    assert np.corrcoef(pred, y)[0, 1] ** 2 > 0.9


def test_glrm_loss_zoo(cl, rng):
    from h2o3_tpu.models import GLRM
    n, F, k = 400, 6, 2
    A = rng.normal(size=(n, k)) @ rng.normal(size=(k, F)) \
        + 0.05 * rng.normal(size=(n, F))
    fr = Frame.from_numpy({f"c{j}": A[:, j] for j in range(F)})
    m = GLRM(k=2, loss="absolute", regularization_x="non_negative",
             gamma_x=0.1, max_iterations=150, init="random",
             seed=1).train(fr)
    assert (m.output["x_factor"] >= -1e-6).all()
    assert np.isfinite(m.output["objective"])
    m2 = GLRM(k=2, loss="huber", regularization_y="l1", gamma_y=0.05,
              max_iterations=100, init="random", seed=1).train(fr)
    assert np.isfinite(m2.output["objective"])


def test_coxph_efron_strata(cl, rng):
    from h2o3_tpu.models import CoxPH
    n = 3000
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    strat = rng.integers(0, 2, n)
    lam0 = np.where(strat == 0, 0.5, 2.0)
    T = rng.exponential(1.0 / (lam0 * np.exp(0.8 * x1 - 0.5 * x2)))
    C = rng.exponential(2.0, n)
    t = np.round(np.minimum(T, C), 1) + 0.01     # induce ties
    e = (T <= C).astype(float)
    fr = Frame.from_numpy({"x1": x1, "x2": x2, "stop": t, "event": e,
                           "s": np.array(["a", "b"], dtype=object)[strat]})
    m = CoxPH(stop_column="stop", event_column="event", ties="efron",
              stratify_by="s").train(fr)
    c = m.output["coef"]
    assert c["x1"] == pytest.approx(0.8, abs=0.12)
    assert c["x2"] == pytest.approx(-0.5, abs=0.12)
    assert m.training_metrics["concordance"] > 0.65
    mb = CoxPH(stop_column="stop", event_column="event",
               ties="breslow", stratify_by="s").train(fr)
    # with heavy ties, Efron's estimates dominate Breslow's toward truth
    assert abs(c["x1"] - 0.8) <= abs(mb.output["coef"]["x1"] - 0.8) + 0.02


def test_modelselection_maxr_and_backward(cl, rng):
    n = 1500
    X = rng.normal(size=(n, 5))
    # only x0, x2 matter
    y = 3 * X[:, 0] - 2 * X[:, 2] + 0.05 * rng.normal(size=n)
    fr = Frame.from_numpy({**{f"x{j}": X[:, j] for j in range(5)}, "y": y})
    m = ModelSelection(response_column="y", mode="maxr",
                       max_predictor_number=3, family="gaussian").train(fr)
    res = m.result()
    assert res.nrows == 3
    names = res.vec("predictor_names").decoded()
    assert set(names[1].split(", ")) == {"x0", "x2"}, names
    r2 = res.vec("best_r2_value").to_numpy()
    assert r2[1] > 0.99
    assert np.all(np.diff(r2) >= -1e-9)     # monotone in subset size
    best2 = m.best_model(2)
    assert best2.coef["x0"] == pytest.approx(3.0, abs=0.05)

    mb = ModelSelection(response_column="y", mode="backward",
                        min_predictor_number=2,
                        family="gaussian").train(fr)
    resb = mb.result()
    sizes = resb.vec("model_size").to_numpy()
    assert sizes.min() == 2 and sizes.max() == 5
    two = next(i for i in range(resb.nrows) if sizes[i] == 2)
    assert set(resb.vec("predictor_names").decoded()[two]
               .split(", ")) == {"x0", "x2"}


def test_modelselection_maxrsweep(cl, rng):
    """maxrsweep finds the same subsets as maxr via sweep operators, with
    matching R^2 and coefficients — and no GLM builds in the search."""
    n = 1500
    X = rng.normal(size=(n, 5))
    y = 3 * X[:, 0] - 2 * X[:, 2] + 0.05 * rng.normal(size=n)
    cols = {**{f"x{j}": X[:, j] for j in range(5)}, "y": y}
    # a categorical predictor exercises grouped (multi-column) sweeps
    cols["g"] = np.array([("a", "b", "c")[i % 3] for i in range(n)],
                         dtype=object)
    fr = Frame.from_numpy(cols)
    m = ModelSelection(response_column="y", mode="maxrsweep",
                       max_predictor_number=3,
                       family="gaussian").train(fr)
    res = m.result()
    assert res.nrows == 3
    names = res.vec("predictor_names").decoded()
    assert set(names[1].split(", ")) == {"x0", "x2"}, names
    r2 = res.vec("best_r2_value").to_numpy()
    assert r2[1] > 0.99
    assert np.all(np.diff(r2) >= -1e-9)
    # coefficients from the swept CPM match the data-generating betas
    coefs = m.output["subsets"][1]["coefficients"]
    assert coefs["x0"] == pytest.approx(3.0, abs=0.05)
    assert coefs["x2"] == pytest.approx(-2.0, abs=0.05)
    # no GLM models were built in the search
    assert all(r["model_key"] is None for r in m.output["subsets"])
    with pytest.raises(ValueError, match="build_glm_model"):
        m.best_model(2)
    # build_glm_model=True attaches real GLMs whose R^2 agrees
    mg = ModelSelection(response_column="y", mode="maxrsweep",
                        max_predictor_number=2, build_glm_model=True,
                        family="gaussian").train(fr)
    best2 = mg.best_model(2)
    assert best2.coef["x0"] == pytest.approx(3.0, abs=0.05)
    sweep_r2 = mg.output["subsets"][1]["metric"]
    glm_r2 = best2.training_metrics.r2
    assert sweep_r2 == pytest.approx(glm_r2, abs=1e-4)


def test_gam_thinplate_splines(cl, rng):
    """bs='tp': thin-plate smooths, incl. a MULTI-column smooth
    (ThinPlateRegressionUtils analog)."""
    from h2o3_tpu.models.gam import GAM
    n = 1200
    x = rng.uniform(-2, 2, n)
    y1 = np.sin(1.7 * x) + 0.05 * rng.normal(size=n)
    fr1 = Frame.from_numpy({"x": x.astype(np.float32),
                            "y": y1.astype(np.float32)})
    m1 = GAM(response_column="y", gam_columns=["x"], bs="tp",
             num_knots=12, family="gaussian", seed=1).train(fr1)
    assert m1.training_metrics.r2 > 0.95
    # 2-D smooth: a radial bump no additive/linear model can capture
    u, v = rng.uniform(-2, 2, n), rng.uniform(-2, 2, n)
    y2 = np.exp(-(u ** 2 + v ** 2)) + 0.03 * rng.normal(size=n)
    fr2 = Frame.from_numpy({"u": u.astype(np.float32),
                            "v": v.astype(np.float32),
                            "y": y2.astype(np.float32)})
    m2 = GAM(response_column="y", gam_columns=[["u", "v"]], bs="tp",
             num_knots=30, family="gaussian", seed=1).train(fr2)
    assert m2.training_metrics.r2 > 0.9
    from h2o3_tpu.models import GLM
    lin = GLM(response_column="y", family="gaussian",
              lambda_=0.0).train(fr2)
    assert m2.training_metrics.r2 > lin.training_metrics.r2 + 0.5
    # scoring on fresh data works through the same basis
    preds = m2.predict(fr2)
    assert preds.nrows == n


def test_gam_monotone_isplines(cl, rng):
    """bs='is': I-spline smooths with non-negative coefficients are
    monotone non-decreasing everywhere (GamSplines/ISplines +
    splines_non_negative analog)."""
    from h2o3_tpu.models.gam import GAM
    n = 1200
    x = rng.uniform(0, 4, n)
    # monotone signal with a flat stretch + noise that tempts wiggles
    f = np.where(x < 1.5, 0.0, np.where(x < 2.5, 2 * (x - 1.5), 2.0))
    y = f + 0.15 * rng.normal(size=n)
    fr = Frame.from_numpy({"x": x.astype(np.float32),
                           "y": y.astype(np.float32)})
    m = GAM(response_column="y", gam_columns=["x"], bs="is",
            num_knots=8, scale=1e-3, family="gaussian", seed=1).train(fr)
    assert m.training_metrics.r2 > 0.85
    grid = Frame.from_numpy({
        "x": np.linspace(0, 4, 200).astype(np.float32),
        "y": np.zeros(200, np.float32)})
    g = m.predict(grid).vec("predict").to_numpy()
    assert np.all(np.diff(g) >= -1e-5), "monotonicity violated"
    # an unconstrained CRS fit on the same data DOES wiggle downward
    mc = GAM(response_column="y", gam_columns=["x"], bs="cr",
             num_knots=8, scale=1e-3, family="gaussian", seed=1).train(fr)
    gc = mc.predict(grid).vec("predict").to_numpy()
    assert np.any(np.diff(gc) < -1e-5)


def test_glm_non_negative(cl, rng):
    """GLM non_negative: all-coefficient and per-column constraint."""
    from h2o3_tpu.models import GLM
    n = 1500
    X = rng.normal(size=(n, 3))
    y = 2 * X[:, 0] - 1.5 * X[:, 1] + 0.05 * rng.normal(size=n)
    fr = Frame.from_numpy({"a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
                           "y": y})
    m = GLM(response_column="y", family="gaussian", lambda_=0.0,
            non_negative=True).train(fr)
    assert m.coef["a"] == pytest.approx(2.0, abs=0.1)
    assert m.coef["b"] >= -1e-8          # clamped at the boundary
    m2 = GLM(response_column="y", family="gaussian", lambda_=0.0,
             non_negative=["b"]).train(fr)
    assert m2.coef["a"] == pytest.approx(2.0, abs=0.1)
    assert m2.coef["b"] >= -1e-8
    with pytest.raises(ValueError, match="non_negative"):
        GLM(response_column="y", family="gaussian", solver="lbfgs",
            non_negative=True).train(fr)


def test_coxph_time_varying_coefficients(cl, rng):
    """Counting-process episodes + a period x covariate interaction
    recover a coefficient that CHANGES over time — the reference's
    _interaction_pairs mechanism (CoxPHModel.java:52) composed with
    start/stop rows."""
    from h2o3_tpu.models import CoxPH
    n = 3000
    x = rng.normal(size=n)
    tau, b_early, b_late = 1.5, 1.2, -0.8
    lam0 = 0.2
    # inverse-CDF sampling of a piecewise-constant-coefficient hazard
    E = -np.log(rng.random(n))
    h_early = lam0 * np.exp(b_early * x)
    h_late = lam0 * np.exp(b_late * x)
    T = np.where(E < h_early * tau, E / h_early,
                 tau + (E - h_early * tau) / h_late)
    cens = 6.0
    event = T <= cens
    T = np.minimum(T, cens)
    # episode rows: [0, min(T, tau)) as 'early'; (tau, T] as 'late'
    rows = {"start": [], "stop": [], "event": [], "period": [], "x": []}
    for i in range(n):
        rows["start"].append(0.0)
        rows["stop"].append(min(T[i], tau))
        rows["event"].append(1.0 if (event[i] and T[i] <= tau) else 0.0)
        rows["period"].append("early")
        rows["x"].append(x[i])
        if T[i] > tau:
            rows["start"].append(tau)
            rows["stop"].append(T[i])
            rows["event"].append(1.0 if event[i] else 0.0)
            rows["period"].append("late")
            rows["x"].append(x[i])
    fr = Frame.from_numpy({
        "start": np.asarray(rows["start"]),
        "stop": np.asarray(rows["stop"]),
        "event": np.asarray(rows["event"]),
        "period": np.asarray(rows["period"], dtype=object),
        "x": np.asarray(rows["x"])})
    m = CoxPH(start_column="start", stop_column="stop",
              event_column="event",
              interaction_pairs=[("period", "x")],
              ignored_columns=["x", "period"]).train(fr)
    coef = m.output["coef"]
    assert coef["period.early:x"] == pytest.approx(b_early, abs=0.12)
    assert coef["period.late:x"] == pytest.approx(b_late, abs=0.15)
    # scoring a raw (unexpanded) frame re-derives the interaction cols
    lp = m.predict(fr).vecs[0].to_numpy()
    assert np.all(np.isfinite(lp))
