"""Round-trip: models trained HERE -> reference-format MOJO zip -> scored by
the repo's own reference-format reader (`export/h2o_mojo.py`, itself
validated against the reference's golden fixtures) -> identical predictions.

This closes the bidirectional portability contract (VERDICT r03 missing #2,
`hex/ModelMojoWriter.java:1`): a model trained on this framework can be
handed to any consumer of the reference MOJO format.

Data is generated float32-representable so host float64 re-parsing cannot
flip a float32 threshold comparison.
"""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.export import load_h2o_mojo, write_h2o_mojo
from h2o3_tpu.frame.vec import T_CAT


@pytest.fixture(scope="module", autouse=True)
def _init():
    h2o3_tpu.init()


def _prostate_like(n=400, seed=0):
    """Prostate-shaped mixed frame: numerics + categoricals, binary target."""
    rng = np.random.default_rng(seed)
    cols = {
        "AGE": rng.integers(45, 80, n).astype(np.float32),
        "PSA": np.round(rng.gamma(2.0, 8.0, n), 1).astype(np.float32),
        "VOL": np.round(rng.random(n) * 50, 1).astype(np.float32),
        "GLEASON": rng.integers(0, 10, n).astype(np.float32),
        "RACE": rng.choice(["black", "white", "other"], n).astype(object),
        "DPROS": rng.choice(["a", "b", "c", "d"], n).astype(object),
    }
    logit = (0.05 * (cols["GLEASON"] - 5) + 0.02 * (cols["PSA"] - 16)
             - 0.01 * cols["VOL"] + 0.3 * (cols["RACE"] == "black"))
    y = rng.random(n) < 1 / (1 + np.exp(-logit))
    cols["CAPSULE"] = np.where(y, "yes", "no").astype(object)
    fr = Frame.from_numpy(cols, types={"RACE": T_CAT, "DPROS": T_CAT,
                                       "CAPSULE": T_CAT})
    data = {k: list(v) for k, v in cols.items()}   # readers select features
    return fr, data


def _native_probs(model, fr, col=2):
    return model.predict(fr).to_numpy()[:, col].astype(np.float64)


def test_gbm_binomial_roundtrip(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import GBM
    m = GBM(response_column="CAPSULE", ntrees=12, max_depth=4, seed=7).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "gbm.zip"))
    mojo = load_h2o_mojo(path)
    assert mojo.algo == "gbm" and mojo.nclasses == 2
    out = mojo.predict(data)
    np.testing.assert_allclose(out["probabilities"][:, 1],
                               _native_probs(m, fr), rtol=0, atol=1e-6)
    # label decisions use the exported default_threshold
    assert set(out["predict"]) <= {"yes", "no"}


def test_gbm_regression_roundtrip(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import GBM
    m = GBM(response_column="PSA", ntrees=10, max_depth=5, seed=3).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "gbm_reg.zip"))
    out = load_h2o_mojo(path).predict(data)
    native = m.predict(fr).to_numpy()[:, 0].astype(np.float64)
    np.testing.assert_allclose(out["predict"], native, rtol=0, atol=1e-5)


def test_gbm_multinomial_roundtrip(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import GBM
    m = GBM(response_column="DPROS", ntrees=6, max_depth=3, seed=5).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "gbm_multi.zip"))
    mojo = load_h2o_mojo(path)
    assert mojo.nclasses == 4
    out = mojo.predict(data)
    native = m.predict(fr).to_numpy()[:, 1:5].astype(np.float64)
    np.testing.assert_allclose(out["probabilities"], native,
                               rtol=0, atol=1e-5)


def test_drf_binomial_and_regression_roundtrip(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import DRF
    mb = DRF(response_column="CAPSULE", ntrees=10, max_depth=4,
             seed=11).train(fr)
    out = load_h2o_mojo(write_h2o_mojo(
        mb, str(tmp_path / "drf.zip"))).predict(data)
    np.testing.assert_allclose(out["probabilities"][:, 1],
                               _native_probs(mb, fr), rtol=0, atol=1e-6)
    mr = DRF(response_column="VOL", ntrees=8, max_depth=4, seed=11).train(fr)
    out = load_h2o_mojo(write_h2o_mojo(
        mr, str(tmp_path / "drf_reg.zip"))).predict(data)
    native = mr.predict(fr).to_numpy()[:, 0].astype(np.float64)
    np.testing.assert_allclose(out["predict"], native, rtol=0, atol=1e-5)


def test_xgboost_exports_as_gbm_format(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import XGBoost
    m = XGBoost(response_column="CAPSULE", ntrees=8, max_depth=4,
                seed=2).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "xgb.zip"))
    mojo = load_h2o_mojo(path)
    assert mojo.algo == "gbm"           # additive-margin family contract
    out = mojo.predict(data)
    np.testing.assert_allclose(out["probabilities"][:, 1],
                               _native_probs(m, fr), rtol=0, atol=1e-6)


def test_glm_binomial_roundtrip(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import GLM
    m = GLM(response_column="CAPSULE", family="binomial",
            lambda_=0.0).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "glm.zip"))
    mojo = load_h2o_mojo(path)
    assert mojo.algo == "glm"
    out = mojo.predict(data)
    np.testing.assert_allclose(out["probabilities"][:, 1],
                               _native_probs(m, fr), rtol=0, atol=1e-5)


def test_glm_gaussian_roundtrip(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import GLM
    m = GLM(response_column="PSA", family="gaussian", lambda_=0.0).train(fr)
    out = load_h2o_mojo(write_h2o_mojo(
        m, str(tmp_path / "glm_g.zip"))).predict(data)
    native = m.predict(fr).to_numpy()[:, 0].astype(np.float64)
    np.testing.assert_allclose(out["predict"], native, rtol=1e-5, atol=1e-4)


def test_format_is_reference_shaped(tmp_path):
    """The archive carries the reference ini surface + tree blob names."""
    import zipfile
    fr, _ = _prostate_like(n=200)
    from h2o3_tpu.models import GBM
    m = GBM(response_column="CAPSULE", ntrees=3, max_depth=3, seed=1).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "fmt.zip"))
    with zipfile.ZipFile(path) as z:
        names = set(z.namelist())
        ini = z.read("model.ini").decode()
    assert "trees/t00_000.bin" in names and "trees/t00_002.bin" in names
    for key in ("mojo_version = 1.30", "algo = gbm", "n_classes = 2",
                "distribution = bernoulli", "link_function = logit",
                "[columns]", "[domains]"):
        assert key in ini, key
    # domains files referenced by the ini exist
    assert any(n.startswith("domains/") for n in names)
    # the declared cardinality must equal the real level count — the
    # reference's ModelMojoReader sizes domain arrays from it (our own
    # reader ignores it, so the round-trip tests can't catch a drift)
    import re
    dom_lines = ini.split("[domains]")[1].strip().splitlines()
    with zipfile.ZipFile(path) as z:
        for line in dom_lines:
            mres = re.match(r"(\d+): (\d+) (d\d+\.txt)", line.strip())
            assert mres, line
            levels = z.read(f"domains/{mres.group(3)}").decode().splitlines()
            assert int(mres.group(2)) == len(levels), line
    # RACE has 3 levels, DPROS 4 — at least one non-binary domain present
    cards = [int(re.match(r"\d+: (\d+)", ln.strip()).group(1))
             for ln in dom_lines]
    assert any(c > 2 for c in cards)


def test_mojo_version_pinned(tmp_path):
    from h2o3_tpu.export.h2o_mojo_writer import (_MOJO_TREE_VERSION,
                                                 _MOJO_GLM_VERSION)
    assert _MOJO_TREE_VERSION == "1.30"
    assert _MOJO_GLM_VERSION == "1.00"


# ---------------------------------------------------------- round-5 algos

def _numeric_frame(n=300, d=5, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32) * 3 + 1
    cols = {f"x{j}": X[:, j] for j in range(d)}
    fr = Frame.from_numpy(cols)
    return fr, {k: list(v) for k, v in cols.items()}


def test_kmeans_roundtrip(tmp_path):
    fr, data = _numeric_frame()
    from h2o3_tpu.models import KMeans
    m = KMeans(k=3, seed=5).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "km.zip"))
    mojo = load_h2o_mojo(path)
    ours = m.predict(fr).vecs[0].to_numpy()[: fr.nrows].astype(int)
    theirs = np.asarray(mojo.predict(data)["predict"], int)
    assert np.array_equal(ours, theirs)


def test_isofor_roundtrip(tmp_path):
    fr, data = _numeric_frame()
    from h2o3_tpu.models import IsolationForest
    m = IsolationForest(ntrees=10, max_depth=5, seed=2).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "if.zip"))
    mojo = load_h2o_mojo(path)
    out = mojo.predict(data)
    # exported trees carry exact per-row path lengths (the normalization
    # constant differs by design — structural vs training min/max)
    ours = m.predict(fr)
    ours_mean = np.asarray(ours.vec("mean_length").to_numpy(),
                           np.float64)[: fr.nrows]
    np.testing.assert_allclose(out["mean_length"], ours_mean,
                               rtol=0, atol=1e-4)
    # ranking must agree: higher anomaly score == shorter path
    rho = np.corrcoef(np.argsort(np.argsort(-out["predict"])),
                      np.argsort(np.argsort(ours_mean)))[0, 1]
    assert rho > 0.999


def test_word2vec_roundtrip(tmp_path):
    from h2o3_tpu.frame.vec import Vec, T_STR
    from h2o3_tpu.models import Word2Vec
    rng = np.random.default_rng(0)
    words = ["alpha", "beta", "gamma", "delta", "eps"]
    doc = list(rng.choice(words, 600)) + [None]
    fr = Frame(["txt"], [Vec.from_numpy(np.asarray(doc, object), T_STR)])
    m = Word2Vec(vec_size=8, epochs=2, seed=1).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "w2v.zip"))
    mojo = load_h2o_mojo(path)
    emb = mojo.transform(words)
    wfr = Frame(["w"], [Vec.from_numpy(np.asarray(words, object), T_STR)])
    ours = np.column_stack([v.to_numpy()[: len(words)]
                            for v in m.transform(wfr).vecs])
    np.testing.assert_allclose(emb, ours, rtol=0, atol=1e-5)


def test_deeplearning_roundtrip(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import DeepLearning
    m = DeepLearning(response_column="CAPSULE", hidden=(8,), epochs=2,
                     mini_batch_size=64, stopping_rounds=0,
                     seed=4).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "dl.zip"))
    mojo = load_h2o_mojo(path)
    out = mojo.predict(data)
    np.testing.assert_allclose(out["probabilities"][:, 1],
                               _native_probs(m, fr), rtol=0, atol=2e-5)


def test_deeplearning_regression_roundtrip(tmp_path):
    fr, data = _numeric_frame()
    rng = np.random.default_rng(1)
    y = (2.0 * np.asarray(data["x0"]) - np.asarray(data["x1"])
         + rng.normal(0, 0.1, fr.nrows)).astype(np.float32)
    cols = {k: np.asarray(v, np.float32) for k, v in data.items()}
    cols["y"] = y
    fr2 = Frame.from_numpy(cols)
    data2 = {k: list(v) for k, v in cols.items()}
    from h2o3_tpu.models import DeepLearning
    m = DeepLearning(response_column="y", hidden=(8,), epochs=3,
                     mini_batch_size=64, stopping_rounds=0,
                     seed=4).train(fr2)
    path = write_h2o_mojo(m, str(tmp_path / "dlr.zip"))
    mojo = load_h2o_mojo(path)
    ours = m.predict(fr2).vecs[0].to_numpy()[: fr2.nrows]
    np.testing.assert_allclose(mojo.predict(data2)["predict"],
                               np.asarray(ours, np.float64),
                               rtol=0, atol=2e-4)


def test_pca_roundtrip(tmp_path):
    fr, data = _numeric_frame()
    from h2o3_tpu.models import PCA
    m = PCA(k=3, transform="standardize", seed=6).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "pca.zip"))
    mojo = load_h2o_mojo(path)
    ours = m.predict(fr)
    ours_M = np.column_stack([v.to_numpy()[: fr.nrows]
                              for v in ours.vecs])
    theirs = mojo.predict(data)["projection"]
    np.testing.assert_allclose(theirs, ours_M, rtol=0, atol=1e-4)


def test_coxph_roundtrip(tmp_path):
    rng = np.random.default_rng(9)
    n = 400
    age = rng.normal(60, 8, n).astype(np.float32)
    bp = rng.normal(120, 15, n).astype(np.float32)
    hazard = np.exp(0.04 * (age - 60) - 0.01 * (bp - 120))
    t = rng.exponential(1.0 / hazard).astype(np.float32)
    event = (rng.random(n) < 0.8).astype(np.float32)
    cols = {"age": age, "bp": bp, "time": t, "event": event}
    fr = Frame.from_numpy(cols)
    from h2o3_tpu.models import CoxPH
    m = CoxPH(stop_column="time", event_column="event").train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "cox.zip"))
    mojo = load_h2o_mojo(path)
    ours = m.predict(fr).vecs[0].to_numpy()[: fr.nrows]
    data = {k: list(v) for k, v in cols.items()}
    theirs = mojo.predict(data)["lp"]
    np.testing.assert_allclose(theirs, np.asarray(ours, np.float64),
                               rtol=0, atol=1e-4)


def test_stackedensemble_roundtrip(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import GBM, GLM, StackedEnsemble
    b1 = GBM(response_column="CAPSULE", ntrees=8, max_depth=3,
             nfolds=3, keep_cross_validation_predictions=True,
             seed=3).train(fr)
    b2 = GLM(response_column="CAPSULE", family="binomial", nfolds=3,
             keep_cross_validation_predictions=True, seed=3).train(fr)
    se = StackedEnsemble(response_column="CAPSULE",
                         base_models=[b1.key, b2.key], seed=3).train(fr)
    path = write_h2o_mojo(se, str(tmp_path / "se.zip"))
    mojo = load_h2o_mojo(path)
    out = mojo.predict(data)
    np.testing.assert_allclose(out["probabilities"][:, 1],
                               _native_probs(se, fr), rtol=0, atol=1e-5)


def test_stackedensemble_widened_bases_roundtrip(tmp_path):
    """VERDICT r5 weak #7: KMeans/PCA/CoxPH base models (all with
    reference-format writers) export inside a StackedEnsemble MOJO and
    score identically through the reader."""
    rng = np.random.default_rng(11)
    n = 400
    age = rng.normal(60, 8, n).astype(np.float32)
    bp = rng.normal(120, 15, n).astype(np.float32)
    hazard = np.exp(0.04 * (age - 60) - 0.01 * (bp - 120))
    t = rng.exponential(1.0 / hazard).astype(np.float32)
    event = (rng.random(n) < 0.8).astype(np.float32)
    yy = rng.random(n) < 1 / (1 + np.exp(-(0.05 * (age - 60))))
    cols = {"age": age, "bp": bp, "time": t, "event": event,
            "y": np.where(yy, "yes", "no").astype(object)}
    fr = Frame.from_numpy(cols, types={"y": T_CAT})
    data = {k: list(v) for k, v in cols.items()}
    from h2o3_tpu.models import (CoxPH, GLM, KMeans, PCA, StackedEnsemble)
    # reference KMeans/PCA MOJO formats are numeric-only: keep the cat
    # response out of the unsupervised bases' feature sets
    b1 = KMeans(k=3, seed=5,
                ignored_columns=["time", "event", "y"]).train(fr)
    b2 = PCA(k=1, transform="standardize", seed=6,
             ignored_columns=["time", "event", "y"]).train(fr)
    b3 = CoxPH(stop_column="time", event_column="event",
               ignored_columns=["y"]).train(fr)
    b4 = GLM(response_column="y", family="binomial",
             ignored_columns=["time", "event"]).train(fr)
    se = StackedEnsemble(response_column="y",
                         base_models=[b1.key, b2.key, b3.key, b4.key],
                         blending_frame=fr, seed=3).train(fr)
    path = write_h2o_mojo(se, str(tmp_path / "se_wide.zip"))
    mojo = load_h2o_mojo(path)
    out = mojo.predict(data)
    np.testing.assert_allclose(out["probabilities"][:, 1],
                               _native_probs(se, fr), rtol=0, atol=1e-4)


def test_writer_dispatch_breadth():
    """VERDICT r4 #6 gate: >= 10 algos with reference-format writers."""
    from h2o3_tpu.export.h2o_mojo_writer import _ENTRY_BUILDERS
    algos = set(_ENTRY_BUILDERS) | {"stackedensemble"}
    assert len(algos - {"isofor"}) >= 10, sorted(algos)
