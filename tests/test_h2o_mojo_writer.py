"""Round-trip: models trained HERE -> reference-format MOJO zip -> scored by
the repo's own reference-format reader (`export/h2o_mojo.py`, itself
validated against the reference's golden fixtures) -> identical predictions.

This closes the bidirectional portability contract (VERDICT r03 missing #2,
`hex/ModelMojoWriter.java:1`): a model trained on this framework can be
handed to any consumer of the reference MOJO format.

Data is generated float32-representable so host float64 re-parsing cannot
flip a float32 threshold comparison.
"""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.export import load_h2o_mojo, write_h2o_mojo
from h2o3_tpu.frame.vec import T_CAT


@pytest.fixture(scope="module", autouse=True)
def _init():
    h2o3_tpu.init()


def _prostate_like(n=400, seed=0):
    """Prostate-shaped mixed frame: numerics + categoricals, binary target."""
    rng = np.random.default_rng(seed)
    cols = {
        "AGE": rng.integers(45, 80, n).astype(np.float32),
        "PSA": np.round(rng.gamma(2.0, 8.0, n), 1).astype(np.float32),
        "VOL": np.round(rng.random(n) * 50, 1).astype(np.float32),
        "GLEASON": rng.integers(0, 10, n).astype(np.float32),
        "RACE": rng.choice(["black", "white", "other"], n).astype(object),
        "DPROS": rng.choice(["a", "b", "c", "d"], n).astype(object),
    }
    logit = (0.05 * (cols["GLEASON"] - 5) + 0.02 * (cols["PSA"] - 16)
             - 0.01 * cols["VOL"] + 0.3 * (cols["RACE"] == "black"))
    y = rng.random(n) < 1 / (1 + np.exp(-logit))
    cols["CAPSULE"] = np.where(y, "yes", "no").astype(object)
    fr = Frame.from_numpy(cols, types={"RACE": T_CAT, "DPROS": T_CAT,
                                       "CAPSULE": T_CAT})
    data = {k: list(v) for k, v in cols.items()}   # readers select features
    return fr, data


def _native_probs(model, fr, col=2):
    return model.predict(fr).to_numpy()[:, col].astype(np.float64)


def test_gbm_binomial_roundtrip(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import GBM
    m = GBM(response_column="CAPSULE", ntrees=12, max_depth=4, seed=7).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "gbm.zip"))
    mojo = load_h2o_mojo(path)
    assert mojo.algo == "gbm" and mojo.nclasses == 2
    out = mojo.predict(data)
    np.testing.assert_allclose(out["probabilities"][:, 1],
                               _native_probs(m, fr), rtol=0, atol=1e-6)
    # label decisions use the exported default_threshold
    assert set(out["predict"]) <= {"yes", "no"}


def test_gbm_regression_roundtrip(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import GBM
    m = GBM(response_column="PSA", ntrees=10, max_depth=5, seed=3).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "gbm_reg.zip"))
    out = load_h2o_mojo(path).predict(data)
    native = m.predict(fr).to_numpy()[:, 0].astype(np.float64)
    np.testing.assert_allclose(out["predict"], native, rtol=0, atol=1e-5)


def test_gbm_multinomial_roundtrip(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import GBM
    m = GBM(response_column="DPROS", ntrees=6, max_depth=3, seed=5).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "gbm_multi.zip"))
    mojo = load_h2o_mojo(path)
    assert mojo.nclasses == 4
    out = mojo.predict(data)
    native = m.predict(fr).to_numpy()[:, 1:5].astype(np.float64)
    np.testing.assert_allclose(out["probabilities"], native,
                               rtol=0, atol=1e-5)


def test_drf_binomial_and_regression_roundtrip(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import DRF
    mb = DRF(response_column="CAPSULE", ntrees=10, max_depth=4,
             seed=11).train(fr)
    out = load_h2o_mojo(write_h2o_mojo(
        mb, str(tmp_path / "drf.zip"))).predict(data)
    np.testing.assert_allclose(out["probabilities"][:, 1],
                               _native_probs(mb, fr), rtol=0, atol=1e-6)
    mr = DRF(response_column="VOL", ntrees=8, max_depth=4, seed=11).train(fr)
    out = load_h2o_mojo(write_h2o_mojo(
        mr, str(tmp_path / "drf_reg.zip"))).predict(data)
    native = mr.predict(fr).to_numpy()[:, 0].astype(np.float64)
    np.testing.assert_allclose(out["predict"], native, rtol=0, atol=1e-5)


def test_xgboost_exports_as_gbm_format(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import XGBoost
    m = XGBoost(response_column="CAPSULE", ntrees=8, max_depth=4,
                seed=2).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "xgb.zip"))
    mojo = load_h2o_mojo(path)
    assert mojo.algo == "gbm"           # additive-margin family contract
    out = mojo.predict(data)
    np.testing.assert_allclose(out["probabilities"][:, 1],
                               _native_probs(m, fr), rtol=0, atol=1e-6)


def test_glm_binomial_roundtrip(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import GLM
    m = GLM(response_column="CAPSULE", family="binomial",
            lambda_=0.0).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "glm.zip"))
    mojo = load_h2o_mojo(path)
    assert mojo.algo == "glm"
    out = mojo.predict(data)
    np.testing.assert_allclose(out["probabilities"][:, 1],
                               _native_probs(m, fr), rtol=0, atol=1e-5)


def test_glm_gaussian_roundtrip(tmp_path):
    fr, data = _prostate_like()
    from h2o3_tpu.models import GLM
    m = GLM(response_column="PSA", family="gaussian", lambda_=0.0).train(fr)
    out = load_h2o_mojo(write_h2o_mojo(
        m, str(tmp_path / "glm_g.zip"))).predict(data)
    native = m.predict(fr).to_numpy()[:, 0].astype(np.float64)
    np.testing.assert_allclose(out["predict"], native, rtol=1e-5, atol=1e-4)


def test_format_is_reference_shaped(tmp_path):
    """The archive carries the reference ini surface + tree blob names."""
    import zipfile
    fr, _ = _prostate_like(n=200)
    from h2o3_tpu.models import GBM
    m = GBM(response_column="CAPSULE", ntrees=3, max_depth=3, seed=1).train(fr)
    path = write_h2o_mojo(m, str(tmp_path / "fmt.zip"))
    with zipfile.ZipFile(path) as z:
        names = set(z.namelist())
        ini = z.read("model.ini").decode()
    assert "trees/t00_000.bin" in names and "trees/t00_002.bin" in names
    for key in ("mojo_version = 1.30", "algo = gbm", "n_classes = 2",
                "distribution = bernoulli", "link_function = logit",
                "[columns]", "[domains]"):
        assert key in ini, key
    # domains files referenced by the ini exist
    assert any(n.startswith("domains/") for n in names)
    # the declared cardinality must equal the real level count — the
    # reference's ModelMojoReader sizes domain arrays from it (our own
    # reader ignores it, so the round-trip tests can't catch a drift)
    import re
    dom_lines = ini.split("[domains]")[1].strip().splitlines()
    with zipfile.ZipFile(path) as z:
        for line in dom_lines:
            mres = re.match(r"(\d+): (\d+) (d\d+\.txt)", line.strip())
            assert mres, line
            levels = z.read(f"domains/{mres.group(3)}").decode().splitlines()
            assert int(mres.group(2)) == len(levels), line
    # RACE has 3 levels, DPROS 4 — at least one non-binary domain present
    cards = [int(re.match(r"\d+: (\d+)", ln.strip()).group(1))
             for ln in dom_lines]
    assert any(c > 2 for c in cards)


def test_mojo_version_pinned(tmp_path):
    from h2o3_tpu.export.h2o_mojo_writer import (_MOJO_TREE_VERSION,
                                                 _MOJO_GLM_VERSION)
    assert _MOJO_TREE_VERSION == "1.30"
    assert _MOJO_GLM_VERSION == "1.00"
