"""Test harness: simulate an 8-device TPU mesh on CPU.

The reference's pattern (SURVEY.md §4): tests run against a real in-process
cloud (water.TestUtil.stall_till_cloudsize), with multi-node tests spawning
real JVMs on localhost (scripts/multiNodeUtils.sh).  Here the analog is a
virtual 8-device CPU mesh: XLA partitions and executes the very same SPMD
programs (collectives included) that run on a TPU slice, so sharding bugs
surface without TPU hardware.
"""

import os

# Force the CPU backend: the test mesh must be 8 virtual CPU devices, never
# the (single, exclusively-held) real TPU chip — grabbing it from multiple
# test processes deadlocks in backend init.  The env var alone is NOT enough:
# this image pre-imports jax from sitecustomize.py with JAX_PLATFORMS=axon
# baked into the config, so we must update the live config too (before any
# backend is initialized).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# NOTE: the persistent XLA compilation cache was tried here and reverted:
# XLA:CPU AOT reload is machine-feature-sensitive in this image (loader
# warns about +prefer-no-scatter mismatches, then segfaults mid-suite).

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cl():
    import h2o3_tpu
    return h2o3_tpu.init()


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
