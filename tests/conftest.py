"""Test harness: simulate an 8-device TPU mesh on CPU.

The reference's pattern (SURVEY.md §4): tests run against a real in-process
cloud (water.TestUtil.stall_till_cloudsize), with multi-node tests spawning
real JVMs on localhost (scripts/multiNodeUtils.sh).  Here the analog is a
virtual 8-device CPU mesh: XLA partitions and executes the very same SPMD
programs (collectives included) that run on a TPU slice, so sharding bugs
surface without TPU hardware.
"""

import os

# Force the CPU backend: the test mesh must be 8 virtual CPU devices, never
# the (single, exclusively-held) real TPU chip — grabbing it from multiple
# test processes deadlocks in backend init.  The env var alone is NOT enough:
# this image pre-imports jax from sitecustomize.py with JAX_PLATFORMS=axon
# baked into the config, so we must update the live config too (before any
# backend is initialized).
os.environ["JAX_PLATFORMS"] = "cpu"
# H2O3_TPU_TEST_DEVICES sizes the virtual mesh (tools/tier1.sh runs the
# suite at 16 at least once); default stays the historical 8.
_n_dev = int(os.environ.get("H2O3_TPU_TEST_DEVICES", "8"))
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={_n_dev}").strip()
# default the hierarchical mesh to 2 virtual hosts so every suite run
# exercises the ICI-then-DCN staged reduce, not just the flat path
os.environ.setdefault("H2O3_TPU_HOSTS", "2")
# pin the autotuner off for the suite: tier-1 asserts exact knob
# behaviour (subtract/fused/sparse-below-8/hier) and must stay
# bit-identical run to run.  tests/test_autotune.py opts back in
# per-test via reset() + monkeypatch.
os.environ.setdefault("H2O3_TPU_AUTOTUNE", "off")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# NOTE: the persistent XLA compilation cache was tried here and reverted:
# XLA:CPU AOT reload is machine-feature-sensitive in this image (loader
# warns about +prefer-no-scatter mismatches, then segfaults mid-suite).

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cl():
    import h2o3_tpu
    return h2o3_tpu.init()


@pytest.fixture()
def rng():
    return np.random.default_rng(42)


# Modules dominated by compile-heavy tree/NN builds or multi-process spawns.
# The smoke tier (`pytest -m "not slow"`) skips these and finishes in ~2 min;
# the full suite remains the merge gate.
_SLOW_MODULES = {
    "test_trees", "test_trees_ext", "test_hist_kernel", "test_multiprocess",
    "test_deeplearning", "test_tree_explain",
    "test_algos3",
}
# test_orchestration left the set: its tests now run tiny shapes by
# default with the original full shapes behind @pytest.mark.heavy, so the
# fast variants contribute tier-1 coverage.


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.nodeid.split("::")[0].rsplit("/", 1)[-1].removesuffix(".py")
        if mod in _SLOW_MODULES:
            item.add_marker(pytest.mark.slow)
        # heavy tests never belong in the smoke tier either — implying
        # `slow` keeps `-m 'not slow'` runs inside their budget too
        if item.get_closest_marker("heavy") is not None:
            item.add_marker(pytest.mark.slow)


def pytest_sessionfinish(session, exitstatus):
    """Compile-stats artifact (tools/tier1.sh sets the path env var).

    Top-10 slowest compiled programs plus the total recompile count from
    the runtime's compile ledger, written next to the durations artifact
    so per-PR compile-time creep is attributable the same way wall-clock
    creep is."""
    path = os.environ.get("H2O3_TIER1_COMPILE_STATS")
    if not path:
        return
    try:
        from h2o3_tpu.runtime import xprof
        snap = xprof.ledger_snapshot()
    except Exception:
        return
    progs = sorted(snap["programs"].items(),
                   key=lambda kv: kv[1]["compile_s"], reverse=True)
    recompiles = sum(max(p["compiles"] - 1, 0) for _, p in progs)
    lines = [f"total_compiles={snap['total_compiles']} "
             f"total_compile_s={snap['total_compile_s']:.2f} "
             f"recompiles={recompiles}",
             f"{'compile_s':>10} {'count':>6}  program (reasons)"]
    def _row(name, p):
        reasons = ",".join(f"{k}={v}" for k, v in sorted(p["reasons"].items()))
        return (f"{p['compile_s']:>10.2f} {p['compiles']:>6}  "
                f"{name} ({reasons})")

    for name, p in progs[:10]:
        lines.append(_row(name, p))
    # The whole-tree scan programs are pinned into the artifact even when
    # they miss the top-10: tools/tier1.sh greps this row so the scan
    # build's compile cost stays attributable per PR.
    for name, p in progs[10:]:
        if name.startswith("tree_build_scan"):
            lines.append(_row(name, p))
    try:
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
    except OSError:
        pass


@pytest.fixture(autouse=True, scope="module")
def _release_compiled_programs():
    """Drop compiled XLA programs between test modules.

    The full suite accumulates hundreds of compiled CPU executables (one
    per tree geometry etc.); past ~120 tests that reliably ended in a
    segfault inside XLA:CPU execution.  Clearing the builder lru_caches +
    jax caches per module keeps the executable population bounded (each
    module recompiles what it needs)."""
    yield
    import gc
    import jax as _jax
    try:
        from h2o3_tpu.models.tree import hist as _h, shared as _s
        for fn in (_h.make_hist_fn, _h.make_fine_hist_fn,
                   _h.make_varbin_hist_fn, _h.make_subtract_level_fn,
                   _h.make_batched_level_fn, _h.make_sparse_level_fn,
                   _h.make_batched_sparse_level_fn,
                   _h.make_scan_level_fn, _h.make_batched_scan_level_fn,
                   _s.make_build_tree_fn, _s.make_tree_scan_fn,
                   _s.make_multinomial_scan_fn, _s.make_grid_scan_fn):
            fn.cache_clear()
    except Exception:
        pass
    _jax.clear_caches()
    gc.collect()
