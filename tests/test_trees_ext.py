"""IsolationForest / ExtendedIsolationForest / XGBoost estimator tests.

Mirrors the reference's pyunit strategy (testdir_algos/{isofor,
isoforextended,xgboost}): anomaly separation on planted outliers, XGBoost
param-alias surface, regularization behavior, DART smoke.
"""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models import (IsolationForest, ExtendedIsolationForest,
                             XGBoost, GBM)


def _with_outliers(rng, n=2000, n_out=20):
    X = rng.normal(size=(n, 2))
    out = rng.normal(size=(n_out, 2)) * 0.5 + 8.0
    Xall = np.concatenate([X, out])
    is_out = np.concatenate([np.zeros(n, bool), np.ones(n_out, bool)])
    return Frame.from_numpy({"x": Xall[:, 0], "y": Xall[:, 1]}), is_out


def test_isolation_forest_separates_outliers(cl, rng):
    fr, is_out = _with_outliers(rng)
    m = IsolationForest(ntrees=50, seed=5).train(fr)
    pred = m.predict(fr)
    assert pred.names == ["predict", "mean_length"]
    score = pred.vecs[0].to_numpy()
    # planted outliers must rank above the bulk
    assert score[is_out].mean() > score[~is_out].mean() + 0.1
    auc_like = (score[is_out][:, None] > score[~is_out][None, :]).mean()
    assert auc_like > 0.95
    ml = pred.vecs[1].to_numpy()
    assert ml[is_out].mean() < ml[~is_out].mean()


def test_isolation_forest_contamination_threshold(cl, rng):
    fr, is_out = _with_outliers(rng)
    m = IsolationForest(ntrees=30, seed=5, contamination=0.01).train(fr)
    assert 0 < m.output["threshold"] < 1


def test_extended_isolation_forest(cl, rng):
    fr, is_out = _with_outliers(rng)
    m = ExtendedIsolationForest(ntrees=40, extension_level=1, seed=5).train(fr)
    pred = m.predict(fr)
    assert pred.names == ["anomaly_score", "mean_length"]
    score = pred.vecs[0].to_numpy()
    assert score[is_out].mean() > score[~is_out].mean() + 0.1


def _reg_frame(rng, n=3000):
    X = rng.normal(size=(n, 4))
    y = 2 * X[:, 0] - X[:, 1] + 0.5 * X[:, 2] ** 2 + 0.1 * rng.normal(size=n)
    cols = {f"x{j}": X[:, j] for j in range(4)}
    cols["y"] = y
    return Frame.from_numpy(cols)


def test_xgboost_regression_and_aliases(cl, rng):
    fr = _reg_frame(rng)
    m = XGBoost(response_column="y", n_estimators=30, eta=0.3, subsample=0.9,
                colsample_bytree=0.9, min_child_weight=2.0,
                objective="reg:squarederror", seed=1).train(fr)
    assert m.params.learn_rate == 0.3
    assert m.params.sample_rate == 0.9
    assert m.training_metrics.rmse < 0.6
    assert m.algo == "xgboost"


def test_xgboost_binary_and_scale_pos_weight(cl, rng):
    n = 4000
    X = rng.normal(size=(n, 3))
    yb = (X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.normal(size=n) > 1.2)
    cols = {f"x{j}": X[:, j] for j in range(3)}
    cols["y"] = np.where(yb, "yes", "no").astype(object)
    fr = Frame.from_numpy(cols)
    m = XGBoost(response_column="y", ntrees=30, seed=1).train(fr)
    assert m.training_metrics.auc > 0.95
    m2 = XGBoost(response_column="y", ntrees=30, seed=1,
                 scale_pos_weight=4.0).train(fr)
    assert m2.training_metrics.auc > 0.9


def test_xgboost_regularization_shrinks(cl, rng):
    fr = _reg_frame(rng)
    m0 = XGBoost(response_column="y", ntrees=20, seed=1,
                 reg_lambda=0.0, reg_alpha=0.0).train(fr)
    m1 = XGBoost(response_column="y", ntrees=20, seed=1,
                 reg_lambda=50.0, reg_alpha=5.0).train(fr)
    v0 = np.abs(np.concatenate([t.values for t in m0.output["trees"]])).max()
    v1 = np.abs(np.concatenate([t.values for t in m1.output["trees"]])).max()
    assert v1 < v0


def test_xgboost_gamma_prunes(cl, rng):
    fr = _reg_frame(rng)
    m0 = XGBoost(response_column="y", ntrees=10, seed=1, gamma=0.0).train(fr)
    m1 = XGBoost(response_column="y", ntrees=10, seed=1,
                 gamma=1e6).train(fr)
    splits0 = sum(v.sum() for t in m0.output["trees"] for v in t.valid)
    splits1 = sum(v.sum() for t in m1.output["trees"] for v in t.valid)
    assert splits1 < splits0


def test_xgboost_dart(cl, rng):
    fr = _reg_frame(rng)
    m = XGBoost(response_column="y", ntrees=25, booster="dart",
                rate_drop=0.3, seed=1).train(fr)
    assert m.output["ntrees_trained"] == 25
    assert m.training_metrics.rmse < 1.0
    pred = m.predict(fr)
    assert np.isfinite(pred.vecs[0].to_numpy()).all()


def test_xgboost_multinomial(cl, rng):
    n = 3000
    X = rng.normal(size=(n, 3))
    cls = np.argmax(X[:, :3] + 0.3 * rng.normal(size=(n, 3)), axis=1)
    cols = {f"x{j}": X[:, j] for j in range(3)}
    cols["y"] = np.array(["a", "b", "c"], dtype=object)[cls]
    fr = Frame.from_numpy(cols)
    m = XGBoost(response_column="y", ntrees=20, seed=1).train(fr)
    pred = m.predict(fr)
    acc = np.mean(pred.vecs[0].decoded() == cols["y"])
    assert acc > 0.8


def test_xgboost_matches_gbm_when_params_align(cl, rng):
    """With lambda=0, alpha=0, gamma=0, mcw=0, xgboost == gbm split math."""
    fr = _reg_frame(rng)
    common = dict(response_column="y", ntrees=10, max_depth=4, seed=7,
                  learn_rate=0.1, nbins=64, min_rows=10.0)
    mg = GBM(**common).train(fr)
    mx = XGBoost(reg_lambda=0.0, min_child_weight=0.0, **common).train(fr)
    pg = mg.predict(fr).vecs[0].to_numpy()
    px = mx.predict(fr).vecs[0].to_numpy()
    np.testing.assert_allclose(pg, px, rtol=1e-4, atol=1e-4)
