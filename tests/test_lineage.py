"""Shard lineage + partial re-materialization correctness (tier-1).

The contract under test: a re-materialized shard is BITWISE equal to the
original (canonical column bytes, content-hash verified), whether it was
rebuilt by replica copy, ranged source re-parse, op-chain replay, or a
checkpoint load — and a rebuild that cannot be proven correct raises
RematError instead of producing wrong data.
"""

import os

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu.frame import lineage
from h2o3_tpu.frame.parse import parse_csv
from h2o3_tpu.frame.vec import T_CAT, T_NUM, T_STR, T_TIME
from h2o3_tpu.runtime import dkv, failure, remat
from h2o3_tpu.runtime.config import reload as config_reload


def _write_csv(tmp_path, name="data.csv", n=240):
    """Mixed-type CSV: numeric, numeric-with-NA, categorical, date, and
    a high-cardinality string column."""
    lines = ["num,gappy,cat,when,tag"]
    for i in range(n):
        gap = "NA" if i % 11 == 0 else f"{i * 0.25}"
        cat = ["red", "green", "blue"][i % 3] if i % 13 else "NA"
        day = f"2021-{(i % 12) + 1:02d}-{(i % 27) + 1:02d}"
        lines.append(f"{i},{gap},{cat},{day},tag_{i:05d}")
    path = tmp_path / name
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def _assert_canonical_equal(a, b, what=""):
    ca, cb = lineage.canonical_cols(a), lineage.canonical_cols(b)
    assert a.names == b.names and a.nrows == b.nrows, what
    for name, x, y in zip(a.names, ca, cb):
        if x.dtype == object:
            assert list(x) == list(y), f"{what}: column {name}"
        else:
            assert x.dtype == y.dtype, f"{what}: column {name} dtype"
            np.testing.assert_array_equal(x, y, err_msg=f"{what}: {name}")


@pytest.fixture(autouse=True)
def _clean(cl):
    failure.reset()
    yield
    failure.reset()
    os.environ.pop("H2O3_TPU_FAULT_INJECT", None)
    for k in ("H2O3_TPU_REPLICATE_BELOW_MB", "H2O3_TPU_LINEAGE_MAX_CHAIN",
              "H2O3_TPU_LINEAGE_MAX_INDEX"):
        os.environ.pop(k, None)
    config_reload()


# ----------------------------------------------------------- parse records

def test_parse_stamps_lineage_record(cl, tmp_path):
    path = _write_csv(tmp_path)
    fr = parse_csv(path, destination_frame="lin_parse")
    rec = lineage.get_record("lin_parse")
    assert rec is not None and rec["kind"] == "parse"
    assert rec["source"] == os.path.abspath(path)
    assert rec["n_shards"] == cl.n_hosts
    assert rec["nrows"] == fr.nrows
    assert rec["schema"]["names"] == fr.names
    assert rec["schema"]["types"] == [v.type for v in fr.vecs]
    assert set(rec["schema"]["types"]) == {T_NUM, T_CAT, T_TIME, T_STR}
    # shards tile the rows exactly, in order, and carry both hashes
    row = 0
    for s in rec["shards"]:
        assert s["row_lo"] == row
        row += s["rows"]
        assert len(s["src_sha1"]) == 40
        assert len(s["val_sha1"]) == 40
    assert row == fr.nrows
    # byte ranges are contiguous over the body (no header overlap)
    spans = [(s["lo"], s["hi"]) for s in rec["shards"] if s["rows"]]
    assert all(a[1] == b[0] for a, b in zip(spans, spans[1:]))
    assert spans[0][0] > 0               # header excluded from shard 0
    dkv.remove("lin_parse")
    lineage.drop_record("lin_parse")


def test_unsafe_sources_leave_no_record(cl, tmp_path):
    # quoted embedded newline: physical lines != rows, so the byte-range
    # claim would be wrong — lineage must refuse to stamp
    path = tmp_path / "quoted.csv"
    path.write_text('a,b\n1,"x\ny"\n2,z\n')
    fr = parse_csv(str(path), destination_frame="lin_quoted")
    assert lineage.get_record("lin_quoted") is None
    dkv.remove("lin_quoted")
    # in-memory buffers have no byte provenance at all
    fr2 = parse_csv(b"a,b\n1,2\n", destination_frame="lin_buf")
    assert lineage.get_record("lin_buf") is None
    dkv.remove("lin_buf")
    del fr, fr2


# ------------------------------------------------------- re-materialization

def test_full_rebuild_is_bitwise_equal(cl, tmp_path):
    fr = parse_csv(_write_csv(tmp_path), destination_frame="lin_full")
    dkv.remove("lin_full")
    fr2 = remat.recover_frame("lin_full")
    _assert_canonical_equal(fr, fr2, "full rebuild")
    assert remat.last_stats["mode"] == "reparse"
    assert dkv.get("lin_full") is fr2    # re-registered under its key
    dkv.remove("lin_full")
    lineage.drop_record("lin_full")


def test_partial_repair_reparses_only_lost_shard(cl, tmp_path):
    fr = parse_csv(_write_csv(tmp_path), destination_frame="lin_part")
    rec = lineage.get_record("lin_part")
    lost = rec["n_shards"] - 1
    # a second ranged re-parse would raise: proves exactly one happens
    failure.reset()
    os.environ["H2O3_TPU_FAULT_INJECT"] = "parse_range:0:2:raise"
    fr2 = remat.recover_frame("lin_part", lost={lost})
    os.environ.pop("H2O3_TPU_FAULT_INJECT")
    _assert_canonical_equal(fr, fr2, "partial repair")
    assert remat.last_stats["reparsed"] == [
        [rec["shards"][lost]["lo"], rec["shards"][lost]["hi"]]]
    assert sorted(remat.last_stats["copied"]) == [
        s["shard"] for s in rec["shards"] if s["shard"] != lost]
    dkv.remove("lin_part")
    lineage.drop_record("lin_part")


def test_changed_source_raises_never_rebuilds_wrong(cl, tmp_path):
    path = _write_csv(tmp_path, "mutates.csv")
    parse_csv(path, destination_frame="lin_mut")
    dkv.remove("lin_mut")
    body = open(path).read().replace("tag_00001", "tag_XXXXX")
    open(path, "w").write(body)
    with pytest.raises(remat.RematError, match="no longer match"):
        remat.recover_frame("lin_mut")
    lineage.drop_record("lin_mut")


def test_metrics_and_timeline(cl, tmp_path):
    from h2o3_tpu.runtime.observability import counter, timeline_events
    fr = parse_csv(_write_csv(tmp_path), destination_frame="lin_met")
    before = counter("remat_shards_total", mode="reparse").value
    dkv.remove("lin_met")
    remat.recover_frame("lin_met")
    gained = counter("remat_shards_total", mode="reparse").value - before
    assert gained == sum(1 for s in lineage.get_record("lin_met")["shards"]
                         if s["rows"])
    ev = [e for e in timeline_events(500) if e.get("kind") == "remat"]
    assert ev and ev[-1]["frame"] == "lin_met"
    del fr
    dkv.remove("lin_met")
    lineage.drop_record("lin_met")


# ------------------------------------------------------------ derived chains

def test_derived_chain_replays_bitwise(cl, tmp_path):
    fr = parse_csv(_write_csv(tmp_path), destination_frame="lin_root")
    piece = fr.drop(["tag"]).split_frame([0.7, 0.3], seed=11)[1]
    lineage.register(piece, "lin_valid")
    rec = lineage.get_record("lin_valid")
    assert rec["kind"] == "derived" and rec["root"] == "lin_root"
    assert [o["op"] for o in rec["ops"]] == ["drop", "split"]
    dkv.remove("lin_valid")
    back = remat.recover_frame("lin_valid")
    _assert_canonical_equal(piece, back, "derived replay")
    assert remat.last_stats["mode"] == "replay"
    for k in ("lin_root", "lin_valid"):
        dkv.remove(k)
        lineage.drop_record(k)


def test_rapids_ops_replay_bitwise(cl, tmp_path):
    from h2o3_tpu.rapids import ops
    fr = parse_csv(_write_csv(tmp_path), destination_frame="lin_rap")
    out = ops.scale(ops.impute(ops.sort(fr.drop(["tag"]), "cat"), "gappy"))
    rec = out._lineage
    assert [o["op"] for o in rec["ops"]] == ["drop", "sort", "impute",
                                             "scale"]
    lineage.register(out, "lin_munged")
    dkv.remove("lin_munged")
    back = remat.recover_frame("lin_munged")
    _assert_canonical_equal(out, back, "rapids replay")
    for k in ("lin_rap", "lin_munged"):
        dkv.remove(k)
        lineage.drop_record(k)


def test_rows_with_huge_index_breaks_chain(cl, tmp_path):
    os.environ["H2O3_TPU_LINEAGE_MAX_INDEX"] = "10"
    config_reload()
    fr = parse_csv(_write_csv(tmp_path), destination_frame="lin_idx")
    small = fr.rows(np.arange(5))        # under the cap: replayable
    assert small._lineage is not None
    big = fr.rows(np.arange(100))        # over the cap: chain broken
    assert big._lineage is None
    dkv.remove("lin_idx")
    lineage.drop_record("lin_idx")


def test_unreplayable_op_breaks_chain(cl, tmp_path):
    fr = parse_csv(_write_csv(tmp_path), destination_frame="lin_brk")
    merged = fr.cbind(fr.rename({c: f"{c}_2" for c in fr.names}))
    assert merged._lineage is None       # cbind is not replayable
    lineage.register(merged, "lin_cbind")
    assert lineage.get_record("lin_cbind") is None
    with pytest.raises(remat.RematError, match="no lineage"):
        remat.recover_frame("lin_cbind")
    for k in ("lin_brk", "lin_cbind"):
        dkv.remove(k)
        lineage.drop_record(k)


def test_deep_chain_checkpoints(cl, tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3_TPU_RECOVERY_DIR", str(tmp_path / "rec"))
    os.makedirs(tmp_path / "rec", exist_ok=True)
    os.environ["H2O3_TPU_LINEAGE_MAX_CHAIN"] = "2"
    config_reload()
    fr = parse_csv(_write_csv(tmp_path), destination_frame="lin_deep")
    out = fr
    for _ in range(4):                   # chain depth 4 > cap 2
        out = out.drop([]).rename({})
    assert len(out._lineage["ops"]) == 8
    lineage.register(out, "lin_ckpt")
    rec = lineage.get_record("lin_ckpt")
    assert rec["kind"] == "checkpoint" and rec["uri"]
    dkv.remove("lin_ckpt")
    back = remat.recover_frame("lin_ckpt")
    _assert_canonical_equal(out, back, "checkpoint rebuild")
    assert remat.last_stats["mode"] == "checkpoint"
    for k in ("lin_deep", "lin_ckpt"):
        dkv.remove(k)
        lineage.drop_record(k)


# -------------------------------------------------------------- replicas

def test_hot_frame_replicas_recover_without_reparse(cl, tmp_path):
    os.environ["H2O3_TPU_REPLICATE_BELOW_MB"] = "10"
    config_reload()
    fr = parse_csv(_write_csv(tmp_path), destination_frame="lin_rep")
    rec = lineage.get_record("lin_rep")
    assert len(rec["replicas"]) == rec["n_shards"]
    for i, meta in rec["replicas"].items():
        assert meta["host"] == (int(i) + 1) % rec["n_shards"]  # neighbor
        assert dkv.get(lineage.replica_key("lin_rep", int(i))) is not None
    # any re-parse would raise: recovery must ride the replicas
    failure.reset()
    os.environ["H2O3_TPU_FAULT_INJECT"] = "parse_range:0:1:raise"
    fr2 = remat.recover_frame("lin_rep", lost={0})
    os.environ.pop("H2O3_TPU_FAULT_INJECT")
    _assert_canonical_equal(fr, fr2, "replica recovery")
    assert remat.last_stats["replica"] == [0]
    assert not remat.last_stats["reparsed"]
    dkv.remove("lin_rep")
    lineage.drop_record("lin_rep")


def test_corrupt_replica_falls_back_to_reparse(cl, tmp_path):
    os.environ["H2O3_TPU_REPLICATE_BELOW_MB"] = "10"
    config_reload()
    fr = parse_csv(_write_csv(tmp_path), destination_frame="lin_bad")
    rep_key = lineage.replica_key("lin_bad", 0)
    rep = dict(dkv.get(rep_key))
    rep["cols"] = [np.asarray(c).copy() for c in rep["cols"]]
    bad = rep["cols"][0]
    bad[0] = -999.0                      # silent bitflip in the replica
    dkv.put(rep_key, rep)
    fr2 = remat.recover_frame("lin_bad", lost={0})
    # the replica failed its hash; the shard came from a re-parse instead
    assert remat.last_stats["reparsed"]
    _assert_canonical_equal(fr, fr2, "corrupt replica fallback")
    dkv.remove("lin_bad")
    lineage.drop_record("lin_bad")


def test_lineage_disabled_leaves_no_records(cl, tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3_TPU_LINEAGE", "0")
    config_reload()
    try:
        fr = parse_csv(_write_csv(tmp_path), destination_frame="lin_off")
        assert lineage.get_record("lin_off") is None
        assert fr.drop(["tag"])._lineage is None
    finally:
        monkeypatch.delenv("H2O3_TPU_LINEAGE")
        config_reload()
    dkv.remove("lin_off")
