"""``train(warm_start=...)`` public API (tier-1).

Contract: warm-starting from a prior model — passed as a live Model, a
DKV key, or a saved artifact path — is bit-identical to the existing
``checkpoint`` continuation, and algos without checkpoint support reject
it loudly instead of silently retraining from scratch.
"""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.models import GBM, GLM
from h2o3_tpu.runtime import dkv


def _frame(n=800, seed=3, key="ws_frame"):
    rng = np.random.default_rng(seed)
    X = rng.random((n, 5))
    y = (4 * np.sin(np.pi * X[:, 0]) + 3 * X[:, 1] ** 2
         + 2 * X[:, 2] + 0.05 * rng.normal(size=n))
    cols = {f"x{j}": X[:, j] for j in range(5)}
    cols["y"] = y
    return Frame.from_numpy(cols, key=key)


_KW = dict(response_column="y", max_depth=3, nbins=32, min_rows=10, seed=11)


def _pred(model, fr):
    return model.predict(fr).vec("predict").to_numpy()


def test_warm_start_bit_identical_to_checkpoint(cl):
    fr = _frame()
    prior = GBM(**_KW, ntrees=4).train(fr)
    chk = GBM(**_KW, ntrees=9, checkpoint=prior.key).train(fr)
    ws = GBM(**_KW, ntrees=9).train(fr, warm_start=prior)
    assert ws.output["ntrees_trained"] == chk.output["ntrees_trained"] == 9
    np.testing.assert_array_equal(_pred(chk, fr), _pred(ws, fr))


def test_warm_start_accepts_key_param_and_path(cl, tmp_path):
    fr = _frame(key="ws_frame2")
    prior = GBM(**_KW, ntrees=3).train(fr)
    ref = GBM(**_KW, ntrees=7, checkpoint=prior.key).train(fr)

    # DKV key form
    by_key = GBM(**_KW, ntrees=7).train(fr, warm_start=prior.key)
    np.testing.assert_array_equal(_pred(ref, fr), _pred(by_key, fr))

    # constructor-param form (flows through the generated estimators too)
    by_param = GBM(**_KW, ntrees=7, warm_start=prior.key).train(fr)
    np.testing.assert_array_equal(_pred(ref, fr), _pred(by_param, fr))

    # saved-artifact form: load from disk into a fresh DKV entry
    path = prior.save(str(tmp_path / "prior.model"))
    dkv.remove(prior.key)
    by_path = GBM(**_KW, ntrees=7).train(fr, warm_start=path)
    np.testing.assert_array_equal(_pred(ref, fr), _pred(by_path, fr))


def test_warm_start_rejected_without_checkpoint_support(cl):
    fr = _frame(key="ws_frame3")
    prior = GBM(**_KW, ntrees=2).train(fr)
    with pytest.raises(ValueError, match="warm_start"):
        GLM(response_column="y").train(fr, warm_start=prior)


def test_warm_start_unresolvable_reference(cl):
    fr = _frame(key="ws_frame4")
    with pytest.raises(ValueError):
        GBM(**_KW, ntrees=4).train(fr, warm_start="no_such_model_anywhere")
