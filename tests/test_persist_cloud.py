"""Cloud persist integration tests against in-process protocol fakes.

The reference integration-tests PersistGcs/PersistS3 against emulator
servers; same approach here: a fake GCS JSON-API server (driven through
the REAL google.cloud.storage SDK via STORAGE_EMULATOR_HOST), a fake S3
REST server (driven through the native SigV4 client), and a fake WebHDFS
namenode.  No mock-root shortcuts — every byte crosses HTTP.
"""

import io
import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import persist


# --------------------------------------------------------------- fake GCS

class _FakeGcs(BaseHTTPRequestHandler):
    store = {}          # (bucket, name) -> bytes
    sessions = {}       # token -> {"bucket","name","data"}

    def log_message(self, *a):
        pass

    def _send(self, code, body=b"", headers=None):
        self.send_response(code)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code, obj, headers=None):
        self._send(code, json.dumps(obj).encode(),
                   {"Content-Type": "application/json", **(headers or {})})

    def _meta(self, bucket, name):
        data = self.store[(bucket, name)]
        return {"kind": "storage#object", "name": name, "bucket": bucket,
                "size": str(len(data)), "generation": "1",
                "metageneration": "1",
                "contentType": "application/octet-stream"}

    def do_GET(self):
        u = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(u.query))
        m = re.fullmatch(r"/download/storage/v1/b/([^/]+)/o/(.+)", u.path)
        if m and q.get("alt") == "media":
            bucket, name = m.group(1), urllib.parse.unquote(m.group(2))
            if (bucket, name) not in self.store:
                return self._json(404, {"error": "not found"})
            data = self.store[(bucket, name)]
            rng = self.headers.get("Range")
            if rng:
                lo, hi = re.fullmatch(r"bytes=(\d+)-(\d+)", rng).groups()
                part = data[int(lo):int(hi) + 1]
                return self._send(206, part)
            return self._send(200, data)
        m = re.fullmatch(r"/storage/v1/b/([^/]+)/o/(.+)", u.path)
        if m:
            bucket, name = m.group(1), urllib.parse.unquote(m.group(2))
            if q.get("alt") == "media":
                if (bucket, name) not in self.store:
                    return self._json(404, {"error": "not found"})
                return self._send(200, self.store[(bucket, name)])
            if (bucket, name) not in self.store:
                return self._json(404, {"error": "not found"})
            return self._json(200, self._meta(bucket, name))
        m = re.fullmatch(r"/storage/v1/b/([^/]+)/o", u.path)
        if m:
            bucket = m.group(1)
            prefix = q.get("prefix", "")
            items = [self._meta(b, n) for (b, n) in sorted(self.store)
                     if b == bucket and n.startswith(prefix)]
            return self._json(200, {"kind": "storage#objects",
                                    "items": items})
        self._json(404, {"error": f"GET {self.path}"})

    def do_POST(self):
        u = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(u.query))
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length)
        m = re.fullmatch(r"/upload/storage/v1/b/([^/]+)/o", u.path)
        if m and q.get("uploadType") == "resumable":
            bucket = m.group(1)
            name = q.get("name")
            if not name and body:
                name = json.loads(body.decode()).get("name")
            token = f"sess{len(self.sessions)}"
            self.sessions[token] = {"bucket": bucket, "name": name,
                                    "data": bytearray()}
            host = self.headers.get("Host")
            return self._send(200, b"", {
                "Location": f"http://{host}/upload-session/{token}"})
        if m and q.get("uploadType") == "multipart":
            bucket = m.group(1)
            ctype = self.headers.get("Content-Type", "")
            boundary = ctype.split("boundary=")[-1].strip('"').encode()
            parts = body.split(b"--" + boundary)
            meta = json.loads(parts[1].split(b"\r\n\r\n", 1)[1]
                              .rsplit(b"\r\n", 1)[0].decode())
            payload = parts[2].split(b"\r\n\r\n", 1)[1]
            payload = payload.rsplit(b"\r\n", 1)[0]
            self.store[(bucket, meta["name"])] = payload
            return self._json(200, self._meta(bucket, meta["name"]))
        self._json(404, {"error": f"POST {self.path}"})

    def do_PUT(self):
        u = urllib.parse.urlsplit(self.path)
        m = re.fullmatch(r"/upload-session/(\w+)", u.path)
        if m:
            sess = self.sessions[m.group(1)]
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length)
            crange = self.headers.get("Content-Range", "")
            cm = re.fullmatch(r"bytes (\d+)-(\d+)/(\d+|\*)", crange)
            if cm:
                lo = int(cm.group(1))
                total = cm.group(3)
                buf = sess["data"]
                if len(buf) < lo:
                    buf.extend(b"\0" * (lo - len(buf)))
                buf[lo:lo + len(body)] = body
                if total != "*" and len(buf) >= int(total):
                    self.store[(sess["bucket"], sess["name"])] = bytes(buf)
                    return self._json(200, self._meta(sess["bucket"],
                                                      sess["name"]))
                return self._send(308, b"", {
                    "Range": f"bytes=0-{len(buf) - 1}"})
            cm = re.fullmatch(r"bytes \*/(\d+|\*)", crange)
            if cm:            # finalize empty or query status
                self.store[(sess["bucket"], sess["name"])] = \
                    bytes(sess["data"])
                return self._json(200, self._meta(sess["bucket"],
                                                  sess["name"]))
            self._json(400, {"error": f"bad content-range {crange}"})
            return
        self._json(404, {"error": f"PUT {self.path}"})

    def do_DELETE(self):
        u = urllib.parse.urlsplit(self.path)
        m = re.fullmatch(r"/storage/v1/b/([^/]+)/o/(.+)", u.path)
        if m:
            bucket, name = m.group(1), urllib.parse.unquote(m.group(2))
            if (bucket, name) in self.store:
                del self.store[(bucket, name)]
                return self._send(204)
            return self._json(404, {"error": "not found"})
        self._json(404, {"error": f"DELETE {self.path}"})


# --------------------------------------------------------------- fake S3

class _FakeS3(BaseHTTPRequestHandler):
    store = {}          # (bucket, key) -> bytes
    uploads = {}        # upload_id -> {"bucket","key","parts":{n: bytes}}

    def log_message(self, *a):
        pass

    def _send(self, code, body=b"", headers=None):
        self.send_response(code)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(body)

    def _split(self):
        u = urllib.parse.urlsplit(self.path)
        parts = u.path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
        return bucket, key, dict(urllib.parse.parse_qsl(
            u.query, keep_blank_values=True))

    def do_GET(self):
        bucket, key, q = self._split()
        if "list-type" in q or not key:
            prefix = q.get("prefix", "")
            keys = [k for (b, k) in sorted(self.store)
                    if b == bucket and k.startswith(prefix)]
            xml = "".join(f"<Contents><Key>{k}</Key></Contents>"
                          for k in keys)
            return self._send(200, (f"<ListBucketResult>{xml}"
                                    f"</ListBucketResult>").encode())
        if (bucket, key) not in self.store:
            return self._send(404, b"<Error><Code>NoSuchKey</Code></Error>")
        data = self.store[(bucket, key)]
        rng = self.headers.get("Range")
        if rng:
            lo, hi = re.fullmatch(r"bytes=(\d+)-(\d+)", rng).groups()
            return self._send(206, data[int(lo):int(hi) + 1])
        return self._send(200, data)

    def do_HEAD(self):
        bucket, key, _ = self._split()
        if (bucket, key) not in self.store:
            return self._send(404)
        self._send(200, self.store[(bucket, key)])

    def do_PUT(self):
        bucket, key, q = self._split()
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length)
        if "partNumber" in q:
            up = self.uploads[q["uploadId"]]
            n = int(q["partNumber"])
            up["parts"][n] = body
            return self._send(200, b"", {"ETag": f'"part{n}"'})
        self.store[(bucket, key)] = body
        self._send(200, b"", {"ETag": '"whole"'})

    def do_POST(self):
        bucket, key, q = self._split()
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length)
        if "uploads" in q:
            uid = f"up{len(self.uploads)}"
            self.uploads[uid] = {"bucket": bucket, "key": key, "parts": {}}
            return self._send(200, (
                f"<InitiateMultipartUploadResult><UploadId>{uid}"
                f"</UploadId></InitiateMultipartUploadResult>").encode())
        if "uploadId" in q:
            up = self.uploads.pop(q["uploadId"])
            data = b"".join(up["parts"][n]
                            for n in sorted(up["parts"]))
            self.store[(up["bucket"], up["key"])] = data
            return self._send(200, b"<CompleteMultipartUploadResult/>")
        self._send(404, body)

    def do_DELETE(self):
        bucket, key, _ = self._split()
        self.store.pop((bucket, key), None)
        self._send(204)


# ------------------------------------------------------------ fake WebHDFS

class _FakeHdfs(BaseHTTPRequestHandler):
    store = {}          # path -> bytes

    def log_message(self, *a):
        pass

    def _send(self, code, body=b"", headers=None):
        self.send_response(code)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code, obj):
        self._send(code, json.dumps(obj).encode(),
                   {"Content-Type": "application/json"})

    def _path_op(self):
        u = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(u.query))
        path = urllib.parse.unquote(u.path)
        for pre in ("/webhdfs/v1", "/webhdfs-data"):
            if path.startswith(pre):
                return path[len(pre):], q, pre
        return path, q, ""

    def do_GET(self):
        path, q, _ = self._path_op()
        op = q.get("op")
        if op == "OPEN":
            if path not in self.store:
                return self._json(404, {"RemoteException":
                                        {"message": "not found"}})
            data = self.store[path]
            off = int(q.get("offset", 0))
            ln = int(q["length"]) if "length" in q else len(data) - off
            return self._send(200, data[off:off + ln])
        if op == "GETFILESTATUS":
            if path not in self.store:
                return self._json(404, {"RemoteException":
                                        {"message": "not found"}})
            return self._json(200, {"FileStatus": {
                "length": len(self.store[path]), "type": "FILE",
                "pathSuffix": ""}})
        if op == "LISTSTATUS":
            if path in self.store:
                return self._json(200, {"FileStatuses": {"FileStatus": [
                    {"pathSuffix": "", "type": "FILE",
                     "length": len(self.store[path])}]}})
            base = path.rstrip("/") + "/"
            kids = [p[len(base):] for p in self.store
                    if p.startswith(base) and "/" not in p[len(base):]]
            if not kids:
                return self._json(404, {"RemoteException":
                                        {"message": "not found"}})
            return self._json(200, {"FileStatuses": {"FileStatus": [
                {"pathSuffix": k, "type": "FILE",
                 "length": len(self.store[base + k])} for k in
                sorted(kids)]}})
        self._json(400, {"RemoteException": {"message": f"op {op}"}})

    def do_PUT(self):
        path, q, pre = self._path_op()
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length)
        if pre == "/webhdfs/v1" and q.get("op") == "CREATE":
            host = self.headers.get("Host")
            loc = (f"http://{host}/webhdfs-data{urllib.parse.quote(path)}"
                   f"?op=CREATE")
            return self._send(307, b"", {"Location": loc})
        if pre == "/webhdfs-data":
            self.store[path] = body
            return self._send(201)
        self._json(400, {"RemoteException": {"message": "bad put"}})

    def do_DELETE(self):
        path, q, _ = self._path_op()
        existed = path in self.store
        self.store.pop(path, None)
        self._json(200, {"boolean": existed})


@pytest.fixture()
def fake_server():
    servers = []

    def start(handler):
        handler.store = {}
        if hasattr(handler, "sessions"):
            handler.sessions = {}
        if hasattr(handler, "uploads"):
            handler.uploads = {}
        srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        servers.append(srv)
        return srv.server_address[1]

    yield start
    for s in servers:
        s.shutdown()
        s.server_close()


def _roundtrip_frame(cl, scheme_uri):
    """export_file -> list -> import_file round trip over one backend."""
    rng = np.random.default_rng(5)
    n = 300
    fr = h2o3_tpu.H2OFrame({
        "x": rng.normal(size=n).astype(np.float32),
        "g": np.array([f"g{i % 4}" for i in range(n)], dtype=object)})
    from h2o3_tpu.frame.parse import export_file
    export_file(fr, scheme_uri)
    back = h2o3_tpu.import_file(scheme_uri)
    assert back.shape == fr.shape
    assert np.allclose(back.vec("x").to_numpy(), fr.vec("x").to_numpy(),
                       atol=1e-6)
    assert list(back.vec("g").decoded()) == list(fr.vec("g").decoded())
    return fr


def test_gcs_backend_against_emulator(cl, fake_server, monkeypatch):
    port = fake_server(_FakeGcs)
    monkeypatch.setenv("STORAGE_EMULATOR_HOST", f"http://127.0.0.1:{port}")
    monkeypatch.delenv("H2O3_TPU_GCS_ROOT", raising=False)
    # drop any cached client bound to an older emulator address
    persist._REGISTRY["gs"]._real = None
    persist._REGISTRY["gcs"]._real = None

    _roundtrip_frame(cl, "gs://bkt/dir/data.csv")
    # raw SPI: range read + size + list + exists + delete
    with persist.open_write("gs://bkt/dir/blob.bin") as f:
        f.write(b"0123456789abcdef")
    assert persist.exists("gs://bkt/dir/blob.bin")
    be, path = persist.split_uri("gs://bkt/dir/blob.bin")
    assert be.read_range(path, 4, 6) == b"456789"
    assert be.size(path) == 16
    ls = persist.list_uris("gs://bkt/dir/*")
    assert "gs://bkt/dir/blob.bin" in ls and "gs://bkt/dir/data.csv" in ls
    persist.delete("gs://bkt/dir/blob.bin")
    assert not persist.exists("gs://bkt/dir/blob.bin")
    persist._REGISTRY["gs"]._real = None
    persist._REGISTRY["gcs"]._real = None


def test_s3_backend_against_emulator(cl, fake_server, monkeypatch):
    port = fake_server(_FakeS3)
    monkeypatch.setenv("H2O3_TPU_S3_ENDPOINT", f"http://127.0.0.1:{port}")
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "test")      # exercise SigV4
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "secret")
    monkeypatch.delenv("H2O3_TPU_S3_ROOT", raising=False)
    persist._REGISTRY["s3"]._real = None

    _roundtrip_frame(cl, "s3://bkt/dir/data.csv")
    with persist.open_write("s3://bkt/dir/blob.bin") as f:
        f.write(b"0123456789abcdef")
    be, path = persist.split_uri("s3://bkt/dir/blob.bin")
    assert be.read_range(path, 4, 6) == b"456789"
    assert be.size(path) == 16
    ls = persist.list_uris("s3://bkt/dir/*")
    assert "s3://bkt/dir/blob.bin" in ls and "s3://bkt/dir/data.csv" in ls
    persist.delete("s3://bkt/dir/blob.bin")
    assert not persist.exists("s3://bkt/dir/blob.bin")
    persist._REGISTRY["s3"]._real = None


def test_s3_multipart_streaming_write(cl, fake_server, monkeypatch):
    from h2o3_tpu.persist import s3 as s3mod
    port = fake_server(_FakeS3)
    monkeypatch.setenv("H2O3_TPU_S3_ENDPOINT", f"http://127.0.0.1:{port}")
    monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)  # unsigned path
    monkeypatch.delenv("AWS_SECRET_ACCESS_KEY", raising=False)
    monkeypatch.setattr(s3mod, "_MULTIPART_CHUNK", 1024)
    persist._REGISTRY["s3"]._real = None

    payload = bytes(range(256)) * 20          # 5120 B -> 5 parts + tail
    with persist.open_write("s3://bkt/big.bin") as f:
        for i in range(0, len(payload), 700):  # odd-sized writes
            f.write(payload[i:i + 700])
    with persist.open_read("s3://bkt/big.bin") as f:
        assert f.read() == payload
    assert _FakeS3.uploads == {}              # completed, not dangling
    persist._REGISTRY["s3"]._real = None


def test_hdfs_backend_against_fake_namenode(cl, fake_server, monkeypatch):
    port = fake_server(_FakeHdfs)
    monkeypatch.setenv("H2O3_TPU_HDFS_NAMENODE", f"http://127.0.0.1:{port}")
    monkeypatch.delenv("H2O3_TPU_HDFS_ROOT", raising=False)
    persist._REGISTRY["hdfs"]._real = None

    with persist.open_write("hdfs://data/dir/blob.bin") as f:
        f.write(b"0123456789abcdef")
    assert persist.exists("hdfs://data/dir/blob.bin")
    be, path = persist.split_uri("hdfs://data/dir/blob.bin")
    assert be.read_range(path, 4, 6) == b"456789"
    assert be.size(path) == 16
    with persist.open_read("hdfs://data/dir/blob.bin") as f:
        assert f.read() == b"0123456789abcdef"
    persist.delete("hdfs://data/dir/blob.bin")
    assert not persist.exists("hdfs://data/dir/blob.bin")
    persist._REGISTRY["hdfs"]._real = None


def test_distributed_parse_over_gcs_ranges(cl, fake_server, monkeypatch):
    """parse_files_distributed reads cloud sources with byte-range
    requests through the persist SPI (PersistGcs-style chunk loads) and
    matches the local parse cell-for-cell."""
    port = fake_server(_FakeGcs)
    monkeypatch.setenv("STORAGE_EMULATOR_HOST", f"http://127.0.0.1:{port}")
    persist._REGISTRY["gs"]._real = None
    rng = np.random.default_rng(3)
    local = {}
    for k, nrows in enumerate((400, 900)):
        lines = ["num,cat,resp"]
        for i in range(nrows):
            lines.append(f"{rng.normal():.4f},lvl{k}_{i % (2 + k)},"
                         f"{'Y' if i % 3 else 'N'}")
        body = ("\n".join(lines) + "\n").encode()
        local[f"part{k}.csv"] = body
        with persist.open_write(f"gs://pbkt/d/part{k}.csv") as f:
            f.write(body)
    from h2o3_tpu.frame import dparse
    import h2o3_tpu.frame.parse as P
    uris = persist.list_uris("gs://pbkt/d/part*.csv")
    assert len(uris) == 2
    fr = dparse.parse_files_distributed(uris)
    # reference: parse the same bytes locally
    import io as _io
    ref_cols = {}
    import tempfile, os as _os
    d = tempfile.mkdtemp()
    lpaths = []
    for name, body in local.items():
        lp = _os.path.join(d, name)
        open(lp, "wb").write(body)
        lpaths.append(lp)
    fr2 = P.parse_files(sorted(lpaths))
    assert fr.shape == fr2.shape == (1300, 3)
    assert fr.types() == fr2.types()
    assert np.allclose(fr.vec("num").to_numpy(),
                       fr2.vec("num").to_numpy(), equal_nan=True)
    assert list(fr.vec("cat").decoded()) == list(fr2.vec("cat").decoded())
    assert dparse.last_stats["bytes_tokenized"] > 0
    persist._REGISTRY["gs"]._real = None
