"""Real H2O MOJO import: score reference-produced artifacts identically.

Golden fixtures come from the reference's own test resources (read-only,
never copied into this repo); tests skip when the reference tree is not
mounted.  The GBM golden value (71.085) is the reference's own
MojoReaderBackendFactoryTest.testMojoE2E expectation.
"""

import os

import numpy as np
import pytest

_REF = "/root/reference/h2o-genmodel/src/test/resources/hex/genmodel"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(_REF), reason="reference fixtures not mounted")

# the reference's own golden row (MojoReaderBackendFactoryTest.makeTestRow)
_GOLDEN_ROW = (
    "75,0,190,80,91,193,371,174,121,-16,13,64,-2,0,63,0,52,44,0,0,32,0,0,0,"
    "0,0,0,0,44,20,36,0,28,0,0,0,0,0,0,52,40,0,0,0,60,0,0,0,0,0,0,52,0,0,0,"
    "0,0,0,0,0,0,0,0,0,56,36,0,0,32,0,0,0,0,0,0,48,32,0,0,0,56,0,0,0,0,0,0,"
    "80,0,0,0,0,0,0,0,0,0,0,0,0,40,52,0,0,28,0,0,0,0,0,0,0,48,48,0,0,32,0,"
    "0,0,0,0,0,0,52,52,0,0,36,0,0,0,0,0,0,0,52,48,0,0,32,0,0,0,0,0,0,0,56,"
    "44,0,0,32,0,0,0,0,0,0,-0.2,0.0,6.1,-1.0,0.0,0.0,0.6,2.1,13.6,30.8,0.0,"
    "0.0,1.7,-1.0,0.6,0.0,1.3,1.5,3.7,14.5,0.1,-5.2,1.4,0.0,0.0,0.0,0.8,"
    "-0.6,-10.7,-15.6,0.4,-3.9,0.0,0.0,0.0,0.0,-0.8,-1.7,-10.1,-22.0,0.0,"
    "0.0,5.7,-1.0,0.0,0.0,-0.1,1.2,14.1,22.5,0.0,-2.5,0.8,0.0,0.0,0.0,1.0,"
    "0.4,-4.8,-2.7,0.1,-6.0,0.0,0.0,0.0,0.0,-0.8,-0.6,-24.0,-29.7,0.0,0.0,"
    "2.0,-6.4,0.0,0.0,0.2,2.9,-12.6,15.2,-0.1,0.0,8.4,-10.0,0.0,0.0,0.6,"
    "5.9,-3.9,52.7,-0.3,0.0,15.2,-8.4,0.0,0.0,0.9,5.1,17.7,70.7,-0.4,0.0,"
    "13.5,-4.0,0.0,0.0,0.9,3.9,25.5,62.9,-0.3,0.0,9.0,-0.9,0.0,0.0,0.9,"
    "2.9,23.3,49.4,8")


def test_reference_gbm_mojo_golden_prediction():
    """Scores the reference's mojo.zip to ITS OWN golden value
    (MojoReaderBackendFactoryTest.java:68: 71.085 +- 0.001)."""
    from h2o3_tpu.export.h2o_mojo import load_h2o_mojo
    m = load_h2o_mojo(os.path.join(_REF, "mojo.zip"))
    assert m.algo == "gbm" and m.nclasses == 1
    assert m.n_features == 262
    vals = [float(v) for v in _GOLDEN_ROW.split(",")]
    data = {f"C{i + 1}": [v] for i, v in enumerate(vals)}
    out = m.predict(data)
    assert out["predict"][0] == pytest.approx(71.085, abs=1e-3)


def test_reference_gbm_varimp_mojo_loads_and_scores():
    from h2o3_tpu.export.h2o_mojo import load_h2o_mojo
    path = os.path.join(_REF, "algos/gbm/gbm_variable_importance.zip")
    m = load_h2o_mojo(path)
    assert m.algo == "gbm"
    rng = np.random.default_rng(1)
    data = {}
    for j, name in enumerate(m.feature_names):
        dom = m.domains.get(j)
        if dom is not None:
            data[name] = [dom[int(i)] for i in
                          rng.integers(0, len(dom), 20)]
        else:
            data[name] = rng.normal(size=20).tolist()
    out = m.predict(data)
    if m.nclasses >= 2:
        probs = out["probabilities"]
        assert probs.shape == (20, m.nclasses)
        assert np.all(probs >= 0) and np.all(probs <= 1)
        assert np.allclose(probs.sum(axis=1), 1.0, atol=1e-6)
    else:
        assert np.all(np.isfinite(out["predict"]))


def test_reference_glm_mojo_scores_prostate():
    from h2o3_tpu.export.h2o_mojo import load_h2o_mojo
    m = load_h2o_mojo(os.path.join(_REF, "algos/pipeline/glm_model.zip"))
    assert m.algo == "glm"
    # the model regresses CAPSULE (gaussian) over prostate columns with a
    # categorical CLUSTER feature; exercise domain mapping + NA imputation
    data = {"CLUSTER": ["3", "0", None], "DPROS": [2.0, 1.0, None],
            "DCAPS": [1.0, 2.0, 1.0], "PSA": [15.0, 4.0, 20.0],
            "VOL": [10.0, 0.0, 30.0], "GLEASON": [7.0, 6.0, None]}
    out = m.predict(data)
    assert out["predict"].shape == (3,)
    assert np.all(np.isfinite(out["predict"]))
    # hand-check row 1 against the published beta vector
    beta = np.asarray(m.archive.info["beta"])
    eta = beta[0]                               # CLUSTER level "0"
    noff = m.cat_offsets[m.cats] - m.cats
    nums = [1.0, 2.0, 4.0, 0.0, 6.0]            # DPROS..GLEASON row 1
    for i, v in enumerate(nums):
        eta += beta[noff + m.cats + i] * v
    eta += beta[-1]
    assert out["predict"][1] == pytest.approx(eta, rel=1e-10)


def test_import_mojo_sniffs_reference_archives():
    import h2o3_tpu
    from h2o3_tpu.export.h2o_mojo import H2OMojoTreeModel
    m = h2o3_tpu.import_mojo(os.path.join(_REF, "mojo.zip"))
    assert isinstance(m, H2OMojoTreeModel)


def test_reference_kmeans_mojo_golden_clusters():
    """KMeansMojoModelTest.testPredict: the reference's own rows assign
    to clusters 0, 1, 2."""
    from h2o3_tpu.export.h2o_mojo import load_h2o_mojo
    m = load_h2o_mojo(os.path.join(_REF, "algos/kmeans"))
    assert m.algo == "kmeans"
    rows = [[2.0, 1.0, 22.0, 1.0, 0.0],
            [2.0, 1.0, 2.0, 3.0, 1.0],
            [2.0, 0.0, 27.0, 0.0, 2.0]]
    data = {}
    for j, name in enumerate(m.feature_names):
        dom = m.domains.get(j)
        col = [r[j] for r in rows]
        data[name] = [dom[int(v)] for v in col] if dom else col
    out = m.predict(data)
    np.testing.assert_array_equal(out["predict"], [0, 1, 2])
    assert out["distances"].shape == (3, 3)


def test_reference_svm_mojo_golden_labels():
    """SvmMojoModelTest: all-zeros row -> label index 1, all-ones -> 0."""
    from h2o3_tpu.export.h2o_mojo import load_h2o_mojo
    m = load_h2o_mojo(os.path.join(_REF, "algos/svm"))
    assert m.algo == "svm"
    rows = [[0.0] * 6, [1.0] * 6]
    data = {}
    for j, name in enumerate(m.feature_names):
        dom = m.domains.get(j)
        col = [r[j] for r in rows]
        data[name] = [dom[int(v)] for v in col] if dom else col
    out = m.predict(data)
    np.testing.assert_array_equal(out["label_index"], [1, 0])


def test_reference_isofor_mojo_scores():
    """IsolationForest MOJO: path-length normalization per
    IsolationForestMojoModel.unifyPreds (fixture has no numeric golden;
    assert the documented invariants on real artifacts)."""
    from h2o3_tpu.export.h2o_mojo import load_h2o_mojo
    m = load_h2o_mojo(os.path.join(_REF, "algos/isofor"))
    assert m.algo == "isolationforest"
    assert m.ntree_groups == 10
    rng = np.random.default_rng(1)
    data = {name: rng.normal(60, 30, 20).tolist()
            for name in m.feature_names}
    out = m.predict(data)
    assert out["predict"].shape == (20,)
    # score = (max-sum)/(max-min): bounded above by the max-path anchor
    assert (out["predict"] <= (70.0 - 0.0) / (70.0 - 40.0)).all()
    np.testing.assert_allclose(out["mean_length"],
                               out["path_length"] / 10.0)
    # deeper mean path  <=>  lower anomaly score (strictly monotonic)
    order = np.argsort(out["mean_length"])
    assert (np.diff(out["predict"][order]) <= 1e-12).all()


def test_import_mojo_accepts_extracted_directory():
    """The public import_mojo entry point routes extracted-directory
    archives to the reference-format reader."""
    from h2o3_tpu.export.mojo import import_mojo
    m = import_mojo(os.path.join(_REF, "algos/kmeans"))
    assert m.algo == "kmeans"


def test_reference_stackedensemble_regression_golden():
    """StackedEnsembleRegressionMojoTest: prostate row -> 66.29695."""
    from h2o3_tpu.export.h2o_mojo import load_h2o_mojo
    m = load_h2o_mojo(os.path.join(_REF, "algos/ensemble/regression.zip"))
    assert m.algo == "stackedensemble"
    row = {"CAPSULE": ["0"], "RACE": ["1"], "DPROS": ["2"],
           "DCAPS": ["1"], "PSA": [1.4], "VOL": [0], "GLEASON": [6]}
    out = m.predict(row)
    assert out["predict"][0] == pytest.approx(66.29695, abs=1e-5)


def test_reference_stackedensemble_binomial_golden():
    """StackedEnsembleBinomialMojoTest: label '0',
    probs [0.8222695, 0.1777305]."""
    from h2o3_tpu.export.h2o_mojo import load_h2o_mojo
    m = load_h2o_mojo(os.path.join(_REF, "algos/ensemble/binomial.zip"))
    row = {"AGE": [65], "RACE": ["1"], "DPROS": ["2"], "DCAPS": ["1"],
           "PSA": [1.4], "VOL": [0], "GLEASON": [6]}
    out = m.predict(row)
    np.testing.assert_allclose(out["probabilities"][0],
                               [0.8222695, 0.1777305], atol=1e-5)
    assert out["predict"][0] == "0"


def test_reference_stackedensemble_pruned_base_models():
    """StackedEnsembleBinomialWithoutUselessModelsMojoTest: 27 slots,
    only base_model6 present (rest pruned -> None + 0.0 columns);
    AGE=65 row labels '1'."""
    from h2o3_tpu.export.h2o_mojo import load_h2o_mojo
    m = load_h2o_mojo(os.path.join(
        _REF, "algos/ensemble/binomial_without_useless_models.zip"))
    assert len(m.base_models) == 27
    assert [i for i, b in enumerate(m.base_models)
            if b is not None] == [6]
    out = m.predict({"AGE": [65]})
    assert out["predict"][0] == "1"


def test_import_mojo_accepts_pathlib_directory(tmp_path):
    import pathlib
    from h2o3_tpu.export.mojo import import_mojo
    m = import_mojo(pathlib.Path(_REF) / "algos" / "kmeans")
    assert m.algo == "kmeans"


def test_reference_word2vec_mojo_golden():
    """Word2VecMojoModelTest: 'a' -> [0,1,0.2], 'b' -> [1,0,0.8],
    out-of-dictionary 'c' -> null (NaN row here)."""
    from h2o3_tpu.export.h2o_mojo import load_h2o_mojo
    m = load_h2o_mojo(os.path.join(_REF, "algos/word2vec"))
    assert m.algo == "word2vec" and m.vec_size == 3
    emb = m.transform(["a", "b", "c"])
    np.testing.assert_allclose(emb[0], [0.0, 1.0, 0.2], atol=1e-4)
    np.testing.assert_allclose(emb[1], [1.0, 0.0, 0.8], atol=1e-4)
    assert np.isnan(emb[2]).all()
