"""External-executor offload + MLflow flavor (VERDICT r03 missing #6/#8).

The executor test runs a REAL second-cluster workflow in-process: a local
frame ships to the REST server via /3/PostFile, trains there, and the
model comes back installed locally and scoring without the server.
"""

import os

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.frame.vec import T_CAT


@pytest.fixture(scope="module", autouse=True)
def _init():
    h2o3_tpu.init()


def _frame(n=250, seed=0):
    rng = np.random.default_rng(seed)
    return Frame.from_numpy({
        "x1": rng.normal(size=n).astype(np.float32),
        "x2": rng.normal(size=n).astype(np.float32),
        "g": rng.choice(["a", "b"], n).astype(object),
        "y": np.where(rng.random(n) < 0.5, "p", "q").astype(object),
    }, types={"g": T_CAT, "y": T_CAT})


def test_upload_frame_roundtrip():
    from h2o3_tpu.api.server import start_server
    from h2o3_tpu import client
    srv = start_server(port=0)
    try:
        conn = client.connect(srv.url)
        fr = _frame()
        rf = conn.upload_frame(fr, destination_frame="shipped")
        assert rf.key == "shipped"
        assert rf.nrows == 250
        assert set(rf.names) == {"x1", "x2", "g", "y"}
    finally:
        srv.stop()


def test_upload_frame_preserves_types():
    """Cat columns with numeric-string levels must NOT be re-inferred as
    numerics server-side (client forwards col_types to /3/Parse)."""
    from h2o3_tpu.api.server import start_server
    from h2o3_tpu import client
    srv = start_server(port=0)
    try:
        conn = client.connect(srv.url)
        rng = np.random.default_rng(3)
        fr = Frame.from_numpy({
            "zip": rng.choice(["0", "1", "2"], 120).astype(object),
            "x": rng.normal(size=120)}, types={"zip": T_CAT})
        rf = conn.upload_frame(fr, destination_frame="typed")
        assert rf.types()["zip"] == "cat"
        assert rf.types()["x"] == "num"
    finally:
        srv.stop()


def test_postfile_spool_is_deleted_after_parse(tmp_path):
    """/3/PostFile spool files are single-use — parsed then unlinked."""
    import glob
    import tempfile
    from h2o3_tpu.api.server import start_server
    from h2o3_tpu import client
    spool = os.path.join(tempfile.gettempdir(), "h2o3_uploads")
    srv = start_server(port=0)
    try:
        conn = client.connect(srv.url)
        before = set(glob.glob(os.path.join(spool, "*")))
        conn.upload_frame(b"x,y\n1,2\n3,4\n", destination_frame="sp")
        after = set(glob.glob(os.path.join(spool, "*")))
        assert after - before == set()      # consumed and removed
    finally:
        srv.stop()


def test_external_executor_trains_and_installs_locally():
    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.remote_exec import train_remote
    from h2o3_tpu.runtime import dkv
    srv = start_server(port=0, auth="static:exec:pw")
    try:
        fr = _frame()
        model = train_remote(srv.url, "gbm", fr, username="exec",
                             password="pw", response_column="y",
                             ntrees=4, max_depth=3, seed=1)
        # the model is LOCAL now: scores without the executor
        srv.stop()
        srv = None
        preds = model.predict(fr)
        assert preds.nrows == 250
        p = preds.vec("p").to_numpy()
        assert np.isfinite(p).all() and (p >= 0).all() and (p <= 1).all()
        # and it is registered in the local DKV under its key
        assert dkv.get(model.key) is not None
    finally:
        if srv is not None:
            srv.stop()


def test_mlflow_flavor_save_load(tmp_path):
    from h2o3_tpu import mlflow_flavor
    from h2o3_tpu.models import GBM
    fr = _frame()
    m = GBM(response_column="y", ntrees=4, max_depth=3, seed=2).train(fr)
    path = mlflow_flavor.save_model(m, str(tmp_path / "mlmodel_dir"))
    assert sorted(os.listdir(path)) == ["MLmodel", "model.h2o3tpu.zip",
                                       "requirements.txt"]
    import yaml
    desc = yaml.safe_load(open(os.path.join(path, "MLmodel")))
    assert "h2o3_tpu" in desc["flavors"]
    assert desc["flavors"]["python_function"]["loader_module"] == \
        "h2o3_tpu.mlflow_flavor"
    loaded = mlflow_flavor.load_model(path)
    cols = {n: fr.vec(n).decoded() if fr.vec(n).type == T_CAT
            else fr.vec(n).to_numpy().tolist() for n in fr.names
            if n != "y"}
    out = loaded.predict(cols)
    native = m.predict(fr).to_numpy()[:, 2]
    np.testing.assert_allclose(out["probabilities"][:, 1], native,
                               atol=1e-5)
