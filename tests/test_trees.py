"""GBM/DRF tests — mirrors pyunit_gbm*/pyunit_drf* coverage plus golden
comparisons against sklearn's boosted/forest baselines on synthetic data."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models.tree.gbm import GBM
from h2o3_tpu.models.tree.drf import DRF


def _friedman(rng, n=3000):
    """Friedman #1 regression surface (nonlinear + interactions)."""
    X = rng.random((n, 5))
    y = (10 * np.sin(np.pi * X[:, 0] * X[:, 1]) + 20 * (X[:, 2] - 0.5) ** 2
         + 10 * X[:, 3] + 5 * X[:, 4] + 0.5 * rng.normal(size=n))
    cols = {f"x{j}": X[:, j] for j in range(5)}
    cols["y"] = y
    return Frame.from_numpy(cols)


def _binary(rng, n=3000):
    X = rng.normal(size=(n, 4))
    logits = 2 * X[:, 0] * X[:, 1] + X[:, 2] ** 2 - 1
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(int)
    cols = {f"x{j}": X[:, j] for j in range(4)}
    cols["y"] = np.array(["n", "y"], dtype=object)[y]
    return Frame.from_numpy(cols), y


def test_gbm_regression(cl, rng):
    fr = _friedman(rng)
    m = GBM(response_column="y", ntrees=40, max_depth=4, learn_rate=0.2,
            seed=1).train(fr)
    assert m.training_metrics.r2 > 0.9, m.training_metrics.describe()
    # prediction roundtrip
    preds = m.predict(fr)
    assert preds.nrows == fr.nrows


def test_gbm_binomial(cl, rng):
    fr, y = _binary(rng)
    m = GBM(response_column="y", ntrees=60, max_depth=5, learn_rate=0.2,
            seed=2).train(fr)
    assert m.training_metrics.auc > 0.9, m.training_metrics.describe()


def test_gbm_hier_split_search_quality(cl, rng):
    """The hierarchical (benchmark-scale) split search trains through the
    scan driver and lands within noise of the exact path's fit."""
    fr = _friedman(rng)
    kw = dict(response_column="y", ntrees=20, max_depth=4, learn_rate=0.2,
              nbins=64, reg_lambda=1.0, seed=1)
    m_exact = GBM(split_search="exact", **kw).train(fr)
    m_hier = GBM(split_search="hier", **kw).train(fr)
    r2_e = m_exact.training_metrics.r2
    r2_h = m_hier.training_metrics.r2
    assert r2_h > 0.85, (r2_e, r2_h)
    assert abs(r2_e - r2_h) < 0.05, (r2_e, r2_h)


def test_gbm_vs_sklearn(cl, rng):
    from sklearn.ensemble import HistGradientBoostingRegressor
    from sklearn.metrics import r2_score
    fr = _friedman(rng, n=4000)
    Xh = np.stack([fr.vec(f"x{j}").to_numpy() for j in range(5)], axis=1)
    yh = fr.vec("y").to_numpy()
    m = GBM(response_column="y", ntrees=60, max_depth=5, learn_rate=0.1,
            min_rows=5, seed=3).train(fr)
    ours = m.predict(fr).vec("predict").to_numpy()
    sk = HistGradientBoostingRegressor(
        max_iter=60, max_depth=5, learning_rate=0.1).fit(Xh, yh)
    sk_r2 = r2_score(yh, sk.predict(Xh))
    our_r2 = r2_score(yh, ours)
    assert our_r2 > sk_r2 - 0.05, (our_r2, sk_r2)


def test_gbm_multinomial(cl, rng):
    n = 3000
    centers = np.array([[2, 0], [-2, 1], [0, -2]])
    labels = rng.integers(0, 3, n)
    X = centers[labels] + rng.normal(size=(n, 2))
    fr = Frame.from_numpy({
        "x0": X[:, 0], "x1": X[:, 1],
        "y": np.array(["a", "b", "c"], dtype=object)[labels]})
    m = GBM(response_column="y", ntrees=20, max_depth=3, seed=4).train(fr)
    assert m.training_metrics.accuracy > 0.85
    preds = m.predict(fr)
    probs = np.stack([preds.vec(c).to_numpy() for c in "abc"], axis=1)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-4)


def test_gbm_categorical_and_na(cl, rng):
    n = 2000
    g = np.array(["a", "b", "c"], dtype=object)[rng.integers(0, 3, n)]
    x = rng.normal(size=n)
    x[rng.random(n) < 0.1] = np.nan          # missing values
    eff = {"a": 0.0, "b": 2.0, "c": -2.0}
    y = np.where(np.isnan(x), 1.0, x) + np.array([eff[s] for s in g])
    fr = Frame.from_numpy({"g": g, "x": x, "y": y})
    m = GBM(response_column="y", ntrees=30, max_depth=4, learn_rate=0.3,
            seed=5).train(fr)
    assert m.training_metrics.r2 > 0.85, m.training_metrics.describe()


def test_gbm_early_stopping(cl, rng):
    fr = _friedman(rng, n=1500)
    train, valid = fr.split_frame([0.8], seed=1)
    m = GBM(response_column="y", ntrees=200, max_depth=3, learn_rate=0.5,
            stopping_rounds=2, stopping_tolerance=1e-3,
            score_tree_interval=5, seed=6).train(train, valid=valid)
    assert m.output["ntrees_trained"] < 200


def test_gbm_poisson(cl, rng):
    n = 2500
    x = rng.normal(size=n)
    y = rng.poisson(np.exp(0.5 * x + 1.0)).astype(float)
    fr = Frame.from_numpy({"x": x, "y": y})
    m = GBM(response_column="y", ntrees=30, distribution="poisson",
            max_depth=3, seed=7).train(fr)
    preds = m.predict(fr).vec("predict").to_numpy()
    assert (preds > 0).all()                     # log link respected
    assert abs(preds.mean() - y.mean()) / y.mean() < 0.1


def test_drf_classification(cl, rng):
    fr, y = _binary(rng)
    m = DRF(response_column="y", ntrees=30, max_depth=10, seed=8).train(fr)
    assert m.training_metrics.auc > 0.9, m.training_metrics.describe()


def test_drf_regression(cl, rng):
    fr = _friedman(rng)
    m = DRF(response_column="y", ntrees=30, max_depth=10, seed=9).train(fr)
    assert m.training_metrics.r2 > 0.85, m.training_metrics.describe()


def test_drf_multinomial(cl, rng):
    n = 2000
    centers = np.array([[2, 0], [-2, 1], [0, -2]])
    labels = rng.integers(0, 3, n)
    X = centers[labels] + rng.normal(size=(n, 2))
    fr = Frame.from_numpy({
        "x0": X[:, 0], "x1": X[:, 1],
        "y": np.array(["a", "b", "c"], dtype=object)[labels]})
    m = DRF(response_column="y", ntrees=20, max_depth=8, seed=10).train(fr)
    assert m.training_metrics.accuracy > 0.85


def test_tree_save_load_predict(cl, rng, tmp_path):
    from h2o3_tpu.models import Model
    fr, y = _binary(rng, n=1000)
    m = GBM(response_column="y", ntrees=10, max_depth=3, seed=11).train(fr)
    p1 = m.predict(fr).vec("y").to_numpy()
    path = m.save(str(tmp_path / "gbm.bin"))
    m2 = Model.load(path)
    p2 = m2.predict(fr).vec("y").to_numpy()
    np.testing.assert_allclose(p1, p2, rtol=1e-5)


def test_fit_bins_inf_stays_in_own_feature(cl, rng):
    """+inf must encode to the FEATURE's top bin, not the padded edge
    width: the encode program pads every edge row to the global max with
    +inf, and searchsorted(side='right') counts the padding as <= inf —
    an unclipped code lands inside a NEIGHBORING feature's packed varbin
    segment (round-4 review finding)."""
    import h2o3_tpu
    from h2o3_tpu.models.tree.binning import fit_bins
    n = 2000
    a = rng.integers(0, 4, n).astype(np.float32)
    a[5] = np.inf
    a[7] = -np.inf
    b = rng.normal(size=n).astype(np.float32)
    fr = h2o3_tpu.Frame.from_numpy({"a": a, "b": b})
    bn = fit_bins(fr, ["a", "b"], nbins=64)
    codes = np.asarray(bn.codes)
    assert len(bn.edges[0]) < len(bn.edges[1])      # uneven edge widths
    assert codes[0, 5] == len(bn.edges[0])          # inf -> own top bin
    assert codes[0, 7] == 0                         # -inf -> bottom bin
    assert codes[0, :n].max() <= len(bn.edges[0])


def test_depth_cap_multinomial_and_default_depth_drf(cl, rng):
    """Dense-level depth cap: a depth request above the cap must produce
    a working (capped) model on every scan driver — the multinomial
    stacking loop used the REQUESTED depth and crashed at trace time
    (round-4 review finding), and default-depth DRF (max_depth=20) must
    train (it Mosaic-OOM'd on chip before the cap existed)."""
    import h2o3_tpu
    from h2o3_tpu.models import GBM, DRF
    from h2o3_tpu.models.tree.shared import effective_max_depth
    n = 600
    x = rng.normal(size=n)
    y3 = np.array(["abc"[i % 3] for i in range(n)], dtype=object)
    fr = h2o3_tpu.Frame.from_numpy({"x": x, "x2": rng.normal(size=n),
                                    "y": y3})
    eff = effective_max_depth(18, 16, 2, fr.padded_rows)
    assert eff < 18
    m = GBM(ntrees=2, max_depth=18, nbins=16, response_column="y",
            seed=1).train(fr)                      # multinomial scan path
    assert len(m.output["stacked"][0].levels if isinstance(
        m.output["stacked"], list) else m.output["stacked"].levels) == eff
    m2 = DRF(ntrees=2, nbins=16, response_column="y", seed=1).train(fr)
    assert m2.predict(fr).nrows == n


def test_histogram_types(cl, rng):
    import h2o3_tpu
    from h2o3_tpu.models import GBM
    from h2o3_tpu.models.tree.binning import fit_bins
    import pytest
    n = 500
    x = rng.normal(size=n) ** 3          # skewed: quantile != uniform
    y = np.where(x > 0, "Y", "N").astype(object)
    fr = h2o3_tpu.Frame.from_numpy({"x": x, "y": y})
    edges = {}
    for ht in ("QuantilesGlobal", "UniformAdaptive", "Random"):
        b = fit_bins(fr, ["x"], nbins=16, seed=1, histogram_type=ht)
        edges[ht] = b.edges[0]
        m = GBM(response_column="y", ntrees=10, max_depth=3,
                learn_rate=0.3, histogram_type=ht, seed=1).train(fr)
        p = m.predict(fr).vec("Y").to_numpy()
        assert np.isfinite(p).all()
        # quantile edges resolve the skewed sign boundary well;
        # uniform/random are legitimately coarser near 0 on x**3 data
        floor = 0.95 if ht == "QuantilesGlobal" else 0.75
        assert np.mean((p > 0.5) == (x > 0)) > floor
    assert not np.array_equal(edges["QuantilesGlobal"],
                              edges["UniformAdaptive"])
    assert not np.array_equal(edges["UniformAdaptive"], edges["Random"])
    # uniform edges are equally spaced
    du = np.diff(edges["UniformAdaptive"])
    np.testing.assert_allclose(du, du[0], rtol=1e-4)
    with pytest.raises(ValueError, match="histogram_type"):
        fit_bins(fr, ["x"], histogram_type="nope")


def test_balance_classes(cl, rng):
    import h2o3_tpu
    from h2o3_tpu.models import GBM
    n = 600
    x = rng.normal(size=n)
    # 95/5 imbalance with a learnable boundary
    rare = rng.random(n) < 0.05
    y = np.where(rare, "POS", "NEG").astype(object)
    x = np.where(rare, x + 2.0, x)
    fr = h2o3_tpu.Frame.from_numpy({"x": x, "y": y})
    plain = GBM(response_column="y", ntrees=10, max_depth=3,
                seed=1).train(fr)
    bal = GBM(response_column="y", ntrees=10, max_depth=3,
              balance_classes=True, seed=1).train(fr)
    p0 = plain.predict(fr).vec("POS").to_numpy()
    p1 = bal.predict(fr).vec("POS").to_numpy()
    # balancing must push minority-class probabilities up overall
    assert p1[rare].mean() > p0[rare].mean()
    # recall of the rare class improves at the 0.5 threshold
    assert (p1[rare] > 0.5).mean() >= (p0[rare] > 0.5).mean()
    assert (p1[rare] > 0.5).mean() > 0.5
    # validation frame without the synthetic weights column still scores
    m = bal.model_performance(fr)
    assert m is not None
    # scoring DataInfo keeps the user's weights (None here), and the
    # builder params are restored so retraining on the raw frame works
    assert bal.datainfo.weights_column is None
    from h2o3_tpu.models import GBM as _G
    b2 = _G(response_column="y", ntrees=2, max_depth=2,
            balance_classes=True, seed=1)
    b2.train(fr)
    b2.train(fr)                       # second run must not KeyError
    assert b2.params.weights_column is None
    # in-training validation scoring works under balancing
    tr, va = fr.split_frame([0.7], seed=3)
    GBM(response_column="y", ntrees=3, max_depth=2, balance_classes=True,
        seed=1, score_tree_interval=1).train(tr, va)
    # explicit factors are honored and validated
    import pytest
    with pytest.raises(ValueError, match="class_sampling_factors"):
        GBM(response_column="y", balance_classes=True,
            class_sampling_factors=[1.0], ntrees=2).train(fr)


def test_monotone_constraints(cl, rng):
    import h2o3_tpu
    import pytest
    from h2o3_tpu.models import GBM, XGBoost
    n = 800
    x = rng.uniform(-3, 3, n)
    z = rng.normal(size=n)
    # noisy, non-monotone-looking sample of a monotone-increasing truth
    y = 2.0 * x + z * 2.0 + 1.5 * np.sin(2.5 * x)
    fr = h2o3_tpu.Frame.from_numpy({"x": x, "z": z, "y": y})
    grid = np.linspace(-3, 3, 60)
    probe = h2o3_tpu.Frame.from_numpy(
        {"x": grid, "z": np.zeros_like(grid)})
    for cls in (GBM, XGBoost):
        m = cls(response_column="y", ntrees=40, max_depth=4,
                learn_rate=0.2, monotone_constraints={"x": 1},
                seed=1).train(fr)
        p = m.predict(probe).vec("predict").to_numpy()
        assert (np.diff(p) >= -1e-5).all(), \
            f"{cls.__name__} predictions not monotone in x"
        # the unconstrained model on this noisy data is NOT monotone
        # (otherwise the assertion above is vacuous)
        m0 = cls(response_column="y", ntrees=40, max_depth=4,
                 learn_rate=0.2, seed=1).train(fr)
        p0 = m0.predict(probe).vec("predict").to_numpy()
        assert (np.diff(p0) < -1e-5).any()
        # decreasing constraint mirrors
        md = cls(response_column="y", ntrees=10, max_depth=3,
                 monotone_constraints={"x": -1}, seed=1).train(fr)
        pd_ = md.predict(probe).vec("predict").to_numpy()
        assert (np.diff(pd_) <= 1e-5).all()
    with pytest.raises(ValueError, match="categorical|unknown"):
        fr2 = h2o3_tpu.Frame.from_numpy({
            "g": np.array(["a", "b"] * 50, object),
            "y": rng.normal(size=100)})
        GBM(response_column="y", ntrees=2,
            monotone_constraints={"g": 1}).train(fr2)


def test_monotone_rejected_outside_gbm(cl, rng):
    import h2o3_tpu
    import pytest
    from h2o3_tpu.models import DRF, GBM
    fr = h2o3_tpu.Frame.from_numpy({"x": rng.normal(size=60),
                                    "y": rng.normal(size=60)})
    with pytest.raises(ValueError, match="only enforced"):
        DRF(response_column="y", ntrees=2,
            monotone_constraints={"x": 1}).train(fr)
    # 0 means unconstrained (reference semantics) — trains fine
    GBM(response_column="y", ntrees=2,
        monotone_constraints={"x": 0}).train(fr)
