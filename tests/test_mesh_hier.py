"""Hierarchical mesh data plane: flat-vs-hier parity + mesh lifecycle.

The data plane reduces over an explicit ``("hosts", "chips")`` mesh
(runtime/cluster.py): histogram partials psum around each host's ICI
ring first, then once across hosts over DCN (runtime/mapreduce.py).
These tests pin

  (a) BIT-parity of the staged schedule against the one-collective flat
      oracle for all four histogram builders (uniform, varbin, smaller-
      sibling subtraction, node-sparse slots) and the fused split search
      built on top — integer-valued stats reduce bitwise-identically
      under any association, so equality is exact, not allclose,
  (b) the ``reduce_mode="check"`` dispatcher (runs both whole programs,
      raises ReduceParityError on divergence) at the builder and the
      map_reduce layer,
  (c) cluster re-init: ``init(hosts=...)`` after a default boot detects
      the geometry change, rebuilds the mesh, flushes compiled caches
      and records a ``cluster_reinit`` event — the silent-stale-mesh
      regression,
  (d) the same parity on 16- and 32-virtual-device meshes in fresh
      subprocesses (the conftest mesh is fixed at 8), and
  (e) the host-kill chaos row: a training process on the 2-host mesh is
      hard-killed (exit 137, all procs of a virtual host die at once),
      a fresh process resume()s on the same mesh and predictions match
      the uninterrupted run — wired into tools/chaos.sh.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax.numpy as jnp

import h2o3_tpu
from h2o3_tpu.models.tree.hist import (fused_best_splits, make_hist_fn,
                                       make_sparse_level_fn,
                                       make_subtract_level_fn,
                                       make_varbin_hist_fn, offset_codes)
from h2o3_tpu.runtime.mapreduce import (ReduceParityError,
                                        assert_reduce_parity,
                                        force_reduce_mode, map_reduce)


def _int_stats(rng, N, L):
    """Integer-valued f32 stats: psum order cannot change a single bit."""
    leaf = jnp.asarray(rng.integers(0, L, N), jnp.int32)
    g = jnp.asarray(rng.integers(-8, 8, N), jnp.float32)
    h = jnp.asarray(rng.integers(0, 4, N), jnp.float32)
    w = jnp.asarray(rng.integers(0, 2, N), jnp.float32)
    return leaf, g, h, w


def _assert_bitwise(a, b, what):
    a, b = np.asarray(a), np.asarray(b)
    assert a.shape == b.shape and a.dtype == b.dtype, \
        f"{what}: shape/dtype mismatch {a.shape}/{a.dtype} vs {b.shape}/{b.dtype}"
    assert a.tobytes() == b.tobytes(), (
        f"{what}: flat and hier reductions are not bit-identical "
        f"(maxdiff {np.max(np.abs(a - b))})")


# ------------------------------------------------------- builder bit-parity

def test_uniform_hist_flat_vs_hier_bitwise(cl, rng):
    N, F, B, L = 1024, 4, 17, 4
    codes = jnp.asarray(rng.integers(0, B, (F, N)), jnp.int32)
    leaf, g, h, w = _int_stats(rng, N, L)
    Hf = make_hist_fn(L, F, B, N, force_impl="einsum",
                      reduce_mode="flat")(codes, leaf, g, h, w)
    Hh = make_hist_fn(L, F, B, N, force_impl="einsum",
                      reduce_mode="hier")(codes, leaf, g, h, w)
    _assert_bitwise(Hf, Hh, "uniform hist")


def test_varbin_hist_flat_vs_hier_bitwise(cl, rng):
    N, F, L = 1024, 4, 4
    bin_counts = (7, 16, 3, 11)
    nbins = max(bin_counts)
    B = nbins + 1
    codes = jnp.asarray(np.stack([
        np.where(rng.random(N) < 0.1, nbins, rng.integers(0, bc, N))
        for bc in bin_counts]), jnp.int32)
    gcodes = offset_codes(codes, bin_counts, nbins)
    leaf, g, h, w = _int_stats(rng, N, L)
    args = (L, F, bin_counts, B, N)
    kw = dict(force_impl="pallas_interpret", precision="f32")
    Hf = make_varbin_hist_fn(*args, reduce_mode="flat", **kw)(
        gcodes, leaf, g, h, w)
    Hh = make_varbin_hist_fn(*args, reduce_mode="hier", **kw)(
        gcodes, leaf, g, h, w)
    _assert_bitwise(Hf, Hh, "varbin hist")


def _chain_leaves(rng, N, depth, p_right=0.3):
    leaves = [np.zeros(N, np.int64)]
    for _ in range(1, depth):
        bit = (rng.random(N) < p_right).astype(np.int64)
        leaves.append(2 * leaves[-1] + bit)
    return leaves


def test_subtract_chain_flat_vs_hier_bitwise(cl, rng):
    """Two independent mode-chains (the carry is mode-specific state)
    must agree bitwise on the histogram AND the per-shard carry at every
    level — the carry is pre-psum, so it never crosses a collective."""
    N, F, B, depth = 1024, 4, 17, 3
    codes = jnp.asarray(rng.integers(0, B, (F, N)), jnp.int32)
    leaf0, g, h, w = _int_stats(rng, N, 1)
    carry_f = carry_h = None
    for d, leaf_np in enumerate(_chain_leaves(rng, N, depth)):
        leaf = jnp.asarray(leaf_np, jnp.int32)
        extra_f = () if d == 0 else (carry_f,)
        extra_h = () if d == 0 else (carry_h,)
        Hf, carry_f = make_subtract_level_fn(d, F, B, N, reduce_mode="flat")(
            codes, leaf, g, h, w, *extra_f)
        Hh, carry_h = make_subtract_level_fn(d, F, B, N, reduce_mode="hier")(
            codes, leaf, g, h, w, *extra_h)
        _assert_bitwise(Hf, Hh, f"subtract hist d={d}")
        _assert_bitwise(carry_f, carry_h, f"subtract carry d={d}")


def test_sparse_level_flat_vs_hier_bitwise(cl, rng):
    """Node-sparse slots at the identity slot map, both schedules."""
    N, F, B, depth = 1024, 4, 17, 3
    codes = jnp.asarray(rng.integers(0, B, (F, N)), jnp.int32)
    _, g, h, w = _int_stats(rng, N, 1)
    leaves = _chain_leaves(rng, N, depth)
    _, carry_f = make_subtract_level_fn(0, F, B, N, reduce_mode="flat")(
        codes, jnp.zeros(N, jnp.int32), g, h, w)
    carry_h = carry_f
    for d in range(1, depth):
        leaf = jnp.asarray(leaves[d], jnp.int32)
        A_prev, A = 2 ** (d - 1), 2 ** d
        ps = jnp.arange(A, dtype=jnp.int32) // 2
        Hf, carry_f = make_sparse_level_fn(
            A_prev, A, F, B, N, reduce_mode="flat")(
            codes, leaf, g, h, w, carry_f, ps)
        Hh, carry_h = make_sparse_level_fn(
            A_prev, A, F, B, N, reduce_mode="hier")(
            codes, leaf, g, h, w, carry_h, ps)
        _assert_bitwise(Hf, Hh, f"sparse hist d={d}")
        _assert_bitwise(carry_f, carry_h, f"sparse carry d={d}")


def test_fused_splits_flat_vs_hier_identical(cl, rng):
    """The fused split search on top of both schedules picks the same
    (feature, bin) winners with the same gains — the whole-level
    decision, not just the histogram, is schedule-invariant."""
    N, F, B, L = 1024, 4, 17, 4
    nbins = B - 1
    codes = jnp.asarray(rng.integers(0, B, (F, N)), jnp.int32)
    leaf, g, h, w = _int_stats(rng, N, L)
    outs = {}
    for mode in ("flat", "hier"):
        H = make_hist_fn(L, F, B, N, force_impl="einsum",
                         reduce_mode=mode)(codes, leaf, g, h, w)
        outs[mode] = fused_best_splits(H, nbins, 1.0, 1.0, 0.0)
    for i, (a, b) in enumerate(zip(outs["flat"], outs["hier"])):
        _assert_bitwise(a, b, f"fused splits output {i}")


# ------------------------------------------------------------- check mode

def test_check_mode_builder_smoke(cl, rng):
    """reduce_mode="check" runs both schedules in-builder and returns the
    hier result; any divergence would raise ReduceParityError."""
    N, F, B, L = 512, 3, 9, 2
    codes = jnp.asarray(rng.integers(0, B, (F, N)), jnp.int32)
    leaf, g, h, w = _int_stats(rng, N, L)
    Hc = make_hist_fn(L, F, B, N, force_impl="einsum",
                      reduce_mode="check")(codes, leaf, g, h, w)
    Hh = make_hist_fn(L, F, B, N, force_impl="einsum",
                      reduce_mode="hier")(codes, leaf, g, h, w)
    _assert_bitwise(Hc, Hh, "check-mode hist")


def test_check_mode_via_forced_env(cl, rng):
    """force_reduce_mode("check") flows through the default dispatch —
    the path H2O3_TPU_REDUCE_MODE=check takes in a real deployment."""
    N, F, B, L = 512, 3, 9, 2
    codes = jnp.asarray(rng.integers(0, B, (F, N)), jnp.int32)
    leaf, g, h, w = _int_stats(rng, N, L)
    with force_reduce_mode("check"):
        H = make_hist_fn(L, F, B, N, force_impl="einsum")(
            codes, leaf, g, h, w)
    assert np.asarray(H).shape == (3, L, F, B)


def test_map_reduce_check_mode(cl, rng):
    x = jnp.asarray(rng.integers(-50, 50, 512), jnp.float32)
    total = map_reduce(lambda d: jnp.sum(d), x, reduce_mode="check")
    assert float(total) == float(np.sum(np.asarray(x)))


def test_parity_assert_raises_on_divergence():
    with pytest.raises(ReduceParityError, match="divergence"):
        assert_reduce_parity(np.zeros(4, np.float32),
                             np.ones(4, np.float32), what="unit")
    with pytest.raises(ReduceParityError, match="structures"):
        assert_reduce_parity({"a": np.zeros(2)}, [np.zeros(2)], what="unit")


# ------------------------------------------------------- cluster re-init

def test_reinit_rebuilds_mesh_and_flushes_caches(cl, rng):
    """init(hosts=...) after the default boot must rebuild the mesh (not
    silently return the stale one), record a cluster_reinit event, and
    leave the data plane correct on the new geometry."""
    from h2o3_tpu.runtime import observability as obs
    from h2o3_tpu.runtime.cluster import cluster
    orig_hosts = cl.n_hosts
    new_hosts = 4 if orig_hosts != 4 else 2
    try:
        c2 = h2o3_tpu.init(hosts=new_hosts)
        assert c2.n_hosts == new_hosts
        assert dict(c2.mesh.shape)["hosts"] == new_hosts
        assert c2.n_row_shards == cl.n_row_shards     # same device count
        # a later default init() returns the REBUILT cluster, not a stale one
        assert h2o3_tpu.init() is c2
        ev = [e for e in obs.timeline_events(1000)
              if e.get("kind") == "cluster_reinit"]
        assert ev, "cluster_reinit event not recorded"
        # parity still holds on the rebuilt mesh (caches were flushed, so
        # these recompile against the new geometry)
        N, F, B, L = 512, 3, 9, 2
        codes = jnp.asarray(rng.integers(0, B, (F, N)), jnp.int32)
        leaf, g, h, w = _int_stats(rng, N, L)
        Hf = make_hist_fn(L, F, B, N, force_impl="einsum",
                          reduce_mode="flat")(codes, leaf, g, h, w)
        Hh = make_hist_fn(L, F, B, N, force_impl="einsum",
                          reduce_mode="hier")(codes, leaf, g, h, w)
        _assert_bitwise(Hf, Hh, "post-reinit hist")
    finally:
        restored = h2o3_tpu.init(hosts=orig_hosts)
        assert restored.n_hosts == orig_hosts


def test_reinit_same_geometry_is_cached(cl):
    """Re-stating the live geometry must NOT rebuild (frames keep their
    shardings; compiled programs stay hot)."""
    assert h2o3_tpu.init(hosts=cl.n_hosts) is h2o3_tpu.init()


def test_reinit_drops_autotune_decisions(cl):
    """Regression: _invalidate_compiled_caches must also flush the
    autotuner's per-signature mode decisions — they bind the mesh
    geometry exactly like compiled programs do, and a rebuilt mesh must
    never serve a choice tuned for the dead one."""
    import os
    from h2o3_tpu.runtime import autotune, config
    saved = os.environ.get("H2O3_TPU_AUTOTUNE")
    orig_hosts = cl.n_hosts
    new_hosts = 4 if orig_hosts != 4 else 2
    try:
        os.environ["H2O3_TPU_AUTOTUNE"] = "on"
        config.reload()
        autotune.reset()
        import types
        p = types.SimpleNamespace(hist_mode="auto", split_mode="auto",
                                  hist_layout="auto",
                                  sparse_depth_threshold=8,
                                  max_depth=6, nbins=32)
        k = autotune.resolve_tree_knobs(p, kind="gbm", F=4, N=4096)
        assert k.sig is not None
        assert autotune.decision_table()["entries"] == 1
        h2o3_tpu.init(hosts=new_hosts)
        assert autotune.decision_table()["entries"] == 0, \
            "mesh rebuild left stale autotune decisions behind"
        # fresh decisions on the new geometry carry its mesh signature
        k2 = autotune.resolve_tree_knobs(p, kind="gbm", F=4, N=4096)
        assert f"mesh{new_hosts}x" in k2.sig
    finally:
        h2o3_tpu.init(hosts=orig_hosts)
        if saved is None:
            os.environ.pop("H2O3_TPU_AUTOTUNE", None)
        else:
            os.environ["H2O3_TPU_AUTOTUNE"] = saved
        config.reload()
        autotune.reset()


# --------------------------------------- 16/32-device subprocess parity

_PARITY_SCRIPT = textwrap.dedent("""
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import h2o3_tpu
    cl = h2o3_tpu.init()
    assert cl.n_row_shards == {n_dev}, cl.mesh.shape
    assert cl.n_hosts == {hosts}, cl.mesh.shape
    from h2o3_tpu.models.tree.hist import (fused_best_splits, make_hist_fn,
                                           make_subtract_level_fn)
    rng = np.random.default_rng(7)
    N, F, B, L = 2048, 4, 17, 4
    nbins = B - 1
    codes = jnp.asarray(rng.integers(0, B, (F, N)), jnp.int32)
    leaf = jnp.asarray(rng.integers(0, L, N), jnp.int32)
    g = jnp.asarray(rng.integers(-8, 8, N), jnp.float32)
    h = jnp.asarray(rng.integers(0, 4, N), jnp.float32)
    w = jnp.asarray(rng.integers(0, 2, N), jnp.float32)
    res = {{}}
    for mode in ("flat", "hier"):
        H = make_hist_fn(L, F, B, N, force_impl="einsum",
                         reduce_mode=mode)(codes, leaf, g, h, w)
        Hs, carry = make_subtract_level_fn(0, F, B, N, reduce_mode=mode)(
            codes, jnp.zeros(N, jnp.int32), g, h, w)
        res[mode] = (np.asarray(H), np.asarray(Hs), np.asarray(carry),
                     [np.asarray(o)
                      for o in fused_best_splits(H, nbins, 1.0, 1.0, 0.0)])
    for a, b in zip(res["flat"][:3], res["hier"][:3]):
        assert a.tobytes() == b.tobytes(), "hist/carry parity"
    for a, b in zip(res["flat"][3], res["hier"][3]):
        assert a.tobytes() == b.tobytes(), "fused splits parity"
    print("PARITY_OK", {n_dev}, {hosts})
""")


@pytest.mark.parametrize("n_dev,hosts", [(16, 2), (32, 4)])
def test_parity_on_larger_virtual_mesh(n_dev, hosts):
    """Flat-vs-hier bit-parity on 16/32 virtual devices.  Fresh
    subprocess: the in-process XLA device count is fixed at boot."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={n_dev}",
        "H2O3_TPU_HOSTS": str(hosts),
    })
    proc = subprocess.run(
        [sys.executable, "-c",
         _PARITY_SCRIPT.format(n_dev=n_dev, hosts=hosts)],
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout:\n{proc.stdout[-3000:]}\n"
        f"stderr:\n{proc.stderr[-3000:]}")
    assert f"PARITY_OK {n_dev} {hosts}" in proc.stdout


# ------------------------------------------------- host-kill chaos row

NTREES = 12
KILL_AT_CHUNK = 3


def _mesh_env(tmp_path, extra=None):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "H2O3_TPU_HOSTS": "2",
        "H2O3_TPU_REDUCE_MODE": "hier",
        "H2O3_TPU_RECOVERY_DIR": str(tmp_path),
        "H2O3_TPU_SNAPSHOT_INTERVAL": "0",
        "H2O3_TPU_SNAPSHOT_ASYNC": "0",
        "H2O3_TPU_LOG_STDERR": "1",
    })
    env.update(extra or {})
    return env


def _write_csv(path, seed=11, n=600):
    rng = np.random.default_rng(seed)
    X = rng.random((n, 4))
    y = (10 * np.sin(np.pi * X[:, 0]) + 5 * X[:, 1] ** 2
         + 3 * X[:, 2] + 0.1 * rng.normal(size=n))
    rows = np.column_stack([X, y])
    path.write_text("x0,x1,x2,x3,y\n" + "\n".join(
        ",".join(f"{v:.9g}" for v in r) for r in rows))
    return str(path)


_TRAIN = textwrap.dedent("""
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    cl = h2o3_tpu.init()
    assert cl.n_hosts == 2, cl.mesh.shape
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.models import GBM
    fr = import_file(sys.argv[1], destination_frame="mesh_chaos_fr")
    m = GBM(response_column="y", ntrees={nt}, max_depth=3, learn_rate=0.2,
            seed=7, score_tree_interval=2).train(fr)
    np.save(sys.argv[2], m.predict(fr).to_numpy()[:, 0])
    print("TRAINED", m.output["ntrees_trained"])
""").format(nt=NTREES)

_RESUME = textwrap.dedent("""
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import h2o3_tpu
    cl = h2o3_tpu.init()
    assert cl.n_hosts == 2, cl.mesh.shape
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.runtime import dkv, recovery
    fr = import_file(sys.argv[1], destination_frame="mesh_chaos_fr")
    done = recovery.resume()
    assert len(done) == 1, f"expected 1 resumed model, got {done}"
    m = dkv.get(done[0])
    print("RESUMED", m.output["ntrees_trained"])
    np.save(sys.argv[2], m.predict(fr).to_numpy()[:, 0])
""")


def _run(script, env, *args, expect_rc=0, timeout=420):
    proc = subprocess.run(
        [sys.executable, "-c", script, *args],
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
        capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == expect_rc, (
        f"rc={proc.returncode} (wanted {expect_rc})\n"
        f"stdout:\n{proc.stdout[-3000:]}\nstderr:\n{proc.stderr[-3000:]}")
    return proc


def test_mesh_host_kill_resume_verify(cl, tmp_path):
    """Host-kill chaos on the hierarchical mesh: the training process
    owns both virtual hosts, so a hard kill (exit 137) takes a whole
    mesh host down mid-collective.  A fresh process rebuilds the SAME
    2-host mesh, resume()s from the snapshot, and predictions match the
    uninterrupted run through the staged ICI+DCN reduce."""
    csv = _write_csv(tmp_path / "mesh_chaos.csv")
    base_dir = tmp_path / "base_recovery"
    base_dir.mkdir()
    base_npy = str(tmp_path / "base.npy")
    out = _run(_TRAIN, _mesh_env(base_dir), csv, base_npy)
    assert f"TRAINED {NTREES}" in out.stdout
    assert not list(base_dir.glob("job_*.json"))

    kill_dir = tmp_path / "kill_recovery"
    kill_dir.mkdir()
    kill_npy = str(tmp_path / "kill.npy")
    _run(_TRAIN,
         _mesh_env(kill_dir, {"H2O3_TPU_FAULT_INJECT":
                              f"tree_chunk:0:{KILL_AT_CHUNK}"}),
         csv, kill_npy, expect_rc=137)
    assert not os.path.exists(kill_npy)
    entries = list(kill_dir.glob("job_*.json"))
    assert len(entries) == 1
    entry = json.loads(entries[0].read_text())
    assert entry["status"] == "running"
    assert entry["snapshot_cursor"]["trees_done"] == 2 * (KILL_AT_CHUNK - 1)

    res_npy = str(tmp_path / "resumed.npy")
    out = _run(_RESUME, _mesh_env(kill_dir), csv, res_npy)
    assert f"RESUMED {NTREES}" in out.stdout
    np.testing.assert_allclose(np.load(res_npy), np.load(base_npy),
                               rtol=1e-4, atol=1e-4)
