"""Rapids-analog munging tests: sort/group_by/merge/rbind/cbind/filter/etc.

Mirrors h2o-py/tests/testdir_munging pyunits: golden comparisons against
pandas-free numpy equivalents.
"""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.rapids import (sort, group_by, merge, rbind, cbind,
                             filter_rows, unique, table, ifelse, hist)


@pytest.fixture()
def fr(rng):
    n = 500
    return Frame.from_numpy({
        "g": np.array(["a", "b", "c"], dtype=object)[
            rng.integers(0, 3, n)],
        "x": rng.normal(size=n),
        "y": rng.integers(0, 100, n).astype(np.float64),
    })


def test_sort_single_and_multi(cl, fr):
    s = sort(fr, "x")
    xs = s.vec("x").to_numpy()
    assert np.all(np.diff(xs) >= 0)
    s2 = sort(fr, ["g", "x"], ascending=[True, False])
    g = s2.vec("g").decoded()
    assert list(g) == sorted(list(g))
    x = s2.vec("x").to_numpy()
    for lbl in "abc":
        seg = x[g == lbl]
        assert np.all(np.diff(seg) <= 0)


def test_sort_nan_last(cl, rng):
    x = np.array([3.0, np.nan, 1.0, 2.0])
    s = sort(Frame.from_numpy({"x": x}), "x")
    out = s.vec("x").to_numpy()
    np.testing.assert_array_equal(out[:3], [1.0, 2.0, 3.0])
    assert np.isnan(out[3])


def test_group_by(cl, fr):
    out = group_by(fr, "g", {"x": ["mean", "count", "min", "max", "sd"]})
    g = fr.vec("g").decoded()
    x = fr.vec("x").to_numpy()
    keys = out.vec("g").decoded() if out.vec("g").type == "cat" \
        else out.vec("g").host_data
    for i, lbl in enumerate(np.asarray(keys)):
        seg = x[g == lbl]
        assert out.vec("mean_x").to_numpy()[i] == pytest.approx(seg.mean(),
                                                                rel=1e-5)
        assert out.vec("count_x").to_numpy()[i] == len(seg)
        assert out.vec("min_x").to_numpy()[i] == pytest.approx(seg.min())
        assert out.vec("max_x").to_numpy()[i] == pytest.approx(seg.max())
        assert out.vec("sd_x").to_numpy()[i] == pytest.approx(
            seg.std(ddof=1), rel=1e-4)


def test_group_by_multikey(cl, rng):
    n = 300
    fr = Frame.from_numpy({
        "a": np.array(["p", "q"], dtype=object)[rng.integers(0, 2, n)],
        "b": rng.integers(0, 3, n).astype(np.float64),
        "v": rng.normal(size=n)})
    out = group_by(fr, ["a", "b"], {"v": ["sum"]})
    assert out.nrows <= 6
    tot = out.vec("sum_v").to_numpy().sum()
    assert tot == pytest.approx(fr.vec("v").to_numpy().sum(), rel=1e-5)


def test_merge_inner_and_left(cl):
    left = Frame.from_numpy({
        "k": np.array(["a", "b", "c", "d"], dtype=object),
        "x": np.array([1.0, 2.0, 3.0, 4.0])})
    right = Frame.from_numpy({
        "k": np.array(["b", "c", "c", "e"], dtype=object),
        "y": np.array([20.0, 30.0, 31.0, 50.0])})
    inner = merge(left, right, "k")
    assert inner.nrows == 3            # b:1, c:2
    ks = inner.vec("k").decoded()
    assert sorted(ks) == ["b", "c", "c"]
    lft = merge(left, right, "k", how="left")
    assert lft.nrows == 5              # a, b, c, c, d
    y = lft.vec("y").to_numpy()
    k = lft.vec("k").decoded()
    assert np.isnan(y[k == "a"]).all() and np.isnan(y[k == "d"]).all()


def test_merge_right_and_outer(cl):
    left = Frame.from_numpy({"k": np.array([1.0, 2, 3]),
                             "x": np.array([10.0, 20, 30])})
    right = Frame.from_numpy({"k": np.array([2.0, 3, 4]),
                              "y": np.array([200.0, 300, 400])})
    r = merge(left, right, "k", how="right")
    assert r.nrows == 3
    np.testing.assert_array_equal(np.sort(r.vec("k").to_numpy()), [2, 3, 4])
    assert np.isnan(r.vec("x").to_numpy()[r.vec("k").to_numpy() == 4]).all()
    o = merge(left, right, "k", how="outer")
    assert o.nrows == 4
    np.testing.assert_array_equal(np.sort(o.vec("k").to_numpy()),
                                  [1, 2, 3, 4])
    assert np.isnan(o.vec("y").to_numpy()[o.vec("k").to_numpy() == 1]).all()
    assert np.isnan(o.vec("x").to_numpy()[o.vec("k").to_numpy() == 4]).all()


def test_rbind_unifies_domains(cl):
    f1 = Frame.from_numpy({"c": np.array(["x", "y"], dtype=object)})
    f2 = Frame.from_numpy({"c": np.array(["y", "z"], dtype=object)})
    out = rbind(f1, f2)
    assert out.nrows == 4
    assert list(out.vec("c").decoded()) == ["x", "y", "y", "z"]


def test_cbind_renames_dups(cl, rng):
    f1 = Frame.from_numpy({"x": rng.normal(size=5)})
    f2 = Frame.from_numpy({"x": rng.normal(size=5)})
    out = cbind(f1, f2)
    assert out.names == ["x", "x1"]


def test_string_ops(cl):
    from h2o3_tpu.rapids import (toupper, tolower, trim, gsub, sub, nchar,
                                 strsplit, substring, countmatches)
    fr = Frame.from_numpy({"g": np.array(["  a-b ", "c-d", "a-b-e"],
                                         dtype=object)})
    t = trim(fr.vec("g"))
    assert list(t.decoded()) == ["a-b", "c-d", "a-b-e"]
    up = toupper(t)
    assert list(up.decoded()) == ["A-B", "C-D", "A-B-E"]
    assert list(tolower(up).decoded()) == ["a-b", "c-d", "a-b-e"]
    assert list(gsub(t, "-", "_").decoded()) == ["a_b", "c_d", "a_b_e"]
    assert list(sub(t, "-", "_").decoded()) == ["a_b", "c_d", "a_b-e"]
    assert list(nchar(t).to_numpy()) == [3.0, 3.0, 5.0]
    assert list(substring(t, 0, 1).decoded()) == ["a", "c", "a"]
    assert list(countmatches(t, "-").to_numpy()) == [1.0, 1.0, 2.0]
    sp = strsplit(t, "-")
    assert sp.names == ["C1", "C2", "C3"]
    assert sp.vec("C3").host_data[2] == "e"
    # cat transforms are domain-only: collapsing labels merges codes
    fr2 = Frame.from_numpy({"g": np.array(["A", "a", "B"], dtype=object)})
    lo = tolower(fr2.vec("g"))
    assert lo.cardinality == 2
    assert list(lo.decoded()) == ["a", "a", "b"]


def test_impute_cut_scale(cl):
    from h2o3_tpu.frame.vec import Vec, T_CAT
    from h2o3_tpu.rapids import impute, cut, scale
    fr = Frame.from_numpy({"x": np.array([1.0, np.nan, 3.0, np.nan])})
    gv = Vec.from_numpy(np.array([0, 0, -1, 1], np.int32), T_CAT,
                        domain=["a", "b"])
    fr = fr.with_vec("g", gv)
    np.testing.assert_allclose(impute(fr, "x").vec("x").to_numpy(),
                               [1, 2, 3, 2])
    np.testing.assert_allclose(
        impute(fr, "x", method="median").vec("x").to_numpy(), [1, 2, 3, 2])
    assert list(impute(fr, "g").vec("g").decoded()) == ["a", "a", "a", "b"]
    c = cut(fr.vec("x"), [0.0, 2.0, 4.0])
    assert list(c.decoded()) == ["(0.0,2.0]", None, "(2.0,4.0]", None]
    s = scale(Frame.from_numpy({"x": np.arange(10.0)}))
    x = s.vec("x").to_numpy()
    assert abs(x.mean()) < 1e-6 and abs(x.std(ddof=1) - 1) < 1e-5


def test_tree_varimp(cl, rng):
    from h2o3_tpu.models import GBM
    n = 1500
    X = rng.normal(size=(n, 4))
    y = 3 * X[:, 1] + 0.8 * X[:, 3] + 0.05 * rng.normal(size=n)
    fr = Frame.from_numpy({**{f"x{j}": X[:, j] for j in range(4)},
                           "y": y})
    m = GBM(response_column="y", ntrees=15, max_depth=3, seed=1).train(fr)
    vi = m.varimp()
    assert list(vi)[0] == "x1" and vi["x1"] == 1.0
    assert vi["x3"] > vi["x0"]
    vs = m.varimp(fr, method="shap")
    assert list(vs)[0] == "x1" and vs["x3"] > vs["x0"]


def test_filter_unique_table_ifelse_hist(cl, rng):
    n = 400
    fr = Frame.from_numpy({
        "g": np.array(["u", "v"], dtype=object)[rng.integers(0, 2, n)],
        "x": rng.normal(size=n)})
    x = fr.vec("x").to_numpy()
    flt = filter_rows(fr, x > 0)
    assert flt.nrows == (x > 0).sum()
    assert np.all(flt.vec("x").to_numpy() > 0)
    u = unique(fr.vec("g"))
    assert sorted(u) == ["u", "v"]
    t = table(fr.vec("g"))
    assert t["u"] + t["v"] == n
    iv = ifelse(fr.vec("x"), 1.0, 0.0)
    got = iv.to_numpy()[:n]
    np.testing.assert_array_equal(got, (x != 0).astype(np.float64))
    counts, edges = hist(fr.vec("x"), breaks=10)
    assert counts.sum() == n
    np_counts, _ = np.histogram(x, bins=edges)
    np.testing.assert_allclose(counts[1:-1], np_counts[1:-1], atol=1)


def test_var_cor(cl, rng):
    import h2o3_tpu
    from h2o3_tpu.rapids import var, cor
    n = 400
    x = rng.normal(size=n)
    y = 2.0 * x + 0.5 * rng.normal(size=n)
    z = rng.normal(size=n)
    x_na = x.copy(); x_na[::50] = np.nan
    fr = h2o3_tpu.Frame.from_numpy({"x": x_na, "y": y, "z": z})
    v = var(fr)
    assert v["columns"] == ["x", "y", "z"]
    ok = np.isfinite(x_na)
    expected = np.cov(np.stack([x_na[ok], y[ok], z[ok]]))
    np.testing.assert_allclose(v["matrix"], expected, rtol=1e-4, atol=1e-4)
    c = cor(fr)
    exp_c = np.corrcoef(np.stack([x_na[ok], y[ok], z[ok]]))
    np.testing.assert_allclose(c["matrix"], exp_c, rtol=1e-4, atol=1e-4)
    assert c["matrix"][0, 1] > 0.9
    # "everything": NaN propagates to pairs involving the NA column
    ce = cor(fr, use="everything")["matrix"]
    assert np.isnan(ce[0, 1]) and np.isfinite(ce[1, 2])


def test_var_cor_edges(cl):
    import h2o3_tpu
    from h2o3_tpu.rapids import var, cor
    # all rows incomplete -> NaN matrix, not fabricated values
    fr = h2o3_tpu.Frame.from_numpy({
        "a": np.array([1.0, np.nan, 3.0]),
        "b": np.array([np.nan, 2.0, np.nan])})
    assert np.isnan(var(fr)["matrix"]).all()
    # categorical NA codes (-1) are NA, not the value -1
    g = np.array(["x", "y", None, "x", "y", "x"], dtype=object)
    fr2 = h2o3_tpu.Frame.from_numpy(
        {"g": g, "v": np.arange(6.0)},
        types={"g": "cat"}, domains={"g": ["x", "y"]})
    v = var(fr2, cols=["g", "v"])
    codes = np.array([0.0, 1.0, 0.0, 1.0, 0.0])
    vals = np.array([0.0, 1.0, 3.0, 4.0, 5.0])
    np.testing.assert_allclose(
        v["matrix"], np.cov(np.stack([codes, vals])), rtol=1e-5, atol=1e-5)
    # correlation is clipped into [-1, 1] even for perfect pairs
    x = np.arange(20.0)
    fr3 = h2o3_tpu.Frame.from_numpy({"x": x, "y": -x})
    c = cor(fr3)["matrix"]
    assert c[0, 1] == -1.0 and abs(c[0, 0]) <= 1.0


def test_rapids_ast_extended_ops(cl):
    import h2o3_tpu
    from h2o3_tpu.rapids import rapids
    fr = h2o3_tpu.Frame.from_numpy(
        {"s": np.array(["ab", "CD", " e "], object),
         "x": np.array([1.0, 2.0, np.nan]),
         "y": np.array([2.0, 4.0, 6.0])}, key="ast_ext")
    up = rapids('(toupper (cols ast_ext "s"))')
    assert list(up.vecs[0].decoded()) == ["AB", "CD", " E "]
    assert list(rapids('(nchar (cols ast_ext "s"))')
                .vecs[0].to_numpy()) == [2.0, 2.0, 3.0]
    imp = rapids('(h2o.impute ast_ext "x" "median")')
    assert np.isfinite(imp.vec("x").to_numpy()).all()
    v = rapids('(var ast_ext)')
    assert v.names == ["x", "y"]
    c = rapids('(cor ast_ext)')
    assert abs(c.vec("y").to_numpy()[1] - 1.0) < 1e-6    # cor(y,y)=1
    sc = rapids('(scale ast_ext TRUE TRUE)')       # boolean tokens
    assert abs(float(np.nanmean(sc.vec("y").to_numpy()))) < 1e-6
    # client-order replaceall: (pattern, replacement, frame, ignore_case)
    rep = rapids('(replaceall "a" "z" (cols ast_ext "s") FALSE)')
    assert list(rep.vecs[0].decoded())[0] == "zb"
    # substring numeric args arrive as floats; coerced to ints
    sub = rapids('(substring (cols ast_ext "s") 0 1)')
    assert list(sub.vecs[0].decoded()) == ["a", "C", " "]
    # impute -1 sentinel fills every numeric column
    allimp = rapids('(h2o.impute ast_ext -1 "mean")')
    assert np.isfinite(allimp.vec("x").to_numpy()).all()
    h2o3_tpu.remove("ast_ext")


def test_lazyframe_string_stats_verbs(cl):
    import h2o3_tpu
    from h2o3_tpu.rapids import lazy
    fr = h2o3_tpu.Frame.from_numpy(
        {"s": np.array(["aa", "ba"], object),
         "x": np.array([1.0, 3.0]), "y": np.array([2.0, 6.0])},
        key="lazy_sv")
    lf = lazy("lazy_sv")
    up = lf[["s"]].toupper().frame()
    assert list(up.vecs[0].decoded()) == ["AA", "BA"]
    g = lf[["s"]].gsub("a", "z").frame()
    assert list(g.vecs[0].decoded()) == ["zz", "bz"]
    n = lf[["s"]].nchar().frame()
    assert list(n.vecs[0].to_numpy()) == [2.0, 2.0]
    c = lf[["x", "y"]].cor()          # matrix Frame directly
    assert abs(c.vec("y").to_numpy()[0] - 1.0) < 1e-6
    assert isinstance(lf[["x"]].var(), float)   # scalar like sd()
    # quoted pattern containing a single quote round-trips the tokenizer
    esc = lf[["s"]].gsub("a", "d'z").frame()
    assert list(esc.vecs[0].decoded()) == ["d'zd'z", "bd'z"]

    sc = lf.scale().frame()
    assert abs(float(np.mean(sc.vec("x").to_numpy()))) < 1e-6
    h2o3_tpu.remove("lazy_sv")
