"""Extended metrics: gains/lift table, KS, concordance, custom metric UDF.

Golden comparisons against hand-computed formulas (GainsLift.java
semantics) on fixtures with known score distributions.
"""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.metrics.core import binomial_metrics
from h2o3_tpu.metrics.gainslift import gains_lift_table, concordance_index


def test_gains_lift_perfect_model(cl):
    """A perfect ranker: top decile captures all positives (10% base)."""
    n = 10_000
    y = np.zeros(n)
    y[:1000] = 1                      # 10% positives
    p = np.linspace(0.999, 0.001, n)  # scores perfectly ordered
    m = binomial_metrics(p, y, np.ones(n))
    gl = m.gains_lift(groups=10)
    # first group (top 10%) captures ~100% of positives -> lift ~10
    assert gl["cumulative_capture_rate"][0] == pytest.approx(1.0, abs=0.02)
    assert gl["lift"][0] == pytest.approx(10.0, rel=0.05)
    assert gl["cumulative_lift"][-1] == pytest.approx(1.0, abs=0.01)
    assert m.ks == pytest.approx(1.0, abs=0.02)


def test_gains_lift_random_model(cl, rng):
    """A random ranker: lift ~= 1 everywhere, KS ~= 0."""
    n = 20_000
    y = (rng.random(n) < 0.3).astype(float)
    p = rng.random(n)
    m = binomial_metrics(p, y, np.ones(n))
    gl = m.gains_lift(groups=8)
    np.testing.assert_allclose(gl["cumulative_lift"], 1.0, atol=0.08)
    assert m.ks < 0.05
    # capture rates sum to ~1
    assert sum(gl["capture_rate"]) == pytest.approx(1.0, abs=0.02)


def test_concordance_index(cl, rng):
    # perfectly concordant: higher risk -> earlier event
    t = np.array([1.0, 2, 3, 4, 5])
    e = np.ones(5)
    risk = np.array([5.0, 4, 3, 2, 1])
    assert concordance_index(t, e, risk) == 1.0
    assert concordance_index(t, e, -risk) == 0.0
    # random risk ~ 0.5
    n = 500
    tt = rng.random(n)
    rr = rng.random(n)
    c = concordance_index(tt, np.ones(n), rr)
    assert 0.4 < c < 0.6


def test_custom_metric_udf(cl, rng):
    from h2o3_tpu.models import GLM
    n = 800
    X = rng.normal(size=(n, 3))
    y = X @ [1.0, -1.0, 0.5] + 0.1 * rng.normal(size=n)
    fr = Frame.from_numpy({**{f"x{j}": X[:, j] for j in range(3)}, "y": y})

    def mae(preds, yy, ww):
        p = preds[: len(yy)].reshape(len(yy), -1)[:, 0]
        return "mae", float(np.average(np.abs(p - yy[: len(p)]),
                                       weights=ww[: len(p)]))

    m = GLM(response_column="y", family="gaussian",
            custom_metric_func=mae).train(fr)
    d = m.training_metrics.describe()
    assert "mae" in d and 0 <= d["mae"] < 1.0
