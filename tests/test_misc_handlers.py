"""REST breadth residue (VERDICT r03 missing #5): CreateFrame, Typeahead,
MissingInserter, Interaction, Tabulate, DCTTransformer, JStack,
NetworkTest — handler logic + route round trips."""

import json
import urllib.parse
import urllib.request

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import (Frame, create_frame, dct_transform,
                      insert_missing_values, interaction, tabulate)
from h2o3_tpu.frame.vec import T_CAT


@pytest.fixture(scope="module", autouse=True)
def _init():
    h2o3_tpu.init()


def test_create_frame_shapes_and_types():
    fr = create_frame(rows=500, cols=10, categorical_fraction=0.3,
                      integer_fraction=0.2, missing_fraction=0.05,
                      factors=7, has_response=True, response_factors=3,
                      seed=42)
    assert fr.nrows == 500 and fr.ncols == 11
    assert fr.names[0] == "response"
    types = fr.types()
    assert types["response"] == "cat"
    assert sum(1 for t in types.values() if t == "cat") == 4  # 3 + response
    # missingness actually lands
    a_num = next(n for n in fr.names[1:] if types[n] == "num")
    vals = fr.vec(a_num).to_numpy()
    assert np.isnan(vals).mean() > 0.005


def test_create_frame_reproducible():
    a = create_frame(rows=50, cols=4, seed=7)
    b = create_frame(rows=50, cols=4, seed=7)
    np.testing.assert_array_equal(a.vec(a.names[0]).to_numpy(),
                                  b.vec(b.names[0]).to_numpy())


def test_insert_missing_values():
    rng = np.random.default_rng(0)
    fr = Frame.from_numpy({
        "a": rng.normal(size=400),
        "c": rng.choice(["x", "y"], 400).astype(object)}, types={"c": T_CAT})
    out = insert_missing_values(fr, fraction=0.3, seed=1)
    a = out.vec("a").to_numpy()
    assert 0.2 < np.isnan(a).mean() < 0.4
    c = out.vec("c").to_numpy()
    assert 0.2 < (np.asarray(c) < 0).mean() < 0.4


def test_interaction_columns():
    rng = np.random.default_rng(1)
    fr = Frame.from_numpy({
        "f1": rng.choice(["a", "b"], 300).astype(object),
        "f2": rng.choice(["p", "q", "r"], 300).astype(object),
        "n": rng.normal(size=300)}, types={"f1": T_CAT, "f2": T_CAT})
    out = interaction(fr, ["f1", "f2"])
    assert out.names == ["f1_f2"]
    dom = out.vec("f1_f2").domain
    assert set(dom) <= {f"{a}_{b}" for a in "ab" for b in "pqr"}
    assert len(dom) == 6
    # codes decode consistently with the source pair
    codes = out.vec("f1_f2").to_numpy()
    f1 = fr.vec("f1")
    f2 = fr.vec("f2")
    for i in (0, 7, 123):
        want = (f1.domain[int(f1.to_numpy()[i])] + "_"
                + f2.domain[int(f2.to_numpy()[i])])
        assert dom[int(codes[i])] == want
    with pytest.raises(ValueError, match="categorical"):
        interaction(fr, ["f1", "n"])


def test_interaction_max_factors_pools_other():
    rng = np.random.default_rng(2)
    fr = Frame.from_numpy({
        "f1": rng.choice(list("abcdef"), 600).astype(object),
        "f2": rng.choice(list("uvwxyz"), 600).astype(object)},
        types={"f1": T_CAT, "f2": T_CAT})
    out = interaction(fr, ["f1", "f2"], max_factors=5)
    dom = out.vec("f1_f2").domain
    assert len(dom) == 6 and dom[-1] == "other"


def test_tabulate_counts_and_means():
    rng = np.random.default_rng(3)
    g = rng.choice(["u", "v"], 1000)
    y = np.where(g == "u", 2.0, 5.0) + 0.01 * rng.normal(size=1000)
    fr = Frame.from_numpy({"g": g.astype(object), "y": y},
                          types={"g": T_CAT})
    out = tabulate(fr, "g", "y", nbins_response=4)
    assert out["predictor_levels"] == ["u", "v"]
    counts = np.asarray(out["count_table"])
    assert counts.sum() == 1000
    means = {row[0]: row[1] for row in out["response_table"]}
    assert means["u"] == pytest.approx(2.0, abs=0.01)
    assert means["v"] == pytest.approx(5.0, abs=0.01)


def test_dct_roundtrip():
    rng = np.random.default_rng(4)
    X = rng.normal(size=(64, 12))
    fr = Frame.from_numpy({f"p{i}": X[:, i] for i in range(12)})
    spec = dct_transform(fr, [4, 3, 1])
    assert spec.ncols == 12
    # orthonormal DCT: inverse(dct(x)) == x
    back = dct_transform(spec, [4, 3, 1], inverse=True)
    Y = np.stack([back.vec(n).to_numpy() for n in back.names], axis=1)
    np.testing.assert_allclose(Y, X, atol=1e-5)
    # Parseval: energy preserved
    S = np.stack([spec.vec(n).to_numpy() for n in spec.names], axis=1)
    np.testing.assert_allclose((S ** 2).sum(), (X ** 2).sum(), rtol=1e-6)


def test_jstack_and_network_test():
    from h2o3_tpu.runtime.observability import jstack, network_test
    traces = jstack()
    assert any("MainThread" in t["name"] for t in traces)
    assert all(t["traces"] for t in traces)
    res = network_test(sizes=(1024, 65536))
    # one row per (size, reduction stage): the flat product axis plus the
    # single-axis "chips" / "hosts" stages of the hierarchical schedule
    assert {r["axis"] for r in res} == {"rows", "chips", "hosts"}
    assert len(res) == 6
    assert all(r["gbytes_per_sec"] > 0 for r in res)


def test_rest_routes_round_trip(tmp_path):
    from h2o3_tpu.api.server import start_server
    srv = start_server(port=0)
    try:
        def get(route):
            with urllib.request.urlopen(f"{srv.url}{route}") as r:
                return json.loads(r.read().decode())

        def post(route, **params):
            data = json.dumps(params).encode()
            req = urllib.request.Request(f"{srv.url}{route}", data=data,
                                         method="POST")
            req.add_header("Content-Type", "application/json")
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read().decode())

        made = post("/3/CreateFrame", rows=100, cols=4, seed=5)
        key = made["key"]["name"]
        assert made["rows"] == 100
        miss = post("/3/MissingInserter", dataset=key, fraction=0.2, seed=1)
        assert miss["key"]["name"] == key
        (tmp_path / "alpha.csv").write_text("x\n1\n")
        ta = get("/3/Typeahead/files?src="
                 + urllib.parse.quote(str(tmp_path / "al")))
        assert str(tmp_path / "alpha.csv") in ta["matches"]
        js = get("/3/JStack")
        assert js["traces"]
        nt = get("/3/NetworkTest")
        assert nt["results"]

        # Timeline honors ?limit= and carries the cluster sections
        from h2o3_tpu.runtime import observability as obs
        for i in range(5):
            obs.record("route_marker", i=i)
        tl = get("/3/Timeline?limit=3")
        assert len(tl["events"]) == 3
        assert "counters" in tl and "nodes" in tl and "traces" in tl
        lg = get("/3/Logs?limit=2")
        assert len(lg["log"]) <= 2

        # /metrics is Prometheus text exposition, not JSON; the in-process
        # server scrapes the same registry this test writes to
        obs.observe("route_scrape_seconds", 0.01, where="test")
        with urllib.request.urlopen(f"{srv.url}/metrics") as r:
            ctype = r.headers.get("Content-Type", "")
            body = r.read().decode()
        assert ctype.startswith("text/plain")
        assert "# TYPE route_scrape_seconds histogram" in body
        assert 'le=' in body
    finally:
        srv.stop()
