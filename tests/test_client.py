"""Client-contract tests: Rapids AST evaluation, lazy expression DAG,
remote REST client, schema metadata + estimator codegen, observability.

Mirrors h2o-py's connection/expr pyunits: the remote client drives a live
in-process REST server over real HTTP.
"""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.rapids.ast import rapids, parse
from h2o3_tpu.rapids.expr import lazy


@pytest.fixture()
def fr(cl, rng):
    n = 400
    f = Frame.from_numpy({
        "g": np.array(["a", "b", "c"], dtype=object)[rng.integers(0, 3, n)],
        "x": rng.normal(size=n),
        "y": rng.integers(0, 50, n).astype(np.float64)},
        key="astfr")
    return f


def test_parse_rapids_text():
    assert parse("(+ 1 2)") == ["+", 1.0, 2.0]
    assert parse("(sort fr ['a' 'b'] [1 0])") == [
        "sort", "fr", ["__list__", ("str", "a"), ("str", "b")],
        ["__list__", 1.0, 0.0]]


def test_rapids_eval_basics(fr):
    assert rapids("(nrow astfr)") == 400
    s = rapids("(sum (cols astfr ['y']))")
    assert s == pytest.approx(float(fr.vec("y").to_numpy().sum()), rel=1e-5)
    out = rapids("(tmp= astfr_s (sort astfr ['y'] [1]))")
    ys = out.vec("y").to_numpy()
    assert np.all(np.diff(ys) >= 0)
    gb = rapids("(GB astfr ['g'] mean 'y' 'all' nrow 'y' 'all')")
    assert gb.nrows == 3
    assert "mean_y" in gb.names and "count_y" in gb.names


def test_rapids_arithmetic_and_filter(fr):
    out = rapids("(tmp= astfr_f (rows astfr (> (cols astfr ['x']) 0)))")
    x = out.vec("x").to_numpy()
    assert out.nrows > 0 and np.all(x > 0)
    tr = rapids("(tmp= astfr_l (log (exp (cols astfr ['x']))))")
    np.testing.assert_allclose(tr.vec("x").to_numpy(),
                               fr.vec("x").to_numpy(), rtol=1e-4)


def test_lazy_expr_dag(fr):
    lf = lazy(fr)
    # nothing executes until demanded
    expr = (lf["x"] * 2 + 1).abs().sqrt()
    assert expr._cached_key is None
    assert "(sqrt (abs (+ (* (cols" in expr.ast()
    got = expr.frame().to_numpy().ravel()[: fr.nrows]
    want = np.sqrt(np.abs(fr.vec("x").to_numpy() * 2 + 1))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # aggregates evaluate to scalars
    assert lf["y"].mean() == pytest.approx(
        float(fr.vec("y").to_numpy().mean()), rel=1e-5)
    # sort/group_by compose lazily
    gb = lf.group_by("g", y=["mean", "sum"]).frame()
    assert gb.nrows == 3
    srt = lf.sort("y", ascending=False).frame()
    assert np.all(np.diff(srt.vec("y").to_numpy()) <= 0)


def test_remote_client_end_to_end(cl, rng, tmp_path):
    from h2o3_tpu.api.server import start_server
    import h2o3_tpu.client as h2oc
    server = start_server(port=0)
    try:
        conn = h2oc.connect(server.url)
        assert conn.cloud["cloud_healthy"]

        n = 600
        X = rng.normal(size=(n, 3))
        y = X[:, 0] * 2 - X[:, 1] + 0.1 * rng.normal(size=n)
        csv = "a,b,c,y\n" + "\n".join(
            f"{X[i,0]},{X[i,1]},{X[i,2]},{y[i]}" for i in range(n))
        p = tmp_path / "train.csv"
        p.write_text(csv)

        fr = conn.import_file(str(p))
        assert fr.nrows == n and fr.names == ["a", "b", "c", "y"]
        assert fr.types()["a"] == "num"
        head = fr.head(5)
        assert len(head["a"]) == 5

        model = conn.train("glm", training_frame=fr, response_column="y",
                           family="gaussian")
        assert model.algo == "glm"
        mm = model.metrics()
        assert mm["r2"] > 0.9

        preds = model.predict(fr)
        assert preds.nrows == n
        perf = model.model_performance(fr)
        assert perf["r2"] > 0.9

        # rapids over the wire
        lz = fr.lazy()
        assert lz.nrow() == n
        m = (lz["a"] + lz["b"]).mean()
        assert m == pytest.approx(float((X[:, 0] + X[:, 1]).mean()),
                                  abs=1e-4)

        # schema metadata + codegen
        schemas = conn.schemas()
        algos = [s["algo"] for s in schemas["schemas"]]
        assert "gbm" in algos and "glm" in algos
        glm_schema = next(s for s in schemas["schemas"]
                          if s["algo"] == "glm")
        names = [pp["name"] for pp in glm_schema["parameters"]]
        assert "alpha" in names or "family" in names

        from h2o3_tpu.bindings.gen import generate_estimators_source
        src = generate_estimators_source(schemas)
        ns: dict = {}
        exec(compile(src, "<gen>", "exec"), ns)
        est = ns["H2OGBMEstimator"](ntrees=5, max_depth=3,
                                    response_column="y")
        m2 = est.train(fr, connection=conn)
        assert m2.metrics()["r2"] > 0.5

        # generated estimators rejects unknown params
        with pytest.raises(TypeError):
            ns["H2OGLMEstimator"](bogus_param=1)

        # observability surfaces
        ev = conn.get("/3/Timeline")["events"]
        assert any(e["kind"] == "job_start" for e in ev)
        assert "log" in conn.get("/3/Logs")
    finally:
        server.stop()


def test_generated_estimators_checked_in():
    """The checked-in generated module matches a fresh generation."""
    from h2o3_tpu.api.server import Api
    from h2o3_tpu.bindings.gen import generate_estimators_source
    import h2o3_tpu.estimators as E
    src = generate_estimators_source(Api().schemas())
    assert "H2OGBMEstimator" in E.__all__
    import os
    path = os.path.join(os.path.dirname(E.__file__), "_generated.py")
    assert open(path).read() == src, \
        "regenerate: python -m h2o3_tpu.bindings.gen"
