"""REST API tests: drive the server over real HTTP (rest-smoke analog)."""

import json
import urllib.request

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.api import start_server


@pytest.fixture(scope="module")
def server():
    s = start_server(port=0)
    yield s
    s.stop()


def _get(server, path):
    with urllib.request.urlopen(server.url + path) as r:
        return json.loads(r.read())


def _post(server, path, payload):
    req = urllib.request.Request(
        server.url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        raise AssertionError(f"{path} -> {e.code}: "
                             f"{e.read().decode()[:1500]}")


def test_cloud_route(cl, server):
    out = _get(server, "/3/Cloud")
    assert out["cloud_healthy"] is True
    assert out["platform"] in ("cpu", "tpu")


def test_parse_train_predict_flow(cl, server, rng, tmp_path):
    n = 500
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] - X[:, 1] > 0).astype(int)
    csv = tmp_path / "data.csv"
    with open(csv, "w") as f:
        f.write("a,b,c,y\n")
        for i in range(n):
            f.write(f"{X[i,0]},{X[i,1]},{X[i,2]},"
                    f"{'yes' if y[i] else 'no'}\n")

    out = _post(server, "/3/Parse",
                {"path": str(csv), "destination_frame": "rest_train"})
    assert out["destination_frame"]["name"] == "rest_train"

    frames = _get(server, "/3/Frames")["frames"]
    assert any(f["frame_id"]["name"] == "rest_train" for f in frames)
    fr = _get(server, "/3/Frames/rest_train")["frames"][0]
    assert fr["rows"] == n
    assert {c["label"] for c in fr["columns"]} == {"a", "b", "c", "y"}

    out = _post(server, "/3/ModelBuilders/gbm",
                {"training_frame": "rest_train", "response_column": "y",
                 "ntrees": 5, "seed": 1})
    model_key = out["job"]["dest"]["name"]
    assert out["model"]["algo"] == "gbm"
    assert out["model"]["training_metrics"]["auc"] > 0.8

    models = _get(server, "/3/Models")["models"]
    assert any(m["model_id"]["name"] == model_key for m in models)

    out = _post(server,
                f"/3/Predictions/models/{model_key}/frames/rest_train", {})
    pred_key = out["predictions_frame"]["name"]
    pf = _get(server, f"/3/Frames/{pred_key}")["frames"][0]
    assert pf["rows"] == n
    assert pf["columns"][0]["label"] == "predict"

    jobs = _get(server, "/3/Jobs")["jobs"]
    assert any(j["status"] == "DONE" for j in jobs)

    req = urllib.request.Request(server.url + f"/3/DKV/{pred_key}",
                                 method="DELETE")
    with urllib.request.urlopen(req) as r:
        assert json.loads(r.read())["removed"] == pred_key


def test_unknown_routes_404(cl, server):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(server, "/3/Nope")
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(server, "/3/Frames/not_a_frame")
    assert e.value.code == 404


def test_deploy_serve_launcher(cl, tmp_path):
    """The launcher boots the runtime + REST and shuts down on SIGTERM."""
    import json
    import os
    import signal
    import subprocess
    import sys
    import time
    import urllib.request
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.Popen(
        [sys.executable, "-m", "h2o3_tpu.deploy.serve", "--port", "54391"],
        env=env, cwd="/root/repo",
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        for _ in range(90):
            time.sleep(1)
            try:
                out = json.load(urllib.request.urlopen(
                    "http://127.0.0.1:54391/3/Cloud", timeout=2))
                assert out["cloud_healthy"]
                break
            except AssertionError:
                raise
            except Exception:
                continue
        else:
            raise AssertionError("launcher never served /3/Cloud")
    finally:
        p.send_signal(signal.SIGTERM)
        assert p.wait(timeout=15) == 0


def test_flow_workbench_served(cl):
    """The interactive Flow SPA: import/train/predict/automl/rapids
    actions wired to the same REST routes clients use."""
    from h2o3_tpu.api.server import start_server
    import urllib.request
    srv = start_server()
    try:
        html = urllib.request.urlopen(srv.url + "/").read().decode()
        assert "h2o3_tpu" in html and "/3/Frames" in html
        assert urllib.request.urlopen(
            srv.url + "/flow").read().decode() == html
        # interactive affordances present and wired to real routes
        for hook in ("doImport", "doTrain", "doAutoML", "doPredict",
                     "doRapids", "doSplit", "doPD", "fillCols"):
            assert hook in html, hook
        for route in ("/3/Parse", "/3/ModelBuilders/", "/99/AutoMLBuilder",
                      "/99/Rapids", "/3/SplitFrame", "/3/PartialDependence",
                      "/3/Models.fetch.bin/", "/mojo"):
            assert route in html, route
    finally:
        srv.stop()


def test_about_config_and_extensions(cl, monkeypatch):
    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.runtime import config as cfg
    from h2o3_tpu.runtime import extensions
    import json
    import urllib.request
    monkeypatch.setenv("H2O3_TPU_SCHEDULER_WORKERS", "5")
    cfg.reload()
    ran = []
    extensions.register("demo_ext", lambda h2o: ran.append(h2o.__version__))
    extensions.load_all()
    assert ran
    srv = start_server()
    try:
        about = json.load(urllib.request.urlopen(srv.url + "/3/About"))
        assert about["config"]["scheduler_workers"] == 5
        assert "demo_ext" in about["extensions"]
        assert "version" in about
    finally:
        srv.stop()
        cfg.reload()


def test_full_remote_workflow(cl, server, rng, tmp_path):
    """The whole h2o-py user journey purely over HTTP via client.py:
    import -> munge (/99/Rapids) -> grid -> automl -> explain ->
    checkpoint -> artifact download/upload round trips."""
    from h2o3_tpu import client as h2oc
    n = 400
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(int)
    csv = tmp_path / "wf.csv"
    with open(csv, "w") as f:
        f.write("a,b,c,y\n")
        for i in range(n):
            f.write(f"{X[i,0]:.5f},{X[i,1]:.5f},{X[i,2]:.5f},"
                    f"{'yes' if y[i] else 'no'}\n")
    conn = h2oc.connect(server.url)

    # import + munge through the lazy expression DAG -> /99/Rapids
    fr = conn.import_file(str(csv), destination_frame="wf_train")
    lz = fr.lazy()
    munged = (lz["a"] * 2.0).execute()      # exercises rapids round trip

    # parameter metadata endpoint drives codegen
    mb = conn.model_builders("gbm")
    names = [p["name"] for p in mb["gbm"]["parameters"]]
    assert "ntrees" in names and "learn_rate" in names
    assert conn.model_builders()["glm"]["parameters"]

    # grid search over REST
    grid = conn.grid("gbm", {"max_depth": [2, 3]}, fr,
                     response_column="y", ntrees=3, seed=1)
    assert len(grid.model_ids) == 2
    table = grid.summary_table()
    assert "max_depth" in table[0] and "model_id" in table[0]
    best = grid.best_model
    assert grid.refresh().model_ids == grid.model_ids  # GET /99/Grids/{id}
    assert any(g["name"] == grid.key
               for g in conn.get("/99/Grids")["grids"])

    # CV params ride the normal train route
    cvm = conn.train("glm", fr, response_column="y", family="binomial",
                     nfolds=3, seed=1, lambda_=0.0)
    cv_metrics = cvm.metrics()
    assert cv_metrics.get("auc") is None or cv_metrics["auc"] > 0.5

    # checkpoint continuation through REST
    m5 = conn.train("gbm", fr, response_column="y", ntrees=2, seed=1,
                    max_depth=3)
    m8 = conn.train("gbm", fr, response_column="y", ntrees=5, seed=1,
                    max_depth=3, checkpoint=m5.key)
    assert m8.schema["output"]["ntrees_trained"] == 5

    # automl over REST + leaderboard route
    aml = conn.automl(fr, response_column="y", max_models=3, seed=1,
                      project_name="wf_proj",
                      exclude_algos=["StackedEnsemble", "DeepLearning"])
    lb = aml.leaderboard()
    assert 1 <= len(lb) <= 4 and "model_id" in lb[0]
    leader = aml.leader

    # explain over REST
    vi = best.varimp()
    assert vi and {"variable", "relative_importance"} <= set(vi[0])
    pd_out = best.partial_dependence(fr, "a", nbins=5)
    assert len(pd_out["grid"]) == len(pd_out["mean_response"]) > 0

    # artifact download / upload round trip
    local = tmp_path / "model.bin"
    best.download(str(local))
    assert local.stat().st_size > 0
    re_up = conn.upload_model(str(local))
    preds = re_up.predict(fr)
    assert preds.nrows == n
    # mojo artifact + server-side save
    mojo = tmp_path / "model.zip"
    best.download_mojo(str(mojo))
    import zipfile
    assert zipfile.is_zipfile(mojo)
    saved = best.save(str(tmp_path))
    import os
    assert os.path.exists(saved)
    # predictions from the leader still flow
    assert leader.predict(fr).nrows == n
    del munged


def test_model_upload_rejects_pickle_gadgets(cl, server, tmp_path):
    """POST /3/Models.upload.bin must refuse pickles that reference
    globals outside the model-artifact allowlist (RCE gadget defense)."""
    import pickle

    class Gadget:
        def __reduce__(self):
            import os
            return (os.system, ("true",))

    bad = tmp_path / "evil.bin"
    with open(bad, "wb") as f:
        pickle.dump(Gadget(), f)
    from h2o3_tpu import client as h2oc
    conn = h2oc.connect(server.url)
    with pytest.raises(h2oc.H2OConnectionError, match="disallowed|blocked"):
        conn.upload_model(str(bad))
