"""REST API tests: drive the server over real HTTP (rest-smoke analog)."""

import json
import urllib.request

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.api import start_server


@pytest.fixture(scope="module")
def server():
    s = start_server(port=0)
    yield s
    s.stop()


def _get(server, path):
    with urllib.request.urlopen(server.url + path) as r:
        return json.loads(r.read())


def _post(server, path, payload):
    req = urllib.request.Request(
        server.url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        raise AssertionError(f"{path} -> {e.code}: "
                             f"{e.read().decode()[:1500]}")


def test_cloud_route(cl, server):
    out = _get(server, "/3/Cloud")
    assert out["cloud_healthy"] is True
    assert out["platform"] in ("cpu", "tpu")


def test_parse_train_predict_flow(cl, server, rng, tmp_path):
    n = 500
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] - X[:, 1] > 0).astype(int)
    csv = tmp_path / "data.csv"
    with open(csv, "w") as f:
        f.write("a,b,c,y\n")
        for i in range(n):
            f.write(f"{X[i,0]},{X[i,1]},{X[i,2]},"
                    f"{'yes' if y[i] else 'no'}\n")

    out = _post(server, "/3/Parse",
                {"path": str(csv), "destination_frame": "rest_train"})
    assert out["destination_frame"]["name"] == "rest_train"

    frames = _get(server, "/3/Frames")["frames"]
    assert any(f["frame_id"]["name"] == "rest_train" for f in frames)
    fr = _get(server, "/3/Frames/rest_train")["frames"][0]
    assert fr["rows"] == n
    assert {c["label"] for c in fr["columns"]} == {"a", "b", "c", "y"}

    out = _post(server, "/3/ModelBuilders/gbm",
                {"training_frame": "rest_train", "response_column": "y",
                 "ntrees": 5, "seed": 1})
    model_key = out["job"]["dest"]["name"]
    assert out["model"]["algo"] == "gbm"
    assert out["model"]["training_metrics"]["auc"] > 0.8

    models = _get(server, "/3/Models")["models"]
    assert any(m["model_id"]["name"] == model_key for m in models)

    out = _post(server,
                f"/3/Predictions/models/{model_key}/frames/rest_train", {})
    pred_key = out["predictions_frame"]["name"]
    pf = _get(server, f"/3/Frames/{pred_key}")["frames"][0]
    assert pf["rows"] == n
    assert pf["columns"][0]["label"] == "predict"

    jobs = _get(server, "/3/Jobs")["jobs"]
    assert any(j["status"] == "DONE" for j in jobs)

    req = urllib.request.Request(server.url + f"/3/DKV/{pred_key}",
                                 method="DELETE")
    with urllib.request.urlopen(req) as r:
        assert json.loads(r.read())["removed"] == pred_key


def test_unknown_routes_404(cl, server):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(server, "/3/Nope")
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(server, "/3/Frames/not_a_frame")
    assert e.value.code == 404


def test_deploy_serve_launcher(cl, tmp_path):
    """The launcher boots the runtime + REST and shuts down on SIGTERM."""
    import json
    import os
    import signal
    import subprocess
    import sys
    import time
    import urllib.request
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.Popen(
        [sys.executable, "-m", "h2o3_tpu.deploy.serve", "--port", "54391"],
        env=env, cwd="/root/repo",
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        for _ in range(90):
            time.sleep(1)
            try:
                out = json.load(urllib.request.urlopen(
                    "http://127.0.0.1:54391/3/Cloud", timeout=2))
                assert out["cloud_healthy"]
                break
            except AssertionError:
                raise
            except Exception:
                continue
        else:
            raise AssertionError("launcher never served /3/Cloud")
    finally:
        p.send_signal(signal.SIGTERM)
        assert p.wait(timeout=15) == 0


def test_flow_dashboard_served(cl):
    from h2o3_tpu.api.server import start_server
    import urllib.request
    srv = start_server()
    try:
        html = urllib.request.urlopen(srv.url + "/").read().decode()
        assert "h2o3_tpu" in html and "/3/Frames" in html
        assert urllib.request.urlopen(
            srv.url + "/flow").read().decode() == html
    finally:
        srv.stop()


def test_about_config_and_extensions(cl, monkeypatch):
    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.runtime import config as cfg
    from h2o3_tpu.runtime import extensions
    import json
    import urllib.request
    monkeypatch.setenv("H2O3_TPU_SCHEDULER_WORKERS", "5")
    cfg.reload()
    ran = []
    extensions.register("demo_ext", lambda h2o: ran.append(h2o.__version__))
    extensions.load_all()
    assert ran
    srv = start_server()
    try:
        about = json.load(urllib.request.urlopen(srv.url + "/3/About"))
        assert about["config"]["scheduler_workers"] == 5
        assert "demo_ext" in about["extensions"]
        assert "version" in about
    finally:
        srv.stop()
        cfg.reload()
