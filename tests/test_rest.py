"""REST API tests: drive the server over real HTTP (rest-smoke analog)."""

import json
import urllib.request

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.api import start_server


@pytest.fixture(scope="module")
def server():
    s = start_server(port=0)
    yield s
    s.stop()


def _get(server, path):
    with urllib.request.urlopen(server.url + path) as r:
        return json.loads(r.read())


def _post(server, path, payload):
    req = urllib.request.Request(
        server.url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        raise AssertionError(f"{path} -> {e.code}: "
                             f"{e.read().decode()[:1500]}")


def test_cloud_route(cl, server):
    out = _get(server, "/3/Cloud")
    assert out["cloud_healthy"] is True
    assert out["platform"] in ("cpu", "tpu")


def test_parse_train_predict_flow(cl, server, rng, tmp_path):
    n = 500
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] - X[:, 1] > 0).astype(int)
    csv = tmp_path / "data.csv"
    with open(csv, "w") as f:
        f.write("a,b,c,y\n")
        for i in range(n):
            f.write(f"{X[i,0]},{X[i,1]},{X[i,2]},"
                    f"{'yes' if y[i] else 'no'}\n")

    out = _post(server, "/3/Parse",
                {"path": str(csv), "destination_frame": "rest_train"})
    assert out["destination_frame"]["name"] == "rest_train"

    frames = _get(server, "/3/Frames")["frames"]
    assert any(f["frame_id"]["name"] == "rest_train" for f in frames)
    fr = _get(server, "/3/Frames/rest_train")["frames"][0]
    assert fr["rows"] == n
    assert {c["label"] for c in fr["columns"]} == {"a", "b", "c", "y"}

    out = _post(server, "/3/ModelBuilders/gbm",
                {"training_frame": "rest_train", "response_column": "y",
                 "ntrees": 5, "seed": 1})
    model_key = out["job"]["dest"]["name"]
    assert out["model"]["algo"] == "gbm"
    assert out["model"]["training_metrics"]["auc"] > 0.8

    models = _get(server, "/3/Models")["models"]
    assert any(m["model_id"]["name"] == model_key for m in models)

    out = _post(server,
                f"/3/Predictions/models/{model_key}/frames/rest_train", {})
    pred_key = out["predictions_frame"]["name"]
    pf = _get(server, f"/3/Frames/{pred_key}")["frames"][0]
    assert pf["rows"] == n
    assert pf["columns"][0]["label"] == "predict"

    jobs = _get(server, "/3/Jobs")["jobs"]
    assert any(j["status"] == "DONE" for j in jobs)

    req = urllib.request.Request(server.url + f"/3/DKV/{pred_key}",
                                 method="DELETE")
    with urllib.request.urlopen(req) as r:
        assert json.loads(r.read())["removed"] == pred_key


def test_unknown_routes_404(cl, server):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(server, "/3/Nope")
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(server, "/3/Frames/not_a_frame")
    assert e.value.code == 404


@pytest.mark.heavy
def test_deploy_serve_launcher(cl, tmp_path):
    """The launcher boots the runtime + REST and shuts down on SIGTERM.

    heavy: boots a full second interpreter + jax runtime (up to 90 s)."""
    import json
    import os
    import signal
    import subprocess
    import sys
    import time
    import urllib.request
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.Popen(
        [sys.executable, "-m", "h2o3_tpu.deploy.serve", "--port", "54391"],
        env=env, cwd="/root/repo",
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        for _ in range(90):
            time.sleep(1)
            try:
                out = json.load(urllib.request.urlopen(
                    "http://127.0.0.1:54391/3/Cloud", timeout=2))
                assert out["cloud_healthy"]
                break
            except AssertionError:
                raise
            except Exception:
                continue
        else:
            raise AssertionError("launcher never served /3/Cloud")
    finally:
        p.send_signal(signal.SIGTERM)
        assert p.wait(timeout=15) == 0


def test_flow_workbench_served(cl):
    """The interactive Flow SPA: import/train/predict/automl/rapids
    actions wired to the same REST routes clients use."""
    from h2o3_tpu.api.server import start_server
    import urllib.request
    srv = start_server()
    try:
        html = urllib.request.urlopen(srv.url + "/").read().decode()
        assert "h2o3_tpu" in html and "/3/Frames" in html
        assert urllib.request.urlopen(
            srv.url + "/flow").read().decode() == html
        # interactive affordances present and wired to real routes
        for hook in ("doImport", "doTrain", "doAutoML", "doPredict",
                     "doRapids", "doSplit", "doPD", "fillCols"):
            assert hook in html, hook
        for route in ("/3/Parse", "/3/ModelBuilders/", "/99/AutoMLBuilder",
                      "/99/Rapids", "/3/SplitFrame", "/3/PartialDependence",
                      "/3/Models.fetch.bin/", "/mojo"):
            assert route in html, route
    finally:
        srv.stop()


def test_about_config_and_extensions(cl, monkeypatch):
    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.runtime import config as cfg
    from h2o3_tpu.runtime import extensions
    import json
    import urllib.request
    monkeypatch.setenv("H2O3_TPU_SCHEDULER_WORKERS", "5")
    cfg.reload()
    ran = []
    extensions.register("demo_ext", lambda h2o: ran.append(h2o.__version__))
    extensions.load_all()
    assert ran
    srv = start_server()
    try:
        about = json.load(urllib.request.urlopen(srv.url + "/3/About"))
        assert about["config"]["scheduler_workers"] == 5
        assert "demo_ext" in about["extensions"]
        assert "version" in about
    finally:
        srv.stop()
        cfg.reload()


@pytest.mark.heavy
def test_full_remote_workflow(cl, server, rng, tmp_path):
    """The whole h2o-py user journey purely over HTTP via client.py:
    import -> munge (/99/Rapids) -> grid -> automl -> explain ->
    checkpoint -> artifact download/upload round trips.

    heavy: trains ~10 models over HTTP (~2+ min CPU);
    test_remote_workflow_fast covers the same route surface at tiny
    shape inside the tier-1 budget."""
    from h2o3_tpu import client as h2oc
    n = 400
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(int)
    csv = tmp_path / "wf.csv"
    with open(csv, "w") as f:
        f.write("a,b,c,y\n")
        for i in range(n):
            f.write(f"{X[i,0]:.5f},{X[i,1]:.5f},{X[i,2]:.5f},"
                    f"{'yes' if y[i] else 'no'}\n")
    conn = h2oc.connect(server.url)

    # import + munge through the lazy expression DAG -> /99/Rapids
    fr = conn.import_file(str(csv), destination_frame="wf_train")
    lz = fr.lazy()
    munged = (lz["a"] * 2.0).execute()      # exercises rapids round trip

    # parameter metadata endpoint drives codegen
    mb = conn.model_builders("gbm")
    names = [p["name"] for p in mb["gbm"]["parameters"]]
    assert "ntrees" in names and "learn_rate" in names
    assert conn.model_builders()["glm"]["parameters"]

    # grid search over REST
    grid = conn.grid("gbm", {"max_depth": [2, 3]}, fr,
                     response_column="y", ntrees=3, seed=1)
    assert len(grid.model_ids) == 2
    table = grid.summary_table()
    assert "max_depth" in table[0] and "model_id" in table[0]
    best = grid.best_model
    assert grid.refresh().model_ids == grid.model_ids  # GET /99/Grids/{id}
    assert any(g["name"] == grid.key
               for g in conn.get("/99/Grids")["grids"])

    # CV params ride the normal train route
    cvm = conn.train("glm", fr, response_column="y", family="binomial",
                     nfolds=3, seed=1, lambda_=0.0)
    cv_metrics = cvm.metrics()
    assert cv_metrics.get("auc") is None or cv_metrics["auc"] > 0.5

    # checkpoint continuation through REST
    m5 = conn.train("gbm", fr, response_column="y", ntrees=2, seed=1,
                    max_depth=3)
    m8 = conn.train("gbm", fr, response_column="y", ntrees=5, seed=1,
                    max_depth=3, checkpoint=m5.key)
    assert m8.schema["output"]["ntrees_trained"] == 5

    # automl over REST + leaderboard route
    aml = conn.automl(fr, response_column="y", max_models=3, seed=1,
                      project_name="wf_proj",
                      exclude_algos=["StackedEnsemble", "DeepLearning"])
    lb = aml.leaderboard()
    assert 1 <= len(lb) <= 4 and "model_id" in lb[0]
    leader = aml.leader

    # explain over REST
    vi = best.varimp()
    assert vi and {"variable", "relative_importance"} <= set(vi[0])
    pd_out = best.partial_dependence(fr, "a", nbins=5)
    assert len(pd_out["grid"]) == len(pd_out["mean_response"]) > 0

    # artifact download / upload round trip
    local = tmp_path / "model.bin"
    best.download(str(local))
    assert local.stat().st_size > 0
    re_up = conn.upload_model(str(local))
    preds = re_up.predict(fr)
    assert preds.nrows == n
    # mojo artifact + server-side save
    mojo = tmp_path / "model.zip"
    best.download_mojo(str(mojo))
    import zipfile
    assert zipfile.is_zipfile(mojo)
    saved = best.save(str(tmp_path))
    import os
    assert os.path.exists(saved)
    # predictions from the leader still flow
    assert leader.predict(fr).nrows == n
    del munged


def test_remote_workflow_fast(cl, server, rng, tmp_path):
    """Tiny-shape variant of test_full_remote_workflow: the same client
    route surface (import -> rapids -> metadata -> grid -> checkpoint ->
    artifact round trips) in seconds, so tier-1 keeps the coverage."""
    from h2o3_tpu import client as h2oc
    n = 120
    X = rng.normal(size=(n, 2))
    y = (X[:, 0] > 0).astype(int)
    csv = tmp_path / "wf_fast.csv"
    with open(csv, "w") as f:
        f.write("a,b,y\n")
        for i in range(n):
            f.write(f"{X[i,0]:.5f},{X[i,1]:.5f},"
                    f"{'yes' if y[i] else 'no'}\n")
    conn = h2oc.connect(server.url)
    fr = conn.import_file(str(csv), destination_frame="wf_fast")
    (fr.lazy()["a"] * 2.0).execute()         # rapids round trip
    mb = conn.model_builders("gbm")
    assert any(p["name"] == "ntrees" for p in mb["gbm"]["parameters"])
    grid = conn.grid("gbm", {"max_depth": [2, 3]}, fr,
                     response_column="y", ntrees=2, seed=1)
    assert len(grid.model_ids) == 2
    best = grid.best_model
    assert grid.refresh().model_ids == grid.model_ids
    m2 = conn.train("gbm", fr, response_column="y", ntrees=1, seed=1,
                    max_depth=2)
    m3 = conn.train("gbm", fr, response_column="y", ntrees=3, seed=1,
                    max_depth=2, checkpoint=m2.key)
    assert m3.schema["output"]["ntrees_trained"] == 3
    vi = best.varimp()
    assert vi and {"variable", "relative_importance"} <= set(vi[0])
    local = tmp_path / "model.bin"
    best.download(str(local))
    re_up = conn.upload_model(str(local))
    assert re_up.predict(fr).nrows == n
    mojo = tmp_path / "model.zip"
    best.download_mojo(str(mojo))
    import zipfile
    assert zipfile.is_zipfile(mojo)


def test_grid_batch_knob_and_failures_over_rest(cl, server, rng, tmp_path):
    """The generated H2OGridSearch bindings class drives /99/Grid with
    the grid_batch knob, and a member whose params fail validation
    surfaces in the grid schema's failed_entries instead of failing the
    whole POST (GridSchemaV99 failure_details analog)."""
    from h2o3_tpu import client as h2oc
    from h2o3_tpu.estimators import H2OGBMEstimator, H2OGridSearch
    n = 150
    X = rng.normal(size=(n, 2))
    yv = X[:, 0] - 0.5 * X[:, 1] + 0.1 * rng.normal(size=n)
    csv = tmp_path / "grid_rest.csv"
    with open(csv, "w") as f:
        f.write("a,b,y\n")
        for i in range(n):
            f.write(f"{X[i,0]:.5f},{X[i,1]:.5f},{yv[i]:.5f}\n")
    conn = h2oc.connect(server.url)
    fr = conn.import_file(str(csv), destination_frame="grid_rest")

    base = H2OGBMEstimator(response_column="y", ntrees=3, max_depth=2,
                           seed=7, reproducible=True)
    gs = H2OGridSearch(base, {"learn_rate": [0.1, 0.3]}, grid_batch="on")
    grid = gs.train(fr)
    assert len(grid.model_ids) == 2
    assert grid.failed_entries == [] and gs.failed_entries == []
    assert grid.refresh().failed_entries == []   # GET path carries it too

    bad = H2OGridSearch(base, {"distribution": ["gaussian", "bogus"]})
    grid2 = bad.train(fr)
    assert len(grid2.model_ids) == 1
    assert len(grid2.failed_entries) == 1
    assert grid2.failed_entries[0]["distribution"] == "bogus"
    assert "error" in grid2.failed_entries[0]


def test_model_upload_rejects_pickle_gadgets(cl, server, tmp_path):
    """POST /3/Models.upload.bin must refuse pickles that reference
    globals outside the model-artifact allowlist (RCE gadget defense)."""
    import pickle

    class Gadget:
        def __reduce__(self):
            import os
            return (os.system, ("true",))

    bad = tmp_path / "evil.bin"
    with open(bad, "wb") as f:
        pickle.dump(Gadget(), f)
    from h2o3_tpu import client as h2oc
    conn = h2oc.connect(server.url)
    with pytest.raises(h2oc.H2OConnectionError, match="disallowed|blocked"):
        conn.upload_model(str(bad))


# ----------------------------------------------------- round-5 route breadth

def test_frames_columns_and_light(cl, server):
    rng = np.random.default_rng(0)
    Frame.from_numpy({"a": rng.normal(size=50),
                      "b": rng.normal(size=50)}, key="rest5_f")
    cols = _get(server, "/3/Frames/rest5_f/columns")
    assert [c["label"] for c in cols["columns"]] == ["a", "b"]
    summ = _get(server, "/3/Frames/rest5_f/columns/a/summary")
    col = summ["frames"][0]["columns"][0]
    assert "mean" in col and col["label"] == "a"
    light = _get(server, "/3/Frames/rest5_f/light")
    assert light["frames"][0]["rows"] == 50


def test_download_dataset(cl, server):
    Frame.from_numpy({"x": np.arange(5.0)}, key="rest5_dl")
    with urllib.request.urlopen(
            server.url + "/3/DownloadDataset?frame_id=rest5_dl") as r:
        body = r.read().decode()
    assert body.splitlines()[0].strip('"') == "x"
    assert len(body.splitlines()) == 6


def test_model_java_and_metrics_stored(cl, server):
    rng = np.random.default_rng(1)
    X = rng.normal(size=(200, 3))
    y = np.where(X[:, 0] > 0, "A", "B").astype(object)
    Frame.from_numpy({"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2],
                      "y": y}, key="rest5_tf")
    out = _post(server, "/3/ModelBuilders/gbm",
                {"training_frame": "rest5_tf", "response_column": "y",
                 "ntrees": 3, "max_depth": 3})
    mid = out["job"]["dest"]["name"]
    with urllib.request.urlopen(
            server.url + f"/3/Models.java/{mid}") as r:
        src = r.read().decode()
    assert "score0" in src
    mm = _get(server, f"/3/ModelMetrics/models/{mid}")
    assert mm["model_metrics"] and mm["model_metrics"][0]["kind"] == \
        "training"


def test_word2vec_synonyms_over_rest(cl, server):
    """The VERDICT r4 #5 pipeline: tokenize -> w2v -> synonyms via REST."""
    from h2o3_tpu.frame.vec import Vec, T_STR
    rng = np.random.default_rng(2)
    words = ["red", "green", "blue", "cyan", "teal"]
    doc = " ".join(rng.choice(words, 400))
    Frame(["txt"], [Vec.from_numpy(np.asarray([doc], object), T_STR)],
          key="rest5_txt")
    tok = _post(server, "/99/Rapids",
                {"ast": "(tmp= rest5_tok (tokenize rest5_txt ' '))"})
    assert tok.get("key") or tok.get("string") or True
    out = _post(server, "/3/ModelBuilders/word2vec",
                {"training_frame": "rest5_tok", "vec_size": 8,
                 "epochs": 1})
    mid = out["job"]["dest"]["name"]
    syn = _get(server,
               f"/3/Word2VecSynonyms?model={mid}&word=red&count=3")
    assert len(syn["synonyms"]) == 3 and "red" not in syn["synonyms"]


def test_grid_export_import_over_rest(cl, server, tmp_path):
    rng = np.random.default_rng(3)
    X = rng.normal(size=(150, 2))
    y = np.where(X[:, 0] + X[:, 1] > 0, "p", "n").astype(object)
    Frame.from_numpy({"x0": X[:, 0], "x1": X[:, 1], "y": y},
                     key="rest5_gf")
    out = _post(server, "/99/Grid/gbm",
                {"training_frame": "rest5_gf", "response_column": "y",
                 "hyper_parameters": {"max_depth": [2, 3]}, "ntrees": 2})
    gid = out["grid_id"]["name"]
    _post(server, f"/99/Grids/{gid}/export",
          {"export_dir": str(tmp_path)})
    imp = _post(server, "/99/Grids.bin/import",
                {"grid_path": f"{tmp_path}/{gid}"})
    assert imp["n_models"] == 2


def test_misc_round5_routes(cl, server):
    assert _get(server, "/3/Ping")["cloud_healthy"] is True
    assert _get(server, "/3/InitID")["session_key"].startswith("_sid_")
    assert _post(server, "/4/sessions", {})["session_key"]
    assert _get(server, "/3/Capabilities")["capabilities"] is not None
    eps = _get(server, "/3/Metadata/endpoints")
    assert eps["count"] >= 60
    _post(server, "/3/NodePersistentStorage/cat1/k1", {"value": "v1"})
    assert _get(server,
                "/3/NodePersistentStorage/cat1/k1")["value"] == "v1"
    assert _get(server,
                "/3/NodePersistentStorage/cat1")["entries"]
    assert _post(server, "/3/LogAndEcho",
                 {"message": "hello"})["message"] == "hello"
    assert _post(server, "/3/GarbageCollect", {})["status"] == "done"


def test_route_family_count_vs_reference():
    """Route-breadth gate (VERDICT r4 #7): >= 60 registered route
    patterns vs the reference's ~150 (water/api/RequestServer.java:56)."""
    from h2o3_tpu.api.server import H2OServer, _Handler
    s = H2OServer(port=0)
    try:
        n = (len(_Handler.routes_get) + len(_Handler.routes_post)
             + len(_Handler.routes_delete))
        assert n >= 60, f"only {n} route patterns registered"
    finally:
        s.httpd.server_close()
