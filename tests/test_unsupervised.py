"""KMeans / PCA / SVD / NaiveBayes / Quantile / Isotonic tests.

Mirrors the reference's pyunit strategy (h2o-py/tests/testdir_algos/{kmeans,
pca,naivebayes,isotonicregression}, testdir_misc/pyunit_quantile.py): golden
comparisons against numpy closed forms on synthetic data.
"""

import numpy as np
import pytest

import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.models import (KMeans, PCA, SVD, NaiveBayes, Quantile,
                             IsotonicRegression, quantile)


def _blobs(rng, n_per=500, centers=((0, 0), (8, 8), (-8, 8)), scale=0.8):
    pts, lab = [], []
    for i, c in enumerate(centers):
        pts.append(rng.normal(size=(n_per, 2)) * scale + np.asarray(c))
        lab += [i] * n_per
    X = np.concatenate(pts)
    perm = rng.permutation(len(X))
    return X[perm], np.asarray(lab)[perm]


# ------------------------------------------------------------------ KMeans
def test_kmeans_recovers_blobs(cl, rng):
    X, lab = _blobs(rng)
    fr = Frame.from_numpy({"x": X[:, 0], "y": X[:, 1]})
    m = KMeans(k=3, standardize=False, seed=42, max_iterations=20).train(fr)
    centers = np.sort(np.round(m.output["centers"]).astype(int), axis=0)
    assert centers.tolist() == [[-8, 0], [0, 8], [8, 8]]
    tm = m.training_metrics
    assert tm.tot_withinss < 0.05 * tm.totss
    assert abs(tm.totss - (tm.tot_withinss + tm.betweenss)) < 1e-6
    assert sorted(tm.size) == [500, 500, 500]
    pred = m.predict(fr)
    labels = pred.vecs[0].to_numpy()
    # each true blob maps to exactly one predicted cluster
    for i in range(3):
        assert len(np.unique(labels[lab == i])) == 1


def test_kmeans_init_methods(cl, rng):
    X, _ = _blobs(rng, n_per=200)
    fr = Frame.from_numpy({"x": X[:, 0], "y": X[:, 1]})
    for init in ("random", "plus_plus", "furthest"):
        m = KMeans(k=3, init=init, seed=7).train(fr)
        assert m.output["k"] == 3
    user = np.array([[0.0, 0.0], [8.0, 8.0], [-8.0, 8.0]])
    m = KMeans(k=3, init="user", user_points=user, standardize=False).train(fr)
    assert m.training_metrics.tot_withinss < 0.05 * m.training_metrics.totss


def test_kmeans_estimate_k(cl, rng):
    X, _ = _blobs(rng, n_per=300)
    fr = Frame.from_numpy({"x": X[:, 0], "y": X[:, 1]})
    m = KMeans(k=8, estimate_k=True, seed=3, standardize=False).train(fr)
    assert m.output["k"] == 3


# -------------------------------------------------------------------- PCA
def test_pca_matches_numpy_svd(cl, rng):
    n, p = 2000, 6
    base = rng.normal(size=(n, 3))
    X = np.concatenate([base, base @ rng.normal(size=(3, 3)) * 0.5], axis=1)
    X += 0.01 * rng.normal(size=X.shape)
    fr = Frame.from_numpy({f"c{i}": X[:, i] for i in range(p)})
    m = PCA(k=3, transform="demean", pca_method="gram_s_v_d").train(fr)
    Xc = X - X.mean(axis=0)
    _, s, Vt = np.linalg.svd(Xc, full_matrices=False)
    sd_true = s[:3] / np.sqrt(n - 1)
    np.testing.assert_allclose(m.output["std_deviation"], sd_true, rtol=1e-3)
    for j in range(3):
        dot = abs(np.dot(m.output["eigenvectors"][:, j], Vt[j]))
        assert dot > 0.999, (j, dot)
    # projection roundtrip
    scores = m.predict(fr)
    Z = np.stack([v.to_numpy() for v in scores.vecs], axis=1)
    Z_true = Xc @ Vt[:3].T
    for j in range(3):
        c = np.corrcoef(Z[:, j], Z_true[:, j])[0, 1]
        assert abs(c) > 0.999


def test_pca_methods_agree(cl, rng):
    X = rng.normal(size=(1500, 5)) @ np.diag([5, 3, 2, 0.5, 0.1])
    fr = Frame.from_numpy({f"c{i}": X[:, i] for i in range(5)})
    ms = {meth: PCA(k=2, transform="demean", pca_method=meth, seed=1).train(fr)
          for meth in ("gram_s_v_d", "power", "randomized")}
    ref = ms["gram_s_v_d"].output["std_deviation"]
    for meth in ("power", "randomized"):
        np.testing.assert_allclose(ms[meth].output["std_deviation"], ref,
                                   rtol=1e-2)


def test_svd(cl, rng):
    X = rng.normal(size=(800, 4))
    fr = Frame.from_numpy({f"c{i}": X[:, i] for i in range(4)})
    m = SVD(nv=4, transform="none").train(fr)
    s_true = np.linalg.svd(X, compute_uv=False)
    np.testing.assert_allclose(m.output["d"], s_true, rtol=1e-3)


# ------------------------------------------------------------- NaiveBayes
def test_naivebayes_gaussian(cl, rng):
    n = 3000
    y = rng.integers(0, 2, n)
    x0 = rng.normal(size=n) + 2.0 * y
    x1 = rng.normal(size=n) - 1.5 * y
    cat = np.where(rng.random(n) < 0.2 + 0.6 * y, "a", "b")
    fr = Frame.from_numpy({
        "x0": x0, "x1": x1, "cat": cat.astype(object),
        "y": np.array(["no", "yes"], dtype=object)[y]})
    m = NaiveBayes(response_column="y", laplace=1.0).train(fr)
    assert m.training_metrics.auc > 0.9
    np.testing.assert_allclose(m.output["apriori"],
                               [np.mean(y == 0), np.mean(y == 1)], atol=0.02)
    pred = m.predict(fr)
    acc = np.mean(pred.vecs[0].decoded() == np.where(y, "yes", "no"))
    assert acc > 0.85


# ---------------------------------------------------------------- Quantile
def test_quantile_matches_numpy(cl, rng):
    x = rng.normal(size=5000)
    fr = Frame.from_numpy({"x": x})
    probs = (0.1, 0.25, 0.5, 0.75, 0.9)
    q = quantile(fr, probs=probs)["x"]
    q_true = np.quantile(x, probs)          # linear interpolation == type 7
    np.testing.assert_allclose(q, q_true, atol=1e-6)


def test_quantile_methods_and_nas(cl, rng):
    x = np.arange(10, dtype=np.float64)
    x_na = np.concatenate([x, [np.nan] * 5])
    fr = Frame.from_numpy({"x": x_na})
    m = Quantile(probs=(0.5,), combine_method="low").train(fr)
    assert m.output["quantiles"]["x"][0] == 4.0
    m = Quantile(probs=(0.5,), combine_method="high").train(fr)
    assert m.output["quantiles"]["x"][0] == 5.0
    m = Quantile(probs=(0.5,), combine_method="average").train(fr)
    assert m.output["quantiles"]["x"][0] == 4.5


def test_quantile_weighted(cl, rng):
    x = np.array([1.0, 2.0, 3.0, 4.0])
    w = np.array([1.0, 1.0, 2.0, 0.0])
    fr = Frame.from_numpy({"x": x, "w": w})
    m = Quantile(probs=(0.5,), weights_column="w",
                 combine_method="low").train(fr)
    # cumweights [1,2,4]@x=[1,2,3]; target 2 -> boundary at x=2
    assert m.output["quantiles"]["x"][0] == 2.0


# ---------------------------------------------------------------- Isotonic
def test_isotonic_monotone_and_accurate(cl, rng):
    n = 4000
    x = rng.uniform(-3, 3, n)
    y = np.tanh(x) + 0.3 * rng.normal(size=n)
    fr = Frame.from_numpy({"x": x, "y": y})
    m = IsotonicRegression(response_column="y").train(fr)
    ty = m.output["thresholds_y"]
    assert np.all(np.diff(ty) >= -1e-12)
    pred = m.predict(fr).vecs[0].to_numpy()
    ok = ~np.isnan(pred)
    rmse = np.sqrt(np.mean((pred[ok] - np.tanh(x[ok])) ** 2))
    assert rmse < 0.1
    assert m.training_metrics.rmse < 0.35


def test_isotonic_out_of_bounds(cl, rng):
    x = np.array([0.0, 1.0, 2.0, 3.0])
    y = np.array([0.0, 1.0, 2.0, 3.0])
    m = IsotonicRegression(response_column="y").train(
        Frame.from_numpy({"x": x, "y": y}))
    test = Frame.from_numpy({"x": np.array([-1.0, 1.5, 9.0])})
    p_na = m.predict(test).vecs[0].to_numpy()
    assert np.isnan(p_na[0]) and np.isnan(p_na[2]) and abs(p_na[1] - 1.5) < 1e-9
    m.params.out_of_bounds = "clip"
    p_clip = m.predict(test).vecs[0].to_numpy()
    assert p_clip[0] == 0.0 and p_clip[2] == 3.0


def test_model_save_load_kmeans(cl, rng, tmp_path):
    X, _ = _blobs(rng, n_per=100)
    fr = Frame.from_numpy({"x": X[:, 0], "y": X[:, 1]})
    m = KMeans(k=3, seed=1).train(fr)
    path = m.save(str(tmp_path / "km.bin"))
    m2 = h2o3_tpu.models.Model.load(path)
    np.testing.assert_allclose(m2.output["centers"], m.output["centers"])
    p1 = m.predict(fr).vecs[0].to_numpy()
    p2 = m2.predict(fr).vecs[0].to_numpy()
    assert np.array_equal(p1, p2)
