"""Rehearsal tests for bench.py's robustness contract (VERDICT r03 weak #1).

The driver runs `python bench.py` under an outer wall clock; the r03 round was
lost because the orchestrator's per-attempt timeouts summed past that clock.
These tests rehearse the failure modes locally and assert the contract: one
JSON record on stdout, rc=0, inside the configured total budget.
"""

import json
import os
import subprocess
import sys
import time

import pytest

BENCH = os.path.join(os.path.dirname(__file__), "..", "bench.py")


def _run(env_overrides, outer_timeout):
    env = os.environ.copy()
    env.update(env_overrides)
    t0 = time.time()
    r = subprocess.run([sys.executable, BENCH], env=env,
                       timeout=outer_timeout, capture_output=True, text=True)
    return r, time.time() - t0


def _record(r):
    assert r.returncode == 0, r.stderr[-2000:]
    recs = [json.loads(ln) for ln in r.stdout.strip().splitlines()
            if ln.startswith("{")]
    assert len(recs) == 1
    assert recs[0]["metric"] == "xgboost_trees_per_sec_airlines10m_shape"
    return recs[0]


@pytest.mark.slow
def test_hung_primary_still_lands_record():
    """Primary worker hangs forever -> orchestrator kills it at the budget
    split, CPU fallback emits the record, total stays under the budget."""
    r, wall = _run({
        "H2O3_BENCH_TEST_HANG": "1",            # primary sleeps 10,000 s
        "H2O3_BENCH_TOTAL_BUDGET": "420",
        "H2O3_BENCH_FALLBACK_RESERVE": "390",
        "H2O3_BENCH_CPU_ROWS": "20000",
        "H2O3_BENCH_CPU_TREES": "3",
    }, outer_timeout=420)
    rec = _record(r)
    assert wall < 420
    assert rec["extra"]["platform"] == "cpu"
    assert rec["extra"]["secondaries"] == "skipped"
    assert "primary_attempt" in rec["extra"]["fallback_errors"]
    assert rec["value"] > 0


@pytest.mark.slow
def test_everything_dead_emits_zero_record():
    """Even when both attempts die instantly, a record lands rc=0."""
    r, wall = _run({
        "H2O3_BENCH_TEST_HANG": "1",
        "H2O3_BENCH_TOTAL_BUDGET": "70",        # reserve clamps to budget-60
        "H2O3_BENCH_FALLBACK_RESERVE": "600",
        "H2O3_BENCH_CPU_ROWS": "100000000",     # fallback can't finish in 60s
        "H2O3_BENCH_CPU_TREES": "50",
    }, outer_timeout=300)
    rec = _record(r)
    assert rec["value"] == 0.0
    assert rec["extra"]["platform"] == "none"
    assert "cpu_attempt" in rec["extra"]["fallback_errors"]


def test_budget_arithmetic_is_total_not_per_attempt():
    """Static check: the orchestrator derives both attempt timeouts from one
    deadline (the r03 bug was per-attempt 2700 s x 2)."""
    src = open(BENCH).read()
    assert "H2O3_BENCH_TOTAL_BUDGET" in src
    assert "deadline - time.time()" in src
    assert "H2O3_BENCH_TIMEOUT" not in src      # the old per-attempt knob


# -------------------------------------------------------- bench_gate tests

def _load_bench_gate():
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "bench_gate.py")
    spec = importlib.util.spec_from_file_location("bench_gate", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench_gate = _load_bench_gate()


def _write(tmp_path, name, record):
    p = tmp_path / name
    p.write_text(json.dumps(record))
    return str(p)


def _record_json(tps, gbm_sec, **extra):
    return {"metric": "trees_per_sec_bench", "value": tps,
            "extra": {"gbm_sec": gbm_sec, "rows": 1000, **extra}}


def _gate(tmp_path, candidate, baselines):
    out = str(tmp_path / "report.txt")
    argv = [candidate, "--out", out]
    for b in baselines:
        argv += ["--baseline", b]
    rc = bench_gate.main(argv)
    report = open(out).read() if os.path.exists(out) else ""
    return rc, report


def test_gate_improvement_passes(tmp_path):
    base = _write(tmp_path, "BENCH_r01.json", _record_json(100.0, 10.0))
    cand = _write(tmp_path, "cand.json", _record_json(150.0, 7.0))
    rc, report = _gate(tmp_path, cand, [base])
    assert rc == 0
    assert "0 regression(s)" in report


def test_gate_in_tolerance_noise_passes(tmp_path):
    """-5% rate / +5% wall sits inside the default 10% band."""
    base = _write(tmp_path, "BENCH_r01.json", _record_json(100.0, 10.0))
    cand = _write(tmp_path, "cand.json", _record_json(95.0, 10.5))
    rc, report = _gate(tmp_path, cand, [base])
    assert rc == 0


def test_gate_regression_fails(tmp_path):
    base = _write(tmp_path, "BENCH_r01.json", _record_json(100.0, 10.0))
    cand = _write(tmp_path, "cand.json", _record_json(50.0, 30.0))
    rc, report = _gate(tmp_path, cand, [base])
    assert rc == 1
    assert "regress" in report
    # both the rate drop and the wall-clock blow-up are flagged
    assert "trees_per_sec_bench" in report and "gbm_sec" in report


def test_gate_new_metric_passes_as_new(tmp_path):
    base = _write(tmp_path, "BENCH_r01.json", _record_json(100.0, 10.0))
    cand = _write(tmp_path, "cand.json",
                  _record_json(100.0, 10.0, glm_sec=3.0))
    rc, report = _gate(tmp_path, cand, [base])
    assert rc == 0
    assert "1 new" in report


def test_gate_skips_unreadable_baseline(tmp_path, capsys):
    """A corrupt baseline round drops out with a note; the rest gate."""
    bad = _write(tmp_path, "BENCH_r01.json", {})
    (tmp_path / "BENCH_r02.json").write_text("not json {")
    good = _write(tmp_path, "BENCH_r03.json", _record_json(100.0, 10.0))
    cand = _write(tmp_path, "cand.json", _record_json(100.0, 10.0))
    rc, _ = _gate(tmp_path, cand,
                  [bad, str(tmp_path / "BENCH_r02.json"), good])
    assert rc == 0
    assert "skipping unreadable baseline" in capsys.readouterr().err


def test_gate_no_baselines_is_config_error(tmp_path):
    cand = _write(tmp_path, "cand.json", _record_json(100.0, 10.0))
    rc = bench_gate.main([cand, "--baseline",
                          str(tmp_path / "missing.json"),
                          "--out", str(tmp_path / "r.txt")])
    assert rc == 2


def test_gate_references_latest_round_not_alltime_best(tmp_path):
    """The r04/r05 scenario: an older round's metric beat the latest
    because the workload shape changed; a candidate equal to the latest
    round must still pass (best is context only)."""
    r04 = _write(tmp_path, "BENCH_r04.json", _record_json(500.0, 1.7))
    r05 = _write(tmp_path, "BENCH_r05.json", _record_json(100.0, 8.3))
    cand = _write(tmp_path, "cand.json", _record_json(100.0, 8.3))
    rc, report = _gate(tmp_path, cand, [r04, r05])
    assert rc == 0
    assert "500.000" in report               # all-time best shown as context
    rounds = bench_gate.load_baselines([r04, r05])
    rows = {r["name"]: r for r in bench_gate.evaluate(
        bench_gate.flatten(_record_json(100.0, 8.3)), rounds)}
    tps = rows["trees_per_sec_bench"]
    assert tps["status"] == "pass"
    assert tps["ref_file"] == "BENCH_r05.json"   # gated vs the latest round
    assert tps["best_file"] == "BENCH_r04.json"  # best is context only


def test_gate_flattens_multichip_entries(tmp_path):
    rec = {"bench": "multichip", "entries": [
        {"n_devices": 8, "trees_per_sec": 10.0, "wall_s": 5.0},
        {"n_devices": 32, "trees_per_sec": 30.0, "wall_s": 6.0}],
        "scaling_8_to_32": 3.0}
    flat = bench_gate.flatten(rec)
    assert flat == {"multichip_trees_per_sec_8dev": 10.0,
                    "multichip_wall_s_8dev": 5.0,
                    "multichip_trees_per_sec_32dev": 30.0,
                    "multichip_wall_s_32dev": 6.0,
                    "scaling_8_to_32": 3.0}
    base = _write(tmp_path, "MULTICHIP_r01.json", rec)
    worse = dict(rec, scaling_8_to_32=2.0)   # -33% > the 15% band
    cand = _write(tmp_path, "cand.json", worse)
    rc, report = _gate(tmp_path, cand, [base])
    assert rc == 1 and "scaling_8_to_32" in report


def test_gate_direction_classifier():
    assert bench_gate.classify("trees_per_sec_x") == "higher"
    assert bench_gate.classify("scaling_8_to_32") == "higher"
    assert bench_gate.classify("glm_higgs_shape_sec") == "lower"
    assert bench_gate.classify("bench_wall_s") == "lower"
    assert bench_gate.classify("rows") == "info"
    assert bench_gate.classify("xgboost_compile_s") == "info"
    assert bench_gate.classify("gbm_higgs_steady_s") == "info"
    assert bench_gate.classify("compiles_total") == "info"
    # serving metrics gate from their first recorded round
    assert bench_gate.classify("serve_p50_ms") == "lower"
    assert bench_gate.classify("serve_p99_ms") == "lower"
    assert bench_gate.classify("serve_latency_seconds") == "lower"
    assert bench_gate.classify("warmup_seconds") == "lower"
    assert bench_gate.classify("serve_qps") == "higher"
    # count-style metrics: dispatch/launch/recompile counts gate
    # lower-better from their first recorded round
    assert bench_gate.classify("treescan_launches_per_tree_scan") == "lower"
    assert bench_gate.classify("treescan_launches_per_tree_level") == "lower"
    assert bench_gate.classify("hist_dispatch_total") == "lower"
    assert bench_gate.classify("recompile_count") == "lower"
    # ... but the ledger echo compiles_total stays informational
    assert bench_gate.classify("compiles_total") == "info"
    # speedup ratios are higher-better
    assert bench_gate.classify("treescan_scan_vs_level_speedup") == "higher"
    assert bench_gate.classify("serve_packed_speedup_vs_numpy") == "higher"


def test_gate_count_metric_regression(tmp_path):
    """A launch-count blow-up (the treescan dispatch pin) regresses; a
    count that shrinks or holds passes."""
    rec = {"metric": "serve_qps", "value": 2000.0,
           "extra": {"treescan_launches_per_tree_scan": 2,
                     "serve_qps": 2000.0}}
    base = _write(tmp_path, "BENCH_r01.json", rec)
    worse = {"metric": "serve_qps", "value": 2000.0,
             "extra": {"treescan_launches_per_tree_scan": 20,
                       "serve_qps": 2000.0}}
    cand = _write(tmp_path, "cand.json", worse)
    rc, report = _gate(tmp_path, cand, [base])
    assert rc == 1 and "treescan_launches_per_tree_scan" in report
    same = {"metric": "serve_qps", "value": 2000.0,
            "extra": {"treescan_launches_per_tree_scan": 2,
                      "serve_qps": 2000.0}}
    cand2 = _write(tmp_path, "cand2.json", same)
    rc, _ = _gate(tmp_path, cand2, [base])
    assert rc == 0


def test_gate_serving_latency_regression(tmp_path):
    rec = {"metric": "serve_qps", "value": 2000.0,
           "extra": {"serve_p50_ms": 2.0, "serve_p99_ms": 5.0,
                     "serve_qps": 2000.0}}
    base = _write(tmp_path, "BENCH_r01.json", rec)
    worse = {"metric": "serve_qps", "value": 2000.0,
             "extra": {"serve_p50_ms": 4.0, "serve_p99_ms": 5.0,
                       "serve_qps": 2000.0}}
    cand = _write(tmp_path, "cand.json", worse)
    rc, report = _gate(tmp_path, cand, [base])
    assert rc == 1 and "serve_p50_ms" in report


def test_gate_absolute_floor_gates_new_metric(tmp_path):
    """autotune_vs_best carries an absolute 0.97 floor: it is GATED even
    on its first round (normal new metrics pass ungated), and a value
    below the floor regresses regardless of history."""
    base = _write(tmp_path, "BENCH_r01.json", _record_json(100.0, 10.0))
    good = _write(tmp_path, "cand.json",
                  _record_json(100.0, 10.0, autotune_vs_best=0.99))
    rc, report = _gate(tmp_path, good, [base])
    assert rc == 0
    assert "absolute floor" in report

    bad = _write(tmp_path, "cand2.json",
                 _record_json(100.0, 10.0, autotune_vs_best=0.90))
    rc, report = _gate(tmp_path, bad, [base])
    assert rc == 1
    assert "below absolute floor" in report


def test_gate_absolute_floor_beats_tolerance_band(tmp_path):
    """A bad prior round cannot drag the floor down: within-tolerance of
    a sub-floor baseline still regresses."""
    base = _write(tmp_path, "BENCH_r01.json",
                  _record_json(100.0, 10.0, autotune_vs_best=0.92))
    cand = _write(tmp_path, "cand.json",
                  _record_json(100.0, 10.0, autotune_vs_best=0.93))
    rc, report = _gate(tmp_path, cand, [base])
    assert rc == 1
    assert "below absolute floor" in report
