"""Rehearsal tests for bench.py's robustness contract (VERDICT r03 weak #1).

The driver runs `python bench.py` under an outer wall clock; the r03 round was
lost because the orchestrator's per-attempt timeouts summed past that clock.
These tests rehearse the failure modes locally and assert the contract: one
JSON record on stdout, rc=0, inside the configured total budget.
"""

import json
import os
import subprocess
import sys
import time

import pytest

BENCH = os.path.join(os.path.dirname(__file__), "..", "bench.py")


def _run(env_overrides, outer_timeout):
    env = os.environ.copy()
    env.update(env_overrides)
    t0 = time.time()
    r = subprocess.run([sys.executable, BENCH], env=env,
                       timeout=outer_timeout, capture_output=True, text=True)
    return r, time.time() - t0


def _record(r):
    assert r.returncode == 0, r.stderr[-2000:]
    recs = [json.loads(ln) for ln in r.stdout.strip().splitlines()
            if ln.startswith("{")]
    assert len(recs) == 1
    assert recs[0]["metric"] == "xgboost_trees_per_sec_airlines10m_shape"
    return recs[0]


@pytest.mark.slow
def test_hung_primary_still_lands_record():
    """Primary worker hangs forever -> orchestrator kills it at the budget
    split, CPU fallback emits the record, total stays under the budget."""
    r, wall = _run({
        "H2O3_BENCH_TEST_HANG": "1",            # primary sleeps 10,000 s
        "H2O3_BENCH_TOTAL_BUDGET": "420",
        "H2O3_BENCH_FALLBACK_RESERVE": "390",
        "H2O3_BENCH_CPU_ROWS": "20000",
        "H2O3_BENCH_CPU_TREES": "3",
    }, outer_timeout=420)
    rec = _record(r)
    assert wall < 420
    assert rec["extra"]["platform"] == "cpu"
    assert rec["extra"]["secondaries"] == "skipped"
    assert "primary_attempt" in rec["extra"]["fallback_errors"]
    assert rec["value"] > 0


@pytest.mark.slow
def test_everything_dead_emits_zero_record():
    """Even when both attempts die instantly, a record lands rc=0."""
    r, wall = _run({
        "H2O3_BENCH_TEST_HANG": "1",
        "H2O3_BENCH_TOTAL_BUDGET": "70",        # reserve clamps to budget-60
        "H2O3_BENCH_FALLBACK_RESERVE": "600",
        "H2O3_BENCH_CPU_ROWS": "100000000",     # fallback can't finish in 60s
        "H2O3_BENCH_CPU_TREES": "50",
    }, outer_timeout=300)
    rec = _record(r)
    assert rec["value"] == 0.0
    assert rec["extra"]["platform"] == "none"
    assert "cpu_attempt" in rec["extra"]["fallback_errors"]


def test_budget_arithmetic_is_total_not_per_attempt():
    """Static check: the orchestrator derives both attempt timeouts from one
    deadline (the r03 bug was per-attempt 2700 s x 2)."""
    src = open(BENCH).read()
    assert "H2O3_BENCH_TOTAL_BUDGET" in src
    assert "deadline - time.time()" in src
    assert "H2O3_BENCH_TIMEOUT" not in src      # the old per-attempt knob
