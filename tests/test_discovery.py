"""Pod discovery + coordinator-restart recovery (VERDICT r03 next-step #9).

Discovery is the H2OCluster.java DNS-clouding analog
(runtime/discovery.py); the restart test kills the "coordinator" process
mid-train and proves a FRESH process re-imports the journaled frame from
its source URI and retrains — no manual re-import (Recovery.java:72-81).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from h2o3_tpu.runtime import discovery


# ------------------------------------------------------------- discovery

def test_indexed_mode(monkeypatch):
    """Coordinator stem comes from the POD hostname (<workload>-<ordinal>),
    not from the service name — job and service are usually named
    differently (deploy/k8s.yaml: job h2o3-tpu, service
    h2o3-tpu-coordinator)."""
    monkeypatch.setenv("H2O3_TPU_POD_INDEX", "3")
    monkeypatch.setattr(socket, "gethostname", lambda: "h2o3-job-3")
    coord, n, pid = discovery.discover("h2o3-svc.ns.svc", expected=4)
    assert coord == "h2o3-job-0.h2o3-svc.ns.svc:8476"
    assert (n, pid) == (4, 3)


def test_indexed_mode_stem_override(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_POD_INDEX", "1")
    monkeypatch.setenv("H2O3_TPU_POD_STEM", "mypods")
    coord, n, pid = discovery.discover("svc", expected=2)
    assert coord == "mypods-0.svc:8476"


def test_indexed_mode_bad_hostname(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_POD_INDEX", "2")
    monkeypatch.delenv("H2O3_TPU_POD_STEM", raising=False)
    monkeypatch.setattr(socket, "gethostname", lambda: "not-ordinal")
    with pytest.raises(RuntimeError, match="H2O3_TPU_POD_STEM"):
        discovery.discover("svc", expected=4)


def test_indexed_mode_needs_size(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_POD_INDEX", "0")
    monkeypatch.delenv("H2O3_TPU_CLUSTER_SIZE", raising=False)
    with pytest.raises(ValueError, match="cluster size"):
        discovery.discover("svc")


def test_dns_mode_localhost(monkeypatch):
    """localhost resolves to 127.0.0.1, which is always an own-address —
    a 1-pod cloud via real DNS."""
    monkeypatch.delenv("H2O3_TPU_POD_INDEX", raising=False)
    coord, n, pid = discovery.discover("localhost", port=9999, expected=1,
                                       timeout_s=10)
    assert coord == "127.0.0.1:9999"
    assert (n, pid) == (1, 0)


def test_dns_mode_rank_is_position(monkeypatch):
    """Rank = index of own address among the sorted records."""
    monkeypatch.delenv("H2O3_TPU_POD_INDEX", raising=False)
    monkeypatch.setattr(discovery, "resolve_service",
                        lambda *a, **k: ["10.0.0.1", "10.0.0.7", "10.0.0.9"])
    monkeypatch.setattr(discovery, "_own_addresses",
                        lambda: {"10.0.0.7"})
    coord, n, pid = discovery.discover("svc", port=1234)
    assert coord == "10.0.0.1:1234"
    assert (n, pid) == (3, 1)


def test_dns_mode_not_a_member(monkeypatch):
    monkeypatch.delenv("H2O3_TPU_POD_INDEX", raising=False)
    monkeypatch.setattr(discovery, "resolve_service",
                        lambda *a, **k: ["10.0.0.1"])
    monkeypatch.setattr(discovery, "_own_addresses", lambda: {"10.9.9.9"})
    with pytest.raises(RuntimeError, match="none of this host"):
        discovery.discover("svc")


def test_resolve_timeout():
    with pytest.raises(TimeoutError):
        discovery.resolve_service("no-such-host-h2o3.invalid",
                                  expected=2, timeout_s=3, poll_s=0.5)


# ------------------------------------- coordinator restart, frame re-import

_TRAIN = """
import os, sys
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax; jax.config.update("jax_platforms", "cpu")
import h2o3_tpu
h2o3_tpu.init()
fr = h2o3_tpu.import_file(sys.argv[1], destination_frame="air")
from h2o3_tpu.models import GBM
from h2o3_tpu.runtime import recovery
b = GBM(response_column="y", ntrees=3, max_depth=3, seed=1)
# journal the job as train() would, then die before finishing (the
# coordinator-crash moment: entry stays status=running)
uri = recovery.journal_start(b, fr, job=None, params=b.params)
assert uri, "journal entry not written"
print("journaled", uri, flush=True)
os._exit(9)
"""

_RESUME = """
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax; jax.config.update("jax_platforms", "cpu")
import h2o3_tpu
h2o3_tpu.init()
from h2o3_tpu.runtime import recovery, dkv
assert dkv.get("air") is None            # fresh process: no frame
keys = recovery.resume()
assert len(keys) == 1, keys
m = dkv.get(keys[0])
assert m is not None
fr = dkv.get("air")
assert fr is not None and fr.nrows == 160   # auto re-imported
p = m.predict(fr)
assert p.nrows == 160
print("RESUMED_OK", keys[0], flush=True)
"""


@pytest.mark.slow
def test_coordinator_restart_reimports_and_retrains(tmp_path):
    rng = np.random.default_rng(0)
    csv = tmp_path / "air.csv"
    rows = ["x1,x2,y"]
    for i in range(160):
        rows.append(f"{rng.normal():.4f},{rng.normal():.4f},"
                    f"{'Y' if rng.random() < 0.5 else 'N'}")
    csv.write_text("\n".join(rows))
    env = dict(os.environ,
               H2O3_TPU_RECOVERY_DIR=str(tmp_path / "recovery"),
               JAX_PLATFORMS="cpu")
    (tmp_path / "recovery").mkdir()
    r1 = subprocess.run([sys.executable, "-c", _TRAIN, str(csv)], env=env,
                        capture_output=True, text=True, timeout=300)
    assert r1.returncode == 9, r1.stderr[-1500:]       # died mid-train
    assert "journaled" in r1.stdout
    r2 = subprocess.run([sys.executable, "-c", _RESUME], env=env,
                        capture_output=True, text=True, timeout=600)
    assert r2.returncode == 0, (r2.stdout[-800:], r2.stderr[-1500:])
    assert "RESUMED_OK" in r2.stdout


# ------------------------------------------------- assisted clustering

def test_flatfile_clouding(tmp_path, monkeypatch):
    """h2o-clustering analog: poll a member flatfile, derive the triple."""
    ff = tmp_path / "flatfile"
    ff.write_text("# members\n10.0.0.9:8476\n10.0.0.2:8476\n")
    monkeypatch.setattr(discovery, "_own_addresses",
                        lambda: {"10.0.0.9"})
    coord, n, pid = discovery.from_flatfile(str(ff), expected=2,
                                            timeout_s=10)
    assert coord == "10.0.0.2:8476"          # sorted; lowest = coordinator
    assert (n, pid) == (2, 1)


def test_flatfile_waits_for_members(tmp_path, monkeypatch):
    """The file is polled until the expected member count appears —
    the 'assisted' part: an external agent writes it after boot."""
    import threading
    import time as _t
    ff = tmp_path / "flatfile"
    ff.write_text("10.0.0.2:8476\n")
    monkeypatch.setattr(discovery, "_own_addresses",
                        lambda: {"10.0.0.2"})

    def agent():
        _t.sleep(1.0)
        ff.write_text("10.0.0.2:8476\n10.0.0.7:8476\n")

    t = threading.Thread(target=agent)
    t.start()
    coord, n, pid = discovery.from_flatfile(str(ff), expected=2,
                                            timeout_s=30, poll_s=0.2)
    t.join()
    assert (coord, n, pid) == ("10.0.0.2:8476", 2, 0)


def test_flatfile_timeout(tmp_path):
    with pytest.raises(TimeoutError):
        discovery.from_flatfile(str(tmp_path / "nope"), expected=2,
                                timeout_s=2, poll_s=0.5)


def test_flatfile_indented_comment_not_a_member(tmp_path, monkeypatch):
    ff = tmp_path / "flatfile"
    ff.write_text("  # operator note\n10.0.0.2:8476\n")
    monkeypatch.setattr(discovery, "_own_addresses",
                        lambda: {"10.0.0.2"})
    coord, n, pid = discovery.from_flatfile(str(ff), expected=1,
                                            timeout_s=10, poll_s=0.2)
    assert (coord, n, pid) == ("10.0.0.2:8476", 1, 0)


def test_flatfile_multi_process_per_host_ranks_by_port(tmp_path,
                                                      monkeypatch):
    """host:port layout with two launchers on one host: the rank is the
    member carrying this process's own port."""
    ff = tmp_path / "flatfile"
    ff.write_text("10.0.0.2:8476\n10.0.0.2:8477\n")
    monkeypatch.setattr(discovery, "_own_addresses",
                        lambda: {"10.0.0.2"})
    coord, n, pid = discovery.from_flatfile(str(ff), expected=2,
                                            timeout_s=10, poll_s=0.2,
                                            own_port=8477)
    assert (coord, n, pid) == ("10.0.0.2:8476", 2, 1)
    with pytest.raises(RuntimeError, match="disambiguate"):
        discovery.from_flatfile(str(ff), expected=2, timeout_s=10,
                                poll_s=0.2)
