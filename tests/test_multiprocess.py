"""Multi-process distributed runtime test — the multiNodeUtils.sh analog.

The reference's core distributed test pattern (SURVEY.md §4,
``scripts/multiNodeUtils.sh:21-26``) spawns real JVMs on localhost and runs
jobs across them.  Here: N real Python processes each with 4 virtual CPU
devices run ``jax.distributed.initialize`` against a localhost coordinator,
boot one 8-device global mesh, and execute the same SPMD training programs —
XLA collectives cross the process boundary exactly as they would cross
ICI/DCN on a TPU pod, and the coordinator DKV service carries the control
plane.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
coord = sys.argv[3]
out_path = sys.argv[4]

import jax
jax.config.update("jax_platforms", "cpu")
# initialize BEFORE anything can touch the XLA backend
jax.distributed.initialize(coordinator_address=coord, num_processes=nproc,
                           process_id=pid)

import numpy as np
import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.frame.vec import T_CAT
from h2o3_tpu.models import GBM, GLM
from h2o3_tpu.runtime import dkv

cl = h2o3_tpu.init(coordinator=coord, num_processes=nproc, process_id=pid)
assert jax.process_count() == nproc, jax.process_count()
assert cl.n_devices == 4 * nproc, cl.n_devices

# identical data everywhere — SPMD: every process executes the same program
rng = np.random.default_rng(0)
n = 4000
x1 = rng.normal(size=n).astype(np.float32)
x2 = rng.normal(size=n).astype(np.float32)
c1 = rng.integers(0, 4, n)
logit = 1.2 * x1 - 0.8 * x2 + 0.5 * (c1 == 2)
y = rng.random(n) < 1 / (1 + np.exp(-logit))
fr = Frame.from_numpy(
    {"x1": x1, "x2": x2, "c1": c1,
     "y": np.where(y, "YES", "NO").astype(object)},
    types={"c1": T_CAT}, domains={"c1": [str(i) for i in range(4)]})

# rollups ride a cross-process psum
mean_x1 = fr.vec("x1").mean()

glm = GLM(response_column="y", family="binomial", lambda_=0.0,
          seed=1).train(fr)
glm_auc = glm.training_metrics.describe()["auc"]

gbm = GBM(response_column="y", ntrees=4, max_depth=3, nbins=16,
          seed=1).train(fr)
gbm_auc = gbm.training_metrics.describe()["auc"]

# control plane: each process publishes a result; all read each other's
dkv.put(f"mp_result_{pid}", {"auc": float(glm_auc)})
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("dkv_published")
peers = {}
for other in range(nproc):
    v = dkv.get(f"mp_result_{other}")
    peers[other] = None if v is None else v["auc"]

with open(out_path, "w") as f:
    json.dump({"pid": pid, "mean_x1": float(mean_x1),
               "glm_auc": float(glm_auc), "gbm_auc": float(gbm_auc),
               "peers": peers}, f)
"""


WORKER_PARSE = r"""
import glob, json, os, sys

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
coord = sys.argv[3]
out_path = sys.argv[4]
data_glob = sys.argv[5]

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=coord, num_processes=nproc,
                           process_id=pid)

import numpy as np
import h2o3_tpu
from h2o3_tpu.frame import dparse
from h2o3_tpu.models import GLM

cl = h2o3_tpu.init(coordinator=coord, num_processes=nproc, process_id=pid)

fr = h2o3_tpu.import_file(data_glob, destination_frame="airlines_mp")
mean_num = fr.vec("num").mean()                 # rides a cross-process psum
span_stats = dict(dparse.last_stats)
glm = GLM(response_column="resp", family="binomial", lambda_=0.0,
          seed=1).train(fr)
auc = glm.training_metrics.describe()["auc"]

cat_codes = fr.vec("cat").to_numpy()            # process_allgather round-trip

# quoted-newline file: the byte split is unsafe -> replicated fallback
qpath = os.path.join(os.path.dirname(data_glob), "qdata.csv")
fq = h2o3_tpu.import_file(qpath, destination_frame="quoted_mp")
q_stats = dict(dparse.last_stats)

with open(out_path, "w") as f:
    json.dump({"pid": pid, "shape": list(fr.shape), "types": fr.types(),
               "mean_num": float(mean_num), "auc": float(auc),
               "domain": fr.vec("cat").domain,
               "mixed_domain": fr.vec("mixedcat").domain,
               "cat_head": [int(v) for v in cat_codes[:5]],
               "txt_head": [str(v) for v in fr.vec("txt").to_numpy()[:3]],
               "stats": span_stats,
               "q_shape": list(fq.shape),
               "q_cell": str(fq.vec("note").to_numpy()[250]),
               "q_suspect": bool(q_stats.get("suspect"))}, f)
"""


def _write_parse_files(tmp_path, nrows_list=(3000, 800, 4200)):
    """Uneven CSV shards; cat levels differ per file to force domain merge.

    ``mixedcat`` holds numeric-looking tokens ("3", "007") everywhere except
    the tail of the last file ("x9") — process 0's spans tokenize it as pure
    float while process 1 sees text, forcing the supplemental raw-token
    domain round (source spellings must survive, no "3.0" float round-trip).
    """
    import numpy as np
    rng = np.random.default_rng(7)
    total_rows = 0
    last = len(nrows_list) - 1
    for k, nrows in enumerate(nrows_list):
        with open(tmp_path / f"part{k}.csv", "w") as f:
            f.write("num,cat,mixedcat,txt,resp\n")
            for i in range(nrows):
                num = "" if i % 131 == 0 else f"{rng.normal():.4f}"
                cat = f"lvl{k}_{i % (3 + k)}"
                if k == last and i >= nrows - 200:
                    mixed = "x9"
                else:
                    mixed = "007" if i % 2 else "3"
                y = "Y" if rng.random() < 0.5 else "N"
                f.write(f"{num},{cat},{mixed},id_{k}_{i},{y}\n")
        total_rows += nrows
    # quoted-newline dataset: one RFC-4180 field with embedded linebreaks
    # sized to straddle the 2-process byte midpoint, so a span boundary
    # lands inside the quotes and the split MUST be detected as unsafe
    blob = "\n".join(f"wrapped line {j}" for j in range(120))
    with open(tmp_path / "qdata.csv", "w") as f:
        f.write('id,note\n')
        for i in range(500):
            if i == 250:
                f.write(f'{i},"{blob}"\n')
            else:
                f.write(f'{i},plain_{i}\n')
    return total_rows


def test_distributed_parse_two_processes(tmp_path):
    """2 processes parse a multi-file CSV, each tokenizing only its own
    byte ranges (ParseDataset.java:688 MultiFileParseTask analog), then
    train on the result."""
    nproc = 2
    total_rows = _write_parse_files(tmp_path)
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    worker_py = tmp_path / "worker_parse.py"
    worker_py.write_text(WORKER_PARSE)
    procs, outs = [], []
    for pid in range(nproc):
        out = tmp_path / f"pout_{pid}.json"
        outs.append(out)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "host_platform_device_count" not in f]
        flags.append("--xla_force_host_platform_device_count=4")
        env["XLA_FLAGS"] = " ".join(flags)
        ambient = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon" not in p]
        env["PYTHONPATH"] = os.pathsep.join([ROOT] + ambient)
        procs.append(subprocess.Popen(
            [sys.executable, str(worker_py), str(pid), str(nproc), coord,
             str(out), str(tmp_path / "part*.csv")],
            env=env, cwd=ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    logs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        logs.append(stdout)
    for pid, p in enumerate(procs):
        assert p.returncode == 0, f"worker {pid} failed:\n{logs[pid][-4000:]}"
    results = [json.loads(o.read_text()) for o in outs]
    r0, r1 = results
    assert r0["shape"] == [total_rows, 5]
    assert r0["shape"] == r1["shape"]
    assert r0["types"] == {"num": "num", "cat": "cat", "mixedcat": "cat",
                           "txt": "str", "resp": "cat"}
    # SPMD: identical global results on every process
    assert abs(r0["mean_num"] - r1["mean_num"]) < 1e-6
    assert abs(r0["auc"] - r1["auc"]) < 1e-6
    assert r0["domain"] == r1["domain"]
    assert r0["cat_head"] == r1["cat_head"]
    assert r0["txt_head"] == ["id_0_0", "id_0_1", "id_0_2"]
    # domain merge saw every file's distinct levels (3 + 4 + 5)
    assert len(r0["domain"]) == 12
    # mixed numeric/text column keeps SOURCE token spellings in the merged
    # domain — never float round-trips like "3.0"/"7.0"
    assert sorted(r0["mixed_domain"]) == ["007", "3", "x9"]
    assert r0["mixed_domain"] == r1["mixed_domain"]
    # quoted-newline input: at least one process detected the unsafe split
    # (the boundary lands inside the quoted blob) and ALL fell back to the
    # replicated parse, which handles the quoting correctly
    assert r0["q_suspect"] or r1["q_suspect"]
    assert r0["q_shape"] == [500, 2] and r1["q_shape"] == [500, 2]
    expected_blob = "\n".join(f"wrapped line {j}" for j in range(120))
    assert r0["q_cell"] == expected_blob == r1["q_cell"]
    # NO single-host tokenization: each process touched only its byte span
    total = r0["stats"]["total_bytes"]
    for r in results:
        st = r["stats"]
        assert st["total_bytes"] == total
        assert 0 < st["bytes_tokenized"] < 0.7 * total, st
        assert 0 < st["rows_local"] < total_rows, st
    combined = sum(r["stats"]["bytes_tokenized"] for r in results)
    assert combined >= 0.9 * total             # headers/partial lines only
    assert sum(r["stats"]["rows_local"] for r in results) == total_rows


WORKER_CHAOS = r"""
import json, os, sys, time

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
coord = sys.argv[3]
out_path = sys.argv[4]
csv_path = sys.argv[5]

import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=coord, num_processes=nproc,
                           process_id=pid)

import h2o3_tpu
from h2o3_tpu.models import GBM
from h2o3_tpu.runtime import dkv, failure, heartbeat

cl = h2o3_tpu.init(coordinator=coord, num_processes=nproc, process_id=pid)
# fast liveness for the test: 0.1s stamps, watchdog sweeping every 0.2s
heartbeat.start(interval=0.1)
failure.stop()
failure.start(poll=0.2, hb_interval=0.1)

fr = h2o3_tpu.import_file(csv_path, destination_frame="chaos_fr")
job = GBM(response_column="resp", ntrees=40, max_depth=3, nbins=16,
          seed=1, score_tree_interval=10**6).train_async(fr)
result = {"pid": pid, "failed": False}
try:
    job.join(timeout=300)
except BaseException as e:
    result["failed"] = True
    result["error_type"] = type(e).__name__
    result["error"] = repr(e)[:300]
    result["job_status"] = job.status

# wait for the watchdog to confirm the death (may lag the XLA error)
deadline = time.time() + 30
while time.time() < deadline and not failure.any_dead():
    time.sleep(0.2)
result["dead_detected"] = failure.any_dead()
result["failure_keys"] = dkv.keys(failure.FAILURES_PREFIX)

with open(out_path, "w") as f:
    json.dump(result, f)
# the backend may be wedged in a dead collective: skip teardown entirely
os._exit(0)
"""


def test_chaos_worker_death_recovery(tmp_path):
    """Kill one worker mid-train via the fault-injection hook; the
    survivor's watchdog aborts the job with a clear error and the journal
    stays resumable; a fresh (restarted) cluster resurrects the model via
    recovery.resume().  Matches water/HeartBeatThread.java:145 +
    hex/faulttolerance/Recovery.java:72-81 — and goes beyond the
    reference, which cannot abort cleanly on member loss."""
    import numpy as np
    nproc = 2
    rng = np.random.default_rng(11)
    n = 4000
    csv_path = tmp_path / "chaos.csv"
    with open(csv_path, "w") as f:
        f.write("x1,x2,resp\n")
        for i in range(n):
            x1, x2 = rng.normal(), rng.normal()
            yv = "Y" if rng.random() < 1 / (1 + np.exp(-(1.5 * x1 - x2))) \
                else "N"
            f.write(f"{x1:.5f},{x2:.5f},{yv}\n")
    recovery_dir = tmp_path / "recovery"
    recovery_dir.mkdir()
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    worker_py = tmp_path / "worker_chaos.py"
    worker_py.write_text(WORKER_CHAOS)
    procs, outs = [], []
    for pid in range(nproc):
        out = tmp_path / f"cout_{pid}.json"
        outs.append(out)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "host_platform_device_count" not in f]
        flags.append("--xla_force_host_platform_device_count=4")
        env["XLA_FLAGS"] = " ".join(flags)
        ambient = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon" not in p]
        env["PYTHONPATH"] = os.pathsep.join([ROOT] + ambient)
        env["H2O3_TPU_RECOVERY_DIR"] = str(recovery_dir)
        # process 1 is hard-killed at its 2nd tree chunk
        env["H2O3_TPU_FAULT_INJECT"] = "tree_chunk:1:2"
        procs.append(subprocess.Popen(
            [sys.executable, str(worker_py), str(pid), str(nproc), coord,
             str(out), str(csv_path)],
            env=env, cwd=ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    logs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        logs.append(stdout)
    # the injected victim dies 137; the survivor exits cleanly
    assert procs[1].returncode == 137, logs[1][-2000:]
    assert procs[0].returncode == 0, logs[0][-4000:]
    r0 = json.loads(outs[0].read_text())
    assert r0["failed"], r0
    assert r0["job_status"] == "FAILED"
    assert r0["dead_detected"], r0
    assert any(k.startswith("!failures/") for k in r0["failure_keys"]), r0
    # the journal entry survived as 'running' -> resumable
    entries = list(recovery_dir.glob("job_*.json"))
    assert entries, "no journal entry written"
    states = [json.loads(e.read_text())["status"] for e in entries]
    assert "running" in states, states
    # ---- phase B: "restarted cluster" (this pytest process, 8-dev mesh)
    from h2o3_tpu.runtime import failure, recovery as rec
    import h2o3_tpu
    h2o3_tpu.init()
    failure.reset()
    h2o3_tpu.import_file(str(csv_path), destination_frame="chaos_fr")
    done = rec.resume(str(recovery_dir))
    assert len(done) == 1, done
    from h2o3_tpu.runtime import dkv as _dkv
    model = _dkv.get(done[0])
    assert model is not None and model.output["ntrees_trained"] == 40
    assert not list(recovery_dir.glob("job_*.json"))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_cluster(tmp_path):
    nproc = 2
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(WORKER)
    procs = []
    outs = []
    for pid in range(nproc):
        out = tmp_path / f"out_{pid}.json"
        outs.append(out)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "host_platform_device_count" not in f]
        flags.append("--xla_force_host_platform_device_count=4")
        env["XLA_FLAGS"] = " ".join(flags)
        # CPU-only workers: drop the axon TPU plugin from the path — its
        # sitecustomize probes the backend, which must not happen before
        # jax.distributed.initialize
        ambient = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon" not in p]
        env["PYTHONPATH"] = os.pathsep.join([ROOT] + ambient)
        procs.append(subprocess.Popen(
            [sys.executable, str(worker_py), str(pid), str(nproc), coord,
             str(out)],
            env=env, cwd=ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    logs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        logs.append(stdout)
    for pid, p in enumerate(procs):
        assert p.returncode == 0, (
            f"worker {pid} failed:\n{logs[pid][-4000:]}")
    results = [json.loads(o.read_text()) for o in outs]
    # SPMD: every process computed the same global result
    assert abs(results[0]["mean_x1"] - results[1]["mean_x1"]) < 1e-6
    assert abs(results[0]["glm_auc"] - results[1]["glm_auc"]) < 1e-6
    assert abs(results[0]["gbm_auc"] - results[1]["gbm_auc"]) < 1e-6
    assert results[0]["glm_auc"] > 0.7
    assert results[0]["gbm_auc"] > 0.7
    # control plane: cross-process DKV resolution
    for r in results:
        assert r["peers"]["0"] is not None or r["peers"].get(0) is not None
        vals = list(r["peers"].values())
        assert all(v is not None for v in vals), r["peers"]


def test_dkv_tls_and_atomics(cl, tmp_path):
    """TLS-wrapped control plane + atomic CAS/incr (single-process)."""
    import os
    import subprocess
    import socket
    import struct
    import pickle
    import threading
    from h2o3_tpu.runtime import dkv
    cert, key = str(tmp_path / "c.pem"), str(tmp_path / "k.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-keyout", key,
         "-out", cert, "-days", "1", "-nodes", "-subj", "/CN=localhost"],
        capture_output=True, check=True)
    os.environ["H2O3_TPU_TLS_CERT"] = cert
    os.environ["H2O3_TPU_TLS_KEY"] = key
    from h2o3_tpu.runtime import config as _cfg
    _cfg.reload()
    try:
        dkv.detach()
        port = dkv.serve(port=0)
        dkv.attach("127.0.0.1", port)
        dkv._rpc("put", key="tls_test", value=42)
        assert dkv._rpc("get", key="tls_test") == 42
        # remote-side atomics
        assert dkv._rpc("cas", key="c1", expected=None, new="a")
        assert not dkv._rpc("cas", key="c1", expected="b", new="x")
        assert dkv._rpc("incr", key="n1", delta=2.5) == 2.5
        # a plaintext client gets no handshake
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=3) as s:
                payload = pickle.dumps({"op": "ping"})
                s.sendall(struct.pack("<Q", len(payload)) + payload)
                s.settimeout(3)
                data = s.recv(8)
                assert not data or len(data) < 8
        except (ConnectionError, socket.timeout, OSError):
            pass
    finally:
        dkv.detach()
        os.environ.pop("H2O3_TPU_TLS_CERT", None)
        os.environ.pop("H2O3_TPU_TLS_KEY", None)
        _cfg.reload()

    # local atomics under contention
    assert dkv.cas("casme", None, "v1")
    assert dkv.cas("casme", "v1", "v2") and dkv.get("casme") == "v2"

    def worker():
        for _ in range(500):
            dkv.incr("ctr_t", 1)
    ts = [threading.Thread(target=worker) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert dkv.get("ctr_t") == 4000


def test_heartbeat_liveness(cl):
    import time
    from h2o3_tpu.runtime import dkv, heartbeat
    name = heartbeat.start(interval=0.05)
    try:
        time.sleep(0.2)
        m = heartbeat.members(interval=0.05)
        assert m[name]["status"] == "alive"
        assert m[name]["pid"] > 0
        # a peer that stopped stamping decays to suspect, then dead
        dkv.put(heartbeat.PREFIX + "ghost",
                {"ts": time.time() - 0.3, "pid": 1})
        m = heartbeat.members(interval=0.05)
        assert m["ghost"]["status"] == "suspect"
        dkv.put(heartbeat.PREFIX + "ghost",
                {"ts": time.time() - 1.0, "pid": 1})
        assert heartbeat.members(interval=0.05)["ghost"]["status"] == "dead"
        # stamps dead >100 intervals are garbage-collected entirely
        dkv.put(heartbeat.PREFIX + "ghost",
                {"ts": time.time() - 60.0, "pid": 1})
        assert "ghost" not in heartbeat.members(interval=0.05)
    finally:
        heartbeat.stop()
        dkv.remove(heartbeat.PREFIX + "ghost")
    # clean stop removes this node's stamp (departure, not failure)
    assert name not in heartbeat.members(interval=0.05)
