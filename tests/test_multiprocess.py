"""Multi-process distributed runtime test — the multiNodeUtils.sh analog.

The reference's core distributed test pattern (SURVEY.md §4,
``scripts/multiNodeUtils.sh:21-26``) spawns real JVMs on localhost and runs
jobs across them.  Here: N real Python processes each with 4 virtual CPU
devices run ``jax.distributed.initialize`` against a localhost coordinator,
boot one 8-device global mesh, and execute the same SPMD training programs —
XLA collectives cross the process boundary exactly as they would cross
ICI/DCN on a TPU pod, and the coordinator DKV service carries the control
plane.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
coord = sys.argv[3]
out_path = sys.argv[4]

import jax
jax.config.update("jax_platforms", "cpu")
# initialize BEFORE anything can touch the XLA backend
jax.distributed.initialize(coordinator_address=coord, num_processes=nproc,
                           process_id=pid)

import numpy as np
import h2o3_tpu
from h2o3_tpu import Frame
from h2o3_tpu.frame.vec import T_CAT
from h2o3_tpu.models import GBM, GLM
from h2o3_tpu.runtime import dkv

cl = h2o3_tpu.init(coordinator=coord, num_processes=nproc, process_id=pid)
assert jax.process_count() == nproc, jax.process_count()
assert cl.n_devices == 4 * nproc, cl.n_devices

# identical data everywhere — SPMD: every process executes the same program
rng = np.random.default_rng(0)
n = 4000
x1 = rng.normal(size=n).astype(np.float32)
x2 = rng.normal(size=n).astype(np.float32)
c1 = rng.integers(0, 4, n)
logit = 1.2 * x1 - 0.8 * x2 + 0.5 * (c1 == 2)
y = rng.random(n) < 1 / (1 + np.exp(-logit))
fr = Frame.from_numpy(
    {"x1": x1, "x2": x2, "c1": c1,
     "y": np.where(y, "YES", "NO").astype(object)},
    types={"c1": T_CAT}, domains={"c1": [str(i) for i in range(4)]})

# rollups ride a cross-process psum
mean_x1 = fr.vec("x1").mean()

glm = GLM(response_column="y", family="binomial", lambda_=0.0,
          seed=1).train(fr)
glm_auc = glm.training_metrics.describe()["auc"]

gbm = GBM(response_column="y", ntrees=4, max_depth=3, nbins=16,
          seed=1).train(fr)
gbm_auc = gbm.training_metrics.describe()["auc"]

# control plane: each process publishes a result; all read each other's
dkv.put(f"mp_result_{pid}", {"auc": float(glm_auc)})
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("dkv_published")
peers = {}
for other in range(nproc):
    v = dkv.get(f"mp_result_{other}")
    peers[other] = None if v is None else v["auc"]

with open(out_path, "w") as f:
    json.dump({"pid": pid, "mean_x1": float(mean_x1),
               "glm_auc": float(glm_auc), "gbm_auc": float(gbm_auc),
               "peers": peers}, f)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_cluster(tmp_path):
    nproc = 2
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(WORKER)
    procs = []
    outs = []
    for pid in range(nproc):
        out = tmp_path / f"out_{pid}.json"
        outs.append(out)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "host_platform_device_count" not in f]
        flags.append("--xla_force_host_platform_device_count=4")
        env["XLA_FLAGS"] = " ".join(flags)
        # CPU-only workers: drop the axon TPU plugin from the path — its
        # sitecustomize probes the backend, which must not happen before
        # jax.distributed.initialize
        ambient = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon" not in p]
        env["PYTHONPATH"] = os.pathsep.join([ROOT] + ambient)
        procs.append(subprocess.Popen(
            [sys.executable, str(worker_py), str(pid), str(nproc), coord,
             str(out)],
            env=env, cwd=ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    logs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        logs.append(stdout)
    for pid, p in enumerate(procs):
        assert p.returncode == 0, (
            f"worker {pid} failed:\n{logs[pid][-4000:]}")
    results = [json.loads(o.read_text()) for o in outs]
    # SPMD: every process computed the same global result
    assert abs(results[0]["mean_x1"] - results[1]["mean_x1"]) < 1e-6
    assert abs(results[0]["glm_auc"] - results[1]["glm_auc"]) < 1e-6
    assert abs(results[0]["gbm_auc"] - results[1]["gbm_auc"]) < 1e-6
    assert results[0]["glm_auc"] > 0.7
    assert results[0]["gbm_auc"] > 0.7
    # control plane: cross-process DKV resolution
    for r in results:
        assert r["peers"]["0"] is not None or r["peers"].get(0) is not None
        vals = list(r["peers"].values())
        assert all(v is not None for v in vals), r["peers"]
