"""Telemetry plane unit tests: metric registry (counters / gauges /
mergeable histograms), wire + Prometheus rendering, span ok/error
recording, trace propagation + forest stitching, and the per-node
log-file handler lifecycle."""

import logging
import math
import os

import pytest

from h2o3_tpu.runtime import observability as obs


@pytest.fixture(autouse=True)
def _clean_registry():
    prev = obs.set_enabled(True)
    obs.reset_metrics()
    yield
    obs.reset_metrics()
    obs.set_enabled(prev)


# ------------------------------------------------------------------ metrics

def test_registry_identity_by_name_and_labels():
    a = obs.counter("reqs", op="put")
    assert obs.counter("reqs", op="put") is a          # same series
    assert obs.counter("reqs", op="get") is not a      # label split
    assert obs.counter("other", op="put") is not a     # name split
    # label values are stringified, so 1 and "1" are the same series
    assert obs.gauge("g", shard=1) is obs.gauge("g", shard="1")


def test_counter_gauge_semantics():
    c = obs.counter("n_ops")
    c.inc()
    c.inc(2.5)
    assert c.wire() == {"n": "n_ops", "l": {}, "t": "c", "v": 3.5}
    g = obs.gauge("mem", kind="in_use")
    g.set(100.0)
    g.set(40.0)
    assert g.value == 40.0                             # last-writer
    w = obs.gauge("mem", kind="peak")
    w.set_max(100.0)
    w.set_max(40.0)
    assert w.value == 100.0                            # watermark
    assert g.wire()["l"] == {"kind": "in_use"}


def test_histogram_bucketization_and_overflow():
    h = obs.histogram("lat")
    assert h.buckets == obs.LATENCY_BUCKETS
    h.observe(0.0003)       # lands in the <= 5e-4 slot
    h.observe(1e9)          # beyond the last edge -> +Inf overflow slot
    i = obs.LATENCY_BUCKETS.index(0.0005)
    assert h.counts[i] == 1
    assert h.counts[-1] == 1
    assert h.count == 2
    assert h.sum == pytest.approx(0.0003 + 1e9)
    w = h.wire()
    assert w["t"] == "h" and len(w["c"]) == len(w["b"]) + 1


def test_latency_buckets_are_log_spaced_and_monotone():
    b = obs.LATENCY_BUCKETS
    assert all(x < y for x, y in zip(b, b[1:]))
    assert b[0] == pytest.approx(1e-4)
    assert b[-1] == pytest.approx(500.0)


def test_histogram_merge_by_summation():
    h1 = obs.histogram("rpc")
    for v in (0.001, 0.002, 10.0):
        h1.observe(v)
    a, b = h1.wire(), h1.wire()
    merged = obs.merge_histograms([a, {"t": "c", "v": 1}, b])
    assert merged["n_obs"] == 6
    assert merged["s"] == pytest.approx(2 * h1.sum)
    assert merged["c"] == [x * 2 for x in h1.counts]
    bad = dict(b, b=[1.0, 2.0])
    with pytest.raises(ValueError, match="bucket edges differ"):
        obs.merge_histograms([a, bad])


def test_merge_wire_adds_node_label():
    obs.counter("x", op="put").inc()
    snap = obs.metrics_wire()
    merged = obs.merge_wire({"nodeA": snap, "nodeB": snap})
    assert len(merged) == 2
    assert {s["l"]["node"] for s in merged} == {"nodeA", "nodeB"}
    assert all(s["l"]["op"] == "put" for s in merged)


def test_enabled_switch_gates_instrumentation():
    obs.set_enabled(False)
    obs.inc("gated")
    obs.observe("gated_h", 0.1)
    obs.set_gauge("gated_g", 1.0)
    assert obs.metrics_wire() == []
    obs.set_enabled(True)
    obs.inc("gated")
    assert len(obs.metrics_wire()) == 1


# --------------------------------------------------------------- prometheus

def test_render_prometheus_text():
    obs.counter("dkv_rpc_failures", op="put").inc()
    obs.gauge("device_memory_bytes", device="0", kind="in_use").set(123.0)
    h = obs.histogram("dkv_rpc_seconds", op="get", side="client")
    h.observe(0.0002)
    h.observe(0.0002)
    h.observe(2.0)
    text = obs.render_prometheus(cluster=False)
    assert "# TYPE dkv_rpc_failures counter" in text
    assert "# TYPE device_memory_bytes gauge" in text
    assert "# TYPE dkv_rpc_seconds histogram" in text
    me = obs.node_name()
    assert f'dkv_rpc_failures{{node="{me}",op="put"}} 1.0' in text
    # histogram buckets are CUMULATIVE and end with +Inf == _count
    lines = [ln for ln in text.splitlines()
             if ln.startswith("dkv_rpc_seconds_bucket")]
    counts = [float(ln.rsplit(" ", 1)[1]) for ln in lines]
    assert counts == sorted(counts)
    assert 'le="+Inf"' in lines[-1] and counts[-1] == 3
    assert f'dkv_rpc_seconds_count{{node="{me}",op="get",side="client"}} 3' \
        in text
    # flat count() counters surface as h2o3_events_total{kind=...}
    obs.count("wal_records", 7)
    text = obs.render_prometheus(cluster=False)
    assert 'h2o3_events_total{kind="wal_records"' in text


def test_prom_label_escaping():
    assert obs._prom_labels({"msg": 'say "hi"'}) == r'{msg="say \"hi\""}'
    assert obs._prom_name("tree.phase-seconds") == "tree_phase_seconds"


# ------------------------------------------------------------------- traces

def test_span_records_ok_and_error():
    with obs.span("unit_ok", tag="a"):
        pass
    with pytest.raises(ValueError):
        with obs.span("unit_err", tag="b"):
            raise ValueError("boom")
    evs = {e["kind"]: e for e in obs.timeline_events(2000)}
    assert evs["unit_ok"]["ok"] is True
    assert "error" not in evs["unit_ok"]
    assert evs["unit_err"]["ok"] is False
    assert evs["unit_err"]["error"] == "ValueError"
    assert evs["unit_err"]["duration_s"] >= 0


def test_span_outside_trace_allocates_no_ids():
    with obs.span("unit_untraced"):
        assert obs.current_trace() is None
    ev = [e for e in obs.timeline_events(2000)
          if e["kind"] == "unit_untraced"][-1]
    assert "trace_id" not in ev and "span_id" not in ev


def test_trace_nesting_and_rpc_adoption():
    with obs.trace("unit_root"):
        ctx = obs.current_trace()
        assert ctx and ctx["trace_id"] and ctx["span_id"]
        with obs.span("unit_child"):
            inner = obs.current_trace()
            assert inner["trace_id"] == ctx["trace_id"]
            assert inner["span_id"] != ctx["span_id"]
        # the handler side adopts the wire context verbatim
        with obs.trace_context({"trace_id": "T", "span_id": "S"}):
            with obs.span("unit_remote"):
                pass
    assert obs.current_trace() is None
    evs = {e["kind"]: e for e in obs.timeline_events(2000)
           if e["kind"].startswith("unit_")}
    root, child = evs["unit_root"], evs["unit_child"]
    assert child["trace_id"] == root["trace_id"]
    assert child["parent_span"] == root["span_id"]
    remote = evs["unit_remote"]
    assert remote["trace_id"] == "T" and remote["parent_span"] == "S"


def test_trace_forest_stitching():
    events = [
        {"ts": 1.0, "kind": "job", "trace_id": "t1", "span_id": "a"},
        {"ts": 2.0, "kind": "tree_phase", "trace_id": "t1", "span_id": "b",
         "parent_span": "a"},
        {"ts": 3.0, "kind": "dkv_handle", "trace_id": "t1", "span_id": "c",
         "parent_span": "missing"},       # shipped span, parent un-shipped
        {"ts": 0.5, "kind": "job", "trace_id": "t0", "span_id": "z"},
        {"ts": 4.0, "kind": "noise"},     # no ids -> excluded
    ]
    forest = obs.trace_forest(events)
    assert [t["trace_id"] for t in forest] == ["t0", "t1"]  # by first ts
    t1 = forest[1]
    assert {s["span_id"] for s in t1["spans"]} == {"a", "c"}  # orphan=root
    a = next(s for s in t1["spans"] if s["span_id"] == "a")
    assert [s["span_id"] for s in a["children"]] == ["b"]


def test_span_disabled_is_transparent():
    obs.set_enabled(False)
    n0 = len(obs.timeline_events(2000))
    with obs.span("unit_gone"):
        pass
    assert len(obs.timeline_events(2000)) == n0


# ----------------------------------------------------------------- log file

def test_log_file_handler_lifecycle(tmp_path, monkeypatch):
    from h2o3_tpu.runtime import config
    template = str(tmp_path / "node_%h_%p.log")
    monkeypatch.setenv("H2O3_TPU_LOG_FILE", template)
    try:
        config.reload()
        path = template.replace("%h", __import__("socket").gethostname()) \
                       .replace("%p", str(os.getpid()))
        obs.log.warning("telemetry log-file smoke line")
        assert os.path.exists(path)
        assert "telemetry log-file smoke line" in open(path).read()
        # the ring handler keeps working alongside the file
        assert any("telemetry log-file smoke line" in ln
                   for ln in obs.recent_logs())
        obs.close_log_file()
        assert not any(isinstance(h, logging.FileHandler)
                       for h in obs.log.handlers)
        obs.close_log_file()               # idempotent
    finally:
        monkeypatch.delenv("H2O3_TPU_LOG_FILE", raising=False)
        config.reload()


# ---------------------------------------------------------------------- api

def test_api_timeline_limit_and_shape():
    from h2o3_tpu.api.server import Api
    for i in range(6):
        obs.record("unit_api_marker", i=i)
    out = Api().timeline(limit=4)
    assert len(out["events"]) == 4
    assert isinstance(out["counters"], dict)
    assert isinstance(out["nodes"], dict)
    assert isinstance(out["traces"], list)


# ---------------------------------------------------------- compile ledger

@pytest.fixture()
def xprof():
    from h2o3_tpu.runtime import xprof as xp
    xp.reset_ledger()
    yield xp
    xp.reset_ledger()


def test_register_program_compile_reasons(xprof):
    """One program, three compile reasons: first build, a new shape, and
    a cluster re-init epoch bump — each attributed in the ledger and the
    recompiles_total/compile_seconds series."""
    import jax
    import jax.numpy as jnp

    def f(x):
        return x * 2.0

    prog = xprof.register_program("unit_prog", jax.jit(f), orig=f)
    x = jnp.ones((8,), jnp.float32)
    assert float(prog(x)[0]) == 2.0
    ent = xprof.ledger_snapshot()["programs"]["unit_prog"]
    assert ent["compiles"] == 1 and ent["reasons"] == {"first": 1}
    assert ent["compile_s"] > 0.0
    prog(x)                                  # seen signature: no recompile
    assert xprof.ledger_snapshot()["programs"]["unit_prog"]["compiles"] == 1
    prog(jnp.ones((16,), jnp.float32))       # new signature
    ent = xprof.ledger_snapshot()["programs"]["unit_prog"]
    assert ent["compiles"] == 2 and ent["reasons"]["shape_change"] == 1
    xprof.invalidate("cluster_reinit")       # what cluster re-init does
    prog(x)                                  # stale executable was dropped
    ent = xprof.ledger_snapshot()["programs"]["unit_prog"]
    assert ent["compiles"] == 3 and ent["reasons"]["cluster_reinit"] == 1
    # XLA cost attribution published alongside the compile counters
    assert ent["flops"] is not None
    series = {s["n"] for s in obs.metrics_wire()}
    assert {"compile_seconds", "recompiles_total", "program_flops"} <= series


def test_program_passthrough_under_trace(xprof):
    """Inside an outer jit the wrapper must inline the ORIGINAL function
    (no nested-jit hop, no AOT compile, no ledger entry)."""
    import jax
    import jax.numpy as jnp

    def f(x):
        return x + 1.0

    prog = xprof.register_program("unit_traced", jax.jit(f), orig=f)

    @jax.jit
    def outer(x):
        return prog(x) * 3.0

    out = outer(jnp.ones((4,), jnp.float32))
    assert float(out[0]) == 6.0
    assert "unit_traced" not in xprof.ledger_snapshot()["programs"]


def test_program_fallback_never_breaks_seam(xprof):
    """AOT failures flip the wrapper to permanent passthrough (with an
    xprof_fallback event) but the call still returns the answer."""
    import jax
    import jax.numpy as jnp

    # compile-stage failure: the registered object has no .lower
    def plain(x):
        return x + 1.0
    prog = xprof.register_program("unit_nolower", plain)
    assert float(prog(jnp.ones((2,), jnp.float32))[0]) == 2.0
    assert prog.fallback
    assert "unit_nolower" not in xprof.ledger_snapshot()["programs"]

    # call-stage failure: statics declared on the wrapper but not on the
    # jit — the compiled executable rejects the stripped arg list
    def g(x, k):
        return x * k
    prog2 = xprof.register_program("unit_mismatch", jax.jit(g),
                                   static_argnums=(1,))
    assert float(prog2(jnp.ones((2,), jnp.float32), 3)[0]) == 3.0
    assert prog2.fallback
    falls = [e for e in obs.timeline_events(500)
             if e.get("kind") == "xprof_fallback"]
    assert {e.get("program") for e in falls} >= {"unit_nolower",
                                                 "unit_mismatch"}


def test_maybe_device_sync_modes(monkeypatch):
    """off records nothing; full syncs every call; sampled syncs every
    Nth; unknown mode strings read as off."""
    import jax.numpy as jnp
    from h2o3_tpu.runtime import config, xprof
    out = jnp.ones((4,), jnp.float32)

    def set_mode(mode, sample=None):
        monkeypatch.setenv("H2O3_TPU_DEVICE_TIMING", mode)
        if sample is not None:
            monkeypatch.setenv("H2O3_TPU_DEVICE_TIMING_SAMPLE", str(sample))
        config.reload()
        obs.set_enabled(True)        # reload re-reads the metrics switch

    try:
        set_mode("off")
        assert xprof.device_timing_mode() == "off"
        assert xprof.maybe_device_sync("unit_phase", 1, 0.0, out) is False
        set_mode("full")
        assert all(xprof.maybe_device_sync("unit_phase", s, 0.0, out)
                   for s in (1, 2, 3))
        set_mode("sampled", sample=2)
        synced = [xprof.maybe_device_sync("unit_phase", s, 0.0, out)
                  for s in (1, 2, 3, 4)]
        assert synced == [False, True, False, True]
        assert "tree_phase_device_seconds" in {
            s["n"] for s in obs.metrics_wire()}
        set_mode("bogus")
        assert xprof.device_timing_mode() == "off"
    finally:
        monkeypatch.delenv("H2O3_TPU_DEVICE_TIMING", raising=False)
        monkeypatch.delenv("H2O3_TPU_DEVICE_TIMING_SAMPLE", raising=False)
        config.reload()


# --------------------------------------------------------------- profiler

def test_device_trace_idempotent(tmp_path):
    """Double-start and stop-without-start are no-ops that record
    profiler_noop events instead of raising."""
    logdir = str(tmp_path / "trace")
    assert obs.profiler_active() is False
    if not obs.start_device_trace(logdir):
        pytest.skip("jax profiler unavailable on this backend")
    try:
        assert obs.profiler_active() is True
        assert obs.start_device_trace(logdir) is False     # already active
    finally:
        assert obs.stop_device_trace() is True
    assert obs.profiler_active() is False
    assert obs.stop_device_trace() is False                # nothing active
    noops = [e for e in obs.timeline_events(500)
             if e.get("kind") == "profiler_noop"]
    assert {e.get("reason") for e in noops} >= {"already_active",
                                                "not_active"}


def test_api_profiler_roundtrip(tmp_path):
    """POST /3/Profiler/start|stop idempotency + GET /3/Profiler/memory
    through the Api surface the REST routes dispatch to."""
    from h2o3_tpu.api.server import Api
    api = Api()
    out = api.profiler_start(logdir=str(tmp_path / "cap"))
    if not out["started"]:
        pytest.skip("jax profiler unavailable on this backend")
    try:
        assert out["active"] is True and out["logdir"].endswith("cap")
        again = api.profiler_start(logdir=str(tmp_path / "cap"))
        assert again["started"] is False and again["active"] is True
    finally:
        stop = api.profiler_stop()
    assert stop["stopped"] is True and stop["active"] is False
    assert api.profiler_stop()["stopped"] is False
    mem = api.profiler_memory()
    assert isinstance(mem, bytes) and len(mem) > 0         # pprof payload


def test_api_compile_ledger_and_metrics_scrape(xprof, cl, monkeypatch):
    """GET /3/Profiler/compiles returns the ledger; GET /metrics carries
    the compile series and refreshes device-memory gauges at scrape
    time (no heartbeat needed)."""
    import jax
    import jax.numpy as jnp
    from h2o3_tpu.api.server import Api

    def f(x):
        return x + 3.0

    prog = xprof.register_program("unit_rest_prog", jax.jit(f), orig=f)
    prog(jnp.ones((4,), jnp.float32))
    api = Api()
    snap = api.compile_ledger()
    assert snap["programs"]["unit_rest_prog"]["compiles"] == 1
    assert snap["total_compiles"] >= 1
    # scrape-time refresh: /metrics re-samples the device allocator stats
    # before rendering (CPU devices report none, so observe the call)
    sampled = []
    from h2o3_tpu.runtime import cluster as _cluster_mod
    monkeypatch.setattr(_cluster_mod, "sample_memory_gauges",
                        lambda: sampled.append(1) or 1)
    text = api.prometheus()
    assert "# TYPE compile_seconds histogram" in text
    assert 'program="unit_rest_prog"' in text
    assert "# TYPE recompiles_total counter" in text
    assert "# TYPE program_flops gauge" in text
    assert sampled, "scrape did not refresh device-memory gauges"


def test_acceptance_gbm_costs_and_reinit_recompiles(cl, rng, xprof):
    """ISSUE acceptance: a GBM train on the 8-device mesh plus the eager
    hist/split entry points yield nonzero compile_seconds and
    program_flops for hist and split programs in /metrics, and re-initing
    the cluster with a new geometry attributes the next compiles to
    recompiles_total{reason="cluster_reinit"}."""
    import jax.numpy as jnp
    import numpy as np
    import h2o3_tpu
    from h2o3_tpu import Frame
    from h2o3_tpu.models import GBM
    from h2o3_tpu.models.tree import hist

    n = 512
    X = rng.normal(size=(n, 3)).astype(np.float32)
    y = 2.0 * X[:, 0] - X[:, 1] + 0.1 * rng.normal(size=n)
    fr = Frame.from_numpy({"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2],
                           "y": y})
    GBM(response_column="y", ntrees=2, max_depth=2, seed=7).train(fr)
    # the fused train traces hist/splits INSIDE tree_scan, so drive them
    # through their eager entry points too (the crosscheck/bench path)
    L, F, B = 2, 5, 7
    codes = jnp.asarray(rng.integers(0, B - 1, (F, n)), jnp.int32)
    leaf = jnp.zeros((n,), jnp.int32)
    g = jnp.asarray(rng.normal(size=n), jnp.float32)
    w = jnp.ones((n,), jnp.float32)
    H = hist.make_hist_fn(L, F, B, n, force_impl="einsum")(
        codes, leaf, g, w, w)
    hist.fused_best_splits(H, B - 1, 0.5, 1.0, 1e-5)
    progs = xprof.ledger_snapshot()["programs"]
    assert progs["tree_scan"]["compile_s"] > 0.0
    for name in ("hist_uniform", "fused_split"):
        assert progs[name]["compile_s"] > 0.0, name
        assert progs[name]["flops"], name
    text = obs.render_prometheus(cluster=False)
    assert 'program="hist_uniform"' in text
    assert 'program="fused_split"' in text
    assert "# TYPE program_flops gauge" in text
    # new geometry: compiled programs went stale; their next compile is
    # attributed to the re-init
    orig_hosts = cl.n_hosts
    new_hosts = 4 if orig_hosts != 4 else 2
    try:
        h2o3_tpu.init(hosts=new_hosts)
        hist.make_hist_fn(L, F, B, n, force_impl="einsum")(
            codes, leaf, g, w, w)
        ent = xprof.ledger_snapshot()["programs"]["hist_uniform"]
        assert ent["reasons"].get("cluster_reinit", 0) >= 1
        assert any(s["n"] == "recompiles_total"
                   and s["l"].get("reason") == "cluster_reinit"
                   for s in obs.metrics_wire())
    finally:
        h2o3_tpu.init(hosts=orig_hosts)


# --------------------------------------------------------- mesh data plane

def test_mesh_shape_gauge_and_collective_seconds(cl):
    """The hierarchical data plane surfaces its geometry and timings:
    publish_mesh_gauges() emits one mesh_shape gauge per mesh axis plus
    the device total, and map_reduce records a collective_seconds
    observation labelled with the collective schedule — all visible in
    the GET /metrics Prometheus text."""
    import jax.numpy as jnp
    import numpy as np
    from h2o3_tpu.runtime.cluster import publish_mesh_gauges
    from h2o3_tpu.runtime.mapreduce import map_reduce

    publish_mesh_gauges()        # re-emit: _clean_registry reset the gauges
    x = jnp.asarray(np.arange(64, dtype=np.float32))
    map_reduce(lambda d: jnp.sum(d), x, reduce_mode="hier")
    map_reduce(lambda d: jnp.sum(d), x, reduce_mode="flat")
    text = obs.render_prometheus(cluster=False)
    me = obs.node_name()
    assert "# TYPE mesh_shape gauge" in text
    assert f'mesh_shape{{axis="hosts",node="{me}"}} {float(cl.n_hosts)}' \
        in text
    assert f'mesh_shape{{axis="chips",node="{me}"}} ' \
        f'{float(cl.n_chips_per_host)}' in text
    assert f'mesh_shape{{axis="total",node="{me}"}} ' \
        f'{float(cl.n_row_shards)}' in text
    assert "# TYPE collective_seconds histogram" in text
    assert 'axis="chips+hosts"' in text      # staged hier schedule
    assert 'axis="rows"' in text             # flat oracle
    assert 'op="map_reduce"' in text


# ------------------------------------------------------------- autotuner

def test_autotune_series_and_rest_route(cl):
    """The autotuner's observability surface: every resolve increments
    autotune_decisions_total{knob,choice,source}, the table size is the
    autotune_cache_entries gauge, both render in GET /metrics, and
    GET /3/Profiler/autotune dumps the decision table (signature ->
    choice, source, predicted vs measured seconds)."""
    import json
    import types

    from h2o3_tpu.api.server import Api
    from h2o3_tpu.runtime import autotune, config

    saved = os.environ.get("H2O3_TPU_AUTOTUNE")
    try:
        os.environ["H2O3_TPU_AUTOTUNE"] = "on"
        config.reload()
        autotune.reset()
        p = types.SimpleNamespace(hist_mode="auto", split_mode="auto",
                                  hist_layout="auto",
                                  sparse_depth_threshold=8,
                                  max_depth=6, nbins=32)
        k = autotune.resolve_tree_knobs(p, kind="gbm", F=4, N=4096)
        assert k.sig is not None
        autotune.resolve_serve_impl(depth=8, R=100, F=16, B=128)

        text = obs.render_prometheus(cluster=False)
        assert "# TYPE autotune_decisions_total counter" in text
        assert 'knob="hist_mode"' in text
        assert 'source="model"' in text
        assert "# TYPE autotune_cache_entries gauge" in text
        me = obs.node_name()
        assert f'autotune_cache_entries{{node="{me}"}} 2.0' in text

        table = Api().autotune_table()
        json.dumps(table)                       # REST payload: plain data
        assert table["mode"] == "on" and table["entries"] == 2
        sigs = {d["signature"] for d in table["decisions"]}
        assert k.sig in sigs
        assert any(s.startswith("serve:") for s in sigs)
        row = next(d for d in table["decisions"]
                   if d["signature"] == k.sig)
        assert row["source"] == "model"
        assert set(row) >= {"signature", "choice", "source", "resolves",
                            "predicted_s", "measured_s", "exploring"}
    finally:
        if saved is None:
            os.environ.pop("H2O3_TPU_AUTOTUNE", None)
        else:
            os.environ["H2O3_TPU_AUTOTUNE"] = saved
        config.reload()
        autotune.reset()
