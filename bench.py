"""Benchmark suite: tpu_hist boosting (headline), DeepLearning, Rapids.

North star (BASELINE.json / SURVEY.md §6): the reference's XGBoost gpu_hist
benchmark gate trains 100 trees on airlines-10m in 22-52s on its GPU node
(compareBenchmarksStage.groovy:174-177) → ~1.9-4.5 trees/sec.  vs_baseline
divides our trees/sec by the best end of that interval (4.5), measured on an
airlines-shaped synthetic set: 10M rows, mixed numeric/categorical, binary
response, max_depth=6, nbins=256 — the same work shape gpu_hist does.

Secondary metrics (BASELINE.md):
 - DeepLearning samples/sec, MNIST shape (DeepLearning.java:648 rows/sec
   hook; no published reference value → no vs_baseline).
 - Rapids sort / merge wall-clock at 10M x 2 cols (reference Jenkins gate:
   sort 2-7 s, merge 4-10 s; vs_baseline divides the reference BEST time by
   ours, so >1 means faster than the reference's best).

Prints ONE JSON line: the headline record with an "extra" dict carrying the
secondary metrics.

Robustness contract (BENCH_r02/r03 post-mortems): the measured region runs in
a *worker subprocess*; the parent orchestrator owns ONE total wall-clock
budget (H2O3_BENCH_TOTAL_BUDGET, default 2100 s) covering probe + primary +
fallback, not per-attempt timeouts — r03 died rc=124 because 2×2700 s of
per-attempt allowance exceeded the driver's outer clock.  The primary attempt
gets the budget minus a guaranteed fallback reserve; the CPU fallback runs a
minutes-scale shape (100 k rows × 10 trees, secondaries skipped) so it always
finishes inside the reserve.  The orchestrator always prints a JSON record and
exits 0.
"""

import contextlib
import json
import os
import subprocess
import sys
import time

import numpy as np

REFERENCE_TREES_PER_SEC = 4.5     # best of the reference gpu_hist interval
REFERENCE_SORT_10M_S = 2.0        # best of Jenkins sort interval (10M rows)
REFERENCE_MERGE_10M_S = 4.0       # best of Jenkins merge interval (10M rows)
# H2O3_BENCH_ROWS/TREES: smoke-test overrides (CI runs the full shape)
N_ROWS = int(os.environ.get("H2O3_BENCH_ROWS", 10_000_000))
N_TREES = int(os.environ.get("H2O3_BENCH_TREES", 50))


def _ledger_totals():
    """(total_compiles, total_compile_s) from the xprof compile ledger —
    zeros when the runtime (or the ledger) is unavailable."""
    try:
        from h2o3_tpu.runtime import xprof
        snap = xprof.ledger_snapshot()
        return snap["total_compiles"], snap["total_compile_s"]
    except Exception:                    # noqa: BLE001 — bench never dies
        return 0, 0.0


@contextlib.contextmanager
def _compile_split(extra, section):
    """Split a bench section's wall clock into compile vs steady time via
    compile-ledger deltas, so the regression gate can tell "kernel got
    slower" from "compile got slower"."""
    c0, s0 = _ledger_totals()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        wall = time.perf_counter() - t0
        c1, s1 = _ledger_totals()
        if c1 > c0:
            extra[f"{section}_compile_s"] = round(s1 - s0, 3)
            extra[f"{section}_steady_s"] = round(
                max(wall - (s1 - s0), 0.0), 3)


def make_airlines_like(n):
    rng = np.random.default_rng(0)
    cols = {
        "year": rng.integers(1987, 2008, n).astype(np.float32),
        "month": rng.integers(1, 13, n).astype(np.float32),
        "day_of_week": rng.integers(1, 8, n).astype(np.float32),
        "crs_dep_time": rng.integers(0, 2400, n).astype(np.float32),
        "distance": np.abs(rng.normal(700, 500, n)).astype(np.float32),
        "carrier": rng.integers(0, 22, n),
        "origin": rng.integers(0, 300, n),
        "dest": rng.integers(0, 300, n),
    }
    logit = (0.002 * (cols["crs_dep_time"] / 100 - 12) ** 2
             - 0.0005 * cols["distance"] / 100
             + 0.2 * np.isin(cols["day_of_week"], (5, 7))
             + 0.1 * rng.normal(size=n))
    dep_delayed = rng.random(n) < 1 / (1 + np.exp(-logit))
    cols["dep_delayed_15min"] = np.where(dep_delayed, "YES", "NO").astype(object)
    types = {"carrier": "cat", "origin": "cat", "dest": "cat"}
    domains = {"carrier": [str(i) for i in range(22)],
               "origin": [str(i) for i in range(300)],
               "dest": [str(i) for i in range(300)]}
    return cols, types, domains


def bench_trees(Frame, T_CAT, XGBoost):
    cols, types, domains = make_airlines_like(N_ROWS)
    types = {k: (T_CAT if v == "cat" else v) for k, v in types.items()}
    fr = Frame.from_numpy(cols, types=types, domains=domains)
    config = dict(response_column="dep_delayed_15min", max_depth=6,
                  nbins=256, seed=1, score_tree_interval=10 ** 9)
    # warmup: two full scan chunks — the first compiles the exact program the
    # timed run reuses, the second absorbs the one-off first-execution
    # anomaly (~6 s, observed on the axon tunnel after each fresh compile)
    XGBoost(ntrees=20, **config).train(fr)
    t0 = time.time()
    XGBoost(ntrees=N_TREES, **config).train(fr)
    dt = time.time() - t0
    del fr
    return N_TREES / dt


def bench_deeplearning(Frame, DeepLearning):
    """MNIST-shape MLP throughput (samples/sec/chip)."""
    n, d = min(60_000, max(N_ROWS, 4_096)), 784
    rng = np.random.default_rng(1)
    X = (rng.random((n, d)) * 255).astype(np.float32)
    y = rng.integers(0, 10, n)
    cols = {f"p{j}": X[:, j] for j in range(d)}
    cols["label"] = np.array([str(v) for v in y], dtype=object)
    fr = Frame.from_numpy(cols)
    # Large effective batch: the per-step FLOPs at batch 512 are ~3 us of
    # MXU — launch/stream overheads dominate and no batching knob in the
    # reference forbids it (its Hogwild default is minibatch=1 per THREAD).
    # bf16 matmuls + random-offset block sampling are the model defaults.
    kw = dict(response_column="label", hidden=(200, 200),
              mini_batch_size=8192, score_interval=1e9, stopping_rounds=0,
              seed=1)
    DeepLearning(epochs=2.0, **kw).train(fr)          # compile warmup
    epochs = 500.0 if N_ROWS >= 1_000_000 else 2.0    # smoke override
    t0 = time.time()
    DeepLearning(epochs=epochs, **kw).train(fr)
    dt = time.time() - t0
    del fr
    return epochs * n / dt


REFERENCE_GLM_HIGGS_S = 47.0      # best of the higgs GLM intervals
REFERENCE_GLM_HIGGS_ROWS = 11_000_000
# (COORDINATE_DESCENT 47-54 s, IRLSM 65-73 s —
#  compareBenchmarksStage.groovy:97-104; 11M rows x 28 numerics.
#  The conservative best-of-either-solver bound is scaled linearly to the
#  benched row count so reduced-shape smoke runs stay honest.)


def make_higgs_like(Frame, n, d=28, seed=3):
    """HIGGS shape: n rows x 28 dense numerics, binary response."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = rng.normal(size=d) * 0.3
    logit = X @ beta - 0.2
    yy = rng.random(n) < 1 / (1 + np.exp(-logit))
    cols = {f"f{j}": X[:, j] for j in range(d)}
    cols["y"] = np.where(yy, "s", "b").astype(object)
    return Frame.from_numpy(cols)


def bench_glm(Frame, GLM, fr):
    """Higgs-shape binomial GLM (IRLSM, lambda=0): train-time seconds."""
    kw = dict(family="binomial", response_column="y", lambda_=0.0)
    GLM(**kw).train(fr)                               # warmup/compile
    t0 = time.time()
    GLM(**kw).train(fr)
    return time.time() - t0


def bench_glm_lambda_path(Frame, GLM, fr):
    """Higgs-shape GLM with a full regularization path (lambda_search).

    The reference GLM gate intervals (47-54 s COORDINATE_DESCENT on higgs,
    compareBenchmarksStage.groovy:97-104) are full solver runs including
    the lambda path — this line is the honest comparison the round-4
    lambda=0 line was not (VERDICT r4 weak #5).  100 lambdas, alpha=0.5,
    warm-started IRLSM down the path.
    """
    kw = dict(family="binomial", response_column="y", lambda_search=True,
              nlambdas=100, alpha=0.5)
    GLM(**kw).train(fr)                               # warmup/compile
    t0 = time.time()
    GLM(**kw).train(fr)
    return time.time() - t0


# --- GBM gate shapes (compareBenchmarksStage.groovy; 50-tree intervals) ---
REFERENCE_GBM_HIGGS_S = 72.0          # :45-52, 50 trees, 11M x 28 numerics
REFERENCE_GBM_HIGGS_ROWS = 11_000_000
REFERENCE_GBM_SPRINGLEAF_S = 52.0     # :35-43, 50 trees, 145k x ~1.9k wide
REFERENCE_GBM_SPRINGLEAF_ROWS = 145_000
REFERENCE_GBM_REDHAT_S = 21.0         # :25-33, 50 trees, 2.2M sparse/cat
REFERENCE_GBM_REDHAT_ROWS = 2_200_000
# The reference gate runs H2O GBM defaults: ntrees=50, max_depth=5,
# nbins=20 — the bench configs below pin the same work shape.
_GBM_GATE = dict(ntrees=50, max_depth=5, nbins=20, seed=1,
                 score_tree_interval=10 ** 9)


def _timed_gbm(GBM, fr, response, warmup_trees=10):
    cfg = dict(_GBM_GATE, response_column=response)
    GBM(**{**cfg, "ntrees": warmup_trees}).train(fr)  # compile + first-exec
    t0 = time.time()
    GBM(**cfg).train(fr)
    return time.time() - t0


def make_springleaf_like(Frame, T_CAT, n, seed=5):
    """Springleaf shape: ~1.9k mostly-sparse columns, 145k rows.

    Mix modeled on the Kaggle set the gate uses: blocks of one-hot
    indicator columns (mutually exclusive — the EFB target), sparse count
    columns, dense numerics, and a few categoricals.
    """
    rng = np.random.default_rng(seed)
    cols, types, domains = {}, {}, {}
    # 60 one-hot groups x 20 indicators = 1200 exclusive sparse cols
    for g in range(60):
        which = rng.integers(0, 20, n)
        for j in range(20):
            cols[f"oh{g}_{j}"] = (which == j).astype(np.float32)
    # 400 sparse count columns (90% zero)
    nz = rng.random((n, 400)) < 0.1
    counts = rng.integers(1, 6, (n, 400)).astype(np.float32) * nz
    for j in range(400):
        cols[f"sp{j}"] = counts[:, j]
    # 280 dense numerics
    dense = rng.normal(size=(n, 280)).astype(np.float32)
    for j in range(280):
        cols[f"num{j}"] = dense[:, j]
    # 20 categoricals
    for j in range(20):
        card = int(rng.integers(3, 40))
        cols[f"cat{j}"] = rng.integers(0, card, n)
        types[f"cat{j}"] = "cat"
        domains[f"cat{j}"] = [str(i) for i in range(card)]
    logit = (0.8 * cols["oh0_3"] + 0.5 * (counts[:, 0] > 0)
             + 0.3 * dense[:, 0] - 0.5
             + 0.3 * rng.normal(size=n))
    cols["target"] = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)),
                              "1", "0").astype(object)
    return cols, types, domains


def make_redhat_like(Frame, T_CAT, n, seed=6):
    """Red Hat shape: 2.2M rows, ~38 boolean chars + high-card cats."""
    rng = np.random.default_rng(seed)
    cols, types, domains = {}, {}, {}
    for j in range(38):
        cols[f"char_{j}"] = (rng.random(n) < 0.3).astype(np.float32)
    for name, card in (("group", 7000), ("activity_category", 7),
                       ("char_a", 50), ("char_b", 100), ("char_c", 500)):
        cols[name] = rng.integers(0, card, n)
        types[name] = "cat"
        domains[name] = [str(i) for i in range(card)]
    cols["days"] = rng.integers(0, 800, n).astype(np.float32)
    logit = (0.4 * cols["char_0"] + 0.3 * cols["char_1"]
             - 0.2 * (cols["activity_category"] == 2)
             + 0.2 * rng.normal(size=n))
    cols["outcome"] = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)),
                               "1", "0").astype(object)
    return cols, types, domains


REFERENCE_PARSE_S = 4.9           # 580 MB / 5.8M rows on 5 nodes
REFERENCE_PARSE_MB = 580.0        # (h2o-docs/src/product/security.rst:1133)


def bench_parse(parse_csv, tmpdir):
    """Parse throughput: ~580 MB CSV -> Frame, single host.

    The reference number is a 5-node cluster parse of the same volume;
    vs_baseline divides its wall clock by ours (>1 = faster than the
    5-node reference).
    """
    import pyarrow as pa
    import pyarrow.csv as pacsv
    path = os.path.join(tmpdir, "parse_bench.csv")
    n = 5_800_000 if N_ROWS >= 1_000_000 else 100_000
    rng = np.random.default_rng(7)
    # float32 columns: realistic ~8-significant-digit cells (the
    # reference's 580 MB / 5.8M-row corpus is ~100 B/row)
    tbl = pa.table({
        **{f"n{j}": rng.normal(size=n).astype(np.float32)
           for j in range(8)},
        "i0": rng.integers(0, 100000, n),
        "c0": np.asarray(rng.integers(0, 50, n)).astype(str),
    })
    pacsv.write_csv(tbl, path)
    mb = os.path.getsize(path) / 1e6
    parse_csv(path)                                   # warmup
    t0 = time.time()
    fr = parse_csv(path)
    dt = time.time() - t0
    assert fr.nrows == n
    os.unlink(path)
    return dt, mb


# tunnel-safe small-fetch sync, shared with bench_pieces.py (bench_util.py)
from bench_util import sync_frame as _sync  # noqa: E402


def bench_rapids(Frame, sort, merge):
    n = N_ROWS
    rng = np.random.default_rng(2)
    big = Frame.from_numpy({
        "KEY": rng.integers(0, n, n).astype(np.float64),
        "X2": rng.random(n)})
    small = Frame.from_numpy({
        "KEY": rng.integers(0, n, n // 10).astype(np.float64),
        "Y2": rng.random(n // 10)})
    _sync(sort(big, "KEY"))                           # warmup/compile
    t0 = time.time()
    _sync(sort(big, "KEY"))
    dt_sort = time.time() - t0
    _sync(merge(big, small, "KEY", how="inner"))      # warmup/compile
    t0 = time.time()
    _sync(merge(big, small, "KEY", how="inner"))
    dt_merge = time.time() - t0
    return dt_sort, dt_merge


def _devices_reachable(timeout_s: float = None) -> bool:
    """Probe device init in a subprocess so a dead accelerator tunnel
    (hung jax.devices(), observed with the axon plugin) cannot hang the
    whole bench — the probe is killed and we fall back to CPU.  The probe
    runs INSIDE the worker's slice of the total budget, so a generous
    timeout costs nothing extra when the tunnel is healthy; 120 s default
    tolerates a slow-but-alive backend init (~60-90 s seen on the tunnel)
    without reclassifying it as dead."""
    if timeout_s is None:
        timeout_s = float(os.environ.get("H2O3_BENCH_PROBE_TIMEOUT", 120))
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('probe-ok')"],
            timeout=timeout_s, capture_output=True, text=True)
        return r.returncode == 0 and r.stdout.strip().endswith("probe-ok")
    except Exception:
        return False


def worker_main():
    if os.environ.get("H2O3_BENCH_TEST_HANG"):        # rehearsal hook
        time.sleep(10_000)
    # Probe device init (killable subprocess) unless this is an explicit
    # CPU run: the image bakes JAX_PLATFORMS=axon into the driver env, so
    # "env var set" must NOT imply "skip the probe" — a dead tunnel would
    # then hang the primary attempt for its whole budget slice instead of
    # failing over in ~probe-timeout seconds (observed in rehearsal).
    if (os.environ.get("JAX_PLATFORMS", "") != "cpu"
            and not os.environ.get("H2O3_BENCH_SKIP_PROBE")
            and not _devices_reachable()):
        # The orchestrator owns the fallback (reduced-shape CPU retry with
        # an annotated record) — exit non-zero rather than silently running
        # the full 10M-row shape on CPU here.
        print("bench: device init unreachable", file=sys.stderr, flush=True)
        sys.exit(3)
    if os.environ.get("JAX_PLATFORMS"):
        # the image pre-imports jax with a baked-in platform; the env var
        # must win (lets CI smoke-run this on CPU, and backs the dead-
        # tunnel fallback above)
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import h2o3_tpu
    from h2o3_tpu import Frame
    from h2o3_tpu.frame.vec import T_CAT
    from h2o3_tpu.models import XGBoost, DeepLearning
    from h2o3_tpu.rapids import sort, merge

    h2o3_tpu.init()
    import jax
    extra = {"platform": jax.devices()[0].platform,
             "rows": N_ROWS, "trees": N_TREES}
    with _compile_split(extra, "xgboost"):
        tps = bench_trees(Frame, T_CAT, XGBoost)
    if os.environ.get("H2O3_BENCH_SKIP_SECONDARY"):
        extra["secondaries"] = "skipped"
    else:
        try:
            sps = bench_deeplearning(Frame, DeepLearning)
            extra["deeplearning_samples_per_sec_mnist_shape"] = round(sps, 1)
        except Exception as e:                        # secondary: never fatal
            extra["deeplearning_error"] = repr(e)[:200]
        try:
            higgs_fr = make_higgs_like(Frame, N_ROWS)
        except Exception as e:
            higgs_fr = None
            extra["higgs_frame_error"] = repr(e)[:200]
        try:
            from h2o3_tpu.models import GLM
            with _compile_split(extra, "glm"):
                dt_glm = bench_glm(Frame, GLM, higgs_fr)
            glm_base = REFERENCE_GLM_HIGGS_S * N_ROWS \
                / REFERENCE_GLM_HIGGS_ROWS
            extra["glm_higgs_shape_sec"] = round(dt_glm, 3)
            extra["glm_vs_baseline"] = round(glm_base / dt_glm, 2)
            dt_path = bench_glm_lambda_path(Frame, GLM, higgs_fr)
            extra["glm_lambda_path_sec"] = round(dt_path, 3)
            extra["glm_lambda_path_vs_baseline"] = round(
                glm_base / dt_path, 2)
        except Exception as e:                        # secondary: never fatal
            extra["glm_error"] = repr(e)[:200]
        try:
            from h2o3_tpu.models import GBM
            with _compile_split(extra, "gbm_higgs"):
                dt = _timed_gbm(GBM, higgs_fr, "y")
            base = REFERENCE_GBM_HIGGS_S * min(N_ROWS,
                                               REFERENCE_GBM_HIGGS_ROWS) \
                / REFERENCE_GBM_HIGGS_ROWS
            extra["gbm_higgs_shape_sec"] = round(dt, 3)
            extra["gbm_higgs_vs_baseline"] = round(base / dt, 2)
            del higgs_fr
        except Exception as e:
            extra["gbm_higgs_error"] = repr(e)[:200]
        try:
            from h2o3_tpu.models import GBM
            n_sl = min(REFERENCE_GBM_SPRINGLEAF_ROWS, N_ROWS)
            cols, ty, dom = make_springleaf_like(Frame, T_CAT, n_sl)
            ty = {k: T_CAT for k in ty}
            fr = Frame.from_numpy(cols, types=ty, domains=dom)
            dt = _timed_gbm(GBM, fr, "target")
            base = REFERENCE_GBM_SPRINGLEAF_S * n_sl \
                / REFERENCE_GBM_SPRINGLEAF_ROWS
            extra["gbm_springleaf_shape_sec"] = round(dt, 3)
            extra["gbm_springleaf_vs_baseline"] = round(base / dt, 2)
            del fr, cols
        except Exception as e:
            extra["gbm_springleaf_error"] = repr(e)[:200]
        try:
            from h2o3_tpu.models import GBM
            n_rh = min(REFERENCE_GBM_REDHAT_ROWS, N_ROWS)
            cols, ty, dom = make_redhat_like(Frame, T_CAT, n_rh)
            ty = {k: T_CAT for k in ty}
            fr = Frame.from_numpy(cols, types=ty, domains=dom)
            dt = _timed_gbm(GBM, fr, "outcome")
            base = REFERENCE_GBM_REDHAT_S * n_rh / REFERENCE_GBM_REDHAT_ROWS
            extra["gbm_redhat_shape_sec"] = round(dt, 3)
            extra["gbm_redhat_vs_baseline"] = round(base / dt, 2)
            del fr, cols
        except Exception as e:
            extra["gbm_redhat_error"] = repr(e)[:200]
        try:
            import tempfile
            from h2o3_tpu.frame.parse import parse_csv
            with _compile_split(extra, "parse"):
                dt, mb = bench_parse(parse_csv, tempfile.gettempdir())
            extra["parse_csv_sec"] = round(dt, 3)
            extra["parse_csv_mb"] = round(mb, 1)
            extra["parse_mb_per_sec"] = round(mb / dt, 1)
            extra["parse_vs_baseline"] = round(
                (REFERENCE_PARSE_S * mb / REFERENCE_PARSE_MB) / dt, 2)
        except Exception as e:
            extra["parse_error"] = repr(e)[:200]
        try:
            dt_sort, dt_merge = bench_rapids(Frame, sort, merge)
            extra["rapids_sort_10m_sec"] = round(dt_sort, 3)
            extra["rapids_sort_vs_baseline"] = round(REFERENCE_SORT_10M_S
                                                     / dt_sort, 3)
            extra["rapids_merge_10m_sec"] = round(dt_merge, 3)
            extra["rapids_merge_vs_baseline"] = round(REFERENCE_MERGE_10M_S
                                                      / dt_merge, 3)
        except Exception as e:
            extra["rapids_error"] = repr(e)[:200]
        try:
            # online serving: packed fused-traversal latency/throughput
            # through the continuous micro-batcher (bench_pieces serve)
            from bench_pieces import serve_piece
            sv = serve_piece()
            extra["serve_p50_ms"] = round(sv["serve_p50_ms"], 3)
            extra["serve_p99_ms"] = round(sv["serve_p99_ms"], 3)
            extra["serve_qps"] = round(sv["serve_qps"], 1)
            extra["serve_packed_speedup_vs_numpy"] = round(
                sv["serve_speedup"], 2)
        except Exception as e:
            extra["serve_error"] = repr(e)[:200]
        try:
            # autotuner: cold/warm-cache "auto" knobs vs the best
            # hand-set configuration (bench_pieces autotune); the gate
            # holds autotune_vs_best to an absolute 0.97 floor
            from bench_pieces import autotune_piece
            at = autotune_piece()
            extra["autotune_hand_trees_per_sec"] = round(
                at["autotune_hand_trees_per_sec"], 2)
            extra["autotune_cold_trees_per_sec"] = round(
                at["autotune_cold_trees_per_sec"], 2)
            extra["autotune_warm_trees_per_sec"] = round(
                at["autotune_warm_trees_per_sec"], 2)
            extra["autotune_vs_best"] = round(at["autotune_vs_best"], 3)
        except Exception as e:
            extra["autotune_error"] = repr(e)[:200]
        try:
            # streaming ingest: end-to-end StreamingFrame + stream=
            # training vs parse-then-train (bench_pieces stream); the
            # gate holds stream_overlap_vs_baseline to an absolute
            # 1.176 floor (streamed <= 0.85x batch wall-clock)
            from bench_pieces import stream_piece
            st = stream_piece()
            extra["stream_batch_s"] = round(st["stream_batch_s"], 3)
            extra["stream_overlap_s"] = round(st["stream_overlap_s"], 3)
            extra["stream_overlap_vs_baseline"] = round(
                st["stream_overlap_vs_baseline"], 3)
        except Exception as e:
            extra["stream_error"] = repr(e)[:200]
        try:
            # whole-tree scan fusion: dispatch-count pin (launches per
            # tree O(1) in depth vs one-per-level) and the deep-tree
            # retrain-latency speedup (bench_pieces treescan); the
            # launch counts gate lower-better, the speedup higher
            from bench_pieces import treescan_piece
            ts = treescan_piece()
            extra["treescan_launches_per_tree_scan"] = \
                ts["treescan_launches_per_tree_scan"]
            extra["treescan_launches_per_tree_level"] = \
                ts["treescan_launches_per_tree_level"]
            extra["treescan_cold_level_s"] = round(
                ts["treescan_cold_level_s"], 3)
            extra["treescan_cold_scan_s"] = round(
                ts["treescan_cold_scan_s"], 3)
            extra["treescan_trees_per_sec_level"] = round(
                ts["treescan_trees_per_sec_level"], 2)
            extra["treescan_trees_per_sec_scan"] = round(
                ts["treescan_trees_per_sec_scan"], 2)
            extra["treescan_scan_vs_level_speedup"] = round(
                ts["treescan_scan_vs_level_speedup"], 3)
        except Exception as e:
            extra["treescan_error"] = repr(e)[:200]
        try:
            # batched grid sweeps: dispatch-count pin (one cohort
            # program serves G members per chunk at a single member's
            # launch count) + bitwise batched-vs-wave parity
            # (bench_pieces grid); grid_batched_vs_sequential holds an
            # absolute 4.0 floor in the gate
            from bench_pieces import grid_piece
            gp = grid_piece()
            extra["grid_launches_batched"] = gp["grid_launches_batched"]
            extra["grid_batched_vs_sequential"] = round(
                gp["grid_batched_vs_sequential"], 3)
            extra["grid_batched_wall_s"] = round(
                gp["grid_batched_wall_s"], 3)
            extra["grid_sequential_wall_s"] = round(
                gp["grid_sequential_wall_s"], 3)
        except Exception as e:
            extra["grid_error"] = repr(e)[:200]
    compiles, compile_s = _ledger_totals()
    if compiles:
        extra["compiles_total"] = compiles
        extra["compile_s_total"] = round(compile_s, 3)
    print(json.dumps({
        "metric": "xgboost_trees_per_sec_airlines10m_shape",
        "value": round(tps, 3),
        "unit": "trees/sec",
        "vs_baseline": round(tps / REFERENCE_TREES_PER_SEC, 3),
        "extra": extra,
    }), flush=True)


def _attempt(env_overrides, timeout_s):
    """Run the bench worker in a subprocess; return (record, error)."""
    env = os.environ.copy()
    env.update(env_overrides)
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env, timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired as e:
        tail = ""
        for stream in (e.stderr, e.stdout):
            if stream:
                if isinstance(stream, bytes):
                    stream = stream.decode("utf-8", "replace")
                tail = stream[-400:]
                break
        return None, f"worker timed out after {timeout_s}s; tail: {tail}"
    except Exception as e:                               # pragma: no cover
        return None, repr(e)[:400]
    if r.stderr:
        sys.stderr.write(r.stderr[-4000:])
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            return rec, None
    tail = (r.stderr or r.stdout or "")[-400:]
    return None, f"worker rc={r.returncode}, no JSON record; tail: {tail}"


def orchestrate():
    """Always emit one JSON record and exit 0, whatever the hardware does.

    Budget arithmetic (the r03 failure mode): ONE total wall-clock budget is
    split between the primary (accelerator) attempt and a guaranteed reserve
    for the CPU fallback.  The fallback shape is sized to single-digit
    minutes (100 k rows, 10 trees, no secondaries) so the reserve suffices
    even on a loaded host; whatever happens, the record lands before the
    driver's outer clock can fire.
    """
    errors = {}
    start = time.time()
    total_budget = int(os.environ.get("H2O3_BENCH_TOTAL_BUDGET", 2100))
    reserve = min(int(os.environ.get("H2O3_BENCH_FALLBACK_RESERVE", 600)),
                  max(total_budget - 60, 60))
    deadline = start + total_budget
    primary_timeout = max(60, deadline - time.time() - reserve)
    rec, err = _attempt({}, primary_timeout)
    if rec is None:
        errors["primary_attempt"] = err
        print(f"bench: primary attempt failed ({err}); re-running on CPU",
              file=sys.stderr, flush=True)
        cpu_rows = min(N_ROWS, int(os.environ.get(
            "H2O3_BENCH_CPU_ROWS", 100_000)))
        cpu_trees = min(N_TREES, int(os.environ.get(
            "H2O3_BENCH_CPU_TREES", 10)))
        cpu_timeout = max(60, deadline - time.time() - 15)
        rec, err = _attempt(
            {"JAX_PLATFORMS": "cpu", "H2O3_BENCH_SKIP_PROBE": "1",
             "H2O3_BENCH_TEST_HANG": "", "H2O3_BENCH_SKIP_SECONDARY": "1",
             "H2O3_BENCH_ROWS": str(cpu_rows),
             "H2O3_BENCH_TREES": str(cpu_trees)}, cpu_timeout)
        if rec is None:
            errors["cpu_attempt"] = err
            rec = {"metric": "xgboost_trees_per_sec_airlines10m_shape",
                   "value": 0.0, "unit": "trees/sec", "vs_baseline": 0.0,
                   "extra": {"platform": "none"}}
    if errors:
        rec.setdefault("extra", {})["fallback_errors"] = errors
    rec.setdefault("extra", {})["bench_wall_s"] = round(time.time() - start, 1)
    print(json.dumps(rec), flush=True)


def multichip_main():
    """``--multichip``: the {8,16,32}-virtual-device scaling curve.

    Runs the airlines-shape tree bench once per device count on the CPU
    mesh (``--xla_force_host_platform_device_count``, hierarchical
    ("hosts","chips") geometry via H2O3_TPU_HOSTS) and writes
    MULTICHIP_r06.json with one ``{n_devices, trees_per_sec}`` entry per
    point plus the 8→32 scaling ratio.  On real multi-host hardware the
    same entry point produces the TPU curve — only the env differs.
    Shape is the CPU-fallback shape (rows/trees overridable) so the
    whole curve lands in minutes.
    """
    out_path = os.environ.get("H2O3_MULTICHIP_OUT", "MULTICHIP_r06.json")
    rows = int(os.environ.get("H2O3_MULTICHIP_ROWS", 100_000))
    trees = int(os.environ.get("H2O3_MULTICHIP_TREES", 10))
    per_point_budget = int(os.environ.get("H2O3_MULTICHIP_BUDGET", 600))
    points = ((8, 2), (16, 2), (32, 4))
    entries = []
    for n_dev, hosts in points:
        t0 = time.time()
        rec, err = _attempt({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS":
                f"--xla_force_host_platform_device_count={n_dev}",
            "H2O3_TPU_HOSTS": str(hosts),
            "H2O3_TPU_REDUCE_MODE": "hier",
            "H2O3_BENCH_SKIP_PROBE": "1",
            "H2O3_BENCH_SKIP_SECONDARY": "1",
            "H2O3_BENCH_ROWS": str(rows),
            "H2O3_BENCH_TREES": str(trees),
        }, per_point_budget)
        entry = {"n_devices": n_dev, "hosts": hosts,
                 "chips_per_host": n_dev // hosts,
                 "trees_per_sec": rec["value"] if rec else 0.0,
                 "wall_s": round(time.time() - t0, 1)}
        if err:
            entry["error"] = err
        entries.append(entry)
        print(json.dumps(entry), flush=True)
    t8 = next((e["trees_per_sec"] for e in entries
               if e["n_devices"] == 8), 0.0)
    t32 = next((e["trees_per_sec"] for e in entries
                if e["n_devices"] == 32), 0.0)
    out = {
        "bench": "xgboost_trees_per_sec_airlines_shape",
        "rows": rows, "trees": trees,
        "reduce_mode": "hier",
        "mesh": "hierarchical (hosts, chips) virtual CPU mesh",
        "entries": entries,
        "scaling_8_to_32": round(t32 / t8, 3) if t8 else 0.0,
        "note": ("virtual devices share one physical CPU: the curve "
                 "validates the collective schedule and SPMD program at "
                 "each geometry; real speedup requires the TPU pod "
                 "(ROADMAP item 1 acceptance)"),
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps({"multichip": out_path,
                      "scaling_8_to_32": out["scaling_8_to_32"]}),
          flush=True)


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker_main()
    elif "--multichip" in sys.argv:
        multichip_main()
    else:
        orchestrate()
