"""Benchmark: DeepLearning MLP training throughput (samples/sec/chip).

The reference logs rows/sec for hex.deeplearning (DeepLearning.java:648,
DeepLearningModel.java:580 "samples/sec").  H2O's Java Hogwild fprop/bprop on
a CPU node sustains on the order of 5e4 samples/sec for a 784->200->200->10
MLP; BASELINE.json's north star is DeepLearning samples/sec/chip.  We report
vs_baseline against that 5e4 reference-shape number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np

REFERENCE_SAMPLES_PER_SEC = 5.0e4   # H2O Java DL per-node ballpark (see above)


def main():
    import jax
    import h2o3_tpu
    from h2o3_tpu import Frame
    from h2o3_tpu.models.deeplearning import DeepLearning

    h2o3_tpu.init()
    rng = np.random.default_rng(0)
    n, p, k = 200_000, 784, 10
    X = rng.normal(size=(n, p)).astype(np.float32)
    w_true = rng.normal(size=(p, k)).astype(np.float32)
    labels = np.argmax(X @ w_true + rng.normal(size=(n, k)), axis=1)
    cols = {f"x{j}": X[:, j] for j in range(p)}
    cols["y"] = labels.astype(str).astype(object)
    fr = Frame.from_numpy(cols)

    # warmup: compile the training program
    DeepLearning(response_column="y", hidden=[256, 256], epochs=0.02,
                 mini_batch_size=512, seed=1, stopping_rounds=0,
                 standardize=False).train(fr)
    # timed run
    t0 = time.time()
    m = DeepLearning(response_column="y", hidden=[256, 256], epochs=2.0,
                     mini_batch_size=512, seed=1, stopping_rounds=0,
                     standardize=False).train(fr)
    dt = time.time() - t0
    samples = m.output["samples_trained"]
    sps = samples / dt
    print(json.dumps({
        "metric": "deeplearning_samples_per_sec_per_chip",
        "value": round(sps, 1),
        "unit": "samples/sec",
        "vs_baseline": round(sps / REFERENCE_SAMPLES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
