"""Benchmark suite: tpu_hist boosting (headline), DeepLearning, Rapids.

North star (BASELINE.json / SURVEY.md §6): the reference's XGBoost gpu_hist
benchmark gate trains 100 trees on airlines-10m in 22-52s on its GPU node
(compareBenchmarksStage.groovy:174-177) → ~1.9-4.5 trees/sec.  vs_baseline
divides our trees/sec by the best end of that interval (4.5), measured on an
airlines-shaped synthetic set: 10M rows, mixed numeric/categorical, binary
response, max_depth=6, nbins=256 — the same work shape gpu_hist does.

Secondary metrics (BASELINE.md):
 - DeepLearning samples/sec, MNIST shape (DeepLearning.java:648 rows/sec
   hook; no published reference value → no vs_baseline).
 - Rapids sort / merge wall-clock at 10M x 2 cols (reference Jenkins gate:
   sort 2-7 s, merge 4-10 s; vs_baseline divides the reference BEST time by
   ours, so >1 means faster than the reference's best).

Prints ONE JSON line: the headline record with an "extra" dict carrying the
secondary metrics.

Robustness contract (BENCH_r02/r03 post-mortems): the measured region runs in
a *worker subprocess*; the parent orchestrator owns ONE total wall-clock
budget (H2O3_BENCH_TOTAL_BUDGET, default 2100 s) covering probe + primary +
fallback, not per-attempt timeouts — r03 died rc=124 because 2×2700 s of
per-attempt allowance exceeded the driver's outer clock.  The primary attempt
gets the budget minus a guaranteed fallback reserve; the CPU fallback runs a
minutes-scale shape (100 k rows × 10 trees, secondaries skipped) so it always
finishes inside the reserve.  The orchestrator always prints a JSON record and
exits 0.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

REFERENCE_TREES_PER_SEC = 4.5     # best of the reference gpu_hist interval
REFERENCE_SORT_10M_S = 2.0        # best of Jenkins sort interval (10M rows)
REFERENCE_MERGE_10M_S = 4.0       # best of Jenkins merge interval (10M rows)
# H2O3_BENCH_ROWS/TREES: smoke-test overrides (CI runs the full shape)
N_ROWS = int(os.environ.get("H2O3_BENCH_ROWS", 10_000_000))
N_TREES = int(os.environ.get("H2O3_BENCH_TREES", 50))


def make_airlines_like(n):
    rng = np.random.default_rng(0)
    cols = {
        "year": rng.integers(1987, 2008, n).astype(np.float32),
        "month": rng.integers(1, 13, n).astype(np.float32),
        "day_of_week": rng.integers(1, 8, n).astype(np.float32),
        "crs_dep_time": rng.integers(0, 2400, n).astype(np.float32),
        "distance": np.abs(rng.normal(700, 500, n)).astype(np.float32),
        "carrier": rng.integers(0, 22, n),
        "origin": rng.integers(0, 300, n),
        "dest": rng.integers(0, 300, n),
    }
    logit = (0.002 * (cols["crs_dep_time"] / 100 - 12) ** 2
             - 0.0005 * cols["distance"] / 100
             + 0.2 * np.isin(cols["day_of_week"], (5, 7))
             + 0.1 * rng.normal(size=n))
    dep_delayed = rng.random(n) < 1 / (1 + np.exp(-logit))
    cols["dep_delayed_15min"] = np.where(dep_delayed, "YES", "NO").astype(object)
    types = {"carrier": "cat", "origin": "cat", "dest": "cat"}
    domains = {"carrier": [str(i) for i in range(22)],
               "origin": [str(i) for i in range(300)],
               "dest": [str(i) for i in range(300)]}
    return cols, types, domains


def bench_trees(Frame, T_CAT, XGBoost):
    cols, types, domains = make_airlines_like(N_ROWS)
    types = {k: (T_CAT if v == "cat" else v) for k, v in types.items()}
    fr = Frame.from_numpy(cols, types=types, domains=domains)
    config = dict(response_column="dep_delayed_15min", max_depth=6,
                  nbins=256, seed=1, score_tree_interval=10 ** 9)
    # warmup: two full scan chunks — the first compiles the exact program the
    # timed run reuses, the second absorbs the one-off first-execution
    # anomaly (~6 s, observed on the axon tunnel after each fresh compile)
    XGBoost(ntrees=20, **config).train(fr)
    t0 = time.time()
    XGBoost(ntrees=N_TREES, **config).train(fr)
    dt = time.time() - t0
    del fr
    return N_TREES / dt


def bench_deeplearning(Frame, DeepLearning):
    """MNIST-shape MLP throughput (samples/sec/chip)."""
    n, d = min(60_000, max(N_ROWS, 4_096)), 784
    rng = np.random.default_rng(1)
    X = (rng.random((n, d)) * 255).astype(np.float32)
    y = rng.integers(0, 10, n)
    cols = {f"p{j}": X[:, j] for j in range(d)}
    cols["label"] = np.array([str(v) for v in y], dtype=object)
    fr = Frame.from_numpy(cols)
    kw = dict(response_column="label", hidden=(200, 200),
              mini_batch_size=512, score_interval=1e9, stopping_rounds=0,
              seed=1)
    DeepLearning(epochs=0.2, **kw).train(fr)          # compile warmup
    epochs = 3.0 if N_ROWS >= 1_000_000 else 0.5      # smoke override
    t0 = time.time()
    DeepLearning(epochs=epochs, **kw).train(fr)
    dt = time.time() - t0
    del fr
    return epochs * n / dt


REFERENCE_GLM_HIGGS_S = 47.0      # best of the higgs GLM intervals
REFERENCE_GLM_HIGGS_ROWS = 11_000_000
# (COORDINATE_DESCENT 47-54 s, IRLSM 65-73 s —
#  compareBenchmarksStage.groovy:97-104; 11M rows x 28 numerics.
#  The conservative best-of-either-solver bound is scaled linearly to the
#  benched row count so reduced-shape smoke runs stay honest.)


def bench_glm(Frame, GLM):
    """Higgs-shape binomial GLM (IRLSM, lambda=0): train-time seconds."""
    n, d = N_ROWS, 28
    rng = np.random.default_rng(3)
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = rng.normal(size=d) * 0.3
    logit = X @ beta - 0.2
    yy = rng.random(n) < 1 / (1 + np.exp(-logit))
    cols = {f"f{j}": X[:, j] for j in range(d)}
    cols["y"] = np.where(yy, "s", "b").astype(object)
    fr = Frame.from_numpy(cols)
    kw = dict(family="binomial", response_column="y", lambda_=0.0)
    GLM(**kw).train(fr)                               # warmup/compile
    t0 = time.time()
    GLM(**kw).train(fr)
    dt = time.time() - t0
    del fr
    return dt


def _sync(frame):
    """Force completion of a frame's device work (async dispatch barrier).

    A one-element fetch of each output column blocks until its whole buffer
    exists; block_until_ready does NOT synchronize over the axon tunnel
    (PROFILE.md), so a tiny real fetch is the reliable sync point.
    """
    for v in frame.vecs:
        if v.data is not None:
            np.asarray(v.data[:1])


def bench_rapids(Frame, sort, merge):
    n = N_ROWS
    rng = np.random.default_rng(2)
    big = Frame.from_numpy({
        "KEY": rng.integers(0, n, n).astype(np.float64),
        "X2": rng.random(n)})
    small = Frame.from_numpy({
        "KEY": rng.integers(0, n, n // 10).astype(np.float64),
        "Y2": rng.random(n // 10)})
    _sync(sort(big, "KEY"))                           # warmup/compile
    t0 = time.time()
    _sync(sort(big, "KEY"))
    dt_sort = time.time() - t0
    _sync(merge(big, small, "KEY", how="inner"))      # warmup/compile
    t0 = time.time()
    _sync(merge(big, small, "KEY", how="inner"))
    dt_merge = time.time() - t0
    return dt_sort, dt_merge


def _devices_reachable(timeout_s: float = None) -> bool:
    """Probe device init in a subprocess so a dead accelerator tunnel
    (hung jax.devices(), observed with the axon plugin) cannot hang the
    whole bench — the probe is killed and we fall back to CPU.  The probe
    runs INSIDE the worker's slice of the total budget, so a generous
    timeout costs nothing extra when the tunnel is healthy; 120 s default
    tolerates a slow-but-alive backend init (~60-90 s seen on the tunnel)
    without reclassifying it as dead."""
    if timeout_s is None:
        timeout_s = float(os.environ.get("H2O3_BENCH_PROBE_TIMEOUT", 120))
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('probe-ok')"],
            timeout=timeout_s, capture_output=True, text=True)
        return r.returncode == 0 and r.stdout.strip().endswith("probe-ok")
    except Exception:
        return False


def worker_main():
    if os.environ.get("H2O3_BENCH_TEST_HANG"):        # rehearsal hook
        time.sleep(10_000)
    # Probe device init (killable subprocess) unless this is an explicit
    # CPU run: the image bakes JAX_PLATFORMS=axon into the driver env, so
    # "env var set" must NOT imply "skip the probe" — a dead tunnel would
    # then hang the primary attempt for its whole budget slice instead of
    # failing over in ~probe-timeout seconds (observed in rehearsal).
    if (os.environ.get("JAX_PLATFORMS", "") != "cpu"
            and not os.environ.get("H2O3_BENCH_SKIP_PROBE")
            and not _devices_reachable()):
        # The orchestrator owns the fallback (reduced-shape CPU retry with
        # an annotated record) — exit non-zero rather than silently running
        # the full 10M-row shape on CPU here.
        print("bench: device init unreachable", file=sys.stderr, flush=True)
        sys.exit(3)
    if os.environ.get("JAX_PLATFORMS"):
        # the image pre-imports jax with a baked-in platform; the env var
        # must win (lets CI smoke-run this on CPU, and backs the dead-
        # tunnel fallback above)
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import h2o3_tpu
    from h2o3_tpu import Frame
    from h2o3_tpu.frame.vec import T_CAT
    from h2o3_tpu.models import XGBoost, DeepLearning
    from h2o3_tpu.rapids import sort, merge

    h2o3_tpu.init()
    import jax
    extra = {"platform": jax.devices()[0].platform,
             "rows": N_ROWS, "trees": N_TREES}
    tps = bench_trees(Frame, T_CAT, XGBoost)
    if os.environ.get("H2O3_BENCH_SKIP_SECONDARY"):
        extra["secondaries"] = "skipped"
    else:
        try:
            sps = bench_deeplearning(Frame, DeepLearning)
            extra["deeplearning_samples_per_sec_mnist_shape"] = round(sps, 1)
        except Exception as e:                        # secondary: never fatal
            extra["deeplearning_error"] = repr(e)[:200]
        try:
            from h2o3_tpu.models import GLM
            dt_glm = bench_glm(Frame, GLM)
            glm_base = REFERENCE_GLM_HIGGS_S * N_ROWS \
                / REFERENCE_GLM_HIGGS_ROWS
            extra["glm_higgs_shape_sec"] = round(dt_glm, 3)
            extra["glm_vs_baseline"] = round(glm_base / dt_glm, 2)
        except Exception as e:                        # secondary: never fatal
            extra["glm_error"] = repr(e)[:200]
        try:
            dt_sort, dt_merge = bench_rapids(Frame, sort, merge)
            extra["rapids_sort_10m_sec"] = round(dt_sort, 3)
            extra["rapids_sort_vs_baseline"] = round(REFERENCE_SORT_10M_S
                                                     / dt_sort, 3)
            extra["rapids_merge_10m_sec"] = round(dt_merge, 3)
            extra["rapids_merge_vs_baseline"] = round(REFERENCE_MERGE_10M_S
                                                      / dt_merge, 3)
        except Exception as e:
            extra["rapids_error"] = repr(e)[:200]
    print(json.dumps({
        "metric": "xgboost_trees_per_sec_airlines10m_shape",
        "value": round(tps, 3),
        "unit": "trees/sec",
        "vs_baseline": round(tps / REFERENCE_TREES_PER_SEC, 3),
        "extra": extra,
    }), flush=True)


def _attempt(env_overrides, timeout_s):
    """Run the bench worker in a subprocess; return (record, error)."""
    env = os.environ.copy()
    env.update(env_overrides)
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env, timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired as e:
        tail = ""
        for stream in (e.stderr, e.stdout):
            if stream:
                if isinstance(stream, bytes):
                    stream = stream.decode("utf-8", "replace")
                tail = stream[-400:]
                break
        return None, f"worker timed out after {timeout_s}s; tail: {tail}"
    except Exception as e:                               # pragma: no cover
        return None, repr(e)[:400]
    if r.stderr:
        sys.stderr.write(r.stderr[-4000:])
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            return rec, None
    tail = (r.stderr or r.stdout or "")[-400:]
    return None, f"worker rc={r.returncode}, no JSON record; tail: {tail}"


def orchestrate():
    """Always emit one JSON record and exit 0, whatever the hardware does.

    Budget arithmetic (the r03 failure mode): ONE total wall-clock budget is
    split between the primary (accelerator) attempt and a guaranteed reserve
    for the CPU fallback.  The fallback shape is sized to single-digit
    minutes (100 k rows, 10 trees, no secondaries) so the reserve suffices
    even on a loaded host; whatever happens, the record lands before the
    driver's outer clock can fire.
    """
    errors = {}
    start = time.time()
    total_budget = int(os.environ.get("H2O3_BENCH_TOTAL_BUDGET", 2100))
    reserve = min(int(os.environ.get("H2O3_BENCH_FALLBACK_RESERVE", 600)),
                  max(total_budget - 60, 60))
    deadline = start + total_budget
    primary_timeout = max(60, deadline - time.time() - reserve)
    rec, err = _attempt({}, primary_timeout)
    if rec is None:
        errors["primary_attempt"] = err
        print(f"bench: primary attempt failed ({err}); re-running on CPU",
              file=sys.stderr, flush=True)
        cpu_rows = min(N_ROWS, int(os.environ.get(
            "H2O3_BENCH_CPU_ROWS", 100_000)))
        cpu_trees = min(N_TREES, int(os.environ.get(
            "H2O3_BENCH_CPU_TREES", 10)))
        cpu_timeout = max(60, deadline - time.time() - 15)
        rec, err = _attempt(
            {"JAX_PLATFORMS": "cpu", "H2O3_BENCH_SKIP_PROBE": "1",
             "H2O3_BENCH_TEST_HANG": "", "H2O3_BENCH_SKIP_SECONDARY": "1",
             "H2O3_BENCH_ROWS": str(cpu_rows),
             "H2O3_BENCH_TREES": str(cpu_trees)}, cpu_timeout)
        if rec is None:
            errors["cpu_attempt"] = err
            rec = {"metric": "xgboost_trees_per_sec_airlines10m_shape",
                   "value": 0.0, "unit": "trees/sec", "vs_baseline": 0.0,
                   "extra": {"platform": "none"}}
    if errors:
        rec.setdefault("extra", {})["fallback_errors"] = errors
    rec.setdefault("extra", {})["bench_wall_s"] = round(time.time() - start, 1)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker_main()
    else:
        orchestrate()
