"""Benchmark: tpu_hist boosting throughput (trees/sec, Airlines-10M shape).

North star (BASELINE.json / SURVEY.md §6): the reference's XGBoost gpu_hist
benchmark gate trains 100 trees on airlines-10m in 22-52s on its GPU node
(compareBenchmarksStage.groovy:174-177) → ~1.9-4.5 trees/sec.  vs_baseline
divides our trees/sec by the best end of that interval (4.5), measured on an
airlines-shaped synthetic set: 10M rows, mixed numeric/categorical, binary
response, max_depth=6, nbins=256 — the same work shape gpu_hist does.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np

REFERENCE_TREES_PER_SEC = 4.5     # best of the reference gpu_hist interval
N_ROWS = 10_000_000
N_TREES = 50


def make_airlines_like(n):
    rng = np.random.default_rng(0)
    cols = {
        "year": rng.integers(1987, 2008, n).astype(np.float32),
        "month": rng.integers(1, 13, n).astype(np.float32),
        "day_of_week": rng.integers(1, 8, n).astype(np.float32),
        "crs_dep_time": rng.integers(0, 2400, n).astype(np.float32),
        "distance": np.abs(rng.normal(700, 500, n)).astype(np.float32),
        "carrier": rng.integers(0, 22, n),
        "origin": rng.integers(0, 300, n),
        "dest": rng.integers(0, 300, n),
    }
    logit = (0.002 * (cols["crs_dep_time"] / 100 - 12) ** 2
             - 0.0005 * cols["distance"] / 100
             + 0.2 * np.isin(cols["day_of_week"], (5, 7))
             + 0.1 * rng.normal(size=n))
    dep_delayed = rng.random(n) < 1 / (1 + np.exp(-logit))
    cols["dep_delayed_15min"] = np.where(dep_delayed, "YES", "NO").astype(object)
    types = {"carrier": "cat", "origin": "cat", "dest": "cat"}
    domains = {"carrier": [str(i) for i in range(22)],
               "origin": [str(i) for i in range(300)],
               "dest": [str(i) for i in range(300)]}
    return cols, types, domains


def main():
    import h2o3_tpu
    from h2o3_tpu import Frame
    from h2o3_tpu.frame.vec import T_CAT
    from h2o3_tpu.models import XGBoost

    h2o3_tpu.init()
    cols, types, domains = make_airlines_like(N_ROWS)
    types = {k: (T_CAT if v == "cat" else v) for k, v in types.items()}
    fr = Frame.from_numpy(cols, types=types, domains=domains)

    config = dict(response_column="dep_delayed_15min", max_depth=6,
                  nbins=256, seed=1, score_tree_interval=10 ** 9)
    # warmup: two full scan chunks — the first compiles the exact program the
    # timed run reuses, the second absorbs the one-off first-execution
    # anomaly (~6 s, observed on the axon tunnel after each fresh compile)
    XGBoost(ntrees=20, **config).train(fr)
    t0 = time.time()
    XGBoost(ntrees=N_TREES, **config).train(fr)
    dt = time.time() - t0
    tps = N_TREES / dt
    print(json.dumps({
        "metric": "xgboost_trees_per_sec_airlines10m_shape",
        "value": round(tps, 3),
        "unit": "trees/sec",
        "vs_baseline": round(tps / REFERENCE_TREES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
