"""Shared bench timing/sync helpers for bench.py and bench_pieces.py.

One home for the two hard-won measurement rules (PROFILE.md round-2
methodology), previously copy-pasted across the bench entry points:

 - **Sync is a tiny REAL device->host fetch.**  ``jax.block_until_ready``
   does NOT synchronize over the axon tunnel — PROFILE.md measured a
   1.1 TFLOP matmul at "0.03 ms" with it — so every sync point here
   fetches one element, which blocks until the whole buffer exists.
 - **Per-dispatch overhead is ~4 ms on the tunnel**: single-call timings
   are meaningless below ~10 ms.  ``timed_amortized`` runs REPS dependent
   invocations inside ONE jit (the carry feeds back into an operand so
   XLA cannot CSE or reorder the calls) and divides.

jax imports stay inside the functions: bench.py's orchestrator must be
importable before any backend is initialized (it rewrites JAX_PLATFORMS
for the worker subprocess).
"""

import time


def device_sync(x):
    """Block until ``x``'s buffer exists: a one-element device->host fetch,
    the tunnel-safe sync point."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    np.asarray(jax.device_get(jnp.ravel(x)[:1]))


def sync_frame(frame):
    """Force completion of a frame's device work (async dispatch barrier):
    one tiny fetch per output column."""
    for v in frame.vecs:
        if v.data is not None:
            device_sync(v.data)


def timed_amortized(fn_build, *args, reps: int = 20) -> float:
    """Milliseconds per invocation of ``fn_build(acc, *args) -> new acc``,
    timed as ``reps`` dependent iterations inside one jit.

    Runs the jitted loop three times: compile+warmup, a second pass to
    absorb the remote backend's first-execution anomaly (~6-17 s observed
    after each fresh compile on the tunnel), then the timed pass.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _reps(*a):
        def body(i, acc):
            return fn_build(acc, *a)
        return jax.lax.fori_loop(0, reps, body, jnp.float32(0.0))

    out = _reps(*args)            # compile + warmup
    device_sync(out)
    out = _reps(*args)            # absorb first-exec anomaly
    device_sync(out)
    t0 = time.perf_counter()
    out = _reps(*args)
    device_sync(out)
    return (time.perf_counter() - t0) / reps * 1e3
