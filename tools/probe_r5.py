"""Round-5 on-chip probe: per-phase timing of the new bench shapes.

Usage: python tools/probe_r5.py [springleaf|redhat|higgs|dl|glmpath|parse]
Each phase prints its wall clock so budget blowups are attributable.
"""

import os
import sys
import time

import numpy as np

# repo root on sys.path at runtime (PYTHONPATH breaks axon plugin discovery)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def t(label, fn):
    t0 = time.time()
    out = fn()
    print(f"  {label}: {time.time() - t0:.2f}s", flush=True)
    return out


def main():
    what = sys.argv[1] if len(sys.argv) > 1 else "springleaf"
    rows = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    import bench
    import h2o3_tpu
    from h2o3_tpu import Frame
    from h2o3_tpu.frame.vec import T_CAT
    h2o3_tpu.init()
    import jax
    print("platform:", jax.devices()[0].platform, flush=True)

    if what == "springleaf":
        n = rows or 145_000
        cols, ty, dom = t("gen", lambda: bench.make_springleaf_like(
            Frame, T_CAT, n))
        ty = {k: T_CAT for k in ty}
        fr = t("frame", lambda: Frame.from_numpy(cols, types=ty,
                                                 domains=dom))
        from h2o3_tpu.models import GBM
        cfg = dict(bench._GBM_GATE, response_column="target")
        t("warmup10", lambda: GBM(**{**cfg, "ntrees": 10}).train(fr))
        m = t("train50", lambda: GBM(**cfg).train(fr))
        print("  efb_bundles:", m.output.get("efb_bundles", "none"),
              flush=True)
    elif what == "redhat":
        n = rows or 2_200_000
        cols, ty, dom = t("gen", lambda: bench.make_redhat_like(
            Frame, T_CAT, n))
        ty = {k: T_CAT for k in ty}
        fr = t("frame", lambda: Frame.from_numpy(cols, types=ty,
                                                 domains=dom))
        from h2o3_tpu.models import GBM
        cfg = dict(bench._GBM_GATE, response_column="outcome")
        t("warmup10", lambda: GBM(**{**cfg, "ntrees": 10}).train(fr))
        t("train50", lambda: GBM(**cfg).train(fr))
    elif what == "higgs":
        n = rows or 10_000_000
        fr = t("gen+frame", lambda: bench.make_higgs_like(Frame, n))
        from h2o3_tpu.models import GBM
        cfg = dict(bench._GBM_GATE, response_column="y")
        t("warmup10", lambda: GBM(**{**cfg, "ntrees": 10}).train(fr))
        t("train50", lambda: GBM(**cfg).train(fr))
    elif what == "glmpath":
        n = rows or 10_000_000
        fr = t("gen+frame", lambda: bench.make_higgs_like(Frame, n))
        from h2o3_tpu.models import GLM
        kw = dict(family="binomial", response_column="y",
                  lambda_search=True, nlambdas=100, alpha=0.5)
        t("warmup", lambda: GLM(**kw).train(fr))
        t("timed", lambda: GLM(**kw).train(fr))
    elif what == "dl":
        from h2o3_tpu.models import DeepLearning
        import bench as b
        b.N_ROWS = rows or 10_000_000
        sps = t("dl", lambda: b.bench_deeplearning(Frame, DeepLearning))
        print(f"  samples/s: {sps:,.0f}", flush=True)
    elif what == "parse":
        import tempfile
        from h2o3_tpu.frame.parse import parse_csv
        dt, mb = bench.bench_parse(parse_csv, tempfile.gettempdir())
        print(f"  parse: {dt:.2f}s for {mb:.0f}MB = {mb/dt:.0f} MB/s",
              flush=True)


if __name__ == "__main__":
    main()
