#!/usr/bin/env bash
# chaos.sh — THE chaos-suite entry point (ROADMAP lists it next to
# tier1.sh).  One command runs the full survivable-training matrix:
#
#   - kill-resume-verify: a real subprocess is hard-killed (exit 137)
#     mid-GBM via H2O3_TPU_FAULT_INJECT, a fresh process re-imports the
#     journaled frame and recovery.resume() continues from the progress
#     snapshot; final predictions must match an uninterrupted run
#     (tests/test_chaos.py),
#   - deep-level kill: the same kill-resume-verify scenario with the
#     node-sparse deep-level layout engaged (hist_layout="sparse" past
#     its depth threshold; deep_level injection point)
#     (tests/test_chaos.py),
#   - coordinator hard-kill: the DKV coordinator os._exit(137)s mid-GBM
#     (dkv_handle:coordinator:N), is restarted on the same port +
#     recovery dir, the worker rides out the outage on its retry budget,
#     fences the new epoch, and the model matches the uninterrupted run
#     (tests/test_chaos.py),
#   - mesh host-kill: the same hard-kill scenario on the hierarchical
#     2-host ("hosts","chips") mesh with the staged ICI+DCN reduce
#     engaged; a fresh process rebuilds the same mesh, resumes, and
#     matches the uninterrupted run (tests/test_mesh_hier.py),
#   - WAL+snapshot rehydration, epoch fencing/re-push, exactly-once
#     dedup across a real SIGKILL, handler hardening
#     (tests/test_dkv_wal.py),
#   - DKV retry budget + exactly-once under dropped responses, plain and
#     TLS (tests/test_dkv_retry.py),
#   - in-process snapshot/journal/resume contracts
#     (tests/test_snapshot_recovery.py).
#
# Exits with pytest's return code.
set -o pipefail
cd "$(dirname "$0")/.."
timeout -k 10 1500 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_chaos.py tests/test_dkv_wal.py tests/test_dkv_retry.py \
    tests/test_snapshot_recovery.py tests/test_failure.py \
    tests/test_mesh_hier.py::test_mesh_host_kill_resume_verify \
    -q -p no:cacheprovider -p no:xdist -p no:randomly
exit $?
