#!/usr/bin/env bash
# chaos.sh — THE chaos-suite entry point (ROADMAP lists it next to
# tier1.sh).  One command runs the full survivable-training matrix,
# one ROW at a time, and writes a per-row PASS/FAIL summary artifact
# (${H2O3_CHAOS_ROWS:-/tmp/chaos_rows.txt}) so CI surfaces exactly
# which scenario regressed:
#
#   - kill-resume:        a real subprocess is hard-killed (exit 137)
#     mid-GBM via H2O3_TPU_FAULT_INJECT, a fresh process re-imports the
#     journaled frame and recovery.resume() continues from the progress
#     snapshot; final predictions must match an uninterrupted run,
#     including the deep-level sparse layout and multinomial variants
#     and the no-snapshot resume-from-zero row (tests/test_chaos.py),
#   - grid-batch:         a 2-member batched grid cohort (one compiled
#     program for both members) hard-killed at a tree-chunk fence; a
#     fresh process finds one resumable journal entry PER MEMBER and
#     recovery.resume() finishes each through the sequential checkpoint
#     path to the uninterrupted batched run's predictions
#     (tests/test_chaos.py),
#   - scan-kill:          the same hard-kill at a tree-chunk fence with
#     tree_program="scan" engaged — the whole-tree scan program's
#     coarser per-tree-chunk snapshots resume to predictions equal to
#     the uninterrupted run (tests/test_chaos.py),
#   - coordinator-kill:   the DKV coordinator os._exit(137)s mid-GBM,
#     is restarted on the same port + recovery dir, the worker rides
#     out the outage on its retry budget and fences the new epoch
#     (tests/test_chaos.py),
#   - multitenant-kill:   1 large + 3 small concurrent jobs under the
#     fair-share scheduler, host hard-killed mid-load; a fresh process
#     re-admits every journaled job (scheduler.readmit) and all four
#     models match uninterrupted runs (tests/test_chaos.py),
#   - host-join:          a host joins mid-train; the elastic observer
#     arms exactly one fenced mesh rebuild at a chunk boundary
#     (recompiles_total{reason="cluster_reinit"}) (tests/test_chaos.py),
#   - scheduler:          fair-share/admission/requeue/readmit/
#     quarantine unit matrix (tests/test_scheduler.py),
#   - mesh host-kill: the hard-kill scenario on the hierarchical 2-host
#     ("hosts","chips") mesh with the staged ICI+DCN reduce engaged
#     (tests/test_mesh_hier.py),
#   - WAL+snapshot rehydration, epoch fencing/re-push, exactly-once
#     dedup across a real SIGKILL, handler hardening
#     (tests/test_dkv_wal.py),
#   - DKV retry budget + exactly-once under dropped responses, plain and
#     TLS (tests/test_dkv_retry.py),
#   - in-process snapshot/journal/resume contracts
#     (tests/test_snapshot_recovery.py),
#   - failure watchdog classification + degraded mode
#     (tests/test_failure.py),
#   - remat-partial:      a host dies mid-GBM on a 4-host virtual mesh;
#     recovery re-parses ONLY the dead host's byte ranges (proved by the
#     parse_range injection counter), derived frames replay from
#     lineage, a failed re-mat degrades to full re-import — never wrong
#     data (tests/test_remat.py),
#   - stream-ingest:      a parse worker dies mid-stream; the partial
#     streaming lineage record holds exactly the landed ranges, resume()
#     re-parses ONLY the missing ones (parse_bytes call count), and the
#     recovered frame is bitwise equal to the batch parse
#     (tests/test_stream_chaos.py).
#
# Exits nonzero if ANY row fails (every row still runs).
set -o pipefail
cd "$(dirname "$0")/.."

ROWS_FILE="${H2O3_CHAOS_ROWS:-/tmp/chaos_rows.txt}"
ROW_TIMEOUT="${H2O3_CHAOS_ROW_TIMEOUT:-1200}"
: > "$ROWS_FILE"
FAILED=0

run_row() {
    local name="$1"; shift
    local t0=$SECONDS
    timeout -k 10 "$ROW_TIMEOUT" env JAX_PLATFORMS=cpu python -m pytest \
        "$@" -q -p no:cacheprovider -p no:xdist -p no:randomly
    local rc=$?
    local dt=$((SECONDS - t0))
    if [ $rc -eq 0 ]; then
        echo "PASS $name ${dt}s" >> "$ROWS_FILE"
    else
        echo "FAIL $name ${dt}s (rc=$rc)" >> "$ROWS_FILE"
        FAILED=1
    fi
}

run_row kill-resume tests/test_chaos.py \
    --deselect tests/test_chaos.py::test_coordinator_hard_kill_midtrain_rehydrate_reattach \
    --deselect tests/test_chaos.py::test_host_kill_mid_multitenant_load \
    --deselect tests/test_chaos.py::test_host_join_fenced_rebuild_midtrain \
    --deselect tests/test_chaos.py::test_kill_resume_mid_scan_program \
    --deselect tests/test_chaos.py::test_kill_resume_mid_grid_cohort
run_row scan-kill \
    tests/test_chaos.py::test_kill_resume_mid_scan_program
run_row grid-batch \
    tests/test_chaos.py::test_kill_resume_mid_grid_cohort
run_row coordinator-kill \
    tests/test_chaos.py::test_coordinator_hard_kill_midtrain_rehydrate_reattach
run_row multitenant-kill \
    tests/test_chaos.py::test_host_kill_mid_multitenant_load
run_row host-join \
    tests/test_chaos.py::test_host_join_fenced_rebuild_midtrain
run_row scheduler tests/test_scheduler.py
run_row mesh-host-kill tests/test_mesh_hier.py::test_mesh_host_kill_resume_verify
run_row dkv-wal tests/test_dkv_wal.py
run_row dkv-retry tests/test_dkv_retry.py
run_row snapshot-recovery tests/test_snapshot_recovery.py
run_row failure-watchdog tests/test_failure.py
run_row remat-partial tests/test_remat.py
run_row stream-ingest tests/test_stream_chaos.py

echo "---- chaos rows ($ROWS_FILE) ----"
cat "$ROWS_FILE"
exit $FAILED
