#!/usr/bin/env python
"""bench_gate: regression gate over the BENCH_*/MULTICHIP_* trajectory.

Diffs a fresh ``bench.py`` (or ``bench.py --multichip``) JSON record
against the accepted baseline rounds with per-metric tolerance bands,
emits a pass/regress table artifact, and exits nonzero on regression —
the compareBenchmarksStage.groovy analog for this repo's bench history.

Reference semantics: each metric is GATED against the most recent
baseline round that reports it (the current accepted state).  The
all-time best across rounds is shown as context, not gated on — bench
workload shapes evolve between rounds (e.g. BENCH_r04's GLM section ran
a different shape than r05's), so an all-time-best gate would misfire
on metrics whose meaning shifted.  A candidate identical to the latest
baseline therefore always passes.

Metric direction is classified by name: ``*_per_sec``, ``*_vs_baseline``,
``trees/sec``-style rates, ``*qps``, ``*speedup*`` and ``scaling_*`` are
higher-better; ``*_sec``/``*_s``/``*_ms``/``*_seconds`` wall clocks and
``*latency*`` series are lower-better, and so are count-style metrics
(``*launches*``, ``*_total``, ``*_count`` — a dispatch or recompile
count that grows is a regression; serving latencies and dispatch pins
gate correctly from their first recorded round).  Sizes and configuration
echoes (rows, trees, platform, ``parse_csv_mb``) and the compile-split
diagnostics (``*_compile_s``/``*_steady_s``, ``compiles_total``) are
informational only.

Usage:
  python tools/bench_gate.py CANDIDATE.json [--baseline FILE ...]
      [--tolerance PCT] [--out REPORT]

Defaults: baselines are the repo's BENCH_r*.json (or MULTICHIP_r*.json
when the candidate is a multichip record), tolerance 10% (25% for
``bench_wall_s``), report written to ``bench_gate_report.txt`` next to
the candidate.  Exit codes: 0 pass, 1 regression, 2 usage/config error.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

DEFAULT_TOLERANCE_PCT = 10.0
# noisy or environment-dominated metrics get looser bands
TOLERANCE_OVERRIDES_PCT = {
    "bench_wall_s": 25.0,
    "scaling_8_to_32": 15.0,
    # recovery timings are I/O-noisy on shared hosts
    "remat_partial_s": 25.0,
    "remat_full_s": 25.0,
    "remat_partial_vs_baseline": 25.0,
    "autotune_vs_best": 3.0,
}
# absolute floors: gated even when the metric has no baseline round yet
# ("new" metrics normally pass ungated).  autotune_vs_best is a ratio of
# tuner-chosen throughput to the best hand-set configuration — the
# acceptance bar is >= 0.97 regardless of history.
ABSOLUTE_FLOORS = {
    "autotune_vs_best": 0.97,
    # streamed end-to-end (ingest overlapped with stream= training)
    # must stay at or under 0.85x of parse-then-train wall-clock:
    # batch/streamed >= 1/0.85
    "stream_overlap_vs_baseline": 1.176,
    # batched grid sweeps: dispatch ratio G*L_seq/L_batched for the G=8
    # cohort — one compiled program must keep serving at least half the
    # fleet per dispatch (full credit is 8.0; slipping under 4.0 means
    # the model axis stopped riding the kernels' nk batch dim)
    "grid_batched_vs_sequential": 4.0,
}
# echoes of configuration / sizes / diagnostics: reported, never gated
INFORMATIONAL = ("platform", "rows", "trees", "parse_csv_mb",
                 "secondaries", "compiles_total", "compile_s_total")
_INFO_SUFFIXES = ("_compile_s", "_steady_s", "_error")

_HIGHER_HINTS = ("per_sec", "_vs_baseline", "_vs_best", "_vs_sequential",
                 "samples_per_sec", "trees_per_sec", "scaling", "qps",
                 "speedup")
_LOWER_SUFFIXES = ("_sec", "_s", "_ms", "_seconds")
# count-style metrics: a launch/dispatch/recompile count that grows is a
# regression (the treescan dispatch pin rides this).  compiles_total
# stays informational — it is listed in INFORMATIONAL, which wins.
_COUNT_HINTS = ("launches",)
_COUNT_SUFFIXES = ("_total", "_count")


def classify(name: str) -> str:
    """'higher' | 'lower' | 'info' for a flattened metric name."""
    if name in INFORMATIONAL or name.endswith(_INFO_SUFFIXES):
        return "info"
    if any(h in name for h in _HIGHER_HINTS):
        return "higher"
    if any(h in name for h in _COUNT_HINTS) \
            or name.endswith(_COUNT_SUFFIXES):
        return "lower"
    if name.endswith(_LOWER_SUFFIXES) or "latency" in name:
        return "lower"
    return "info"


def flatten(record: dict) -> dict:
    """One bench JSON record -> flat {metric: numeric} dict.

    Accepts the raw worker record ({metric, value, vs_baseline, extra}),
    a driver wrapper ({parsed: record}), or a multichip summary
    ({entries: [{n_devices, trees_per_sec, ...}], scaling_8_to_32})."""
    if not isinstance(record, dict):
        return {}
    if "parsed" in record and isinstance(record["parsed"], dict):
        record = record["parsed"]
    out = {}
    if "entries" in record and isinstance(record["entries"], list):
        for ent in record["entries"]:
            nd = ent.get("n_devices")
            if nd is None:
                continue
            for k in ("trees_per_sec", "wall_s"):
                if isinstance(ent.get(k), (int, float)):
                    out[f"multichip_{k}_{nd}dev"] = float(ent[k])
        if isinstance(record.get("scaling_8_to_32"), (int, float)):
            out["scaling_8_to_32"] = float(record["scaling_8_to_32"])
        return out
    metric = record.get("metric")
    if isinstance(metric, str) and isinstance(record.get("value"),
                                              (int, float)):
        out[metric] = float(record["value"])
        if isinstance(record.get("vs_baseline"), (int, float)):
            out[f"{metric}_vs_baseline"] = float(record["vs_baseline"])
    for k, v in (record.get("extra") or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[k] = float(v)
    return out


def _round_of(path: str) -> int:
    m = re.search(r"r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else -1


def load_baselines(paths) -> list:
    """[(round_no, path, flat_metrics)] sorted oldest -> newest; rounds
    that produced no metrics (failed runs like BENCH_r02/r03) drop out."""
    rounds = []
    for p in paths:
        try:
            with open(p) as f:
                flat = flatten(json.load(f))
        except (OSError, ValueError) as e:
            print(f"bench_gate: skipping unreadable baseline {p}: {e}",
                  file=sys.stderr)
            continue
        if flat:
            rounds.append((_round_of(p), p, flat))
    rounds.sort(key=lambda t: t[0])
    return rounds


def evaluate(candidate: dict, rounds: list,
             tolerance_pct: float = DEFAULT_TOLERANCE_PCT) -> list:
    """Per-metric verdicts: list of dicts with name/status/detail.

    status: 'pass' | 'regress' | 'new' | 'info'."""
    latest = {}
    best = {}
    for _, path, flat in rounds:              # oldest -> newest
        for name, val in flat.items():
            latest[name] = (val, path)
            direction = classify(name)
            if direction == "info":
                continue
            prev = best.get(name)
            better = (prev is None
                      or (direction == "higher" and val > prev[0])
                      or (direction == "lower" and val < prev[0]))
            if better:
                best[name] = (val, path)
    results = []
    for name in sorted(candidate):
        val = candidate[name]
        direction = classify(name)
        row = {"name": name, "value": val, "direction": direction}
        if direction == "info":
            row.update(status="info", detail="informational")
            results.append(row)
            continue
        floor = ABSOLUTE_FLOORS.get(name)
        if floor is not None and val < floor:
            row.update(status="regress", floor=floor,
                       detail=f"below absolute floor {floor}")
            results.append(row)
            continue
        if name not in latest:
            if floor is not None:
                row.update(status="pass", floor=floor,
                           detail=f"meets absolute floor {floor} "
                                  "(no baseline yet)")
            else:
                row.update(status="new",
                           detail="no baseline for this metric")
            results.append(row)
            continue
        ref, ref_path = latest[name]
        tol = TOLERANCE_OVERRIDES_PCT.get(name, tolerance_pct) / 100.0
        if direction == "higher":
            ok = val >= ref * (1.0 - tol)
            delta_pct = (val / ref - 1.0) * 100.0 if ref else 0.0
        else:
            ok = val <= ref * (1.0 + tol)
            delta_pct = (ref / val - 1.0) * 100.0 if val else 0.0
        row.update(status="pass" if ok else "regress",
                   ref=ref, ref_file=os.path.basename(ref_path),
                   delta_pct=round(delta_pct, 1),
                   tolerance_pct=TOLERANCE_OVERRIDES_PCT.get(
                       name, tolerance_pct))
        if name in best:
            row["best"] = best[name][0]
            row["best_file"] = os.path.basename(best[name][1])
        results.append(row)
    return results


def render_table(results: list) -> str:
    hdr = (f"{'metric':42} {'value':>12} {'ref':>12} {'Δ%':>7} "
           f"{'best':>12} {'status':>8}")
    lines = [hdr, "-" * len(hdr)]
    order = {"regress": 0, "new": 1, "pass": 2, "info": 3}
    for r in sorted(results, key=lambda r: (order[r["status"]], r["name"])):
        ref = f"{r['ref']:.3f}" if "ref" in r else "-"
        bst = f"{r['best']:.3f}" if "best" in r else "-"
        dlt = f"{r['delta_pct']:+.1f}" if "delta_pct" in r else "-"
        note = f"  [{r['detail']}]" if "floor" in r else ""
        lines.append(f"{r['name']:42} {r['value']:>12.3f} {ref:>12} "
                     f"{dlt:>7} {bst:>12} {r['status']:>8}{note}")
    n_reg = sum(1 for r in results if r["status"] == "regress")
    n_gated = sum(1 for r in results if r["status"] in ("pass", "regress"))
    lines.append("")
    lines.append(f"gated {n_gated} metrics, {n_reg} regression(s), "
                 f"{sum(1 for r in results if r['status'] == 'new')} new")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("candidate", help="fresh bench JSON record to gate")
    ap.add_argument("--baseline", action="append", default=[],
                    help="baseline JSON (repeatable; default: repo "
                         "BENCH_r*.json / MULTICHIP_r*.json)")
    ap.add_argument("--tolerance", type=float,
                    default=DEFAULT_TOLERANCE_PCT,
                    help="default tolerance band in percent")
    ap.add_argument("--out", default="",
                    help="report artifact path (default: "
                         "bench_gate_report.txt next to the candidate)")
    args = ap.parse_args(argv)

    try:
        with open(args.candidate) as f:
            candidate = flatten(json.load(f))
    except (OSError, ValueError) as e:
        print(f"bench_gate: cannot read candidate {args.candidate}: {e}",
              file=sys.stderr)
        return 2
    if not candidate:
        print(f"bench_gate: candidate {args.candidate} carries no metrics",
              file=sys.stderr)
        return 2

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    baselines = args.baseline
    if not baselines:
        pat = "MULTICHIP_r*.json" if any(
            k.startswith(("multichip_", "scaling_")) for k in candidate) \
            else "BENCH_r*.json"
        baselines = sorted(glob.glob(os.path.join(repo, pat)))
    rounds = load_baselines(baselines)
    if not rounds:
        print("bench_gate: no readable baselines "
              f"(looked at {len(baselines)} file(s))", file=sys.stderr)
        return 2

    results = evaluate(candidate, rounds, tolerance_pct=args.tolerance)
    table = render_table(results)
    out_path = args.out or os.path.join(
        os.path.dirname(os.path.abspath(args.candidate)) or ".",
        "bench_gate_report.txt")
    try:
        with open(out_path, "w") as f:
            f.write(table + "\n")
        print(f"bench_gate: report -> {out_path}")
    except OSError as e:
        print(f"bench_gate: cannot write report {out_path}: {e}",
              file=sys.stderr)
    print(table)
    return 1 if any(r["status"] == "regress" for r in results) else 0


if __name__ == "__main__":
    sys.exit(main())
