#!/usr/bin/env bash
# tier1.sh — THE tier-1 verify entry point, checked in so the marker
# expression cannot drift between ROADMAP.md and what builder/CI actually
# run.  ROADMAP.md's "Tier-1 verify" points here; this file is the only
# place the command (and its wall-clock budget) lives.
#
# Budget note: the original 870 s was sized for the ~665 s seed suite;
# PR 2's subtraction-parity tests grew it to ~830 s (budget 1200), PR 3's
# chaos matrix (kill-resume-verify subprocesses) added ~200 s (budget
# 1500), and PR 5's fused-split parity suite + mid-multinomial-round
# chaos row add ~150 s, so the budget became 1700 s.  By PR 14 a clean
# run had crept to ~1560 s (headroom ratio down to ~1.1x) and PR 15's
# streaming-ingest suite (test_stream/test_warm_start/test_stream_chaos,
# ~40 s) pushed a noisy run past the cliff at 97%, so the budget is
# 2200 s — back to ~1.4x over the ~1600 s clean run.  Keep the ratio
# when tier-1 grows again.  PR 16's whole-tree-scan parity suite
# (tests/test_tree_scan.py, compile-heavy scan-vs-level program pairs)
# + the scan-kill chaos row land on a ~2375 s measured clean run, so
# the budget is 3300 s (~1.4x).
# PR 11's online-serving suite (tests/test_serving.py: pack parity,
# packed-vs-ref check mode across the four tree algos, micro-batcher
# demux, REST realtime round-trip) rides inside `tests/` and adds ~70 s,
# still within the 1700 s budget; its SIGTERM-drain launcher test is
# `heavy` and runs only in the full suite.
# The 16-device mesh re-run at the bottom has its own 300 s budget
# (~45 s clean) on top.
#
# Prints DOTS_PASSED=<n> (count of passing-test dots in the progress
# lines) and exits with pytest's return code — the rc is captured from
# PIPESTATUS before the DOTS line so the tee/grep epilogue can never
# mask a pytest failure (or a timeout's 124) from CI.
#
# Timing artifact: --durations=25 makes pytest print the slowest 25
# tests; the block is extracted to tier1_durations.txt (override with
# H2O3_TIER1_DURATIONS) so per-PR budget creep is attributable instead
# of discovered at the timeout cliff.
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
# Compile-stats artifact: conftest's pytest_sessionfinish hook dumps the
# runtime compile ledger (top-10 slowest compiles + recompile count) to
# this path — the compile-time analog of the durations artifact.
compile_stats_file=${H2O3_TIER1_COMPILE_STATS:-/tmp/tier1_compile_stats.txt}
export H2O3_TIER1_COMPILE_STATS="$compile_stats_file"
timeout -k 10 3300 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow and not heavy' --continue-on-collection-errors \
    --durations=25 --durations-min=1.0 \
    -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
durations_file=${H2O3_TIER1_DURATIONS:-/tmp/tier1_durations.txt}
sed -n '/slowest.*durations/,/^[=]/p' /tmp/_t1.log | sed '$d' \
    > "$durations_file" || true
[ -s "$durations_file" ] && echo "DURATIONS_FILE=$durations_file"
[ -s "$compile_stats_file" ] && echo "COMPILE_STATS_FILE=$compile_stats_file"
# Surface the whole-tree scan program's compile-ledger row (conftest pins
# it into the artifact even outside the top-10) so the one-launch-per-tree
# build's compile cost is visible in every tier-1 log.
grep -a 'tree_build_scan' "$compile_stats_file" 2>/dev/null \
    | sed 's/^[[:space:]]*/TREE_BUILD_SCAN_COMPILE: /' || true
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
# Second pass on a 16-device virtual mesh (4 hosts x 4 chips): the main
# suite is pinned at 8 devices, so the mesh/data-plane contract tests
# re-run here at the larger geometry at least once per tier-1 run.
# Focused (one module) to keep the added wall clock ~1 min.
timeout -k 10 300 env JAX_PLATFORMS=cpu H2O3_TPU_TEST_DEVICES=16 \
    H2O3_TPU_HOSTS=4 python -m pytest tests/test_mesh_hier.py \
    --deselect 'tests/test_mesh_hier.py::test_parity_on_larger_virtual_mesh[16-2]' \
    --deselect 'tests/test_mesh_hier.py::test_parity_on_larger_virtual_mesh[32-4]' \
    -q -p no:cacheprovider -p no:xdist -p no:randomly
rc16=$?
echo MESH16_RC=$rc16
[ "$rc" -eq 0 ] && rc=$rc16
exit $rc
