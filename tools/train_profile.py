"""Stage-level timing of the bench.py XGBoost train path on the chip.

bench.py r04 measured 1.69 trees/s end-to-end while bench_pieces.py's
kernel sum projects ~5/s — this script finds the missing ~380 ms/tree by
timing each stage of the exact train() pipeline separately:

  ingest     Frame.from_numpy (host->device push of the 10M x 9 table)
  fit_bins   quantile edge fit + 10M x 8 quantization to codes
  compile    first scan_fn chunk (10 trees) — compile + first exec
  chunk      steady-state scan_fn chunk (10 trees per dispatch)
  finalize   training-metrics path on the final margin F

Usage (chip): python tools/train_profile.py
Smoke:        JAX_PLATFORMS=cpu H2O3_TP_ROWS=100000 python tools/train_profile.py
"""

import json
import os
import time

import numpy as np

N_ROWS = int(os.environ.get("H2O3_TP_ROWS", 10_000_000))


def main():
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp

    import h2o3_tpu
    from h2o3_tpu import Frame
    from h2o3_tpu.frame.vec import T_CAT

    h2o3_tpu.init()
    import bench as B

    def stamp(stage, t0, **extra):
        dt = time.perf_counter() - t0
        print(json.dumps({"stage": stage, "s": round(dt, 3), **extra}),
              flush=True)
        return time.perf_counter()

    cols, types, domains = B.make_airlines_like(N_ROWS)
    types = {k: (T_CAT if v == "cat" else v) for k, v in types.items()}

    t0 = time.perf_counter()
    fr = Frame.from_numpy(cols, types=types, domains=domains)
    for v in fr.vecs:                       # force the push
        if v.data is not None:
            np.asarray(v.data[:1])
    t0 = stamp("ingest", t0)

    from h2o3_tpu.models.tree.binning import fit_bins, edges_matrix
    names = [n for n in fr.names if n != "dep_delayed_15min"]
    binned = fit_bins(fr, names, nbins=256, seed=1)
    np.asarray(binned.codes[:1, :1])
    t0 = stamp("fit_bins", t0, nfeatures=binned.nfeatures,
               bin_counts=list(binned.bin_counts))

    from h2o3_tpu.models.tree.shared import make_tree_scan_fn
    codes = binned.codes
    N = codes.shape[1]
    y = (np.asarray(cols["dep_delayed_15min"]) == "YES").astype(np.float32)
    y = jnp.asarray(y)
    if N > y.shape[0]:
        y = jnp.pad(y, (0, N - y.shape[0]))
    w = jnp.ones((N,), jnp.float32)
    edges_mat = jnp.asarray(edges_matrix(binned.edges, 256), jnp.float32)
    scan_fn = make_tree_scan_fn(
        "bernoulli", 1.5, 0.5, 0.9, 6, 256, binned.nfeatures, N,
        "bf16", 1.0, 1.0, hier=False, bin_counts=binned.bin_counts)
    scalars = (1.0, 1.0, 0.0, 0.3, 1.0, 0.0, 0.0, 0.0)
    F0 = jnp.zeros((N,), jnp.float32)
    rng = jax.random.PRNGKey(1)

    chunk_counter = [0]

    def run_chunk(F):
        cn = chunk_counter[0]
        chunk_counter[0] += 1
        F, lv, vals, cov = scan_fn(codes, y, w, F, edges_mat,
                                   rng, cn, 10, *scalars, 0)
        return F, (lv, vals, cov)

    F, out = run_chunk(F0)
    np.asarray(F[:1])
    t0 = stamp("compile+first_chunk", t0)

    for rep in range(3):
        F, out = run_chunk(F)
        np.asarray(F[:1])
        t0 = stamp(f"chunk_{rep}", t0, trees=10,
                   ms_per_tree=None)

    # finalize path: metrics from the final margin (no traverse)
    t0 = time.perf_counter()
    p = jax.nn.sigmoid(F)
    auc_in = np.asarray(jnp.stack([1 - p, p], axis=1))
    t0 = stamp("fetch_probs_10m", t0)


if __name__ == "__main__":
    main()
