"""Kernel lab: A/B variants of the varbin histogram one-hot build.

The varbin kernel costs ~27.7 ms/level on chip (10M rows, airlines bins),
flat in L — so the per-slot one-hot build (compare + cast + concatenate)
is the whole cost, ~2.4 ops/slot effective.  The concatenate is a pure
VMEM copy of the [Q8, R] one-hot per row block; these variants remove it:

  concat   — shipped kernel (baseline): pieces list -> jnp.concatenate -> dot
  perfdot  — no concatenate: per-feature dot accumulated into out slices
  scratch  — compares write straight into a VMEM scratch at static offsets,
             then ONE dot

All share the stat/A build; parity is asserted against the shipped kernel
before timing.  Timing uses PROFILE.md methodology (fori_loop of REPS
dependent calls in one jit, small-fetch sync).

Usage (chip): python tools/kernel_lab.py
CPU check:    JAX_PLATFORMS=cpu H2O3_LAB_ROWS=100000 python tools/kernel_lab.py
"""

import functools
import json
import os
import time

import numpy as np

N_ROWS = int(os.environ.get("H2O3_LAB_ROWS", 10_000_000))
REPS = int(os.environ.get("H2O3_LAB_REPS", 20))
BIN_COUNTS = (21, 12, 7, 256, 256, 22, 256, 256)
F, NBINS = 8, 256
B = NBINS + 1


def main():
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    import h2o3_tpu
    cl = h2o3_tpu.init()
    platform = jax.devices()[0].platform
    interp = platform != "tpu"
    n = N_ROWS - (N_ROWS % (512 * cl.n_row_shards))

    from h2o3_tpu.models.tree.hist import (make_varbin_hist_fn, offset_codes,
                                           varbin_layout)

    offsets, seg_rows, Q8, _ = varbin_layout(BIN_COUNTS, B)
    L = 32
    L3 = 3 * L
    R = int(min(4096, max(512, (4_194_304 // max(Q8 * 2, 1)) // 128 * 128)))
    R = min(R, max(512, ((n + 511) // 512) * 512))
    nblk = (n + R - 1) // R
    pad_to = nblk * R
    dt = jnp.bfloat16
    code_dt = jnp.int16

    def build_A(leaf_i32, ST_f32):
        cols = jax.lax.broadcasted_iota(jnp.int32, (R, L3), 1)
        l_of, s_of = cols // 3, cols % 3
        match = leaf_i32[:, None] == l_of
        sv = jnp.where(s_of == 0, ST_f32[0][:, None],
                       jnp.where(s_of == 1, ST_f32[1][:, None],
                                 ST_f32[2][:, None]))
        return jnp.where(match, sv, 0.0).astype(dt)

    def make_variant(kind):
        def kernel(codes_ref, leaf_ref, st_ref, out_ref, *scr):
            i = pl.program_id(0)

            @pl.when(i == 0)
            def _():
                out_ref[:] = jnp.zeros_like(out_ref)

            A = build_A(leaf_ref[0].astype(jnp.int32),
                        st_ref[:].astype(jnp.float32))
            codes = codes_ref[:].astype(jnp.int32)
            if kind == "concat":
                pieces = []
                for f in range(F):
                    q_of = jax.lax.broadcasted_iota(
                        jnp.int32, (int(seg_rows[f]), 1), 0) + int(offsets[f])
                    pieces.append((codes[f, :][None, :] == q_of).astype(dt))
                OHT = jnp.concatenate(pieces, axis=0)
                out_ref[:] += jnp.dot(OHT, A,
                                      preferred_element_type=jnp.float32)
            elif kind == "perfdot":
                for f in range(F):
                    q_of = jax.lax.broadcasted_iota(
                        jnp.int32, (int(seg_rows[f]), 1), 0) + int(offsets[f])
                    piece = (codes[f, :][None, :] == q_of).astype(dt)
                    out_ref[int(offsets[f]):int(offsets[f] + seg_rows[f]),
                            :] += jnp.dot(
                        piece, A, preferred_element_type=jnp.float32)
            elif kind == "scratch":
                oh = scr[0]
                for f in range(F):
                    q_of = jax.lax.broadcasted_iota(
                        jnp.int32, (int(seg_rows[f]), 1), 0) + int(offsets[f])
                    oh[int(offsets[f]):int(offsets[f] + seg_rows[f]), :] = (
                        codes[f, :][None, :] == q_of).astype(dt)
                out_ref[:] += jnp.dot(oh[:], A,
                                      preferred_element_type=jnp.float32)

        scratch = [pltpu.VMEM((Q8, R), dt)] if kind == "scratch" else []
        call = pl.pallas_call(
            kernel,
            grid=(nblk,),
            in_specs=[
                pl.BlockSpec((F, R), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, R), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((3, R), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((Q8, L3), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((Q8, L3), jnp.float32),
            scratch_shapes=scratch,
            interpret=interp,
        )

        @jax.jit
        def run(gcodes, leaf, g, h, w):
            pad = pad_to - n

            def padr(x, fill):
                if pad == 0:
                    return x
                return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)],
                               constant_values=fill)
            ST = jnp.stack([g, h, w], axis=0).astype(dt)
            return call(padr(gcodes.astype(code_dt), -1),
                        padr(leaf[None].astype(code_dt), -1),
                        padr(ST, 0))

        return run

    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 8)
    codes = jnp.stack([
        jax.random.randint(ks[f], (n,), 0, min(bc, NBINS), dtype=jnp.int32)
        for f, bc in enumerate(BIN_COUNTS)], axis=0)
    gcodes = offset_codes(codes, BIN_COUNTS, NBINS)
    g = jax.random.normal(ks[0], (n,), jnp.float32)
    h = jnp.abs(jax.random.normal(ks[1], (n,), jnp.float32)) + 0.1
    w = jnp.ones((n,), jnp.float32)
    leaf = jax.random.randint(ks[2], (n,), 0, L, dtype=jnp.int32)

    def sync(x):
        np.asarray(jax.device_get(jnp.ravel(x)[:1]))

    def timed(run):
        @jax.jit
        def reps(gc, lf, gg, hh, ww):
            def body(i, acc):
                out = run(gc, lf, gg + acc * 0.0, hh, ww)
                return out[0, 0] * 1e-30
            return jax.lax.fori_loop(0, REPS, body, jnp.float32(0.0))

        out = reps(gcodes, leaf, g, h, w); sync(out)
        out = reps(gcodes, leaf, g, h, w); sync(out)
        t0 = time.perf_counter()
        out = reps(gcodes, leaf, g, h, w); sync(out)
        return (time.perf_counter() - t0) / REPS * 1e3

    ref = None
    for kind in ("concat", "perfdot", "scratch"):
        try:
            run = make_variant(kind)
            out = np.asarray(run(gcodes, leaf, g, h, w))
            if ref is None:
                ref = out
            ok = bool(np.allclose(out, ref, rtol=2e-2, atol=1e-2))
            ms = timed(run)
            print(json.dumps({"variant": kind, "ms": round(ms, 3),
                              "parity": ok, "platform": platform,
                              "rows": n, "L": L}), flush=True)
        except Exception as e:  # noqa: BLE001 — lab tool: report and go on
            print(json.dumps({"variant": kind,
                              "error": repr(e)[:300]}), flush=True)


if __name__ == "__main__":
    main()
